"""Fused decode-layer megakernel (passes/fusion_decode.py +
ops/pallas/decode_layer.py + the serving megakernel= mode):

- fused-vs-unfused greedy streams BIT-IDENTICAL across the composition
  matrix (dense, paged, paged+kv_int8, weight-quant int8/int4,
  spec=k=8), decode compile count pinned at 1;
- a recursive jaxpr walk over the TRANSFORMED decode-block program:
  NO fp32 hidden-state interior ((S, 1, ff) MLP activation,
  (S, kvh, g, dh) attention internals) outside the fused calls, one
  fused call per layer — the structural form of the VMEM-residency
  claim (the unfused program shows both shapes, sanity-checking the
  walk);
- the Pallas megakernel itself in interpret mode, pinned against the
  plain-jnp reference for the fp32 and int8 paged arenas — and the
  reference pinned against the model's own decode-layer math so the
  oracle can never drift;
- pass soundness: a pjit that merely WEARS the marker name but fails
  the attention→o_proj→MLP certificate is left unfused;
- routing: megakernel= refused alongside an explicit backend, the env
  knob routes the factory but never reroutes a prebuilt backend, and a
  model that never marks fails loudly instead of silently serving the
  unfused program.
"""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
from paddle_tpu.models.llama import (LlamaDecoderLayer, LlamaForCausalLM,
                                     llama_tiny_config)
from paddle_tpu.serving import (ContinuousBatchingEngine, QuantConfig,
                                Server, SpecConfig)
from paddle_tpu.serving.engine import ModelStepBackend


@pytest.fixture(scope="module")
def setup():
    paddle.seed(0)
    cfg = llama_tiny_config(tensor_parallel=False)
    return LlamaForCausalLM(cfg), cfg


def _prompts(cfg, seed, lens):
    rs = np.random.RandomState(seed)
    return [rs.randint(0, cfg.vocab_size, (L,)).astype(np.int32)
            for L in lens]


def _stream(engine, prompts, max_new=5):
    engine.reset()
    srv = Server(engine)
    rids = [srv.submit(p, max_new_tokens=max_new) for p in prompts]
    res = srv.run_until_idle()
    return [res[r] for r in rids]


def _ab(model, cfg, kw, seed=1, expect_rewrites=True):
    prompts = _prompts(cfg, seed, (5, 9, 12))
    plain = ContinuousBatchingEngine(model, megakernel=False, **kw)
    mega = ContinuousBatchingEngine(model, megakernel=True, **kw)
    ref = _stream(plain, prompts)
    got = _stream(mega, prompts)
    for a, b in zip(ref, got):
        np.testing.assert_array_equal(a, b)
    assert mega.decode_compile_count() == 1
    if expect_rewrites:
        assert mega.megakernel_rewrites() == cfg.num_hidden_layers
    return mega


PAGED_KW = dict(num_slots=2, max_len=64, decode_block=4, paged=True,
                block_size=8, prefill_chunk=8)


class TestFusedBitParity:
    """The composition matrix: fused greedy streams must equal the
    unfused engine's token-for-token (on CPU the fused body is the
    captured unfused jaxpr — this pins the pass/splice/arg plumbing)."""

    def test_dense(self, setup):
        model, cfg = setup
        _ab(model, cfg, dict(num_slots=2, max_len=64, decode_block=4,
                             prompt_buckets=(16,)))

    def test_paged(self, setup):
        model, cfg = setup
        _ab(model, cfg, dict(PAGED_KW))

    def test_paged_kv_int8(self, setup):
        model, cfg = setup
        mega = _ab(model, cfg, dict(PAGED_KW, kv_int8=True))
        mega.manager.assert_consistent()

    def test_quant_int8_paged(self, setup):
        model, cfg = setup
        # weight quant pins allow_kernel=False: the fused calls exist
        # but none may route to the Pallas kernel (the in-graph dequant
        # must stay an XLA gemm-prologue fusion)
        mega = _ab(model, cfg, dict(PAGED_KW, kv_int8=True,
                                    quant=QuantConfig(weights="int8")))
        assert mega.megakernel_kernel_calls() == 0

    def test_quant_int4_dense(self, setup):
        model, cfg = setup
        _ab(model, cfg, dict(num_slots=2, max_len=64, decode_block=4,
                             prompt_buckets=(16,),
                             quant=QuantConfig(weights="int4")))

    def test_spec_k8_paged(self, setup):
        """spec composes by NOT fusing: the (S, k+1) verify program is
        outside the marked s=1 decode shape (documented follow-up), so
        megakernel+spec serves the unfused verify block — accepted,
        streams identical, zero rewrites."""
        model, cfg = setup
        mega = _ab(model, cfg,
                   dict(PAGED_KW, max_len=96, spec=SpecConfig(k=8)),
                   expect_rewrites=False)
        assert mega.megakernel_rewrites() == 0


class TestNoTransientWalk:
    """The acceptance-criteria walk: between the fused ops, no (S, d)
    hidden-state round-trip exists — concretely, the transformed block
    program holds no fp32 MLP/attention interior outside the fused
    calls, and each layer crosses the boundary exactly once."""

    def test_fused_program_holds_no_hidden_state_interior(self, setup):
        from paddle_tpu.passes.fusion_decode import (
            fused_decode_calls, walk_eqns, walk_outside_fused)
        from paddle_tpu.serving.engine import build_slot_block_fn
        model, cfg = setup
        kw = dict(PAGED_KW, kv_int8=True)
        mega = ContinuousBatchingEngine(model, megakernel=True, **kw)
        _stream(mega, _prompts(cfg, 2, (5, 9)), max_new=3)
        closed = mega.backend._block_jit._closed
        S = kw["num_slots"]
        kvh = cfg.num_key_value_heads
        g = cfg.num_attention_heads // kvh
        dh = cfg.hidden_size // cfg.num_attention_heads
        banned = {(S, 1, cfg.intermediate_size),   # MLP activation
                  (S, kvh, g, dh)}                 # attention interior

        def f32_shapes(eqns):
            out = set()
            for eqn in eqns:
                for v in eqn.outvars:
                    aval = getattr(v, "aval", None)
                    if aval is not None and \
                            getattr(aval, "dtype", None) == jnp.float32:
                        out.add(tuple(aval.shape))
            return out

        outside = f32_shapes(walk_outside_fused(closed))
        assert not (outside & banned), \
            f"hidden-state interior outside fused calls: {outside & banned}"
        calls = fused_decode_calls(closed)
        assert len(calls) == cfg.num_hidden_layers
        for eqn in calls:
            # per layer, the hidden state crosses the fused boundary
            # exactly once in and once out
            assert tuple(eqn.invars[0].aval.shape) == (S, 1,
                                                       cfg.hidden_size)
            assert tuple(eqn.outvars[0].aval.shape) == (S, 1,
                                                        cfg.hidden_size)
        # sanity: the UNFUSED program does materialize both interiors
        plain = ContinuousBatchingEngine(model, **kw)
        fn = build_slot_block_fn(plain.backend._pure,
                                 plain.decode_block, paged=True)
        closed_u = jax.make_jaxpr(fn)(plain.backend._pv,
                                      plain.backend._bv, plain._cache,
                                      plain._state)
        assert banned <= f32_shapes(walk_eqns(closed_u.jaxpr))


class TestMegaKernelInterpret:
    """The Pallas megakernel itself, interpret mode on CPU."""

    def _args(self, mode, seed=0):
        pytest.importorskip("jax.experimental.pallas")
        from paddle_tpu.ops.pallas.paged_attention import quantize_kv
        rs = np.random.RandomState(seed)
        S, d, h, kvh, dh, ff = 3, 128, 4, 2, 32, 384
        NB, BS, MB, P = 12, 8, 4, 64

        def f32(*shape, s=1.0):
            return jnp.asarray((s * rs.randn(*shape)).astype(np.float32))

        def w(*shape):
            return f32(*shape, s=1.0 / np.sqrt(shape[0]))

        x = f32(S, 1, d)
        pos = jnp.asarray([5, 13, 26], jnp.int32)
        tbl = jnp.asarray(rs.randint(1, NB, (S, MB)).astype(np.int32))
        wts = (f32(d), w(d, h * dh), w(d, kvh * dh), w(d, kvh * dh),
               w(h * dh, d), f32(d), w(d, ff), w(d, ff), w(ff, d))
        if mode == "paged_int8":
            kc, ks = quantize_kv(f32(NB, BS, kvh, dh, s=3))
            vc, vs = quantize_kv(f32(NB, BS, kvh, dh))
            cache = (kc, vc, ks, vs)
        else:
            cache = (f32(NB, BS, kvh, dh), f32(NB, BS, kvh, dh))
        return (x, f32(P, dh), f32(P, dh), 1e-5, 1e-5, pos, tbl) \
            + cache + wts

    @pytest.mark.parametrize("mode", ["paged", "paged_int8"])
    def test_kernel_matches_reference(self, mode, monkeypatch):
        import paddle_tpu.ops.pallas.fused as fused
        from paddle_tpu.ops.pallas import decode_layer as dl
        monkeypatch.setattr(fused, "_FORCE_INTERPRET", True)
        args = self._args(mode)
        ref = dl.decode_layer_reference(mode, *args)
        got = dl.decode_layer_paged_kernel(mode, *args)
        assert len(ref) == len(got)
        for a, b in zip(ref, got):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-4, rtol=1e-4)

    def test_viability_gate(self):
        from paddle_tpu.ops.pallas import decode_layer as dl
        args = self._args("paged_int8")
        avals = tuple(jax.ShapeDtypeStruct(np.shape(a),
                                           jnp.asarray(a).dtype)
                      for a in args)
        fixed, cache, wts = dl.split_args("paged_int8", avals)
        # dense never kernels; paged viability needs a pallas backend
        assert not dl.kernel_viable("dense", fixed[0], cache, wts)
        import paddle_tpu.ops.pallas.fused as fused
        if not fused._FORCE_INTERPRET and \
                jax.default_backend() == "cpu":
            assert not dl.kernel_viable("paged_int8", fixed[0], cache,
                                        wts)

    def test_reference_matches_model_math(self, setup):
        """The parity oracle cannot drift: decode_layer_reference must
        reproduce the model's OWN decode-layer output on identical
        inputs (the marked region replays LlamaDecoderLayer's
        _decode_forward, which is what the fused call captures)."""
        from paddle_tpu import framework
        from paddle_tpu.ops.pallas import decode_layer as dl
        from paddle_tpu.tensor import Tensor
        model, cfg = setup
        layer = model.llama.layers[0]
        wts = layer._decode_layer_weights()
        rs = np.random.RandomState(7)
        S, d = 2, cfg.hidden_size
        kvh = cfg.num_key_value_heads
        dh = cfg.hidden_size // cfg.num_attention_heads
        NB, BS, MB = 10, 8, 4
        x = jnp.asarray(rs.randn(S, 1, d).astype(np.float32))
        ck = jnp.asarray(rs.randn(NB, BS, kvh, dh).astype(np.float32))
        cv = jnp.asarray(rs.randn(NB, BS, kvh, dh).astype(np.float32))
        tbl = jnp.asarray(rs.randint(1, NB, (S, MB)).astype(np.int32))
        pos = jnp.asarray([3, 11], jnp.int32)
        cos = model.llama.rope_cos._value
        sin = model.llama.rope_sin._value
        eps = float(layer.input_layernorm.epsilon)
        ref = dl.decode_layer_reference(
            "paged", x, cos, sin, eps, eps, pos, tbl, ck, cv,
            *[w._value for w in wts])
        with framework.functional_mode():
            out, new_cache = layer._decode_forward(
                Tensor(x), cos, sin, None, (Tensor(ck), Tensor(cv)),
                Tensor(pos), None, Tensor(tbl))
        got = (out._value,) + tuple(c._value for c in new_cache)
        for a, b in zip(ref, got):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-5, rtol=1e-5)


class TestDecodeFusionPass:
    def test_impostor_marker_left_unfused(self):
        """A pjit wearing the marker name whose body is NOT the decode
        chain must fail the certificate and stay unfused (the pass
        never rewrites on faith)."""
        from paddle_tpu.passes.fusion_decode import (
            decode_fusion_pass, fused_decode_calls)

        @jax.jit
        def pt_decode_layer_dense(x, cos, sin, eps1, eps2, pos, aux,
                                  ck, cv, *wts):
            return x + 1.0, ck, cv

        def outer(x, cos, sin, pos, aux, ck, cv, wts):
            return pt_decode_layer_dense(x, cos, sin, 1e-5, 1e-5, pos,
                                         aux, ck, cv, *wts)

        d = 16
        wts = tuple(jnp.ones((d, d)) for _ in range(9))
        args = (jnp.ones((2, 1, d)), jnp.ones((8, 4)), jnp.ones((8, 4)),
                jnp.zeros((2,), jnp.int32), jnp.zeros((2,), jnp.int32),
                jnp.ones((2, 32, 1, 4)), jnp.ones((2, 32, 1, 4)), wts)
        closed = jax.make_jaxpr(outer)(*args)
        out = decode_fusion_pass(closed)
        assert decode_fusion_pass.last_rewrites.get("declined", 0) >= 1
        assert not fused_decode_calls(out)

    def test_unmarkable_model_fails_loudly(self, setup, monkeypatch):
        """megakernel=True on a model that never marks must raise, not
        silently serve the unfused program."""
        model, cfg = setup
        monkeypatch.setattr(LlamaDecoderLayer, "_markable",
                            lambda self, *a: False)
        eng = ContinuousBatchingEngine(
            model, num_slots=2, max_len=64, decode_block=4,
            prompt_buckets=(16,), megakernel=True)
        srv = Server(eng)
        srv.submit(_prompts(cfg, 3, (5,))[0], max_new_tokens=3)
        with pytest.raises(RuntimeError, match="no decode layer"):
            srv.run_until_idle()


class TestMegakernelRouting:
    def test_refused_alongside_explicit_backend(self, setup):
        model, cfg = setup
        backend = ModelStepBackend(model, 2, 64, 4)
        with pytest.raises(ValueError, match="megakernel"):
            ContinuousBatchingEngine(backend=backend, megakernel=True)

    def test_env_routes_factory_never_prebuilt_backend(self, setup,
                                                       monkeypatch):
        model, cfg = setup
        backend = ModelStepBackend(model, 2, 64, 4)   # env unset: plain
        monkeypatch.setenv("PT_SERVING_MEGAKERNEL", "1")
        routed = ContinuousBatchingEngine(model, num_slots=2, max_len=64,
                                          decode_block=4,
                                          prompt_buckets=(16,))
        assert routed.megakernel()
        kept = ContinuousBatchingEngine(backend=backend)
        assert not kept.megakernel()

    def test_refused_with_tensor_parallel(self, setup):
        model, cfg = setup
        if jax.device_count() < 2:
            pytest.skip("needs >= 2 (simulated) devices for a TP mesh")
        from paddle_tpu.serving import TPConfig
        with pytest.raises(NotImplementedError, match="megakernel"):
            ContinuousBatchingEngine(
                model, num_slots=2, max_len=64, decode_block=4,
                prompt_buckets=(16,), tp=TPConfig(axes=("mp",)),
                megakernel=True)
