"""Model-zoo coverage: GPT, ERNIE-MoE, diffusion UNet, ViT, MobileNetV2.

Each family gets a forward-shape check plus (for the trainable LMs /
diffusion) a couple of fused train steps asserting the loss moves — the
reference's model tests assert convergence on toy data (SURVEY §4)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer
from paddle_tpu.jit import TrainStep
from paddle_tpu.tensor import Tensor


def _ids(b, s, vocab, seed=0):
    return jnp.asarray(
        np.random.RandomState(seed).randint(0, vocab, (b, s)), jnp.int32)


class TestGPT:
    def test_forward_and_train(self):
        from paddle_tpu.models.gpt import GPTForCausalLM, gpt_tiny_config
        cfg = gpt_tiny_config()
        model = GPTForCausalLM(cfg)
        ids = _ids(2, 16, cfg.vocab_size)
        logits = model(Tensor(ids))
        assert tuple(logits.shape) == (2, 16, cfg.vocab_size)
        opt = optimizer.AdamW(learning_rate=1e-3,
                              parameters=model.parameters())

        def loss_fn(m, batch):
            x, y = batch
            loss, _ = m(x, labels=y)
            return loss
        step = TrainStep(model, loss_fn, opt)
        lab = _ids(2, 16, cfg.vocab_size, seed=1)
        losses = [float(step((Tensor(ids), Tensor(lab)))._value)
                  for _ in range(5)]
        assert losses[-1] < losses[0]


class TestErnieMoE:
    def test_forward_aux_loss_and_train(self):
        from paddle_tpu.models.ernie_moe import (ErnieMoEForCausalLM,
                                                 ernie_moe_tiny_config)
        cfg = ernie_moe_tiny_config()
        model = ErnieMoEForCausalLM(cfg)
        ids = _ids(2, 16, cfg.vocab_size)
        logits = model(Tensor(ids))
        assert tuple(logits.shape) == (2, 16, cfg.vocab_size)
        assert model.ernie.aux_loss() is not None  # MoE layers engaged
        opt = optimizer.AdamW(learning_rate=1e-3,
                              parameters=model.parameters())

        def loss_fn(m, batch):
            x, y = batch
            loss, _ = m(x, labels=y)
            return loss
        step = TrainStep(model, loss_fn, opt)
        lab = _ids(2, 16, cfg.vocab_size, seed=1)
        losses = [float(step((Tensor(ids), Tensor(lab)))._value)
                  for _ in range(5)]
        assert losses[-1] < losses[0]

    def test_expert_params_carry_ep_spec(self):
        from paddle_tpu.models.ernie_moe import (ErnieMoEModel,
                                                 ernie_moe_tiny_config)
        model = ErnieMoEModel(ernie_moe_tiny_config())
        specs = [p._sharding_spec for n, p in model.named_parameters()
                 if "experts" in n]
        assert specs and all(
            s is not None and "ep" in jax.tree.leaves(tuple(s))
            for s in specs)


class TestDiffusion:
    def test_unet_shapes_and_train(self):
        from paddle_tpu.models.diffusion import (LatentDiffusion,
                                                 sdxl_tiny_config)
        cfg = sdxl_tiny_config()
        model = LatentDiffusion(cfg)
        b, hw = 2, cfg.sample_size
        rs = np.random.RandomState(0)
        latents = jnp.asarray(rs.randn(b, cfg.in_channels, hw, hw),
                              jnp.float32)
        ctx = jnp.asarray(rs.randn(b, 8, cfg.cross_attention_dim),
                          jnp.float32)
        noise = jnp.asarray(rs.randn(*latents.shape), jnp.float32)
        ts = jnp.asarray([10, 500], jnp.int32)
        # direct UNet output shape
        out = model.unet(Tensor(latents), Tensor(ts), Tensor(ctx))
        assert tuple(out.shape) == tuple(latents.shape)
        opt = optimizer.AdamW(learning_rate=1e-3,
                              parameters=model.parameters())

        def loss_fn(m, batch):
            l, c, n, t = batch
            return m(l, c, n, t)
        step = TrainStep(model, loss_fn, opt)
        batch = tuple(map(Tensor, (latents, ctx, noise, ts)))
        losses = [float(step(batch)._value) for _ in range(4)]
        assert np.isfinite(losses).all()
        assert losses[-1] < losses[0]

    def test_vae_roundtrip_and_train(self):
        from paddle_tpu.models.diffusion import AutoencoderKL
        paddle.seed(0)
        vae = AutoencoderKL(in_channels=3, latent_channels=4,
                            block_out_channels=(8, 16))
        x = Tensor(jnp.asarray(np.random.RandomState(0).rand(
            1, 3, 16, 16), jnp.float32))
        mean, logvar = vae.encode(x)
        assert tuple(mean.shape) == (1, 4, 8, 8)      # 1/2 res per stage
        assert tuple(logvar.shape) == (1, 4, 8, 8)
        rec = vae.decode(vae.sample_latent(x))
        assert tuple(rec.shape) == tuple(x.shape)
        loss = vae(x)
        loss.backward()
        g = vae.conv_in.weight.grad
        assert g is not None and np.isfinite(np.asarray(g._value)).all()

    def test_text_to_image_pipeline(self):
        from paddle_tpu.models.diffusion import (AutoencoderKL,
                                                 DDIMScheduler,
                                                 StableDiffusionPipeline,
                                                 UNet2DConditionModel,
                                                 sdxl_tiny_config)
        paddle.seed(0)
        cfg = sdxl_tiny_config(sample_size=8)
        pipe = StableDiffusionPipeline(
            UNet2DConditionModel(cfg),
            AutoencoderKL(in_channels=3, latent_channels=4,
                          block_out_channels=(8, 16)),
            DDIMScheduler())
        rs = np.random.RandomState(1)
        pe = Tensor(jnp.asarray(rs.rand(1, 4, cfg.cross_attention_dim),
                                jnp.float32))
        ne = Tensor(jnp.zeros((1, 4, cfg.cross_attention_dim),
                              jnp.float32))
        img = pipe(pe, ne, steps=2, guidance_scale=3.0)
        assert tuple(img.shape) == (1, 3, 16, 16)
        assert np.isfinite(np.asarray(img._value)).all()
        # guidance direction actually matters: cfg-scale changes output
        img2 = pipe(pe, ne, steps=2, guidance_scale=0.0)
        assert not np.allclose(np.asarray(img._value),
                               np.asarray(img2._value))

    def test_ddpm_roundtrip(self):
        from paddle_tpu.models.diffusion import DDPMScheduler
        sched = DDPMScheduler(num_train_timesteps=100)
        x0 = jnp.ones((1, 2, 4, 4))
        noise = jnp.zeros_like(x0)
        # zero noise at t=0 stays ~x0
        noisy = sched.add_noise(x0, noise, jnp.asarray([0]))
        np.testing.assert_allclose(np.asarray(noisy),
                                   np.sqrt(float(sched.alphas_cumprod[0])) *
                                   np.asarray(x0), rtol=1e-5)

    def test_ddim_step_recovers_x0_with_true_noise(self):
        from paddle_tpu.models.diffusion import DDIMScheduler
        sched = DDIMScheduler(num_train_timesteps=100)
        rs = np.random.RandomState(0)
        x0 = jnp.asarray(rs.randn(1, 2, 4, 4), jnp.float32)
        eps = jnp.asarray(rs.randn(1, 2, 4, 4), jnp.float32)
        t = jnp.asarray(50)
        xt = sched.add_noise(x0, eps, t)
        # stepping all the way to alpha=1 with the true noise returns x0
        x_prev = sched.step(eps, t, xt, prev_timestep=jnp.asarray(-1))
        np.testing.assert_allclose(np.asarray(x_prev), np.asarray(x0),
                                   rtol=1e-3, atol=1e-4)


class TestVision:
    def test_vit_forward(self):
        from paddle_tpu.vision.models import VisionTransformer
        model = VisionTransformer(image_size=32, patch_size=8, embed_dim=64,
                                  depth=2, num_heads=4, num_classes=10)
        x = Tensor(jnp.ones((2, 3, 32, 32), jnp.float32))
        out = model(x)
        assert tuple(out.shape) == (2, 10)

    def test_mobilenet_v2_forward(self):
        from paddle_tpu.vision.models import mobilenet_v2
        model = mobilenet_v2(scale=0.25, num_classes=10)
        model.eval()
        x = Tensor(jnp.ones((1, 3, 32, 32), jnp.float32))
        out = model(x)
        assert tuple(out.shape) == (1, 10)
