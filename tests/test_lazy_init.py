"""paddle.LazyGuard — deferred parameter init (reference:
python/paddle/fluid/lazy_init.py LazyGuard — verify): construction
under the guard creates LazyParameter leaves with known shape/dtype
and zero initializer compute; first value access materializes."""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer
from paddle_tpu.tensor import LazyParameter


def test_lazy_construction_defers_and_counts():
    with paddle.LazyGuard():
        net = nn.Sequential(nn.Linear(16, 64), nn.ReLU(),
                            nn.Linear(64, 8))
    ps = list(net.parameters())
    assert all(isinstance(p, LazyParameter) for p in ps)
    assert not any(p.materialized() for p in ps)
    # shape/dtype/size/ndim metadata without materializing
    assert net[0].weight.shape == [16, 64]
    assert net[0].weight.ndim == 2
    assert net[0].weight.size == 16 * 64
    assert str(net[0].weight.dtype) == "float32"
    assert "unmaterialized" in repr(net[0].weight)
    assert not any(p.materialized() for p in ps)
    total = sum(p.size for p in ps)
    assert total == 16 * 64 + 64 + 64 * 8 + 8


def test_forward_materializes_with_init_parity():
    paddle.seed(11)
    with paddle.LazyGuard():
        lazy = nn.Linear(4, 3)
    assert not lazy.weight.materialized()
    x = paddle.to_tensor(
        np.random.RandomState(0).rand(2, 4).astype("float32"))
    out = lazy(x)
    assert lazy.weight.materialized()
    paddle.seed(11)
    eager = nn.Linear(4, 3)
    np.testing.assert_allclose(out.numpy(), eager(x).numpy(), rtol=1e-6)


def test_lazy_model_trains_and_saves():
    paddle.seed(0)
    with paddle.LazyGuard():
        net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(),
                            nn.Linear(16, 2))
    opt = optimizer.SGD(learning_rate=0.1,
                        parameters=net.parameters())
    rs = np.random.RandomState(1)
    x = paddle.to_tensor(rs.rand(4, 8).astype("float32"))
    y = paddle.to_tensor(rs.rand(4, 2).astype("float32"))
    losses = []
    for _ in range(5):
        loss = ((net(x) - y) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss.item()))
    assert losses[-1] < losses[0]
    sd = net.state_dict()           # materializes remaining leaves
    assert all(hasattr(v, "numpy") for v in sd.values())


def test_nested_guard_and_normal_after_exit():
    with paddle.LazyGuard():
        with paddle.LazyGuard():
            inner = nn.Linear(2, 2)
        still_lazy = nn.Linear(2, 2)
    after = nn.Linear(2, 2)
    assert isinstance(inner.weight, LazyParameter)
    assert isinstance(still_lazy.weight, LazyParameter)
    assert not isinstance(after.weight, LazyParameter)
