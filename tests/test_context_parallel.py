"""Ring attention + Ulysses SEP parity vs dense attention (SURVEY §4:
serial-vs-parallel parity for every parallelism dimension)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

import paddle_tpu as paddle
from paddle_tpu.distributed.context_parallel import (ring_attention_spmd,
                                                     ulysses_attention_spmd)
from paddle_tpu.distributed.mesh import set_current_mesh
from paddle_tpu.distributed.sharding_utils import place_model, shard_batch
from paddle_tpu.ops.pallas.flash_attention import _xla_sdpa
from paddle_tpu.tensor import Tensor


@pytest.fixture(autouse=True)
def _clear_mesh():
    yield
    set_current_mesh(None)


def _sep_mesh(S):
    return Mesh(np.array(jax.devices()[:S]), ("sep",))


def _qkv(b=2, s=32, h=4, hk=None, d=8, seed=0):
    hk = hk or h
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    q = jax.random.normal(ks[0], (b, s, h, d))
    k = jax.random.normal(ks[1], (b, s, hk, d))
    v = jax.random.normal(ks[2], (b, s, hk, d))
    return q, k, v


class TestRingAttention:
    @pytest.mark.parametrize("causal", [True, False])
    def test_forward_parity(self, causal):
        q, k, v = _qkv()
        mesh = _sep_mesh(4)
        out = jax.jit(lambda *a: ring_attention_spmd(
            *a, mesh=mesh, causal=causal))(q, k, v)
        ref = _xla_sdpa(q, k, v, None, causal, 0.0, None)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    def test_gqa(self):
        q, k, v = _qkv(h=8, hk=2)
        mesh = _sep_mesh(4)
        out = jax.jit(lambda *a: ring_attention_spmd(
            *a, mesh=mesh, causal=True))(q, k, v)
        ref = _xla_sdpa(q, k, v, None, True, 0.0, None)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    def test_grad_parity(self):
        q, k, v = _qkv()
        mesh = _sep_mesh(4)

        def loss_ring(q, k, v):
            return ring_attention_spmd(q, k, v, mesh=mesh,
                                       causal=True).sum()

        def loss_ref(q, k, v):
            return _xla_sdpa(q, k, v, None, True, 0.0, None).sum()

        g1 = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
        g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5)

    def test_sep1_fallback(self):
        q, k, v = _qkv()
        mesh = _sep_mesh(1)
        out = ring_attention_spmd(q, k, v, mesh=mesh, causal=True)
        ref = _xla_sdpa(q, k, v, None, True, 0.0, None)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-6)


class TestUlysses:
    @pytest.mark.parametrize("causal", [True, False])
    def test_forward_parity(self, causal):
        q, k, v = _qkv(h=8)
        mesh = _sep_mesh(4)
        out = jax.jit(lambda *a: ulysses_attention_spmd(
            *a, mesh=mesh, causal=causal))(q, k, v)
        ref = _xla_sdpa(q, k, v, None, causal, 0.0, None)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    def test_grad_parity(self):
        q, k, v = _qkv(h=8)
        mesh = _sep_mesh(4)

        def loss_u(q, k, v):
            return ulysses_attention_spmd(q, k, v, mesh=mesh,
                                          causal=True).sum()

        def loss_ref(q, k, v):
            return _xla_sdpa(q, k, v, None, True, 0.0, None).sum()

        g1 = jax.jit(jax.grad(loss_u, argnums=(0, 1, 2)))(q, k, v)
        g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5)

    def test_heads_not_divisible_raises(self):
        q, k, v = _qkv(h=6)
        mesh = _sep_mesh(4)
        with pytest.raises(ValueError, match="not divisible"):
            ulysses_attention_spmd(q, k, v, mesh=mesh)


class TestLlamaContextParallel:
    @pytest.mark.parametrize("mode", ["ring", "ulysses"])
    def test_loss_parity_and_train(self, mode):
        from paddle_tpu import optimizer
        from paddle_tpu.jit import TrainStep
        from paddle_tpu.models.llama import (LlamaForCausalLM,
                                             llama_tiny_config)
        paddle.seed(21)
        cfg_ref = llama_tiny_config(tensor_parallel=False)
        ref = LlamaForCausalLM(cfg_ref)
        paddle.seed(21)
        cfg_cp = llama_tiny_config(tensor_parallel=False,
                                   sequence_parallel=True,
                                   sequence_parallel_mode=mode)
        cp = LlamaForCausalLM(cfg_cp)
        cp.set_state_dict(ref.state_dict())

        np.random.seed(9)
        ids = np.random.randint(0, cfg_ref.vocab_size, (2, 32))
        ids = ids.astype(np.int32)
        labels = np.roll(ids, -1, 1).astype(np.int32)

        l_ref, _ = ref(Tensor(jnp.asarray(ids)), Tensor(jnp.asarray(labels)))

        mesh = _sep_mesh(4)
        set_current_mesh(mesh)
        place_model(cp, mesh)

        def loss_fn(m, batch):
            i, l = batch
            loss, _ = m(i, l)
            return loss

        opt = optimizer.AdamW(learning_rate=1e-3,
                              parameters=cp.parameters())
        step = TrainStep(cp, loss_fn, opt)
        batch = (shard_batch(mesh, paddle.to_tensor(ids), P(None, "sep")),
                 shard_batch(mesh, paddle.to_tensor(labels),
                             P(None, "sep")))
        l0 = float(step(batch).item())
        np.testing.assert_allclose(l0, float(l_ref.item()), rtol=2e-4)
        l1 = float(step(batch).item())
        assert np.isfinite(l1) and l1 < l0


class TestRingPallasBlocks:
    """VERDICT r2 missing #4: the ring inner block must run the Pallas
    flash kernel (not the O(chunk^2) XLA path) when shapes tile."""

    @pytest.fixture
    def interpret(self):
        from paddle_tpu.ops.pallas import flash_attention as fa
        fa._FORCE_INTERPRET = True
        yield fa
        fa._FORCE_INTERPRET = False

    def test_flash_block_matches_xla_block(self, interpret):
        from paddle_tpu.distributed.context_parallel import _xla_block
        fa = interpret
        q, k, v = _qkv(b=1, s=32, h=4, hk=2, d=16)
        sc = 1.0 / np.sqrt(q.shape[-1])
        for causal in (False, True):
            o_p, lse_p = fa.flash_block(q, k, v, causal, sc)
            o_x, lse_x = _xla_block(q, k, v, causal, sc)
            np.testing.assert_allclose(np.asarray(o_p), np.asarray(o_x),
                                       rtol=2e-3, atol=2e-4)
            np.testing.assert_allclose(np.asarray(lse_p),
                                       np.asarray(lse_x),
                                       rtol=2e-3, atol=2e-4)

    def test_flash_block_grads_both_cotangents(self, interpret):
        """lse cotangent folds into the delta slot — check against the
        einsum block with an lse-dependent scalar loss."""
        from paddle_tpu.distributed.context_parallel import _xla_block
        fa = interpret
        q, k, v = _qkv(b=1, s=32, h=4, hk=2, d=16)
        sc = 1.0 / np.sqrt(q.shape[-1])

        def loss_p(q, k, v):
            o, lse = fa.flash_block(q, k, v, True, sc)
            return (o ** 2).sum() + (jnp.sin(lse)).sum()

        def loss_x(q, k, v):
            o, lse = _xla_block(q, k, v, True, sc)
            return (o.astype(q.dtype) ** 2).sum() + (jnp.sin(lse)).sum()

        gp = jax.grad(loss_p, argnums=(0, 1, 2))(q, k, v)
        gx = jax.grad(loss_x, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gp, gx):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-2, atol=2e-3)

    def test_ring_uses_pallas_and_matches_dense(self, interpret):
        fa = interpret
        q, k, v = _qkv(b=1, s=64, h=4, hk=2, d=16)
        mesh = _sep_mesh(4)
        out = ring_attention_spmd(q, k, v, mesh=mesh, causal=True)
        assert fa.sdpa_last_dispatch() == "ring_pallas"
        ref = _xla_sdpa(q, k, v, None, True, 0.0,
                        1.0 / np.sqrt(q.shape[-1]))
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-3, atol=2e-4)

    def test_ring_pallas_grad_parity(self, interpret):
        q, k, v = _qkv(b=1, s=64, h=2, hk=2, d=16)
        mesh = _sep_mesh(4)
        sc = 1.0 / np.sqrt(q.shape[-1])

        def ring_loss(q, k, v):
            return (ring_attention_spmd(
                q, k, v, mesh=mesh, causal=True) ** 2).sum()

        def dense_loss(q, k, v):
            return (_xla_sdpa(q, k, v, None, True, 0.0, sc) ** 2).sum()
        gr = jax.grad(ring_loss, argnums=(0, 1, 2))(q, k, v)
        gd = jax.grad(dense_loss, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gr, gd):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-2, atol=2e-3)

    def test_ring_pallas_bf16(self, interpret):
        """bf16 is the flagship training dtype: the cond branches must
        agree on dtype (block output is cast to the f32 merge dtype)."""
        q, k, v = _qkv(b=1, s=64, h=2, hk=2, d=16)
        q, k, v = (x.astype(jnp.bfloat16) for x in (q, k, v))
        mesh = _sep_mesh(4)
        out = ring_attention_spmd(q, k, v, mesh=mesh, causal=True)
        assert out.dtype == jnp.bfloat16
        ref = _xla_sdpa(q, k, v, None, True, 0.0,
                        1.0 / np.sqrt(q.shape[-1]))
        np.testing.assert_allclose(
            np.asarray(out, np.float32), np.asarray(ref, np.float32),
            rtol=5e-2, atol=5e-2)
