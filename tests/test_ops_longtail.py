"""Long-tail op additions (round 2): special functions, integration,
distance, indexing, vision layout (reference: python/paddle/tensor/
math.py + manipulation.py + nn/functional/vision.py — OpTest pattern:
numpy/scipy reference comparison)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F


def t(a):
    return paddle.to_tensor(a)


class TestMathLongTail:
    def test_special_functions(self):
        np.testing.assert_allclose(
            paddle.sinc(t(np.array([0.5], "float32"))).numpy(),
            np.sinc([0.5]), rtol=1e-6)
        assert bool(paddle.signbit(t(np.array([-1.], "float32")))
                    .numpy()[0])
        np.testing.assert_allclose(
            paddle.exp2(t(np.array([3.], "float32"))).numpy(), [8.0])
        np.testing.assert_allclose(
            paddle.float_power(t(np.array([2.], "float32")), 3).numpy(),
            [8.0])
        np.testing.assert_allclose(
            paddle.ldexp(t(np.array([1.5], "float32")),
                         t(np.array([2], "int32"))).numpy(), [6.0])
        np.testing.assert_allclose(
            paddle.polygamma(t(np.array([2.0], "float32")), 1).numpy(),
            [float(np.pi ** 2 / 6 - 1)], rtol=1e-4)
        np.testing.assert_allclose(
            paddle.i0e(t(np.array([1.0], "float32"))).numpy(),
            [0.4657596], rtol=1e-5)

    def test_integration(self):
        import scipy.integrate as si
        x = np.linspace(0, 1, 5).astype("float32")
        np.testing.assert_allclose(
            paddle.trapezoid(t(x), dx=0.25).numpy(),
            np.trapezoid(x, dx=0.25), rtol=1e-6)
        np.testing.assert_allclose(
            paddle.cumulative_trapezoid(t(x), dx=0.25).numpy(),
            si.cumulative_trapezoid(x, dx=0.25), rtol=1e-5)
        xs = np.array([0., 0.5, 2.0], "float32")
        ys = xs ** 2
        np.testing.assert_allclose(
            paddle.trapezoid(t(ys), t(xs)).numpy(),
            np.trapezoid(ys, xs), rtol=1e-6)

    def test_distance_and_blas(self):
        a = np.random.RandomState(0).rand(4, 3).astype("float32")
        b = np.random.RandomState(1).rand(5, 3).astype("float32")
        ref = np.sqrt(((a[:, None] - b[None]) ** 2).sum(-1))
        np.testing.assert_allclose(paddle.cdist(t(a), t(b)).numpy(),
                                   ref, rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(
            paddle.cdist(t(a), t(b), p=1.0).numpy(),
            np.abs(a[:, None] - b[None]).sum(-1), rtol=1e-4)
        i_ = np.random.RandomState(2).rand(2, 3, 4).astype("float32")
        m1 = np.random.RandomState(3).rand(2, 3, 5).astype("float32")
        m2 = np.random.RandomState(4).rand(2, 5, 4).astype("float32")
        np.testing.assert_allclose(
            paddle.baddbmm(t(i_), t(m1), t(m2), beta=0.5,
                           alpha=2.0).numpy(),
            0.5 * i_ + 2.0 * (m1 @ m2), rtol=1e-5)

    def test_renorm_nanquantile_vander(self):
        w = np.random.RandomState(5).rand(4, 6).astype("float32") * 10
        rn = paddle.renorm(t(w), p=2.0, axis=0, max_norm=1.0).numpy()
        assert (np.sqrt((rn ** 2).sum(axis=1)) <= 1.0 + 1e-4).all()
        np.testing.assert_allclose(
            paddle.nanquantile(t(np.array([1., np.nan, 3.], "float32")),
                               0.5).numpy(), 2.0)
        np.testing.assert_allclose(
            paddle.vander(t(np.array([1., 2., 3.], "float32")),
                          n=3).numpy(),
            np.vander([1., 2., 3.], 3), rtol=1e-6)

    def test_grad_flows_through_new_ops(self):
        x = t(np.array([1.0, 2.0], "float32"))
        x.stop_gradient = False
        paddle.cdist(x.reshape([2, 1]), x.reshape([2, 1])).sum().backward()
        assert x.grad is not None


class TestManipulationLongTail:
    def test_index_fill(self):
        x = np.arange(12, dtype="float32").reshape(3, 4)
        out = paddle.index_fill(t(x), t(np.array([0, 2], "int32")),
                                0, -1.0).numpy()
        assert (out[[0, 2]] == -1).all() and (out[1] == x[1]).all()

    def test_unflatten_as_strided(self):
        assert paddle.unflatten(t(np.ones((2, 6), "float32")),
                                1, [2, 3]).shape == [2, 2, 3]
        s = paddle.as_strided(t(np.arange(10, dtype="float32")),
                              [3, 3], [3, 1]).numpy()
        ref = np.lib.stride_tricks.as_strided(
            np.arange(10, dtype="float32"), (3, 3), (12, 4))
        np.testing.assert_allclose(s, ref)


class TestVisionLongTail:
    def test_pixel_shuffle_round_trip(self):
        x = np.arange(2 * 8 * 2 * 2, dtype="float32").reshape(2, 8, 2, 2)
        ps = F.pixel_shuffle(t(x), 2)
        assert ps.shape == [2, 2, 4, 4]
        np.testing.assert_allclose(F.pixel_unshuffle(ps, 2).numpy(), x)

    def test_channel_shuffle_permutes(self):
        x = np.arange(6, dtype="float32").reshape(1, 6, 1, 1)
        out = F.channel_shuffle(t(x), 3).numpy().reshape(-1)
        np.testing.assert_allclose(out, [0, 2, 4, 1, 3, 5])

    def test_temporal_shift_shapes_and_content(self):
        x = np.random.RandomState(0).rand(8, 4, 3, 3).astype("float32")
        out = F.temporal_shift(t(x), seg_num=4).numpy()
        assert out.shape == x.shape
        x5 = x.reshape(2, 4, 4, 3, 3)
        np.testing.assert_allclose(out.reshape(2, 4, 4, 3, 3)[:, :-1, 0],
                                   x5[:, 1:, 0])   # shift-back channel

    def test_fold_inverts_unfold(self):
        img = np.random.RandomState(0).rand(2, 3, 8, 8).astype("float32")
        cols = F.unfold(t(img), kernel_sizes=2, strides=2)
        assert cols.shape == [2, 12, 16]
        back = F.fold(cols, output_sizes=(8, 8), kernel_sizes=2,
                      strides=2)
        np.testing.assert_allclose(back.numpy(), img, rtol=1e-6)

    def test_fold_overlapping_sums(self):
        img = np.ones((1, 1, 4, 4), "float32")
        cols = F.unfold(t(img), kernel_sizes=3, strides=1)
        back = F.fold(cols, output_sizes=(4, 4), kernel_sizes=3,
                      strides=1).numpy()
        # center pixels covered by 4 blocks, corners by 1
        assert back[0, 0, 0, 0] == 1.0 and back[0, 0, 1, 1] == 4.0


class TestHistogramdd:
    def test_ragged_bins_and_contract(self):
        rs = np.random.RandomState(0)
        x = rs.rand(100, 2).astype("float32")
        h, edges = paddle.histogramdd(t(x), bins=[3, 5])
        ref_h, ref_e = np.histogramdd(x, bins=[3, 5])
        np.testing.assert_allclose(h.numpy(), ref_h)
        assert len(edges) == 2
        np.testing.assert_allclose(edges[0].numpy(), ref_e[0], rtol=1e-5)
        np.testing.assert_allclose(edges[1].numpy(), ref_e[1], rtol=1e-5)


class TestMultiDynamicAxisExport:
    def test_two_dynamic_dims(self, tmp_path):
        from paddle_tpu import jit, nn
        from paddle_tpu.static import InputSpec
        paddle.seed(0)
        m = nn.Linear(4, 2)
        jit.save(m, str(tmp_path / "dyn2"),
                 input_spec=[InputSpec(shape=[None, None, 4],
                                       dtype="float32")])
        loaded = jit.load(str(tmp_path / "dyn2"))
        for b, s in ((2, 3), (5, 7)):
            out = loaded(t(np.ones((b, s, 4), "float32")))
            assert out.shape == [b, s, 2]


class TestRegistryBootstrapOrder:
    def test_register_before_query_keeps_builtins(self):
        # fresh-module semantics simulated via the private flag
        from paddle_tpu.ops import registry
        assert registry.get_op_meta("matmul") is not None
        registry.register_op("my_early_op", amp="white")
        assert registry.get_op_meta("matmul") is not None
        assert len(registry.all_ops()) > 200


class TestLongTailReviewFixes:
    def test_index_fill_inplace_grad(self):
        x = t(np.ones((3, 4), "float32"))
        x.stop_gradient = False
        paddle.index_fill_(x, t(np.array([0, 2], "int32")), 0, 0.0)
        (x * 2).sum().backward()
        # filled rows must NOT receive gradient through the fill
        g = x.grad.numpy()
        assert (g[[0, 2]] == 0).all(), g
        assert (g[1] == 2).all(), g

    def test_index_fill_outofplace_grad_zero_on_filled(self):
        x = t(np.ones((3, 4), "float32"))
        x.stop_gradient = False
        out = paddle.index_fill(x, t(np.array([0, 2], "int32")), 0, 0.0)
        (out * 2).sum().backward()
        g = x.grad.numpy()
        assert (g[[0, 2]] == 0).all() and (g[1] == 2).all()

    def test_cdist_self_distance_grad_finite(self):
        x = t(np.array([[0., 0.], [1., 1.]], "float32"))
        x.stop_gradient = False
        paddle.cdist(x, x).sum().backward()
        assert np.isfinite(x.grad.numpy()).all()

    def test_unfold_fold_four_element_paddings(self):
        img = np.random.RandomState(0).rand(1, 2, 4, 4).astype("float32")
        cols = F.unfold(t(img), kernel_sizes=2, strides=2,
                        paddings=[1, 0, 1, 0])   # top/left/bottom/right
        assert cols.shape[0] == 1
        back = F.fold(cols, output_sizes=(4, 4), kernel_sizes=2,
                      strides=2, paddings=[1, 0, 1, 0])
        np.testing.assert_allclose(back.numpy(), img, rtol=1e-6)


class TestR3FinalApiAdditions:
    """isin/shape/log_normal/matrix_transpose/positive/set_printoptions —
    the last missing names from the top-level API probe (reference:
    python/paddle/tensor/{math,random,linalg}.py — verify)."""

    def test_isin(self):
        x = t(np.array([[1, 2], [3, 4]], "int32"))
        test = t(np.array([2, 4], "int32"))
        np.testing.assert_array_equal(
            paddle.isin(x, test).numpy(), [[False, True], [False, True]])
        np.testing.assert_array_equal(
            paddle.isin(x, test, invert=True).numpy(),
            [[True, False], [True, False]])

    def test_shape_op(self):
        s = paddle.shape(t(np.ones((2, 5, 3), "float32")))
        assert s.numpy().tolist() == [2, 5, 3]
        assert s.numpy().dtype == np.int32

    def test_matrix_transpose(self):
        x = np.random.RandomState(0).rand(2, 3, 4).astype("float32")
        np.testing.assert_array_equal(
            paddle.matrix_transpose(t(x)).numpy(), x.swapaxes(-2, -1))
        with pytest.raises(ValueError):
            paddle.matrix_transpose(t(np.ones((3,), "float32")))

    def test_positive(self):
        x = t(np.array([-1.0, 2.0], "float32"))
        np.testing.assert_array_equal(paddle.positive(x).numpy(), [-1., 2.])
        with pytest.raises(TypeError):
            paddle.positive(t(np.array([True])))

    def test_log_normal_moments(self):
        paddle.seed(7)
        s = paddle.log_normal(0.0, 0.5, shape=[4000])
        lm = np.log(s.numpy())
        assert (s.numpy() > 0).all()
        assert abs(lm.mean()) < 0.1 and abs(lm.std() - 0.5) < 0.1

    def test_log_normal_inplace(self):
        paddle.seed(7)
        x = t(np.zeros((200,), "float32"))
        out = x.log_normal_(0.0, 1.0)
        assert out is x and (x.numpy() > 0).all()

    def test_set_printoptions(self):
        paddle.set_printoptions(precision=2, sci_mode=False)
        try:
            assert "1.23" in repr(t(np.array([1.23456], "float32")))
        finally:
            np.set_printoptions(precision=8, suppress=False)


class TestTensorMethodParity:
    """Method-parity probe: the r3-continuation bindings (reference:
    python/paddle/tensor/tensor.prototype.pyi method surface — verify)."""

    def test_bound_methods_exist_and_work(self):
        x = t(np.ones((2, 2), "float32") * 0.5)
        for m in ("acos asin atan cosh sinh digamma lgamma erfinv frac "
                  "logit sgn conj angle real imag rad2deg deg2rad rank "
                  "diff").split():
            assert hasattr(x, m), m
        np.testing.assert_allclose(x.acos().numpy(), np.arccos(0.5),
                                   rtol=1e-6)
        m = t(np.eye(2, dtype="float32") * 4)
        np.testing.assert_allclose(m.cholesky().numpy(),
                                   np.eye(2) * 2, atol=1e-6)
        v = t(np.array([1.0, 2.0], "float32"))
        np.testing.assert_allclose(m.mv(v).numpy(), [4.0, 8.0])
        s = t(np.array([1.0, 3.0, 5.0], "float32"))
        assert s.searchsorted(
            t(np.array([4.0], "float32"))).numpy().tolist() == [2]
        w = t(np.arange(6, dtype="float32").reshape(2, 3))
        assert w.unflatten(1, [3, 1]).shape == [2, 3, 1]
        assert w.slice([1], [0], [2]).shape == [2, 2]
        assert w.index_sample(
            t(np.array([[0], [1]], "int32"))).shape == [2, 1]

    def test_inplace_method_family(self):
        y = t(np.full((2, 2), 4.0, "float32"))
        out = y.sqrt_()
        assert out is y
        np.testing.assert_allclose(y.numpy(), 2.0)
        y.exp_()
        np.testing.assert_allclose(y.numpy(), np.exp(2.0), rtol=1e-6)
        y.reciprocal_()
        np.testing.assert_allclose(y.numpy(), np.exp(-2.0), rtol=1e-6)
        z = t(np.array([1.7, -1.7], "float32"))
        np.testing.assert_allclose(z.floor_().numpy(), [1.0, -2.0])

    def test_inplace_exp_grad_records_on_tape(self):
        # _inplace reuses the out-of-place op's tape node: grads flow
        y = t(np.ones((3,), "float32"))
        y.stop_gradient = False
        z = y * 2.0
        z.exp_()
        z.sum().backward()
        np.testing.assert_allclose(y.grad.numpy(), 2 * np.exp(2.0),
                                   rtol=1e-5)

    def test_inplace_rejected_in_static_mode(self):
        paddle.enable_static()
        try:
            x = paddle.static.data("x_ip", [2], "float32")
            y = x * 2.0
            with pytest.raises(RuntimeError, match="static-graph mode"):
                y.exp_()
        finally:
            paddle.disable_static()

    def test_inplace_manipulation_tape(self):
        # scatter_/reshape_ previously re-pointed at the out-of-place
        # node and silently fell off the tape (same class as exp_ bug)
        src = t(np.ones((3, 2), "float32"))
        src.stop_gradient = False
        x = src * 2.0
        upd = t(np.full((1, 2), 10.0, "float32"))
        upd.stop_gradient = False
        x.scatter_(t(np.array([1], "int32")), upd)
        x.sum().backward()
        g = src.grad.numpy()
        assert (g[1] == 0).all(), g      # overwritten row: no grad
        assert (g[0] == 2).all() and (g[2] == 2).all(), g
        np.testing.assert_allclose(upd.grad.numpy(), 1.0)

        y = t(np.arange(6, dtype="float32"))
        y.stop_gradient = False
        z = y * 3.0
        z.reshape_([2, 3])
        assert z.shape == [2, 3]
        z.sum().backward()
        np.testing.assert_allclose(y.grad.numpy(), 3.0)

    def test_inplace_relu_tape(self):
        import paddle_tpu.nn.functional as F
        y = t(np.array([-1.0, 2.0], "float32"))
        y.stop_gradient = False
        z = y * 2.0
        F.relu_(z)
        z.sum().backward()
        np.testing.assert_allclose(y.grad.numpy(), [0.0, 2.0])
