"""Bandwidth-true quantized serving (serving/quant.py +
ops/pallas/paged_attention.py int8 in-read dequant):

- in-kernel/in-read int8-KV decode parity pinned against the
  dequant-then-dense reference (interpret-mode kernel AND the CPU
  per-block scan fallback), plus greedy engine streams token-identical
  to the oracle route;
- a recursive jaxpr walk asserting the quantized decode program holds
  NO dense fp32 KV transient (neither the arena shape nor the gathered
  per-slot dense shape);
- weight-only int8/int4 serving: engine streams BIT-IDENTICAL to
  generate() on a host-dequantized twin model (the in-graph dequant is
  exact), composing with paged/kv_int8/spec, with the
  runtime-queryable error bounds and registry bytes accounting;
- the routing matrix: explicit backends never rerouted by
  PT_SERVING_QUANT_WEIGHTS, quant= alongside an explicit backend /
  bogus configs / psum+quant refused loudly.
"""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny_config
from paddle_tpu.nn.quant import dequantize_array, quantize_array
from paddle_tpu.serving import (ContinuousBatchingEngine, PagedEngine,
                                QuantConfig, Scheduler, Server,
                                SpecConfig, SpecEngine)
from paddle_tpu.serving.quant import resolve_quant_config

_QUANT_PATTERNS = ("q_proj", "k_proj", "v_proj", "o_proj", "gate_proj",
                   "up_proj", "down_proj", "lm_head")


@pytest.fixture(scope="module")
def setup():
    """One model + its host-dequantized int8 twin for the whole file.
    The twin is THE oracle: the engine's in-graph dequant must make
    quantized serving bit-identical to generate() on the twin."""
    paddle.seed(0)
    cfg = llama_tiny_config(tensor_parallel=False)
    model = LlamaForCausalLM(cfg)
    twin = LlamaForCausalLM(cfg)
    for (n, p), (_, tp_) in zip(model.named_parameters(),
                                twin.named_parameters()):
        v = p._value
        if v.ndim == 2 and any(s in n for s in _QUANT_PATTERNS):
            codes, scales = quantize_array(v, 8, -1)
            tp_._value = dequantize_array(codes, scales, 8,
                                          out_dtype=v.dtype)
        else:
            tp_._value = v
    for (_, b), (_, tb) in zip(model.named_buffers(),
                               twin.named_buffers()):
        tb._value = b._value
    return model, twin, cfg


def _ref(model, prompt, max_new, **kw):
    return model.generate(paddle.to_tensor(prompt[None, :]),
                          max_new_tokens=max_new, **kw).numpy()[0]


def _stream(engine, prompts, max_new=6, **submit_kw):
    engine.reset()
    srv = Server(engine)
    rids = [srv.submit(p, max_new_tokens=max_new, **submit_kw)
            for p in prompts]
    res = srv.run_until_idle()
    return [res[r] for r in rids]


def _prompts(cfg, seed, lens):
    rs = np.random.RandomState(seed)
    return [rs.randint(0, cfg.vocab_size, (L,)).astype(np.int32)
            for L in lens]


# ---------------------------------------------------------------------------
# int8 KV: in-read dequant vs the dequant-then-dense oracle
# ---------------------------------------------------------------------------

class TestInt8KVInRead:
    def _arena(self, seed=0):
        from paddle_tpu.ops.pallas import paged_attention as pa
        rs = np.random.RandomState(seed)
        S, MB, BS, KVH, G, D, NB = 3, 4, 8, 2, 2, 16, 16
        H = KVH * G
        q = jnp.asarray(rs.randn(S, H, D).astype(np.float32))
        kc, ks = pa.quantize_kv(
            jnp.asarray(3 * rs.randn(NB, BS, KVH, D).astype(np.float32)))
        vc, vs = pa.quantize_kv(
            jnp.asarray(rs.randn(NB, BS, KVH, D).astype(np.float32)))
        tbl = jnp.asarray(rs.randint(1, NB, (S, MB)).astype(np.int32))
        lens = jnp.asarray([5, 17, 32], jnp.int32)
        return q, kc, vc, ks, vs, tbl, lens, D

    def test_cpu_fallback_matches_oracle(self):
        """The per-block scan fallback (what the whole CPU lane runs)
        matches the dequant-then-dense oracle: same quantized inputs,
        fp32 accumulation reassociated by the online softmax."""
        from paddle_tpu.ops.pallas import paged_attention as pa
        q, kc, vc, ks, vs, tbl, lens, D = self._arena()
        ref = pa.paged_attention_int8_reference(
            q[:, None], kc, vc, ks, vs, tbl, lens, scale=D ** -0.5)[:, 0]
        out = pa._int8_decode_fallback(q, kc, vc, ks, vs, tbl, lens,
                                       scale=D ** -0.5)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5)

    def test_interpret_kernel_matches_oracle(self, monkeypatch):
        """The Pallas int8 kernel (interpret mode on CPU) dequantizes
        code+scale blocks in registers and matches the oracle, GQA
        heads included."""
        pytest.importorskip("jax.experimental.pallas")
        import paddle_tpu.ops.pallas.fused as fused
        from paddle_tpu.ops.pallas import paged_attention as pa
        monkeypatch.setattr(fused, "_FORCE_INTERPRET", True)
        q, kc, vc, ks, vs, tbl, lens, D = self._arena(1)
        out = pa.paged_attention_decode_int8(q, kc, vc, ks, vs, tbl,
                                             lens, scale=D ** -0.5)
        ref = pa.paged_attention_int8_reference(
            q[:, None], kc, vc, ks, vs, tbl, lens, scale=D ** -0.5)[:, 0]
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5)

    def test_int8_kernel_not_dispatched_on_cpu(self):
        """Off-TPU the int8 read must take the per-block fallback (the
        no-fp32-transient lane), never the kernel."""
        import paddle_tpu.ops.pallas.fused as fused
        from paddle_tpu.ops.pallas.paged_attention import _kernel_ok_int8
        if jax.default_backend() == "cpu" and not fused._FORCE_INTERPRET:
            assert not _kernel_ok_int8(jnp.zeros((4, 8, 2, 16), jnp.int8))

    def test_int8_engine_stream_matches_oracle_route(self, setup,
                                                     monkeypatch):
        """Greedy int8-KV engine streams are token-identical whether
        the decode read runs the in-read path (production) or the
        dequant-then-dense oracle — 'within the queryable bound' made
        concrete: the ~1e-6 softmax reassociation never flips argmax on
        this stream."""
        from paddle_tpu.ops.pallas import paged_attention as pa
        model, _, cfg = setup
        e8 = ContinuousBatchingEngine(
            model, num_slots=2, max_len=64, decode_block=4, paged=True,
            block_size=8, prefill_chunk=8, kv_int8=True)
        prompts = _prompts(cfg, 3, (5, 9, 12))
        got = _stream(e8, prompts)
        monkeypatch.setattr(pa, "_FORCE_INT8_REFERENCE", True)
        # fresh engine: the production program is already compiled on
        # e8's backend; the oracle route must trace its own
        e8_ref = ContinuousBatchingEngine(
            model, num_slots=2, max_len=64, decode_block=4, paged=True,
            block_size=8, prefill_chunk=8, kv_int8=True)
        ref = _stream(e8_ref, prompts)
        for a, b in zip(got, ref):
            np.testing.assert_array_equal(a, b)
        e8.manager.assert_consistent()

    def test_quantized_decode_holds_no_dense_fp32_kv(self, setup):
        """Recursive jaxpr walk over the int8 engine's ONE decode-block
        program: no fp32 intermediate of the arena shape
        (num_blocks, block_size, kvh, d) — a whole-arena dequant — and
        none of the gathered per-slot dense shapes
        (S, T, kvh, d) / (S, mb, bs, kvh, d) — the PR 4 transient this
        PR exists to kill. The fp32 engine's program, by contrast, DOES
        read dense-shaped fp32 (sanity that the walk can see one)."""
        from jax.extend.core import ClosedJaxpr, Jaxpr

        def walk(jaxpr):
            for eqn in jaxpr.eqns:
                yield eqn
                for v in eqn.params.values():
                    if isinstance(v, ClosedJaxpr):
                        yield from walk(v.jaxpr)
                    elif isinstance(v, Jaxpr):
                        yield from walk(v)

        def fp32_shapes(engine):
            back = engine.backend
            from paddle_tpu.serving.engine import build_slot_block_fn
            fn = build_slot_block_fn(back._pure, engine.decode_block,
                                     paged=True)
            closed = jax.make_jaxpr(fn)(
                back._pv, back._bv, engine._cache, engine._state)
            shapes = set()
            for eqn in walk(closed.jaxpr):
                for v in list(eqn.outvars) + list(eqn.invars):
                    aval = getattr(v, "aval", None)
                    if aval is not None and \
                            getattr(aval, "dtype", None) == jnp.float32:
                        shapes.add(tuple(aval.shape))
            return shapes

        model, _, cfg = setup
        S, bs = 2, 8
        e8 = ContinuousBatchingEngine(
            model, num_slots=S, max_len=64, decode_block=4, paged=True,
            block_size=bs, prefill_chunk=8, kv_int8=True)
        nb = e8.num_kv_blocks
        mb = e8.max_blocks
        kvh = cfg.num_key_value_heads
        d = cfg.hidden_size // cfg.num_attention_heads
        banned = {(nb, bs, kvh, d),                  # full-arena dequant
                  (S, mb * bs, kvh, d),              # gathered dense
                  (S, mb, bs, kvh, d)}               # pre-reshape gather
        got = fp32_shapes(e8)
        assert not (got & banned), \
            f"quantized decode materializes dense fp32 KV: {got & banned}"
        # sanity: the walk sees the fp32 engine's dense arena reads
        efp = ContinuousBatchingEngine(
            model, num_slots=S, max_len=64, decode_block=4, paged=True,
            block_size=bs, prefill_chunk=8)
        assert (e8.num_kv_blocks, bs, kvh, d) in fp32_shapes(efp)

    def test_fp32_mode_untouched_bit_identical(self, setup):
        """fp32-mode paged streams stay bit-identical to generate() —
        the in-read int8 path must not perturb the fp32 route."""
        model, _, cfg = setup
        engine = ContinuousBatchingEngine(
            model, num_slots=2, max_len=64, decode_block=4, paged=True,
            block_size=8, prefill_chunk=8)
        prompts = _prompts(cfg, 4, (5, 9))
        for got, p in zip(_stream(engine, prompts), prompts):
            np.testing.assert_array_equal(
                got, _ref(model, p, 6, temperature=0.0))


# ---------------------------------------------------------------------------
# weight-only int8/int4 serving
# ---------------------------------------------------------------------------

class TestWeightOnlyServing:
    def test_int8_dense_stream_bit_identical_to_dequant_twin(self,
                                                             setup):
        """The quant engine's greedy stream equals generate() on the
        host-dequantized twin BIT-FOR-BIT (in-graph dequant is the same
        math), with the compile count pinned at 1."""
        model, twin, cfg = setup
        eng = ContinuousBatchingEngine(
            model, num_slots=2, max_len=64, decode_block=4,
            quant="int8")
        prompts = _prompts(cfg, 5, (5, 9, 12))
        for got, p in zip(_stream(eng, prompts), prompts):
            np.testing.assert_array_equal(
                got, _ref(twin, p, 6, temperature=0.0))
        assert eng.decode_compile_count() == 1
        assert 0.0 < eng.weight_error_bound() < 0.1
        b = eng.quant_error_bound()
        assert b["kv"] == 0.0 and b["weights"] > 0.0

    def test_sampled_stream_matches_twin_seed(self, setup):
        """Seeded sampling rides the same key schedule through the
        quantized block — parity with the twin's generate(seed)."""
        model, twin, cfg = setup
        eng = ContinuousBatchingEngine(
            model, num_slots=2, max_len=64, decode_block=4,
            quant=QuantConfig(weights="int8"))
        p = _prompts(cfg, 6, (9,))[0]
        got = _stream(eng, [p], temperature=1.0, top_k=50, seed=7)[0]
        np.testing.assert_array_equal(
            got, _ref(twin, p, 6, do_sample=True, temperature=1.0,
                      top_k=50, seed=7))

    def test_paged_kv_int8_plus_weight_int8(self, setup):
        """The fully quantized stack (int8 arena + int8 weights) serves
        with both bounds positive, ONE decode + ONE chunk program, and
        ~3x fewer bytes per decode step than the fp32 paged engine."""
        model, _, cfg = setup
        q8 = ContinuousBatchingEngine(
            model, num_slots=2, max_len=64, decode_block=4, paged=True,
            block_size=8, prefill_chunk=8, kv_int8=True, quant="int8")
        fp = ContinuousBatchingEngine(
            model, num_slots=2, max_len=64, decode_block=4, paged=True,
            block_size=8, prefill_chunk=8)
        prompts = _prompts(cfg, 7, (5, 9))
        got = _stream(q8, prompts)
        assert [g.shape for g in got] == [(11,), (15,)]
        assert q8.decode_compile_count() == 1
        assert q8.prefill_compile_count() == 1
        b = q8.quant_error_bound()
        assert b["kv"] > 0.0 and b["weights"] > 0.0
        assert fp.decode_bytes_per_step()["total"] \
            > 2.5 * q8.decode_bytes_per_step()["total"]
        q8.manager.assert_consistent()

    def test_int4_grouped_stream_matches_dequant_twin(self, setup):
        """int4 weights with per-group scales: the serving stream
        equals generate() on a twin dequantized with the SAME grouped
        recipe, and the int4 bound is looser than int8's."""
        model, _, cfg = setup
        gcfg = QuantConfig(weights="int4", group_size=32)
        twin4 = LlamaForCausalLM(cfg)
        for (n, p), (_, t4) in zip(model.named_parameters(),
                                   twin4.named_parameters()):
            v = p._value
            if v.ndim == 2 and any(s in n for s in _QUANT_PATTERNS):
                c, s = quantize_array(v, 4, 32)
                t4._value = dequantize_array(c, s, 4,
                                             in_features=int(v.shape[0]),
                                             out_dtype=v.dtype)
            else:
                t4._value = v
        for (_, b), (_, tb) in zip(model.named_buffers(),
                                   twin4.named_buffers()):
            tb._value = b._value
        eng = ContinuousBatchingEngine(
            model, num_slots=2, max_len=64, decode_block=4, quant=gcfg)
        prompts = _prompts(cfg, 8, (5, 9))
        for got, p in zip(_stream(eng, prompts), prompts):
            np.testing.assert_array_equal(
                got, _ref(twin4, p, 6, temperature=0.0))
        e8 = ContinuousBatchingEngine(
            model, num_slots=2, max_len=64, decode_block=4, quant="int8")
        assert eng.weight_error_bound() > e8.weight_error_bound()

    def test_spec_quant_stream_matches_plain_quant(self, setup):
        """spec= composes with quant=: the draft-verify engine on
        quantized weights emits the same greedy stream as the plain
        quant engine (the verify head dequantizes the same codes)."""
        model, _, cfg = setup
        plain = ContinuousBatchingEngine(
            model, num_slots=2, max_len=64, decode_block=4, quant="int8")
        spec = ContinuousBatchingEngine(
            model, num_slots=2, max_len=64, decode_block=4,
            spec=SpecConfig(k=4), quant="int8")
        assert isinstance(spec, SpecEngine)
        prompts = _prompts(cfg, 9, (5, 9))
        a = _stream(plain, prompts, max_new=8)
        b = _stream(spec, prompts, max_new=8)
        for x, y in zip(a, b):
            np.testing.assert_array_equal(x, y)
        assert spec.decode_compile_count() == 1

    def test_bytes_read_accounting_in_registry(self, setup):
        """The decode dispatch notes bytes-read/step into
        pt_serving_decode_bytes_read_total, and the quant engine's rate
        sits well under the fp32 engine's."""
        from paddle_tpu.observability import metrics
        model, _, cfg = setup
        fp = ContinuousBatchingEngine(model, num_slots=2, max_len=64,
                                      decode_block=4)
        q8 = ContinuousBatchingEngine(model, num_slots=2, max_len=64,
                                      decode_block=4, quant="int8")
        prompts = _prompts(cfg, 10, (5,))
        prev = metrics.enabled()
        metrics.enable(True)
        try:
            c = metrics.REGISTRY.get(
                "pt_serving_decode_bytes_read_total")
            b0 = c.value()
            _stream(fp, prompts)
            per_fp = (c.value() - b0) / max(fp.steps, 1)
            b0 = c.value()
            _stream(q8, prompts)
            per_q8 = (c.value() - b0) / max(q8.steps, 1)
            # the bound gauges refresh on quant_error_bound()
            q8.quant_error_bound()
            g = metrics.REGISTRY.get("pt_serving_weight_error_bound")
            assert g.value() > 0.0
        finally:
            metrics.enable(prev)
        assert per_fp > 0 and per_q8 > 0
        assert per_fp > 1.5 * per_q8

    def test_bound_gauges_registered_at_import(self):
        """Catalog-complete-at-zero: both quant gauges exist in the
        registry without any engine having been built in this process
        path (registered at serving import)."""
        from paddle_tpu.observability.metrics import REGISTRY
        for fam in ("pt_serving_kv_error_bound",
                    "pt_serving_weight_error_bound",
                    "pt_serving_decode_bytes_read_total"):
            assert REGISTRY.get(fam) is not None, fam

    def test_weight_bound_dominates_measured_error(self, setup):
        """|dequant - fp32| of every quantized weight sits under the
        queryable bound (half the worst quantization step)."""
        model, _, _ = setup
        eng = ContinuousBatchingEngine(
            model, num_slots=2, max_len=64, decode_block=4, quant="int8")
        bound = eng.weight_error_bound()
        named = list(model.named_parameters())
        back = eng.backend
        for i, meta in back._qmeta.items():
            codes, scales = back._pv[i]
            deq = dequantize_array(codes, scales, meta.bits,
                                   in_features=meta.in_features)
            err = float(jnp.max(jnp.abs(deq - named[i][1]._value)))
            assert err <= bound + 1e-7


# ---------------------------------------------------------------------------
# tensor-parallel composition
# ---------------------------------------------------------------------------

@pytest.mark.skipif(jax.device_count() < 8,
                    reason="needs 8 (simulated) devices for the 2x4 mesh")
class TestTPQuant:
    def test_exact_mode_sharded_quant_bit_identical(self):
        """quant= composes with tp mode='exact': per-shard scales ride
        the weight PartitionSpecs (column-sharded weights' per-channel
        scales split on the out dim), and the sharded quantized stream
        is BIT-IDENTICAL to the 1-chip quant engine; mode='psum' +
        quant refuses loudly."""
        from paddle_tpu.distributed.mesh import build_device_mesh
        from paddle_tpu.serving import TPConfig
        paddle.seed(0)
        cfg = llama_tiny_config(num_attention_heads=8,
                                num_key_value_heads=8)
        model = LlamaForCausalLM(cfg)
        mesh = build_device_mesh({"dp": 2, "mp": 4})
        one = ContinuousBatchingEngine(
            model, num_slots=2, max_len=64, decode_block=4,
            prompt_buckets=(16,), quant="int8")
        tp = ContinuousBatchingEngine(
            model, num_slots=2, max_len=64, decode_block=4,
            prompt_buckets=(16,), quant="int8",
            tp=TPConfig(axes=("dp", "mp"), mesh=mesh))
        prompts = _prompts(cfg, 12, (5, 9))
        a, b = _stream(one, prompts), _stream(tp, prompts)
        for x, y in zip(a, b):
            np.testing.assert_array_equal(x, y)
        assert tp.tp_degree() == 8
        assert tp.weight_error_bound() == one.weight_error_bound() > 0
        with pytest.raises(NotImplementedError, match="psum"):
            ContinuousBatchingEngine(
                model, num_slots=2, max_len=64, decode_block=4,
                quant="int8",
                tp=TPConfig(axes=("dp", "mp"), mode="psum", mesh=mesh))


# ---------------------------------------------------------------------------
# routing matrix
# ---------------------------------------------------------------------------

class TestQuantRouting:
    def test_env_flag_never_reroutes_explicit_backend(self, setup,
                                                      monkeypatch):
        """PT_SERVING_QUANT_WEIGHTS opts IN new engine builds only: a
        caller holding an explicit backend keeps its fp32 weights."""
        from paddle_tpu.serving import ModelStepBackend
        model, _, cfg = setup
        backend = ModelStepBackend(model, num_slots=2, max_len=64,
                                   decode_block=4)
        monkeypatch.setenv("PT_SERVING_QUANT_WEIGHTS", "int8")
        eng = ContinuousBatchingEngine(backend=backend)
        assert eng.backend.quant_cfg is None
        assert eng.weight_error_bound() == 0.0
        # ...while a model build under the same env DOES quantize
        eng2 = ContinuousBatchingEngine(model, num_slots=2, max_len=64,
                                        decode_block=4)
        assert eng2.backend.quant_cfg is not None
        assert eng2.weight_error_bound() > 0.0

    def test_quant_with_explicit_backend_refused(self, setup):
        from paddle_tpu.serving import ModelStepBackend
        model, _, cfg = setup
        backend = ModelStepBackend(model, num_slots=2, max_len=64,
                                   decode_block=4)
        with pytest.raises(ValueError, match="explicit backend"):
            ContinuousBatchingEngine(backend=backend, quant="int8")
        paged = ContinuousBatchingEngine(
            model, num_slots=2, max_len=64, decode_block=4, paged=True,
            block_size=8, prefill_chunk=8)
        with pytest.raises(ValueError, match="explicit backend"):
            ContinuousBatchingEngine(backend=paged.backend,
                                     quant=QuantConfig())
        # quant=False against a QUANTIZED backend refuses too: the
        # codes are baked in — silently serving quantized weights to a
        # caller who pinned fp32 would be the inverse misconfiguration
        qb = ContinuousBatchingEngine(model, num_slots=2, max_len=64,
                                      decode_block=4, quant="int8")
        with pytest.raises(ValueError, match="explicit backend"):
            ContinuousBatchingEngine(backend=qb.backend, quant=False)

    def test_invalid_configs_refused_loudly(self, setup):
        model, _, cfg = setup
        with pytest.raises(ValueError, match="int8"):
            QuantConfig(weights="fp8")
        with pytest.raises(ValueError, match="group_size"):
            QuantConfig(group_size=0)
        with pytest.raises(ValueError, match="QuantConfig"):
            resolve_quant_config(42)
        # group_size must divide every quantized weight's in_features
        with pytest.raises(ValueError, match="does not divide"):
            ContinuousBatchingEngine(
                model, num_slots=2, max_len=64, decode_block=4,
                quant=QuantConfig(weights="int8", group_size=48))

    def test_env_knob_routes_through_flags(self, setup, monkeypatch):
        monkeypatch.setenv("PT_SERVING_QUANT_WEIGHTS", "int4")
        monkeypatch.setenv("PT_SERVING_QUANT_GROUP", "32")
        cfg = resolve_quant_config(None)
        assert cfg == QuantConfig(weights="int4", group_size=32)
        monkeypatch.setenv("PT_SERVING_QUANT_WEIGHTS", "")
        assert resolve_quant_config(None) is None
        monkeypatch.delenv("PT_SERVING_QUANT_WEIGHTS")
        assert resolve_quant_config(None) is None
        assert resolve_quant_config("int8") == QuantConfig()
        assert resolve_quant_config(False) is None

    def test_direct_paged_ctor_honors_quant(self, setup):
        """PagedEngine(model, ..., quant=...) — the direct-constructor
        route — quantizes like the factory (same contract as
        kv_int8)."""
        model, _, cfg = setup
        eng = PagedEngine(model, num_slots=2, max_len=64,
                          decode_block=4, block_size=8, prefill_chunk=8,
                          quant="int8")
        assert eng.weight_error_bound() > 0.0
        prompts = _prompts(cfg, 11, (5,))
        assert _stream(eng, prompts)[0].shape == (11,)
        eng.manager.assert_consistent()
