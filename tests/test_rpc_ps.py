"""RPC + parameter-server mode (reference: python/paddle/distributed/rpc/,
python/paddle/distributed/ps/ — verify)."""
import os
import socket
import subprocess
import sys
import textwrap

import numpy as np
import pytest

import paddle_tpu.distributed.rpc as rpc


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


class TestRpcSingleWorld:
    def test_sync_async_and_infos(self):
        rpc.init_rpc("w0", rank=0, world_size=1)
        try:
            import operator
            assert rpc.rpc_sync("w0", operator.add, args=(2, 3)) == 5
            fut = rpc.rpc_async("w0", operator.mul, args=(4, 5))
            assert fut.wait(10) == 20
            # rank addressing + worker infos
            assert rpc.rpc_sync(0, operator.add, args=(1, 1)) == 2
            infos = rpc.get_all_worker_infos()
            assert len(infos) == 1 and infos[0].name == "w0"
            assert rpc.get_worker_info().rank == 0
            # remote exceptions propagate
            with pytest.raises(ZeroDivisionError):
                rpc.rpc_sync("w0", operator.truediv, args=(1, 0))
            with pytest.raises(ValueError):
                rpc.rpc_sync("nope", operator.add, args=(1, 1))
        finally:
            rpc.shutdown()

    def test_reinit_after_shutdown(self):
        rpc.init_rpc("w0", rank=0, world_size=1)
        rpc.shutdown()
        rpc.init_rpc("w0", rank=0, world_size=1)
        import operator
        assert rpc.rpc_sync("w0", operator.add, args=(1, 2)) == 3
        rpc.shutdown()


SERVER_SCRIPT = textwrap.dedent("""
    import os
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")
    from paddle_tpu.distributed import ps
    ps.init_server()
    ps.run_server(poll_s=0.05)
    print("SERVER_DONE")
""")

TRAINER_SCRIPT = textwrap.dedent("""
    import os
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.distributed import ps

    ps.init_worker()
    ps.create_table("emb", 4, optimizer="sgd", lr=0.5, init_range=0.01)

    ids = np.array([1, 2, 3])
    before = ps.pull_sparse("emb", ids)
    assert before.shape == (3, 4)
    # pulls are stable (lazy rows persist server-side)
    again = ps.pull_sparse("emb", ids)
    assert np.allclose(before, again)

    # manual push applies SGD: row -= lr * grad (duplicates pre-merged)
    g = np.ones((4, 4), np.float32)
    ps.push_sparse("emb", np.array([1, 2, 3, 1]), g)
    after = ps.pull_sparse("emb", ids)
    exp = before.copy()
    exp[0] -= 0.5 * 2.0   # id 1 pushed twice
    exp[1] -= 0.5
    exp[2] -= 0.5
    assert np.allclose(after, exp, atol=1e-6), (after, exp)

    # SparseEmbedding: backward pushes through the grad hook
    emb = ps.SparseEmbedding("emb2", 8, 4, lr=1.0)
    x = paddle.to_tensor(np.array([[1, 2], [2, 5]], np.int64))
    out = emb(x)
    assert list(out.shape) == [2, 2, 4]
    rows_before = ps.pull_sparse("emb2", np.array([1, 2, 5]))
    out.sum().backward()
    rows_after = ps.pull_sparse("emb2", np.array([1, 2, 5]))
    # d(sum)/d(row): id1 once, id2 twice, id5 once; lr=1
    assert np.allclose(rows_before[0] - 1.0, rows_after[0], atol=1e-6)
    assert np.allclose(rows_before[1] - 2.0, rows_after[1], atol=1e-6)
    assert np.allclose(rows_before[2] - 1.0, rows_after[2], atol=1e-6)

    assert ps.table_size("emb") == 3
    import tempfile
    d = tempfile.mkdtemp()
    assert ps.save_table("emb", d) == 3
    ps.shutdown()
    print("TRAINER_DONE")
""")


MODES_TRAINER_SCRIPT = textwrap.dedent("""
    import os
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    from paddle_tpu.distributed import ps

    ps.init_worker(mode="async", async_interval=0.01)
    assert ps.training_mode() == "async"
    ps.create_table("a", 4, optimizer="sgd", lr=0.5)

    ids = np.array([1, 2])
    before = ps.pull_sparse("a", ids)
    # async push returns immediately; barrier drains the send buffer
    ps.push_sparse("a", np.array([1, 2, 1]), np.ones((3, 4), np.float32))
    ps.barrier_worker()
    after = ps.pull_sparse("a", ids)
    exp = before.copy()
    exp[0] -= 0.5 * 2.0
    exp[1] -= 0.5
    assert np.allclose(after, exp, atol=1e-6), (after, exp)

    # ---- GeoSGD: local updates, delta sync every geo_step pushes ----
    ps.set_training_mode("geo", geo_step=3)
    ps.create_table("g", 4, optimizer="sgd", lr=0.5)
    ids = np.array([7])
    r0 = ps.pull_sparse("g", ids).copy()
    g = np.ones((1, 4), np.float32)
    ps.push_sparse("g", ids, g)          # local only
    ps.push_sparse("g", ids, g)          # local only
    local = ps.pull_sparse("g", ids)
    assert np.allclose(local, r0 - 1.0, atol=1e-6)           # 2 * lr*g
    srv = ps._pull_sparse_sync("g", ids.reshape(-1))
    assert np.allclose(srv, r0, atol=1e-6), "delta shipped early"
    ps.push_sparse("g", ids, g)          # 3rd push -> flush
    srv = ps._pull_sparse_sync("g", ids.reshape(-1))
    assert np.allclose(srv, r0 - 1.5, atol=1e-6), (srv, r0)
    assert np.allclose(ps.pull_sparse("g", ids), r0 - 1.5, atol=1e-6)

    # explicit barrier also flushes a partial window
    ps.push_sparse("g", ids, g)
    ps.barrier_worker()
    srv = ps._pull_sparse_sync("g", ids.reshape(-1))
    assert np.allclose(srv, r0 - 2.0, atol=1e-6)

    ps.shutdown()
    print("MODES_DONE")
""")


SSD_TRAINER_SCRIPT = textwrap.dedent("""
    import os, tempfile
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    from paddle_tpu.distributed import ps

    ps.init_worker()
    # 64 rows through an 8-row hot cache forces eviction to the disk tier
    ps.create_table("s", 4, optimizer="adagrad", lr=0.5,
                    table_type="ssd", cache_rows=8)
    # memory table with the same seed: the rows materialize in the same
    # order, so every pull must match bit-for-bit (tier parity)
    ps.create_table("m", 4, optimizer="adagrad", lr=0.5)

    ids = np.arange(64)
    before = ps.pull_sparse("s", ids)
    assert np.allclose(before, ps.pull_sparse("s", ids)), "spill unstable"
    assert np.allclose(before, ps.pull_sparse("m", ids)), "tier mismatch"

    st = ps.table_stats("s")[0]
    assert st["type"] == "ssd" and st["hot_rows"] <= 8, st
    assert st["disk_rows"] >= 56, st          # eviction actually spilled
    assert ps.table_stats("m")[0]["disk_rows"] == 0

    # pushes land on rows on BOTH sides of the cache boundary; the
    # adagrad accumulator must survive the spill round-trip too
    g = np.ones((64, 4), np.float32)
    for t in ("s", "m"):
        ps.push_sparse(t, ids, g)
        ps.push_sparse(t, ids, g)
    after = ps.pull_sparse("s", ids)
    assert np.allclose(after, ps.pull_sparse("m", ids), atol=1e-6)
    # adagrad: step1 acc=1 -> -0.5; step2 acc=2 -> -0.5/sqrt(2)
    exp = before - 0.5 - 0.5 / np.sqrt(2.0)
    assert np.allclose(after, exp, atol=1e-4), (after[0], exp[0])

    assert ps.table_size("s") == 64
    d = tempfile.mkdtemp()
    assert ps.save_table("s", d) == 64
    saved = np.load(os.path.join(d, "s.shard0.npz"))
    order = np.argsort(saved["ids"])
    assert np.allclose(saved["rows"][order], after, atol=1e-6)

    ps.shutdown()
    print("SSD_DONE")
""")


class TestPsCluster:
    def test_one_server_one_trainer(self, tmp_path):
        port = _free_port()
        base_env = {
            **os.environ,
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
            "PADDLE_MASTER": f"127.0.0.1:{port}",
            "PADDLE_PSERVER_NUM": "1",
            "PADDLE_TRAINER_NUM": "1",
            "PADDLE_TRAINER_ID": "0",
        }
        srv = subprocess.Popen(
            [sys.executable, "-c", SERVER_SCRIPT],
            env={**base_env, "TRAINING_ROLE": "PSERVER"},
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        trn = subprocess.Popen(
            [sys.executable, "-c", TRAINER_SCRIPT],
            env={**base_env, "TRAINING_ROLE": "TRAINER"},
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        try:
            t_out, _ = trn.communicate(timeout=180)
            s_out, _ = srv.communicate(timeout=60)
        finally:
            for p in (srv, trn):
                if p.poll() is None:
                    p.kill()
        assert trn.returncode == 0, t_out
        assert "TRAINER_DONE" in t_out, t_out
        assert srv.returncode == 0, s_out
        assert "SERVER_DONE" in s_out, s_out

    def test_async_and_geo_modes(self):
        port = _free_port()
        base_env = {
            **os.environ,
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
            "PADDLE_MASTER": f"127.0.0.1:{port}",
            "PADDLE_PSERVER_NUM": "1",
            "PADDLE_TRAINER_NUM": "1",
            "PADDLE_TRAINER_ID": "0",
        }
        srv = subprocess.Popen(
            [sys.executable, "-c", SERVER_SCRIPT],
            env={**base_env, "TRAINING_ROLE": "PSERVER"},
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        trn = subprocess.Popen(
            [sys.executable, "-c", MODES_TRAINER_SCRIPT],
            env={**base_env, "TRAINING_ROLE": "TRAINER"},
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        try:
            t_out, _ = trn.communicate(timeout=180)
            s_out, _ = srv.communicate(timeout=60)
        finally:
            for p in (srv, trn):
                if p.poll() is None:
                    p.kill()
        assert trn.returncode == 0, t_out
        assert "MODES_DONE" in t_out, t_out
        assert srv.returncode == 0, s_out

    def test_ssd_table(self):
        port = _free_port()
        base_env = {
            **os.environ,
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
            "PADDLE_MASTER": f"127.0.0.1:{port}",
            "PADDLE_PSERVER_NUM": "1",
            "PADDLE_TRAINER_NUM": "1",
            "PADDLE_TRAINER_ID": "0",
        }
        srv = subprocess.Popen(
            [sys.executable, "-c", SERVER_SCRIPT],
            env={**base_env, "TRAINING_ROLE": "PSERVER"},
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        trn = subprocess.Popen(
            [sys.executable, "-c", SSD_TRAINER_SCRIPT],
            env={**base_env, "TRAINING_ROLE": "TRAINER"},
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        try:
            t_out, _ = trn.communicate(timeout=180)
            s_out, _ = srv.communicate(timeout=60)
        finally:
            for p in (srv, trn):
                if p.poll() is None:
                    p.kill()
        assert trn.returncode == 0, t_out
        assert "SSD_DONE" in t_out, t_out
        assert srv.returncode == 0, s_out
