"""Observability subsystem (paddle_tpu/observability/): metrics
registry semantics + disabled-path inertness, per-request lifecycle
traces (exactly one terminal span per submitted request, pinned under a
seeded chaos schedule), the merged Perfetto/chrome trace artifact
(request rows + RecordEvent host spans + tick markers on one clock),
the crash flight recorder (bounded ring, circuit-open auto-dump,
snapshot/restore round-trip), metrics exposition coverage across
server/engine/paging/resilience/faults/collectives/passes, and the
profiler scheduler-gating + export/summary satellites."""
import json
import os

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.profiler as profiler
from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny_config
from paddle_tpu.observability import (FlightRecorder, ObservabilityConfig,
                                      RequestTracer, export_chrome_trace,
                                      metrics)
from paddle_tpu.serving import (ContinuousBatchingEngine, RequestFailure,
                                ResilienceConfig, Scheduler, Server)
from paddle_tpu.utils import faults


@pytest.fixture(scope="module")
def setup():
    """One model + one dense + ONE paged engine for the whole file
    (reset() frees state, never the compiled programs; a second paged
    backend per process trips the documented compile-cache landmine)."""
    paddle.seed(0)
    cfg = llama_tiny_config(tensor_parallel=False)
    model = LlamaForCausalLM(cfg)
    dense = ContinuousBatchingEngine(model, num_slots=2, max_len=64,
                                     decode_block=4,
                                     prompt_buckets=(8, 16))
    paged = ContinuousBatchingEngine(model, num_slots=2, max_len=64,
                                     decode_block=4, paged=True,
                                     block_size=8, prefill_chunk=8)
    return model, cfg, dense, paged


@pytest.fixture(autouse=True)
def _isolated():
    """Every test starts disarmed and with a zeroed registry, and ends
    the same way — metric samples and fault schedules must never bleed
    across tests."""
    faults.clear()
    prev = metrics.enabled()
    metrics.REGISTRY.reset()
    yield
    faults.clear()
    metrics.enable(prev)
    metrics.REGISTRY.reset()


def _prompts(cfg, seed, lens):
    rs = np.random.RandomState(seed)
    return [rs.randint(0, cfg.vocab_size, (L,)).astype(np.int32)
            for L in lens]


# ---------------------------------------------------------------------------
# metrics registry
# ---------------------------------------------------------------------------

class TestMetricsRegistry:
    def test_counter_gauge_histogram_semantics(self):
        metrics.enable(True)
        c = metrics.counter("t_obs_c", "help text", labels=("site",))
        c.inc(site="a")
        c.inc(2, site="a")
        c.inc(site="b")
        assert c.value(site="a") == 3.0 and c.value(site="b") == 1.0
        with pytest.raises(ValueError):
            c.inc(-1, site="a")          # counters are monotone
        g = metrics.gauge("t_obs_g")
        g.set(7.5)
        g.inc(0.5)
        assert g.value() == 8.0
        h = metrics.histogram("t_obs_h", buckets=(0.1, 1.0))
        for v in (0.05, 0.5, 0.5, 5.0):
            h.observe(v)
        s = h.samples()[0]["value"]
        assert s["count"] == 4 and s["sum"] == pytest.approx(6.05)
        # cumulative: <=0.1 -> 1, <=1.0 -> 3, +Inf -> 4
        assert s["buckets"] == {"0.1": 1, "1.0": 3, "+Inf": 4}

    def test_get_or_create_identity_and_mismatch(self):
        a = metrics.counter("t_obs_same", "x", labels=("k",))
        b = metrics.counter("t_obs_same", "x", labels=("k",))
        assert a is b
        with pytest.raises(ValueError):
            metrics.gauge("t_obs_same")          # kind mismatch
        with pytest.raises(ValueError):
            metrics.counter("t_obs_same", labels=("other",))
        with pytest.raises(ValueError):
            metrics.enable(True) or a.inc(wrong="v")  # label schema

    def test_disabled_hot_path_is_inert(self):
        metrics.enable(False)
        c = metrics.counter("t_obs_dis", labels=("x",))
        h = metrics.histogram("t_obs_dis_h")
        g = metrics.gauge("t_obs_dis_g")
        c.inc(x="v")
        h.observe(1.0)
        g.set(3.0)
        # no samples were even CREATED — the first-line bool return
        assert c.samples() == [] and h.samples() == [] \
            and g.samples() == []

    def test_dump_and_prometheus_rendering(self):
        metrics.enable(True)
        metrics.counter("t_obs_render", "counts things",
                        labels=("kind",)).inc(kind='we"ird')
        metrics.histogram("t_obs_render_h", "hist",
                          buckets=(1.0,)).observe(0.5)
        d = metrics.dump()
        assert d["t_obs_render"]["kind"] == "counter"
        assert d["t_obs_render"]["samples"][0]["labels"] == {
            "kind": 'we"ird'}
        text = metrics.render_prometheus()
        assert "# TYPE t_obs_render counter" in text
        assert 't_obs_render{kind="we\\"ird"} 1.0' in text
        assert 't_obs_render_h_bucket{le="1.0"} 1' in text
        assert 't_obs_render_h_bucket{le="+Inf"} 1' in text
        assert "t_obs_render_h_count 1" in text


class TestDisabledPathInert:
    def test_disabled_stream_touches_nothing(self, setup):
        """Metrics off + tracing off: a full served stream leaves the
        registry without a single sample, records no traces, and the
        engine carries no tracer (the hot paths pay one is-None
        check)."""
        model, cfg, dense, paged = setup
        metrics.enable(False)
        dense.reset()
        srv = Server(dense, observability=ObservabilityConfig(
            trace_requests=False, flight_size=0))
        for p in _prompts(cfg, 1, [5, 9]):
            srv.submit(p, max_new_tokens=4)
        srv.run_until_idle()
        assert dense.tracer is None
        assert srv.tracer.traces == {}
        assert srv.flight.events() == []
        sampled = [k for k, v in metrics.dump().items() if v["samples"]]
        assert sampled == []


# ---------------------------------------------------------------------------
# request traces
# ---------------------------------------------------------------------------

class TestRequestTraces:
    def test_completed_request_span_lifecycle(self, setup):
        model, cfg, dense, paged = setup
        dense.reset()
        srv = Server(dense, observability=ObservabilityConfig(
            trace_requests=True))
        rid = srv.submit(_prompts(cfg, 2, [6])[0], max_new_tokens=5)
        srv.run_until_idle()
        tr = srv.tracer.traces[rid]
        names = tr.span_names()
        # lifecycle order: queue wait -> prefill -> decode residency ->
        # harvest -> exactly one terminal
        for want in ("queue_wait", "prefill", "decode", "harvest",
                     "terminal:completed"):
            assert want in names, (want, names)
        assert names.index("queue_wait") < names.index("prefill")
        assert tr.terminals == ["completed"]
        assert tr.open == {}

    def test_chaos_schedule_every_request_one_terminal(self, setup):
        """The acceptance invariant under injected chaos: every
        submitted request's trace reaches EXACTLY one terminal span,
        and the terminal agrees with what landed in results."""
        model, cfg, dense, paged = setup
        paged.reset()
        res = ResilienceConfig(retry_attempts=2, retry_backoff_s=0.001,
                               breaker_threshold=64, max_queue_depth=4)
        srv = Server(paged, Scheduler(prefill_token_budget=8),
                     resilience=res,
                     observability=ObservabilityConfig(
                         trace_requests=True))
        prompts = _prompts(cfg, 3, [5, 9, 17, 4, 12, 7, 20, 6])
        with faults.injected(
                "serving.step_block:p=0.15;serving.prefill_tick:p=0.1;"
                "serving.allocate:at=2;server.tick:at=4", seed=7):
            rids = []
            for i, p in enumerate(prompts):
                rids.append(srv.submit(
                    p, max_new_tokens=4 + (i % 3),
                    arrival_step=i // 2,
                    deadline_ticks=2 if i == 5 else None))
            results = srv.run_until_idle(max_ticks=300)
        assert set(rids) == set(results)
        terms = srv.tracer.terminal_states()
        for rid in rids:
            assert len(terms[rid]) == 1, (rid, terms[rid])
            out = results[rid]
            if isinstance(out, RequestFailure):
                assert terms[rid] == [out.reason]
            else:
                assert terms[rid] == ["completed"]
            assert srv.tracer.traces[rid].open == {}
        paged.manager.assert_consistent()

    def test_shed_request_still_terminates(self, setup):
        model, cfg, dense, paged = setup
        dense.reset()
        srv = Server(dense,
                     resilience=ResilienceConfig(max_queue_depth=1),
                     observability=ObservabilityConfig(
                         trace_requests=True))
        ps = _prompts(cfg, 4, [5, 5, 5])
        # arrival far in the future keeps them queued -> 3rd submit sheds
        r = [srv.submit(p, max_new_tokens=3, arrival_step=50)
             for p in ps]
        assert isinstance(srv.results[r[-1]], RequestFailure)
        assert srv.tracer.terminal_states()[r[-1]] == ["shed"]
        srv.run_until_idle()
        for rid in r:
            assert len(srv.tracer.terminal_states()[rid]) == 1


class TestMergedChromeTrace:
    def test_single_served_batch_trace_has_all_streams(self, setup,
                                                       tmp_path):
        """The acceptance artifact: ONE Perfetto-loadable chrome-trace
        JSON from one served batch containing request spans, RecordEvent
        host spans, and tick markers — all on the perf_counter clock."""
        model, cfg, dense, paged = setup
        dense.reset()
        srv = Server(dense, observability=ObservabilityConfig(
            trace_requests=True))
        prof = profiler.Profiler(targets=[profiler.ProfilerTarget.CPU],
                                 timer_only=True)
        prof._drain_events()             # a clean host ring
        with prof:
            for p in _prompts(cfg, 5, [6, 11, 4]):
                srv.submit(p, max_new_tokens=6)
            srv.run_until_idle()
        path = str(tmp_path / "nested" / "serve_trace.json")
        srv.export_trace(path, profiler=prof)
        events = json.load(open(path))["traceEvents"]

        req_rows = {e["tid"] for e in events
                    if e.get("ph") == "M" and
                    str(e["args"].get("name", "")).startswith("request ")}
        assert len(req_rows) == 3        # one named row per request
        for tid in req_rows:             # each row carries real spans
            assert any(e.get("ph") == "X" and e.get("tid") == tid
                       for e in events)
        names = [e.get("name") for e in events]
        assert "queue_wait" in names and "decode" in names
        # RecordEvent host spans from the SAME engine dispatches
        assert any(n == "serving.decode_block" for n in names)
        assert any(n == "serving.prefill" for n in names)
        # tick markers on the server row
        ticks = [e for e in events if e.get("name") == "tick"]
        assert ticks and all(e["tid"] == 0 and e["ph"] == "X"
                             for e in ticks)
        # aligned clocks: every span timestamp sits in one monotonic
        # window (a wall-clock mixup would land µs-epoch outliers)
        ts = [e["ts"] for e in events if e.get("ph") == "X"]
        assert max(ts) - min(ts) < 600e6   # within 10 minutes
        # thread metadata names the rows for Perfetto
        assert any(e.get("ph") == "M" and
                   e["args"].get("name") == "server" for e in events)


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------

class TestFlightRecorder:
    def test_ring_is_bounded(self):
        fr = FlightRecorder(capacity=4)
        for i in range(10):
            fr.record("e", i=i)
        ev = fr.events()
        assert len(ev) == 4
        assert [e["seq"] for e in ev] == [7, 8, 9, 10]
        assert fr.recorded_total() == 10

    def test_capacity_zero_disables(self):
        fr = FlightRecorder(capacity=0)
        fr.record("e")
        assert fr.events() == [] and fr.recorded_total() == 0

    def test_env_capacity_knob(self, monkeypatch):
        monkeypatch.setenv("PT_FLIGHT_RECORDER_SIZE", "3")
        fr = FlightRecorder()
        assert fr.capacity == 3

    def test_dumps_on_circuit_open(self, setup, tmp_path):
        """Breaker opens -> the black box lands on disk before the
        drain, with the failure history inside."""
        model, cfg, dense, paged = setup
        dense.reset()
        srv = Server(dense,
                     resilience=ResilienceConfig(
                         retry_attempts=0, breaker_threshold=2),
                     observability=ObservabilityConfig(
                         flight_dump_dir=str(tmp_path)))
        for p in _prompts(cfg, 6, [5, 7]):
            srv.submit(p, max_new_tokens=6)
        with faults.injected("serving.step_block:every=1"):
            results = srv.run_until_idle(max_ticks=50)
        assert all(isinstance(v, RequestFailure)
                   for v in results.values())
        path = srv.flight.last_dump_path
        assert path and os.path.dirname(path) == str(tmp_path)
        dump = json.load(open(path))
        assert dump["format"] == "pt-flight-recorder"
        assert dump["reason"] == "circuit_open"
        kinds = [e["kind"] for e in dump["events"]]
        assert "step_failure" in kinds and "breaker_open" in kinds
        assert "circuit_open_drain" in kinds

    def test_snapshot_restore_roundtrip(self, setup, tmp_path):
        """The ring rides the snapshot: a restored server still holds
        the pre-kill events (and the snapshot dumped a sidecar file)."""
        model, cfg, dense, paged = setup
        dense.reset()
        srv = Server(dense)
        for p in _prompts(cfg, 7, [5, 9]):
            srv.submit(p, max_new_tokens=12)
        srv.run_until_idle(max_ticks=2)        # killed mid-stream
        pre = srv.flight.events()
        assert pre, "ticks should have recorded"
        snap = str(tmp_path / "srv.npz")
        srv.snapshot(snap)
        assert os.path.exists(snap + ".flight.json")

        dense2 = ContinuousBatchingEngine(model, num_slots=2, max_len=64,
                                          decode_block=4,
                                          prompt_buckets=(8, 16))
        srv2 = Server.restore(snap, dense2)
        ev = srv2.flight.events()
        kinds = [e["kind"] for e in ev]
        assert "restored" in kinds
        # pre-kill history survived with original seq numbers
        pre_seqs = [e["seq"] for e in pre]
        assert [e["seq"] for e in ev if e["kind"] == "tick"][:len(pre_seqs)]
        assert ev[0]["seq"] == pre[0]["seq"]
        # and the restored stream still finishes
        res = srv2.run_until_idle()
        assert all(not isinstance(v, RequestFailure)
                   for v in res.values())


# ---------------------------------------------------------------------------
# exposition coverage (acceptance: every instrumented subsystem)
# ---------------------------------------------------------------------------

class TestMetricsCoverage:
    def test_exposition_covers_all_subsystems(self, setup):
        import jax
        from jax.sharding import Mesh
        from paddle_tpu.distributed import collectives as cc
        from paddle_tpu.passes import PassManager, default_pipeline

        model, cfg, dense, paged = setup
        metrics.enable(True)

        # server + engine + paging + resilience (retry) + faults
        paged.reset()
        srv = Server(paged,
                     resilience=ResilienceConfig(retry_attempts=2,
                                                 retry_backoff_s=0.001))
        with faults.injected("serving.step_block:at=2"):
            for i, p in enumerate(_prompts(cfg, 8, [5, 17, 17])):
                srv.submit(p, max_new_tokens=4, arrival_step=i)
            srv.run_until_idle(max_ticks=100)

        # collectives: flat 1-device plan still counts bytes + bound
        mesh = Mesh(np.asarray(jax.devices()[:1]), ("dp",))
        cc.all_reduce(np.ones((1, 64), np.float32), ("dp",), mesh,
                      compress=None)
        cc.all_reduce(np.ones((1, 512), np.float32), ("dp",), mesh,
                      compress="int8")

        # passes: run the pipeline over a softmax so a rewrite fires
        def f(x):
            return jax.nn.softmax(x, axis=-1)

        PassManager(default_pipeline()).run(
            jax.make_jaxpr(f)(np.zeros((4, 8), np.float32)))

        d = metrics.dump()

        def sampled(name):
            return bool(d[name]["samples"])

        # one family per subsystem named in the acceptance criteria
        assert sampled("pt_server_ticks_total")              # server
        assert sampled("pt_engine_decode_steps_total")       # engine
        assert sampled("pt_paging_prefix_lookups_total")     # paging
        assert sampled("pt_server_retries_total")            # resilience
        assert sampled("pt_server_step_failures_total")
        assert sampled("pt_fault_fires_total")               # faults
        assert sampled("pt_collectives_bytes_total")         # collectives
        assert sampled("pt_collectives_int8_error_bound")
        assert sampled("pt_passes_runs_total")               # passes
        assert sampled("pt_passes_rewrites_total")
        # the prometheus text renders every family it dumped
        text = metrics.render_prometheus()
        for fam in d:
            assert f"# TYPE {fam} " in text


# ---------------------------------------------------------------------------
# profiler satellites
# ---------------------------------------------------------------------------

class TestProfilerSchedulerGating:
    def _mk(self, **kw):
        return profiler.Profiler(targets=[profiler.ProfilerTarget.CPU],
                                 timer_only=True, **kw)

    def test_closed_scheduler_keeps_host_ring_silent(self):
        """Regression: start() armed the host ring unconditionally, so
        spans recorded through CLOSED warmup steps; and CLOSED->RECORD
        in step() never re-armed it."""
        import time
        p = self._mk(scheduler=profiler.make_scheduler(
            closed=1, record=1, repeat=2))
        p._drain_events()
        p.start()
        with profiler.RecordEvent("warmup"):
            time.sleep(0.001)
        p.step()                         # CLOSED -> RECORD: re-arm
        with profiler.RecordEvent("hot"):
            time.sleep(0.001)
        p.step()                         # RECORD -> CLOSED: disarm
        with profiler.RecordEvent("cold"):
            time.sleep(0.001)
        p.stop()
        assert [e["name"] for e in p._drain_events()] == ["hot"]

    def test_schedulerless_profiler_records_immediately(self):
        p = self._mk()
        p._drain_events()
        with p:
            with profiler.RecordEvent("x"):
                pass
        assert [e["name"] for e in p._drain_events()] == ["x"]


class TestProfilerExportSummary:
    def test_export_creates_parent_dirs(self, tmp_path):
        p = profiler.Profiler(targets=[profiler.ProfilerTarget.CPU],
                              timer_only=True)
        with p:
            with profiler.RecordEvent("span"):
                pass
        path = str(tmp_path / "a" / "b" / "trace.json")
        p.export(path)
        assert json.load(open(path))["traceEvents"] is not None
        assert p._last_export == path

    def test_summary_print_table_off_returns_aggregate(self, capsys):
        import time
        p = profiler.Profiler(targets=[profiler.ProfilerTarget.CPU],
                              timer_only=True)
        with p:
            with profiler.RecordEvent("agg_span"):
                time.sleep(0.002)
            with profiler.RecordEvent("agg_span"):
                pass
        table, agg = p.summary(print_table=False)
        assert capsys.readouterr().out == ""
        assert agg["agg_span"]["calls"] == 2
        assert agg["agg_span"]["total_us"] >= 1000
        assert "agg_span" in table

    def test_summary_prints_by_default(self, capsys):
        p = profiler.Profiler(targets=[profiler.ProfilerTarget.CPU],
                              timer_only=True)
        with p:
            with profiler.RecordEvent("printed"):
                pass
        table, agg = p.summary()
        assert "printed" in capsys.readouterr().out


class TestEnvKnobs:
    def test_knobs_ride_flags_helpers(self, monkeypatch):
        """PT_METRICS / PT_TRACE_REQUESTS / PT_FLIGHT_RECORDER_SIZE all
        parse through utils.flags env_bool/env_int — uniform falsy
        spellings, lenient-empty ints."""
        from paddle_tpu.utils.flags import env_bool, env_int
        monkeypatch.setenv("PT_METRICS", "off")
        assert env_bool("PT_METRICS") is False
        monkeypatch.setenv("PT_TRACE_REQUESTS", "1")
        assert RequestTracer().enabled is True
        monkeypatch.setenv("PT_TRACE_REQUESTS", "no")
        assert RequestTracer().enabled is False
        monkeypatch.setenv("PT_FLIGHT_RECORDER_SIZE", " ")
        assert env_int("PT_FLIGHT_RECORDER_SIZE", 256) == 256
        assert FlightRecorder().capacity == 256
