"""Hierarchical + quantized collectives (distributed/collectives/).

Runs on the 8 simulated CPU devices conftest forces. Pins:
- hierarchical all-reduce / all-gather / reduce-scatter bit-identical
  to the flat fp32 collectives over a 2x4 mesh (integer-valued data,
  so fp32 sums are exact and bit-compare is meaningful);
- int8 quantized all-reduce inside the documented error bound and
  EXACT for constant inputs;
- the bucketing scheduler preserving gradient values vs unbucketed
  sync (in-graph hook and eager fused path);
- plan selection (flat fallback), config plumbing, microbench output.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.distributed import collectives as cc
from paddle_tpu.distributed.collectives import (
    BucketedGradSync, CollectiveConfig, build_buckets, configure,
    int8_error_bound, plan_hierarchy, run_comms_bench)
from paddle_tpu.distributed.mesh import build_device_mesh

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 simulated devices")


@pytest.fixture(scope="module")
def mesh():
    return build_device_mesh({"dp": 2, "mp": 4})


def _idata(rs, shape, lo=-32, hi=32):
    # integer-valued fp32: sums are exact in any association order, so
    # flat-vs-hierarchical comparisons are BIT comparisons
    return rs.randint(lo, hi, size=shape).astype(np.float32)


class TestPlan:
    def test_auto_two_level(self, mesh):
        p = plan_hierarchy(("dp", "mp"), mesh)
        assert not p.flat
        assert p.outer == ("dp",) and p.inner == "mp"
        assert p.inner_size == 4 and p.total_size == 8

    def test_axis_order_normalized(self, mesh):
        # innermost mesh axis becomes the fast level regardless of the
        # order the caller wrote
        p = plan_hierarchy(("mp", "dp"), mesh)
        assert p.inner == "mp" and p.outer == ("dp",)

    def test_flat_fallback_single_axis(self, mesh):
        p = plan_hierarchy(("mp",), mesh)
        assert p.flat and p.total_size == 4

    def test_degree_one_axes_dropped(self):
        m = build_device_mesh({"dp": 1, "mp": 8})
        p = plan_hierarchy(("dp", "mp"), m)
        assert p.flat and p.axes == ("mp",) and p.total_size == 8

    def test_forced_flat(self, mesh):
        assert plan_hierarchy(("dp", "mp"), mesh, hierarchy="flat").flat

    def test_unknown_axis_raises(self, mesh):
        with pytest.raises(ValueError, match="not in mesh"):
            plan_hierarchy(("nope",), mesh)


class TestHierarchicalBitIdentity:
    @pytest.mark.parametrize("shape", [(64,), (37,), (8, 7)])
    def test_all_reduce(self, mesh, shape):
        # 37 elements: not divisible by inner_size=4 — exercises the
        # padding path
        rs = np.random.RandomState(0)
        x = _idata(rs, (8,) + shape)
        flat = np.asarray(cc.all_reduce(x, ("dp", "mp"), mesh,
                                        compress=None, hierarchy="flat"))
        hier = np.asarray(cc.all_reduce(x, ("dp", "mp"), mesh,
                                        compress=None, hierarchy="auto"))
        assert np.array_equal(flat, hier)
        np.testing.assert_array_equal(flat, x.sum(axis=0))

    def test_reduce_scatter_placement(self, mesh):
        # output row d is device d's chunk: the comparison pins chunk
        # ASSIGNMENT, not just the global sum
        rs = np.random.RandomState(1)
        x = _idata(rs, (8, 32))
        flat = np.asarray(cc.reduce_scatter(x, ("dp", "mp"), mesh,
                                            hierarchy="flat"))
        hier = np.asarray(cc.reduce_scatter(x, ("dp", "mp"), mesh,
                                            hierarchy="auto"))
        assert flat.shape == (8, 4)
        assert np.array_equal(flat, hier)
        total = x.sum(axis=0)
        for d in range(8):
            np.testing.assert_array_equal(flat[d], total[4 * d:4 * d + 4])

    def test_all_gather_order(self, mesh):
        rs = np.random.RandomState(2)
        x = _idata(rs, (8, 5))
        flat = np.asarray(cc.all_gather(x, ("dp", "mp"), mesh,
                                        hierarchy="flat"))
        hier = np.asarray(cc.all_gather(x, ("dp", "mp"), mesh,
                                        hierarchy="auto"))
        assert np.array_equal(flat, hier)
        np.testing.assert_array_equal(flat, x.reshape(-1))

    def test_reduce_scatter_indivisible_raises(self, mesh):
        with pytest.raises(ValueError, match="not divisible"):
            cc.reduce_scatter(np.zeros((8, 30), np.float32),
                              ("dp", "mp"), mesh)

    def test_wrong_leading_dim_raises(self, mesh):
        with pytest.raises(ValueError, match="dim 0"):
            cc.all_reduce(np.zeros((4, 8), np.float32), ("dp", "mp"),
                          mesh)

    def test_tensor_in_tensor_out(self, mesh):
        x = paddle.to_tensor(np.ones((8, 6), np.float32))
        out = cc.all_reduce(x, ("dp", "mp"), mesh, compress=None)
        assert isinstance(out, paddle.Tensor)
        np.testing.assert_array_equal(out.numpy(), np.full(6, 8.0))


class TestQuantizedAllReduce:
    @pytest.mark.parametrize("hierarchy", ["auto", "flat"])
    def test_within_documented_bound(self, mesh, hierarchy):
        rs = np.random.RandomState(3)
        x = (rs.randn(8, 3000).astype(np.float32)) * 5
        ref = np.asarray(cc.all_reduce(x, ("dp", "mp"), mesh,
                                       compress=None, hierarchy="flat"))
        q = np.asarray(cc.all_reduce(x, ("dp", "mp"), mesh,
                                     compress="int8",
                                     hierarchy=hierarchy))
        bound = float(int8_error_bound(
            np.abs(x).max(), 8, bucket_absmax_out=np.abs(ref).max()))
        err = np.abs(q - ref).max()
        assert err <= bound
        # and the bound is not vacuous: it's small vs the data scale
        assert bound < np.abs(ref).max()

    @pytest.mark.parametrize("hierarchy", ["auto", "flat"])
    def test_constant_input_exact(self, mesh, hierarchy):
        for v in (3.25, -0.875, 11.0):
            x = np.full((8, 1037), v, np.float32)
            out = np.asarray(cc.all_reduce(x, ("dp", "mp"), mesh,
                                           compress="int8",
                                           hierarchy=hierarchy))
            np.testing.assert_array_equal(out, np.full(1037, v * 8))

    def test_zero_buckets_exact(self, mesh):
        x = np.zeros((8, 64), np.float32)
        out = np.asarray(cc.all_reduce(x, ("dp", "mp"), mesh,
                                       compress="int8"))
        assert np.all(out == 0)

    def test_runtime_error_bound_in_graph(self, mesh):
        # quantized_all_reduce(return_error_bound=True) reports a bound
        # the measured error respects, from inside shard_map
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P
        from paddle_tpu.distributed.collectives.quantized import (
            quantized_all_reduce)
        plan = plan_hierarchy(("dp", "mp"), mesh)
        rs = np.random.RandomState(4)
        x = rs.randn(8, 777).astype(np.float32)

        def inner(xl):
            out, bound = quantized_all_reduce(
                jnp.squeeze(xl, 0), plan, return_error_bound=True)
            return out, bound
        out, bound = shard_map(
            inner, mesh=mesh, in_specs=(P(("dp", "mp")),),
            out_specs=(P(), P()), check_rep=False)(jnp.asarray(x))
        err = np.abs(np.asarray(out) - x.sum(axis=0)).max()
        assert err <= float(bound)

    def test_config_routes_compress(self, mesh):
        x = np.full((8, 512), 1.5, np.float32)
        with configure(compress="int8"):
            out = np.asarray(cc.all_reduce(x, ("dp", "mp"), mesh))
        np.testing.assert_array_equal(out, np.full(512, 12.0))


class TestBucketing:
    def test_build_buckets_size_targeted(self):
        sizes = [("a", 100), ("b", 100), ("c", 150), ("d", 10),
                 ("e", 1000)]
        # 4-byte elems, 800-byte target -> a+b (800) | c+d (640) | e
        assert build_buckets(sizes, bucket_bytes=800) == \
            [["a", "b"], ["c", "d"], ["e"]]

    def test_build_buckets_oversized_tensor_alone(self):
        assert build_buckets([("big", 10 ** 6), ("s", 1)],
                             bucket_bytes=1024) == [["big"], ["s"]]

    def test_in_graph_hook_preserves_values(self, mesh):
        # shard_map over dp: per-device grads differ; bucketed sync must
        # equal plain psum-mean exactly (fp32, integer-valued)
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P
        rs = np.random.RandomState(5)
        shapes = {"w1": (4, 8), "b1": (8,), "w2": (8, 3), "b2": (3,)}
        stacked = {k: _idata(rs, (2,) + s) for k, s in shapes.items()}
        hook = BucketedGradSync(axes=("dp",), bucket_bytes=64,
                                compress=None, mesh=mesh)

        def inner(gs):
            local = {k: jnp.squeeze(v, 0) for k, v in gs.items()}
            synced = hook(local)
            ref = {k: jax.lax.pmean(v, "dp") for k, v in local.items()}
            return synced, ref
        specs = {k: P("dp") for k in shapes}
        synced, ref = shard_map(
            inner, mesh=mesh, in_specs=(specs,),
            out_specs=({k: P() for k in shapes},
                       {k: P() for k in shapes}),
            check_rep=False)(stacked)
        for k in shapes:
            assert np.array_equal(np.asarray(synced[k]),
                                  np.asarray(ref[k])), k
            assert synced[k].shape == shapes[k]

    def test_in_graph_hook_means_without_registered_mesh(self, mesh):
        # no mesh registered with the hook: the mean divisor must come
        # from the BOUND axes (regression: a flat total_size=1 plan
        # silently turned mean into sum)
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P
        hook = BucketedGradSync(axes=("dp",), mesh=None)
        x = np.asarray([[2.0, 4.0], [6.0, 8.0]], np.float32)

        def inner(g):
            return hook({"w": jnp.squeeze(g, 0)})["w"]
        out = shard_map(inner, mesh=mesh, in_specs=(P("dp"),),
                        out_specs=P(), check_rep=False)(jnp.asarray(x))
        np.testing.assert_array_equal(np.asarray(out), [4.0, 6.0])

    def test_zero_size_grads_skipped(self, mesh):
        # a zero-size gradient must pass through untouched, not shift
        # bucket offsets or crash the fused reshape
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P
        hook = BucketedGradSync(axes=("dp",), bucket_bytes=1 << 10,
                                mesh=mesh)
        gs = {"empty": np.zeros((2, 0, 3), np.float32),
              "w": np.asarray([[1.0, 3.0], [5.0, 7.0]], np.float32)}

        def inner(d):
            local = {k: jnp.squeeze(v, 0) for k, v in d.items()}
            return hook(local)
        out = shard_map(inner, mesh=mesh,
                        in_specs=({k: P("dp") for k in gs},),
                        out_specs={"empty": P("dp"), "w": P()},
                        check_rep=False)(
            {k: jnp.asarray(v) for k, v in gs.items()})
        assert out["empty"].shape == (0, 3)   # two (0,3) shards concat
        np.testing.assert_array_equal(np.asarray(out["w"]), [3.0, 5.0])
        # eager path: zero-size grads are filtered, others preserved
        from paddle_tpu.distributed.collectives import (
            bucketed_allreduce_gradients)
        p1 = paddle.to_tensor(np.zeros((0, 3), np.float32))
        p1.grad = paddle.to_tensor(np.zeros((0, 3), np.float32))
        p2 = paddle.to_tensor(np.ones((2, 2), np.float32))
        p2.grad = paddle.to_tensor(np.full((2, 2), 4.0, np.float32))
        bucketed_allreduce_gradients([p1, p2], bucket_bytes=8)
        np.testing.assert_array_equal(p2.grad.numpy(),
                                      np.full((2, 2), 4.0))

    def test_error_bound_budget_falls_back_to_fp32(self, mesh):
        # error_bound configured: buckets whose runtime bound exceeds
        # it must ship the fp32 reduction (bound=0 -> always fp32,
        # bit-equal to pmean); a lax budget keeps the quantized result
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P
        rs = np.random.RandomState(9)
        g = (rs.randn(2, 600) * 3).astype(np.float32)

        def run(bound):
            with configure(compress="int8", error_bound=bound):
                hook = BucketedGradSync(axes=("dp",), mesh=mesh)

            def inner(v):
                local = jnp.squeeze(v, 0)
                return hook({"w": local})["w"], \
                    jax.lax.pmean(local, "dp")
            return shard_map(inner, mesh=mesh, in_specs=(P("dp"),),
                             out_specs=(P(), P()), check_rep=False)(
                jnp.asarray(g))
        out0, ref = run(0.0)
        np.testing.assert_array_equal(np.asarray(out0), np.asarray(ref))
        outq, ref = run(1e9)
        assert np.abs(np.asarray(outq) - np.asarray(ref)).max() > 0

    def test_partially_bound_axes_raise(self, mesh):
        # hook over ("dp","mp") inside a shard_map that only binds
        # "dp": neither silently skipping nor subset-syncing is safe
        from jax.experimental.shard_map import shard_map
        from jax.sharding import Mesh, PartitionSpec as P
        hook = BucketedGradSync(axes=("dp", "mp"), mesh=mesh)
        sub = Mesh(np.array(jax.devices()[:2]), ("dp",))

        def inner(g):
            return hook({"w": jnp.squeeze(g, 0)})["w"]
        with pytest.raises(ValueError, match="only .* bound"):
            shard_map(inner, mesh=sub, in_specs=(P("dp"),),
                      out_specs=P(), check_rep=False)(
                jnp.ones((2, 4), jnp.float32))

    def test_hook_noop_outside_shard_map(self, mesh):
        # under plain jit (GSPMD) the axes are unbound: hook must be
        # identity, never a double reduction
        hook = BucketedGradSync(axes=("dp",), mesh=mesh)
        g = {"w": jnp.arange(6, dtype=jnp.float32)}
        out = jax.jit(lambda d: hook(d))(g)
        np.testing.assert_array_equal(np.asarray(out["w"]),
                                      np.asarray(g["w"]))
        out2 = hook(dict(g))          # eager
        np.testing.assert_array_equal(np.asarray(out2["w"]),
                                      np.asarray(g["w"]))

    def test_eager_bucketed_matches_unbucketed(self):
        # world size 1: both paths must leave grads exactly unchanged
        # while exercising the fuse/split bookkeeping
        from paddle_tpu import nn
        from paddle_tpu.distributed.fleet.utils import (
            fused_allreduce_gradients)
        paddle.seed(0)
        net = nn.Sequential(nn.Linear(6, 5), nn.ReLU(), nn.Linear(5, 2))
        x = paddle.to_tensor(np.ones((3, 6), np.float32))
        (net(x) ** 2).mean().backward()
        before = {i: p.grad.numpy().copy()
                  for i, p in enumerate(net.parameters())
                  if p.grad is not None}
        fused_allreduce_gradients(list(net.parameters()),
                                  bucket_bytes=40)   # tiny: many buckets
        for i, p in enumerate(net.parameters()):
            if p.grad is not None:
                np.testing.assert_array_equal(p.grad.numpy(), before[i])

    def test_dataparallel_sync_and_no_sync(self):
        from paddle_tpu import nn
        from paddle_tpu.distributed import DataParallel
        paddle.seed(0)
        net = DataParallel(nn.Linear(4, 2), comm_buffer_size=1)
        x = paddle.to_tensor(np.ones((2, 4), np.float32))
        (net(x) ** 2).mean().backward()
        g = net._layers.weight.grad.numpy().copy()
        with net.no_sync():
            net.sync_gradients()          # must be a no-op
        np.testing.assert_array_equal(net._layers.weight.grad.numpy(), g)
        net.sync_gradients()              # world 1: identity
        np.testing.assert_array_equal(net._layers.weight.grad.numpy(), g)

    def test_optimizer_hook_wiring_flag_off_and_on(self, mesh):
        from paddle_tpu import nn, optimizer
        from paddle_tpu.distributed.collectives import attach_grad_sync
        net = nn.Linear(4, 2)
        opt = optimizer.SGD(learning_rate=0.1,
                            parameters=net.parameters())
        assert attach_grad_sync(opt, axes=("dp",)) is None   # default off
        assert opt._grad_sync is None
        with configure(bucketed_grad_sync=True):
            hook = attach_grad_sync(opt, axes=("dp",))
        assert hook is opt._grad_sync
        assert isinstance(hook, BucketedGradSync)
        # flag back off: a re-attach clears the stale bucketed hook
        # (re-sharding must not keep syncing over the old axis) but
        # leaves a custom user hook alone
        assert attach_grad_sync(opt, axes=("mp",)) is None
        assert opt._grad_sync is None
        custom = lambda g: g                        # noqa: E731
        opt._grad_sync = custom
        attach_grad_sync(opt, axes=("dp",))
        assert opt._grad_sync is custom
        opt._grad_sync = hook
        # functional_update with the hook attached (axes unbound ->
        # identity) must produce the same step as without it
        x = paddle.to_tensor(np.ones((3, 4), np.float32))
        (net(x) ** 2).mean().backward()
        params = {n: p._value for n, p in
                  zip(opt._param_names, opt._param_list)}
        grads = {n: p.grad._value for n, p in
                 zip(opt._param_names, opt._param_list)
                 if p.grad is not None}
        state = opt.functional_state()
        new_p, _ = opt.functional_update(params, grads, state, 0.1)
        opt._grad_sync = None
        ref_p, _ = opt.functional_update(params, grads, state, 0.1)
        for n in new_p:
            np.testing.assert_array_equal(np.asarray(new_p[n]),
                                          np.asarray(ref_p[n]))

    def test_group_sharded_attaches_hook_behind_flag(self, mesh):
        from paddle_tpu import nn, optimizer
        from paddle_tpu.distributed.mesh import set_current_mesh
        from paddle_tpu.distributed.sharding import group_sharded_parallel
        set_current_mesh(mesh)
        try:
            net = nn.Linear(8, 4)
            opt = optimizer.SGD(learning_rate=0.1,
                                parameters=net.parameters())
            group_sharded_parallel(net, opt, "os")
            assert opt._grad_sync is None            # flag off: untouched
            with configure(bucketed_grad_sync=True):
                group_sharded_parallel(net, opt, "os")
            assert isinstance(opt._grad_sync, BucketedGradSync)
        finally:
            set_current_mesh(None)


class TestConfig:
    def test_validation(self):
        with pytest.raises(ValueError):
            CollectiveConfig(hierarchy="ring")
        with pytest.raises(ValueError):
            CollectiveConfig(compress="fp4")

    def test_configure_scoped(self):
        base = cc.collective_config().compress
        with configure(compress="int8"):
            assert cc.collective_config().compress == "int8"
        assert cc.collective_config().compress == base


class TestMicrobench:
    def test_reports_bytes_bandwidth_and_error(self, mesh):
        out = run_comms_bench(size_mb=0.1, iters=1, mesh=mesh)
        assert out["devices"] == 8 and out["mode"] == "hierarchical"
        for op in ("all_reduce", "reduce_scatter", "all_gather",
                   "all_reduce_int8"):
            assert out[op]["bytes_moved"] > 0
            assert out[op]["algbw_gbps"] > 0
            assert out[op]["time_ms"] > 0
        q = out["all_reduce_int8"]
        assert q["within_bound"] and q["constant_exact"]
        assert q["max_error"] == out["quant_vs_fp32_max_error"]
        assert q["bytes_moved"] < out["all_reduce"]["bytes_moved"]


class TestProfilerSpans:
    def test_record_event_emitted(self, mesh):
        from paddle_tpu import profiler
        prof = profiler.Profiler(targets=[profiler.ProfilerTarget.CPU],
                                 timer_only=True)
        with prof:
            cc.all_reduce(np.ones((8, 16), np.float32), ("dp", "mp"),
                          mesh, compress=None)
        ev = prof._drain_events()
        names = {e["name"] for e in ev}
        assert any(n.startswith("collectives::all_reduce") for n in names)


class TestBareShardMapErrorBound:
    """Regression (ROADMAP open item, PR 2 code): the runtime bound of
    quantized_all_reduce derived n from plan.total_size, which is 1 for
    a plan built with no registered mesh (bare shard_map) — the bound
    was understated ~n-fold, so BucketedGradSync's error_bound
    hard-guarantee mode could keep over-budget buckets. n must come
    from psum(1, axes) like bucketing.py's mean divisor."""

    def _bare_mesh(self):
        from jax.sharding import Mesh
        return Mesh(np.array(jax.devices()[:8]), ("r",))

    def _host_expected_bound(self, per_dev, bucket=512):
        # replicate the wire format on host: quantize each contribution,
        # fp32-accumulate the dequants, re-quantize the reduction — the
        # documented two-phase bound with the TRUE n=8
        from paddle_tpu.distributed.collectives.hierarchical import \
            pad_to_multiple
        from paddle_tpu.distributed.collectives.quantized import (
            _dequantize, _quantize, int8_error_bound)
        qs = [_quantize(pad_to_multiple(
            jnp.asarray(x).reshape(-1), bucket)[0], bucket)
            for x in per_dev]
        s_in = float(max(jnp.max(s) for _, s in qs))
        acc = sum(jnp.sum(_dequantize(q[None], s[None]), axis=0)
                  for q, s in qs)
        _, s_out = _quantize(acc.reshape(-1), bucket)
        n = len(per_dev)
        return (float(int8_error_bound(s_in, n,
                                       bucket_absmax_out=jnp.max(s_out))),
                float(int8_error_bound(s_in, 1,
                                       bucket_absmax_out=jnp.max(s_out))))

    def test_bound_counts_bound_ranks_not_plan_size(self):
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P
        from paddle_tpu.distributed.collectives.hierarchical import \
            HierarchyPlan
        from paddle_tpu.distributed.collectives.quantized import \
            quantized_all_reduce
        mesh = self._bare_mesh()
        # EXACTLY what plan_hierarchy returns with no mesh registered:
        # flat, total_size=1 — the bug's trigger
        plan = HierarchyPlan(("r",), None, None, 1, 1)
        rs = np.random.RandomState(11)
        x = rs.randn(8, 777).astype(np.float32)

        def inner(xl):
            return quantized_all_reduce(jnp.squeeze(xl, 0), plan,
                                        return_error_bound=True)
        out, bound = shard_map(
            inner, mesh=mesh, in_specs=(P("r"),),
            out_specs=(P(), P()), check_rep=False)(jnp.asarray(x))
        err = np.abs(np.asarray(out) - x.sum(axis=0)).max()
        expected_n8, wrong_n1 = self._host_expected_bound(list(x))
        assert err <= float(bound)                 # contract holds
        np.testing.assert_allclose(float(bound), expected_n8,
                                   rtol=1e-6)      # n is REALLY 8
        assert float(bound) > 2 * wrong_n1         # not the n=1 bound

    def test_hard_guarantee_rejects_over_budget_under_bare_shard_map(
            self):
        # budget just under the true bound: with the fix the hook must
        # fall back to the exact fp32 reduction; pre-fix the ~8x
        # understated bound sat far below the budget and the quantized
        # (lossy) bucket was kept
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P
        mesh = self._bare_mesh()
        rs = np.random.RandomState(12)
        x = rs.randn(8, 777).astype(np.float32)
        expected_n8, wrong_n1 = self._host_expected_bound(list(x))
        budget = 0.9 * expected_n8
        assert budget > 2 * wrong_n1     # pre-fix bound passes budget
        hook = BucketedGradSync(axes=("r",), compress="int8", mesh=None)
        hook.error_bound = budget

        def inner(g):
            return hook({"w": jnp.squeeze(g, 0)})["w"]
        out = shard_map(inner, mesh=mesh, in_specs=(P("r"),),
                        out_specs=P(), check_rep=False)(jnp.asarray(x))
        np.testing.assert_allclose(np.asarray(out),
                                   x.sum(axis=0) / 8, rtol=1e-6,
                                   atol=1e-6)      # exact fp32 fallback
