"""Op surface tests vs numpy references (reference pattern:
test/legacy_test/test_*_op.py — verify)."""
import numpy as np
import pytest
import scipy.special

import paddle_tpu as paddle
from op_test import OpTest


def rnd(*shape):
    return np.random.rand(*shape).astype(np.float32)


BINARY_CASES = [
    (paddle.add, np.add), (paddle.subtract, np.subtract),
    (paddle.multiply, np.multiply), (paddle.divide, np.divide),
    (paddle.maximum, np.maximum), (paddle.minimum, np.minimum),
    (paddle.pow, np.power), (paddle.atan2, np.arctan2),
]


@pytest.mark.parametrize("op,ref", BINARY_CASES,
                         ids=[o.__name__ for o, _ in BINARY_CASES])
def test_binary_ops(op, ref):
    x, y = rnd(3, 4) + 0.5, rnd(3, 4) + 0.5
    OpTest(op, ref).check_output([x, y])
    OpTest(op, ref).check_grad([x, y], wrt=(0, 1))


UNARY_CASES = [
    (paddle.exp, np.exp), (paddle.log, np.log), (paddle.sqrt, np.sqrt),
    (paddle.tanh, np.tanh), (paddle.sin, np.sin), (paddle.cos, np.cos),
    (paddle.sigmoid, scipy.special.expit), (paddle.abs, np.abs),
    (paddle.square, np.square), (paddle.floor, np.floor),
    (paddle.erf, scipy.special.erf),
    (paddle.log1p, np.log1p), (paddle.rsqrt, lambda v: 1 / np.sqrt(v)),
]


@pytest.mark.parametrize(
    "op,ref", UNARY_CASES,
    ids=["exp", "log", "sqrt", "tanh", "sin", "cos", "sigmoid", "abs",
         "square", "floor", "erf", "log1p", "rsqrt"])
def test_unary_ops(op, ref):
    x = rnd(3, 4) + 0.5
    OpTest(op, ref).check_output([x], atol=1e-4, rtol=1e-3)


def test_unary_grads():
    x = rnd(3, 3) + 0.5
    for op in (paddle.exp, paddle.log, paddle.tanh, paddle.sqrt):
        OpTest(op).check_grad([x])


def test_broadcasting():
    x, y = rnd(3, 1, 4), rnd(5, 1)
    OpTest(paddle.add, np.add).check_output([x, y])
    OpTest(paddle.multiply, np.multiply).check_grad([x, y], wrt=(0, 1))


def test_matmul():
    a, b = rnd(3, 4), rnd(4, 5)
    OpTest(paddle.matmul, np.matmul).check_output([a, b])
    OpTest(paddle.matmul, np.matmul).check_grad([a, b], wrt=(0, 1))
    # batched + transpose flags
    a3, b3 = rnd(2, 3, 4), rnd(2, 5, 4)
    ot = OpTest(paddle.matmul, lambda x, y, **kw: np.matmul(
        x, np.swapaxes(y, -1, -2)), kwargs={"transpose_y": True})
    ot.check_output([a3, b3])


def test_reductions():
    x = rnd(3, 4, 5)
    OpTest(paddle.sum, np.sum).check_output([x])
    OpTest(paddle.mean, np.mean, {"axis": 1}).check_output(
        [x], atol=1e-6)
    OpTest(paddle.max, lambda v, axis, keepdim: np.max(
        v, axis=axis, keepdims=keepdim),
        {"axis": 2, "keepdim": True}).check_output([x])
    OpTest(paddle.prod, np.prod, {"axis": 0}).check_output([x], atol=1e-5)
    OpTest(paddle.sum, lambda v, axis: np.sum(v, axis=tuple(axis)),
           {"axis": [0, 2]}).check_output([x], atol=1e-5)
    OpTest(paddle.mean, np.mean).check_grad([x])
    np.testing.assert_allclose(
        paddle.std(paddle.to_tensor(x)).item(), x.std(ddof=1), rtol=1e-5)


def test_manipulation():
    x = rnd(2, 3, 4)
    OpTest(paddle.reshape, lambda v, shape: np.reshape(v, shape),
           {"shape": (6, 4)}).check_output([x])
    OpTest(paddle.transpose, lambda v, perm: np.transpose(v, perm),
           {"perm": (2, 0, 1)}).check_output([x])
    OpTest(paddle.flatten, lambda v, start_axis: v.reshape(2, -1),
           {"start_axis": 1}).check_output([x])
    t = paddle.to_tensor(x)
    assert paddle.concat([t, t], axis=1).shape == [2, 6, 4]
    assert paddle.stack([t, t]).shape == [2, 2, 3, 4]
    parts = paddle.split(t, 3, axis=1)
    assert len(parts) == 3 and parts[0].shape == [2, 1, 4]
    parts = paddle.split(t, [1, 3], axis=2)
    assert parts[1].shape == [2, 3, 3]
    assert paddle.squeeze(paddle.ones((2, 1, 3)), 1).shape == [2, 3]
    assert paddle.unsqueeze(t, [0, 2]).shape == [1, 2, 1, 3, 4]
    assert paddle.tile(paddle.ones((2, 3)), [2, 2]).shape == [4, 6]
    assert paddle.expand(paddle.ones((1, 3)), [5, 3]).shape == [5, 3]
    assert paddle.flip(t, [0]).shape == [2, 3, 4]
    assert paddle.roll(t, 1, 0).shape == [2, 3, 4]


def test_concat_grad():
    def op(a, b):
        return paddle.concat([a, b], axis=0)
    OpTest(op, lambda a, b: np.concatenate([a, b])).check_output(
        [rnd(2, 3), rnd(4, 3)])
    OpTest(op).check_grad([rnd(2, 3), rnd(4, 3)], wrt=(0, 1))


def test_gather_scatter():
    x = rnd(5, 3)
    idx = np.array([0, 2, 4], np.int32)
    OpTest(paddle.gather, lambda v, i: v[i]).check_output([x, idx])
    out = paddle.gather_nd(paddle.to_tensor(x),
                           paddle.to_tensor(np.array([[0, 1], [2, 2]],
                                                     np.int32)))
    np.testing.assert_allclose(np.asarray(out._value),
                               np.array([x[0, 1], x[2, 2]]))
    t = paddle.to_tensor(x)
    upd = paddle.to_tensor(rnd(2, 3))
    res = paddle.scatter(t, paddle.to_tensor(np.array([1, 3], np.int32)),
                         upd)
    expect = x.copy()
    expect[[1, 3]] = np.asarray(upd._value)
    np.testing.assert_allclose(np.asarray(res._value), expect)


def test_index_sort_topk():
    x = rnd(4, 6)
    t = paddle.to_tensor(x)
    np.testing.assert_allclose(np.asarray(paddle.sort(t, 1)._value),
                               np.sort(x, 1))
    np.testing.assert_allclose(np.asarray(paddle.argsort(t, 1)._value),
                               np.argsort(x, 1, kind="stable"))
    vals, idx = paddle.topk(t, 3, axis=1)
    np.testing.assert_allclose(np.asarray(vals._value),
                               -np.sort(-x, 1)[:, :3], rtol=1e-6)
    np.testing.assert_allclose(
        np.asarray(paddle.argmax(t, 1)._value), np.argmax(x, 1))
    np.testing.assert_allclose(
        np.asarray(paddle.cumsum(t, 1)._value), np.cumsum(x, 1), rtol=1e-5)


def test_where_comparison():
    x, y = rnd(3, 4), rnd(3, 4)
    t, u = paddle.to_tensor(x), paddle.to_tensor(y)
    np.testing.assert_array_equal(
        np.asarray((t > u)._value), x > y)
    out = paddle.where(t > u, t, u)
    np.testing.assert_allclose(np.asarray(out._value), np.maximum(x, y))
    assert bool(paddle.allclose(t, paddle.to_tensor(x.copy())).item())


def test_einsum_norm():
    a, b = rnd(3, 4), rnd(4, 5)
    out = paddle.einsum("ij,jk->ik", paddle.to_tensor(a),
                        paddle.to_tensor(b))
    np.testing.assert_allclose(np.asarray(out._value), a @ b, rtol=1e-5)
    x = rnd(3, 4)
    np.testing.assert_allclose(
        paddle.norm(paddle.to_tensor(x)).item(),
        np.linalg.norm(x), rtol=1e-5)


def test_creation():
    assert paddle.zeros([2, 3]).shape == [2, 3]
    assert str(paddle.ones([2], dtype="int32").dtype) == "int32"
    np.testing.assert_array_equal(
        np.asarray(paddle.arange(0, 10, 2)._value), np.arange(0, 10, 2))
    assert paddle.eye(3).shape == [3, 3]
    assert paddle.full([2, 2], 7.0).numpy()[0, 0] == 7.0
    assert paddle.linspace(0, 1, 5).shape == [5]
    tr = paddle.tril(paddle.ones([4, 4]))
    assert tr.numpy()[0, 3] == 0 and tr.numpy()[3, 0] == 1
    x = paddle.rand([100, 100])
    assert 0.4 < float(x.mean()) < 0.6
    r = paddle.randn([1000])
    assert abs(float(r.mean())) < 0.2
    p = paddle.randperm(16)
    assert sorted(p.tolist()) == list(range(16))


def test_random_seed_determinism():
    paddle.seed(7)
    a = paddle.rand([4, 4]).numpy()
    paddle.seed(7)
    b = paddle.rand([4, 4]).numpy()
    np.testing.assert_array_equal(a, b)


def test_cast_dtypes():
    x = paddle.to_tensor(np.array([1.7, -2.3], np.float32))
    assert str(paddle.cast(x, "int32").dtype) == "int32"
    assert str(x.astype("bfloat16").dtype) == "bfloat16"
    # int64/float64 degrade (documented)
    y = paddle.to_tensor(np.array([1, 2], np.int64))
    assert str(y.dtype) == "int32"


def test_indexing():
    x = rnd(4, 5, 6)
    t = paddle.to_tensor(x)
    np.testing.assert_allclose(np.asarray(t[1]._value), x[1])
    np.testing.assert_allclose(np.asarray(t[1:3, ::2]._value), x[1:3, ::2])
    np.testing.assert_allclose(np.asarray(t[..., -1]._value), x[..., -1])
    idx = paddle.to_tensor(np.array([0, 2], np.int32))
    np.testing.assert_allclose(np.asarray(t[idx]._value), x[[0, 2]])
    t2 = paddle.to_tensor(x.copy())
    t2[0] = 0.0
    assert float(t2[0].sum()) == 0.0


def test_inplace_and_item():
    t = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
    t.add_(paddle.to_tensor(np.array([1.0, 1.0], np.float32)))
    np.testing.assert_allclose(t.numpy(), [2.0, 3.0])
    assert paddle.to_tensor(3.5).item() == 3.5
    assert paddle.to_tensor([[1, 2]]).tolist() == [[1, 2]]


class TestEnforceLayer:
    """Systematic error layer (reference: PADDLE_ENFORCE_* + typed
    EnforceNotMet hierarchy — SURVEY §2.1 'Enforce')."""

    def test_typed_hierarchy_catchable_both_ways(self):
        import pytest
        from paddle_tpu.utils.enforce import (InvalidArgumentError,
                                              EnforceNotMet, enforce_eq)
        with pytest.raises(InvalidArgumentError):
            enforce_eq(3, 4, "degree")
        with pytest.raises(ValueError):     # stays ValueError-compatible
            enforce_eq(3, 4, "degree")
        with pytest.raises(EnforceNotMet, match="expected 4, got 3"):
            enforce_eq(3, 4, "degree")

    def test_helpers_and_hints(self):
        import pytest
        import numpy as np
        from paddle_tpu.utils import enforce as E
        E.enforce(True, "fine")
        E.enforce_ge(5, 5, "n")
        E.enforce_in("ring", ("ring", "ulysses"), "mode")
        E.enforce_shape(np.zeros((2, 3)), [None, 3])
        E.enforce_dtype(np.zeros((1,), "float32"), "float32")
        E.enforce_dtype(np.zeros((1,), "int64"), "int64")   # no 64->32
        E.enforce_dtype(np.zeros((1,), "float64"), "float64")
        with pytest.raises(E.InvalidArgumentError, match="Hint"):
            E.enforce_shape(np.zeros((2, 3)), [4, 3], "w",
                            hint="transpose your input")
        with pytest.raises(E.PreconditionNotMetError):
            E.enforce(False, "nope")

    def test_rethrow_wraps_with_context(self):
        import pytest
        from paddle_tpu.utils.enforce import rethrow, EnforceNotMet
        try:
            raise KeyError("missing")
        except KeyError as e:
            with pytest.raises(EnforceNotMet, match="loading ckpt"):
                rethrow(e, "loading ckpt")

    def test_generation_uses_typed_error(self):
        import pytest
        import numpy as np
        from paddle_tpu.models.llama import (LlamaForCausalLM,
                                             llama_tiny_config)
        from paddle_tpu.utils.enforce import OutOfRangeError
        paddle.seed(0)
        m = LlamaForCausalLM(llama_tiny_config(tensor_parallel=False))
        ids = paddle.to_tensor(np.zeros((1, 8), "int32"))
        with pytest.raises(OutOfRangeError):
            m.generate(ids, max_new_tokens=10_000)

    def test_notfound_str_and_range_valueerror_compat(self):
        import pytest
        from paddle_tpu.utils.enforce import (NotFoundError,
                                              OutOfRangeError)
        e = NotFoundError("ckpt not found", "check the path")
        assert str(e) == "ckpt not found\n  [Hint: check the path]"
        with pytest.raises(ValueError):      # back-compat
            raise OutOfRangeError("too long")
        import paddle_tpu.utils as U
        assert U.AlreadyExistsError and U.ExecutionTimeoutError


class TestLongTailR2B:
    """Round-2 second-batch long-tail ops (reference:
    python/paddle/tensor/{math,manipulation,attribute}.py — verify)."""

    def test_complex_polar_sgn(self):
        c = paddle.complex(paddle.to_tensor([1., 2.]),
                           paddle.to_tensor([3., 4.]))
        np.testing.assert_allclose(c.numpy(), [1 + 3j, 2 + 4j])
        p = paddle.polar(paddle.to_tensor([2.]),
                         paddle.to_tensor([np.pi], "float32"))
        np.testing.assert_allclose(p.numpy(), [-2 + 0j], atol=1e-6)
        s = paddle.sgn(c)
        np.testing.assert_allclose(np.abs(s.numpy()), [1., 1.], rtol=1e-6)
        np.testing.assert_allclose(
            paddle.sgn(paddle.to_tensor([-5., 0., 3.])).numpy(), [-1, 0, 1])

    def test_pdist(self):
        x = np.random.rand(5, 3).astype(np.float32)
        got = paddle.pdist(paddle.to_tensor(x)).numpy()
        want = np.array([np.linalg.norm(x[i] - x[j])
                         for i in range(5) for j in range(i + 1, 5)])
        np.testing.assert_allclose(got, want, rtol=1e-5)

    def test_predicates_and_rank(self):
        x = paddle.to_tensor([[1., 2.]])
        assert int(paddle.rank(x).item()) == 2
        assert paddle.is_floating_point(x) and not paddle.is_complex(x)
        assert paddle.is_tensor(x) and not paddle.is_tensor(x.numpy())
        assert paddle.is_integer(paddle.to_tensor([1]))
        assert bool(paddle.is_empty(paddle.zeros((0, 2))).item())
        assert not bool(paddle.is_empty(x).item())

    def test_multiplex_combinations_cat_inverse(self):
        a = paddle.to_tensor([[1., 2.], [3., 4.]])
        b = paddle.to_tensor([[10., 20.], [30., 40.]])
        out = paddle.multiplex([a, b], paddle.to_tensor([[0], [1]]))
        np.testing.assert_allclose(out.numpy(), [[1, 2], [30, 40]])
        c = paddle.combinations(paddle.to_tensor([1, 2, 3]))
        np.testing.assert_allclose(c.numpy(), [[1, 2], [1, 3], [2, 3]])
        cr = paddle.combinations(paddle.to_tensor([1, 2]), r=2,
                                 with_replacement=True)
        np.testing.assert_allclose(cr.numpy(), [[1, 1], [1, 2], [2, 2]])
        np.testing.assert_allclose(paddle.cat([a, b], axis=1).numpy(),
                                   np.concatenate([a.numpy(), b.numpy()], 1))
        m = paddle.to_tensor([[4., 0.], [0., 2.]])
        np.testing.assert_allclose(paddle.inverse(m).numpy(),
                                   [[.25, 0], [0, .5]])

    def test_inplace_random_fills(self):
        paddle.seed(7)
        x = paddle.zeros((2000,))
        x.uniform_(0., 4.)
        v = x.numpy()
        assert 0 <= v.min() and v.max() <= 4 and abs(v.mean() - 2) < .2
        x.normal_(mean=1., std=3.)
        v = x.numpy()
        assert abs(v.mean() - 1) < .3 and abs(v.std() - 3) < .3
        x.exponential_(4.)
        assert abs(x.numpy().mean() - .25) < .05
        x.geometric_(0.25)
        v = x.numpy()
        assert v.min() >= 1 and abs(v.mean() - 4) < .4

    def test_inplace_random_cuts_grad(self):
        w = paddle.to_tensor([1., 2.], stop_gradient=False)
        z = w * 2
        w.uniform_()
        (z.sum() + (w * 5).sum()).backward()
        # only the pre-overwrite read of w contributes
        np.testing.assert_allclose(w.grad.numpy(), [2., 2.])


def test_iinfo_finfo_dlpack_flops_hub(tmp_path):
    import torch
    import paddle_tpu.nn as nn
    assert paddle.iinfo("int8").max == 127
    assert paddle.finfo("bfloat16").bits == 16
    t = paddle.to_tensor(np.arange(6, dtype=np.float32).reshape(2, 3))
    tt = torch.utils.dlpack.from_dlpack(paddle.utils.dlpack.to_dlpack(t))
    np.testing.assert_array_equal(tt.numpy(), t.numpy())
    back = paddle.utils.dlpack.from_dlpack(torch.arange(4).float())
    np.testing.assert_array_equal(back.numpy(), [0., 1., 2., 3.])
    net = nn.Sequential(nn.Linear(10, 20), nn.ReLU(), nn.Linear(20, 5))
    assert paddle.flops(net, (1, 10)) == 2 * (10 * 20 + 20 * 5)
    (tmp_path / "hubconf.py").write_text(
        "def tiny(width=4):\n"
        "    '''doc'''\n"
        "    import paddle_tpu.nn as nn\n"
        "    return nn.Linear(width, width)\n")
    assert paddle.hub.list(str(tmp_path)) == ["tiny"]
    assert paddle.hub.load(str(tmp_path), "tiny", width=3).weight.shape \
        == [3, 3]
    import pytest as _pytest
    with _pytest.raises(ValueError):
        paddle.hub.load("x", "y", source="github")
