"""sparse.nn tests: Conv3D/SubmConv3D/BatchNorm/attention vs dense
references."""
import jax
import numpy as np
import pytest

import paddle_tpu as paddle
class TestSparseNN:
    """sparse.nn Conv3D/SubmConv3D/BatchNorm/attention vs dense
    references (VERDICT r2: SURVEY §2.2 sparse row was partial — no
    sparse conv3d / attention ops)."""

    def _voxels(self, N=2, D=6, H=5, W=7, C=3, nnz=25, seed=0):
        rs = np.random.RandomState(seed)
        coords = set()
        while len(coords) < nnz:
            coords.add((rs.randint(N), rs.randint(D), rs.randint(H),
                        rs.randint(W)))
        idx = np.array(sorted(coords)).T                 # (4, nnz)
        vals = rs.randn(idx.shape[1], C).astype(np.float32)
        x = paddle.sparse.sparse_coo_tensor(
            idx, vals, shape=(N, D, H, W, C))
        dense = np.zeros((N, D, H, W, C), np.float32)
        dense[tuple(idx)] = vals
        return x, dense

    def _dense_conv(self, dense, w, stride, padding):
        import jax.numpy as jnp
        out = jax.lax.conv_general_dilated(
            jnp.asarray(dense), jnp.asarray(w),
            window_strides=(stride,) * 3,
            padding=[(padding,) * 2] * 3,
            dimension_numbers=("NDHWC", "DHWIO", "NDHWC"))
        return np.asarray(out)

    @pytest.mark.parametrize("stride,padding", [(1, 1), (2, 1), (1, 0)])
    def test_conv3d_matches_dense(self, stride, padding):
        from paddle_tpu.sparse.nn import Conv3D
        x, dense = self._voxels()
        conv = Conv3D(3, 4, kernel_size=3, stride=stride, padding=padding)
        out = conv(x)
        ref = self._dense_conv(dense, np.asarray(conv.weight._value),
                               stride, padding)
        ref = ref + np.asarray(conv.bias._value)
        got = np.asarray(out.to_dense().numpy())
        # sparse conv only materializes sites reachable from an active
        # input voxel; compare on those sites and assert the rest of ref
        # is bias-only
        oi = np.asarray(out.indices())
        np.testing.assert_allclose(got[tuple(oi)], ref[tuple(oi)],
                                   rtol=1e-4, atol=1e-4)
        mask = np.zeros(ref.shape[:4], bool)
        mask[tuple(oi)] = True
        np.testing.assert_allclose(
            ref[~mask], np.broadcast_to(np.asarray(conv.bias._value),
                                        ref.shape)[~mask],
            rtol=1e-4, atol=1e-4)

    def test_subm_conv3d_matches_dense_on_input_sites(self):
        from paddle_tpu.sparse.nn import SubmConv3D
        x, dense = self._voxels()
        conv = SubmConv3D(3, 4, kernel_size=3, padding=1, bias_attr=False)
        out = conv(x)
        ii = np.asarray(x.indices())
        oi = np.asarray(out.indices())
        np.testing.assert_array_equal(ii, oi)   # pattern preserved
        ref = self._dense_conv(dense, np.asarray(conv.weight._value),
                               1, 1)
        got = np.asarray(out.to_dense().numpy())
        np.testing.assert_allclose(got[tuple(oi)], ref[tuple(oi)],
                                   rtol=1e-4, atol=1e-4)

    def test_batchnorm_relu(self):
        from paddle_tpu.sparse.nn import BatchNorm, ReLU
        x, _ = self._voxels()
        bn = BatchNorm(3)
        out = bn(x)
        v = np.asarray(out.values().numpy())
        np.testing.assert_allclose(v.mean(0), 0.0, atol=1e-5)
        np.testing.assert_allclose(v.std(0), 1.0, atol=1e-2)
        r = ReLU()(out)
        assert (np.asarray(r.values().numpy()) >= 0).all()

    def test_sparse_attention_matches_masked_dense(self):
        from paddle_tpu.sparse.nn import functional as F
        rs = np.random.RandomState(1)
        b, h, s, d = 1, 2, 8, 4
        q, k, v = (rs.randn(b, h, s, d).astype(np.float32)
                   for _ in range(3))
        # random causal-ish pattern, SHARED across batch heads (jax's
        # batched BCSR requires uniform nse per batch)
        pat = np.tril(rs.rand(s, s) < 0.6)
        np.fill_diagonal(pat, True)
        allow = np.broadcast_to(pat, (b * h, s, s)).copy()
        rptr = [0]
        cols1 = []
        for r in range(s):
            cs = np.nonzero(pat[r])[0]
            cols1.extend(cs)
            rptr.append(rptr[-1] + len(cs))
        nse = len(cols1)
        crows = np.broadcast_to(np.asarray(rptr), (b * h, s + 1))
        cols = np.broadcast_to(np.asarray(cols1), (b * h, nse))
        vals = np.ones((b * h, nse), np.float32)
        mask = paddle.sparse.sparse_csr_tensor(
            crows, cols, vals, shape=(b * h, s, s))
        out = F.attention(paddle.to_tensor(q), paddle.to_tensor(k),
                          paddle.to_tensor(v), mask).numpy()
        # dense reference
        scores = q @ k.transpose(0, 1, 3, 2) / np.sqrt(d)
        scores = np.where(allow.reshape(b, h, s, s), scores, -1e30)
        e = np.exp(scores - scores.max(-1, keepdims=True))
        p = e / e.sum(-1, keepdims=True)
        ref = p @ v
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)

    def test_sparse_layers_register_in_parent(self):
        """sparse.nn modules are nn.Layer subclasses: their params reach
        an enclosing model's parameters()/state_dict (else they would
        silently never train)."""
        from paddle_tpu import nn
        from paddle_tpu.sparse.nn import SubmConv3D, BatchNorm

        class Net(nn.Layer):
            def __init__(self):
                super().__init__()
                self.conv = SubmConv3D(3, 4, 3, padding=1)
                self.bn = BatchNorm(4)
                self.fc = nn.Linear(4, 2)

        net = Net()
        names = [n for n, _ in net.named_parameters()]
        assert any("conv.weight" in n for n in names), names
        assert any("bn.weight" in n for n in names), names
        sd = net.state_dict()
        assert any("_mean" in k for k in sd), list(sd)[:8]
        # sparse forward flows through the composed model
        x, _ = self._voxels(C=3)
        out = net.bn(net.conv(x))
        assert out.to_dense().shape[-1] == 4

    def test_attention_padding_never_leaks_outside_pattern(self):
        """-inf key_padding_mask on every allowed key of a row must give
        exact zeros — never probability mass on DISALLOWED keys."""
        from paddle_tpu.sparse.nn import functional as F
        rs = np.random.RandomState(2)
        b, h, s, d = 1, 1, 4, 4
        q, k, v = (rs.randn(b, h, s, d).astype(np.float32)
                   for _ in range(3))
        # row 0 allows only key 0; rows 1..3 allow keys {0..r}
        pat = np.tril(np.ones((s, s), bool))
        rptr = np.cumsum([0] + [pat[r].sum() for r in range(s)])
        cols1 = np.concatenate([np.nonzero(pat[r])[0] for r in range(s)])
        mask = paddle.sparse.sparse_csr_tensor(
            rptr[None], cols1[None], np.ones((1, len(cols1)), np.float32),
            shape=(b * h, s, s))
        # pad key 0 out entirely: row 0's only allowed key is dead
        kp = np.zeros((b, s), np.float32)
        kp[0, 0] = -np.inf
        out = F.attention(paddle.to_tensor(q), paddle.to_tensor(k),
                          paddle.to_tensor(v), mask,
                          key_padding_mask=paddle.to_tensor(kp)).numpy()
        np.testing.assert_array_equal(out[0, 0, 0], np.zeros(d))
        # other rows: reference = softmax over allowed keys minus key 0
        for r in range(1, s):
            sc = (q[0, 0, r] @ k[0, 0, :r + 1].T) / np.sqrt(d)
            sc[0] = -np.inf
            e = np.exp(sc - sc[1:].max())
            e[0] = 0.0
            p = e / e.sum()
            np.testing.assert_allclose(out[0, 0, r],
                                       p @ v[0, 0, :r + 1],
                                       rtol=1e-4, atol=1e-5)

    def test_batchnorm_running_stats_unbiased(self):
        """Running variance uses the unbiased (n/(n-1)) correction —
        same semantics as the dense BatchNorm."""
        from paddle_tpu.sparse.nn import BatchNorm
        x, _ = self._voxels(nnz=10)
        bn = BatchNorm(3, momentum=0.0)     # running := batch stats
        bn(x)
        vals = np.asarray(x.values().numpy())
        n = vals.shape[0]
        expect = vals.var(0) * n / (n - 1)
        np.testing.assert_allclose(np.asarray(bn._variance.numpy()),
                                   expect, rtol=1e-5)

    def test_functional_is_importable_module(self):
        """paddle parity: sparse.nn.functional is a real module."""
        import importlib
        m = importlib.import_module("paddle_tpu.sparse.nn.functional")
        assert hasattr(m, "attention") and hasattr(m, "subm_conv3d")
