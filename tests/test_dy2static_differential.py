"""Differential dy2static fuzzing: a catalog of control-flow shapes
(tensor if/elif, early returns, while with break/continue, for-range,
nesting) instantiated with random constants and inputs, run eager vs
``@to_static`` — values and gradients must match. Complements the
targeted conversion tests in test_dy2static.py the way the reference's
dygraph_to_static suite sweeps program shapes (reference:
test/dygraph_to_static — verify)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.jit import to_static


def prog_if_else(c1, c2):
    def f(x):
        if x.sum() > c1:
            y = x * c2
        else:
            y = x + c1
        return y.mean()
    return f


def prog_early_return(c1, c2):
    def f(x):
        if x.sum() > c1:
            return (x * c2).sum()
        z = x - c1
        return z.mean()
    return f


def prog_elif_chain(c1, c2):
    def f(x):
        s = x.sum()
        if s > c1 + 10:
            out = x * 3.0
        elif s > c1:
            out = x * c2
        elif s > c1 - 10:
            out = x + c2
        else:
            out = -x
        return out.sum()
    return f


def prog_while_accum(c1, c2):
    def f(x):
        total = x.sum() * 0.0
        i = paddle.to_tensor(np.float32(0.0))
        while i < c1:
            total = total + (x * (i + 1.0)).mean()
            i = i + 1.0
        return total * c2
    return f


def prog_while_break(c1, c2):
    def f(x):
        acc = x.sum() * 0.0
        i = paddle.to_tensor(np.float32(0.0))
        while i < 8.0:
            acc = acc + x.mean() * c2
            if acc > c1:
                break
            i = i + 1.0
        return acc
    return f


def prog_while_continue(c1, c2):
    def f(x):
        acc = x.sum() * 0.0
        i = paddle.to_tensor(np.float32(0.0))
        while i < 6.0:
            i = i + 1.0
            if i * 1.0 > c1:
                continue
            acc = acc + x.mean() * i
        return acc * c2
    return f


def prog_nested(c1, c2):
    def f(x):
        acc = x.sum() * 0.0
        i = paddle.to_tensor(np.float32(0.0))
        while i < 4.0:
            if (x.mean() + i) > c1:
                acc = acc + x.mean() * c2
            else:
                acc = acc - x.mean()
            i = i + 1.0
        return acc
    return f


def prog_for_range(c1, c2):
    def f(x):
        acc = x.mean() * 0.0
        for i in range(4):
            acc = acc + x.mean() * float(i + 1)
        return acc * c2 + c1
    return f


CATALOG = [prog_if_else, prog_early_return, prog_elif_chain,
           prog_while_accum, prog_while_break, prog_while_continue,
           prog_nested, prog_for_range]


class TestDy2StaticDifferential:
    @pytest.mark.parametrize("seed", list(range(16)))
    def test_value_and_grad_parity(self, seed):
        rng = np.random.RandomState(seed)
        maker = CATALOG[seed % len(CATALOG)]
        c1 = float(np.round(rng.uniform(-2, 4), 2))
        c2 = float(np.round(rng.uniform(0.5, 2.0), 2))
        if maker is prog_while_accum:
            c1 = float(rng.randint(1, 5))
        fn = maker(c1, c2)
        sfn = to_static(maker(c1, c2))
        for trial in range(3):
            xv = rng.randn(3, 4).astype(np.float32)
            xe = paddle.to_tensor(xv.copy())
            xe.stop_gradient = False
            out_e = fn(xe)
            out_e.backward()
            ge = xe.grad.numpy()

            xs = paddle.to_tensor(xv.copy())
            xs.stop_gradient = False
            out_s = sfn(xs)
            out_s.backward()
            gs = xs.grad.numpy()

            np.testing.assert_allclose(
                float(out_s._value), float(out_e._value), rtol=2e-5,
                atol=2e-6,
                err_msg=f"{maker.__name__} c1={c1} c2={c2} t{trial}")
            np.testing.assert_allclose(
                gs, ge, rtol=2e-4, atol=2e-5,
                err_msg=f"grad {maker.__name__} c1={c1} c2={c2}")
