"""CPU smoke for the user-facing examples/ scripts — the migration
surface a reference user tries first must not rot. Full/weekly lane
only (full_lane.txt): five subprocess jax startups (~3-4 min).

Each example documents its own CPU smoke invocation in its docstring;
these run exactly those."""
import os
import subprocess
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CASES = [
    ("train_llama.py", ["--cpu", "--tiny", "--steps", "2",
                        "--batch", "2", "--seq", "32"]),
    ("generate.py", ["--cpu", "--tiny", "--max-new", "4"]),
    ("finetune_vision.py", ["--cpu", "--epochs", "1"]),
    ("ps_recsys.py", []),
    ("text_to_image.py", []),
]


@pytest.mark.parametrize("script,args",
                         CASES, ids=[c[0] for c in CASES])
def test_example_cpu_smoke(script, args):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("PALLAS_AXON_POOL_IPS", None)   # no axon register() dial
    env["XLA_FLAGS"] = ("--xla_llvm_disable_expensive_passes=true"
                        " --xla_backend_optimization_level=0")
    p = subprocess.run(
        [sys.executable, os.path.join(ROOT, "examples", script), *args],
        capture_output=True, text=True, timeout=600, env=env, cwd=ROOT)
    assert p.returncode == 0, (script, p.stdout[-1500:],
                               p.stderr[-1500:])
