"""linalg / functional autograd / distribution / fft / signal surface
tests vs numpy-scipy references (reference pattern: test/legacy_test/
test_*_op.py and test/distribution/ — verify)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import linalg, distribution as D, fft, signal
from paddle_tpu.tensor import Tensor


def rnd(*shape):
    return np.random.rand(*shape).astype(np.float32)


def spd(n):
    a = rnd(n, n)
    return (a @ a.T + n * np.eye(n)).astype(np.float32)


class TestLinalg:
    def test_cholesky_roundtrip(self):
        a = spd(4)
        for upper in (False, True):
            c = linalg.cholesky(paddle.to_tensor(a), upper=upper).numpy()
            rec = c.T @ c if upper else c @ c.T
            np.testing.assert_allclose(rec, a, rtol=1e-4, atol=1e-4)

    def test_cholesky_solve(self):
        a, b = spd(4), rnd(4, 2)
        c = np.linalg.cholesky(a)
        x = linalg.cholesky_solve(paddle.to_tensor(b), paddle.to_tensor(c),
                                  upper=False).numpy()
        np.testing.assert_allclose(a @ x, b, rtol=1e-3, atol=1e-3)

    def test_det_slogdet_inv(self):
        a = spd(3)
        np.testing.assert_allclose(linalg.det(paddle.to_tensor(a)).numpy(),
                                   np.linalg.det(a), rtol=1e-3)
        sld = linalg.slogdet(paddle.to_tensor(a)).numpy()
        sign, logdet = np.linalg.slogdet(a)
        np.testing.assert_allclose(sld, [sign, logdet], rtol=1e-4)
        inv = linalg.inv(paddle.to_tensor(a)).numpy()
        np.testing.assert_allclose(a @ inv, np.eye(3), atol=1e-4)

    def test_solve_triangular_lstsq(self):
        a, b = spd(4), rnd(4)
        x = linalg.solve(paddle.to_tensor(a), paddle.to_tensor(b)).numpy()
        np.testing.assert_allclose(a @ x, b, rtol=1e-3, atol=1e-3)
        t = np.triu(rnd(4, 4)) + 2 * np.eye(4, dtype=np.float32)
        y = linalg.triangular_solve(paddle.to_tensor(t),
                                    paddle.to_tensor(b[:, None])).numpy()
        np.testing.assert_allclose(t @ y, b[:, None], atol=1e-4)
        a2, b2 = rnd(6, 3), rnd(6, 2)
        sol, res, rank, sv = linalg.lstsq(paddle.to_tensor(a2),
                                          paddle.to_tensor(b2))
        ref = np.linalg.lstsq(a2, b2, rcond=None)[0]
        np.testing.assert_allclose(sol.numpy(), ref, rtol=1e-3, atol=1e-4)
        assert int(rank.numpy()) == 3

    def test_qr_svd(self):
        a = rnd(5, 3)
        q, r = linalg.qr(paddle.to_tensor(a))
        np.testing.assert_allclose(q.numpy() @ r.numpy(), a, atol=1e-4)
        np.testing.assert_allclose(q.numpy().T @ q.numpy(), np.eye(3),
                                   atol=1e-4)
        u, s, vh = linalg.svd(paddle.to_tensor(a))
        np.testing.assert_allclose(
            u.numpy() @ np.diag(s.numpy()) @ vh.numpy(), a, atol=1e-4)
        np.testing.assert_allclose(
            s.numpy(), np.linalg.svd(a, compute_uv=False), rtol=1e-4)

    def test_eigh(self):
        a = spd(4)
        w, v = linalg.eigh(paddle.to_tensor(a))
        np.testing.assert_allclose(a @ v.numpy(),
                                   v.numpy() * w.numpy()[None, :],
                                   rtol=1e-3, atol=1e-3)
        np.testing.assert_allclose(
            linalg.eigvalsh(paddle.to_tensor(a)).numpy(),
            np.linalg.eigvalsh(a), rtol=1e-4)

    def test_lu_unpack_roundtrip(self):
        a = spd(4)
        lu_mat, piv = linalg.lu(paddle.to_tensor(a))
        p, l, u = linalg.lu_unpack(lu_mat, piv)
        np.testing.assert_allclose(
            p.numpy() @ l.numpy() @ u.numpy(), a, rtol=1e-3, atol=1e-3)

    def test_lu_unpack_batched(self):
        a = np.stack([spd(4), spd(4) + np.float32(1)])
        lu_mat, piv = linalg.lu(paddle.to_tensor(a))
        p, l, u = linalg.lu_unpack(lu_mat, piv)
        np.testing.assert_allclose(
            p.numpy() @ l.numpy() @ u.numpy(), a, rtol=1e-3, atol=1e-3)

    def test_vector_norm_keepdim_and_vecdot_conj(self):
        x = rnd(3, 4)
        out = linalg.vector_norm(paddle.to_tensor(x), keepdim=True)
        assert out.shape == [1, 1]
        z = np.array([1j, 2 + 1j], np.complex64)
        got = linalg.vecdot(paddle.to_tensor(z), paddle.to_tensor(z)).numpy()
        np.testing.assert_allclose(got, np.vdot(z, z), rtol=1e-6)

    def test_pinv_matrix_rank_cond(self):
        a = rnd(4, 3)
        np.testing.assert_allclose(linalg.pinv(paddle.to_tensor(a)).numpy(),
                                   np.linalg.pinv(a), rtol=1e-3, atol=1e-4)
        assert int(linalg.matrix_rank(paddle.to_tensor(a)).numpy()) == 3
        s = spd(3)
        np.testing.assert_allclose(linalg.cond(paddle.to_tensor(s)).numpy(),
                                   np.linalg.cond(s), rtol=1e-3)

    def test_matrix_exp_multi_dot_norms(self):
        a = 0.1 * spd(3)
        import scipy.linalg
        np.testing.assert_allclose(
            linalg.matrix_exp(paddle.to_tensor(a)).numpy(),
            scipy.linalg.expm(a), rtol=1e-3, atol=1e-4)
        ms = [rnd(2, 3), rnd(3, 4), rnd(4, 2)]
        np.testing.assert_allclose(
            linalg.multi_dot([paddle.to_tensor(m) for m in ms]).numpy(),
            ms[0] @ ms[1] @ ms[2], rtol=1e-4)
        v = rnd(5)
        np.testing.assert_allclose(
            linalg.vector_norm(paddle.to_tensor(v), p=3).numpy(),
            np.sum(np.abs(v) ** 3) ** (1 / 3), rtol=1e-4)
        m = rnd(3, 4)
        np.testing.assert_allclose(
            linalg.matrix_norm(paddle.to_tensor(m)).numpy(),
            np.linalg.norm(m), rtol=1e-4)

    def test_svd_lowrank(self):
        # exactly rank-2 matrix: lowrank svd with q>=2 recovers it
        a = (rnd(6, 2) @ rnd(2, 5)).astype(np.float32)
        u, s, v = linalg.svd_lowrank(paddle.to_tensor(a), q=4)
        rec = u.numpy() @ np.diag(s.numpy()) @ v.numpy().T
        np.testing.assert_allclose(rec, a, rtol=1e-2, atol=1e-3)

    def test_cov_corrcoef(self):
        x = rnd(3, 50)
        np.testing.assert_allclose(linalg.cov(paddle.to_tensor(x)).numpy(),
                                   np.cov(x), rtol=1e-3, atol=1e-5)
        np.testing.assert_allclose(
            linalg.corrcoef(paddle.to_tensor(x)).numpy(),
            np.corrcoef(x), rtol=1e-3, atol=1e-5)

    def test_svd_grad_flows(self):
        a = paddle.to_tensor(spd(3), stop_gradient=False)
        _, s, _ = linalg.svd(a)
        s.sum().backward()
        assert a.grad is not None
        assert np.all(np.isfinite(a.grad.numpy()))


class TestFunctionalAutograd:
    def test_vjp_jvp(self):
        from paddle_tpu.autograd import vjp, jvp
        x = paddle.to_tensor(rnd(3))
        out, g = vjp(lambda t: (t * t).sum(), x)
        np.testing.assert_allclose(g.numpy(), 2 * x.numpy(), rtol=1e-5)
        out, tan = jvp(lambda t: (t * t).sum(), x,
                       paddle.to_tensor(np.ones(3, np.float32)))
        np.testing.assert_allclose(tan.numpy(), np.sum(2 * x.numpy()),
                                   rtol=1e-5)

    def test_jacobian(self):
        from paddle_tpu.autograd import jacobian
        x = paddle.to_tensor(rnd(3))
        jac = jacobian(lambda t: t * t, x)
        np.testing.assert_allclose(jac[:].numpy(),
                                   np.diag(2 * x.numpy()), rtol=1e-5)

    def test_hessian(self):
        from paddle_tpu.autograd import hessian
        x = paddle.to_tensor(rnd(3))
        h = hessian(lambda t: (t ** 3).sum(), x)
        np.testing.assert_allclose(h[:].numpy(),
                                   np.diag(6 * x.numpy()), rtol=1e-4)


class TestDistribution:
    def test_normal(self):
        d = D.Normal(0.0, 2.0)
        import scipy.stats
        np.testing.assert_allclose(
            d.log_prob(paddle.to_tensor(1.5)).numpy(),
            scipy.stats.norm(0, 2).logpdf(1.5), rtol=1e-4)
        np.testing.assert_allclose(d.entropy().numpy(),
                                   scipy.stats.norm(0, 2).entropy(),
                                   rtol=1e-5)
        np.testing.assert_allclose(
            d.cdf(paddle.to_tensor(0.7)).numpy(),
            scipy.stats.norm(0, 2).cdf(0.7), rtol=1e-4)
        s = d.sample((5000,))
        assert abs(float(s.numpy().mean())) < 0.15
        assert abs(float(s.numpy().std()) - 2.0) < 0.15

    def test_sampling_reproducible_under_seed(self):
        paddle.seed(7)
        a = D.Normal(0.0, 1.0).sample((4,)).numpy()
        paddle.seed(7)
        b = D.Normal(0.0, 1.0).sample((4,)).numpy()
        np.testing.assert_array_equal(a, b)

    @pytest.mark.parametrize("d,scipy_name,args", [
        (lambda: D.Uniform(1.0, 3.0), "uniform", dict(loc=1, scale=2)),
        (lambda: D.Laplace(0.5, 2.0), "laplace", dict(loc=0.5, scale=2)),
        (lambda: D.Gumbel(0.5, 2.0), "gumbel_r", dict(loc=0.5, scale=2)),
        (lambda: D.Cauchy(0.5, 2.0), "cauchy", dict(loc=0.5, scale=2)),
        (lambda: D.Exponential(1.5), "expon", dict(scale=1 / 1.5)),
    ])
    def test_logprob_vs_scipy(self, d, scipy_name, args):
        import scipy.stats
        ref = getattr(scipy.stats, scipy_name)(**args)
        v = 1.7
        np.testing.assert_allclose(
            d().log_prob(paddle.to_tensor(v)).numpy(), ref.logpdf(v),
            rtol=1e-4, atol=1e-5)

    def test_gamma_beta_dirichlet(self):
        import scipy.stats
        g = D.Gamma(2.0, 3.0)
        np.testing.assert_allclose(
            g.log_prob(paddle.to_tensor(0.7)).numpy(),
            scipy.stats.gamma(2, scale=1 / 3).logpdf(0.7), rtol=1e-4)
        b = D.Beta(2.0, 3.0)
        np.testing.assert_allclose(
            b.log_prob(paddle.to_tensor(0.3)).numpy(),
            scipy.stats.beta(2, 3).logpdf(0.3), rtol=1e-4)
        np.testing.assert_allclose(b.entropy().numpy(),
                                   scipy.stats.beta(2, 3).entropy(),
                                   rtol=1e-4)
        alpha = np.array([1.0, 2.0, 3.0], np.float32)
        dd = D.Dirichlet(alpha)
        x = np.array([0.2, 0.3, 0.5], np.float32)
        np.testing.assert_allclose(
            dd.log_prob(paddle.to_tensor(x)).numpy(),
            scipy.stats.dirichlet(alpha).logpdf(x), rtol=1e-4)

    def test_categorical_multinomial(self):
        probs = np.array([0.2, 0.3, 0.5], np.float32)
        c = D.Categorical(probs=probs)
        np.testing.assert_allclose(
            c.log_prob(paddle.to_tensor(2)).numpy(), np.log(0.5), rtol=1e-4)
        s = c.sample((2000,)).numpy()
        freq = np.bincount(s.astype(int), minlength=3) / 2000
        np.testing.assert_allclose(freq, probs, atol=0.05)
        m = D.Multinomial(10, probs)
        v = np.array([2.0, 3.0, 5.0], np.float32)
        import scipy.stats
        np.testing.assert_allclose(
            m.log_prob(paddle.to_tensor(v)).numpy(),
            scipy.stats.multinomial(10, probs).logpmf(v), rtol=1e-3)

    def test_discrete(self):
        import scipy.stats
        be = D.Bernoulli(probs=0.3)
        np.testing.assert_allclose(
            be.log_prob(paddle.to_tensor(1.0)).numpy(), np.log(0.3),
            rtol=1e-4)
        p = D.Poisson(2.5)
        np.testing.assert_allclose(
            p.log_prob(paddle.to_tensor(3.0)).numpy(),
            scipy.stats.poisson(2.5).logpmf(3), rtol=1e-4)
        geo = D.Geometric(0.3)
        np.testing.assert_allclose(
            geo.log_prob(paddle.to_tensor(2.0)).numpy(),
            scipy.stats.geom(0.3, loc=-1).logpmf(2), rtol=1e-4)
        bi = D.Binomial(np.float32(8), np.float32(0.4))
        np.testing.assert_allclose(
            bi.log_prob(paddle.to_tensor(3.0)).numpy(),
            scipy.stats.binom(8, 0.4).logpmf(3), rtol=1e-4)

    def test_mvn(self):
        import scipy.stats
        cov = spd(3).astype(np.float64)
        loc = np.zeros(3, np.float32)
        mvn = D.MultivariateNormal(loc, covariance_matrix=cov.astype(
            np.float32))
        v = rnd(3)
        np.testing.assert_allclose(
            mvn.log_prob(paddle.to_tensor(v)).numpy(),
            scipy.stats.multivariate_normal(loc, cov).logpdf(v), rtol=1e-3)
        s = mvn.sample((4000,)).numpy()
        np.testing.assert_allclose(np.cov(s.T), cov, atol=0.6)

    def test_kl(self):
        p, q = D.Normal(0.0, 1.0), D.Normal(1.0, 2.0)
        expect = np.log(2) + (1 + 1) / (2 * 4) - 0.5
        np.testing.assert_allclose(D.kl_divergence(p, q).numpy(), expect,
                                   rtol=1e-5)
        # KL >= 0 and 0 for identical for several families
        for mk in (lambda: D.Beta(2.0, 3.0), lambda: D.Gamma(2.0, 3.0),
                   lambda: D.Categorical(probs=np.array([0.2, 0.8],
                                                        np.float32))):
            np.testing.assert_allclose(
                D.kl_divergence(mk(), mk()).numpy(), 0.0, atol=1e-6)
        with pytest.raises(NotImplementedError):
            D.kl_divergence(D.LogNormal(0.0, 1.0), D.Normal(0.0, 1.0))

    def test_transformed(self):
        base = D.Normal(0.0, 1.0)
        t = D.TransformedDistribution(base, [D.ExpTransform()])
        ref = D.LogNormal(0.0, 1.0)
        v = 0.8
        np.testing.assert_allclose(
            t.log_prob(paddle.to_tensor(v)).numpy(),
            ref.log_prob(paddle.to_tensor(v)).numpy(), rtol=1e-4)

    def test_rsample_differentiable(self):
        # rsample through an affine-of-normal must carry pathwise grads
        # when parameters are tensors traced in a jitted fn
        import jax
        import jax.numpy as jnp

        def f(mu):
            from paddle_tpu import framework
            with framework.rng_context(jax.random.PRNGKey(0)):
                d = D.Normal(mu, jnp.float32(1.0))
                return d.rsample((16,))._value.mean()

        g = jax.grad(f)(jnp.float32(0.5))
        np.testing.assert_allclose(np.asarray(g), 1.0, rtol=1e-4)


class TestFFT:
    def test_fft_roundtrip_and_ref(self):
        x = rnd(8)
        y = fft.fft(paddle.to_tensor(x)).numpy()
        np.testing.assert_allclose(y, np.fft.fft(x), rtol=1e-3, atol=1e-4)
        back = fft.ifft(paddle.to_tensor(y)).numpy()
        np.testing.assert_allclose(back.real, x, atol=1e-5)

    def test_rfft_family(self):
        x = rnd(16)
        np.testing.assert_allclose(fft.rfft(paddle.to_tensor(x)).numpy(),
                                   np.fft.rfft(x), rtol=1e-3, atol=1e-4)
        y = np.fft.rfft(x)
        np.testing.assert_allclose(
            fft.irfft(paddle.to_tensor(y)).numpy(), x, atol=1e-5)
        np.testing.assert_allclose(
            fft.hfft(paddle.to_tensor(y.astype(np.complex64))).numpy(),
            np.fft.hfft(y), rtol=1e-3, atol=1e-3)

    def test_2d_n_and_shift(self):
        x = rnd(4, 6)
        np.testing.assert_allclose(fft.fft2(paddle.to_tensor(x)).numpy(),
                                   np.fft.fft2(x), rtol=1e-3, atol=1e-4)
        np.testing.assert_allclose(fft.fftn(paddle.to_tensor(x)).numpy(),
                                   np.fft.fftn(x), rtol=1e-3, atol=1e-4)
        np.testing.assert_allclose(
            fft.fftshift(paddle.to_tensor(x)).numpy(), np.fft.fftshift(x))
        np.testing.assert_allclose(fft.fftfreq(8, 0.5).numpy(),
                                   np.fft.fftfreq(8, 0.5).astype(
                                       np.float32))

    def test_norm_modes(self):
        x = rnd(8)
        np.testing.assert_allclose(
            fft.fft(paddle.to_tensor(x), norm="ortho").numpy(),
            np.fft.fft(x, norm="ortho"), rtol=1e-3, atol=1e-4)


class TestSignal:
    def test_frame_overlap_add_roundtrip(self):
        x = rnd(32)
        fr = signal.frame(paddle.to_tensor(x), 8, 8)
        assert fr.shape == [8, 4]
        back = signal.overlap_add(fr, 8).numpy()
        np.testing.assert_allclose(back, x, atol=1e-6)

    def test_stft_matches_scipy(self):
        import scipy.signal as ss
        x = rnd(64).astype(np.float64)
        win = np.hanning(16).astype(np.float32)
        ours = signal.stft(paddle.to_tensor(x.astype(np.float32)), 16,
                           hop_length=8, window=paddle.to_tensor(win),
                           center=False).numpy()
        _, _, ref = ss.stft(x, window=win.astype(np.float64), nperseg=16,
                            noverlap=8, boundary=None, padded=False)
        # scipy normalizes by win.sum(); undo
        ref = ref * win.sum()
        np.testing.assert_allclose(ours, ref, rtol=1e-2, atol=1e-3)

    def test_stft_istft_roundtrip(self):
        x = rnd(128)
        win = np.hanning(32).astype(np.float32)
        spec = signal.stft(paddle.to_tensor(x), 32, hop_length=8,
                           window=paddle.to_tensor(win))
        back = signal.istft(spec, 32, hop_length=8,
                            window=paddle.to_tensor(win),
                            length=128).numpy()
        np.testing.assert_allclose(back, x, atol=1e-4)


def test_continuous_bernoulli_vs_torch():
    import torch
    import paddle_tpu.distribution as D
    for pv in (0.2, 0.5, 0.7):
        ours = D.ContinuousBernoulli(np.array([pv], np.float32))
        t = torch.distributions.ContinuousBernoulli(torch.tensor([pv]))
        for x in (0.1, 0.5, 0.9):
            np.testing.assert_allclose(
                ours.log_prob(np.array([x], np.float32)).numpy(),
                t.log_prob(torch.tensor([x])).numpy(), atol=1e-4)
        np.testing.assert_allclose(ours.mean.numpy(), t.mean.numpy(),
                                   atol=1e-4)
        np.testing.assert_allclose(ours.variance.numpy(),
                                   t.variance.numpy(), atol=1e-4)
    paddle.seed(0)
    s = D.ContinuousBernoulli(np.array([0.3], np.float32)).sample((4000,))
    assert abs(float(s.numpy().mean()) - 0.4302) < 0.02


def test_independent_vs_torch():
    import torch
    import paddle_tpu.distribution as D
    base = D.Normal(np.zeros((3, 4), np.float32),
                    np.ones((3, 4), np.float32))
    ind = D.Independent(base, 1)
    assert ind.batch_shape == (3,) and ind.event_shape == (4,)
    x = np.random.RandomState(0).randn(3, 4).astype(np.float32)
    want = torch.distributions.Independent(
        torch.distributions.Normal(torch.zeros(3, 4), torch.ones(3, 4)),
        1).log_prob(torch.tensor(x)).numpy()
    np.testing.assert_allclose(ind.log_prob(x).numpy(), want, atol=1e-5)
    with pytest.raises(ValueError):
        D.Independent(base, 3)


class TestTransformsRound2:
    """Transform long tail (reference: python/paddle/distribution/
    transform.py) — tanh & stick-breaking checked against torch."""

    def test_tanh_and_stickbreaking_vs_torch(self):
        import torch
        import paddle_tpu.distribution as D
        x = np.random.RandomState(0).randn(5).astype(np.float32)
        t = D.TanhTransform()
        tt = torch.distributions.transforms.TanhTransform()
        np.testing.assert_allclose(t.forward(x).numpy(),
                                   tt(torch.tensor(x)).numpy(), atol=1e-6)
        np.testing.assert_allclose(
            t.forward_log_det_jacobian(x).numpy(),
            tt.log_abs_det_jacobian(torch.tensor(x),
                                    tt(torch.tensor(x))).numpy(),
            atol=1e-5)
        s = D.StickBreakingTransform()
        ts = torch.distributions.transforms.StickBreakingTransform()
        y = s.forward(x).numpy()
        np.testing.assert_allclose(y, ts(torch.tensor(x)).numpy(),
                                   atol=1e-5)
        np.testing.assert_allclose(s.inverse(y).numpy(), x, atol=1e-3)
        np.testing.assert_allclose(
            s.forward_log_det_jacobian(x).numpy(),
            ts.log_abs_det_jacobian(torch.tensor(x),
                                    torch.tensor(y)).numpy(), atol=1e-4)

    def test_chain_stack_reshape_power_independent(self):
        import paddle_tpu.distribution as D
        x = np.random.RandomState(1).randn(5).astype(np.float32)
        c = D.ChainTransform([D.AffineTransform(1.0, 2.0),
                              D.ExpTransform()])
        np.testing.assert_allclose(c.forward(x).numpy(),
                                   np.exp(1 + 2 * x), rtol=1e-5)
        np.testing.assert_allclose(c.inverse(c.forward(x).numpy()).numpy(),
                                   x, atol=1e-4)
        p = D.PowerTransform(2.0)
        xx = np.abs(x) + 0.1
        np.testing.assert_allclose(p.inverse(p.forward(xx).numpy()).numpy(),
                                   xx, atol=1e-5)
        r = D.ReshapeTransform((6,), (2, 3))
        assert r.forward(np.arange(6, dtype=np.float32)).shape == [2, 3]
        with pytest.raises(ValueError):
            D.ReshapeTransform((6,), (2, 2))
        st = D.StackTransform([D.ExpTransform(),
                               D.AffineTransform(0.0, 2.0)])
        out = st.forward(np.stack([x, x])).numpy()
        np.testing.assert_allclose(out[0], np.exp(x), rtol=1e-5)
        np.testing.assert_allclose(out[1], 2 * x, rtol=1e-5)
        i = D.IndependentTransform(D.ExpTransform(), 1)
        assert i.forward_log_det_jacobian(
            np.ones((3, 4), np.float32)).shape == [3]
