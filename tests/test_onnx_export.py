"""ONNX export tests: proto wire-codec round trip + numeric parity of
exported graphs against the live model, via the in-tree numpy
evaluator (reference parity: paddle.onnx.export / paddle2onnx — the
reference validates its converter with numpy-checked op tests)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
import paddle_tpu.onnx as ponnx
from paddle_tpu.onnx import proto


class TestProtoCodec:
    def test_roundtrip_model(self):
        model = {
            "ir_version": 8, "producer_name": "paddle_tpu",
            "graph": {
                "name": "g",
                "node": [{"input": ["x", "w"], "output": ["y"],
                          "op_type": "MatMul", "name": "n1"}],
                "initializer": [{"dims": [2, 3], "data_type": 1,
                                 "raw_data": b"\0" * 24, "name": "w"}],
                "input": [{"name": "x", "type": {"tensor_type": {
                    "elem_type": 1, "shape": {"dim": [
                        {"dim_value": 4}, {"dim_value": 2}]}}}}],
                "output": [{"name": "y", "type": {"tensor_type": {
                    "elem_type": 1, "shape": {"dim": [
                        {"dim_value": 4}, {"dim_value": 3}]}}}}],
            },
            "opset_import": [{"domain": "", "version": 13}],
        }
        blob = proto.encode("Model", model)
        back = proto.decode("Model", blob)
        assert back["ir_version"] == 8
        assert back["graph"]["node"][0]["op_type"] == "MatMul"
        assert back["graph"]["initializer"][0]["dims"] == [2, 3]
        assert back["graph"]["input"][0]["type"]["tensor_type"][
            "shape"]["dim"][0]["dim_value"] == 4

    def test_negative_int64_varint(self):
        blob = proto.encode("Attribute", {"name": "axis", "i": -1,
                                          "type": proto.ATTR_INT})
        assert proto.decode("Attribute", blob)["i"] == -1

    def test_packed_repeated_int64(self):
        blob = proto.encode("Tensor", {"dims": [5, 7, 1024]})
        assert proto.decode("Tensor", blob)["dims"] == [5, 7, 1024]

    def test_attr_float_and_string(self):
        blob = proto.encode("Attribute", {
            "name": "eq", "s": b"ab,bc->ac", "type": proto.ATTR_STRING})
        d = proto.decode("Attribute", blob)
        assert d["s"] == b"ab,bc->ac"


def _roundtrip(layer, *inputs, atol=1e-4, rtol=1e-3):
    import tempfile
    import os
    with tempfile.TemporaryDirectory() as td:
        p = ponnx.export(layer, os.path.join(td, "m"),
                         input_spec=[paddle.to_tensor(x)
                                     for x in inputs])
        m = ponnx.runtime.load(p)
        out = ponnx.runtime.run(
            m, {f"input_{i}": x for i, x in enumerate(inputs)})
    got = out["output_0"]
    layer.eval()
    ref = layer(*[paddle.to_tensor(x) for x in inputs])
    ref = (ref[0] if isinstance(ref, (tuple, list)) else ref).numpy()
    np.testing.assert_allclose(got, ref, rtol=rtol, atol=atol)
    return m


class TestExportNumericParity:
    def test_mlp_gelu_layernorm_softmax(self):
        paddle.seed(0)
        net = nn.Sequential(nn.Linear(8, 32), nn.GELU(),
                            nn.LayerNorm(32), nn.Linear(32, 4),
                            nn.Softmax(axis=-1))
        x = np.random.RandomState(0).rand(3, 8).astype("float32")
        m = _roundtrip(net, x)
        ops = {n["op_type"] for n in m["graph"]["node"]}
        assert "Einsum" in ops and "Erf" in ops

    def test_transformer_encoder_layer(self):
        paddle.seed(1)
        tl = nn.TransformerEncoderLayer(d_model=32, nhead=4,
                                        dim_feedforward=64, dropout=0.0)
        x = np.random.RandomState(1).rand(2, 6, 32).astype("float32")
        _roundtrip(tl, x)

    def test_conv_bn_pool(self):
        paddle.seed(2)
        cnn = nn.Sequential(nn.Conv2D(3, 8, 3, stride=2, padding=1),
                            nn.BatchNorm2D(8), nn.ReLU(),
                            nn.MaxPool2D(2, 2))
        cnn.eval()
        x = np.random.RandomState(2).rand(2, 3, 16, 16).astype("float32")
        m = _roundtrip(cnn, x)
        ops = {n["op_type"] for n in m["graph"]["node"]}
        assert "Conv" in ops and "MaxPool" in ops

    def test_llama_tiny_logits(self):
        from paddle_tpu.models.llama import (LlamaForCausalLM,
                                             llama_tiny_config)
        paddle.seed(0)
        model = LlamaForCausalLM(llama_tiny_config(tensor_parallel=False))
        model.eval()
        ids = np.random.RandomState(0).randint(
            0, 512, (1, 12)).astype(np.int32)

        class LogitsOnly(nn.Layer):
            def __init__(self, m):
                super().__init__()
                self.m = m

            def forward(self, ids):
                out = self.m(ids)
                return out[0] if isinstance(out, tuple) else out

        _roundtrip(LogitsOnly(model), ids, atol=1e-3)

    def test_multi_input(self):
        class TwoIn(nn.Layer):
            def forward(self, a, b):
                return (a * b).sum(axis=-1)

        a = np.random.RandomState(3).rand(2, 4).astype("float32")
        b = np.random.RandomState(4).rand(2, 4).astype("float32")
        _roundtrip(TwoIn(), a, b)

    def test_unmapped_primitive_raises_with_name(self):
        import jax.numpy as jnp
        from paddle_tpu.tensor import apply_op

        class Sorter(nn.Layer):
            def forward(self, x):
                return apply_op(lambda v: jnp.sort(v, axis=-1), x)

        x = np.random.RandomState(5).rand(2, 6).astype("float32")
        with pytest.raises(NotImplementedError, match="sort"):
            ponnx.export(Sorter(), "/tmp/_should_not_exist",
                         input_spec=[paddle.to_tensor(x)])


def test_avg_pool_roundtrip():
    """reduce_window_sum -> AveragePool(count_include_pad=1) * k."""
    paddle.seed(4)
    net = nn.Sequential(nn.Conv2D(3, 4, 3, padding=1),
                        nn.AvgPool2D(2, 2), nn.ReLU())
    net.eval()
    x = np.random.RandomState(4).rand(1, 3, 8, 8).astype("float32")
    m = _roundtrip(net, x)
    ops = {n["op_type"] for n in m["graph"]["node"]}
    assert "AveragePool" in ops


def test_bf16_export_roundtrip():
    """bf16 (the TPU-first dtype) exports with BFLOAT16 raw tensors and
    evaluates in bf16 end-to-end."""
    import ml_dtypes
    paddle.set_default_dtype("bfloat16")
    try:
        paddle.seed(0)
        net = nn.Sequential(nn.Linear(8, 16), nn.GELU(),
                            nn.Linear(16, 4))
    finally:
        paddle.set_default_dtype("float32")
    x = np.random.RandomState(0).rand(2, 8).astype(ml_dtypes.bfloat16)
    import tempfile
    import os
    with tempfile.TemporaryDirectory() as td:
        p = ponnx.export(net, os.path.join(td, "m"),
                         input_spec=[paddle.to_tensor(x)])
        m = ponnx.runtime.load(p)
        out = ponnx.runtime.run(m, {"input_0": x})["output_0"]
    assert out.dtype == ml_dtypes.bfloat16
    net.eval()
    ref = net(paddle.to_tensor(x)).numpy()
    np.testing.assert_allclose(out.astype(np.float32),
                               ref.astype(np.float32), atol=0.1)
