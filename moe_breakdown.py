"""MoE gate/dispatch/expert time attribution + dispatch-impl A/B on chip.

VERDICT r3 #4 / #2: EP was the one parallelism axis with zero perf
evidence, and the dispatch hot path had never been timed. This tool
times, at the ERNIE-MoE base shapes (d=768, ffn=3072, E=8, k=2,
b16 x s1024 -> 16384 tokens, bf16):

  stages (each its own jitted function, block_until_ready timing):
    gate      logits matmul + softmax/top-k + aux loss
    route     dual index-map build (argsort / searchsorted)
    dispatch  token -> expert-major buffer        (per impl)
    experts   the two stacked-expert einsums
    combine   expert-major -> token, gate-weighted (per impl)

  end-to-end (value_and_grad of the full block, the training shape):
    scatter         r3 path: buf.at[slot].set dispatch
    gather_jnp      r4 path: all-gather dual-map dispatch (XLA gather)
    gather_pallas   r4 path with the Pallas scalar-prefetch row kernel

Merged into WORKLOADS_r05.json under "moe_breakdown"; one JSON line per
measurement so a mid-run wedge keeps earlier points.
"""
from __future__ import annotations

import functools
import json
import math
import os
import time

import numpy as np

from _bench_common import configure_jax, merge_artifact

OUT = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                   "WORKLOADS_r05.json")


def main():
    jax = configure_jax()
    import jax.numpy as jnp
    on_tpu = jax.devices()[0].platform != "cpu"
    tiny = not on_tpu

    from paddle_tpu.ops.pallas import moe_dispatch as md

    # ERNIE-MoE base block shapes (bench_workloads.py ernie_moe config)
    if tiny:
        t, d, h, e, k = 256, 128, 256, 4, 2
    else:
        t, d, h, e, k = 16384, 768, 3072, 8, 2
    cap = int(math.ceil(1.25 * t * k / e))
    dt = jnp.bfloat16

    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.randn(t, d), dtype=dt)
    wg = jnp.asarray(rs.randn(d, e) * 0.02, dtype=dt)
    w1 = jnp.asarray(rs.randn(e, d, h) * 0.02, dtype=dt)
    w2 = jnp.asarray(rs.randn(e, h, d) * 0.02, dtype=dt)

    def gate_fn(x, wg):
        lg = (x @ wg).astype(jnp.float32)
        probs = jax.nn.softmax(lg, axis=-1)
        topv, topi = jax.lax.top_k(probs, k)
        top1 = jnp.argmax(lg, axis=-1)
        me = jnp.mean(probs, axis=0)
        ce = jnp.mean(jax.nn.one_hot(top1, e, dtype=lg.dtype), axis=0)
        aux = jnp.sum(me * ce) * e
        topv = topv / (jnp.sum(topv, axis=-1, keepdims=True) + 1e-9)
        return lg, topi, topv, aux

    def route_fn(topi):
        # the SAME routing math the shipped layer runs (single source of
        # truth — a drift here would silently A/B a different algorithm)
        return md.build_index_maps(topi, e, cap)

    def experts_fn(buf, w1, w2):
        hdn = jax.nn.gelu(jnp.einsum("ecd,edh->ech",
                                     buf.reshape(e, cap, d), w1))
        return jnp.einsum("ech,ehd->ecd", hdn, w2).reshape(e * cap, d)

    def scatter_dispatch(x, slot):
        tok = jnp.repeat(jnp.arange(t), k)
        buf = jnp.zeros((e * cap, d), x.dtype)
        return buf.at[slot].set(x[tok], mode="drop")

    def scatter_combine(flat, gates, slot):
        out_tk = flat.at[slot].get(mode="fill", fill_value=0)
        out_tk = out_tk * gates.reshape(-1, 1).astype(flat.dtype)
        return out_tk.reshape(t, k, d).sum(axis=1)

    chip = (os.environ.get("PALLAS_AXON_TPU_GEN", "v5e") if on_tpu
            else "cpu")
    result = {"tokens": t, "d_model": d, "d_hidden": h, "experts": e,
              "top_k": k, "capacity": cap, "dtype": "bfloat16",
              "chip": chip}

    def _merge(result):
        merge_artifact(OUT, "moe_breakdown", result, chip)

    def timeit(fn, *args, iters=10 if not tiny else 2, warmup=1,
               primary_idx=0):
        # device-honest timing: iterations serialized in one lax.scan,
        # clock stopped on a fetched scalar (see _bench_common; the
        # tunnel's block_until_ready can return before completion)
        from _bench_common import scan_chain_bench
        return scan_chain_bench(fn, args, primary_idx=primary_idx,
                                iters=iters, warmup=warmup)

    # ---- stage attribution -------------------------------------------
    lg, topi, topv, aux = jax.jit(gate_fn)(x, wg)
    slot, inv, keep = jax.jit(route_fn)(topi)
    gates = jnp.where(keep.reshape(t, k), topv, 0.0)
    buf = jax.jit(md.moe_dispatch)(x, inv, slot)

    stages = {}
    stages["gate_ms"] = round(timeit(gate_fn, x, wg), 3)
    stages["route_ms"] = round(timeit(route_fn, topi), 3)
    stages["experts_ms"] = round(timeit(experts_fn, buf, w1, w2), 3)
    for impl, disp, comb in (
            ("scatter", scatter_dispatch, None),
            ("gather_jnp", None, None),
            ("gather_pallas", None, None),
            ("gather_pallas_mr", None, None)):
        if impl.startswith("gather"):
            os.environ["PT_MOE_GATHER"] = impl[len("gather_"):]
            if impl.startswith("gather_pallas") \
                    and not md._pallas_ok(d, dt):
                stages[f"dispatch_{impl}_ms"] = None
                continue
            stages[f"dispatch_{impl}_ms"] = round(
                timeit(lambda xx, ii, ss: md.moe_dispatch(xx, ii, ss),
                       x, inv, slot), 3)
            stages[f"combine_{impl}_ms"] = round(
                timeit(lambda ff, gg, ii, ss:
                       md.moe_combine(ff, gg, ii, ss),
                       buf, gates, inv, slot), 3)
        else:
            stages[f"dispatch_{impl}_ms"] = round(
                timeit(disp, x, slot), 3)
            stages[f"combine_{impl}_ms"] = round(
                timeit(scatter_combine, buf, gates, slot), 3)
    os.environ["PT_MOE_GATHER"] = "jnp"
    result["stages"] = stages
    _merge(result)
    print("MOE_STAGES " + json.dumps(stages), flush=True)

    # ---- end-to-end fwd+bwd A/B --------------------------------------
    def block_loss(params, x, mode):
        wg, w1, w2 = params
        lg, topi, topv, aux = gate_fn(x, wg)
        slot, inv, keep = route_fn(jax.lax.stop_gradient(topi))
        gates = jnp.where(keep.reshape(t, k), topv, 0.0)
        if mode == "scatter":
            buf = scatter_dispatch(x, slot)
            eo = experts_fn(buf, w1, w2)
            out = scatter_combine(eo, gates, slot)
        else:
            buf = md.moe_dispatch(x, inv, slot)
            eo = experts_fn(buf, w1, w2)
            out = md.moe_combine(eo, gates, inv, slot)
        return (out.astype(jnp.float32) ** 2).mean() + 0.01 * aux

    e2e = {}
    params = (wg, w1, w2)
    for mode, impl in (("scatter", "jnp"), ("gather", "jnp"),
                       ("gather", "pallas"), ("gather", "pallas_mr")):
        name = mode if mode == "scatter" else f"gather_{impl}"
        os.environ["PT_MOE_GATHER"] = impl
        if impl.startswith("pallas") and not md._pallas_ok(d, dt):
            e2e[name] = None
            continue
        g = functools.partial(jax.value_and_grad(block_loss), mode=mode)
        try:
            e2e[name + "_fwdbwd_ms"] = round(
                timeit(g, params, x, primary_idx=1), 3)
        except Exception as ex:
            e2e[name + "_error"] = f"{type(ex).__name__}: {ex}"[:200]
        result["e2e"] = e2e
        _merge(result)
    os.environ["PT_MOE_GATHER"] = "jnp"
    print("MOE_E2E " + json.dumps(e2e), flush=True)

    # expert-FLOP utilization of the best end-to-end mode
    flops = 2 * 2 * e * cap * d * h * 3   # two einsums, fwd+2x bwd
    best = min((v for kk, v in e2e.items()
                if kk.endswith("_ms") and v), default=None)
    if best:
        result["expert_flops_per_step"] = flops
        result["best_e2e_ms"] = best
    _merge(result)
    print("MOE_BREAKDOWN_DONE " + json.dumps(
        {"best_e2e_ms": best}), flush=True)


if __name__ == "__main__":
    main()
