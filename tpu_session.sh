#!/bin/bash
# One-command TPU measurement session — run the moment the axon tunnel
# is healthy (probe first; a wedged tunnel hangs jax.devices()):
#   timeout 90 python -c "import jax; print(jax.devices())" || exit 1
#   bash tpu_session.sh
# Priority order (each stage survives a later wedge; bench and the
# workloads runner write partial artifacts after every completed stage):
#   1. headline bench                  -> BENCH_TPU_MEASURED_r03.json
#      (stage order inside: small -> ~1B big -> decode; long deadline so
#       the big-config compile isn't deadline-killed mid-flight, and a
#       persistent compile cache so a repeat run skips the compiles)
#   2. non-Llama BASELINE workloads    -> WORKLOADS_r03.json
#   3. profile re-capture (attribution after kernel tuning)
#   4. on-chip kernel validation tests
# (the flash block sweep already produced FLASH_BLOCKS_r03.json; rerun
#  sweep_flash_blocks.py manually if the kernel set changes)
set -x
cd "$(dirname "$0")"

BENCH_TPU_DEADLINE_S=1500 BENCH_TOTAL_BUDGET_S=2100 \
    timeout -s INT -k 30 2160 python bench.py \
    | tee /tmp/bench_last.json
# keep the self-reported artifact regardless of the driver's own run.
# Parse the TOP-LEVEL chip field — a cpu-fallback artifact embeds the
# previous v5e numbers under last_measured_tpu, so a substring grep
# would overwrite the genuine measurement with the fallback.
if python -c '
import json, sys
d = json.load(open("/tmp/bench_last.json"))
sys.exit(0 if d.get("chip") == "v5e" else 1)' 2>/dev/null; then
    cp /tmp/bench_last.json BENCH_TPU_MEASURED_r03.json
fi

bash workloads_session.sh

timeout -s INT -k 30 580 python profile_tpu.py 2>&1 | tail -3

PT_TPU_TESTS=1 timeout -s INT -k 30 560 python -m pytest tests/test_pallas_tpu.py -q \
    2>&1 | tail -5
