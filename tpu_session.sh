#!/bin/bash
# One-command TPU measurement session — run the moment the axon tunnel
# is healthy (probe first; a wedged tunnel hangs jax.devices()):
#   timeout 90 python -c "import jax; print(jax.devices())" || exit 1
#   bash tpu_session.sh
# Priority order (each stage survives a later wedge; bench and the
# workloads runner write partial artifacts after every completed stage):
#   1. headline bench                  -> BENCH_TPU_MEASURED_r05.json
#      (stage order inside: tiny liveness stamp -> small -> ~1B big
#       [run_steps scan dispatch] -> selective-remat probe -> decode;
#       persistent compile cache so a repeat run skips the compiles)
#   2. non-Llama BASELINE workloads    -> WORKLOADS_r05.json
#   3. decode serving sweep            -> merged into BENCH_TPU_MEASURED_r05
#   4. MoE gate/dispatch/expert breakdown + Pallas-vs-jnp dispatch A/B
#                                      -> merged into WORKLOADS_r05.json
#   5. profile re-capture (attribution after run_steps lever)
#   6. on-chip kernel validation tests
set -x
cd "$(dirname "$0")"
# a concurrently-polling watcher would contend for the exclusive axon
# chip claim mid-session (r3 post-mortem: the leftover r3 watcher is
# the prime suspect for the driver-window backend-init hangs)
touch .watch_stop

BENCH_TPU_DEADLINE_S=1500 BENCH_TOTAL_BUDGET_S=2100 \
    timeout -s INT -k 30 2160 python bench.py \
    | tee /tmp/bench_last.json
# keep the self-reported artifact regardless of the driver's own run.
# Parse the TOP-LEVEL chip field — a cpu-fallback artifact embeds the
# previous v5e numbers under last_measured_tpu, so a substring grep
# would overwrite the genuine measurement with the fallback.
python - <<'EOF'
import json, os
try:
    new = json.load(open("/tmp/bench_last.json"))
except Exception:
    raise SystemExit
if new.get("chip") != "v5e":
    raise SystemExit
out = "BENCH_TPU_MEASURED_r05.json"
# merge: a deadline-cut stage in the new run must not erase a number
# the previous session measured (e.g. decode_* / config_big keys) —
# but run-specific diagnostics must never be carried into a clean run
NEVER_CARRY = {"config_errors", "partial", "stage_s",
               "carried_from_previous"}
try:
    old = json.load(open(out)) if os.path.exists(out) else {}
except Exception:
    old = {}   # corrupt artifact must not block recording a good run
if old.get("chip") == "v5e":
    carried = []
    for k, v in old.items():
        if k not in NEVER_CARRY and new.get(k) is None:
            new[k] = v
            carried.append(k)
    if carried:
        new["carried_from_previous"] = sorted(carried)
    # headline follows bench.py's head = big or small over the MERGED
    # configs, so a carried config_big keeps its top-level value/mfu
    head = new.get("config_big") or new.get("config_small")
    if head:
        new["value"] = head["tokens_per_sec"]
        new["mfu"] = head["mfu"]
        new["vs_baseline"] = round(head["mfu"] / 0.45, 4)
        for k in ("model_params", "batch", "seq", "final_loss",
                  "step_ms"):
            if k in head:
                new[k] = head[k]
tmp = out + ".tmp"
json.dump(new, open(tmp, "w"), indent=1)
os.replace(tmp, out)   # atomic: a kill mid-write can't corrupt it
EOF

bash workloads_session.sh

# decode serving sweep (VERDICT r3 #7): batch x sampling x ragged table
timeout -s INT -k 30 900 python sweep_decode.py 2>&1 | tail -3

# MoE breakdown + dispatch A/B (VERDICT r3 #4): merged into WORKLOADS
timeout -s INT -k 30 700 python moe_breakdown.py 2>&1 | tail -3

timeout -s INT -k 30 580 python profile_tpu.py 2>&1 | tail -3

PT_TPU_TESTS=1 timeout -s INT -k 30 560 python -m pytest tests/test_pallas_tpu.py -q \
    2>&1 | tail -5

touch .session_done
