#!/bin/bash
# One-command TPU measurement session — run the moment the axon tunnel
# is healthy (probe first; a wedged tunnel hangs jax.devices()):
#   timeout 90 python -c "import jax; print(jax.devices())" || exit 1
#   bash tpu_session.sh
# Produces, in priority order (each stage survives a later wedge):
#   1. on-chip kernel validation (splash/ring/window/flash_block)
#   2. PROFILE_r03.json + profile_r03/ trace  (MFU attribution)
#   3. BENCH_TPU_MEASURED_r03.json            (self-reported headline)
set -x
cd "$(dirname "$0")"

PT_TPU_TESTS=1 timeout 560 python -m pytest tests/test_pallas_tpu.py -q \
    2>&1 | tail -5

timeout 580 python profile_tpu.py 2>&1 | tail -3

timeout 590 python bench.py | tee /tmp/bench_last.json
# keep the self-reported artifact regardless of the driver's own run
if grep -q '"chip": "v5e"' /tmp/bench_last.json 2>/dev/null; then
    cp /tmp/bench_last.json BENCH_TPU_MEASURED_r03.json
fi
