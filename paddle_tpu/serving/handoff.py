"""KV-block handoff wire format for the disaggregated serving fleet.

A prefill worker that finishes a prompt owns exactly the state a decode
worker needs to continue the stream: the slot's paged KV blocks (or the
dense cache row), the in-hand first token, the post-split rng key, the
remaining token budget and the request itself. This module defines that
payload as a **versioned, bytes-true wire format**:

- ``KVHandoff`` is the in-memory form: a JSON-safe ``meta`` dict plus a
  dict of numpy arrays (prompt, rng key, per-layer KV block data).
- ``encode_handoff()``/``decode_handoff()`` round-trip it through ONE
  uncompressed npz byte buffer (no pickle — same discipline as the
  PR 5 snapshot format); ``len(encode_handoff(h))`` is the real wire
  size.
- **Bytes-true**: arrays ship at their storage dtype. An int8 KV arena
  ships int8 codes + fp32 absmax scales and is NEVER dequantized in
  transit — the wire payload is ~3.6x smaller than the fp32 arena's
  (4d/(d+4) at head_dim d), which is the point of quantizing it.
- Only the blocks holding PROMPT positions ship (``ceil(L/bs)`` of the
  request's ``blocks_needed`` total): decode-position blocks are junk
  the decode worker writes before it ever reads, so they cost zero
  wire bytes.

The format is **layout-free**: arrays are logical (host-gathered), so a
payload extracted from a TP-sharded source adopts onto any target mesh
— the target engine re-commits through its backend's ``commit_arrays``
hook, the same path snapshot restore uses. For transports that ship
per-shard chunks instead (a real network fleet), ``reshard_kv_chunks``
re-chunks a sharded KV axis between source and target TP degrees one
output part at a time (the memory-efficient redistribution discipline
of arXiv:2112.01075: peak footprint is one part, never the whole
transfer).

Fault sites (``utils.faults``): ``fleet.serialize`` fires in
``encode_handoff()`` before any bytes are produced, so a retry re-extracts and
re-serializes the identical payload.
"""
from __future__ import annotations

import io
import json
from dataclasses import dataclass
from typing import Dict, List, Sequence

import numpy as np

from ..utils import faults

__all__ = ["FETCH_FORMAT", "HANDOFF_FORMAT", "HANDOFF_VERSION",
           "KVHandoff", "decode_handoff", "encode_handoff",
           "reshard_kv_chunks"]

HANDOFF_FORMAT = "pt-kv-handoff"
# prefix-fetch responses (serving/prefix_cache.py) ride the SAME v1
# serializer/CRC/validation machinery under their own format stamp, so
# a fetch payload mis-delivered to an adopt path is refused by kind,
# never silently armed as a stream
FETCH_FORMAT = "pt-kv-fetch"
HANDOFF_VERSION = 1
_KNOWN_FORMATS = frozenset({HANDOFF_FORMAT, FETCH_FORMAT})


@dataclass
class KVHandoff:
    """One slot's portable handoff payload.

    ``meta`` (JSON-safe): ``format``/``version``, ``kind``
    ("dense"|"paged"), the serialized request
    (``resilience.request_to_meta``), the armed-slot scalars (``tok0``,
    ``pos0``, ``rem0``, dense ``pad0``), the paged geometry
    (``n_blocks`` total to allocate, ``n_ship`` actually shipped,
    ``block_size``, ``kv_int8``), per-leaf block specs for
    compatibility validation, the first-token timestamp ``t_admit``
    (TTFT keeps measuring the prefill worker's first token), and the
    ``source`` worker name + TP degree.

    ``arrays``: ``prompt`` (int32), ``key`` ((2,) uint32 — the
    post-split state key, i.e. the NEXT decode step's split input),
    and ``kv_<i>`` per cache leaf — paged: ``(n_ship, bs, ...)`` block
    rows at storage dtype; dense: the ``(1, pos0, ...)`` populated row
    prefix.
    """
    meta: dict
    arrays: Dict[str, np.ndarray]

    @property
    def request_id(self) -> int:
        return self.meta["request"]["request_id"]

    @property
    def kind(self) -> str:
        return self.meta["kind"]

    def kv_bytes(self) -> int:
        """Bytes of KV payload on the wire (codes + scales at storage
        dtype) — the number the fp32-vs-int8 bench ratio compares."""
        return sum(int(v.nbytes) for k, v in self.arrays.items()
                   if k.startswith("kv_"))

    def payload_crc32(self) -> int:
        """CRC32 over every array's name, dtype, shape and raw bytes
        (name-sorted, so the digest is layout-order independent). The
        fleet stamps it into ``meta["crc32"]`` at ship time;
        ``DecodeWorker.adopt`` recomputes it BEFORE touching any
        allocator state — a tampered/corrupted payload is refused
        loudly, never scattered into an arena."""
        import zlib
        c = 0
        for name in sorted(self.arrays):
            a = np.ascontiguousarray(self.arrays[name])
            c = zlib.crc32(
                f"{name}|{a.dtype}|{a.shape}".encode(), c)
            c = zlib.crc32(a.tobytes(), c)
        return c & 0xFFFFFFFF

    def verify_crc(self):
        """Raise ValueError when ``meta["crc32"]`` (if present) does
        not match the arrays actually carried."""
        want = self.meta.get("crc32")
        if want is not None and int(want) != self.payload_crc32():
            raise ValueError(
                f"handoff payload CRC mismatch (rid {self.request_id})"
                " — refusing to adopt corrupted KV state")


def encode_handoff(handoff: KVHandoff) -> bytes:
    """Serialize to one uncompressed npz byte string (bytes-true:
    int8 stays int8 on the wire). The ``fleet.serialize`` fault site
    fires BEFORE any bytes exist, so a retry is side-effect free."""
    faults.fault_point("fleet.serialize")
    bio = io.BytesIO()
    payload = dict(handoff.arrays)
    payload["__meta__"] = np.array(json.dumps(
        {"format": HANDOFF_FORMAT, "version": HANDOFF_VERSION,
         **handoff.meta}))
    np.savez(bio, **payload)
    return bio.getvalue()


def decode_handoff(data: bytes) -> KVHandoff:
    """Inverse of :func:`encode_handoff`; refuses foreign or future-versioned
    payloads loudly instead of adopting garbage into an arena."""
    with np.load(io.BytesIO(data), allow_pickle=False) as z:
        meta = json.loads(str(z["__meta__"]))
        arrays = {k: z[k] for k in z.files if k != "__meta__"}
    if meta.get("format") not in _KNOWN_FORMATS:
        raise ValueError("payload is not a KV handoff")
    if meta.get("version") != HANDOFF_VERSION:
        raise ValueError(
            f"KV handoff version {meta.get('version')} unsupported "
            f"(this build reads {HANDOFF_VERSION})")
    return KVHandoff(meta=meta, arrays=arrays)


def reshard_kv_chunks(chunks: Sequence[np.ndarray], dst_parts: int,
                      axis: int = 1) -> List[np.ndarray]:
    """Re-chunk per-shard KV pieces from a source TP degree to a target
    degree along ``axis`` (the kv-head axis for this repo's sharding).

    Portable redistribution per arXiv:2112.01075: each output part is
    assembled from exactly the input slices that cover its index range,
    so peak memory is ONE output part — the full logical array is never
    materialized. ``concatenate(result) == concatenate(chunks)`` along
    ``axis`` by construction (identity-pinned in tests)."""
    if dst_parts < 1:
        raise ValueError(f"dst_parts={dst_parts}; must be >= 1")
    sizes = [c.shape[axis] for c in chunks]
    total = sum(sizes)
    if total % dst_parts != 0:
        raise ValueError(
            f"axis extent {total} does not divide into {dst_parts} "
            "target shards")
    per = total // dst_parts
    starts = np.cumsum([0] + sizes)
    out: List[np.ndarray] = []
    for j in range(dst_parts):
        lo, hi = j * per, (j + 1) * per
        pieces = []
        for i, c in enumerate(chunks):
            s, e = int(starts[i]), int(starts[i + 1])
            if e <= lo or s >= hi:
                continue
            sl = [slice(None)] * c.ndim
            sl[axis] = slice(max(lo - s, 0), min(hi - s, e - s))
            pieces.append(c[tuple(sl)])
        out.append(pieces[0] if len(pieces) == 1
                   else np.concatenate(pieces, axis=axis))
    return out
