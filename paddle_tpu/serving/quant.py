"""Weight-only quantized serving: int8/int4 decode weights dequantized
in-gemm inside the ONE compiled decode block.

Plain decode re-reads every parameter byte per step — at serving batch
sizes the step is HBM-bandwidth-bound, so fp32 weights cap decode
tokens/s at (weight bytes)/(HBM GB/s) regardless of MXU headroom. This
module routes the serving weights through the ``nn/quant`` weight-only
machinery: q/k/v/o, MLP gate/up/down and lm_head are quantized ONCE at
engine build (int8 per-channel or per-group absmax; int4 nibble-packed
on the in dim), the backends hold codes + fp32 scales instead of fp32
weights, and the pure decode step dequantizes each weight in-graph
right where it is consumed — XLA fuses the scale multiply into the gemm
prologue, so HBM sees ~4x (int8) / ~8x (int4) fewer weight bytes per
decode step with no separate dequant pass.

Composition (the same contract as paged/spec/tp):

- everything is default-off: pass ``quant=QuantConfig(...)`` (or
  ``quant="int8"``/``"int4"``) to the ``ContinuousBatchingEngine``
  factory, or set ``PT_SERVING_QUANT_WEIGHTS=int8|int4``
  (``PT_SERVING_QUANT_GROUP`` for per-group scales);
- an explicitly passed backend is NEVER rerouted by the env knob, and
  ``quant=`` alongside an explicit backend is refused loudly (the
  quantization is baked into the backend at construction);
- composes with ``paged=`` (int8 KV arena + int8 weights = the
  bandwidth-true stack), ``spec=`` (the verify program dequantizes the
  same codes), and ``tp=`` in mode="exact" (per-shard scales ride the
  SAME PartitionSpecs as their weights: a column-sharded weight's
  per-channel scales split on the out dim). mode="psum" + quant is
  refused (row-sharded int4 packing and group boundaries do not split
  cleanly — a follow-up, not a silent fallback);
- error accounting mirrors the KV arena's EQuARX contract: the
  worst-case elementwise |dequant - fp32| over every quantized weight
  is computed at build time and runtime-queryable via
  ``engine.weight_error_bound()`` /
  ``engine.quant_error_bound()["weights"]``, surfaced as the
  ``pt_serving_weight_error_bound`` gauge next to the KV bound.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, Optional

from ..nn.quant import dequantize_array, quant_step_bound, quantize_array
from ..observability import metrics as _om
from ..utils.flags import env_int, env_str

__all__ = ["QuantConfig", "resolve_quant_config", "quantize_backend_params",
           "wrap_pure_with_dequant"]

# quant-bound gauges (no-ops until metrics.enable()/PT_METRICS;
# registered at import so the catalog-complete-at-zero contract holds —
# serving/__init__ imports this module eagerly)
_M_KV_BOUND = _om.gauge(
    "pt_serving_kv_error_bound",
    "runtime worst-case |dequant - fp32| over the engine's int8 KV "
    "arena (0 in fp32 mode)")
_M_W_BOUND = _om.gauge(
    "pt_serving_weight_error_bound",
    "build-time worst-case |dequant - fp32| over the engine's "
    "weight-only-quantized decode weights (0 in fp32 mode)")

_BITS = {"int8": 8, "int4": 4}

# the serving weight set: attention + MLP projections and the lm_head —
# the decode step's bandwidth, per the reference quantized_linear scope.
# Embeddings (a gather, not a gemm) and norm/bias vectors stay fp32.
_WEIGHT_PATTERNS = ("q_proj", "k_proj", "v_proj", "o_proj", "gate_proj",
                    "up_proj", "down_proj", "lm_head")


@dataclasses.dataclass(frozen=True)
class QuantConfig:
    """How to quantize the serving weights. ``weights``: "int8" |
    "int4". ``group_size``: -1 = per-output-channel absmax scales;
    > 0 = one scale per ``group_size`` input rows per channel (must
    divide every quantized weight's in_features — refused loudly
    otherwise, matching the nn/quant contract)."""
    weights: str = "int8"
    group_size: int = -1

    def __post_init__(self):
        if self.weights not in _BITS:
            raise ValueError(
                f"QuantConfig.weights={self.weights!r}; expected 'int8' "
                "or 'int4'")
        if self.group_size != -1 and self.group_size <= 0:
            raise ValueError(
                f"QuantConfig.group_size={self.group_size}; expected -1 "
                "(per-channel) or a positive group size")

    @property
    def bits(self) -> int:
        return _BITS[self.weights]


def resolve_quant_config(quant) -> Optional[QuantConfig]:
    """Normalize the engine's ``quant`` argument: QuantConfig
    pass-through, ``"int8"``/``"int4"`` shorthand, ``True`` -> int8
    defaults, ``False`` -> off, ``None`` -> the
    ``PT_SERVING_QUANT_WEIGHTS`` env knob (empty/unset disables;
    ``PT_SERVING_QUANT_GROUP`` sets the group size)."""
    if isinstance(quant, QuantConfig):
        return quant
    if quant is True:
        return QuantConfig()
    if quant is False:
        return None
    if isinstance(quant, str):
        return QuantConfig(weights=quant)
    if quant is not None:
        raise ValueError(f"quant={quant!r}: pass a QuantConfig, "
                         "'int8'/'int4', True/False, or None "
                         "(env-controlled)")
    w = env_str("PT_SERVING_QUANT_WEIGHTS", "")
    if not w:
        return None
    return QuantConfig(weights=w,
                       group_size=env_int("PT_SERVING_QUANT_GROUP", -1))


@dataclasses.dataclass(frozen=True)
class _QMeta:
    """Per-weight dequant recipe recorded at quantize time (static —
    baked into the compiled program, never traced)."""
    bits: int
    in_features: int
    dtype: object          # original weight dtype the dequant restores


def quantize_backend_params(model, pv, cfg: QuantConfig):
    """Quantize the serving weight set inside a backend's flat ``pv``
    list (aligned with ``model.named_parameters()`` order). Quantized
    entries become ``(codes, scales)`` tuples — a pytree jit/shard_map
    thread through unchanged; everything else keeps its fp32 value.
    Returns ``(new_pv, qmeta: {index: _QMeta}, weight_error_bound)``.

    A model with NO matching 2-D weights is refused loudly: silently
    serving fp32 from a quant= request would be a misconfiguration,
    not a preference (same contract as kv_int8 on an explicit
    backend)."""
    named = list(model.named_parameters())
    if len(named) != len(pv):
        raise ValueError("backend pv is not aligned with "
                         "model.named_parameters() — cannot map weights")
    new_pv = list(pv)
    qmeta: Dict[int, _QMeta] = {}
    bound = 0.0
    for i, (name, _p) in enumerate(named):
        v = pv[i]
        if getattr(v, "ndim", 0) != 2:
            continue
        if not any(pat in name for pat in _WEIGHT_PATTERNS):
            continue
        codes, scales = quantize_array(v, cfg.bits, cfg.group_size)
        new_pv[i] = (codes, scales)
        qmeta[i] = _QMeta(bits=cfg.bits, in_features=int(v.shape[0]),
                          dtype=v.dtype)
        bound = max(bound, quant_step_bound(scales, cfg.bits))
    if not qmeta:
        raise ValueError(
            f"{type(model).__name__} has no quantizable serving weights "
            f"(looked for 2-D parameters matching {_WEIGHT_PATTERNS}) — "
            "weight-only serving quant needs the standard projection "
            "layout")
    return new_pv, qmeta, bound


def dequantize_pv(pv, qmeta: Dict[int, _QMeta]):
    """In-graph inverse of :func:`quantize_backend_params`: rebuild the
    flat fp32 pv the model's forward expects. Runs INSIDE the compiled
    decode/prefill/verify programs — XLA fuses each weight's scale
    multiply into its consumer gemm, so the fp32 weight exists only as
    the gemm operand, never as an HBM round-trip."""
    out = list(pv)
    for i, m in qmeta.items():
        codes, scales = pv[i]
        out[i] = dequantize_array(codes, scales, m.bits,
                                  in_features=m.in_features,
                                  out_dtype=m.dtype)
    return out


def wrap_pure_with_dequant(pure, qmeta: Dict[int, _QMeta]):
    """Wrap a ``build_decode_step`` pure so every program built from it
    (decode block, prefill, chunk, spec verify) dequantizes the
    quantized pv entries at entry — ONE wrapper serves all program
    shapes, which is what keeps quant composable with paged/spec."""
    def pure_q(pv, bv, *args, **kw):
        return pure(dequantize_pv(pv, qmeta), bv, *args, **kw)
    return pure_q


def scale_pspec(weight_spec, scales):
    """PartitionSpec for a quantized weight's scales under
    tensor-parallel serving (mode="exact"): the scales ride the SAME
    axes as their weight's out dim — per-channel ``(out,)`` scales of a
    column-sharded ``P(None, axes)`` weight shard as ``P(axes)``,
    grouped ``(groups, out)`` as ``P(None, axes)``; a replicated weight
    replicates its scales."""
    from jax.sharding import PartitionSpec as P
    dims = tuple(weight_spec)
    if not dims or all(d is None for d in dims):
        return P()
    if len(dims) != 2 or dims[0] is not None:
        raise NotImplementedError(
            f"weight-only quant cannot shard scales for weight spec "
            f"{weight_spec} — only out-dim (column) sharding composes "
            "(tp mode='exact')")
    out_axes = dims[1]
    if scales.ndim == 1:
        return P(out_axes)
    return P(None, out_axes)
