"""Fleet wire transports: the 3-method ``Transport`` interface, the
deterministic in-process queue transport, and a REAL network transport
over localhost TCP sockets.

The fleet's failure-domain contract needs a wire that can actually
fail the way networks fail — torn writes, flipped bits, dropped
connections, lost acks — so recovery code is exercised against real
kernel socket buffers, not a python deque. :class:`SocketTransport`
provides that while staying CPU-lane testable and deterministic under
the ``utils.faults`` schedule:

- **Length-framed messages with a CRC32 trailer.** One frame =
  ``magic | seq | src_len | payload_len | src | payload | crc32``
  (all integers big-endian; the CRC covers every preceding byte). A
  receiver that sees a bad magic or CRC discards the frame and drops
  the connection WITHOUT acking — corruption is detected at the wire,
  never adopted into an arena.
- **Per-(src, dst) monotonic sequence numbers** ride the frame header.
  Within one connection a duplicate seq is dropped at the receiver;
  across a reconnect the receiver cannot know what the old connection
  delivered, so a retransmitted frame is delivered AGAIN — the
  transport is **at-least-once**, and exactly-once is restored one
  layer up by ``DecodeWorker.adopt()``'s (rid, payload seq) dedup.
- **Stop-and-wait acks with per-send wall-clock timeouts.** ``send``
  returns only after the receiver acked the frame's seq (or raises
  :class:`TransportError` after the retry budget); each attempt is
  bounded by ``io_timeout_s`` of wall clock.
- **Reconnect with exponential backoff** (the PR 5 policy: ``base *
  2^attempt`` plus seeded jitter) around every transient wire failure,
  after which the SAME frame — same seq — is retransmitted.

Every endpoint of this transport lives in one process (the CPU-lane
fleet), so the receive side is serviced inline: ``send`` pumps the
destination endpoint while waiting for its ack, and ``recv`` pumps
before popping. The bytes still genuinely traverse a kernel TCP
socket — partial delivery, coalescing and connection teardown are
real, which is the point.

Deterministic fault sites (``utils.faults``), all in the send path so
call counts are schedule-stable:

- ``fleet.transport``      — refuse the send before any bytes move
  (the PR 14 site; fires in BOTH transports).
- ``transport.partial_write`` — write only a prefix of the frame, then
  drop the connection (torn write; receiver discards the partial).
- ``transport.corrupt``    — flip one payload byte; the receiver's CRC
  check discards the frame and the sender retransmits.
- ``transport.disconnect`` — drop the connection after the full frame
  is written but BEFORE the ack is read (ack loss; the retransmit
  delivers a duplicate the adopt layer must dedup).
"""
from __future__ import annotations

import socket
import struct
import time
import zlib
from collections import deque
from typing import Dict, Optional

import numpy as np

from ..observability import metrics as _om
from ..utils import faults

__all__ = ["InProcessTransport", "SocketTransport", "Transport",
           "TransportError", "fetch_endpoint"]

#: suffix of a worker's prefix-fetch receive queue. Fetch RESPONSES
#: (serving/prefix_cache.py) travel the same transport as handoffs but
#: on a per-worker side channel, so a bulk fetch payload can never
#: interleave into — or stall behind — the worker's handoff stream.
FETCH_ENDPOINT_SUFFIX = "#fetch"


def fetch_endpoint(worker: str) -> str:
    """Transport endpoint name of ``worker``'s prefix-fetch channel."""
    return worker + FETCH_ENDPOINT_SUFFIX

# transport metric families (registered at import; no-ops until
# metrics.enable()/PT_METRICS)
_M_SENDS = _om.counter("pt_transport_sends_total",
                       "frames successfully sent and acked")
_M_RESENDS = _om.counter("pt_transport_resends_total",
                         "frame retransmissions after a wire failure")
_M_RECONNECTS = _om.counter("pt_transport_reconnects_total",
                            "outgoing connections re-established")
_M_CRC_DROPS = _om.counter("pt_transport_crc_drops_total",
                           "received frames discarded on a bad "
                           "magic/CRC")
_M_DUP_FRAMES = _om.counter("pt_transport_dup_frames_total",
                            "received frames dropped as same-connection "
                            "duplicates")


class TransportError(RuntimeError):
    """A send that could not be delivered within the retry budget.
    The fleet's resilience layer treats it as TRANSIENT (retry /
    breaker), same as an :class:`~paddle_tpu.utils.faults.
    InjectedFault` — the wire being down is an operational failure,
    not a programming error."""


class Transport:
    """Wire interface between fleet workers. ``send`` must raise on
    failure (the fleet's retry/breaker machinery wraps it); ``recv``
    returns the next payload for ``dst`` or None. Implementations must
    preserve per-destination FIFO order of successful sends — adoption
    order is part of the deterministic replay contract. Delivery is
    allowed to be at-least-once: the adopt layer dedups on
    (rid, payload seq)."""

    def send(self, dst: str, data: bytes):
        raise NotImplementedError

    def recv(self, dst: str) -> Optional[bytes]:
        raise NotImplementedError

    def pending(self) -> int:
        raise NotImplementedError

    def drop_endpoint(self, dst: str):
        """Tear down ``dst``'s receive side (its worker is dead):
        undelivered payloads are dropped — the fleet redrives them from
        its own records, never from the dead worker's queue. A later
        send/recv under the same name (a migrated successor)
        re-creates the endpoint fresh."""

    def close(self):
        """Release every OS resource the transport holds."""


class InProcessTransport(Transport):
    """Deterministic in-process transport: per-destination FIFO queues
    of real byte strings (payloads cross an actual serialize/
    deserialize boundary, so wire size and dtype fidelity are measured,
    not assumed). The ``fleet.transport`` fault site fires in ``send``
    BEFORE the payload is enqueued — a retry never double-delivers."""

    def __init__(self):
        self._queues: Dict[str, deque] = {}
        self.sends = 0
        self.bytes_sent = 0

    def send(self, dst: str, data: bytes):
        faults.fault_point("fleet.transport")
        self._queues.setdefault(dst, deque()).append(bytes(data))
        self.sends += 1
        self.bytes_sent += len(data)

    def recv(self, dst: str) -> Optional[bytes]:
        q = self._queues.get(dst)
        return q.popleft() if q else None

    def pending(self) -> int:
        return sum(len(q) for q in self._queues.values())

    def drop_endpoint(self, dst: str):
        self._queues.pop(dst, None)


# ---------------------------------------------------------------------------
# the socket transport
# ---------------------------------------------------------------------------

_MAGIC = b"PTF1"
_ACK_MAGIC = b"PTA1"
# magic(4) | seq(u64) | src_len(u16) | payload_len(u32)
_HDR = struct.Struct(">4sQHI")
_ACK = struct.Struct(">4sQ")
_CRC = struct.Struct(">I")


class _Endpoint:
    """One destination's receive side: a listening socket plus every
    accepted connection's read buffer and last-delivered seq."""

    def __init__(self):
        self.listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self.listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR,
                                 1)
        self.listener.bind(("127.0.0.1", 0))
        self.listener.listen(8)
        self.listener.setblocking(False)
        self.port = self.listener.getsockname()[1]
        self.conns: list = []           # [(sock, bytearray, {src: seq})]
        self.rx: deque = deque()        # delivered payload byte strings

    def close(self):
        for sock, _buf, _seen in self.conns:
            try:
                sock.close()
            except OSError:
                pass
        self.conns = []
        try:
            self.listener.close()
        except OSError:
            pass


class SocketTransport(Transport):
    """Localhost-TCP transport (see the module docstring for the frame
    format and delivery semantics). ``src`` names the sending endpoint
    (one fleet = one sender); receive endpoints are created lazily per
    destination name on first use.

    Counters (host attributes, mirrored into the ``pt_transport_*``
    metric families): ``sends`` (acked), ``resends``, ``reconnects``,
    ``crc_drops``, ``dup_frames``, ``bytes_sent`` (acked frames'
    payload bytes)."""

    def __init__(self, src: str = "fleet", *,
                 io_timeout_s: float = 5.0,
                 retry_attempts: int = 4,
                 retry_backoff_s: float = 0.005,
                 retry_jitter: float = 0.25,
                 seed: int = 0):
        if retry_attempts < 0:
            raise ValueError(
                f"retry_attempts={retry_attempts}; must be >= 0")
        self.src = src
        self.io_timeout_s = float(io_timeout_s)
        self.retry_attempts = int(retry_attempts)
        self.retry_backoff_s = float(retry_backoff_s)
        self.retry_jitter = float(retry_jitter)
        self._rng = np.random.RandomState(seed)
        self._endpoints: Dict[str, _Endpoint] = {}
        self._out: Dict[str, socket.socket] = {}
        self._seq: Dict[str, int] = {}       # per-dst (src is fixed)
        self.sends = 0
        self.resends = 0
        self.reconnects = 0
        self.crc_drops = 0
        self.dup_frames = 0
        self.bytes_sent = 0
        self._closed = False

    # -- endpoint / connection plumbing ------------------------------------
    def _endpoint(self, name: str) -> _Endpoint:
        if self._closed:
            raise TransportError("transport is closed")
        ep = self._endpoints.get(name)
        if ep is None:
            ep = _Endpoint()
            self._endpoints[name] = ep
        return ep

    def _backoff_s(self, attempt: int) -> float:
        return self.retry_backoff_s * (2.0 ** attempt) \
            * (1.0 + self.retry_jitter
               * float(self._rng.random_sample()))

    def _connect(self, dst: str) -> socket.socket:
        sock = self._out.get(dst)
        if sock is not None:
            return sock
        port = self._endpoint(dst).port
        sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        sock.settimeout(self.io_timeout_s)
        try:
            sock.connect(("127.0.0.1", port))
        except OSError as e:
            sock.close()
            raise TransportError(
                f"connect to {dst!r} (127.0.0.1:{port}) failed: {e}")
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._out[dst] = sock
        return sock

    def _drop_out(self, dst: str):
        sock = self._out.pop(dst, None)
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass

    # -- receive side (serviced inline: every endpoint is in-process) ------
    def _service(self, name: str):
        """Accept pending connections for ``name`` and drain every
        complete frame into its rx queue, acking each accepted frame.
        Non-blocking: returns once no more progress can be made."""
        ep = self._endpoints.get(name)
        if ep is None:
            return
        while True:                     # accept everything waiting
            try:
                conn, _addr = ep.listener.accept()
            except (BlockingIOError, OSError):
                break
            conn.setblocking(False)
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            ep.conns.append((conn, bytearray(), {}))
        live = []
        for conn, buf, seen in ep.conns:
            eof = False
            while True:
                try:
                    chunk = conn.recv(65536)
                except (BlockingIOError, InterruptedError):
                    break
                except OSError:
                    eof = True
                    break
                if not chunk:
                    eof = True
                    break
                buf.extend(chunk)
            bad = self._parse_frames(ep, conn, buf, seen)
            if bad or eof:              # torn/corrupt stream: drop the
                try:                    # connection; the sender
                    conn.close()        # retransmits on a fresh one
                except OSError:
                    pass
                continue
            live.append((conn, buf, seen))
        ep.conns = live

    def _parse_frames(self, ep: _Endpoint, conn, buf: bytearray,
                      seen: Dict[str, int]) -> bool:
        """Consume complete frames from ``buf``; returns True when the
        stream is corrupt (bad magic/CRC) and must be dropped."""
        while True:
            if len(buf) < _HDR.size:
                return False
            magic, seq, src_len, payload_len = _HDR.unpack_from(buf, 0)
            if magic != _MAGIC:
                self.crc_drops += 1
                _M_CRC_DROPS.inc()
                return True
            total = _HDR.size + src_len + payload_len + _CRC.size
            if len(buf) < total:
                return False
            body = bytes(buf[:total - _CRC.size])
            (crc,) = _CRC.unpack_from(buf, total - _CRC.size)
            if zlib.crc32(body) & 0xFFFFFFFF != crc:
                self.crc_drops += 1
                _M_CRC_DROPS.inc()
                return True
            src = body[_HDR.size:_HDR.size + src_len].decode("utf-8")
            payload = body[_HDR.size + src_len:]
            del buf[:total]
            if seq <= seen.get(src, 0):
                # same-connection duplicate (stop-and-wait never sends
                # these, but the wire contract tolerates them)
                self.dup_frames += 1
                _M_DUP_FRAMES.inc()
            else:
                seen[src] = seq
                ep.rx.append(payload)
            try:                        # ack even duplicates — the ack
                conn.sendall(_ACK.pack(_ACK_MAGIC, seq))  # is what the
            except OSError:             # sender is starved of
                return True
        return False

    # -- send --------------------------------------------------------------
    def _frame(self, dst: str, data: bytes) -> bytes:
        seq = self._seq.get(dst, 0) + 1
        self._seq[dst] = seq
        src_b = self.src.encode("utf-8")
        body = _HDR.pack(_MAGIC, seq, len(src_b), len(data)) \
            + src_b + data
        return body + _CRC.pack(zlib.crc32(body) & 0xFFFFFFFF)

    def send(self, dst: str, data: bytes):
        """Deliver ``data`` to ``dst`` with at-least-once semantics;
        raises :class:`TransportError` after the retry budget. The
        frame (and its seq) is built ONCE — every retry retransmits
        the identical bytes."""
        faults.fault_point("fleet.transport")
        self._endpoint(dst)             # receive side must exist
        frame = self._frame(dst, data)
        seq = self._seq[dst]
        last = ""
        for attempt in range(self.retry_attempts + 1):
            if attempt > 0:
                self.resends += 1
                _M_RESENDS.inc()
                time.sleep(self._backoff_s(attempt - 1))
            try:
                sock = self._connect(dst)
            except TransportError as e:
                last = str(e)
                continue
            try:
                self._write_frame(sock, frame)
                self._await_ack(dst, sock, seq)
            except (TransportError, OSError) as e:
                last = f"{type(e).__name__}: {e}"
                self._drop_out(dst)
                self.reconnects += 1
                _M_RECONNECTS.inc()
                continue
            self.sends += 1
            self.bytes_sent += len(data)
            _M_SENDS.inc()
            return
        raise TransportError(
            f"send to {dst!r} (seq {seq}) failed after "
            f"{self.retry_attempts + 1} attempts: {last}")

    def _write_frame(self, sock: socket.socket, frame: bytes):
        if faults.should_fire("transport.partial_write"):
            # torn write: a prefix reaches the kernel, then the
            # connection dies — the receiver discards the partial frame
            sock.sendall(frame[:max(1, len(frame) // 2)])
            raise TransportError("injected partial write")
        if faults.should_fire("transport.corrupt"):
            # one flipped payload byte; the receiver's CRC catches it
            corrupt = bytearray(frame)
            corrupt[_HDR.size + len(self.src) + 1] ^= 0xFF
            sock.sendall(bytes(corrupt))
            return
        sock.sendall(frame)

    def _await_ack(self, dst: str, sock: socket.socket, seq: int):
        """Pump the destination endpoint (in-process receive side)
        until our seq is acked, bounded by the per-send wall clock."""
        if faults.should_fire("transport.disconnect"):
            # ack loss: the frame is already on the wire (the receiver
            # will deliver it) but the sender never learns — the
            # retransmit produces the duplicate adopt() must dedup
            raise TransportError("injected disconnect before ack")
        deadline = time.perf_counter() + self.io_timeout_s
        buf = bytearray()
        sock.setblocking(False)
        try:
            while True:
                self._service(dst)
                try:
                    chunk = sock.recv(4096)
                    if not chunk:
                        raise TransportError(
                            "connection closed before ack (frame "
                            "refused or receiver dropped it)")
                    buf.extend(chunk)
                except (BlockingIOError, InterruptedError):
                    pass
                while len(buf) >= _ACK.size:
                    magic, got = _ACK.unpack_from(buf, 0)
                    del buf[:_ACK.size]
                    if magic != _ACK_MAGIC:
                        raise TransportError("bad ack magic")
                    if got == seq:
                        return
                    # acks for older retransmitted seqs can linger on a
                    # reused connection; skip them
                if time.perf_counter() > deadline:
                    raise TransportError(
                        f"ack timeout after {self.io_timeout_s}s")
                time.sleep(0.0005)
        finally:
            sock.settimeout(self.io_timeout_s)

    # -- receive / lifecycle ----------------------------------------------
    def recv(self, dst: str) -> Optional[bytes]:
        ep = self._endpoints.get(dst)
        if ep is None:
            self._endpoint(dst)
            return None
        self._service(dst)
        return ep.rx.popleft() if ep.rx else None

    def pending(self) -> int:
        for name in list(self._endpoints):
            self._service(name)
        return sum(len(ep.rx) for ep in self._endpoints.values())

    def drop_endpoint(self, dst: str):
        ep = self._endpoints.pop(dst, None)
        if ep is not None:
            ep.close()
        self._drop_out(dst)
        self._seq.pop(dst, None)

    def close(self):
        for name in list(self._endpoints):
            self.drop_endpoint(name)
        for dst in list(self._out):
            self._drop_out(dst)
        self._closed = True

    def stats(self) -> dict:
        return {"sends": self.sends, "resends": self.resends,
                "reconnects": self.reconnects,
                "crc_drops": self.crc_drops,
                "dup_frames": self.dup_frames,
                "bytes_sent": self.bytes_sent}
