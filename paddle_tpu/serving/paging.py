"""Block-paged KV cache for the continuous-batching engine: shared
arena + per-slot block tables, ref-counted prefix reuse, chunked
prefill.

The dense engine (engine.py) preallocates one ``(max_len, kvh, d)`` KV
row per slot, so HBM is sized for the worst-case sequence and a shared
system prompt is recomputed and stored per request. Paged mode replaces
the per-slot rows with ONE ``(num_blocks, block_size, kvh, d)`` arena
per layer plus an in-graph ``(S, max_blocks)`` block table riding the
slot state (vLLM's PagedAttention restated under the repo's
static-shape rules — the table is state, the arena never reshapes):

- ``BlockManager`` (host): free list + refcounts + a rolling-hash
  prefix index. Full prompt blocks are keyed by the chain digest of
  their token contents; a later request with the same prefix maps the
  SAME arena blocks into its table and skips recomputing them. A
  retired request's registered blocks stay cached (refcount 0, LRU)
  until the pool needs them, so a hot system prompt survives across
  requests. Hash collisions are detected by comparing the stored token
  tuple and fall back to recompute. Block 0 is the reserved trash
  block: dead slots' in-graph writes are redirected there, so a block
  the host has re-allocated mid-stream can never be corrupted.
- Chunked prefill: prompts are processed through ONE compiled
  ``(1, prefill_chunk)`` program (engine.build_paged_chunk_fn) in
  chunks interleaved with decode blocks, paced by the scheduler's
  per-tick prefill token budget — a long prompt no longer stalls every
  in-flight decode for its whole length, it steals at most
  ``budget`` tokens of prefill per tick. The dense engine's per-bucket
  prefill jits collapse to one program.
- Attention runs the Pallas paged-attention kernel on TPU and the
  gathered-dense reference off-TPU (ops/pallas/paged_attention.py);
  greedy paged streams are bit-identical to the dense fp32 engine and
  to per-request ``generate()``.
- ``kv_int8=True`` stores the arena as int8 codes + per-vector fp32
  absmax scales (the EQuARX recipe from
  ``distributed/collectives/quantized.py``; ~3.9x less KV HBM); the
  worst-case dequant error is runtime-queryable via
  :meth:`PagedEngine.kv_error_bound`.

Everything is default-off: construct ``ContinuousBatchingEngine(...,
paged=True)`` or set ``PT_SERVING_PAGED=1`` (``PT_SERVING_KV_INT8=1``
for the int8 arena).
"""
from __future__ import annotations

import hashlib
import time
from collections import OrderedDict
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..observability import metrics as _om
from ..observability.tracing import now_us as _trace_now
from ..utils import faults
from ..utils.flags import env_flag, env_int
from .engine import (ContinuousBatchingEngine, ModelStepBackend, _SlotRun,
                     _M_PREFILLS, _M_TOKENS, _StepBackendCommon,
                     artifact_fingerprint, build_paged_chunk_fn,
                     build_slot_block_fn, init_slot_state)

__all__ = ["BlockManager", "PagedArtifactStepBackend",
           "PagedModelStepBackend", "PagedEngine"]

TRASH_BLOCK = 0

# arena metric families (no-ops until metrics.enable()/PT_METRICS)
_M_BLK_FREE = _om.gauge("pt_paging_blocks_free",
                        "arena blocks on the free list")
_M_BLK_REF = _om.gauge("pt_paging_blocks_referenced",
                       "arena blocks held at refcount >= 1")
_M_BLK_CACHED = _om.gauge("pt_paging_blocks_cached",
                          "released registered blocks LRU-retained for "
                          "prefix reuse")
_M_PFX_LOOKUPS = _om.counter("pt_paging_prefix_lookups_total",
                             "prefix-index lookups at admission")
_M_PFX_HITS = _om.counter("pt_paging_prefix_hit_blocks_total",
                          "prompt blocks served from the prefix index")
_M_ALLOC_FAIL = _om.counter("pt_paging_allocate_failures_total",
                            "block allocations refused (pool exhausted "
                            "or injected fault)")
_M_BLK_EVICT = _om.counter("pt_blockmanager_evictions_total",
                           "registered refcount-0 blocks evicted from "
                           "the LRU prefix cache (allocate-pressure "
                           "or fleet watermark)")
_M_BLK_PRESSURE = _om.gauge("pt_blockmanager_block_pressure",
                            "fraction of the usable pool NOT on the "
                            "free list (referenced + LRU-cached) — the "
                            "eviction tier's control signal")


def _sha1_chain(parent_digest: bytes, tokens: Tuple[int, ...]) -> bytes:
    """Rolling block hash: H(parent_digest || token bytes). Chaining
    makes the key position-dependent — block j only matches block j of
    an identical prefix, never a same-content block elsewhere."""
    h = hashlib.sha1(parent_digest)
    h.update(np.asarray(tokens, np.int32).tobytes())
    return h.digest()


class BlockManager:
    """Host-side arena bookkeeping: free list, per-block refcounts,
    rolling-hash prefix index with LRU retention of released registered
    blocks. Pure python — it runs once per admission/retirement, never
    inside the compiled stream."""

    def __init__(self, num_blocks: int, block_size: int, hash_fn=None):
        if num_blocks < 2:
            raise ValueError(f"num_blocks={num_blocks}: need at least "
                             "the trash block plus one usable block")
        self.num_blocks = num_blocks
        self.block_size = block_size
        self.hash_fn = hash_fn or _sha1_chain
        self.reset()

    def reset(self):
        self._free: List[int] = list(range(1, self.num_blocks))
        self._ref: Dict[int, int] = {}          # allocated -> refcount
        self._index: Dict[bytes, Tuple[int, Tuple[int, ...]]] = {}
        self._digest_of: Dict[int, bytes] = {}  # registered blocks
        self._depth: Dict[bytes, int] = {}      # digest -> chain blocks
        self._cached: "OrderedDict[int, None]" = OrderedDict()
        # block id -> prefix-index hits observed (eviction cost signal)
        self._hits: Dict[int, int] = {}
        self.lookups = 0
        self.hit_blocks = 0
        self.evictions = 0
        self._note_pool()

    def _note_pool(self):
        """Refresh the pool-pressure gauges (one metrics-enabled check;
        called from the host-side accounting paths only)."""
        if not _om.enabled():
            return
        _M_BLK_FREE.set(len(self._free))
        _M_BLK_REF.set(len(self._ref))
        _M_BLK_CACHED.set(len(self._cached))
        _M_BLK_PRESSURE.set(self.block_pressure())

    # -- capacity ----------------------------------------------------------
    def available(self) -> int:
        return len(self._free) + len(self._cached)

    def usable_blocks(self) -> int:
        """Pool capacity excluding the reserved trash block — the
        admission-validation bound (a request needing more than this
        can NEVER be admitted, no matter what retires)."""
        return self.num_blocks - 1

    def block_pressure(self) -> float:
        """Fraction of the usable pool not on the free list. Referenced
        AND LRU-cached blocks both count as pressure: cached blocks are
        reclaimable, but only by evicting warm prefix state — exactly
        the trade the fleet's watermark eviction arbitrates."""
        return 1.0 - len(self._free) / self.usable_blocks()

    def _evict_victim(self) -> int:
        """Pick and unregister the next cached block to evict. The
        score is COST-AWARE, not pure LRU: least observed prefix-index
        reuse first (a 24-block system prompt shared by 100 tenants
        outlives a cold one-off chain of the same age), ties broken by
        LRU age. With no recorded hits anywhere this degrades to
        exactly the old LRU-first order."""
        best, best_score = None, None
        for pos, b in enumerate(self._cached):
            score = (self._hits.get(b, 0), pos)
            if best_score is None or score < best_score:
                best, best_score = b, score
        del self._cached[best]
        digest = self._digest_of.pop(best)
        del self._index[digest]
        self._depth.pop(digest, None)
        self._hits.pop(best, None)
        self.evictions += 1
        _M_BLK_EVICT.inc()
        return best

    def allocate(self, n: int) -> Optional[List[int]]:
        """n fresh blocks at refcount 1, evicting cached prefix blocks
        (least-reused first, then LRU) if the free list runs short;
        None if the pool can't cover the request (caller re-queues).
        The ``serving.allocate`` fault site deterministically simulates
        transient exhaustion (returns None with the pool untouched)."""
        if faults.should_fire("serving.allocate"):
            _M_ALLOC_FAIL.inc()
            return None
        if self.available() < n:
            _M_ALLOC_FAIL.inc()
            return None
        out = []
        for _ in range(n):
            if self._free:
                b = self._free.pop()
            else:
                b = self._evict_victim()
            self._ref[b] = 1
            out.append(b)
        self._note_pool()
        return out

    def eviction_victims(self, n: int) -> List[int]:
        """Non-mutating preview of the next ``n`` blocks
        :meth:`evict_cached` would pick, in eviction order — the spill
        tier reads this to copy exactly the chains about to die,
        WITHOUT perturbing hit counts or LRU order (a perturbed
        preview would desynchronize from the real eviction)."""
        scored = sorted(((self._hits.get(b, 0), pos, b)
                         for pos, b in enumerate(self._cached)))
        return [b for _, _, b in scored[:n]]

    def chain_tokens_map(self) -> Dict[bytes, Tuple[int, ...]]:
        """Reconstruct full chain tokens for every registered digest
        that is reachable from the root: ``{digest: tokens of the
        whole chain ending at it}``. The index stores only per-block
        chunks; this stitches them depth-by-depth by re-deriving each
        digest from its candidate parent — stateless, so the snapshot
        format never changes. A chain whose head was evicted is
        unreachable and simply omitted (it could not be re-matched or
        spilled anyway)."""
        by_depth: Dict[int, List[Tuple[bytes, Tuple[int, ...]]]] = {}
        for d, (_bid, chunk) in self._index.items():
            by_depth.setdefault(self._depth.get(d, 0), []).append(
                (d, chunk))
        toks: Dict[bytes, Tuple[int, ...]] = {}
        for d, chunk in by_depth.get(1, ()):
            if self.hash_fn(b"", chunk) == d:
                toks[d] = tuple(chunk)
        for k in sorted(x for x in by_depth if x > 1):
            prev = [(pd, pt) for pd, pt in toks.items()
                    if self._depth.get(pd) == k - 1]
            for d, chunk in by_depth[k]:
                for pd, pt in prev:
                    if self.hash_fn(pd, chunk) == d:
                        toks[d] = pt + tuple(chunk)
                        break
        return toks

    def evict_cached(self, n: int) -> int:
        """Evict up to ``n`` retained registered blocks back to the
        free list (the fleet's watermark eviction tier drives this),
        least-reused-first with LRU tiebreak (see
        :meth:`_evict_victim`). Referenced blocks are untouchable;
        returns the count actually evicted. Directory consequences are
        the caller's: the owner's next heartbeat publish simply no
        longer lists the digests."""
        done = 0
        while done < n and self._cached:
            self._free.append(self._evict_victim())
            done += 1
        if done:
            self._note_pool()
        return done

    # -- prefix sharing ----------------------------------------------------
    def _shareable_blocks(self, prompt) -> int:
        # whole blocks only, and never the one holding the LAST prompt
        # token — at least one token must prefill so the first-token
        # logits exist
        return (len(prompt) - 1) // self.block_size

    def match_prefix(self, prompt) -> List[int]:
        """Longest chain of indexed blocks matching the prompt's full
        prefix blocks; each match is ref-acquired for the caller. A
        digest hit whose stored tokens differ (hash collision) stops
        the chain — the caller just recomputes from there."""
        bs = self.block_size
        self.lookups += 1
        blocks: List[int] = []
        parent = b""
        for j in range(self._shareable_blocks(prompt)):
            chunk = tuple(int(t) for t in prompt[j * bs:(j + 1) * bs])
            digest = self.hash_fn(parent, chunk)
            entry = self._index.get(digest)
            if entry is None or entry[1] != chunk:
                break
            blocks.append(entry[0])
            parent = digest
        for b in blocks:
            self._acquire(b)
            # reuse tally: the eviction tier's cost signal — every
            # observed hit makes the block costlier to evict
            self._hits[b] = self._hits.get(b, 0) + 1
        self.hit_blocks += len(blocks)
        _M_PFX_LOOKUPS.inc()
        _M_PFX_HITS.inc(len(blocks))
        self._note_pool()
        return blocks

    def _acquire(self, block_id: int):
        r = self._ref.get(block_id, 0)
        if r == 0:                    # resurrect from the LRU cache
            del self._cached[block_id]
        self._ref[block_id] = r + 1

    def register_prefix(self, prompt, block_ids: Sequence[int],
                        n_blocks: Optional[int] = None):
        """Index the prompt's full prefix blocks (now filled) so later
        requests can share them. Blocks that were themselves matched
        from the index re-derive the same digests — no-ops.

        ``n_blocks`` overrides the default shareable count — decode-time
        block sharing passes the FULLY-WRITTEN block count of the
        completed sequence (every position resident, including decode
        positions), which can exceed ``_shareable_blocks`` of the prompt
        alone."""
        bs = self.block_size
        if n_blocks is None:
            n_blocks = self._shareable_blocks(prompt)
        parent = b""
        for j in range(n_blocks):
            chunk = tuple(int(t) for t in prompt[j * bs:(j + 1) * bs])
            digest = self.hash_fn(parent, chunk)
            bid = block_ids[j]
            if digest not in self._index and bid not in self._digest_of:
                self._index[digest] = (bid, chunk)
                self._digest_of[bid] = digest
            # depth is a pure function of the digest (it hashes the
            # whole chain), so re-registration writes the same value
            self._depth[digest] = j + 1
            parent = digest

    def registered_chains(self) -> Dict[bytes, int]:
        """``{digest: covered_blocks}`` for every registered block —
        what a fleet worker publishes to the prefix-cache directory on
        each heartbeat. A digest at chain position j covers j+1 blocks
        of any prompt whose prefix hashes to it."""
        return {d: self._depth.get(d, 0) for d in self._index}

    def release(self, block_ids: Sequence[int]):
        """Drop one reference per block. At refcount 0 a registered
        block parks in the LRU cache (still matchable); an unregistered
        one returns to the free list. Releasing an unheld block is a
        hard error — the double-free guard."""
        for bid in block_ids:
            r = self._ref.get(bid)
            if not r:
                raise RuntimeError(f"double free of arena block {bid}")
            if r > 1:
                self._ref[bid] = r - 1
            else:
                del self._ref[bid]
                if bid in self._digest_of:
                    self._cached[bid] = None
                else:
                    self._free.append(bid)
        self._note_pool()

    # -- invariants --------------------------------------------------------
    def assert_consistent(self):
        """Hard-check the arena accounting invariants (paging test
        teardowns + the chaos suite call this after every stream):

        - free + referenced + LRU-retained partition the usable pool
          exactly (every non-trash block in exactly ONE set);
        - every refcount >= 1 (zeroes must leave the map);
        - the prefix index and the registered-block map are mutual
          inverses, retained blocks are all registered, and no free
          block is still registered.
        """
        free, ref = set(self._free), set(self._ref)
        cached, reg = set(self._cached), set(self._digest_of)
        assert len(self._free) == len(free), \
            f"duplicate ids in free list: {sorted(self._free)}"
        assert not (free & ref), f"free AND referenced: {free & ref}"
        assert not (free & cached), f"free AND retained: {free & cached}"
        assert not (ref & cached), \
            f"referenced AND retained: {ref & cached}"
        universe = free | ref | cached
        assert TRASH_BLOCK not in universe, "trash block was allocated"
        want = set(range(1, self.num_blocks))
        assert universe == want, (
            f"block accounting leak: missing {sorted(want - universe)}, "
            f"unknown {sorted(universe - want)}")
        bad_refs = {b: r for b, r in self._ref.items() if r < 1}
        assert not bad_refs, f"non-positive refcounts: {bad_refs}"
        assert cached <= reg, \
            f"retained but unregistered: {cached - reg}"
        assert not (free & reg), \
            f"free but still registered: {free & reg}"
        assert len(self._index) == len(reg), \
            "prefix index and registered-block map out of sync"
        for digest, (bid, _) in self._index.items():
            assert self._digest_of.get(bid) == digest, \
                f"index entry for block {bid} disagrees with digest map"
        stale_depth = set(self._depth) - set(self._index)
        assert not stale_depth, \
            f"chain-depth entries for unregistered digests: " \
            f"{sorted(d.hex() for d in stale_depth)}"
        stale_hits = set(self._hits) - reg
        assert not stale_hits, \
            f"reuse tallies for unregistered blocks: " \
            f"{sorted(stale_hits)}"


class PagedModelStepBackend(ModelStepBackend):
    """Paged twin of ModelStepBackend: the pool cache is the shared
    block arena, the decode program threads the in-state block table
    through the forward, and prefill is ONE fixed-shape chunk program
    instead of per-bucket jits."""

    is_paged = True      # engine.__new__ routes on this, not isinstance

    def __init__(self, model, num_slots: int, max_len: int,
                 decode_block: int, block_size: int, num_blocks: int,
                 kv_int8: bool, prefill_chunk: int, quant=None,
                 fuse=None):
        from ..models.generation import (build_decode_step,
                                         forward_accepts_block_table,
                                         forward_accepts_pad)
        from ..tensor import Tensor
        if not forward_accepts_pad(type(model)):
            raise ValueError(
                f"{type(model).__name__}.forward does not accept per-row "
                "pad counts — the slot pool needs ragged decode support")
        if not forward_accepts_block_table(type(model)):
            raise ValueError(
                f"{type(model).__name__}.forward does not accept a "
                "block_table — paged KV needs it threaded to "
                "cached_attention (see models/llama.py)")
        if max_len % block_size != 0:
            raise ValueError(f"max_len={max_len} must be a multiple of "
                             f"block_size={block_size}")
        self.num_slots, self.max_len = num_slots, max_len
        self.block_size = decode_block
        self.kv_block_size = block_size
        self.num_kv_blocks = num_blocks
        self.max_blocks = max_len // block_size
        self.kv_int8 = kv_int8
        self.prefill_chunk_len = prefill_chunk
        tree_holder = {"tree": None}
        self._tree_holder = tree_holder    # spec backends reuse it
        self._pure = build_decode_step(model, None, tree_holder)
        cache0 = model.init_paged_kv_cache(num_blocks, block_size,
                                           kv_int8=kv_int8)
        flat, tree = jax.tree.flatten(
            cache0, is_leaf=lambda x: isinstance(x, Tensor))
        tree_holder["tree"] = tree
        self.pool_specs = tuple((c._value.shape, c._value.dtype)
                                for c in flat)
        self._pv = [p._value for _, p in model.named_parameters()]
        self._bv = [b._value for _, b in model.named_buffers()]
        # weight-only quant BEFORE the decode-block and chunk programs
        # are built (serving/quant.py)
        self._setup_weight_quant(model, quant)
        self._pure = self._maybe_quant_pure(self._pure)
        self._resolve_fuse(fuse)
        self.decode_traces = [0]
        self.prefill_traces = [0]
        # the decode block routes through the megakernel builder when
        # armed; the chunked-prefill program stays unfused (s > 1 —
        # compute-bound, outside the marked decode shape)
        self._block_jit = self._block_jit_for(
            build_slot_block_fn(self._pure, decode_block,
                                self.decode_traces, paged=True))
        self._chunk_jit = jax.jit(
            build_paged_chunk_fn(self._pure, prefill_chunk,
                                 self.prefill_traces),
            donate_argnums=(3,))

    def init_state(self):
        state = init_slot_state(self.num_slots)
        state["table"] = jnp.zeros((self.num_slots, self.max_blocks),
                                   jnp.int32)        # all-trash tables
        return state

    def prefill_chunk(self, ids, cache_flat, table_row, start_pos,
                      n_valid, key, temp, topk, topp):
        return self._chunk_jit(self._pv, self._bv, ids, cache_flat,
                               table_row, start_pos, n_valid, key, temp,
                               topk, topp)

    def prefill(self, *a, **kw):
        raise RuntimeError("the paged backend prefills in chunks — use "
                           "prefill_chunk (engine.admit drives it)")


class PagedArtifactStepBackend(_StepBackendCommon):
    """AOT paged backend: the paged engine's TWO programs (ONE decode
    block + ONE chunked-prefill chunk), deserialized from an
    ``export_decoder(..., engine_slots=N, engine_paged=True)`` artifact
    — no model code or tracing needed on the serving host. The
    ``artifact_fingerprint`` (sha1 over the serialized programs +
    config) rides engine snapshots so a restore onto a DIFFERENT
    artifact is refused instead of silently resuming on other
    programs."""

    is_paged = True

    def __init__(self, blob):
        eng = blob["engine"]
        cfgs = eng["config"]
        if not cfgs.get("paged"):
            raise ValueError(
                "artifact holds the dense engine programs — load it "
                "with ArtifactStepBackend, or re-export with "
                "export_decoder(..., engine_paged=True)")
        self.artifact_fingerprint = artifact_fingerprint(
            cfgs, eng["block"], eng["chunk"])
        self.num_slots = cfgs["num_slots"]
        self.max_len = cfgs["max_len"]
        self.block_size = cfgs["decode_block"]
        self.kv_block_size = cfgs["block_size"]
        self.num_kv_blocks = cfgs["num_blocks"]
        self.max_blocks = self.max_len // self.kv_block_size
        self.kv_int8 = bool(cfgs.get("kv_int8", False))
        self.prefill_chunk_len = cfgs["prefill_chunk"]
        self.carries_nan_flags = cfgs.get("block_outputs", 4) >= 5
        self.pool_specs = tuple((tuple(shape), np.dtype(dtype))
                                for shape, dtype in eng["pool_specs"])
        self._block = jax.export.deserialize(eng["block"])
        self._chunk = jax.export.deserialize(eng["chunk"])
        self._pv = [jnp.asarray(v) for v in blob["params"]]
        self._bv = [jnp.asarray(v) for v in blob["buffers"]]
        self.decode_traces = [1]     # two AOT-compiled programs
        self.prefill_traces = [1]

    def init_state(self):
        state = init_slot_state(self.num_slots)
        state["table"] = jnp.zeros((self.num_slots, self.max_blocks),
                                   jnp.int32)
        return state

    def pool_cache(self):
        return tuple(jnp.zeros(shape, dtype)
                     for shape, dtype in self.pool_specs)

    def decode_block(self, cache_flat, state):
        return self._block.call(self._pv, self._bv, cache_flat, state)

    def prefill_chunk(self, ids, cache_flat, table_row, start_pos,
                      n_valid, key, temp, topk, topp):
        return self._chunk.call(self._pv, self._bv, ids, cache_flat,
                                table_row, start_pos, n_valid, key,
                                temp, topk, topp)

    def prefill(self, *a, **kw):
        raise RuntimeError("the paged backend prefills in chunks — use "
                           "prefill_chunk (engine.admit drives it)")


def _arm_fn(state, slot, table_row, tok0, pos0, rem0, eos0, temp0,
            topk0, topp0, key0):
    """Turn a slot live after its chunked prefill finished: the arena
    already holds the prompt's K/V, so arming is a pure state update
    (the paged analogue of engine._admit_fn without the row splice).
    ``slot`` is traced — one compiled program serves every arming."""

    def set1(a, v):
        return a.at[slot].set(jnp.asarray(v, a.dtype))

    return dict(
        state, tok=set1(state["tok"], tok0),
        pos=set1(state["pos"], pos0),
        pad=set1(state["pad"], 0),        # paged prompts are unpadded
        live=set1(state["live"], rem0 > 0),
        eos=set1(state["eos"], eos0),
        remaining=set1(state["remaining"], rem0),
        key=state["key"].at[slot].set(key0),
        temp=set1(state["temp"], temp0),
        topk=set1(state["topk"], topk0),
        topp=set1(state["topp"], topp0),
        table=state["table"].at[slot].set(table_row))


@dataclass
class _PrefillJob:
    """One admitted request still streaming its prompt into the arena
    (``done`` counts tokens already resident, including the shared
    prefix it skipped). For a preemption resume, ``prompt`` is the
    original prompt plus the generated history being re-prefilled and
    ``resume_tok`` is the carried in-hand next token — the chunk
    programs' in-graph samples are discarded and the slot arms with it
    instead."""
    run: _SlotRun
    slot: int
    prompt: np.ndarray
    done: int
    table_row: np.ndarray          # (max_blocks,) int32
    key: jnp.ndarray               # post-split state key
    sub: jnp.ndarray               # prefill sampling key
    temp: jnp.ndarray
    topk: jnp.ndarray
    topp: jnp.ndarray
    tok0: Optional[int] = None
    resume_tok: Optional[int] = None


class PagedEngine(ContinuousBatchingEngine):
    """Paged-KV continuous batching. Same Server/Scheduler contract as
    the dense engine; differences:

    - ``admit()`` only reserves blocks and queues a prefill job; the
      prompt streams into the arena via :meth:`prefill_tick` (chunk
      programs), and the slot arms when its last chunk lands.
    - ``try_admit()`` can return False (block pool exhausted) — the
      Server re-queues and retries after retirements free blocks.
    - prompts are UNPADDED (no buckets): position 0 is token 0, which
      is what makes whole prefix blocks shareable across requests.
    """

    def __init__(self, model=None, num_slots: int = 4,
                 max_len: int = 256, decode_block: int = 8,
                 prompt_buckets: Optional[Sequence[int]] = None,
                 backend=None, *, paged: bool = True, spec=None,
                 block_size: Optional[int] = None,
                 num_blocks: Optional[int] = None,
                 kv_int8: Optional[bool] = None,
                 prefill_chunk: Optional[int] = None,
                 hash_fn=None, tp=None, quant=None, megakernel=None):
        if prompt_buckets is not None:
            raise ValueError(
                "paged mode takes no prompt_buckets: prompts are "
                "unpadded and prefilled in fixed-size chunks")
        if backend is not None:
            # the backend already baked these in — a silently ignored
            # kv_int8=True (fp32 arena, bound 0.0), num_blocks or
            # quant= would be a misconfiguration, not a preference
            given = {k: v for k, v in (("block_size", block_size),
                                       ("num_blocks", num_blocks),
                                       ("kv_int8", kv_int8),
                                       ("prefill_chunk", prefill_chunk),
                                       ("quant", quant),
                                       ("megakernel", megakernel))
                     if v is not None}
            if given:
                raise ValueError(
                    f"{sorted(given)} cannot be set alongside an "
                    "explicit backend — they are baked into it at "
                    "construction")
        if block_size is None:
            # resolution order: explicit arg > env knob > a valid
            # (stamp-matching) autotune-table winner > the documented
            # default 16 — a stale table never silently reshapes arenas
            block_size = env_int("PT_SERVING_BLOCK_SIZE", 0)
            if block_size <= 0:
                from ..ops.pallas.autotune import tuned_paged_block_size
                block_size = tuned_paged_block_size(16)
        if num_blocks is None:
            # full dense capacity + trash by default — HBM savings come
            # from passing a smaller pool (plus sharing); correctness
            # never depends on the pool being oversized
            num_blocks = 1 + num_slots * (max_len // block_size)
        if kv_int8 is None:
            kv_int8 = env_flag("PT_SERVING_KV_INT8")
        if prefill_chunk is None:
            prefill_chunk = env_int("PT_SERVING_PREFILL_CHUNK",
                                    2 * block_size)
        if backend is None:
            if model is None:
                raise ValueError("pass a model or a paged step backend")
            from .quant import resolve_quant_config
            from .tp import resolve_tp_config
            tp_cfg = resolve_tp_config(tp)
            q_cfg = resolve_quant_config(quant)
            if tp_cfg is not None:
                if megakernel:
                    raise NotImplementedError(
                        "megakernel decode is not yet composed with "
                        "tensor-parallel serving — drop megakernel= or "
                        "tp= (ROADMAP follow-up)")
                # tensor-parallel paged serving: the shared KV arena
                # shards its kv-head dim over the mesh (serving/tp.py);
                # an explicit backend is never rerouted by the env flag
                from .tp import ShardedPagedStepBackend
                backend = ShardedPagedStepBackend(
                    model, num_slots, max_len, decode_block,
                    block_size, num_blocks, bool(kv_int8),
                    prefill_chunk, tp_cfg, quant=q_cfg)
            else:
                # subclass hook: the speculative engine swaps in the
                # verify-capable paged backend here (serving/spec.py)
                backend = self._build_paged_backend(
                    model, num_slots, max_len, decode_block, block_size,
                    num_blocks, bool(kv_int8), prefill_chunk, q_cfg,
                    fuse=megakernel)
        self.kv_block_size = backend.kv_block_size
        self.num_kv_blocks = backend.num_kv_blocks
        self.max_blocks = backend.max_blocks
        self.kv_int8 = backend.kv_int8
        self.prefill_chunk_len = backend.prefill_chunk_len
        self.manager = BlockManager(self.num_kv_blocks,
                                    self.kv_block_size, hash_fn)
        self._arm_jit = jax.jit(_arm_fn, donate_argnums=(0,))
        super().__init__(backend=backend, spec=spec)

    def _build_paged_backend(self, model, num_slots, max_len,
                             decode_block, block_size, num_blocks,
                             kv_int8, prefill_chunk, quant=None,
                             fuse=None):
        return PagedModelStepBackend(
            model, num_slots, max_len, decode_block, block_size,
            num_blocks, kv_int8, prefill_chunk, quant=quant, fuse=fuse)

    # -- lifecycle ---------------------------------------------------------
    def reset(self):
        super().reset()
        self.manager.reset()
        self._jobs: List[_PrefillJob] = []
        self.prompt_tokens = 0         # all prompt tokens submitted
        self.shared_tokens = 0         # skipped via prefix reuse
        self.prefilled_tokens = 0      # actually computed
        self.prefill_chunks = 0        # chunk programs dispatched
        self.fetched_tokens = 0        # of shared: remote-fetched KV

    # -- introspection -----------------------------------------------------
    def prefix_cache_hit_rate(self) -> float:
        """Fraction of submitted prompt tokens served from shared
        prefix blocks instead of recomputed."""
        return self.shared_tokens / self.prompt_tokens \
            if self.prompt_tokens else 0.0

    def prefill_compile_count(self) -> int:
        return self.backend.prefill_traces[0]

    def kv_error_bound(self) -> float:
        """Runtime worst-case |dequantized - fp32| over the int8 arena
        (0.0 in fp32 mode): the EQuARX single-quantization bound from
        the largest live absmax scale."""
        if not self.kv_int8:
            return 0.0
        from ..ops.pallas.paged_attention import kv_int8_error_bound
        worst = 0.0
        for (shape, dtype), buf in zip(self.backend.pool_specs,
                                       self._cache):
            if np.dtype(dtype) == np.float32 and len(shape) == 3:
                worst = max(worst, float(jnp.max(buf)))
        return float(kv_int8_error_bound(worst))

    def blocks_needed(self, prompt_len: int, max_new_tokens: int) -> int:
        # positions written: prompt [0, L) plus generated tokens at
        # [L, L+max_new-1) — the final sampled token is never written
        return -(-(prompt_len + max(max_new_tokens - 1, 0))
                 // self.kv_block_size)

    def bucket_len(self, prompt_len: int) -> int:
        return prompt_len            # unpadded prompts, no buckets

    def validate_request(self, prompt_len: int, max_new_tokens: int):
        super().validate_request(prompt_len, max_new_tokens)
        need = self.blocks_needed(prompt_len, max_new_tokens)
        # the MANAGER is the source of truth, not the engine's
        # num_kv_blocks attribute: allocate() draws from the manager,
        # so validating against a stale attribute let an impossible
        # request through the door and into run_until_idle's re-queue
        # path forever (the PR-5 livelock fix; regression-pinned with a
        # tiny pool in tests/test_resilience.py)
        pool = self.manager.usable_blocks()
        if need > pool:
            raise ValueError(
                f"request needs {need} KV blocks but the arena only "
                f"has {pool}; raise num_blocks or shorten the request")

    # -- admission ---------------------------------------------------------
    def try_admit(self, request) -> bool:
        prompt = np.asarray(request.prompt, np.int32).reshape(-1)
        resume = getattr(request, "resume", None)
        if resume is not None and resume.tokens:
            # preemption resume: the "prompt" to prefill is the original
            # prompt plus the generated history minus the in-hand next
            # token; the first full prompt blocks are usually still in
            # the prefix index (eviction retained them), so most of this
            # re-prefill is cache hits rather than recompute
            full = np.concatenate([
                prompt, np.asarray(resume.tokens[:-1], np.int32)])
            mnt = request.max_new_tokens - len(resume.tokens) + 1
        else:
            resume = None
            full, mnt = prompt, request.max_new_tokens
        L = int(full.shape[0])
        self.validate_request(L, mnt)
        slot = next((i for i, s in enumerate(self._slots) if s is None),
                    None)
        if slot is None:
            raise RuntimeError("no free slot (scheduler bug)")
        shared = self._match_prefix_for_admission(full)
        total = self.blocks_needed(L, mnt)
        fresh = self.manager.allocate(total - len(shared))
        if fresh is None:            # pool exhausted: retry later
            self.manager.release(shared)
            return False
        block_ids = shared + fresh
        if self.tracer is not None:
            self.tracer.span_end(request.request_id, "queue_wait",
                                 shared_blocks=len(shared),
                                 fresh_blocks=len(fresh),
                                 resumed=resume is not None)
        table_row = np.zeros((self.max_blocks,), np.int32)
        table_row[:len(block_ids)] = block_ids
        if resume is None:
            key = jax.random.PRNGKey(request.seed)
            key, sub = jax.random.split(key)  # generate()'s key schedule
            run = _SlotRun(request, block_ids=block_ids)
            resume_tok = None
        else:
            # the saved key IS the next step's split input — arming with
            # it (and discarding the chunk programs' in-graph samples)
            # keeps seeded-sampled resumes bit-identical
            key = jnp.asarray(np.asarray(resume.key, np.uint32))
            sub = jax.random.PRNGKey(0)            # discarded draw
            run = _SlotRun(request, tokens=list(resume.tokens),
                           t_admit=resume.t_admit, block_ids=block_ids)
            resume_tok = int(resume.tokens[-1])
        self._slots[slot] = run
        self._prefill_slots.add(slot)
        n_shared = len(shared) * self.kv_block_size
        self.prompt_tokens += L
        self.shared_tokens += n_shared
        self._jobs.append(_PrefillJob(
            run=run, slot=slot, prompt=full, done=n_shared,
            table_row=table_row, key=key, sub=sub,
            temp=jnp.float32(request.temperature),
            topk=jnp.int32(request.top_k),
            topp=jnp.float32(request.top_p), resume_tok=resume_tok))
        return True

    def _match_prefix_for_admission(self, full) -> List[int]:
        """Admission-time prefix match. The base engine consults only
        its LOCAL index; the fleet's prefill engines override this to
        also fetch a longer chain another worker has registered
        (serving/prefix_cache.py) — either way the returned blocks are
        ref-acquired for the admitting request and ``done`` starts past
        them."""
        return self.manager.match_prefix(full)

    def admit(self, request) -> bool:
        if not self.try_admit(request):
            raise RuntimeError(
                "KV block pool exhausted; use try_admit/Server (which "
                "re-queue) or raise num_blocks")
        return False

    # -- chunked prefill ---------------------------------------------------
    def prefill_tick(self, token_budget: Optional[int] = None) -> int:
        """Advance pending prefill jobs by up to ``token_budget`` prompt
        tokens (always at least one chunk when work is pending, so a
        tiny budget still progresses). Jobs run FIFO; a finished job
        arms its slot (or retires immediately on eos/max_new==1)."""
        from ..profiler import RecordEvent
        spent = 0
        C = self.prefill_chunk_len
        while self._jobs and (token_budget is None or spent == 0
                              or spent < token_budget):
            # fires BEFORE the chunk dispatch: the job's cursor hasn't
            # advanced, so a retry re-dispatches the identical chunk
            faults.fault_point("serving.prefill_tick")
            job = self._jobs[0]
            L = len(job.prompt)
            n = min(C, L - job.done)
            ids = np.zeros((1, C), np.int32)
            ids[0, :n] = job.prompt[job.done:job.done + n]
            tr = self.tracer
            t_chunk = _trace_now() if tr is not None else 0.0
            with RecordEvent("serving.prefill_chunk"):
                tok0_dev, self._cache = self.backend.prefill_chunk(
                    jnp.asarray(ids), self._cache,
                    jnp.asarray(job.table_row[None]),
                    jnp.asarray(job.done, jnp.int32),
                    jnp.asarray(n, jnp.int32),
                    job.sub, job.temp, job.topk, job.topp)
            job.done += n
            spent += n
            self.prefill_chunks += 1
            self.prefilled_tokens += n
            _M_PREFILLS.inc()
            if tr is not None:
                tr.span_at(job.run.request.request_id, "prefill_chunk",
                           t_chunk, tokens=n, done=job.done, total=L)
            if job.done >= L:
                self._jobs.pop(0)
                self._finish_prefill(job, tok0_dev)
        return spent

    def _finish_prefill(self, job: _PrefillJob, tok0_dev):
        req = job.run.request
        now = time.perf_counter()
        eos = req.eos_token_id
        if job.resume_tok is not None:
            # preemption resume: the carried stream owns the next token
            # — the chunk's in-graph sample is discarded, tokens and the
            # TTFT timestamp ride over from the evicted run
            tok0 = job.resume_tok
            rem0 = req.max_new_tokens - len(job.run.tokens)
            req.resume = None
            if self.tracer is not None:
                self.tracer.instant(req.request_id, "resume",
                                    slot=job.slot,
                                    reused_tokens=len(job.run.tokens))
        else:
            tok0 = int(tok0_dev)
            job.run.tokens = [tok0]
            job.run.t_admit = now           # TTFT timestamp
            self.tokens_emitted += 1
            _M_TOKENS.inc()
            rem0 = req.max_new_tokens - 1
            if eos is not None and tok0 == eos:
                rem0 = 0
        # the prompt's full blocks are resident now — index them so the
        # NEXT request with this prefix skips the compute
        self.manager.register_prefix(job.prompt, job.run.block_ids)
        self._prefill_slots.discard(job.slot)
        if rem0 <= 0:                # finished at admission
            self._retire(job.slot, job.run, now)
            return
        L = len(job.prompt)
        self._state = self._arm_jit(
            self._state, jnp.int32(job.slot),
            jnp.asarray(job.table_row), jnp.int32(tok0), jnp.int32(L),
            jnp.int32(rem0), jnp.int32(-1 if eos is None else eos),
            job.temp, job.topk, job.topp, job.key)
        if self.tracer is not None:
            self.tracer.span_begin(req.request_id, "decode",
                                   slot=job.slot)
        self._remaining_host[job.slot] = rem0

    def _retire(self, slot, run, now):
        super()._retire(slot, run, now)
        if run.block_ids is not None:
            if run.failure is None and run.tokens:
                # decode-time block sharing: every position the stream
                # WROTE is resident — prompt plus generated history
                # minus the final sampled token (never written). Extend
                # the digest chain over the fully-written blocks so a
                # later request continuing this conversation shares the
                # decode-position KV too. Failed/poisoned runs register
                # NOTHING (a poisoned block must never be matchable).
                seq = np.concatenate([
                    np.asarray(run.request.prompt, np.int32).reshape(-1),
                    np.asarray(run.tokens[:-1], np.int32)])
                self.manager.register_prefix(
                    seq, run.block_ids,
                    n_blocks=len(seq) // self.kv_block_size)
            self.manager.release(run.block_ids)
            run.block_ids = None     # the no-double-free invariant

    # -- resilience hooks --------------------------------------------------
    def _abort_prefill(self, slot):
        """Cancel a mid-prefill request: drop its pending job (the
        chunk loop never sees it again); its blocks release through the
        shared ``_retire`` path. The slot never armed, so there is no
        in-graph state to kill."""
        self._jobs = [j for j in self._jobs if j.slot != slot]

    def _release_slot_resources(self, run):
        """Preemption release: the run's arena blocks drop one ref —
        registered prompt-prefix blocks park in the LRU cache (their
        prefix-index entries RETAINED, so the resume's re-prefill is
        mostly cache hits), unregistered decode blocks return to the
        free list."""
        if run.block_ids is not None:
            self.manager.release(run.block_ids)
            run.block_ids = None

    def _poison_live_slot(self):
        """Paged poison: NaN the arena block holding the victim's
        position ``pos-1``. That block is always (a) within the slot's
        attended range, so the sentinel trips on the very next step,
        and (b) a FRESH block owned only by this slot — its index
        ``(pos-1)//bs >= (L-1)//bs`` sits past both the shared-prefix
        and the registered range, so no other slot (and no future
        prefix match) can ever read the poison."""
        for slot, run in enumerate(self._slots):
            if run is not None and slot not in self._prefill_slots:
                L = int(np.asarray(run.request.prompt).reshape(-1)
                        .shape[0])
                pos = L + len(run.tokens) - 1
                blk = run.block_ids[(pos - 1) // self.kv_block_size]
                self._cache = tuple(
                    c.at[blk].set(jnp.nan)
                    if jnp.issubdtype(c.dtype, jnp.floating) else c
                    for c in self._cache)
                return slot
        return None

    # -- snapshot / restore ------------------------------------------------
    def snapshot_state(self):
        meta, arrays = super().snapshot_state()
        m = self.manager
        meta["manager"] = {
            "num_blocks": m.num_blocks, "block_size": m.block_size,
            "free": list(m._free),
            "ref": [[int(b), int(r)] for b, r in m._ref.items()],
            "digest_of": [[int(b), d.hex()]
                          for b, d in m._digest_of.items()],
            "index": [[d.hex(), int(bid), [int(t) for t in chunk]]
                      for d, (bid, chunk) in m._index.items()],
            "cached": [int(b) for b in m._cached],   # LRU order
            "lookups": m.lookups, "hit_blocks": m.hit_blocks,
            "depth": [[d.hex(), int(n)] for d, n in m._depth.items()],
            "evictions": m.evictions,
            "hits": [[int(b), int(h)] for b, h in m._hits.items()],
        }
        jobs_meta = []
        for j, job in enumerate(self._jobs):
            arrays[f"job{j}_prompt"] = np.asarray(job.prompt, np.int32)
            arrays[f"job{j}_table"] = np.asarray(job.table_row, np.int32)
            arrays[f"job{j}_key"] = np.asarray(job.key)
            arrays[f"job{j}_sub"] = np.asarray(job.sub)
            jobs_meta.append({
                "slot": job.slot, "done": job.done,
                "temp": float(job.temp), "topk": int(job.topk),
                "topp": float(job.topp), "tok0": job.tok0,
                "resume_tok": job.resume_tok})
        meta["jobs"] = jobs_meta
        meta["paged_counters"] = {
            "prompt_tokens": self.prompt_tokens,
            "shared_tokens": self.shared_tokens,
            "prefilled_tokens": self.prefilled_tokens,
            "prefill_chunks": self.prefill_chunks,
            "fetched_tokens": self.fetched_tokens}
        return meta, arrays

    def restore_state(self, meta, arrays):
        super().restore_state(meta, arrays)
        mm = meta["manager"]
        m = self.manager
        if (mm["num_blocks"], mm["block_size"]) != (m.num_blocks,
                                                   m.block_size):
            raise ValueError(
                f"snapshot arena {mm['num_blocks']}x{mm['block_size']} "
                f"does not match this engine's "
                f"{m.num_blocks}x{m.block_size}")
        m._free = list(mm["free"])
        m._ref = {int(b): int(r) for b, r in mm["ref"]}
        m._digest_of = {int(b): bytes.fromhex(d)
                        for b, d in mm["digest_of"]}
        m._index = {bytes.fromhex(d): (int(bid), tuple(chunk))
                    for d, bid, chunk in mm["index"]}
        m._cached = OrderedDict((int(b), None) for b in mm["cached"])
        m.lookups, m.hit_blocks = mm["lookups"], mm["hit_blocks"]
        m._depth = {bytes.fromhex(d): int(n)
                    for d, n in mm.get("depth", [])}
        m._depth = {d: n for d, n in m._depth.items()
                    if d in m._index}
        m.evictions = int(mm.get("evictions", 0))
        m._hits = {int(b): int(h) for b, h in mm.get("hits", [])
                   if int(b) in m._digest_of}
        m.assert_consistent()
        self._jobs = []
        for j, jm in enumerate(meta["jobs"]):
            run = self._slots[jm["slot"]]
            self._jobs.append(_PrefillJob(
                run=run, slot=jm["slot"],
                prompt=np.asarray(arrays[f"job{j}_prompt"], np.int32),
                done=jm["done"],
                table_row=np.asarray(arrays[f"job{j}_table"], np.int32),
                key=jnp.asarray(arrays[f"job{j}_key"]),
                sub=jnp.asarray(arrays[f"job{j}_sub"]),
                temp=jnp.float32(jm["temp"]),
                topk=jnp.int32(jm["topk"]),
                topp=jnp.float32(jm["topp"]), tok0=jm["tok0"],
                resume_tok=jm.get("resume_tok")))
        pc = meta["paged_counters"]
        self.prompt_tokens = pc["prompt_tokens"]
        self.shared_tokens = pc["shared_tokens"]
        self.prefilled_tokens = pc["prefilled_tokens"]
        self.prefill_chunks = pc["prefill_chunks"]
        self.fetched_tokens = pc.get("fetched_tokens", 0)
