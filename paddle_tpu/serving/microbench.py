"""Tensor-parallel serving bench: the slot-pool decode block sharded
over a device mesh (serving/tp.py) A/B'd against the 1-chip engine.

What the stage pins every round:

- **bit-identity**: the exact-mode sharded greedy stream must equal the
  1-chip stream token-for-token (the TP correctness contract);
- **tokens/s** for both engines — on the CPU lane the "mesh" is
  ``--xla_force_host_platform_device_count`` simulated devices sharing
  one socket, so the sharded number is a plumbing-overhead record, not
  a speedup claim (the speedup exists where the shards are real chips);
- **collective traffic**: logical payload bytes and collective calls
  per decode step, read back from the ``pt_collectives_*`` metrics the
  sharded backend notes per dispatched block;
- **int8 hop**: the psum-mode hidden-state all-reduce compressed with
  the EQuARX wire format, with its runtime-queryable error bound.

Wired into bench.py as the ``serving-tp`` child stage (CPU lane,
non-null on the fallback path like comms/passes/observability; the TPU
child runs it too when its window owns more than one chip).
"""
from __future__ import annotations

import time

import numpy as np

__all__ = ["run_serving_tp_bench"]


def run_serving_tp_bench(requests: int = 6, max_new: int = 16,
                         num_slots: int = 2, decode_block: int = 4
                         ) -> dict:
    import jax

    import paddle_tpu as paddle
    from paddle_tpu.distributed.mesh import build_device_mesh
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.observability import metrics
    from paddle_tpu.serving import (ContinuousBatchingEngine, Server,
                                    TPConfig)

    n_dev = jax.device_count()
    if n_dev < 2:
        return {"serving_tp_devices": n_dev,
                "serving_tp_skipped": "needs >= 2 devices "
                "(simulated or real) to shard the decode block"}
    # widest 2-level mesh the device count allows: 2 x (n/2) exercises
    # the hierarchical inner/outer plan; an odd count falls back flat
    if n_dev % 2 == 0:
        mesh = build_device_mesh({"dp": 2, "mp": n_dev // 2})
        axes = ("dp", "mp")
    else:
        mesh = build_device_mesh({"dp": 1, "mp": n_dev},
                                 allow_subset=True)
        axes = ("mp",)

    paddle.seed(0)
    cfg = LlamaConfig(
        vocab_size=512, hidden_size=256, intermediate_size=768,
        num_hidden_layers=4, num_attention_heads=8,
        num_key_value_heads=8, max_position_embeddings=256)
    model = LlamaForCausalLM(cfg)
    rs = np.random.RandomState(0)
    prompts = [rs.randint(0, cfg.vocab_size,
                          (4 + (i % 3) * 6,)).astype(np.int32)
               for i in range(requests)]

    one = ContinuousBatchingEngine(
        model, num_slots=num_slots, max_len=16 + max_new,
        decode_block=decode_block, prompt_buckets=(16,))
    tp = ContinuousBatchingEngine(
        model, num_slots=num_slots, max_len=16 + max_new,
        decode_block=decode_block, prompt_buckets=(16,),
        tp=TPConfig(axes=axes, mesh=mesh))

    def run(engine):
        engine.reset()
        srv = Server(engine)
        rids = [srv.submit(p, max_new_tokens=max_new, arrival_step=i)
                for i, p in enumerate(prompts)]
        t0 = time.perf_counter()
        res = srv.run_until_idle()
        return [res[r] for r in rids], time.perf_counter() - t0

    run(one), run(tp)                       # compile warmup
    ref, dt_one = run(one)

    prev_enabled = metrics.enabled()
    metrics.enable(True)
    try:
        bytes_c = metrics.counter(
            "pt_collectives_bytes_total",
            "payload bytes handed to collectives",
            labels=("op", "mode"))
        calls_c = metrics.counter(
            "pt_collectives_calls_total",
            "host-level collective dispatches", labels=("op", "mode"))
        b0 = bytes_c.value(op="tp_block", mode="tp_graph")
        c0 = calls_c.value(op="tp_block", mode="tp_graph")
        got, dt_tp = run(tp)
        steps = tp.steps           # run() resets the engine counters
        bytes_step = (bytes_c.value(op="tp_block", mode="tp_graph")
                      - b0) / max(steps, 1)
        calls_step = (calls_c.value(op="tp_block", mode="tp_graph")
                      - c0) / max(steps, 1)
    finally:
        metrics.enable(prev_enabled)
    identical = all(np.array_equal(a, b) for a, b in zip(ref, got))

    # the int8 hop only exists in psum mode (exact mode has no
    # reduction to compress) — one short stream + the runtime bound
    p8 = ContinuousBatchingEngine(
        model, num_slots=num_slots, max_len=16 + max_new,
        decode_block=decode_block, prompt_buckets=(16,),
        tp=TPConfig(axes=axes, mode="psum", int8=True, mesh=mesh))
    s8 = Server(p8)
    s8.submit(prompts[0], max_new_tokens=max_new)
    s8.run_until_idle()
    int8_bound = p8.tp_int8_error_bound()

    useful = requests * max_new
    return {
        "serving_tp_devices": tp.tp_degree(),
        "serving_tp_axes": "x".join(str(mesh.shape[a]) for a in axes),
        "serving_tp_bit_identical": bool(identical),
        "serving_tp_tokens_per_sec_1chip": round(useful / dt_one, 1),
        "serving_tp_tokens_per_sec_mesh": round(useful / dt_tp, 1),
        "serving_tp_collective_bytes_per_step": int(bytes_step),
        "serving_tp_collective_calls_per_step": round(calls_step, 2),
        "serving_tp_int8_error_bound": float(int8_bound),
        "serving_tp_decode_compiles": tp.decode_compile_count(),
    }
