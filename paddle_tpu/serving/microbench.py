"""Serving microbenches: tensor-parallel decode (serving/tp.py),
speculative draft-verify decode (serving/spec.py), quantized and
megakernel decode, the multi-tenant front door (serving/frontend.py),
and the disaggregated prefill/decode fleet (serving/fleet.py) — each
A/B'd against the plain engine.

Tensor-parallel stage — the slot-pool decode block sharded
over a device mesh (serving/tp.py) A/B'd against the 1-chip engine.

What the stage pins every round:

- **bit-identity**: the exact-mode sharded greedy stream must equal the
  1-chip stream token-for-token (the TP correctness contract);
- **tokens/s** for both engines — on the CPU lane the "mesh" is
  ``--xla_force_host_platform_device_count`` simulated devices sharing
  one socket, so the sharded number is a plumbing-overhead record, not
  a speedup claim (the speedup exists where the shards are real chips);
- **collective traffic**: logical payload bytes and collective calls
  per decode step, read back from the ``pt_collectives_*`` metrics the
  sharded backend notes per dispatched block;
- **int8 hop**: the psum-mode hidden-state all-reduce compressed with
  the EQuARX wire format, with its runtime-queryable error bound.

Wired into bench.py as the ``serving-tp`` child stage (CPU lane,
non-null on the fallback path like comms/passes/observability; the TPU
child runs it too when its window owns more than one chip).
"""
from __future__ import annotations

import time

import numpy as np

__all__ = ["run_fleet_kill_soak", "run_serving_autoscale_bench",
           "run_serving_disagg_bench",
           "run_serving_failover_bench", "run_serving_frontdoor_bench",
           "run_serving_megakernel_bench",
           "run_serving_prefixcache_bench", "run_serving_quant_bench",
           "run_serving_recovery_bench", "run_serving_spec_bench",
           "run_serving_tp_bench"]


def run_serving_disagg_bench(requests_per_group: int = 6,
                             groups: int = 3, max_new: int = 8,
                             num_slots: int = 2) -> dict:
    """Disaggregated prefill/decode fleet stage (serving/fleet.py +
    handoff.py): a 2-prefill/2-decode paged fleet on a shared-system-
    prompt workload, A/B'd against a single-replica Server and against
    itself with affinity routing off.

    What the stage pins every round:

    - **handoff payload at wire size**: mean KV payload bytes per
      request for the fp32 arena vs the int8 arena on the SAME
      workload — the int8 payload must be ~3.6x smaller (codes +
      scales ship quantized, never dequantized in transit);
    - **fleet-wide prefix cache**: burst hit rate with affinity
      routing (each group's warm system prompt lands where its
      registered blocks live) vs the single-replica rate (gate: >=)
      and vs the same fleet with affinity off (scattered groups pay
      the prefix cold);
    - **disagg-vs-unified TTFT p50 and decode tokens/s**: the
      pipelining record on the CPU lane (the hardware-pool split is a
      TPU-fleet claim; the CPU number tracks overhead);
    - the compile-count pin: ONE decode block per decode worker, ONE
      chunk program per prefill worker, and cross-worker streams
      bit-identical to the unified server.
    """
    import paddle_tpu as paddle
    from paddle_tpu.models.llama import (LlamaForCausalLM,
                                         llama_tiny_config)
    from paddle_tpu.serving import (ContinuousBatchingEngine,
                                    DecodeWorker, Fleet, PrefillWorker,
                                    PrefillPagedEngine, Server)

    paddle.seed(0)
    cfg = llama_tiny_config(tensor_parallel=False)
    model = LlamaForCausalLM(cfg)
    rs = np.random.RandomState(0)
    kw = dict(num_slots=num_slots, max_len=64, decode_block=4,
              block_size=8, prefill_chunk=16)

    # shared-system-prompt workload: each group shares a 16-token
    # prefix (two full blocks); one warm request per group first, so
    # the burst measures the hot-tenant steady state
    sys_ps = [rs.randint(0, cfg.vocab_size, (16,)).astype(np.int32)
              for _ in range(groups)]
    warm = [np.concatenate([sp, rs.randint(0, cfg.vocab_size, (2,))
                            .astype(np.int32)]) for sp in sys_ps]
    burst = [np.concatenate([sys_ps[g], rs.randint(
        0, cfg.vocab_size, (3 + k % 4,)).astype(np.int32)])
        for g in range(groups) for k in range(requests_per_group)]

    def drive(submit, run, engines):
        for p in warm:
            submit(p)
        run()
        pt0 = sum(e.prompt_tokens for e in engines)
        st0 = sum(e.shared_tokens for e in engines)
        rids = [submit(p) for p in burst]
        t0 = time.perf_counter()
        res = run()
        dt = time.perf_counter() - t0
        pt = sum(e.prompt_tokens for e in engines) - pt0
        st = sum(e.shared_tokens for e in engines) - st0
        return rids, res, dt, st / pt

    pf_engines = [PrefillPagedEngine(model, **kw) for _ in range(2)]
    dc_engines = [ContinuousBatchingEngine(model, paged=True, **kw)
                  for _ in range(2)]

    def mk_fleet(affinity, pf_list, dc_list):
        for e in pf_list + dc_list:
            e.reset()
        return Fleet([PrefillWorker(e) for e in pf_list],
                     [DecodeWorker(e) for e in dc_list],
                     affinity=affinity, spill_depth=100)

    # ---- unified single-replica baseline ---------------------------------
    uni_eng = ContinuousBatchingEngine(model, paged=True, **kw)
    uni = Server(uni_eng)
    uni_rids, uni_res, dt_uni, uni_rate = drive(
        lambda p: uni.submit(p, max_new_tokens=max_new),
        lambda: uni.run_until_idle(), [uni_eng])
    uni_ttft = [uni.ttft[r] * 1000 for r in uni_rids if r in uni.ttft]

    # ---- fp32 fleet, affinity on -----------------------------------------
    fleet = mk_fleet(True, pf_engines, dc_engines)
    f_rids, f_res, dt_fleet, fleet_rate = drive(
        lambda p: fleet.submit(p, max_new_tokens=max_new),
        lambda: fleet.run_until_idle(max_ticks=2000),
        [w.engine for w in fleet.prefill])
    identical = all(np.array_equal(f_res[a], uni_res[b])
                    for a, b in zip(f_rids, uni_rids))
    # burst requests only, matching the unified sample (warm requests
    # pay the cold prefix and would bias the fleet p50 upward)
    ttft_ms = [d.server.ttft[r] * 1000 for d in fleet.decode
               for r in f_rids if r in d.server.ttft]
    fst = fleet.stats()
    compiles = (max(d.engine.decode_compile_count()
                    for d in fleet.decode),
                max(w.engine.prefill_compile_count()
                    for w in fleet.prefill))
    kv_fp32 = fst["handoff_kv_bytes_mean"]
    wire_fp32 = fst["handoff_wire_bytes_mean"]

    # ---- same engines, affinity OFF (the A/B) ----------------------------
    off = mk_fleet(False, pf_engines, dc_engines[:1])
    *_, off_rate = drive(
        lambda p: off.submit(p, max_new_tokens=max_new),
        lambda: off.run_until_idle(max_ticks=2000),
        [w.engine for w in off.prefill])

    # ---- int8 fleet: same workload, quantized wire -----------------------
    f8 = Fleet([PrefillWorker(PrefillPagedEngine(
        model, kv_int8=True, **kw))],
        [DecodeWorker(ContinuousBatchingEngine(
            model, paged=True, kv_int8=True, **kw))],
        affinity=True, spill_depth=100)
    drive(lambda p: f8.submit(p, max_new_tokens=max_new),
          lambda: f8.run_until_idle(max_ticks=2000),
          [w.engine for w in f8.prefill])
    kv_int8 = f8.stats()["handoff_kv_bytes_mean"]

    useful = len(burst) * max_new
    return {
        "serving_disagg_workers": "2p+2d",
        "serving_disagg_bit_identical": bool(identical),
        "serving_disagg_handoffs": fst["handoffs"],
        "serving_disagg_handoff_kv_bytes_fp32": kv_fp32,
        "serving_disagg_handoff_kv_bytes_int8": kv_int8,
        "serving_disagg_handoff_int8_ratio": round(
            kv_fp32 / max(kv_int8, 1.0), 2),
        "serving_disagg_handoff_wire_bytes": wire_fp32,
        "serving_disagg_prefix_hit_rate_fleet": round(fleet_rate, 4),
        "serving_disagg_prefix_hit_rate_noaffinity": round(off_rate,
                                                           4),
        "serving_disagg_prefix_hit_rate_single": round(uni_rate, 4),
        "serving_disagg_affinity_ge_single": bool(
            fleet_rate >= uni_rate - 1e-9),
        "serving_disagg_tokens_per_sec": round(useful / dt_fleet, 1),
        "serving_disagg_tokens_per_sec_unified": round(
            useful / dt_uni, 1),
        "serving_disagg_ttft_p50_ms": round(
            float(np.percentile(ttft_ms, 50)), 2) if ttft_ms else None,
        "serving_disagg_ttft_p50_ms_unified": round(
            float(np.percentile(uni_ttft, 50)), 2) if uni_ttft
        else None,
        "serving_disagg_spillovers": fst["spillovers"],
        "serving_disagg_decode_compiles": compiles[0],
        "serving_disagg_prefill_compiles": compiles[1],
    }


def run_serving_prefixcache_bench(max_new: int = 8,
                                  sys_len: int = 192,
                                  tail_len: int = 7) -> dict:
    """Fleet-wide KV prefix cache stage (serving/prefix_cache.py):
    cold vs warm-local vs warm-remote TTFT on a shared-system-prompt
    workload, plus the bytes-moved-vs-flops-saved accounting that IS
    the feature's economic claim.

    What the stage pins every round:

    - **TTFT ladder**: the same system prompt served (a) cold — full
      chunked prefill, (b) warm-LOCAL — the PR 4 index covers the
      prefix on the admitting worker, (c) warm-REMOTE — another worker
      holds the warm copy and the admitting worker fetches it over the
      ``#fetch`` side channel, then prefills only the tail. Gate (in
      bench.py): warm-remote strictly beats cold — a fetch must cost
      less than the prefill it saves, or the tier is pointless;
    - **bytes moved vs flops saved**: wire KV bytes per fetch against
      ``~2 * n_params * covered_tokens`` of skipped prefill compute —
      the trade the directory arbitrates;
    - **counters from the metrics registry** (fetches / fetched blocks
      / failures / duplicates / evictions) — the observability
      satellite read back the way an operator would read it;
    - the compile pin: decode and prefill compile counts stay 1 on
      every worker — the fetch adopts through the shared scatter
      program, never a new steady-path program.

    A warm-up round on a DIFFERENT system prompt first compiles every
    program (chunk prefill, decode block, adopt + fetch scatter), so
    the measured TTFTs compare compute, not compilation. The default
    system prefix is 24 blocks (192 tokens) — long enough that the
    saved chunk dispatches dominate the fixed per-fetch cost
    (serialize + CRC + one scatter) even on the CPU lane.
    """
    import paddle_tpu as paddle
    from paddle_tpu.models.llama import (LlamaForCausalLM,
                                         llama_tiny_config)
    from paddle_tpu.observability import metrics as om
    from paddle_tpu.serving import (ContinuousBatchingEngine,
                                    DecodeWorker, Fleet, PrefillWorker,
                                    PrefillPagedEngine)
    from paddle_tpu.serving import prefix_cache as pc

    paddle.seed(0)
    om.reset()
    om.enable(True)
    cfg = llama_tiny_config(tensor_parallel=False)
    model = LlamaForCausalLM(cfg)
    rs = np.random.RandomState(0)
    kw = dict(num_slots=2, max_len=256, decode_block=4, block_size=8,
              prefill_chunk=8)
    pf = [PrefillPagedEngine(model, **kw) for _ in range(2)]
    dc = [ContinuousBatchingEngine(model, paged=True, **kw)
          for _ in range(2)]
    fleet = Fleet([PrefillWorker(e) for e in pf],
                  [DecodeWorker(e) for e in dc])

    def prompt(sys_p):
        return np.concatenate(
            [sys_p, rs.randint(0, cfg.vocab_size,
                               (tail_len,)).astype(np.int32)])

    def ttft_ms(rid):
        for d in fleet.decode:
            if rid in d.server.ttft:
                return d.server.ttft[rid] * 1000.0
        return None

    def serve(p, worker):
        rid = fleet.submit(p, max_new_tokens=max_new,
                           prefill_worker=worker)
        res = fleet.run_until_idle(max_ticks=2000)
        return rid, res[rid], ttft_ms(rid)

    # ---- warm-up: compile every program incl. the fetch scatter ----------
    sys_w = rs.randint(0, cfg.vocab_size, (sys_len,)).astype(np.int32)
    serve(prompt(sys_w), "prefill0")
    serve(prompt(sys_w), "prefill1")        # first fetch: compiles
    warmup_fetches = fleet.prefix_fetches

    # ---- the measured ladder on a fresh system prompt --------------------
    sys_m = rs.randint(0, cfg.vocab_size, (sys_len,)).astype(np.int32)
    _, _, cold_ms = serve(prompt(sys_m), "prefill0")         # cold
    _, _, local_ms = serve(prompt(sys_m), "prefill0")        # warm-local
    p_rem = prompt(sys_m)
    rr, out_r, remote_ms = serve(p_rem, "prefill1")          # warm-remote
    ref = model.generate(paddle.to_tensor(p_rem[None, :]),
                         max_new_tokens=max_new,
                         temperature=0.0).numpy()[0]
    identical = bool(np.array_equal(out_r, ref))

    fetches = fleet.prefix_fetches - warmup_fetches
    kv_bytes = fleet.prefix_fetch_kv_bytes[warmup_fetches:]
    covered = sum(e.fetched_tokens for e in pf)
    n_params = int(model.num_params())
    flops_saved = 2 * n_params * covered
    bytes_moved = int(np.sum(kv_bytes)) if kv_bytes else 0
    fst = fleet.stats()
    out = {
        "serving_prefixcache_bit_identical": identical,
        "serving_prefixcache_ttft_cold_ms": round(cold_ms, 2),
        "serving_prefixcache_ttft_warm_local_ms": round(local_ms, 2),
        "serving_prefixcache_ttft_warm_remote_ms": round(remote_ms, 2),
        "serving_prefixcache_remote_vs_cold_speedup": round(
            cold_ms / max(remote_ms, 1e-9), 2),
        "serving_prefixcache_fetches": fetches,
        "serving_prefixcache_fetch_kv_bytes_mean": round(
            float(np.mean(kv_bytes)), 1) if kv_bytes else 0.0,
        "serving_prefixcache_bytes_moved": bytes_moved,
        "serving_prefixcache_covered_tokens": covered,
        "serving_prefixcache_flops_saved": flops_saved,
        "serving_prefixcache_flops_per_wire_byte": round(
            flops_saved / bytes_moved, 1) if bytes_moved else None,
        "serving_prefixcache_fetch_counter": int(
            pc._M_FETCHES.value()),
        "serving_prefixcache_fail_counters": {
            k: int(v) for k, v in
            fst["prefix_fetch_failures"].items()},
        "serving_prefixcache_duplicates": fst[
            "prefix_fetch_duplicates"],
        "serving_prefixcache_evictions": fst["prefix_evictions"],
        "serving_prefixcache_directory_entries": fst[
            "prefix_directory"]["entries"],
        "serving_prefixcache_decode_compiles": max(
            e.decode_compile_count() for e in dc),
        "serving_prefixcache_prefill_compiles": max(
            e.prefill_compile_count() for e in pf),
    }
    om.reset()
    om.enable(False)
    return out


def run_serving_failover_bench(requests: int = 6, max_new: int = 24,
                               num_slots: int = 2,
                               kill_after: int = 3) -> dict:
    """Fleet failure-domain stage (serving/transport.py + fleet.py):
    kill-one-decode-worker A/B on a paged 2-prefill/2-decode fleet
    over the REAL localhost-TCP SocketTransport with ~1% wire faults
    armed (partial_write/corrupt/disconnect).

    What the stage pins every round:

    - **recovered-stream bit-identity**: every stream of the killed
      run — including the redriven ones, greedy AND seeded-sampled —
      token-equal to the clean (unfailed) run of the same workload;
    - **redrive latency p50/p95**: wall time from lease-expiry
      detection to the redriven stream's terminal;
    - **goodput with and without the mid-run kill**: completed useful
      tokens/s A/B — the cost of losing (and re-homing) a failure
      domain mid-traffic;
    - **handoff retry/dedup counters from the metrics registry**:
      transport resends/reconnects/CRC drops, fleet handoff retries,
      and (rid, seq)-deduplicated adopts;
    - the compile-count pin: the surviving decode worker's ONE block
      (redrive arms through the existing programs, zero new compiles).
    """
    import paddle_tpu as paddle
    from paddle_tpu.models.llama import (LlamaForCausalLM,
                                         llama_tiny_config)
    from paddle_tpu.observability import metrics as om
    from paddle_tpu.serving import (ContinuousBatchingEngine,
                                    DecodeWorker, Fleet, PrefillWorker,
                                    PrefillPagedEngine, RequestFailure,
                                    SocketTransport)
    from paddle_tpu.serving import fleet as fleet_mod
    from paddle_tpu.serving import transport as transport_mod
    from paddle_tpu.utils import faults

    paddle.seed(0)
    cfg = llama_tiny_config(tensor_parallel=False)
    model = LlamaForCausalLM(cfg)
    rs = np.random.RandomState(0)
    kw = dict(num_slots=num_slots, max_len=64, decode_block=4,
              block_size=8, prefill_chunk=16)
    prompts = [rs.randint(0, cfg.vocab_size,
                          (int(rs.randint(5, 14)),)).astype(np.int32)
               for _ in range(requests)]
    news = [max_new - (i % 3) * 2 for i in range(requests)]
    sampled = [i % 3 == 1 for i in range(requests)]

    pf_engines = [PrefillPagedEngine(model, **kw) for _ in range(2)]
    dc_engines = [ContinuousBatchingEngine(model, paged=True, **kw)
                  for _ in range(2)]

    def drive(kill: bool):
        for e in pf_engines + dc_engines:
            e.reset()
        t = SocketTransport("fleet", retry_backoff_s=0.001)
        fleet = Fleet([PrefillWorker(e) for e in pf_engines],
                      [DecodeWorker(e) for e in dc_engines],
                      transport=t, lease_misses=2, spill_depth=100)
        rids = []
        for i, (p, mn) in enumerate(zip(prompts, news)):
            skw = dict(temperature=0.9, top_k=40, seed=100 + i) \
                if sampled[i] else {}
            rids.append(fleet.submit(p, max_new_tokens=mn, **skw))
        t0 = time.perf_counter()
        spec = ("transport.partial_write:p=0.01;transport.corrupt:"
                "p=0.01;transport.disconnect:p=0.01")
        with faults.injected(spec if kill else "", seed=7):
            if kill:
                for _ in range(kill_after):
                    fleet.tick()
                fleet.kill_decode_worker(1)
            res = fleet.run_until_idle(max_ticks=2000)
        dt = time.perf_counter() - t0
        done = sum(news[i] for i, r in enumerate(rids)
                   if not isinstance(res.get(r), RequestFailure))
        out = ([res[r] if not isinstance(res[r], RequestFailure)
                else None for r in rids], done / dt, fleet.stats())
        t.close()
        return out

    drive(kill=False)                # warm-up: compiles land here, so
    om.reset()                       # the A/B compares steady states
    om.enable(True)
    try:
        clean_rows, clean_goodput, _ = drive(kill=False)
        kill_rows, kill_goodput, kst = drive(kill=True)
    finally:
        om.enable(False)
    identical = all(a is not None and b is not None
                    and np.array_equal(a, b)
                    for a, b in zip(clean_rows, kill_rows))
    lat = kst["redrive_latency_p50_s"]
    lat95 = kst["redrive_latency_p95_s"]
    return {
        "serving_failover_workers": "2p+2d",
        "serving_failover_bit_identical": bool(identical),
        "serving_failover_workers_lost": kst["workers_lost"],
        "serving_failover_redrives": kst["redrives"],
        "serving_failover_redrive_latency_p50_ms": round(
            lat * 1000, 2) if lat is not None else 0.0,
        "serving_failover_redrive_latency_p95_ms": round(
            lat95 * 1000, 2) if lat95 is not None else 0.0,
        "serving_failover_goodput_tokens_per_sec": round(
            kill_goodput, 1),
        "serving_failover_goodput_tokens_per_sec_clean": round(
            clean_goodput, 1),
        "serving_failover_goodput_ratio": round(
            kill_goodput / clean_goodput, 3) if clean_goodput else 0.0,
        # the registry's view (both runs; the kill run armed it)
        "serving_failover_handoff_retries": int(
            fleet_mod._M_FLEET_RETRIES.value()),
        "serving_failover_duplicate_adopts": int(
            fleet_mod._M_ADOPT_DUPS.value()),
        "serving_failover_transport_resends": int(
            transport_mod._M_RESENDS.value()),
        "serving_failover_transport_crc_drops": int(
            transport_mod._M_CRC_DROPS.value()),
        "serving_failover_transport_reconnects": int(
            transport_mod._M_RECONNECTS.value()),
        "serving_failover_decode_compiles": max(
            e.decode_compile_count() for e in dc_engines),
    }


def run_fleet_kill_soak(seed: int = 0, kills: int = 2,
                        requests: int = 12, max_new: int = 16,
                        wire_fault_p: float = 0.01) -> dict:
    """Seeded worker-kill chaos soak (tools/chaos.sh): K decode-worker
    kills at seeded ticks over one traffic run on the socket
    transport with wire faults armed; after each kill a fresh decode
    worker scales in (``add_decode_worker``) so capacity survives the
    schedule. Asserts every request completed-or-explicitly-failed,
    completed greedy rows bit-identical to generate(), and zero block
    leaks on every surviving arena (prefill AND decode)."""
    import paddle_tpu as paddle
    from paddle_tpu.models.llama import (LlamaForCausalLM,
                                         llama_tiny_config)
    from paddle_tpu.serving import (ContinuousBatchingEngine,
                                    DecodeWorker, Fleet, PrefillWorker,
                                    PrefillPagedEngine, RequestFailure,
                                    SocketTransport)
    from paddle_tpu.utils import faults

    paddle.seed(0)
    cfg = llama_tiny_config(tensor_parallel=False)
    model = LlamaForCausalLM(cfg)
    rs = np.random.RandomState(seed)
    kw = dict(num_slots=2, max_len=64, decode_block=4, block_size=8,
              prefill_chunk=8)
    prompts = [rs.randint(0, cfg.vocab_size,
                          (int(rs.randint(4, 14)),)).astype(np.int32)
               for _ in range(requests)]
    news = [max_new - int(rs.randint(0, 8)) for _ in range(requests)]
    kill_ticks = sorted(int(t) for t in rs.randint(2, 14, size=kills))

    t = SocketTransport("fleet", retry_backoff_s=0.001)
    fleet = Fleet(
        [PrefillWorker(PrefillPagedEngine(model, **kw))
         for _ in range(2)],
        [DecodeWorker(ContinuousBatchingEngine(model, paged=True,
                                               **kw))
         for _ in range(2)],
        transport=t, lease_misses=2, spill_depth=100)
    rids = [fleet.submit(p, max_new_tokens=mn, arrival_step=i % 4)
            for i, (p, mn) in enumerate(zip(prompts, news))]
    spec = (f"transport.partial_write:p={wire_fault_p};"
            f"transport.corrupt:p={wire_fault_p};"
            f"transport.disconnect:p={wire_fault_p}")
    killed = 0
    next_name = len(fleet.decode)
    with faults.injected(spec, seed=seed):
        ticks = 0
        while fleet.busy() and ticks < 3000:
            fleet.tick()
            ticks += 1
            if killed < kills and ticks >= kill_ticks[killed]:
                victims = [i for i, d in enumerate(fleet.decode)
                           if not d.killed]
                vi = victims[int(rs.randint(0, len(victims)))]
                fleet.kill_decode_worker(vi)
                killed += 1
                fleet.add_decode_worker(DecodeWorker(
                    ContinuousBatchingEngine(model, paged=True, **kw),
                    name=f"decode{next_name}"))
                next_name += 1
        res = fleet.results
    completed = failed = 0
    for rid, p, mn in zip(rids, prompts, news):
        assert rid in res, f"request {rid} vanished"
        v = res[rid]
        if isinstance(v, RequestFailure):
            assert v.reason in ("timeout", "poisoned", "circuit_open",
                                "shed", "handoff", "worker_lost"), \
                f"{rid}: unexpected reason {v.reason}"
            failed += 1
        else:
            ref = model.generate(paddle.to_tensor(p[None, :]),
                                 max_new_tokens=mn).numpy()[0]
            assert np.array_equal(v, ref), \
                f"completed stream {rid} not bit-identical"
            completed += 1
    # zero leaks on every surviving arena, both specialties
    for w in fleet.prefill:
        if fleet._alive(w.name) and hasattr(w.engine, "manager"):
            assert not w.engine.manager._ref
            w.engine.manager.assert_consistent()
    for d in fleet.decode:
        if fleet._alive(d.name) and hasattr(d.engine, "manager"):
            assert not d.engine.manager._ref
            d.engine.manager.assert_consistent()
    st = fleet.stats()
    t.close()
    return {
        "soak_seed": seed, "soak_kills": killed,
        "soak_requests": requests, "soak_completed": completed,
        "soak_failed": failed, "soak_redrives": st["redrives"],
        "soak_workers_lost": st["workers_lost"],
        "soak_duplicate_adopts": st["duplicate_adopts"],
        "soak_transport": st["transport"], "soak_ticks": st["ticks"],
        "soak_leaks": 0,
    }


def run_serving_frontdoor_bench(requests_per_tenant: int = 18,
                                max_new: int = 8, num_slots: int = 4,
                                decode_block: int = 4) -> dict:
    """Multi-tenant front-door stage (serving/frontend.py): weighted-
    fair shares, priority preemption, and per-priority TTFT on the
    paged engine.

    What the stage pins every round:

    - **fairness**: a saturated 3-tenant workload (weights 1:2:3, equal
      request shapes) measured via the streaming sink's per-tenant
      token tallies while every tenant is still backlogged — measured
      throughput shares must sit within 10% of the configured weights;
    - **preemption**: a pool full of low-priority decodes evicted by a
      high-priority burst — preemption count, the evicted requests
      still completing (no starvation), and their outputs BIT-IDENTICAL
      to an uninterrupted run (the resume-correctness contract);
    - **TTFT p50/p95 split by priority**: the latency win preemption
      buys the high tier while the low tier still finishes;
    - the compile-count pin: ONE decode block + ONE chunk program
      across fairness, evictions and resumes (no new compiled
      programs).
    """
    import paddle_tpu as paddle
    from paddle_tpu.models.llama import (LlamaForCausalLM,
                                         llama_tiny_config)
    from paddle_tpu.serving import (ContinuousBatchingEngine, Frontend,
                                    TenantConfig)

    paddle.seed(0)
    cfg = llama_tiny_config(tensor_parallel=False)
    model = LlamaForCausalLM(cfg)
    rs = np.random.RandomState(0)
    weights = {"bronze": 1.0, "silver": 2.0, "gold": 3.0}
    engine = ContinuousBatchingEngine(
        model, num_slots=num_slots, max_len=64,
        decode_block=decode_block, paged=True, block_size=8,
        prefill_chunk=16)

    # ---- phase 1: weighted-fair shares under saturation ------------------
    fe = Frontend(engine, tenants={t: TenantConfig(weight=w)
                                   for t, w in weights.items()},
                  preemption=True)
    for i in range(requests_per_tenant):
        for t in weights:
            p = rs.randint(0, cfg.vocab_size, (6,)).astype(np.int32)
            fe.submit(p, tenant=t, max_new_tokens=max_new)

    def outstanding(t):
        c = fe.server.tenant_counts.get(t, {})
        return c.get("submitted", 0) - c.get("completed", 0) \
            - c.get("failed", 0)

    t0 = time.perf_counter()
    # measure only while EVERY tenant is backlogged: the share claim is
    # about contention, not about who finishes first
    while all(outstanding(t) > 0 for t in weights) and fe.pump():
        pass
    dt_shares = time.perf_counter() - t0
    streamed = dict(fe.tenant_tokens)
    total = max(sum(streamed.values()), 1)
    wsum = sum(weights.values())
    shares = {t: streamed.get(t, 0) / total for t in weights}
    expected = {t: w / wsum for t, w in weights.items()}
    rel_err = max(abs(shares[t] - expected[t]) / expected[t]
                  for t in weights)
    fe.run_until_idle()                     # drain the tail

    # ---- phase 2: priority preemption + per-priority TTFT ----------------
    prompts = [rs.randint(0, cfg.vocab_size,
                          (5 + (i % 3) * 4,)).astype(np.int32)
               for i in range(num_slots)]
    hi_prompts = [rs.randint(0, cfg.vocab_size, (6,)).astype(np.int32)
                  for _ in range(2)]

    def low_refs():
        engine.reset()
        ref_fe = Frontend(engine)
        rids = [ref_fe.submit(p, max_new_tokens=24) for p in prompts]
        res = ref_fe.run_until_idle()
        return [res[r] for r in rids]

    ref = low_refs()                        # uninterrupted twins

    def burst(preempt):
        engine.reset()
        f = Frontend(engine, preemption=preempt)
        lo = [f.submit(p, max_new_tokens=24, priority=0)
              for p in prompts]
        for _ in range(3):
            f.pump()                        # pool fully decoding
        hi_ = [f.submit(p, max_new_tokens=6, priority=5)
               for p in hi_prompts]
        return f, lo, hi_, f.run_until_idle()

    # the A/B that makes the TTFT split meaningful: the same
    # high-priority burst lands on the same busy pool, with and
    # without the eviction policy. One warmup pass first — the first
    # eviction ever compiles the (tiny) slot-cancel program, which
    # would otherwise land inside the preemption side's TTFT
    burst(True)
    fe_off, _, hi_off, _ = burst(False)
    fe2, low, hi, res = burst(True)
    st = fe2.stats()
    identical = all(np.array_equal(res[r], a)
                    for r, a in zip(low, ref))

    def ttft_ms(frontend, rids, q):
        vals = [frontend.server.ttft[r] * 1000 for r in rids
                if r in frontend.server.ttft]
        return round(float(np.percentile(vals, q)), 2) if vals else None

    return {
        "serving_frontdoor_weights": {t: w for t, w in weights.items()},
        "serving_frontdoor_share_bronze": round(shares["bronze"], 4),
        "serving_frontdoor_share_silver": round(shares["silver"], 4),
        "serving_frontdoor_share_gold": round(shares["gold"], 4),
        "serving_frontdoor_share_max_rel_err": round(rel_err, 4),
        "serving_frontdoor_shares_within_10pct": bool(rel_err <= 0.10),
        "serving_frontdoor_fair_tokens_per_sec": round(
            total / dt_shares, 1),
        "serving_frontdoor_preemptions": st["preemptions"],
        "serving_frontdoor_resumes": st["resumes"],
        "serving_frontdoor_bit_identical": bool(identical),
        "serving_frontdoor_ttft_p50_ms_high": ttft_ms(fe2, hi, 50),
        "serving_frontdoor_ttft_p95_ms_high": ttft_ms(fe2, hi, 95),
        "serving_frontdoor_ttft_p50_ms_high_nopreempt":
            ttft_ms(fe_off, hi_off, 50),
        "serving_frontdoor_ttft_p95_ms_high_nopreempt":
            ttft_ms(fe_off, hi_off, 95),
        "serving_frontdoor_ttft_p50_ms_low": ttft_ms(fe2, low, 50),
        "serving_frontdoor_ttft_p95_ms_low": ttft_ms(fe2, low, 95),
        "serving_frontdoor_decode_compiles":
            engine.decode_compile_count(),
        "serving_frontdoor_prefill_compiles":
            engine.prefill_compile_count(),
    }


def run_serving_megakernel_bench(requests: int = 8, max_new: int = 32,
                                 num_slots: int = 8,
                                 decode_block: int = 8) -> dict:
    """Fused decode-layer A/B: the megakernel engine (decode-fusion
    pass + ops/pallas/decode_layer.py) against the plain paged+int8-KV
    engine on the SAME greedy stream.

    What the stage pins every round:

    - **bit-identity**: fused greedy streams must equal the unfused
      engine's token-for-token (on the CPU lane the fused call's body
      IS the captured unfused jaxpr, so this pins the pass/splice
      plumbing; on TPU the same gate pins the kernel's numerics
      against greedy argmax);
    - **decode tokens/s A/B** — an overhead record on the CPU lane
      (same math, one extra call boundary); the HBM win belongs to the
      TPU child, where the fused program stops round-tripping the
      hidden state between attention/o_proj/MLP;
    - **the no-transient jaxpr walk**: the transformed decode-block
      program must hold NO fp32 hidden-state interior ((S, 1, ff) MLP
      activation, (S, kvh, g, dh) attention internals) outside the
      fused calls — the structural form of the VMEM-residency claim;
    - rewrite/kernel-call counts from the pass, and the compile-count
      pin (ONE decode program).
    """
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.passes.fusion_decode import (fused_decode_calls,
                                                 walk_outside_fused)
    from paddle_tpu.serving import ContinuousBatchingEngine, Server

    paddle.seed(0)
    cfg = LlamaConfig(
        vocab_size=512, hidden_size=256, intermediate_size=768,
        num_hidden_layers=4, num_attention_heads=8,
        num_key_value_heads=8, max_position_embeddings=256,
        tensor_parallel=False)
    model = LlamaForCausalLM(cfg)
    rs = np.random.RandomState(0)
    prompts = [rs.randint(0, cfg.vocab_size,
                          (8 + (i % 3) * 8,)).astype(np.int32)
               for i in range(requests)]
    max_len = -(-(32 + max_new) // 16) * 16
    kw = dict(num_slots=num_slots, max_len=max_len,
              decode_block=decode_block, paged=True, block_size=16,
              prefill_chunk=32, kv_int8=True)
    plain = ContinuousBatchingEngine(model, megakernel=False, **kw)
    mega = ContinuousBatchingEngine(model, megakernel=True, **kw)

    def run(engine):
        engine.reset()
        srv = Server(engine)
        rids = [srv.submit(p, max_new_tokens=max_new) for p in prompts]
        t0 = time.perf_counter()
        res = srv.run_until_idle()
        return [res[r] for r in rids], time.perf_counter() - t0

    run(plain), run(mega)                   # compile warmup
    ref, dt_plain = run(plain)
    got, dt_mega = run(mega)
    identical = all(np.array_equal(a, b) for a, b in zip(ref, got))

    # the no-transient walk over the TRANSFORMED decode-block program
    closed = mega.backend._block_jit._closed
    S = num_slots
    kvh = cfg.num_key_value_heads
    g = cfg.num_attention_heads // kvh
    dh = cfg.hidden_size // cfg.num_attention_heads
    banned = {(S, 1, cfg.intermediate_size), (S, kvh, g, dh)}
    outside = set()
    for eqn in walk_outside_fused(closed):
        for v in eqn.outvars:
            aval = getattr(v, "aval", None)
            if aval is not None and \
                    getattr(aval, "dtype", None) == jnp.float32:
                outside.add(tuple(aval.shape))
    no_transient = not (outside & banned)

    useful = requests * max_new
    return {
        "serving_megakernel_bit_identical": bool(identical),
        "serving_megakernel_tokens_per_sec_unfused":
            round(useful / dt_plain, 1),
        "serving_megakernel_tokens_per_sec":
            round(useful / dt_mega, 1),
        "serving_megakernel_speedup": round(dt_plain / dt_mega, 3),
        "serving_megakernel_rewrites": mega.megakernel_rewrites(),
        "serving_megakernel_kernel_calls":
            mega.megakernel_kernel_calls(),
        "serving_megakernel_fused_calls_in_program":
            len(fused_decode_calls(closed)),
        "serving_megakernel_no_hidden_state_transient":
            bool(no_transient),
        "serving_megakernel_decode_compiles":
            mega.decode_compile_count(),
    }


def run_serving_quant_bench(requests: int = 8, max_new: int = 48,
                            num_slots: int = 8, decode_block: int = 8,
                            weights: str = "int8") -> dict:
    """Bandwidth-true quantized serving A/B: the fully quantized paged
    engine (int8 KV arena + weight-only ``weights`` decode weights,
    dequant inside the read/gemm) against the fp32 paged engine on the
    SAME greedy stream.

    What the stage pins every round:

    - **decode tokens/s A/B** — the ROADMAP gate is that quantization
      moves tokens/s, not just bytes/slot. On the CPU lane the arena is
      host RAM and the dequant costs real VPU-less cycles, so the CPU
      number is an overhead record (the speedup claim belongs to the
      TPU child, where decode is HBM-bandwidth-bound and bytes ARE
      time);
    - **bytes-read/step accounting** from the metrics registry
      (``pt_serving_decode_bytes_read_total`` per engine step): the
      quant engine must read ~3-4x fewer bytes per decode step;
    - **both error bounds** (``engine.quant_error_bound()``): the
      runtime EQuARX KV bound and the build-time weight bound;
    - **token agreement** with the fp32 stream (reported, not gated —
      quantized logits legitimately diverge within the bounds);
    - the compile-count pin (ONE decode program per engine).
    """
    import paddle_tpu as paddle
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.observability import metrics
    from paddle_tpu.serving import (ContinuousBatchingEngine,
                                    QuantConfig, Scheduler, Server)

    paddle.seed(0)
    cfg = LlamaConfig(
        vocab_size=512, hidden_size=256, intermediate_size=768,
        num_hidden_layers=4, num_attention_heads=8,
        num_key_value_heads=8, max_position_embeddings=256,
        tensor_parallel=False)
    model = LlamaForCausalLM(cfg)
    rs = np.random.RandomState(0)
    prompts = [rs.randint(0, cfg.vocab_size,
                          (8 + (i % 3) * 8,)).astype(np.int32)
               for i in range(requests)]
    max_len = -(-(32 + max_new) // 16) * 16      # block_size multiple

    # the baseline pins BOTH halves fp32 explicitly — an armed
    # PT_SERVING_QUANT_WEIGHTS / PT_SERVING_KV_INT8 in the operator's
    # shell must not silently quantize it into a quant-vs-quant A/B
    fp32 = ContinuousBatchingEngine(
        model, num_slots=num_slots, max_len=max_len,
        decode_block=decode_block, paged=True, block_size=16,
        prefill_chunk=32, kv_int8=False, quant=False)
    quant = ContinuousBatchingEngine(
        model, num_slots=num_slots, max_len=max_len,
        decode_block=decode_block, paged=True, block_size=16,
        prefill_chunk=32, kv_int8=True,
        quant=QuantConfig(weights=weights))

    def run(engine):
        engine.reset()
        srv = Server(engine, Scheduler())
        rids = [srv.submit(p, max_new_tokens=max_new) for p in prompts]
        t0 = time.perf_counter()
        res = srv.run_until_idle()
        return [res[r] for r in rids], time.perf_counter() - t0

    run(fp32), run(quant)                   # compile warmup

    prev_enabled = metrics.enabled()
    metrics.enable(True)
    try:
        # registered at serving import (engine.py) — fetch, don't
        # re-declare (a drifting copy of the help string would be
        # silently ignored by get-or-create)
        bytes_c = metrics.REGISTRY.get(
            "pt_serving_decode_bytes_read_total")
        b0 = bytes_c.value()
        ref, dt_fp32 = run(fp32)
        bytes_fp32 = (bytes_c.value() - b0) / max(fp32.steps, 1)
        b0 = bytes_c.value()
        got, dt_quant = run(quant)
        bytes_quant = (bytes_c.value() - b0) / max(quant.steps, 1)
    finally:
        metrics.enable(prev_enabled)
    # GENERATED tokens only — results are prompt + generated rows, and
    # counting the identical-by-construction prompt prefix inflates
    # the agreement number
    agree = float(np.mean([np.mean(a[len(p):] == b[len(p):])
                           for a, b, p in zip(ref, got, prompts)]))
    bounds = quant.quant_error_bound()

    useful = requests * max_new
    return {
        "serving_quant_weights": weights,
        "serving_quant_kv": "int8",
        "serving_quant_tokens_per_sec_fp32": round(useful / dt_fp32, 1),
        "serving_quant_tokens_per_sec": round(useful / dt_quant, 1),
        "serving_quant_speedup": round(dt_fp32 / dt_quant, 3),
        "serving_quant_bytes_per_step_fp32": int(bytes_fp32),
        "serving_quant_bytes_per_step": int(bytes_quant),
        "serving_quant_bytes_ratio": round(
            bytes_fp32 / max(bytes_quant, 1), 2),
        "serving_quant_kv_error_bound": round(bounds["kv"], 6),
        "serving_quant_weight_error_bound": round(bounds["weights"], 6),
        "serving_quant_token_agreement": round(agree, 4),
        "serving_quant_decode_compiles": quant.decode_compile_count(),
    }


def run_serving_spec_bench(requests: int = 8, max_new: int = 64,
                           num_slots: int = 8, k: int = 8,
                           decode_block: int = 8,
                           warm_tokens: int = 32,
                           candidate_tokens: int = 96) -> dict:
    """Speculative-decode A/B: the draft-verify engine
    (``spec=SpecConfig(k=...)``) against the plain slot-pool engine on
    the SAME stream of repetitive continuations — prompt-lookup's
    target case (templated/self-repetitive text: code edits, RAG,
    form letters). The workload is built from the model itself: one
    batched generate scans ``candidate_tokens`` single-token prompts,
    the ``requests`` most lookup-predictable streams are selected, and
    each request's prompt carries the stream's first ``warm_tokens``
    generated tokens so decoding resumes mid-cycle (the drafter locks
    on immediately — acceptance is reported, not assumed).

    What the stage pins every round:

    - **bit-identity**: spec-mode greedy streams must equal the plain
      engine's token-for-token (the correctness contract);
    - **decode tokens/s A/B** + speedup (CPU-lane gate: >= 1.3x at
      this config; the 2-3x target belongs to the TPU lane, where the
      (S, k+1) verify forward re-reads weights once instead of k+1
      times per emitted token);
    - **acceptance rate** and **mean accepted draft tokens per verify
      step** — the two knobs the speedup decomposes into;
    - the compile-count pin (ONE verify program).
    """
    import paddle_tpu as paddle
    from paddle_tpu.models.llama import (LlamaForCausalLM,
                                         llama_tiny_config)
    from paddle_tpu.serving import (ContinuousBatchingEngine, Server,
                                    SpecConfig, ngram_propose)

    paddle.seed(0)
    cfg = llama_tiny_config(tensor_parallel=False)
    model = LlamaForCausalLM(cfg)

    # ONE batched generate over the candidate streams; predictability
    # is scored over exactly the window the bench will decode
    ids = np.tile(np.arange(candidate_tokens, dtype=np.int32)[:, None],
                  (1, 24))
    full = model.generate(paddle.to_tensor(ids),
                          max_new_tokens=warm_tokens + max_new).numpy()
    cut = 24 + warm_tokens

    def lookup_score(row) -> float:
        hist, gen = list(row[:cut]), row[cut:]
        acc = i = 0
        while i < len(gen):
            prop = ngram_propose(np.asarray(hist), k, 4, 1)
            a = 0
            for j in range(prop.size):
                if i + j < len(gen) and prop[j] == gen[i + j]:
                    a += 1
                else:
                    break
            for j in range(min(a + 1, len(gen) - i)):
                hist.append(int(gen[i + j]))
            acc += a
            i += a + 1
        return acc / max(len(gen), 1)

    scores = np.asarray([lookup_score(full[v])
                         for v in range(candidate_tokens)])
    top = np.argsort(scores, kind="stable")[::-1][:requests]
    prompts = [full[t][:cut].astype(np.int32) for t in top]
    max_len = cut + max_new + 8

    base = ContinuousBatchingEngine(
        model, num_slots=num_slots, max_len=max_len,
        decode_block=decode_block, prompt_buckets=(cut,))
    spec = ContinuousBatchingEngine(
        model, num_slots=num_slots, max_len=max_len,
        decode_block=decode_block, prompt_buckets=(cut,),
        spec=SpecConfig(k=k, ngram_max=4))

    def run(engine):
        engine.reset()
        srv = Server(engine)
        rids = [srv.submit(p, max_new_tokens=max_new) for p in prompts]
        t0 = time.perf_counter()
        res = srv.run_until_idle()
        return [res[r] for r in rids], time.perf_counter() - t0

    run(base), run(spec)                    # compile warmup
    ref, dt_base = run(base)
    got, dt_spec = run(spec)
    identical = all(np.array_equal(a, b) for a, b in zip(ref, got))

    useful = requests * max_new
    return {
        "serving_spec_k": k,
        "serving_spec_bit_identical": bool(identical),
        "serving_spec_tokens_per_sec_baseline": round(useful / dt_base,
                                                      1),
        "serving_spec_tokens_per_sec": round(useful / dt_spec, 1),
        "serving_spec_speedup": round(dt_base / dt_spec, 3),
        "serving_spec_acceptance_rate": round(spec.acceptance_rate(),
                                              4),
        "serving_spec_mean_accepted_per_step": round(
            spec.mean_accepted_per_step(), 3),
        "serving_spec_tokens_per_step": round(
            useful / max(spec.verify_steps, 1), 2),
        "serving_spec_verify_steps": spec.verify_steps,
        "serving_spec_workload_lookup_score": round(
            float(scores[top].mean()), 3),
        "serving_spec_decode_compiles": spec.decode_compile_count(),
    }


def run_serving_tp_bench(requests: int = 6, max_new: int = 16,
                         num_slots: int = 2, decode_block: int = 4
                         ) -> dict:
    import jax

    import paddle_tpu as paddle
    from paddle_tpu.distributed.mesh import build_device_mesh
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.observability import metrics
    from paddle_tpu.serving import (ContinuousBatchingEngine, Server,
                                    TPConfig)

    n_dev = jax.device_count()
    if n_dev < 2:
        return {"serving_tp_devices": n_dev,
                "serving_tp_skipped": "needs >= 2 devices "
                "(simulated or real) to shard the decode block"}
    # widest 2-level mesh the device count allows: 2 x (n/2) exercises
    # the hierarchical inner/outer plan; an odd count falls back flat
    if n_dev % 2 == 0:
        mesh = build_device_mesh({"dp": 2, "mp": n_dev // 2})
        axes = ("dp", "mp")
    else:
        mesh = build_device_mesh({"dp": 1, "mp": n_dev},
                                 allow_subset=True)
        axes = ("mp",)

    paddle.seed(0)
    cfg = LlamaConfig(
        vocab_size=512, hidden_size=256, intermediate_size=768,
        num_hidden_layers=4, num_attention_heads=8,
        num_key_value_heads=8, max_position_embeddings=256)
    model = LlamaForCausalLM(cfg)
    rs = np.random.RandomState(0)
    prompts = [rs.randint(0, cfg.vocab_size,
                          (4 + (i % 3) * 6,)).astype(np.int32)
               for i in range(requests)]

    one = ContinuousBatchingEngine(
        model, num_slots=num_slots, max_len=16 + max_new,
        decode_block=decode_block, prompt_buckets=(16,))
    tp = ContinuousBatchingEngine(
        model, num_slots=num_slots, max_len=16 + max_new,
        decode_block=decode_block, prompt_buckets=(16,),
        tp=TPConfig(axes=axes, mesh=mesh))

    def run(engine):
        engine.reset()
        srv = Server(engine)
        rids = [srv.submit(p, max_new_tokens=max_new, arrival_step=i)
                for i, p in enumerate(prompts)]
        t0 = time.perf_counter()
        res = srv.run_until_idle()
        return [res[r] for r in rids], time.perf_counter() - t0

    run(one), run(tp)                       # compile warmup
    ref, dt_one = run(one)

    prev_enabled = metrics.enabled()
    metrics.enable(True)
    try:
        bytes_c = metrics.counter(
            "pt_collectives_bytes_total",
            "payload bytes handed to collectives",
            labels=("op", "mode"))
        calls_c = metrics.counter(
            "pt_collectives_calls_total",
            "host-level collective dispatches", labels=("op", "mode"))
        b0 = bytes_c.value(op="tp_block", mode="tp_graph")
        c0 = calls_c.value(op="tp_block", mode="tp_graph")
        got, dt_tp = run(tp)
        steps = tp.steps           # run() resets the engine counters
        bytes_step = (bytes_c.value(op="tp_block", mode="tp_graph")
                      - b0) / max(steps, 1)
        calls_step = (calls_c.value(op="tp_block", mode="tp_graph")
                      - c0) / max(steps, 1)
    finally:
        metrics.enable(prev_enabled)
    identical = all(np.array_equal(a, b) for a, b in zip(ref, got))

    # the int8 hop only exists in psum mode (exact mode has no
    # reduction to compress) — one short stream + the runtime bound
    p8 = ContinuousBatchingEngine(
        model, num_slots=num_slots, max_len=16 + max_new,
        decode_block=decode_block, prompt_buckets=(16,),
        tp=TPConfig(axes=axes, mode="psum", int8=True, mesh=mesh))
    s8 = Server(p8)
    s8.submit(prompts[0], max_new_tokens=max_new)
    s8.run_until_idle()
    int8_bound = p8.tp_int8_error_bound()

    useful = requests * max_new
    return {
        "serving_tp_devices": tp.tp_degree(),
        "serving_tp_axes": "x".join(str(mesh.shape[a]) for a in axes),
        "serving_tp_bit_identical": bool(identical),
        "serving_tp_tokens_per_sec_1chip": round(useful / dt_one, 1),
        "serving_tp_tokens_per_sec_mesh": round(useful / dt_tp, 1),
        "serving_tp_collective_bytes_per_step": int(bytes_step),
        "serving_tp_collective_calls_per_step": round(calls_step, 2),
        "serving_tp_int8_error_bound": float(int8_bound),
        "serving_tp_decode_compiles": tp.decode_compile_count(),
    }


def run_serving_autoscale_bench(seed: int = 0, horizon: int = 36,
                                max_new: int = 10) -> dict:
    """SLO-driven autoscaling stage (serving/loadgen.py +
    serving/autoscaler.py): ONE seeded kill-and-burst trace — steady
    traffic, a burst episode, a decode-worker kill inside the burst,
    recovery — replayed against three fleets: AUTOSCALED (starts at
    the min size, control loop armed), STATIC-PEAK (pinned at the
    autoscaler's max), STATIC-MIN (pinned at the min, no repair).

    What the stage pins every round:

    - **identical traffic**: all three arms replay the same
      materialized trace (same prompts, ticks, sampling seeds) and the
      same kill tick — the A/B/C is about fleet sizing only;
    - **bit-identity across scale events**: every request completed in
      both the autoscaled and static-peak arms must match
      token-for-token, and completed greedy rows must equal
      ``generate()`` — scaling up mid-burst, draining after it, and
      redriving through the kill never touch token streams;
    - **SLO attainment vs worker-ticks**: fraction of completed
      requests with TTFT under the target, against the capacity spent
      (sum over ticks of live decode workers) — the autoscaled arm
      should track static-peak's attainment at fewer worker-ticks;
    - **the loop converging**: the autoscaled fleet scales up on the
      burst (and repairs the kill immediately — below-min bypasses
      hysteresis), then drains back to the min size after the burst
      clears; peak and end sizes are reported;
    - the compile-count pin: every decode engine — including the ones
      scaled in mid-run — compiles its decode block exactly once.
    """
    import paddle_tpu as paddle
    from paddle_tpu.models.llama import (LlamaForCausalLM,
                                         llama_tiny_config)
    from paddle_tpu.observability import metrics as om
    from paddle_tpu.serving import (Autoscaler, AutoscalerConfig,
                                    ContinuousBatchingEngine,
                                    DecodeWorker, Fleet, PrefillWorker,
                                    PrefillPagedEngine, RequestFailure,
                                    TraceConfig, generate_trace, replay)

    paddle.seed(0)
    cfg = llama_tiny_config(tensor_parallel=False)
    model = LlamaForCausalLM(cfg)
    kw = dict(num_slots=2, max_len=64, decode_block=4, block_size=8,
              prefill_chunk=8)
    scfg = AutoscalerConfig(min_decode=2, max_decode=4,
                            interval_ticks=2, queue_high=2,
                            pressure_high=0.92, ttft_slo_s=0.5,
                            breach_intervals=2, clear_intervals=4,
                            up_cooldown=2, down_cooldown=3)

    trace = generate_trace(TraceConfig(
        seed=seed, horizon=horizon, base_rate=0.2, bursts=1,
        burst_mult=6.0, burst_len=(8, 12), prompt_alpha=1.5,
        prompt_lo=4, prompt_hi=12, output_alpha=1.2, output_lo=4,
        output_hi=max_new, vocab_size=cfg.vocab_size,
        shared_fraction=0.3, shared_len=8, sampled_fraction=0.25))
    b0, b1 = trace.burst_windows[0]
    kill_tick = (b0 + b1) // 2
    # every arm runs the SAME total tick window (trace + recovery
    # tail): worker-ticks then mean "capacity reserved over the
    # window", the quantity autoscaling actually saves, and the tail
    # gives the control loop room to drain back to the min size
    total_ticks = horizon + 60

    def drive(n_decode, autoscale):
        fleet = Fleet(
            [PrefillWorker(PrefillPagedEngine(model, **kw))
             for _ in range(2)],
            [DecodeWorker(ContinuousBatchingEngine(model, paged=True,
                                                   **kw))
             for _ in range(n_decode)],
            lease_misses=2, spill_depth=100)
        scaler = Autoscaler(
            fleet,
            lambda: ContinuousBatchingEngine(model, paged=True, **kw),
            config=scfg) if autoscale else None
        state = {"killed": False, "worker_ticks": 0,
                 "peak": n_decode, "clock": 0}

        def submit(r):
            return fleet.submit(
                r.prompt, max_new_tokens=r.max_new_tokens,
                temperature=r.temperature, top_k=r.top_k, seed=r.seed,
                arrival_step=r.arrival_step, tenant=r.tenant,
                priority=r.priority)

        def on_tick(clock):
            state["clock"] = clock
            if not state["killed"] and clock >= kill_tick:
                live = [i for i, d in enumerate(fleet.decode)
                        if not d.killed]
                if len(live) > 1:
                    fleet.kill_decode_worker(live[-1])
                    state["killed"] = True
            n_live = len(fleet._live_decode())
            state["worker_ticks"] += n_live
            state["peak"] = max(state["peak"], n_live)
            if scaler is not None:
                scaler.on_tick(clock)

        t0 = time.perf_counter()
        ids = replay(trace, submit, fleet.tick, fleet.busy,
                     max_ticks=3000, on_tick=on_tick)
        while state["clock"] < total_ticks:
            fleet.tick()
            on_tick(state["clock"] + 1)
        dt = time.perf_counter() - t0
        # zero block leaks on every surviving arena — including the
        # workers the autoscaler scaled in and the ones it drained
        for w in list(fleet.prefill) + list(fleet.decode):
            if fleet._alive(w.name) and hasattr(w.engine, "manager"):
                assert not w.engine.manager._ref, \
                    f"block leak on {w.name}"
                w.engine.manager.assert_consistent()
        res = fleet.results
        ttft = {}
        for w in list(fleet.prefill) + list(fleet.decode):
            ttft.update(w.server.ttft)
        rows, completed_tokens = {}, 0
        for tid, rid in ids.items():
            v = res.get(rid)
            if v is not None and not isinstance(v, RequestFailure):
                rows[tid] = np.asarray(v)
                completed_tokens += int(np.asarray(v).size)
        attain = [1 for tid, rid in ids.items()
                  if tid in rows and rid in ttft
                  and ttft[rid] <= scfg.ttft_slo_s]
        return {
            "fleet": fleet, "scaler": scaler, "ids": ids,
            "rows": rows, "dt": dt,
            "completed": len(rows), "failed": len(ids) - len(rows),
            "tokens": completed_tokens,
            "worker_ticks": state["worker_ticks"],
            "peak": state["peak"],
            "end_live": len(fleet._live_decode()),
            "attainment": len(attain) / max(len(rows), 1),
            "ticks": fleet.stats()["ticks"],
        }

    # warm-up: compiles land here so the arms compare steady states
    drive(scfg.min_decode, autoscale=False)
    om.reset()
    om.enable(True)
    try:
        auto = drive(scfg.min_decode, autoscale=True)
        peak = drive(scfg.max_decode, autoscale=False)
        mini = drive(scfg.min_decode, autoscale=False)
    finally:
        om.enable(False)

    both = sorted(set(auto["rows"]) & set(peak["rows"]))
    identical = all(np.array_equal(auto["rows"][t], peak["rows"][t])
                    for t in both)
    greedy_ok = True
    for t in both[:8]:
        r = trace.requests[t]
        if r.temperature > 0.0:
            continue
        ref = model.generate(paddle.to_tensor(r.prompt[None, :]),
                             max_new_tokens=r.max_new_tokens
                             ).numpy()[0]
        greedy_ok = greedy_ok and np.array_equal(auto["rows"][t], ref)
    compiles = max(
        (d.engine.decode_compile_count()
         for d in auto["fleet"].decode), default=1)
    sc = auto["scaler"].stats()
    return {
        "serving_autoscale_requests": len(trace),
        "serving_autoscale_burst_window": [int(b0), int(b1)],
        "serving_autoscale_kill_tick": int(kill_tick),
        "serving_autoscale_bit_identical_vs_peak": bool(identical),
        "serving_autoscale_greedy_matches_generate": bool(greedy_ok),
        "serving_autoscale_decode_compiles": int(compiles),
        "serving_autoscale_scale_ups": sc["scale_ups"],
        "serving_autoscale_scale_downs": sc["scale_downs"],
        "serving_autoscale_removals": sc["removals"],
        "serving_autoscale_peak_size": auto["peak"],
        "serving_autoscale_end_size": auto["end_live"],
        "serving_autoscale_returned_to_min": bool(
            auto["end_live"] == scfg.min_decode),
        "serving_autoscale_completed": auto["completed"],
        "serving_autoscale_failed": auto["failed"],
        "serving_autoscale_attainment": round(auto["attainment"], 4),
        "serving_autoscale_attainment_static_peak": round(
            peak["attainment"], 4),
        "serving_autoscale_attainment_static_min": round(
            mini["attainment"], 4),
        "serving_autoscale_worker_ticks": auto["worker_ticks"],
        "serving_autoscale_worker_ticks_static_peak":
            peak["worker_ticks"],
        "serving_autoscale_worker_ticks_static_min":
            mini["worker_ticks"],
        "serving_autoscale_worker_tick_ratio_vs_peak": round(
            auto["worker_ticks"] / max(peak["worker_ticks"], 1), 3),
        "serving_autoscale_goodput_per_worker_tick": round(
            auto["tokens"] / max(auto["worker_ticks"], 1), 3),
        "serving_autoscale_goodput_per_worker_tick_static_peak": round(
            peak["tokens"] / max(peak["worker_ticks"], 1), 3),
        "serving_autoscale_goodput_per_worker_tick_static_min": round(
            mini["tokens"] / max(mini["worker_ticks"], 1), 3),
        "serving_autoscale_tokens_per_sec": round(
            auto["tokens"] / auto["dt"], 1) if auto["dt"] else 0.0,
        "serving_autoscale_leaks": 0,
    }


def run_serving_recovery_bench(seed: int = 0, requests: int = 6,
                               max_new: int = 10) -> dict:
    """Durable-control-plane stage (serving/durability.py +
    fleet.py): ONE seeded workload run twice — a CLEAN arm straight
    to idle, and a CRASHED arm that checkpoints mid-traffic, submits
    more, is killed two ticks later with streams in every state, and
    comes back via ``Fleet.recover``.

    What the stage pins every round:

    - **bit-identity through the crash**: every row the crashed arm
      completes must equal the clean arm's token-for-token (greedy
      AND seeded-sampled) — the whole point of journaled rng keys +
      redrive;
    - **recovery cost**: wall time of ``Fleet.recover`` itself
      (manifest load + journal replay + worker restore + redrive
      dispatch), the journal records replayed, and the streams
      redriven;
    - the compile-count pin: recovery reuses the restored arenas —
      decode compiles stay 1 per engine, no new programs on the
      steady path;
    - zero block leaks on every recovered arena.
    """
    import shutil
    import tempfile

    import paddle_tpu as paddle
    from paddle_tpu.models.llama import (LlamaForCausalLM,
                                         llama_tiny_config)
    from paddle_tpu.serving import (ContinuousBatchingEngine,
                                    DecodeWorker, Fleet,
                                    PrefillPagedEngine, PrefillWorker,
                                    RequestFailure)

    paddle.seed(0)
    cfg = llama_tiny_config(tensor_parallel=False)
    model = LlamaForCausalLM(cfg)
    kw = dict(num_slots=2, max_len=64, decode_block=4, block_size=8,
              prefill_chunk=8)
    rs = np.random.RandomState(seed)
    lens = rs.randint(5, 18, size=requests)
    prompts = [rs.randint(0, cfg.vocab_size, (int(L),)).astype(np.int32)
               for L in lens]
    sample_kw = [{} if i % 3 else
                 {"temperature": 0.9, "top_k": 40, "seed": 11 + i}
                 for i in range(requests)]

    pf = [PrefillPagedEngine(model, **kw) for _ in range(2)]
    dc = [ContinuousBatchingEngine(model, paged=True, **kw)
          for _ in range(2)]
    by_name = {f"prefill{i}": e for i, e in enumerate(pf)}
    by_name.update({f"decode{i}": e for i, e in enumerate(dc)})

    def submit_all(fleet):
        """First half before the mid-run boundary, second half after —
        the caller decides what the boundary is (checkpoint or just
        ticks). Returns {rid: prompt index}."""
        rid_of = {}
        for i in range(requests // 2):
            rid_of[fleet.submit(prompts[i], max_new_tokens=max_new,
                                **sample_kw[i])] = i
        return rid_of

    def submit_rest(fleet, rid_of):
        for i in range(requests // 2, requests):
            rid_of[fleet.submit(prompts[i], max_new_tokens=max_new,
                                **sample_kw[i])] = i
        return rid_of

    def rows_of(fleet, rid_of):
        res = fleet.results
        out = {}
        for rid, i in rid_of.items():
            v = res.get(rid)
            if v is not None and not isinstance(v, RequestFailure):
                out[i] = np.asarray(v)
        return out

    # -- clean arm (also the warm-up: compiles land here) --
    for e in list(by_name.values()):
        e.reset()
    clean_fleet = Fleet([PrefillWorker(e) for e in pf],
                        [DecodeWorker(e) for e in dc])
    rid_of = submit_all(clean_fleet)
    for _ in range(4):
        clean_fleet.tick()
    submit_rest(clean_fleet, rid_of)
    t0 = time.perf_counter()
    clean_fleet.run_until_idle(max_ticks=600)
    clean_dt = time.perf_counter() - t0
    clean_rows = rows_of(clean_fleet, rid_of)
    del clean_fleet

    # -- crashed arm --
    d = tempfile.mkdtemp(prefix="pt-recovery-bench-")
    try:
        for e in list(by_name.values()):
            e.reset()
        fleet = Fleet([PrefillWorker(e) for e in pf],
                      [DecodeWorker(e) for e in dc], durability=d)
        rid_of2 = submit_all(fleet)
        for _ in range(4):
            fleet.tick()
        t0 = time.perf_counter()
        fleet.checkpoint()
        ckpt_dt = time.perf_counter() - t0
        submit_rest(fleet, rid_of2)
        for _ in range(2):
            fleet.tick()
        journal_appends = fleet._journal.appends
        del fleet                       # CRASH: only the dir survives
        for e in list(by_name.values()):
            e.reset()
        t0 = time.perf_counter()
        fleet2 = Fleet.recover(
            d, engine_factory=lambda role, name: by_name[name])
        recover_dt = time.perf_counter() - t0
        t0 = time.perf_counter()
        fleet2.run_until_idle(max_ticks=600)
        drain_dt = time.perf_counter() - t0
        crashed_rows = rows_of(fleet2, rid_of2)
        lr = dict(fleet2.last_recovery)
        leaks = 0
        for w in list(fleet2.prefill) + list(fleet2.decode):
            if hasattr(w.engine, "manager"):
                leaks += len(w.engine.manager._ref)
        compiles = max((dw.engine.decode_compile_count()
                        for dw in fleet2.decode), default=1)
        del fleet2
    finally:
        shutil.rmtree(d, ignore_errors=True)

    identical = (sorted(clean_rows) == sorted(crashed_rows)
                 and all(np.array_equal(clean_rows[i], crashed_rows[i])
                         for i in clean_rows))
    return {
        "serving_recovery_requests": int(requests),
        "serving_recovery_bit_identical": bool(identical),
        "serving_recovery_completed": len(crashed_rows),
        "serving_recovery_journal_appends": int(journal_appends),
        "serving_recovery_journal_replayed": int(lr["replayed"]),
        "serving_recovery_redriven": int(lr["redriven"]),
        "serving_recovery_torn_tail": bool(lr["torn_tail"]),
        "serving_recovery_checkpoint_wall_s": round(ckpt_dt, 4),
        "serving_recovery_recover_wall_s": round(recover_dt, 4),
        "serving_recovery_drain_wall_s": round(drain_dt, 4),
        "serving_recovery_clean_wall_s": round(clean_dt, 4),
        "serving_recovery_decode_compiles": int(compiles),
        "serving_recovery_leaks": int(leaks),
    }
