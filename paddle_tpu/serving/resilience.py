"""Resilience policies for the serving loop: deadlines + cancellation,
bounded-queue admission control with load shedding, retry with
exponential backoff around transient step failures, a circuit breaker,
the NaN-logit quarantine gate, and crash-safe snapshot plumbing.

Philosophy: the engine (engine.py / paging.py) owns MECHANISM — it can
cancel a slot, abort a prefill job, report per-row NaN flags, and
serialize its full state — while this module owns POLICY: when to shed,
when to expire, how many times to retry, when to give up and drain.
``Server`` threads a :class:`ResilienceConfig` through its tick loop;
the default config changes nothing observable (no deadlines, shedding
off, retries only ever see :class:`~paddle_tpu.utils.faults.
InjectedFault`-style transient errors), so the bit-identity contract of
PRs 1/4 is untouched — pinned by the inertness tests.

Failure taxonomy (the ``reason`` on every :class:`RequestFailure`):

- ``"shed"``        — rejected at submit, queue depth at the cap
- ``"timeout"``     — deadline/queue-wait exceeded (queued or in-flight;
  in-flight cancellation frees the slot and releases paged blocks at
  correct refcounts)
- ``"poisoned"``    — the slot's logits went NaN; only that slot is
  quarantined, surviving greedy rows stay bit-identical
- ``"circuit_open"`` — the breaker tripped after N consecutive step
  failures; every in-flight and queued request is drained

Snapshots are single npz files written atomically (tmp + rename via
``distributed.checkpoint.atomic_savez``) holding the engine's device
state plus host metadata as an embedded JSON string — a ``Server``
killed mid-stream restores in a fresh process and finishes every
stream bit-identical to an uninterrupted run (pinned in
tests/test_resilience.py for the dense AND paged engines).
"""
from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Dict, Optional, Tuple

import numpy as np

from ..utils.faults import InjectedFault
from ..utils.flags import env_bool, env_float, env_int
from .scheduler import Request

__all__ = ["RequestFailure", "ResilienceConfig", "ResilienceState",
           "save_snapshot", "load_snapshot", "request_to_meta",
           "request_from_meta"]


@dataclass
class RequestFailure:
    """Recorded in ``Server.results[request_id]`` when a request ends
    any way other than completing — the explicit alternative to a
    silent hang. ``tokens_emitted``: useful tokens produced before the
    failure (partial work is accounted, not returned)."""
    request_id: int
    reason: str
    message: str = ""
    tokens_emitted: int = 0

    def __bool__(self):      # `if results[rid]` reads as "succeeded?"
        return False


def _transient_types() -> Tuple[type, ...]:
    """Exception types the retry loop treats as transient: injected
    faults always; the fleet transport's wire failure (a send that
    exhausted its reconnect budget — the network being down is
    operational, not a bug); XLA's runtime error (device-side failures
    — e.g. a preempted or flaky accelerator) when the class is
    importable. Programming errors (ValueError & friends) always
    propagate."""
    from .transport import TransportError
    types = [InjectedFault, TransportError]
    try:
        from jax.errors import JaxRuntimeError
        types.append(JaxRuntimeError)
    except ImportError:
        pass
    return tuple(types)


@dataclass
class ResilienceConfig:
    """Server-level policy knobs (every one also env-overridable so a
    bench child or an operator can arm them without code):

    - ``deadline_s`` / ``deadline_ticks``: default per-request
      deadlines (a request's own fields win).
    - ``max_queue_wait_ticks``: cap on ticks a request may sit queued
      past its arrival before it times out.
    - ``max_queue_depth``: admission control — a submit beyond this
      many queued requests is shed immediately.
    - ``retry_attempts`` / ``retry_backoff_s`` / ``retry_jitter``:
      exponential backoff (base · 2^attempt, +jitter fraction, seeded)
      around transient step/prefill/harvest failures.
    - ``breaker_threshold``: consecutive transient failures before the
      circuit opens and the server drains everything as
      ``circuit_open``.
    - ``nan_sentinel``: host gate on the engine's in-graph NaN flags.
    """
    deadline_s: Optional[float] = None
    deadline_ticks: Optional[int] = None
    max_queue_wait_ticks: Optional[int] = None
    max_queue_depth: Optional[int] = None
    retry_attempts: int = 2
    retry_backoff_s: float = 0.02
    retry_jitter: float = 0.25
    breaker_threshold: int = 8
    nan_sentinel: bool = True
    seed: int = 0

    @classmethod
    def from_env(cls) -> "ResilienceConfig":
        def opt_f(name):
            v = env_float(name, -1.0)
            return None if v < 0 else v

        def opt_i(name):
            v = env_int(name, -1)
            return None if v < 0 else v

        return cls(
            deadline_s=opt_f("PT_SERVING_DEADLINE_S"),
            deadline_ticks=opt_i("PT_SERVING_DEADLINE_TICKS"),
            max_queue_wait_ticks=opt_i("PT_SERVING_MAX_QUEUE_WAIT"),
            max_queue_depth=opt_i("PT_SERVING_MAX_QUEUE_DEPTH"),
            retry_attempts=env_int("PT_SERVING_RETRIES", 2),
            retry_backoff_s=env_float("PT_SERVING_BACKOFF_S", 0.02),
            retry_jitter=env_float("PT_SERVING_JITTER", 0.25),
            breaker_threshold=env_int("PT_SERVING_BREAKER", 8),
            nan_sentinel=env_bool("PT_SERVING_NAN_SENTINEL", True),
            seed=env_int("PT_SERVING_RESILIENCE_SEED", 0))


@dataclass
class ResilienceState:
    """Mutable runtime state + counters for one Server (surfaced via
    ``Server.stats()``). The jitter RNG is seeded so a replayed fault
    schedule produces the identical backoff sequence."""
    config: ResilienceConfig
    rng: np.random.RandomState = field(init=False)
    transient: Tuple[type, ...] = field(init=False)
    shed_requests: int = 0
    timeouts: int = 0
    retries: int = 0
    step_failures: int = 0
    tick_faults: int = 0
    consecutive_failures: int = 0
    breaker_open: bool = False
    failures_by_reason: Dict[str, int] = field(default_factory=dict)
    last_error: str = ""

    def __post_init__(self):
        self.rng = np.random.RandomState(self.config.seed)
        self.transient = _transient_types()

    def backoff_s(self, attempt: int) -> float:
        c = self.config
        return c.retry_backoff_s * (2.0 ** attempt) \
            * (1.0 + c.retry_jitter * float(self.rng.random_sample()))

    def count_failure(self, reason: str):
        self.failures_by_reason[reason] = \
            self.failures_by_reason.get(reason, 0) + 1
        if reason == "timeout":
            self.timeouts += 1

    def counters(self) -> dict:
        return {
            "requests_failed": sum(self.failures_by_reason.values()),
            "failures_by_reason": dict(self.failures_by_reason),
            "shed_requests": self.shed_requests,
            "timeouts": self.timeouts,
            "retries": self.retries,
            "step_failures": self.step_failures,
            "tick_faults": self.tick_faults,
            "consecutive_failures": self.consecutive_failures,
            "breaker_open": self.breaker_open,
        }

    def restore_counters(self, c: dict):
        """Rehydrate from a snapshot's ``counters()`` dict — the
        breaker state and failure budget survive a restore (an OPEN
        circuit must not silently re-close and resume dispatching to a
        device the policy quarantined)."""
        self.failures_by_reason = dict(c.get("failures_by_reason", {}))
        self.shed_requests = c.get("shed_requests", 0)
        self.timeouts = c.get("timeouts", 0)
        self.retries = c.get("retries", 0)
        self.step_failures = c.get("step_failures", 0)
        self.tick_faults = c.get("tick_faults", 0)
        self.consecutive_failures = c.get("consecutive_failures", 0)
        self.breaker_open = c.get("breaker_open", False)


# ---------------------------------------------------------------------------
# request (de)serialization for snapshots
# ---------------------------------------------------------------------------

_REQ_FIELDS = ("request_id", "max_new_tokens", "temperature", "top_k",
               "top_p", "eos_token_id", "seed", "arrival_step",
               "t_submit", "deadline_ticks", "deadline_s", "tenant",
               "priority", "wait_from")


def request_to_meta(req: Request) -> dict:
    """JSON-safe dict of a Request minus its prompt (prompts are
    arrays — they ride the snapshot's npz payload instead). Preemption
    ``resume`` state — the generated tokens, the slot rng key, the
    first-token timestamp — serializes inline: it is exactly the host
    half of the per-slot snapshot format, small enough for JSON."""
    meta = {f: getattr(req, f) for f in _REQ_FIELDS}
    if req.resume is not None:
        meta["resume"] = {
            "tokens": [int(t) for t in req.resume.tokens],
            "key": [int(k) for k in
                    np.asarray(req.resume.key, np.uint32).reshape(-1)],
            "t_admit": float(req.resume.t_admit),
            "redrive": bool(req.resume.redrive)}
    return meta


def request_from_meta(meta: dict, prompt) -> Request:
    from .scheduler import ResumeState
    resume = None
    rs = meta.get("resume")
    if rs is not None:
        resume = ResumeState(tokens=list(rs["tokens"]),
                             key=np.asarray(rs["key"], np.uint32),
                             t_admit=rs["t_admit"],
                             redrive=bool(rs.get("redrive", False)))
    # tolerant field read: snapshots written before tenant/priority
    # existed restore with the dataclass defaults
    return Request(prompt=np.asarray(prompt, np.int32).reshape(-1),
                   resume=resume,
                   **{f: meta[f] for f in _REQ_FIELDS if f in meta})


# ---------------------------------------------------------------------------
# snapshot file format: one npz, atomic rename, JSON metadata embedded
# ---------------------------------------------------------------------------

_SNAP_VERSION = 1


def save_snapshot(path: str, meta: dict, arrays: Dict[str, np.ndarray]):
    """Write ``{meta, arrays}`` as ONE crash-safe npz: the metadata
    travels as a JSON string array (no pickle), and the write goes
    through the checkpoint module's atomic tmp+rename helper — a crash
    mid-write leaves the previous snapshot intact, never a torn file."""
    from ..distributed.checkpoint import atomic_savez
    payload = dict(arrays)
    payload["__meta__"] = np.array(json.dumps(
        {"format": "pt-serving-snapshot", "version": _SNAP_VERSION,
         **meta}))
    atomic_savez(path, payload)


def load_snapshot(path: str):
    """Returns ``(meta, arrays)``. Arrays are materialized eagerly so
    the npz handle never outlives the call."""
    with np.load(path, allow_pickle=False) as z:
        meta = json.loads(str(z["__meta__"]))
        arrays = {k: z[k] for k in z.files if k != "__meta__"}
    if meta.get("format") != "pt-serving-snapshot":
        raise ValueError(f"{path} is not a serving snapshot")
    if meta.get("version") != _SNAP_VERSION:
        raise ValueError(
            f"snapshot version {meta.get('version')} unsupported "
            f"(this build reads {_SNAP_VERSION})")
    return meta, arrays
