"""Continuous-batching decode engine: a fixed pool of S sequence slots
kept alive inside ONE jitted step.

Reference parity: the reference serving stack's fused_multi_transformer
decode loop + PaddleNLP's dynamic-batching inference server (SURVEY §2.1
Inference, §3.5 AnalysisPredictor — verify); the design is the
vLLM-style continuous batching discipline restated under the repo's
static-shape rules.

TPU-native design: the KV cache is preallocated at
``(S, max_len, kv_heads, head_dim)`` and never reshapes — a retiring
request frees its SLOT, not its memory. Per-slot ``pos``/``pad``/
``live``/``eos``/``remaining``/rng-key/sampling-param state rides
in-graph as (S,) arrays, so ONE compiled program (a ``lax.scan`` of the
shared decode step over ``decode_block`` tokens) serves every mix of
request depths, greedy/sampled traffic, and admission pattern — zero
recompiles across the stream. Admission reuses the existing shared
prefill/decode step from ``models/generation`` at batch 1 (prompt
left-padded to a bucket length), then splices the prefilled row into
the pool with ``lax.dynamic_update_slice`` on the batch dim while the
other slots' cache rows stay untouched (prefill-insert). The defining
invariant: a continuously-batched stream of ragged greedy requests is
bit-identical to per-request ``generate()`` calls.
"""
from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..observability import metrics as _om
from ..observability.tracing import now_us as _trace_now
from ..utils import faults

# engine metric families (no-ops until metrics.enable()/PT_METRICS)
_M_STEPS = _om.counter("pt_engine_decode_steps_total",
                       "decode-block steps executed")
_M_TOKENS = _om.counter("pt_engine_tokens_emitted_total",
                        "useful tokens emitted (prefill + decode)")
_M_DECODE_TOKENS = _om.counter("pt_engine_decode_tokens_total",
                               "live-slot decode tokens emitted")
_M_COMPILES = _om.gauge("pt_engine_decode_compiles",
                        "times the decode-block program was traced "
                        "(static-shape invariant: stays 1)")
_M_PREFILLS = _om.counter("pt_engine_prefills_total",
                          "prefill dispatches (whole-prompt or chunk)")
_M_BYTES = _om.counter(
    "pt_serving_decode_bytes_read_total",
    "estimated HBM bytes read by decode steps (weights + buffers + "
    "KV pool, capacity-based — the quant-vs-fp32 A/B numerator)")
_M_W_BYTES = _om.gauge(
    "pt_serving_decode_weight_bytes",
    "weight + buffer bytes one decode step reads (codes + scales "
    "under weight-only quant)")
_M_KV_BYTES = _om.gauge(
    "pt_serving_decode_kv_bytes",
    "KV pool bytes resident per decode step (codes + scales under "
    "the int8 arena)")

__all__ = ["ContinuousBatchingEngine", "ModelStepBackend",
           "ArtifactStepBackend", "slot_sample_logits", "init_slot_state",
           "build_slot_block_fn", "build_slot_prefill_fn",
           "build_paged_chunk_fn"]


def slot_sample_logits(logits, keys, temperature, top_k, top_p):
    """Per-slot sampling over (S, V) logits (or log-probs — per-row
    shifts cancel in every branch): ``temperature``/``top_k``/``top_p``
    are (S,) arrays so one compiled program serves mixed greedy/sampled
    traffic. Greedy rows (temperature <= 0) take argmax; sampled rows
    share ONE descending sort for both the top-k threshold and the
    top-p cutoff, then draw categorically with per-row keys."""
    S, V = logits.shape
    logits = logits.astype(jnp.float32)
    greedy = temperature <= 0.0
    greedy_tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    t = jnp.where(greedy, jnp.float32(1.0),
                  temperature.astype(jnp.float32))
    scaled = logits / t[:, None]
    sorted_desc = jnp.sort(scaled, axis=-1)[:, ::-1]
    k = jnp.clip(top_k.astype(jnp.int32), 0, V)
    use_k = (k > 0) & (k < V)
    kth = jnp.take_along_axis(sorted_desc,
                              jnp.maximum(k - 1, 0)[:, None], axis=-1)
    kth = jnp.where(use_k[:, None], kth, -jnp.inf)
    filt = jnp.where(scaled < kth, -jnp.inf, scaled)
    # masking below-kth values inside the sorted array == re-sorting the
    # filtered row (kept prefix unchanged, dropped tail -> -inf)
    sorted_f = jnp.where(sorted_desc < kth, -jnp.inf, sorted_desc)
    probs = jax.nn.softmax(sorted_f, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    cutoff_idx = jnp.clip(
        jnp.sum(cum < top_p[:, None], axis=-1, keepdims=True), 0, V - 1)
    cutoff = jnp.take_along_axis(sorted_f, cutoff_idx, axis=-1)
    cutoff = jnp.where((top_p < 1.0)[:, None], cutoff, -jnp.inf)
    filt = jnp.where(filt < cutoff, -jnp.inf, filt)
    sampled = jax.vmap(
        lambda kk, row: jax.random.categorical(kk, row))(keys, filt)
    return jnp.where(greedy, greedy_tok, sampled.astype(jnp.int32))


def init_slot_state(num_slots: int) -> Dict[str, jnp.ndarray]:
    """Fresh all-slots-free in-graph state pytree."""
    S = num_slots
    return {
        "tok": jnp.zeros((S,), jnp.int32),
        "pos": jnp.zeros((S,), jnp.int32),
        "pad": jnp.zeros((S,), jnp.int32),
        "live": jnp.zeros((S,), bool),
        "eos": jnp.full((S,), -1, jnp.int32),
        "remaining": jnp.zeros((S,), jnp.int32),
        "key": jnp.zeros((S, 2), jnp.uint32),
        "temp": jnp.zeros((S,), jnp.float32),
        "topk": jnp.zeros((S,), jnp.int32),
        "topp": jnp.ones((S,), jnp.float32),
    }


def build_slot_block_fn(pure, block: int, trace_counter=None,
                        paged: bool = False):
    """The engine's ONE decode program: ``lax.scan`` of the shared step
    over ``block`` tokens with per-slot positions. Each scan iteration:
    per-slot key split -> forward (vector ``pos``, per-slot ``pad``) ->
    per-slot sampling -> in-graph eos/budget retirement (a finished
    slot's ``live`` drops and its pos/tok freeze — it is masked junk
    until the host refills it between blocks). Emits the (block, S)
    token matrix plus per-step live-slot counts (the occupancy/tok-s
    numerators), so the host syncs ONCE per block.

    ``paged``: the state carries a per-slot block ``table`` and the
    cache is the shared block arena; dead slots' tables are redirected
    to the trash block 0 IN-GRAPH, so a retired slot whose blocks the
    host has already handed to another request can never scatter junk
    into them mid-block.

    Besides tokens and live masks the block also emits per-step (S,)
    ``ok`` flags — True iff the row's log-probs held no NaN (the logit
    sentinel the resilience layer uses to quarantine a poisoned slot
    without touching its neighbours). The flags are a side output of
    the SAME single compiled program; healthy streams are bit-identical
    with or without the sentinel reading them."""

    def block_fn(pv, bv, cache_flat, state):
        if trace_counter is not None:       # runs only while tracing
            trace_counter[0] += 1

        def body(carry, _):
            cf, st = carry
            sp = jax.vmap(jax.random.split)(st["key"])     # (S, 2, 2)
            new_key, sub = sp[:, 0], sp[:, 1]
            if paged:
                tbl = jnp.where(st["live"][:, None], st["table"], 0)
                logp, cf = pure(pv, bv, st["tok"][:, None], cf,
                                st["pos"], None, None, tbl)
            else:
                logp, cf = pure(pv, bv, st["tok"][:, None], cf,
                                st["pos"], None, st["pad"])
            # NaN (not -inf: log-probs legitimately underflow) marks a
            # poisoned row — numerically impossible from finite
            # weights/cache, so a False flag means corrupted state
            ok = ~jnp.any(jnp.isnan(logp), axis=-1)
            nxt = slot_sample_logits(logp, sub, st["temp"], st["topk"],
                                     st["topp"])
            live = st["live"]
            hit = live & (st["eos"] >= 0) & (nxt == st["eos"])
            rem = jnp.where(live, st["remaining"] - 1, st["remaining"])
            rem = jnp.where(hit, 0, rem)
            st2 = dict(st, tok=jnp.where(live, nxt, st["tok"]),
                       pos=st["pos"] + live.astype(jnp.int32),
                       remaining=rem, key=new_key,
                       live=live & (rem > 0))
            # ``live`` (the start-of-step mask) marks which rows of the
            # token matrix are real emissions — an eos retirement zeroes
            # ``remaining``, so the host must count emissions from this
            # mask, not from remaining deltas
            return (cf, st2), (nxt, live, ok)

        (cache_flat, state), (toks, lives, oks) = jax.lax.scan(
            body, (cache_flat, state), None, length=block)
        return cache_flat, state, toks, lives, oks

    return block_fn


def build_slot_prefill_fn(pure, row_specs):
    """Batch-1 prefill of a prompt bucket into a fresh full-length cache
    row (the row is spliced into the pool by the admit program). Reuses
    the SAME shared step as ``generate()`` — prompt left-padded to the
    bucket length, per-row pad counts mask the filler — so slot decode
    is bit-identical to a standalone ``generate()`` call. The first
    token is sampled in-graph with the request's own params (one
    dispatch per admission, not two)."""

    def prefill_fn(pv, bv, ids, pad, key, temp, topk, topp):
        zero = tuple(jnp.zeros(shape, dtype) for shape, dtype in row_specs)
        logp, row = pure(pv, bv, ids, zero, jnp.asarray(0, jnp.int32),
                         None, pad)
        tok0 = slot_sample_logits(logp, key[None], temp[None],
                                  topk[None], topp[None])[0]
        return tok0, row

    return prefill_fn


def build_paged_chunk_fn(pure, chunk: int, trace_counter=None):
    """ONE chunked-prefill program for every prompt of every length:
    a fixed ``(1, chunk)`` right-padded token window written straight
    into the paged arena through the request's block table (pad columns
    carry junk K/V that decode overwrites before it can ever be
    attended — writes past the table width land in the trash block).
    The candidate first token is sampled in-graph from the last REAL
    column with the request's own params; the host uses it only on the
    final chunk. Unlike the dense engine's per-bucket prefill jits,
    this compiles exactly once."""

    def chunk_fn(pv, bv, ids, cache_flat, table, start_pos, n_valid,
                 key, temp, topk, topp):
        if trace_counter is not None:       # runs only while tracing
            trace_counter[0] += 1
        logp, cache_flat = pure(
            pv, bv, ids, cache_flat, jnp.reshape(start_pos, (1,)),
            None, None, table, n_valid - 1)
        tok0 = slot_sample_logits(logp, key[None], temp[None],
                                  topk[None], topp[None])[0]
        return tok0, cache_flat

    return chunk_fn


def _cancel_fn(state, slot):
    """Kill one slot in-graph (deadline/poison cancellation): ``live``
    drops and ``remaining`` zeroes, so the next decode block treats the
    row as retired junk (and, paged, redirects its table to the trash
    block). One compiled program serves every cancellation."""
    return dict(state,
                live=state["live"].at[slot].set(False),
                remaining=state["remaining"].at[slot].set(0))


def _admit_fn(cache_flat, state, row_flat, slot, tok0, pos0, pad0, rem0,
              eos0, temp0, topk0, topp0, key0):
    """Splice a prefilled row into the pool (dynamic_update_slice on the
    batch dim — other slots' rows untouched) and arm the slot's state.
    ``slot`` is traced, so ONE compiled program serves every admission."""
    new_cache = tuple(
        jax.lax.dynamic_update_slice(c, r.astype(c.dtype),
                                     (slot,) + (0,) * (c.ndim - 1))
        for c, r in zip(cache_flat, row_flat))

    def set1(a, v):
        return a.at[slot].set(jnp.asarray(v, a.dtype))

    new_state = dict(
        state, tok=set1(state["tok"], tok0),
        pos=set1(state["pos"], pos0), pad=set1(state["pad"], pad0),
        live=set1(state["live"], rem0 > 0),
        eos=set1(state["eos"], eos0),
        remaining=set1(state["remaining"], rem0),
        key=state["key"].at[slot].set(key0),
        temp=set1(state["temp"], temp0),
        topk=set1(state["topk"], topk0),
        topp=set1(state["topp"], topp0))
    return new_cache, new_state


class _FusedBlockJit:
    """Megakernel decode-block program: traces the block fn under the
    decode-layer marking context, runs the decode fusion pass
    (passes/fusion_decode.py) over the jaxpr — splicing one fused
    "decode layer" call per layer per scan step — and jits the
    TRANSFORMED program. Built lazily on first call (same laziness as
    ``jax.jit``); zero marked layers is a hard error, because a
    requested megakernel that silently serves the unfused program
    would be a misconfiguration, not a preference."""

    def __init__(self, block_fn, donate=(2, 3), allow_kernel=True):
        self._block_fn = block_fn
        self._donate = donate
        self._allow_kernel = allow_kernel
        self._jit = None
        self._closed = None
        self.rewrites = 0       # fused decode-layer calls spliced
        self.kernel_calls = 0   # of those, Pallas-megakernel-routed

    def _build(self, args):
        from ..ops.pallas import decode_layer as _dl
        from ..passes.fusion_decode import make_decode_fusion_pass
        with _dl.marking():
            closed, out_shape = jax.make_jaxpr(
                self._block_fn, return_shape=True)(*args)
        run = make_decode_fusion_pass(allow_kernel=self._allow_kernel)
        closed = run(closed)
        stats = run.last_rewrites
        self.rewrites = stats.get("decode_layer", 0)
        self.kernel_calls = stats.get("kernel", 0)
        if self.rewrites == 0:
            raise RuntimeError(
                "megakernel decode requested but no decode layer was "
                f"fused (pass stats: {stats or 'no marked regions'}) — "
                "the model must mark its decode layers (see "
                "models/llama.py LlamaDecoderLayer._marked_decode)")
        self._closed = closed
        out_tree = jax.tree.structure(out_shape)

        def run_block(*call_args):
            flat = jax.tree.leaves(call_args)
            out = jax.core.eval_jaxpr(self._closed.jaxpr,
                                      self._closed.consts, *flat)
            return jax.tree.unflatten(out_tree, out)

        self._jit = jax.jit(run_block, donate_argnums=self._donate)

    def __call__(self, *args):
        if self._jit is None:
            self._build(args)
        return self._jit(*args)


class _StepBackendCommon:
    """Shared slot-state/accounting helpers for every step backend
    (in-process, paged, AOT) — keyed off ``num_slots``/``pool_specs``
    which each backend sets up."""

    # weight-only quantization state (serving/quant.py): None/empty on
    # fp32 backends, so every hot path stays one falsy check
    quant_cfg = None
    _qmeta = None
    _weight_bound = 0.0
    # megakernel decode (ops/pallas/decode_layer.py): resolved by the
    # model backends' constructors, always False on AOT backends
    fuse = False

    def _resolve_fuse(self, fuse) -> bool:
        """``fuse=None`` defers to the PT_SERVING_MEGAKERNEL env knob
        (same contract as paged/kv_int8 resolution); explicit backends
        are never rerouted by it because resolution only runs in the
        model-backend constructors."""
        if fuse is None:
            from ..utils.flags import env_bool
            fuse = env_bool("PT_SERVING_MEGAKERNEL")
        self.fuse = bool(fuse)
        return self.fuse

    def _block_jit_for(self, block_fn, donate=(2, 3)):
        """The decode-block program builder every model backend routes
        through: plain ``jax.jit`` normally, the pass-transformed fused
        program under megakernel mode. Weight-quant engines keep the
        fused-call structure but pin the captured-jaxpr body
        (allow_kernel=False) so XLA's dequant-into-gemm prologue fusion
        is never traded for an HBM-materialized fp32 weight."""
        if not self.fuse:
            return jax.jit(block_fn, donate_argnums=donate)
        return _FusedBlockJit(block_fn, donate=donate,
                              allow_kernel=not self._qmeta)

    def init_state(self):
        return init_slot_state(self.num_slots)

    def kv_bytes_per_slot(self) -> int:
        """HBM bytes of KV cache per slot (the paged backend's arena is
        shared, so its per-slot figure shrinks with block count)."""
        total = sum(int(np.prod(shape)) * np.dtype(dtype).itemsize
                    for shape, dtype in self.pool_specs)
        return total // self.num_slots

    def _setup_weight_quant(self, model, quant):
        """Quantize the serving weight set in-place (model backends
        call this between pv construction and program building; see
        serving/quant.py). No-op when ``quant`` is None."""
        if quant is None:
            return
        from .quant import quantize_backend_params
        self.quant_cfg = quant
        self._pv, self._qmeta, self._weight_bound = \
            quantize_backend_params(model, self._pv, quant)

    def _maybe_quant_pure(self, pure):
        """Wrap a pure step with the in-graph dequant when this backend
        holds quantized weights — EVERY program (decode block, prefill,
        chunk, spec verify) must be built from the wrapped step."""
        if not self._qmeta:
            return pure
        from .quant import wrap_pure_with_dequant
        return wrap_pure_with_dequant(pure, self._qmeta)

    def param_bytes(self) -> int:
        """HBM bytes of weights + buffers one decode step reads (codes
        AND scales under weight-only quant — the wire footprint, which
        is the point)."""
        return sum(int(v.nbytes) for v in jax.tree.leaves(self._pv)) \
            + sum(int(v.nbytes) for v in jax.tree.leaves(self._bv))


class ModelStepBackend(_StepBackendCommon):
    """In-process backend: jits the slot block + per-bucket prefills
    over a live model (the same pure step ``generate()`` uses)."""

    def __init__(self, model, num_slots: int, max_len: int,
                 decode_block: int, quant=None, fuse=None):
        from ..models.generation import (build_decode_step,
                                         forward_accepts_pad)
        from ..tensor import Tensor
        if not forward_accepts_pad(type(model)):
            raise ValueError(
                f"{type(model).__name__}.forward does not accept per-row "
                "pad counts — the slot pool needs ragged decode support")
        self.num_slots, self.max_len = num_slots, max_len
        self.block_size = decode_block
        tree_holder = {"tree": None}
        self._tree_holder = tree_holder    # spec backends reuse it
        self._pure = build_decode_step(model, None, tree_holder)
        cache0 = model.init_kv_cache(num_slots, max_len)
        flat, tree = jax.tree.flatten(
            cache0, is_leaf=lambda x: isinstance(x, Tensor))
        tree_holder["tree"] = tree
        self.pool_specs = tuple((c._value.shape, c._value.dtype)
                                for c in flat)
        self.row_specs = tuple(((1,) + shape[1:], dtype)
                               for shape, dtype in self.pool_specs)
        self._pv = [p._value for _, p in model.named_parameters()]
        self._bv = [b._value for _, b in model.named_buffers()]
        # weight-only quant happens BEFORE any program is built so the
        # decode block, prefills (and subclasses' chunk/verify programs)
        # all trace against codes + in-graph dequant
        self._setup_weight_quant(model, quant)
        self._pure = self._maybe_quant_pure(self._pure)
        self._resolve_fuse(fuse)
        self.decode_traces = [0]
        self._block_jit = self._block_jit_for(
            build_slot_block_fn(self._pure, decode_block,
                                self.decode_traces))
        self._prefill_jits: Dict[int, callable] = {}

    def pool_cache(self):
        return tuple(jnp.zeros(shape, dtype)
                     for shape, dtype in self.pool_specs)

    def decode_block(self, cache_flat, state):
        return self._block_jit(self._pv, self._bv, cache_flat, state)

    def prefill(self, bucket_len, ids, pad, key, temp, topk, topp):
        fn = self._prefill_jits.get(bucket_len)
        if fn is None:
            fn = jax.jit(build_slot_prefill_fn(self._pure, self.row_specs))
            self._prefill_jits[bucket_len] = fn
        return fn(self._pv, self._bv, ids, pad, key, temp, topk, topp)


def artifact_fingerprint(cfgs: dict, *programs: bytes) -> str:
    """Artifact identity: sha1 over the recorded config + the
    serialized programs. Recorded into engine snapshots so a restore
    onto a DIFFERENT artifact is refused — the ONE recipe shared by the
    dense and paged artifact backends (changing it in one place cannot
    silently de-gate the other)."""
    import hashlib
    h = hashlib.sha1(repr(sorted(
        (k, str(v)) for k, v in cfgs.items())).encode())
    for prog in programs:
        h.update(prog)
    return h.hexdigest()


class ArtifactStepBackend(_StepBackendCommon):
    """AOT backend: the SAME engine programs, deserialized from an
    ``export_decoder(..., engine_slots=...)`` artifact — no model code
    or tracing needed on the serving host (reference: AnalysisPredictor
    serving from the saved program alone)."""

    def __init__(self, blob):
        eng = blob["engine"]
        cfgs = eng["config"]
        self.artifact_fingerprint = artifact_fingerprint(
            cfgs, eng["block"],
            *(eng["prefill"][lb] for lb in sorted(eng["prefill"])))
        self.num_slots = cfgs["num_slots"]
        self.max_len = cfgs["max_len"]
        self.block_size = cfgs["decode_block"]
        # pre-NaN-sentinel artifacts exported a 4-output decode block
        # (no per-step ok flags); the engine pads the missing flags
        # with None so both generations serve — new exports record
        # block_outputs=5
        self.carries_nan_flags = cfgs.get("block_outputs", 4) >= 5
        self.pool_specs = tuple((tuple(shape), np.dtype(dtype))
                                for shape, dtype in eng["pool_specs"])
        self._block = jax.export.deserialize(eng["block"])
        self._prefills = {int(k): jax.export.deserialize(v)
                          for k, v in eng["prefill"].items()}
        self._pv = [jnp.asarray(v) for v in blob["params"]]
        self._bv = [jnp.asarray(v) for v in blob["buffers"]]
        self.decode_traces = [1]     # one AOT-compiled decode program

    def pool_cache(self):
        return tuple(jnp.zeros(shape, dtype)
                     for shape, dtype in self.pool_specs)

    def decode_block(self, cache_flat, state):
        return self._block.call(self._pv, self._bv, cache_flat, state)

    def prefill(self, bucket_len, ids, pad, key, temp, topk, topp):
        fn = self._prefills.get(int(bucket_len))
        if fn is None:
            raise ValueError(
                f"prompt bucket {bucket_len} was not exported; available: "
                f"{sorted(self._prefills)} — re-export with it in "
                "engine_prompt_buckets")
        return fn.call(self._pv, self._bv, ids, pad, key, temp, topk,
                       topp)


@dataclass
class _SlotRun:
    """Host-side bookkeeping for one in-flight request. ``t_admit`` is
    the moment the first token existed (prefill completion) — the TTFT
    timestamp. ``block_ids``: the paged engine's arena blocks to
    release at retirement (None on the dense engine)."""
    request: object
    tokens: List[int] = field(default_factory=list)
    t_admit: float = 0.0
    t_done: float = 0.0
    block_ids: Optional[List[int]] = None
    # set when the request was cancelled/quarantined instead of
    # completing ("timeout", "poisoned", "circuit_open", ...); the
    # Server records a RequestFailure in results instead of tokens
    failure: Optional[str] = None


class ContinuousBatchingEngine:
    """Slot-pool decode engine over a step backend. The host syncs with
    the device once per ``decode_block`` tokens: it reads the (block, S)
    token matrix plus the post-block ``remaining`` counters, harvests
    retired requests, and refills free slots — the decode program itself
    is compiled exactly once for the engine's lifetime.

    ``paged=True`` (or ``PT_SERVING_PAGED=1``) constructs the
    block-paged variant (``serving.paging.PagedEngine``): shared KV
    arena + per-slot block tables, ref-counted prefix reuse, chunked
    prefill — see that module for the paged-only knobs."""

    def __new__(cls, *args, **kw):
        if cls is ContinuousBatchingEngine:
            paged = kw.get("paged")
            backend = kw.get("backend") if len(args) < 6 else args[5]
            if paged is None:
                from ..utils.flags import env_flag
                if getattr(backend, "is_paged", False):
                    paged = True     # a paged backend IS the decision
                elif backend is None:
                    paged = env_flag("PT_SERVING_PAGED")
                # an explicit non-paged backend (e.g. the AOT
                # ArtifactStepBackend in GenerationPredictor) is never
                # rerouted by the env flag
            from .spec import spec_requested
            spec = spec_requested(kw.get("spec"), backend)
            if paged:
                from .paging import PagedEngine
                from .spec import SpecPagedEngine
                return object.__new__(
                    SpecPagedEngine if spec else PagedEngine)
            if spec:
                from .spec import SpecEngine
                return object.__new__(SpecEngine)
        return object.__new__(cls)

    def __init__(self, model=None, num_slots: int = 4, max_len: int = 256,
                 decode_block: int = 8,
                 prompt_buckets: Optional[Sequence[int]] = None,
                 backend=None, *, paged: Optional[bool] = None,
                 spec=None, tp=None, quant=None, megakernel=None):
        if backend is None:
            if model is None:
                raise ValueError("pass a model or a step backend")
            from .quant import resolve_quant_config
            from .tp import resolve_tp_config
            tp_cfg = resolve_tp_config(tp)
            q_cfg = resolve_quant_config(quant)
            if tp_cfg is not None and megakernel:
                raise NotImplementedError(
                    "megakernel decode is not yet composed with "
                    "tensor-parallel serving (the sharded block builds "
                    "its own shard_map programs) — drop megakernel= or "
                    "tp= (ROADMAP follow-up)")
            if tp_cfg is not None:
                # tensor-parallel serving: the SAME decode/prefill
                # programs, sharded over a mesh (serving/tp.py). An
                # explicitly passed backend is never rerouted by the
                # PT_SERVING_TP env flag — same contract as paged.
                from .tp import ShardedModelStepBackend
                backend = ShardedModelStepBackend(
                    model, num_slots, max_len, decode_block, tp_cfg,
                    quant=q_cfg)
            else:
                # subclass hook: the speculative engine swaps in the
                # verify-capable backend here (serving/spec.py)
                backend = self._build_backend(model, num_slots, max_len,
                                              decode_block, q_cfg,
                                              fuse=megakernel)
        elif megakernel is not None:
            # same contract as quant=: the fused program is baked into
            # the backend at construction, and the env knob never
            # reroutes an explicit backend (resolution only runs above)
            raise ValueError(
                "megakernel= cannot be set alongside an explicit "
                "backend — the fused decode program is baked into the "
                "backend at construction")
        elif quant is not None:
            # same contract as kv_int8/num_blocks on the paged engine:
            # the quantization is baked into the backend at construction
            # — a silently ignored quant= (INCLUDING quant=False against
            # a quantized backend, which cannot be de-quantized) would
            # be a misconfiguration, not a preference (and the env knob
            # never reroutes an explicit backend either: resolution
            # only runs above)
            raise ValueError(
                "quant= cannot be set alongside an explicit backend — "
                "weight-only quantization is baked into the backend at "
                "construction")
        if spec and not hasattr(self, "spec_k"):
            # only the factory (ContinuousBatchingEngine(...)) routes
            # spec= to the speculative engine classes; a direct
            # subclass constructor silently ignoring it would be a
            # misconfiguration, not a preference
            raise ValueError(
                "spec= is only honored through the "
                "ContinuousBatchingEngine factory (or construct "
                "serving.spec.SpecEngine/SpecPagedEngine directly)")
        self.backend = backend
        self.num_slots = backend.num_slots
        self.max_len = backend.max_len
        self.decode_block = backend.block_size
        self.prompt_buckets = tuple(sorted(prompt_buckets)) \
            if prompt_buckets else None
        self._admit_jit = jax.jit(_admit_fn, donate_argnums=(0, 1))
        self._cancel_jit = jax.jit(_cancel_fn, donate_argnums=(0,))
        # host-side gate on the in-graph NaN flags (the flags are
        # always computed — same single compiled program either way)
        self.nan_sentinel = True
        # set by the Server iff request tracing is armed (None keeps
        # the hot paths at one `is None` check)
        self.tracer = None
        self.reset()

    def _build_backend(self, model, num_slots, max_len, decode_block,
                       quant=None, fuse=None):
        return ModelStepBackend(model, num_slots, max_len, decode_block,
                                quant=quant, fuse=fuse)

    # -- lifecycle ---------------------------------------------------------
    def reset(self):
        """Free every slot and zero the counters (compiled programs are
        kept — repeat streams never recompile)."""
        self._cache = self.backend.pool_cache()
        self._state = self.backend.init_state()
        self._slots: List[Optional[_SlotRun]] = [None] * self.num_slots
        self._prefill_slots: set = set()   # paged: mid-prefill slots
        self._remaining_host = np.zeros((self.num_slots,), np.int64)
        self._finished: List[_SlotRun] = []
        self._pending_block = None     # dispatched, not yet harvested
        self._bytes_step = None        # decode_bytes_per_step memo
        self.steps = 0                # engine decode steps executed
        self.tokens_emitted = 0       # useful tokens (incl. prefill's)
        self.decode_tokens = 0        # live-slot decode steps only
        self.slot_steps = 0           # S * steps (occupancy denominator)

    # -- introspection -----------------------------------------------------
    def free_slot_count(self) -> int:
        return sum(1 for s in self._slots if s is None)

    def has_live(self) -> bool:
        return any(s is not None for s in self._slots)

    def has_decoding(self) -> bool:
        """Any slot past prefill (worth running a decode block for) —
        differs from :meth:`has_live` only on the paged engine, where a
        slot can be occupied but still mid-chunked-prefill."""
        return any(s is not None and i not in self._prefill_slots
                   for i, s in enumerate(self._slots))

    def occupancy(self) -> float:
        """Fraction of decode-block slot-steps that emitted a token
        (prefill tokens live outside the pool and don't count here)."""
        return self.decode_tokens / self.slot_steps if self.slot_steps \
            else 0.0

    def decode_compile_count(self) -> int:
        """Number of times the decode-block program was traced/compiled
        — the static-shape invariant holds iff this stays 1."""
        return self.backend.decode_traces[0]

    def megakernel(self) -> bool:
        """Whether the decode block was built through the decode-layer
        fusion pass (ops/pallas/decode_layer.py megakernel mode)."""
        return bool(getattr(self.backend, "fuse", False))

    def megakernel_rewrites(self) -> int:
        """Fused decode-layer calls spliced into the ONE decode-block
        program (layers × 1; 0 before the lazy first build or with
        megakernel off)."""
        return int(getattr(self.backend._block_jit, "rewrites", 0)) \
            if hasattr(self.backend, "_block_jit") else 0

    def megakernel_kernel_calls(self) -> int:
        """Of the fused calls, how many routed to the Pallas megakernel
        (0 off-TPU / under weight quant — those run the bit-exact
        captured-jaxpr body)."""
        return int(getattr(self.backend._block_jit, "kernel_calls", 0)) \
            if hasattr(self.backend, "_block_jit") else 0

    def tp_degree(self) -> int:
        """Devices the decode block is sharded over (1 = TP off)."""
        return getattr(self.backend, "tp_degree", 1)

    def tp_int8_error_bound(self) -> float:
        """Runtime worst-case elementwise error of the tensor-parallel
        int8 hidden-state all-reduce, probed against the LIVE cache and
        slot state (0.0 unless a psum-mode TP backend with the int8 hop
        is armed — see serving/tp.py)."""
        fn = getattr(self.backend, "tp_int8_error_bound", None)
        if fn is None:
            return 0.0
        return fn(self._cache, self._state)

    def kv_error_bound(self) -> float:
        """Runtime worst-case |dequant - fp32| over the KV cache — 0.0
        on the dense engine (fp32 rows); the paged engine's int8 arena
        overrides this with the EQuARX bound."""
        return 0.0

    def weight_error_bound(self) -> float:
        """Build-time worst-case elementwise |dequant - fp32| over the
        weight-only-quantized decode weights (half the largest
        quantization step; 0.0 when quant is off)."""
        return float(getattr(self.backend, "_weight_bound", 0.0))

    def quant_error_bound(self) -> dict:
        """Both quantization error components of the decode path, from
        the live engine: ``{"kv": ..., "weights": ...}`` (each 0.0 when
        that half is off). Also refreshes the
        ``pt_serving_{kv,weight}_error_bound`` gauges, so a scrape
        after any call carries the current bounds."""
        kv, w = self.kv_error_bound(), self.weight_error_bound()
        if _om.enabled():
            from .quant import _M_KV_BOUND, _M_W_BOUND
            _M_KV_BOUND.set(kv)
            _M_W_BOUND.set(w)
        return {"kv": kv, "weights": w}

    def decode_bytes_per_step(self) -> dict:
        """Estimated HBM bytes ONE decode step reads:
        ``{"weights": ..., "kv": ..., "total": ...}`` — every
        weight/buffer byte (codes + scales under weight-only quant)
        plus the KV pool's resident bytes (codes + scales under the
        int8 arena). Capacity-based: the paged read only touches live
        blocks, so the kv term is an upper bound — but it is the term
        quantization shrinks, which is what the A/B measures."""
        if self._bytes_step is None:
            w = self.backend.param_bytes() \
                if hasattr(self.backend, "param_bytes") else 0
            kv = sum(int(c.nbytes) for c in self._cache)
            self._bytes_step = {"weights": w, "kv": kv,
                                "total": w + kv}
        return self._bytes_step

    def _note_decode_bytes(self, steps: int):
        """Metrics hook on the decode dispatch path (one enabled-check
        when metrics are off)."""
        if not _om.enabled():
            return
        b = self.decode_bytes_per_step()
        _M_BYTES.inc(b["total"] * steps)
        _M_W_BYTES.set(b["weights"])
        _M_KV_BYTES.set(b["kv"])

    def bucket_len(self, prompt_len: int) -> int:
        if self.prompt_buckets is None:
            return prompt_len
        for b in self.prompt_buckets:
            if b >= prompt_len:
                return b
        raise ValueError(
            f"prompt length {prompt_len} exceeds the largest bucket "
            f"{self.prompt_buckets[-1]}")

    def validate_request(self, prompt_len: int, max_new_tokens: int):
        """Raise ValueError if the request can never fit a slot — run
        at submit time so a bad request is rejected at the door instead
        of aborting the serving loop mid-stream at admission."""
        if prompt_len <= 0:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens={max_new_tokens}; must be >= 1")
        lb = self.bucket_len(prompt_len)
        if lb + max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt bucket ({lb}) + max_new_tokens "
                f"({max_new_tokens}) exceeds the slot capacity "
                f"({self.max_len}); raise max_len or shorten the request")

    # -- admission ---------------------------------------------------------
    def admit(self, request) -> bool:
        """Prefill the request's prompt (batch-1, left-padded to its
        bucket) and splice the row into a free slot. Returns True if the
        request already finished at admission (max_new==1 or eos on the
        first token) — it then never occupies a slot. A request carrying
        preemption ``resume`` state re-prefills its generated history
        instead (see :meth:`_admit_resume`)."""
        from ..profiler import RecordEvent
        resume = getattr(request, "resume", None)
        if resume is not None and resume.tokens:
            return self._admit_resume(request, resume)
        prompt = np.asarray(request.prompt, np.int32).reshape(-1)
        L = int(prompt.shape[0])
        self.validate_request(L, request.max_new_tokens)
        Lb = self.bucket_len(L)
        slot = next((i for i, s in enumerate(self._slots) if s is None),
                    None)
        if slot is None:
            raise RuntimeError("no free slot (scheduler bug)")
        tr = self.tracer
        if tr is not None:
            tr.span_end(request.request_id, "queue_wait")
            t_prefill = _trace_now()
        ids = np.zeros((1, Lb), np.int32)
        ids[0, Lb - L:] = prompt
        pad0 = Lb - L
        key = jax.random.PRNGKey(request.seed)
        key, sub = jax.random.split(key)      # generate()'s key schedule
        temp = jnp.float32(request.temperature)   # <= 0 means greedy
        topk = jnp.int32(request.top_k)
        topp = jnp.float32(request.top_p)
        with RecordEvent("serving.prefill"):
            tok0_dev, row = self.backend.prefill(
                Lb, jnp.asarray(ids), jnp.asarray([pad0], jnp.int32),
                sub, temp, topk, topp)
        tok0 = int(tok0_dev)
        if tr is not None:
            tr.span_at(request.request_id, "prefill", t_prefill,
                       tokens=L, bucket=Lb)
        _M_PREFILLS.inc()
        _M_TOKENS.inc()
        run = _SlotRun(request, tokens=[tok0], t_admit=time.perf_counter())
        self.tokens_emitted += 1
        eos = request.eos_token_id
        rem0 = request.max_new_tokens - 1
        if eos is not None and tok0 == eos:
            rem0 = 0
        if rem0 <= 0:
            run.t_done = time.perf_counter()
            self._finished.append(run)
            return True
        with RecordEvent("serving.admit"):
            self._cache, self._state = self._admit_jit(
                self._cache, self._state, row, jnp.int32(slot),
                jnp.int32(tok0), jnp.int32(Lb), jnp.int32(pad0),
                jnp.int32(rem0),
                jnp.int32(-1 if eos is None else eos),
                temp, topk, topp, key)
        if tr is not None:
            tr.span_begin(request.request_id, "decode", slot=slot)
        self._slots[slot] = run
        self._remaining_host[slot] = rem0
        return False

    def _admit_resume(self, request, resume) -> bool:
        """Re-admit a preempted request: re-prefill prompt + generated
        history — the KV the eviction dropped — into a fresh row, then
        arm the slot with the CARRIED stream state (``tokens[-1]`` as
        the in-hand next token, the saved rng key, the remaining token
        budget). The re-prefill's in-graph sample is DISCARDED (the
        stream already owns its next token, and the saved key must not
        be advanced), so the resumed greedy AND seeded-sampled streams
        are bit-identical to an uninterrupted run. Padding shifts are
        invisible by construction: RoPE positions are pad-corrected and
        masked slots contribute exact zeros, the same property that
        makes bucket-padded serving equal generate()."""
        from ..profiler import RecordEvent
        prompt = np.asarray(request.prompt, np.int32).reshape(-1)
        toks = list(resume.tokens)
        full = np.concatenate([prompt, np.asarray(toks[:-1], np.int32)])
        pl = int(full.shape[0])
        rem0 = request.max_new_tokens - len(toks)
        self.validate_request(pl, rem0 + 1)
        Lb = self.bucket_len(pl)
        slot = next((i for i, s in enumerate(self._slots) if s is None),
                    None)
        if slot is None:
            raise RuntimeError("no free slot (scheduler bug)")
        tr = self.tracer
        if tr is not None:
            tr.span_end(request.request_id, "queue_wait", resumed=True)
            t_prefill = _trace_now()
        ids = np.zeros((1, Lb), np.int32)
        ids[0, Lb - pl:] = full
        pad0 = Lb - pl
        with RecordEvent("serving.prefill"):
            _discard, row = self.backend.prefill(
                Lb, jnp.asarray(ids), jnp.asarray([pad0], jnp.int32),
                jax.random.PRNGKey(0), jnp.float32(0.0), jnp.int32(0),
                jnp.float32(1.0))
        if tr is not None:
            tr.span_at(request.request_id, "prefill", t_prefill,
                       tokens=pl, bucket=Lb, resumed=True)
        _M_PREFILLS.inc()
        # t_admit carries over: the first token existed before the
        # eviction, so TTFT keeps measuring the first admission
        run = _SlotRun(request, tokens=toks, t_admit=resume.t_admit)
        eos = request.eos_token_id
        with RecordEvent("serving.admit"):
            self._cache, self._state = self._admit_jit(
                self._cache, self._state, row, jnp.int32(slot),
                jnp.int32(toks[-1]), jnp.int32(Lb), jnp.int32(pad0),
                jnp.int32(rem0),
                jnp.int32(-1 if eos is None else eos),
                jnp.float32(request.temperature),
                jnp.int32(request.top_k), jnp.float32(request.top_p),
                jnp.asarray(np.asarray(resume.key, np.uint32)))
        if tr is not None:
            tr.instant(request.request_id, "resume", slot=slot,
                       reused_tokens=len(toks))
            tr.span_begin(request.request_id, "decode", slot=slot)
        self._slots[slot] = run
        self._remaining_host[slot] = rem0
        request.resume = None       # consumed; a later preemption
        return False                # rebuilds it from the live run

    def try_admit(self, request) -> bool:
        """Admit if resources allow; False means "retry later" (the
        paged engine's block pool can be exhausted even with a free
        slot — the dense engine always admits into a free slot)."""
        self.admit(request)
        return True

    # -- preemption --------------------------------------------------------
    def can_resume(self, run: "_SlotRun") -> bool:
        """Whether a preempted ``run`` could later be re-admitted: its
        prompt + generated history must still fit the engine (dense: a
        prompt bucket; paged: the block pool). The preemption policy
        checks this BEFORE evicting — a victim that could never come
        back would be a silent kill, not a preemption."""
        if not run.tokens:
            return True          # mid-prefill: requeues as submitted
        req = run.request
        pl = int(np.asarray(req.prompt).reshape(-1).shape[0]) \
            + len(run.tokens) - 1
        mnt = req.max_new_tokens - len(run.tokens) + 1
        try:
            self.validate_request(pl, mnt)
        except ValueError:
            return False
        return True

    def preempt_slot(self, slot: int):
        """Evict the request in ``slot`` mid-flight WITHOUT failing it:
        the slot is killed in-graph through the same ``_cancel_fn``
        program deadlines use, its resources release (paged blocks at
        exact refcounts — the prefix-index entries are retained, which
        is what makes the later re-prefill mostly cache hits), and the
        run is handed back to the caller with the slot's rng key so the
        request can requeue carrying :class:`~.scheduler.ResumeState`.
        Returns ``(run, key)``; ``key`` is None for a mid-prefill
        victim (nothing armed yet — it requeues as-submitted). Only
        legal at a tick boundary, like snapshots."""
        run = self._slots[slot]
        if run is None:
            raise RuntimeError(f"slot {slot} is empty")
        if self._pending_block is not None:
            raise RuntimeError(
                "preempt only at a tick boundary — a dispatched decode "
                "block is awaiting harvest (call step_block first)")
        key = None
        if slot in self._prefill_slots:
            self._prefill_slots.discard(slot)
            self._abort_prefill(slot)
        else:
            key = np.asarray(self._state["key"])[slot].copy()
            self._state = self._cancel_jit(self._state, jnp.int32(slot))
        if self.tracer is not None:
            rid = run.request.request_id
            self.tracer.span_end(rid, "decode", preempted=True)
            self.tracer.instant(rid, "preempt", slot=slot,
                                tokens=len(run.tokens))
        self._slots[slot] = None
        self._remaining_host[slot] = 0
        self._release_slot_resources(run)
        return run, key

    def _release_slot_resources(self, run: "_SlotRun"):
        """Free everything a preempted run held besides the slot
        itself — dense rows are pool-owned, nothing to do (the paged
        engine releases the run's arena blocks here)."""

    # -- decode ------------------------------------------------------------
    def has_pending_harvest(self) -> bool:
        """A decode block was dispatched but its host transfer failed —
        the next :meth:`step_block` retries just the harvest."""
        return self._pending_block is not None

    def step_block(self):
        """Run one compiled decode block over the pool, then sync ONCE:
        pull the token matrix + remaining counters, credit each live
        slot its emitted tokens, retire finished slots.

        Failure semantics (fault sites / resilience): the
        ``serving.step_block`` site raises BEFORE the device dispatch
        (state untouched — a retry re-runs the identical block), and
        ``serving.harvest`` raises between dispatch and the host
        transfer; the dispatched outputs park in ``_pending_block`` so
        a retry harvests them without re-stepping (no token is ever
        decoded twice or dropped). A slot whose log-probs went NaN is
        quarantined alone via :meth:`cancel_slot` — the other rows'
        streams are untouched (bit-identical, pinned in tests)."""
        from ..profiler import RecordEvent
        if self._pending_block is None:
            if not self.has_decoding():
                return
            if faults.should_fire("serving.poison"):
                self._poison_live_slot()
            faults.fault_point("serving.step_block")
            with RecordEvent("serving.decode_block"):
                out = self.backend.decode_block(self._cache, self._state)
            self._cache, self._state = out[0], out[1]
            # old AOT artifacts predate the ok flags: pad with None
            self._pending_block = tuple(out[2:]) \
                if len(out) > 4 else (out[2], out[3], None)
            self.steps += self.decode_block
            self.slot_steps += self.decode_block * self.num_slots
            _M_STEPS.inc(self.decode_block)
            _M_COMPILES.set(self.backend.decode_traces[0])
            self._note_decode_bytes(self.decode_block)
        faults.fault_point("serving.harvest")
        toks, lives, oks = self._pending_block
        toks_np = np.asarray(toks)                  # ONE host sync/block
        lives_np = np.asarray(lives)                # (block, S)
        oks_np = None if oks is None else np.asarray(oks)
        rem_np = np.asarray(self._state["remaining"])
        self._pending_block = None
        emitted = int(lives_np.sum())
        self.decode_tokens += emitted
        self.tokens_emitted += emitted
        _M_DECODE_TOKENS.inc(emitted)
        _M_TOKENS.inc(emitted)
        now = time.perf_counter()
        for slot, run in enumerate(self._slots):
            if run is None or slot in self._prefill_slots:
                continue     # mid-prefill slots are not decoding yet
            # live is monotone within a block (True rows are a prefix)
            n = int(lives_np[:, slot].sum())
            if n > 0:
                run.tokens.extend(int(t) for t in toks_np[:n, slot])
            if self.nan_sentinel and oks_np is not None and n > 0 \
                    and not bool(oks_np[:n, slot].all()):
                self.cancel_slot(slot, "poisoned")
                continue
            self._remaining_host[slot] = rem_np[slot]
            if rem_np[slot] == 0:
                self._retire(slot, run, now)

    # -- cancellation / quarantine ----------------------------------------
    def live_runs(self):
        """Host bookkeeping of every occupied slot: [(slot, _SlotRun)]
        (mid-prefill slots included) — the resilience layer's deadline
        scan."""
        return [(i, r) for i, r in enumerate(self._slots)
                if r is not None]

    def cancel_slot(self, slot: int, reason: str) -> bool:
        """Cancel the request in ``slot`` mid-flight: kill the slot
        in-graph (live drops before the next decode block), release its
        resources (paged: arena blocks at correct refcounts, pending
        prefill job dropped), and surface the run through
        ``drain_finished`` with ``failure=reason`` so the Server records
        a RequestFailure instead of hanging the stream."""
        run = self._slots[slot]
        if run is None:
            return False
        run.failure = reason
        if slot in self._prefill_slots:
            self._prefill_slots.discard(slot)
            self._abort_prefill(slot)   # paged: drop the pending job
        else:
            self._state = self._cancel_jit(self._state, jnp.int32(slot))
        self._retire(slot, run, time.perf_counter())
        self._remaining_host[slot] = 0
        return True

    def _abort_prefill(self, slot):
        """Dense admission is synchronous — nothing to abort."""

    def _poison_live_slot(self):
        """Fault action for the ``serving.poison`` site: corrupt the
        FIRST decoding slot's KV cache row with NaN so its next logits
        trip the sentinel. Only that slot's row is touched — the
        quarantine-blast-radius invariant the chaos tests pin."""
        for slot, run in enumerate(self._slots):
            if run is not None and slot not in self._prefill_slots:
                self._cache = tuple(
                    c.at[slot].set(jnp.nan)
                    if jnp.issubdtype(c.dtype, jnp.floating) else c
                    for c in self._cache)
                return slot
        return None

    def _retire(self, slot, run, now):
        """Move a finished slot to the harvest list (the paged engine
        also releases the slot's arena blocks here)."""
        run.t_done = now
        if self.tracer is not None:
            self.tracer.span_end(run.request.request_id, "decode",
                                 tokens=len(run.tokens))
        self._finished.append(run)
        self._slots[slot] = None

    def drain_finished(self) -> List[_SlotRun]:
        done, self._finished = self._finished, []
        return done

    # -- crash-safe snapshot / restore -------------------------------------
    def _run_meta(self, run: _SlotRun) -> dict:
        from .resilience import request_to_meta
        return {"request": request_to_meta(run.request),
                "tokens": [int(t) for t in run.tokens],
                "t_admit": run.t_admit, "t_done": run.t_done,
                "failure": run.failure,
                "block_ids": None if run.block_ids is None
                else [int(b) for b in run.block_ids]}

    def _run_from_meta(self, meta: dict, prompt) -> _SlotRun:
        from .resilience import request_from_meta
        return _SlotRun(request=request_from_meta(meta["request"], prompt),
                        tokens=list(meta["tokens"]),
                        t_admit=meta["t_admit"], t_done=meta["t_done"],
                        failure=meta["failure"],
                        block_ids=None if meta["block_ids"] is None
                        else list(meta["block_ids"]))

    def snapshot_state(self):
        """(meta dict, host-array dict) capturing everything needed to
        resume every in-flight stream: the KV cache, the in-graph slot
        state (positions, rng keys, sampling params — and the paged
        block tables riding it), and the host bookkeeping. Taken at a
        tick boundary (the only host-consistent point); a restored
        engine finishes each stream bit-identical to an uninterrupted
        run because the decode program is a pure function of exactly
        this state."""
        if self._pending_block is not None:
            raise RuntimeError(
                "snapshot only at a tick boundary — a dispatched decode "
                "block is awaiting harvest (call step_block first)")
        arrays = {}
        for i, c in enumerate(self._cache):
            arrays[f"cache_{i}"] = np.asarray(c)
        for k, v in self._state.items():
            arrays[f"state_{k}"] = np.asarray(v)
        slots_meta = []
        for i, run in enumerate(self._slots):
            if run is None:
                slots_meta.append(None)
                continue
            arrays[f"slot{i}_prompt"] = np.asarray(
                run.request.prompt, np.int32).reshape(-1)
            slots_meta.append(self._run_meta(run))
        fin_meta = []
        for j, run in enumerate(self._finished):
            arrays[f"fin{j}_prompt"] = np.asarray(
                run.request.prompt, np.int32).reshape(-1)
            fin_meta.append(self._run_meta(run))
        meta = {
            "engine_class": type(self).__name__,
            # artifact-backed engines record which programs produced
            # this state; model-backed engines record None (either side
            # None -> compatibility is left to the pool_specs check)
            "backend_artifact": getattr(self.backend,
                                        "artifact_fingerprint", None),
            "num_slots": self.num_slots, "max_len": self.max_len,
            "decode_block": self.decode_block,
            "pool_specs": [[list(s), str(np.dtype(d))]
                           for s, d in self.backend.pool_specs],
            "remaining": [int(r) for r in self._remaining_host],
            "prefill_slots": sorted(self._prefill_slots),
            "slots": slots_meta, "finished": fin_meta,
            "counters": {"steps": self.steps,
                         "tokens_emitted": self.tokens_emitted,
                         "decode_tokens": self.decode_tokens,
                         "slot_steps": self.slot_steps},
        }
        return meta, arrays

    def restore_state(self, meta: dict, arrays: dict):
        """Inverse of :meth:`snapshot_state`, into a freshly
        constructed engine of the SAME configuration (same model/
        backend shapes — validated against ``pool_specs``). Compiled
        programs are rebuilt lazily by the new process; only state is
        restored."""
        want = [[list(s), str(np.dtype(d))]
                for s, d in self.backend.pool_specs]
        if meta["pool_specs"] != want:
            raise ValueError(
                "snapshot pool_specs do not match this engine — restore "
                "needs the same model config / slots / max_len / paging "
                f"layout (saved {meta['pool_specs'][:2]}..., engine "
                f"{want[:2]}...)")
        if meta["engine_class"] != type(self).__name__:
            raise ValueError(
                f"snapshot was taken by {meta['engine_class']}, this "
                f"engine is {type(self).__name__} (dense/paged mismatch)")
        saved_fp = meta.get("backend_artifact")
        cur_fp = getattr(self.backend, "artifact_fingerprint", None)
        if saved_fp is not None and cur_fp is not None \
                and saved_fp != cur_fp:
            raise ValueError(
                "snapshot was taken on a different AOT artifact "
                f"(saved {saved_fp[:12]}..., this backend "
                f"{cur_fp[:12]}...) — restore with the artifact that "
                "produced the snapshot")
        self.reset()
        self._cache = tuple(jnp.asarray(arrays[f"cache_{i}"])
                            for i in range(len(self.backend.pool_specs)))
        self._state = {k: jnp.asarray(arrays[f"state_{k}"])
                       for k in self.backend.init_state()}
        commit = getattr(self.backend, "commit_arrays", None)
        if commit is not None:        # TP backends re-shard onto the mesh
            self._cache, self._state = commit(self._cache, self._state)
        self._slots = [
            None if m is None
            else self._run_from_meta(m, arrays[f"slot{i}_prompt"])
            for i, m in enumerate(meta["slots"])]
        self._finished = [
            self._run_from_meta(m, arrays[f"fin{j}_prompt"])
            for j, m in enumerate(meta["finished"])]
        self._prefill_slots = set(meta["prefill_slots"])
        self._remaining_host = np.asarray(meta["remaining"], np.int64)
        c = meta["counters"]
        self.steps = c["steps"]
        self.tokens_emitted = c["tokens_emitted"]
        self.decode_tokens = c["decode_tokens"]
        self.slot_steps = c["slot_steps"]

    def snapshot(self, path: str):
        """Write a crash-safe engine snapshot (single npz file, atomic
        tmp+rename via the checkpoint write helpers)."""
        from .resilience import save_snapshot
        meta, arrays = self.snapshot_state()
        save_snapshot(path, {"engine": meta}, arrays)

    def restore(self, path: str):
        from .resilience import load_snapshot
        meta, arrays = load_snapshot(path)
        self.restore_state(meta["engine"], arrays)
