"""Tensor-parallel serving: the ONE compiled decode block sharded over
a TPU mesh.

The slot-pool engine (engine.py / paging.py) runs its single compiled
decode program on one chip, so the max servable model is one chip's HBM
and decode bandwidth is one chip's. This module shards that same
program — and the chunked-prefill program — over a
``jax.sharding.Mesh`` via ``shard_map``:

- the KV cache is sharded on the **kv-head axis**: the dense
  ``(S, max_len, kvh, d)`` per-slot rows AND the paged
  ``(num_blocks, block_size, kvh, d)`` arena (plus its int8 scale
  arrays) split dim 2 across the TP axes, so per-chip KV HBM shrinks by
  the TP degree — the single-chip ceiling the ROADMAP names;
- attention weights are column-sharded (q/k/v out dims — each device
  owns a contiguous group of heads aligned with its kv-head shard),
  MLP gate/up column-sharded, lm_head vocab-sharded; per-slot state
  (pos/live/keys/sampling params/block tables) is replicated;
- the final logits are produced through the
  ``distributed/collectives`` all-gather path: the hierarchical plan is
  auto-selected from the mesh topology (``plan_hierarchy``), so a
  reduction spanning two mesh levels rides the HiCCL inner/outer
  decomposition.

Two weight layouts, selected by ``TPConfig.mode``:

- ``"exact"`` (default): o_proj / down_proj / embedding stay
  REPLICATED and the sharded activations are all-gathered in front of
  them. Every cross-device collective is then pure data movement
  (gather of independent head/column slices), so sharded greedy AND
  seeded-sampled streams are **bit-identical** to the 1-chip engine —
  the serving bit-identity harness is the verifier.
- ``"psum"``: the Megatron row-parallel layout — o_proj / down_proj
  are row-sharded and the hidden state is all-reduced per layer.
  Sums reassociate, so this mode is *not* bit-identical; in exchange
  every large weight is sharded. ``TPConfig.int8`` compresses the
  hidden-state all-reduce with the EQuARX wire format
  (``collectives.quantized``); the worst-case error is
  runtime-queryable via :meth:`engine.tp_int8_error_bound` and gated
  by ``TPConfig.int8_max_error`` — the first decode block probes the
  bound against the live cache/state and refuses to run over budget.

Everything is default-off: pass ``tp=TPConfig(...)`` (or ``tp=True``)
to ``ContinuousBatchingEngine`` / the paged engine, or set
``PT_SERVING_TP=1`` (axes via ``PT_SERVING_TP_AXES``, comma-separated
mesh axis names, default ``"mp"``; ``PT_SERVING_TP_MODE`` /
``PT_SERVING_TP_INT8`` select the layout). An explicitly passed
backend is never rerouted by the env flags.
"""
from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..distributed.collectives.hierarchical import plan_hierarchy
from ..distributed.mesh import get_current_mesh
from ..observability import metrics as _om
from ..utils import tp_hooks
# the trace-time hooks the model's forward calls live in
# utils/tp_hooks.py (dependency-light on purpose: models must not
# import the serving package at module level — see that docstring);
# re-exported here so TP users find them next to the backends
from ..utils.tp_hooks import (current_tp, maybe_gather,  # noqa: F401
                              maybe_gather_logits, maybe_reduce)
from ..utils.flags import env_bool, env_str
from .engine import (ModelStepBackend, build_slot_block_fn,
                     build_slot_prefill_fn)
from .paging import PagedModelStepBackend

__all__ = ["TPConfig", "resolve_tp_config", "ShardedModelStepBackend",
           "ShardedPagedStepBackend"]

# mesh-shape gauges (no-ops until metrics.enable()/PT_METRICS): the
# observability satellite — per-collective bytes/calls already ride
# pt_collectives_* (noted per dispatched block below); these record the
# topology the decode block is sharded over
_M_TP_DEVICES = _om.gauge(
    "pt_serving_tp_devices",
    "devices the serving decode block is sharded over (1 = TP off)")
_M_TP_AXIS = _om.gauge(
    "pt_serving_tp_mesh_axis_size",
    "mesh axis sizes of the serving TP mesh", labels=("axis",))


# ---------------------------------------------------------------------------
# config
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class TPConfig:
    """How to shard the serving decode block.

    ``axes``: mesh axis names the weights/KV heads split over (the
    hierarchical collective plan is derived from their mesh order;
    degree-1 axes are dropped). ``mode``: ``"exact"`` | ``"psum"`` (see
    module docstring). ``int8``: compress the psum-mode hidden-state
    all-reduce; ``int8_max_error`` arms the runtime gate on the
    queryable EQuARX bound. ``mesh``: defaults to the process-current
    mesh (``distributed.mesh.get_current_mesh``)."""
    axes: Tuple[str, ...] = ("mp",)
    mode: str = "exact"
    int8: bool = False
    int8_max_error: Optional[float] = None
    mesh: Optional[Mesh] = None

    def __post_init__(self):
        if self.mode not in ("exact", "psum"):
            raise ValueError(f"TPConfig.mode={self.mode!r}; expected "
                             "'exact' or 'psum'")
        if self.int8 and self.mode != "psum":
            raise ValueError(
                "TPConfig.int8 compresses the hidden-state all-reduce, "
                "which only exists in mode='psum' (exact mode has no "
                "reduction to compress)")
        if isinstance(self.axes, str):
            object.__setattr__(self, "axes", (self.axes,))
        else:
            object.__setattr__(self, "axes", tuple(self.axes))


def resolve_tp_config(tp) -> Optional[TPConfig]:
    """Normalize the engine's ``tp`` argument: TPConfig pass-through,
    ``True`` -> defaults, ``False`` -> off, ``None`` -> the
    ``PT_SERVING_TP`` env knobs (routed through the flags helpers)."""
    if isinstance(tp, TPConfig):
        return tp
    if tp is True:
        return TPConfig()
    if tp is False:
        return None
    if tp is not None:
        raise ValueError(f"tp={tp!r}: pass a TPConfig, True/False, or "
                         "None (env-controlled)")
    if not env_bool("PT_SERVING_TP"):
        return None
    axes = tuple(a.strip() for a in
                 env_str("PT_SERVING_TP_AXES", "mp").split(",")
                 if a.strip())
    return TPConfig(axes=axes or ("mp",),
                    mode=env_str("PT_SERVING_TP_MODE", "exact"),
                    int8=env_bool("PT_SERVING_TP_INT8"))


# ---------------------------------------------------------------------------
# backend mixin: spec derivation + shard_map wrapping
# ---------------------------------------------------------------------------

def _param_pspec(name: str, sharding_spec, mode: str,
                 axes: Tuple[str, ...]) -> P:
    """Serving partition spec for one parameter, derived from the
    training-time ``_sharding_spec`` the model already attaches
    (llama's Column/Row pattern over "mp"):

    - out-dim ("column") shards stay sharded in both modes — their
      gathers are exact;
    - in-dim ("row") shards (o_proj/down_proj) replicate in exact mode
      and stay row-sharded in psum mode;
    - the embedding table always replicates (a sharded-vocab lookup
      needs mask+psum semantics the decode block does not carry).
    """
    if sharding_spec is None:
        return P()
    dims = tuple(sharding_spec)
    idx = [i for i, d in enumerate(dims)
           if d == "mp" or (isinstance(d, (tuple, list)) and "mp" in d)]
    if not idx:
        return P()
    if "embed_tokens" in name or "embedding" in name:
        return P()
    i = idx[0]
    if mode == "exact" and i == 0:
        return P()                    # row-parallel weight: replicate
    return P(*[axes if j == i else None for j in range(len(dims))])


class _TPBackendMixin:
    """Shared TP plumbing for the dense and paged sharded backends."""

    def _setup_tp(self, model, tp: TPConfig):
        mesh = tp.mesh if tp.mesh is not None else get_current_mesh()
        if mesh is None:
            raise ValueError(
                "tensor-parallel serving needs a mesh: build one via "
                "HybridCommunicateGroup/build_device_mesh (sets the "
                "current mesh) or pass TPConfig(mesh=...)")
        sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
        for a in tp.axes:
            if a not in sizes:
                raise ValueError(f"TP axis {a!r} not in mesh axes "
                                 f"{tuple(sizes)}")
        plan = plan_hierarchy(tp.axes, mesh)
        if plan.total_size < 2:
            raise ValueError(
                f"TP axes {tp.axes} have total degree "
                f"{plan.total_size} on this mesh — nothing to shard "
                "(drop tp= or grow the mesh)")
        self.tp = tp
        self.tp_mesh = mesh
        self.tp_plan = plan
        self.tp_degree = plan.total_size
        self._tp_spec = tp_hooks.TPSpec(plan=plan,
                                        degree=plan.total_size,
                                        mode=tp.mode, int8=tp.int8)
        # parameter specs, aligned with self._pv construction order
        named = list(model.named_parameters())
        self._pv_pspecs = [
            _param_pspec(n, getattr(p, "_sharding_spec", None),
                         tp.mode, plan.axes) for n, p in named]
        self._bv_pspecs = [P() for _ in self._bv]
        sharded = [(n, s, p) for (n, p), s in zip(named, self._pv_pspecs)
                   if s != P()]
        if not sharded:
            raise ValueError(
                f"{type(model).__name__} carries no 'mp' partition "
                "specs — build it with tensor_parallel=True (or attach "
                "_sharding_spec to its weights) before sharding the "
                "decode block")
        d = self.tp_degree
        for n, s, p in sharded:
            dim = next(i for i, e in enumerate(tuple(s)) if e)
            if p._value.shape[dim] % d:
                raise ValueError(
                    f"parameter {n} dim {dim} ({p._value.shape[dim]}) "
                    f"is not divisible by the TP degree {d}")
        cfg = getattr(model, "config", None)
        for attr in ("num_attention_heads", "num_key_value_heads"):
            hv = getattr(cfg, attr, None)
            if hv is not None and hv % d:
                raise ValueError(
                    f"{attr}={hv} not divisible by TP degree {d} — "
                    "head-axis sharding needs whole heads per device")
        if self._qmeta:
            # weight-only quant composes with the exact layout only:
            # per-shard scales ride the weight's out-dim axes (a
            # row-sharded psum weight would split int4 nibble packing
            # and group boundaries on the in dim — refused, not
            # silently de-quantized)
            if tp.mode != "exact":
                raise NotImplementedError(
                    "weight-only serving quant composes with tp "
                    "mode='exact' only — row-parallel (psum) shards "
                    "split the quantized in dim; drop quant= or use "
                    "mode='exact'")
            from .quant import scale_pspec
            for i in self._qmeta:
                scales = self._pv[i][1]
                self._pv_pspecs[i] = (self._pv_pspecs[i],
                                      scale_pspec(self._pv_pspecs[i],
                                                  scales))
        # the KV cache shards its kv-head dim (dim 2 of every pool leaf,
        # 4D arenas/rows and 3D int8 scale arrays alike)
        for shape, _ in self.pool_specs:
            if shape[2] % d:
                raise ValueError(
                    f"KV cache kv-head dim ({shape[2]}) not divisible "
                    f"by TP degree {d}")
        self._cache_pspecs = tuple(
            P(None, None, plan.axes) if len(shape) == 3
            else P(None, None, plan.axes, None)
            for shape, _ in self.pool_specs)
        self._state_pspecs = jax.tree.map(lambda _: P(),
                                          super().init_state())
        # shard-commit the weights once (uncommitted arrays would be
        # re-laid-out on every dispatch; quantized entries are
        # (codes, scales) tuples with matching spec tuples)
        def _commit(v, s):
            if isinstance(s, tuple) and not isinstance(s, P):
                return tuple(jax.device_put(a, NamedSharding(mesh, ps))
                             for a, ps in zip(v, s))
            return jax.device_put(v, NamedSharding(mesh, s))
        self._pv = [_commit(v, s)
                    for v, s in zip(self._pv, self._pv_pspecs)]
        self._bv = [jax.device_put(v, NamedSharding(mesh, P()))
                    for v in self._bv]
        self._int8_gate_pending = tp.int8 and \
            tp.int8_max_error is not None
        self._bound_jit = None
        self._note_mesh_metrics()
        self._setup_collective_accounting(model)

    # -- observability ----------------------------------------------------
    def _note_mesh_metrics(self):
        if not _om.enabled():
            return
        _M_TP_DEVICES.set(self.tp_degree)
        sizes = dict(zip(self.tp_mesh.axis_names,
                         self.tp_mesh.devices.shape))
        for a in self.tp_plan.axes:
            _M_TP_AXIS.set(sizes[a], axis=a)

    def _setup_collective_accounting(self, model):
        """Static per-TOKEN collective payloads. The in-graph gathers
        never cross the host-level ``collectives`` wrappers (where the
        pt_collectives_* families are normally noted), so the backend
        accounts them here, derived from the model dims: a decode step
        moves S tokens (one per slot), a dense prefill bucket_len
        tokens, a prefill chunk prefill_chunk tokens — each compiled
        dispatch fires 2L+1 collectives regardless of token count.
        Noted under mode="tp_graph" with op="tp_block" (decode) vs
        op="tp_prefill", so per-decode-step rates never mix in
        prefill traffic."""
        cfg = getattr(model, "config", None)
        self._tp_bytes_tok = 0
        self._tp_calls_dispatch = 0
        if cfg is None:
            return
        h = cfg.hidden_size
        ff = cfg.intermediate_size
        V = cfg.vocab_size
        L = cfg.num_hidden_layers
        if self.tp.mode == "exact":
            # per token: L head-gathers (h) + L act-gathers (ff) + the
            # logits gather (V), fp32
            self._tp_bytes_tok = 4 * (L * (h + ff) + V)
        else:
            # psum: L attention + L mlp all-reduces (h) per token +
            # logits gather; int8 hops carry ~(1 + 4/bucket) B/element
            per_el = 1.03 if self.tp.int8 else 4
            self._tp_bytes_tok = int(2 * L * h * per_el + 4 * V)
        self._tp_calls_dispatch = 2 * L + 1

    def _note_collectives(self, op: str, dispatches: int, tokens: int):
        if not _om.enabled() or not self._tp_calls_dispatch:
            return
        mode = "tp_graph" + (",int8" if self.tp.int8 else "")
        _om.counter("pt_collectives_calls_total",
                    "host-level collective dispatches",
                    labels=("op", "mode")).inc(
            self._tp_calls_dispatch * dispatches, op=op, mode=mode)
        _om.counter("pt_collectives_bytes_total",
                    "payload bytes handed to collectives",
                    labels=("op", "mode")).inc(
            self._tp_bytes_tok * tokens, op=op, mode=mode)
        self._note_mesh_metrics()

    # -- shard_map plumbing -----------------------------------------------
    def _shard_jit(self, fn, in_specs, out_specs, donate=()):
        from jax.experimental.shard_map import shard_map
        spec = self._tp_spec

        def tp_fn(*args):
            with tp_hooks.active(spec):
                return fn(*args)

        return jax.jit(shard_map(tp_fn, mesh=self.tp_mesh,
                                 in_specs=in_specs,
                                 out_specs=out_specs, check_rep=False),
                       donate_argnums=donate)

    def _replicate(self, tree):
        sh = NamedSharding(self.tp_mesh, P())
        return jax.tree.map(lambda a: jax.device_put(a, sh), tree)

    def pool_cache(self):
        return tuple(
            jax.device_put(jnp.zeros(shape, dtype),
                           NamedSharding(self.tp_mesh, s))
            for (shape, dtype), s in zip(self.pool_specs,
                                         self._cache_pspecs))

    def init_state(self):
        return self._replicate(super().init_state())

    def commit_arrays(self, cache_flat, state):
        """Re-commit restored host arrays onto the mesh (snapshot
        restore hands plain ``jnp.asarray`` values)."""
        cache = tuple(
            jax.device_put(c, NamedSharding(self.tp_mesh, s))
            for c, s in zip(cache_flat, self._cache_pspecs))
        return cache, self._replicate(state)

    # -- int8 bound probe + gate ------------------------------------------
    def _int8_bound_fn(self):
        """One decode STEP (not a block) with the bound sink armed:
        returns the worst runtime EQuARX bound over every int8 hop of
        the live cache/state. A separate tiny program — the decode
        block itself stays unchanged and its compile count stays 1."""
        spec = self._tp_spec
        pure, paged = self._pure, isinstance(self,
                                             PagedModelStepBackend)

        def probe(pv, bv, cache_flat, state):
            sink: list = []
            tp_hooks._BOUND_SINK = sink
            try:
                with tp_hooks.active(spec):
                    if paged:
                        tbl = jnp.where(state["live"][:, None],
                                        state["table"], 0)
                        logp, _ = pure(pv, bv, state["tok"][:, None],
                                       cache_flat, state["pos"], None,
                                       None, tbl)
                    else:
                        logp, _ = pure(pv, bv, state["tok"][:, None],
                                       cache_flat, state["pos"], None,
                                       state["pad"])
            finally:
                tp_hooks._BOUND_SINK = None
            del logp
            if not sink:
                return jnp.float32(0.0)
            return jnp.max(jnp.stack(sink))

        return self._shard_jit(
            probe,
            in_specs=(self._pv_pspecs, self._bv_pspecs,
                      self._cache_pspecs, self._state_pspecs),
            out_specs=P())

    def tp_int8_error_bound(self, cache_flat, state) -> float:
        """Runtime worst-case elementwise |int8 all-reduce - fp32| over
        the decode step's hidden-state hops, from the LIVE cache/state
        (0.0 when the int8 hop is off)."""
        if not self.tp.int8:
            return 0.0
        if self._bound_jit is None:
            self._bound_jit = self._int8_bound_fn()
        return float(self._bound_jit(self._pv, self._bv, cache_flat,
                                     state))

    def _check_int8_gate(self, cache_flat, state):
        if not self._int8_gate_pending:
            return
        bound = self.tp_int8_error_bound(cache_flat, state)
        limit = self.tp.int8_max_error
        if bound > limit:
            # the gate stays ARMED: a caller that catches this and
            # re-drives the engine gets refused again, not silently
            # served over budget
            raise RuntimeError(
                f"int8 hidden-state all-reduce error bound {bound:.3e} "
                f"exceeds TPConfig.int8_max_error={limit:.3e} — run "
                "fp32 (int8=False) or raise the budget")
        self._int8_gate_pending = False


# ---------------------------------------------------------------------------
# sharded backends
# ---------------------------------------------------------------------------

class ShardedModelStepBackend(_TPBackendMixin, ModelStepBackend):
    """Dense slot-pool backend with the decode block and per-bucket
    prefills sharded over the TP mesh. Exact-mode streams are
    bit-identical to :class:`ModelStepBackend` on one chip."""

    def __init__(self, model, num_slots: int, max_len: int,
                 decode_block: int, tp: TPConfig, quant=None):
        # fuse=False, not env-resolved: the sharded shard_map programs
        # below replace the base decode block, and the megakernel pass
        # is not yet composed with TP (the engine factory refuses
        # megakernel= + tp= loudly; the env knob must not half-arm it)
        super().__init__(model, num_slots, max_len, decode_block,
                         quant=quant, fuse=False)
        self._setup_tp(model, tp)
        # local-shape row specs: the prefill program zero-fills its
        # fresh cache row INSIDE shard_map, where shapes are per-device
        d = self.tp_degree
        self._row_specs_local = tuple(
            (shape[:2] + (shape[2] // d,) + shape[3:], dtype)
            for shape, dtype in self.row_specs)
        self._row_out_pspecs = tuple(
            P(None, None, self.tp_plan.axes) if len(shape) == 3
            else P(None, None, self.tp_plan.axes, None)
            for shape, _ in self.row_specs)
        self._block_jit = self._shard_jit(
            build_slot_block_fn(self._pure, self.block_size,
                                self.decode_traces),
            in_specs=(self._pv_pspecs, self._bv_pspecs,
                      self._cache_pspecs, self._state_pspecs),
            out_specs=(self._cache_pspecs, self._state_pspecs,
                       P(), P(), P()),
            donate=(2, 3))
        self._prefill_jits = {}

    def decode_block(self, cache_flat, state):
        self._check_int8_gate(cache_flat, state)
        out = self._block_jit(self._pv, self._bv, cache_flat, state)
        self._note_collectives("tp_block", self.block_size,
                               self.block_size * self.num_slots)
        return out

    def prefill(self, bucket_len, ids, pad, key, temp, topk, topp):
        fn = self._prefill_jits.get(bucket_len)
        if fn is None:
            fn = self._shard_jit(
                build_slot_prefill_fn(self._pure,
                                      self._row_specs_local),
                in_specs=(self._pv_pspecs, self._bv_pspecs,
                          P(), P(), P(), P(), P(), P()),
                out_specs=(P(), self._row_out_pspecs))
            self._prefill_jits[bucket_len] = fn
        out = fn(self._pv, self._bv, ids, pad, key, temp, topk, topp)
        self._note_collectives("tp_prefill", 1, bucket_len)
        return out


class ShardedPagedStepBackend(_TPBackendMixin, PagedModelStepBackend):
    """Paged twin: the shared KV arena (fp32 or int8 codes + scales)
    shards its kv-head dim, block tables stay replicated in-state, and
    both the decode block and the ONE chunked-prefill program run under
    ``shard_map``. Exact-mode paged streams are bit-identical to the
    1-chip paged engine (and therefore to dense / ``generate()``)."""

    def __init__(self, model, num_slots: int, max_len: int,
                 decode_block: int, block_size: int, num_blocks: int,
                 kv_int8: bool, prefill_chunk: int, tp: TPConfig,
                 quant=None):
        from .engine import build_paged_chunk_fn
        # fuse=False for the same reason as the dense sharded backend
        super().__init__(model, num_slots, max_len, decode_block,
                         block_size, num_blocks, kv_int8, prefill_chunk,
                         quant=quant, fuse=False)
        self._setup_tp(model, tp)
        self._block_jit = self._shard_jit(
            build_slot_block_fn(self._pure, self.block_size,
                                self.decode_traces, paged=True),
            in_specs=(self._pv_pspecs, self._bv_pspecs,
                      self._cache_pspecs, self._state_pspecs),
            out_specs=(self._cache_pspecs, self._state_pspecs,
                       P(), P(), P()),
            donate=(2, 3))
        self._chunk_jit = self._shard_jit(
            build_paged_chunk_fn(self._pure, prefill_chunk,
                                 self.prefill_traces),
            in_specs=(self._pv_pspecs, self._bv_pspecs, P(),
                      self._cache_pspecs, P(), P(), P(), P(), P(), P(),
                      P()),
            out_specs=(P(), self._cache_pspecs),
            donate=(3,))

    def decode_block(self, cache_flat, state):
        self._check_int8_gate(cache_flat, state)
        out = self._block_jit(self._pv, self._bv, cache_flat, state)
        self._note_collectives("tp_block", self.block_size,
                               self.block_size * self.num_slots)
        return out

    def prefill_chunk(self, ids, cache_flat, table_row, start_pos,
                      n_valid, key, temp, topk, topp):
        out = self._chunk_jit(self._pv, self._bv, ids, cache_flat,
                              table_row, start_pos, n_valid, key, temp,
                              topk, topp)
        self._note_collectives("tp_prefill", 1, self.prefill_chunk_len)
        return out
