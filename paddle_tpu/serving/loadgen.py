"""Deterministic open-loop trace-driven load generation.

Realistic serving traffic is not a fixed request list: arrivals are
Poisson, the rate breathes with a diurnal cycle and spikes in bursts,
prompt/output lengths are heavy-tailed, tenants and priorities mix, and
a large fraction of prompts share a system prefix. An AUTOSCALER can
only be tested against that shape — a constant drip never breaches an
SLO and never clears one.

This module materializes such traffic UP FRONT as a replayable
schedule: ``generate_trace(TraceConfig(...))`` returns a :class:`Trace`
whose requests are fully built :class:`~paddle_tpu.serving.scheduler.
Request` objects pinned to submit ticks. Determinism follows the
``utils/faults`` discipline — every stochastic component (arrivals,
lengths, tenant/priority mix, prompt content, burst windows) draws from
its OWN seeded ``np.random.RandomState((seed, i))`` stream, so adding a
component never shifts another's sequence and the same config replays
byte-identically (pinned by JSON round-trip equality in the tests).
Traces serialize to JSON (:meth:`Trace.to_json`) so a bench artifact
carries its workload as provenance.

Open-loop means arrivals do not wait for completions: the schedule
says WHEN each request submits, the fleet says how it copes. The
:func:`replay` driver walks the tick clock, submitting due requests and
ticking the serving loop — identical traffic against an autoscaled
fleet, a static fleet, or a single Server, which is exactly the A/B the
``serving-autoscale`` bench stage scores.
"""
from __future__ import annotations

import json
import math
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from .scheduler import Request

__all__ = ["TraceConfig", "Trace", "generate_trace", "replay"]

# per-component rng stream ids (the faults.py idiom: one stream each,
# so adding a component never shifts another's sequence)
_S_ARRIVALS, _S_LENGTHS, _S_MIX, _S_CONTENT, _S_BURSTS = range(5)


def _bounded_pareto(rng: np.random.RandomState, alpha: float,
                    lo: int, hi: int) -> int:
    """Inverse-CDF sample of a bounded Pareto(alpha) on [lo, hi] —
    heavy-tailed like real prompt/output lengths, but never past the
    engine's validated capacity."""
    if lo >= hi:
        return lo
    u = float(rng.random_sample())
    la, ha = lo ** -alpha, hi ** -alpha
    x = (la - u * (la - ha)) ** (-1.0 / alpha)
    return int(min(hi, max(lo, round(x))))


def _weighted_pick(rng: np.random.RandomState, items: List,
                   weights: List[float]):
    total = float(sum(weights))
    u = float(rng.random_sample()) * total
    acc = 0.0
    for it, w in zip(items, weights):
        acc += w
        if u < acc:
            return it
    return items[-1]


@dataclass
class TraceConfig:
    """Workload shape knobs. Every field is JSON-serializable so the
    config rides the trace artifact.

    - ``base_rate``: mean arrivals per tick before modulation.
    - ``diurnal_period`` / ``diurnal_amplitude``: sinusoidal rate
      cycle (period in ticks; 0 disables). Rate swings between
      ``base*(1-a)`` and ``base*(1+a)``.
    - ``bursts`` / ``burst_mult`` / ``burst_len``: seeded burst
      episodes — each picks a start tick and a length in
      ``burst_len`` and multiplies the arrival rate by ``burst_mult``
      inside the window.
    - ``prompt_*`` / ``output_*``: bounded-Pareto length
      distributions (alpha, lo, hi).
    - ``tenants`` / ``priority_weights``: weighted mixes.
    - ``shared_fraction`` / ``shared_len`` / ``shared_prompts``: the
      fraction of prompts carrying one of N shared system prefixes
      (the prefix tier's reuse signal).
    - ``sampled_fraction``: fraction of requests decoded with seeded
      sampling instead of greedy (temperature/top_k below).
    """
    seed: int = 0
    horizon: int = 120                   # submit window, in ticks
    base_rate: float = 0.25
    diurnal_period: int = 0
    diurnal_amplitude: float = 0.5
    bursts: int = 0
    burst_mult: float = 4.0
    burst_len: Tuple[int, int] = (10, 25)
    prompt_alpha: float = 1.5
    prompt_lo: int = 4
    prompt_hi: int = 24
    output_alpha: float = 1.2
    output_lo: int = 4
    output_hi: int = 24
    vocab_size: int = 512
    tenants: Dict[str, float] = field(
        default_factory=lambda: {"default": 1.0})
    priority_weights: Dict[int, float] = field(
        default_factory=lambda: {0: 1.0})
    shared_fraction: float = 0.0
    shared_len: int = 16
    shared_prompts: int = 1
    sampled_fraction: float = 0.0
    temperature: float = 0.9
    top_k: int = 40

    def to_dict(self) -> dict:
        return {
            "seed": self.seed, "horizon": self.horizon,
            "base_rate": self.base_rate,
            "diurnal_period": self.diurnal_period,
            "diurnal_amplitude": self.diurnal_amplitude,
            "bursts": self.bursts, "burst_mult": self.burst_mult,
            "burst_len": list(self.burst_len),
            "prompt_alpha": self.prompt_alpha,
            "prompt_lo": self.prompt_lo, "prompt_hi": self.prompt_hi,
            "output_alpha": self.output_alpha,
            "output_lo": self.output_lo, "output_hi": self.output_hi,
            "vocab_size": self.vocab_size,
            "tenants": dict(self.tenants),
            "priority_weights": {str(k): v for k, v
                                 in self.priority_weights.items()},
            "shared_fraction": self.shared_fraction,
            "shared_len": self.shared_len,
            "shared_prompts": self.shared_prompts,
            "sampled_fraction": self.sampled_fraction,
            "temperature": self.temperature, "top_k": self.top_k}

    @classmethod
    def from_dict(cls, d: dict) -> "TraceConfig":
        d = dict(d)
        d["burst_len"] = tuple(d.get("burst_len", (10, 25)))
        d["priority_weights"] = {int(k): v for k, v in
                                 d.get("priority_weights",
                                       {"0": 1.0}).items()}
        return cls(**d)


class Trace:
    """A materialized schedule: requests pinned to submit ticks.
    ``requests[i].request_id`` is the TRACE-LOCAL id ``i`` — the
    serving stack assigns its own ids at submit; :func:`replay`
    returns the mapping."""

    def __init__(self, config: TraceConfig, requests: List[Request],
                 burst_windows: List[Tuple[int, int]]):
        self.config = config
        self.requests = requests
        self.burst_windows = burst_windows

    def schedule(self) -> List[Tuple[int, Request]]:
        return [(r.arrival_step, r) for r in self.requests]

    def __len__(self):
        return len(self.requests)

    def stats(self) -> dict:
        """Workload summary for bench provenance."""
        if not self.requests:
            return {"requests": 0}
        plens = [int(r.prompt.size) for r in self.requests]
        olens = [r.max_new_tokens for r in self.requests]
        # shared-prefix reuse: requests whose leading shared_len tokens
        # coincide with at least one other request's
        heads: Dict[Tuple[int, ...], int] = {}
        for r in self.requests:
            h = tuple(int(t) for t in r.prompt[:self.config.shared_len])
            heads[h] = heads.get(h, 0) + 1
        return {
            "requests": len(self.requests),
            "horizon": self.config.horizon,
            "burst_windows": [list(w) for w in self.burst_windows],
            "prompt_len_mean": round(float(np.mean(plens)), 2),
            "prompt_len_max": int(max(plens)),
            "output_len_mean": round(float(np.mean(olens)), 2),
            "shared_prefix": sum(n for n in heads.values() if n > 1),
            "sampled": sum(1 for r in self.requests
                           if r.temperature > 0.0),
        }

    # -- JSON round trip ---------------------------------------------------
    def to_json(self) -> str:
        return json.dumps({
            "format": "pt-loadgen-trace", "version": 1,
            "config": self.config.to_dict(),
            "burst_windows": [list(w) for w in self.burst_windows],
            "requests": [{
                "id": r.request_id, "t": r.arrival_step,
                "prompt": [int(t) for t in r.prompt],
                "max_new_tokens": r.max_new_tokens,
                "temperature": r.temperature, "top_k": r.top_k,
                "seed": r.seed, "tenant": r.tenant,
                "priority": r.priority,
            } for r in self.requests]}, sort_keys=True)

    @classmethod
    def from_json(cls, s: str) -> "Trace":
        d = json.loads(s)
        if d.get("format") != "pt-loadgen-trace":
            raise ValueError("not a loadgen trace")
        reqs = [Request(
            request_id=r["id"],
            prompt=np.asarray(r["prompt"], np.int32),
            max_new_tokens=r["max_new_tokens"],
            temperature=r["temperature"], top_k=r["top_k"],
            seed=r["seed"], arrival_step=r["t"],
            tenant=r["tenant"], priority=r["priority"])
            for r in d["requests"]]
        return cls(TraceConfig.from_dict(d["config"]), reqs,
                   [tuple(w) for w in d.get("burst_windows", [])])


def generate_trace(config: TraceConfig) -> Trace:
    """Materialize the full schedule for ``config`` — same config,
    same trace, byte-for-byte (JSON-equality pinned)."""
    cfg = config
    arrivals = np.random.RandomState((cfg.seed, _S_ARRIVALS))
    lengths = np.random.RandomState((cfg.seed, _S_LENGTHS))
    mix = np.random.RandomState((cfg.seed, _S_MIX))
    content = np.random.RandomState((cfg.seed, _S_CONTENT))
    bursts = np.random.RandomState((cfg.seed, _S_BURSTS))

    windows: List[Tuple[int, int]] = []
    for _ in range(cfg.bursts):
        start = int(bursts.randint(0, max(1, cfg.horizon)))
        length = int(bursts.randint(cfg.burst_len[0],
                                    cfg.burst_len[1] + 1))
        windows.append((start, min(cfg.horizon, start + length)))

    shared = [content.randint(0, cfg.vocab_size,
                              (cfg.shared_len,)).astype(np.int32)
              for _ in range(max(1, cfg.shared_prompts))]
    t_names = sorted(cfg.tenants)
    t_weights = [cfg.tenants[n] for n in t_names]
    p_vals = sorted(cfg.priority_weights)
    p_weights = [cfg.priority_weights[p] for p in p_vals]

    def rate(t: int) -> float:
        r = cfg.base_rate
        if cfg.diurnal_period > 0:
            r *= 1.0 + cfg.diurnal_amplitude * math.sin(
                2.0 * math.pi * t / cfg.diurnal_period)
        if any(a <= t < b for a, b in windows):
            r *= cfg.burst_mult
        return max(0.0, r)

    requests: List[Request] = []
    rid = 0
    for t in range(cfg.horizon):
        for _ in range(int(arrivals.poisson(rate(t)))):
            plen = _bounded_pareto(lengths, cfg.prompt_alpha,
                                   cfg.prompt_lo, cfg.prompt_hi)
            olen = _bounded_pareto(lengths, cfg.output_alpha,
                                   cfg.output_lo, cfg.output_hi)
            tenant = _weighted_pick(mix, t_names, t_weights)
            priority = _weighted_pick(mix, p_vals, p_weights)
            # every mix draw happens unconditionally so changing one
            # fraction never shifts a later request's tenant/seed —
            # the same per-stream independence faults.py keeps
            is_shared = (float(mix.random_sample())
                         < cfg.shared_fraction)
            is_sampled = (float(mix.random_sample())
                          < cfg.sampled_fraction)
            spi = int(mix.randint(0, len(shared)))
            rseed = int(mix.randint(0, 2 ** 31))
            if is_shared:
                sp = shared[spi]
                tail_len = max(1, plen - int(sp.size))
                prompt = np.concatenate(
                    [sp, content.randint(
                        0, cfg.vocab_size,
                        (tail_len,)).astype(np.int32)])
            else:
                prompt = content.randint(
                    0, cfg.vocab_size, (plen,)).astype(np.int32)
            requests.append(Request(
                request_id=rid, prompt=prompt, max_new_tokens=olen,
                temperature=cfg.temperature if is_sampled else 0.0,
                top_k=cfg.top_k if is_sampled else 0,
                seed=rseed if is_sampled else 0,
                arrival_step=t, tenant=tenant, priority=priority))
            rid += 1
    return Trace(cfg, requests, windows)


def replay(trace: Trace, submit: Callable[[Request], int],
           tick: Callable[[], None], busy: Callable[[], bool],
           max_ticks: int = 5000,
           on_tick: Optional[Callable[[int], None]] = None
           ) -> Dict[int, int]:
    """Open-loop drive: walk the tick clock over the trace horizon,
    submitting each request at its pinned tick, then drain until
    ``busy()`` clears or ``max_ticks``. ``submit(req)`` returns the
    serving stack's id; the returned dict maps trace-local ids to
    them. ``on_tick(clock)`` runs after every tick — the autoscaler's
    evaluation hook."""
    sched = sorted(trace.schedule(), key=lambda e: (e[0],
                                                    e[1].request_id))
    ids: Dict[int, int] = {}
    i, clock = 0, 0
    while clock < trace.config.horizon or (busy() and
                                           clock < max_ticks):
        while i < len(sched) and sched[i][0] <= clock:
            req = sched[i][1]
            ids[req.request_id] = submit(req)
            i += 1
        tick()
        clock += 1
        if on_tick is not None:
            on_tick(clock)
    return ids
