"""SLO-driven autoscaling: size the decode fleet to the traffic.

A static fleet is sized for one load level; real traffic breathes
(see :mod:`.loadgen`). This module closes the loop: a control loop
watches the serving stack's PUBLIC surfaces — the metrics registry's
rolling-window TTFT p95 against a target SLO, queue depth, and block
pressure out of ``Fleet.stats()`` — and decides, each evaluation
interval, to scale the decode pool up (adopt a warm engine from a
factory), scale it down (drain the least-loaded worker, then remove it
once its in-flight streams finish in place), or hold.

The split mirrors the rest of the stack's mechanism/policy discipline:

- :class:`DecisionKernel` is PURE policy — a hysteresis/cooldown state
  machine over :class:`Observation` values with no fleet in sight, so
  the tests drive it with synthetic metric streams and pin exact
  decision sequences (breach, clear, flap, lease-death mid-cooldown).
- :class:`Autoscaler` binds a kernel to a live
  :class:`~paddle_tpu.serving.fleet.Fleet`: it builds observations,
  applies decisions through the fleet's scale surface
  (``add_decode_worker`` / ``drain_decode_worker`` /
  ``remove_decode_worker`` / ``undrain_decode_worker``), retries
  transiently-failed scale actions under the PR 5 policy (the
  ``fleet.scale`` fault site), and records every decision to the
  flight recorder plus ``pt_autoscaler_decisions_total{action}`` /
  ``pt_autoscaler_fleet_size``.

Hysteresis and cooldowns are the thrash guards: a signal must breach
for ``breach_intervals`` CONSECUTIVE evaluations before a scale-up (one
noisy sample does nothing), clear for ``clear_intervals`` before a
scale-down, and each direction then goes cold for its cooldown — a
scale-up also arms the down-cooldown, so freshly added capacity is
never immediately drained. One exception bypasses both guards: a fleet
below ``min_decode`` live workers (a lease death ate a worker) is a
known topology loss, not a noisy signal, and repairs immediately.

Correctness pin (tests/test_autoscaler.py): token streams riding
through scale events — alive during a drain, arriving mid-scale-up —
stay BIT-IDENTICAL to a static-fleet run, and compile counts stay 1,
because scale-up adopts compat-checked engines and drained workers'
streams finish in place. ``dry_run`` records what the loop WOULD do
without touching the fleet.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, List, Optional

from ..observability import metrics as _om
from ..utils import faults
from .fleet import DecodeWorker, Fleet
from .resilience import ResilienceConfig, ResilienceState

__all__ = ["AutoscalerConfig", "Observation", "DecisionKernel",
           "Autoscaler"]

# registered at import so the catalog shows the families before the
# first decision (the metrics-module convention)
_M_DECISIONS = _om.counter(
    "pt_autoscaler_decisions_total",
    "autoscaler decisions by action (up/down/hold)",
    labels=("action",))
_M_FLEET_SIZE = _om.gauge(
    "pt_autoscaler_fleet_size",
    "live decode workers the autoscaler last observed")


@dataclass
class AutoscalerConfig:
    """Policy knobs for one control loop.

    - ``ttft_slo_s``: target TTFT; the rolling p95 over ``window``
      recent samples breaching it is the primary scale-up signal.
    - ``queue_high`` / ``pressure_high``: secondary breach signals
      (summed prefill queue depth, max per-worker block pressure).
    - ``breach_intervals`` / ``clear_intervals``: hysteresis — how
      many CONSECUTIVE breaching (resp. clear) evaluations before the
      loop acts.
    - ``up_cooldown`` / ``down_cooldown``: evaluations a direction
      stays cold after acting (an up also arms the down-cooldown).
    - ``min_decode`` / ``max_decode``: hard fleet-size bounds; below
      ``min_decode`` repairs immediately, bypassing hysteresis AND
      cooldown (topology loss is not a noisy signal).
    - ``interval_ticks``: evaluation cadence for :meth:`Autoscaler.
      on_tick`.
    - ``dry_run``: record decisions (flight + metrics) but never act.
    """
    ttft_slo_s: float = 0.25
    window: int = 64
    queue_high: int = 8
    pressure_high: float = 0.92
    breach_intervals: int = 2
    clear_intervals: int = 3
    up_cooldown: int = 3
    down_cooldown: int = 5
    min_decode: int = 1
    max_decode: int = 4
    interval_ticks: int = 8
    dry_run: bool = False


@dataclass
class Observation:
    """One evaluation's inputs — everything the kernel sees. A missing
    TTFT read (metrics disabled, or no completions yet) is ``None``
    and simply contributes no breach on that signal; queue depth and
    pressure stay actionable."""
    ttft_p95_s: Optional[float] = None
    queue_depth: int = 0
    block_pressure: float = 0.0
    fleet_size: int = 1          # live decode workers (incl. draining)
    draining: int = 0
    dead: int = 0


@dataclass
class Decision:
    action: str                  # "up" | "down" | "hold"
    reason: str
    obs: Observation
    acted: bool = False
    detail: str = ""


class DecisionKernel:
    """Pure hysteresis/cooldown state machine. ``decide(obs)`` per
    evaluation interval; no side effects beyond its own streak and
    cooldown counters, so synthetic observation streams pin exact
    decision sequences."""

    def __init__(self, config: Optional[AutoscalerConfig] = None):
        self.cfg = config or AutoscalerConfig()
        self.breach_streak = 0
        self.clear_streak = 0
        self.up_cold = 0         # evaluations until scale-up re-arms
        self.down_cold = 0

    def breach_reasons(self, obs: Observation) -> List[str]:
        c, out = self.cfg, []
        if obs.ttft_p95_s is not None and obs.ttft_p95_s > c.ttft_slo_s:
            out.append("ttft")
        if obs.queue_depth > c.queue_high:
            out.append("queue")
        if obs.block_pressure > c.pressure_high:
            out.append("pressure")
        return out

    def decide(self, obs: Observation) -> Decision:
        c = self.cfg
        # topology repair first: below the floor is a known loss, not
        # a noisy signal — bypasses hysteresis and cooldown
        routable = obs.fleet_size - obs.draining
        if routable < c.min_decode:
            self.breach_streak = self.clear_streak = 0
            self.up_cold = c.up_cooldown
            return Decision("up", "below_min", obs)

        reasons = self.breach_reasons(obs)
        if reasons:
            self.breach_streak += 1
            self.clear_streak = 0
        else:
            self.clear_streak += 1
            self.breach_streak = 0

        # gate on the pre-decrement value so cooldown=N suppresses
        # exactly N subsequent evaluations
        up_ok, down_ok = self.up_cold == 0, self.down_cold == 0
        if self.up_cold > 0:
            self.up_cold -= 1
        if self.down_cold > 0:
            self.down_cold -= 1

        if (self.breach_streak >= c.breach_intervals and up_ok):
            if obs.fleet_size >= c.max_decode and obs.draining == 0:
                return Decision("hold", "at_max", obs)
            self.breach_streak = 0
            self.up_cold = c.up_cooldown
            # freshly added capacity must not be immediately drained
            self.down_cold = max(self.down_cold, c.down_cooldown)
            return Decision("up", "+".join(reasons), obs)

        if (self.clear_streak >= c.clear_intervals and down_ok):
            if routable <= c.min_decode:
                return Decision("hold", "at_min", obs)
            self.clear_streak = 0
            self.down_cold = c.down_cooldown
            return Decision("down", "clear", obs)

        return Decision("hold",
                        "breaching" if reasons else "clear", obs)


class Autoscaler:
    """Bind a :class:`DecisionKernel` to a live fleet.

    ``engine_factory()`` must return a WARM engine compatible with the
    fleet's existing decode pool (same config/dtype/layout — the fleet
    re-validates at ``add_decode_worker``); pre-compiled factories keep
    the scale-up compile count at zero. Scale-ups get fresh
    ``scale{n}`` names — dead workers' tombstones keep their names
    reserved in the fleet's health map, so reuse is never attempted.

    Scale-down is a two-phase lifecycle spanning evaluations: the
    decision drains the least-loaded non-draining worker (new handoffs
    stop routing to it); every subsequent :meth:`step` first tries to
    REMOVE any drained worker that has gone idle (not a decision —
    the completion of one). Streams on the draining worker finish in
    place, untouched — that is the bit-identity argument.
    """

    def __init__(self, fleet: Fleet,
                 engine_factory: Callable[[], object],
                 config: Optional[AutoscalerConfig] = None,
                 resilience: Optional[ResilienceConfig] = None):
        self.fleet = fleet
        self.factory = engine_factory
        self.cfg = config or AutoscalerConfig()
        self.kernel = DecisionKernel(self.cfg)
        self._res = ResilienceState(resilience or ResilienceConfig())
        self.decisions: List[Decision] = []
        self._next_name = 0
        self._ttft_seen = 0
        self.peak_size = 0
        self.scale_ups = 0
        self.scale_downs = 0
        self.removals = 0
        self.retries = 0

    # -- observation (public surfaces only) --------------------------------
    def observe(self) -> Observation:
        st = self.fleet.stats()
        decode = st["decode_workers"]
        live = [d for d in decode if d["state"] == "live"]
        pressures = [d["block_pressure"] for d in live] + \
            [w["block_pressure"] for w in st["prefill_workers"]
             if w["state"] == "live"]
        # p95 over the samples that arrived SINCE the last evaluation
        # (capped at cfg.window): a count-based ring never ages, so
        # reading the full ring would latch a burst-era breach forever
        # — an interval with no completions reads as no TTFT signal,
        # not as the stale breach
        ttft = None
        fam = _om.REGISTRY.get("pt_server_ttft_seconds")
        if fam is not None and hasattr(fam, "recent_quantile"):
            n = int(fam.count())   # cumulative, never wraps
            fresh = n - self._ttft_seen
            self._ttft_seen = n
            if fresh > 0:
                ttft = fam.recent_quantile(
                    0.95, window=min(fresh, self.cfg.window))
        return Observation(
            ttft_p95_s=ttft,
            queue_depth=sum(w["queue"] for w in st["prefill_workers"]
                            if w["state"] == "live"),
            block_pressure=max(pressures) if pressures else 0.0,
            fleet_size=len(live),
            draining=sum(1 for d in live if d["draining"]),
            dead=sum(1 for d in decode if d["state"] == "dead"))

    # -- the loop ----------------------------------------------------------
    def on_tick(self, clock: int):
        """Evaluation cadence hook for :func:`.loadgen.replay` — runs
        one :meth:`step` every ``interval_ticks`` ticks."""
        if clock % self.cfg.interval_ticks == 0:
            self.step()

    def step(self) -> Decision:
        """One evaluation interval: finish pending drains, observe,
        decide, act (unless ``dry_run``), record."""
        if not self.cfg.dry_run:
            self._reap_drained()
        obs = self.observe()
        d = self.kernel.decide(obs)
        if not self.cfg.dry_run and d.action != "hold":
            self._apply(d)
        self.decisions.append(d)
        # peak over POST-action size too: an up that lands this very
        # interval counts, not just once the next observation sees it
        self.peak_size = max(self.peak_size, obs.fleet_size,
                             len(self.fleet._live_decode()))
        _M_DECISIONS.inc(action=d.action)
        _M_FLEET_SIZE.set(obs.fleet_size)
        self.fleet.flight.record(
            "autoscale", action=d.action, reason=d.reason,
            acted=d.acted, detail=d.detail, fleet_size=obs.fleet_size,
            draining=obs.draining, queue=obs.queue_depth,
            pressure=obs.block_pressure, ttft_p95_s=obs.ttft_p95_s,
            dry_run=self.cfg.dry_run)
        return d

    # -- actuation ---------------------------------------------------------
    def _with_retry(self, what: str, fn: Callable[[], object]):
        """PR 5 policy around one scale action: transient failures
        (the armed ``fleet.scale`` site raises InjectedFault) retry
        with seeded backoff; a still-failing action is dropped — the
        NEXT evaluation re-decides from fresh observations, so a lost
        action costs one interval, never the loop."""
        attempts = self._res.config.retry_attempts
        for attempt in range(attempts + 1):
            try:
                return fn()
            except self._res.transient:
                if attempt >= attempts:
                    self.fleet.flight.record(
                        "autoscale_action_failed", what=what,
                        attempts=attempt + 1)
                    return None
                self.retries += 1
                self._res.retries += 1
                self._res.backoff_s(attempt)  # seeded draw, no sleep

    def _reap_drained(self):
        """Remove drained workers that have gone idle. The fleet's
        ``remove_decode_worker`` re-validates (busy slots, queued
        adoptions, wire-assigned payloads all refuse) — a still-busy
        drain just waits for a later interval."""
        st = self.fleet.stats()
        for i in range(len(st["decode_workers"]) - 1, -1, -1):
            d = st["decode_workers"][i]
            if not (d["draining"] and d["state"] == "live"):
                continue
            def _rm(idx=i):
                try:
                    return self.fleet.remove_decode_worker(idx)
                except RuntimeError:
                    return None     # still owns streams; next interval
            if self._with_retry("remove", _rm) is not None:
                self.removals += 1

    def _apply(self, d: Decision):
        if d.action == "up":
            st = self.fleet.stats()
            draining = [i for i, w in enumerate(st["decode_workers"])
                        if w["draining"] and w["state"] == "live"]
            if draining:
                # cheapest capacity: cancel a pending drain — no new
                # engine, no new programs
                idx = draining[0]
                ok = self._with_retry(
                    "undrain",
                    lambda: self.fleet.undrain_decode_worker(idx)
                    or True)
                if ok:
                    d.acted, d.detail = True, \
                        f"undrain:{st['decode_workers'][idx]['name']}"
                    self.scale_ups += 1
                return
            name = f"scale{self._next_name}"
            self._next_name += 1
            # build the engine ONCE — a retry re-attempts the fleet
            # registration, not the (expensive, possibly pooled)
            # engine construction
            w = DecodeWorker(self.factory(), name=name)
            def _add():
                self.fleet.add_decode_worker(w)
                return True
            if self._with_retry("add", _add):
                d.acted, d.detail = True, f"add:{name}"
                self.scale_ups += 1
        elif d.action == "down":
            st = self.fleet.stats()
            victims = [
                (w["free_slots"], i)
                for i, w in enumerate(st["decode_workers"])
                if w["state"] == "live" and not w["draining"]]
            if len(victims) <= self.cfg.min_decode:
                return
            _, idx = max(victims)   # most free slots = least loaded
            ok = self._with_retry(
                "drain",
                lambda: self.fleet.drain_decode_worker(idx) or True)
            if ok:
                d.acted, d.detail = True, \
                    f"drain:{st['decode_workers'][idx]['name']}"
                self.scale_downs += 1

    # -- reporting ---------------------------------------------------------
    def stats(self) -> dict:
        actions = {"up": 0, "down": 0, "hold": 0}
        for d in self.decisions:
            actions[d.action] += 1
        return {"decisions": actions,
                "scale_ups": self.scale_ups,
                "scale_downs": self.scale_downs,
                "removals": self.removals,
                "retries": self.retries,
                "peak_size": self.peak_size,
                "dry_run": self.cfg.dry_run}
