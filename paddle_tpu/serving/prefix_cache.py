"""Fleet-wide KV prefix cache: warm prefix state as a storage tier.

PR 4 gave every paged engine a worker-LOCAL prefix index: full prompt
blocks keyed by a chained-SHA1 digest, LRU-retained at refcount 0, so a
hot system prompt survives across requests on that worker. At fleet
scale the same prefix is re-prefilled once per worker it lands on — the
dominant avoidable prefill cost. This module turns the local index
into a fleet tier:

- :class:`PrefixCacheDirectory` — the fleet-level catalog. Each
  heartbeat, every paged worker publishes its registered digest chains
  (``BlockManager.registered_chains()``: digest → covered block count),
  so directory state rides the PR 15 lease machinery: a dead worker's
  entries drop with its lease, an evicted block's digest vanishes on
  the owner's next beat. Lookup walks the REQUESTER's digest chain and
  returns the deepest prefix some single live owner covers
  consecutively from the root (an owner holding only a chain tail
  cannot serve it — its ``match_prefix`` walks from the root too).
- :func:`extract_prefix` / :func:`adopt_prefix` — the remote fetch.
  The owner re-matches the token prefix against its OWN index (ref-
  acquiring the blocks for the copy, token-compared so a hash collision
  degrades to a shorter match, never a wrong block), ships the covered
  block rows at storage dtype as a ``pt-kv-fetch`` payload over the
  same v1 serializer/CRC machinery as KV handoffs, and the requester
  adopts them through the PR 15 idempotent-adopt scatter
  (:func:`_adopt_scatter` — the SAME program shape
  ``DecodeWorker.adopt`` uses, zero new compiled programs on the
  decode/prefill steady paths), registers the chain in its own index,
  and chunk-prefills only the uncovered suffix.
- Cross-TP-layout fetches: a sharded owner ships per-shard chunks
  along the kv-head axis; the requester re-chunks them to its own
  degree via ``handoff.reshard_kv_chunks`` (arXiv:2112.01075 — peak
  footprint one part) before the logical scatter, and its backend's
  ``commit_arrays`` hook re-commits onto the local mesh.

Failure semantics: a fetch that fails for ANY reason — owner dead
mid-fetch, injected ``fleet.fetch`` fault past the retry budget, CRC
mismatch, stale directory (owner evicted the blocks since its last
beat), requester pool full — falls back to LOCAL PREFILL. The request
never fails because a warm copy was advertised; remote state is an
optimization tier, not a dependency.

Metric families (registered at import; no-ops until
``metrics.enable()``/``PT_METRICS``): fetches, fetched blocks/bytes,
failures by reason, duplicate responses dropped, directory entries.
"""
from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Tuple

import numpy as np

from ..observability import metrics as _om
from .handoff import FETCH_FORMAT, KVHandoff, reshard_kv_chunks

__all__ = ["PrefixCacheDirectory", "adopt_prefix", "extract_prefix"]

#: kv-head axis of every pool leaf (4D arenas and 3D int8 scale
#: arrays alike) — the axis serving/tp.py shards and cross-layout
#: fetches re-chunk.
KV_HEAD_AXIS = 2

_M_FETCHES = _om.counter("pt_prefix_fetches_total",
                         "remote prefix fetches adopted")
_M_FETCH_BLOCKS = _om.counter("pt_prefix_fetch_blocks_total",
                              "KV blocks adopted from remote prefix "
                              "fetches")
_M_FETCH_BYTES = _om.counter("pt_prefix_fetch_bytes_total",
                             "wire bytes of adopted prefix-fetch "
                             "payloads")
_M_FETCH_FAILS = _om.counter("pt_prefix_fetch_failures_total",
                             "prefix fetches that fell back to local "
                             "prefill, by reason", labels=("reason",))
_M_FETCH_DUPS = _om.counter("pt_prefix_fetch_duplicates_total",
                            "stale/duplicate fetch responses dropped "
                            "(at-least-once wire retransmits)")
_M_DIR_ENTRIES = _om.gauge("pt_prefix_directory_entries",
                           "distinct digest chains in the fleet prefix "
                           "directory")


class PrefixCacheDirectory:
    """Fleet-level map of registered prefix chains to owning workers.

    State is heartbeat-shaped: :meth:`publish` REPLACES a worker's
    entry set wholesale (the worker's ``registered_chains()`` snapshot
    is the truth; anything it evicted since the last beat simply stops
    being listed), and :meth:`drop_worker` removes a dead worker's
    entries the moment its lease expires. The directory stores no
    token data — hash collisions are caught owner-side at extract
    time by the index's stored-token comparison."""

    def __init__(self):
        self._by_worker: Dict[str, Dict[bytes, int]] = {}
        self._owners: Dict[bytes, set] = {}

    def publish(self, worker: str, chains: Dict[bytes, int]):
        """Replace ``worker``'s published digest set."""
        old = self._by_worker.get(worker, {})
        for digest in old:
            if digest not in chains:
                self._unlist(digest, worker)
        for digest in chains:
            if digest not in old:
                self._owners.setdefault(digest, set()).add(worker)
        self._by_worker[worker] = dict(chains)
        self._note()

    def drop_worker(self, worker: str):
        """Expire every entry the worker published (lease death)."""
        for digest in self._by_worker.pop(worker, {}):
            self._unlist(digest, worker)
        self._note()

    def _unlist(self, digest: bytes, worker: str):
        owners = self._owners.get(digest)
        if owners is not None:
            owners.discard(worker)
            if not owners:
                del self._owners[digest]

    def _note(self):
        if _om.enabled():
            _M_DIR_ENTRIES.set(len(self._owners))

    def owners(self, digest: bytes) -> Tuple[str, ...]:
        return tuple(sorted(self._owners.get(digest, ())))

    def size(self) -> int:
        return len(self._owners)

    def worker_entries(self, worker: str) -> Dict[bytes, int]:
        return dict(self._by_worker.get(worker, {}))

    def deepest_covered(self, prompt, block_size: int, hash_fn,
                        exclude: Iterable[str] = ()
                        ) -> Tuple[int, Tuple[str, ...]]:
        """Walk ``prompt``'s digest chain and return ``(n_blocks,
        owners)``: the deepest full-block prefix that at least one
        worker (outside ``exclude``) covers CONSECUTIVELY from the
        root, and the workers that do. A worker listing only a chain
        tail (its chain head was LRU-evicted) is not an owner — its
        own ``match_prefix`` could not serve the fetch."""
        bs = block_size
        excl = set(exclude)
        best: Tuple[int, Tuple[str, ...]] = (0, ())
        alive: Optional[set] = None
        parent = b""
        for j in range((len(prompt) - 1) // bs):
            chunk = tuple(int(t) for t in prompt[j * bs:(j + 1) * bs])
            digest = hash_fn(parent, chunk)
            cand = {o for o in self._owners.get(digest, ())
                    if o not in excl}
            alive = cand if alive is None else (alive & cand)
            if not alive:
                break
            best = (j + 1, tuple(sorted(alive)))
            parent = digest
        return best

    def stats(self) -> dict:
        return {"entries": len(self._owners),
                "workers": sorted(self._by_worker),
                "deepest_chain": max(
                    (n for c in self._by_worker.values()
                     for n in c.values()), default=0)}


def _adopt_scatter(cache_flat, rows_flat, table):
    """ONE fixed-shape scatter arming adopted KV rows into an arena —
    shared by ``DecodeWorker.adopt`` (handoffs) and
    :func:`adopt_prefix` (fetches). Rows are padded to ``max_blocks``;
    pad rows write zeros into the reserved trash block (the table tail
    is 0), so the program shape never depends on the payload."""
    return tuple(c.at[table].set(r.astype(c.dtype))
                 for c, r in zip(cache_flat, rows_flat))


def extract_prefix(engine, tokens, n_blocks: int, skip: int = 0,
                   source: str = "") -> Optional[KVHandoff]:
    """Owner-side fetch service: build a ``pt-kv-fetch`` payload with
    the arena rows of blocks ``[skip, n_blocks)`` of ``tokens``'s
    digest chain. Returns None when this engine's index no longer
    covers ``n_blocks`` consecutive blocks (the directory was stale —
    the requester falls back to local prefill). The matched blocks are
    ref-acquired for the duration of the copy and released before
    returning, so concurrent eviction can never tear the payload."""
    bs = engine.kv_block_size
    sub = np.asarray(tokens[:n_blocks * bs + 1], np.int32)
    blocks = engine.manager.match_prefix(sub)
    if len(blocks) < n_blocks:
        engine.manager.release(blocks)
        return None
    ids = np.asarray(blocks[skip:n_blocks], np.int32)
    src_tp = engine.tp_degree()
    arrays: Dict[str, np.ndarray] = {"tokens": sub}
    for i, c in enumerate(engine._cache):
        rows = np.asarray(c[ids])
        if src_tp > 1:
            # a sharded owner ships per-shard chunks along the kv-head
            # axis; the requester reshards them to ITS degree
            for s, piece in enumerate(
                    np.split(rows, src_tp, axis=KV_HEAD_AXIS)):
                arrays[f"kv_{i}_p{s}"] = np.ascontiguousarray(piece)
        else:
            arrays[f"kv_{i}"] = rows
    engine.manager.release(blocks)
    meta = {
        "format": FETCH_FORMAT, "kind": "prefix",
        "n_blocks": int(n_blocks), "skip": int(skip),
        "block_size": int(bs), "kv_int8": bool(engine.kv_int8),
        "leaf_specs": [[list(s[1:]), str(np.dtype(d))]
                       for s, d in engine.backend.pool_specs],
        "src_tp_degree": int(src_tp),
        "source": {"worker": source},
    }
    return KVHandoff(meta=meta, arrays=arrays)


def _logical_rows(h: KVHandoff, leaf: int, src_tp: int,
                  dst_tp: int) -> np.ndarray:
    """Reassemble one leaf's logical block rows from the payload —
    directly for an unsharded source, via ``reshard_kv_chunks`` for a
    sharded one (int8 scale leaves ride the same path: they are just
    another leaf with the kv-head axis in the same place)."""
    direct = h.arrays.get(f"kv_{leaf}")
    if direct is not None:
        return direct
    parts = [h.arrays[f"kv_{leaf}_p{s}"] for s in range(src_tp)]
    total = sum(p.shape[KV_HEAD_AXIS] for p in parts)
    if dst_tp > 1 and total % dst_tp == 0:
        parts = reshard_kv_chunks(parts, dst_tp, axis=KV_HEAD_AXIS)
    return np.concatenate(parts, axis=KV_HEAD_AXIS) \
        if len(parts) > 1 else parts[0]


def adopt_prefix(engine, h: KVHandoff, local_blocks: List[int],
                 full) -> Optional[List[int]]:
    """Requester-side adopt: scatter the fetched block rows into this
    engine's arena at exact refcounts and register the extended chain.

    Allocates ``n_blocks - skip`` fresh blocks (refcount 1 — the same
    hold the admitting request would have acquired by matching them
    locally), scatters through the shared :func:`_adopt_scatter`
    program, registers ``local_blocks + fetched`` under the prompt's
    digest chain (so the copy is immediately matchable AND publishable
    here), and re-commits via the backend's ``commit_arrays`` hook on
    TP targets. Returns the fetched block ids, or None when the pool
    cannot cover them (caller falls back to local prefill). Raises
    ValueError on an incompatible payload — geometry mismatches are
    bugs, not fallbacks."""
    import jax
    meta = h.meta
    if meta.get("kind") != "prefix":
        raise ValueError(
            f"{meta.get('kind')!r} payload on the prefix-fetch channel")
    specs = [[list(s[1:]), str(np.dtype(d))]
             for s, d in engine.backend.pool_specs]
    if meta["leaf_specs"] != specs:
        raise ValueError(
            "prefix-fetch KV layout does not match this engine — same "
            "model config / paging layout required")
    if meta["block_size"] != engine.kv_block_size \
            or bool(meta["kv_int8"]) != bool(engine.kv_int8):
        raise ValueError(
            "prefix-fetch arena geometry mismatch (block_size/kv_int8)")
    n_blocks, skip = int(meta["n_blocks"]), int(meta["skip"])
    k = n_blocks - skip
    if k <= 0 or len(local_blocks) != skip:
        raise ValueError(
            f"prefix-fetch covers blocks [{skip}, {n_blocks}) but the "
            f"requester holds {len(local_blocks)} local blocks")
    fetched = engine.manager.allocate(k)
    if fetched is None:
        return None
    src_tp = int(meta.get("src_tp_degree", 1))
    dst_tp = engine.tp_degree()
    table = np.zeros((engine.max_blocks,), np.int32)
    table[:k] = fetched
    rows = []
    for i, (shape, dtype) in enumerate(engine.backend.pool_specs):
        r = np.zeros((engine.max_blocks,) + tuple(shape[1:]),
                     np.dtype(dtype))
        r[:k] = _logical_rows(h, i, src_tp, dst_tp)
        rows.append(r)
    jit = getattr(engine, "_prefix_adopt_jit", None)
    if jit is None:
        jit = jax.jit(_adopt_scatter, donate_argnums=(0,))
        engine._prefix_adopt_jit = jit
    engine._cache = jit(engine._cache, tuple(rows), table)
    bs = engine.kv_block_size
    engine.manager.register_prefix(
        np.asarray(full[:n_blocks * bs + 1], np.int32),
        list(local_blocks) + fetched)
    commit = getattr(engine.backend, "commit_arrays", None)
    if commit is not None:
        engine._cache, engine._state = commit(engine._cache,
                                              engine._state)
    return fetched
