"""Durable fleet control plane: write-ahead journal, coordinated
checkpoint manifests, and a disk spill tier for evicted prefix chains.

Every failure domain before this one (PR 5 snapshot/restore, PR 15
leases + redrive, PR 17 below-min repair) assumes the fleet *process*
survives: router registries, ship/dedup records, the prefix directory
and fleet-durable results all live in hub memory, so a kill -9 of the
whole process loses every in-flight stream even though per-worker
snapshots exist. This module makes the control plane itself durable:

- :class:`WriteAheadJournal` — an fsync'd append-only segment of
  control-plane transitions (submit, ship w/ rng key + seq, adopt,
  heartbeat-progress high-water marks, terminal rows, scale actions).
  Records reuse the PR 15 frame discipline ON DISK: a fixed big-endian
  header ``magic|seq|payload_len``, a JSON payload, and a CRC32
  trailer over header+payload. Replay walks frames until the first
  short or CRC-bad one, TRUNCATES the torn tail loudly (a torn tail is
  a crash artifact, never silently replayed as junk), and hands back
  every intact record. ``journal.write`` / ``journal.torn_tail`` fault
  sites make both edges chaos-testable.
- Checkpoint manifests — ``Fleet.checkpoint`` snapshots every live
  worker's Server (the PR 5 npz path), then commits fleet registries +
  directory topology + the flight ring ATOMICALLY by renaming a
  ``manifest-<epoch>.json`` into place (:func:`write_manifest`, via
  the hardened ``checkpoint.py`` atomic helpers — contents AND parent
  directory fsync'd). The manifest rename is THE commit point: journal
  epoch N+1 opens only after it, and :func:`load_latest_manifest`
  walks epochs newest-first, discarding torn/invalid manifests loudly.
  ``checkpoint.commit`` faults the instant before the rename.
- :class:`PrefixSpillStore` — watermark-evicted prefix chains land on
  disk as raw ``pt-kv-fetch`` payload bytes (the EXACT serializer +
  CRC the fleet fetch path ships over the wire, so spilled int8 chains
  stay bytes-true codes+scales). Extraction is SIDE-EFFECT-FREE
  (:func:`extract_chain` walks the index without touching hit counts
  or LRU order — a spill must never change which block the eviction
  it precedes picks). Reads CRC-verify, token-compare (a collision
  degrades to a miss, never a wrong block) and fault through
  ``spill.read``; any failure is a miss and the requester falls back
  to local prefill bit-identically. An LRU byte cap bounds the tier.

The journal's replay contract is idempotency under the one crash
window the commit ordering leaves open (manifest N committed, journal
N not yet truncated): progress records only ever EXTEND a stream's
token high-water mark, terminals are first-write-wins, and topology
records are set-operations — replaying an already-absorbed prefix of
the journal over a manifest is a no-op.
"""
from __future__ import annotations

import json
import os
import struct
import warnings
import zlib
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..distributed.checkpoint import atomic_json_dump
from ..observability import metrics as _om
from ..utils import faults
from .handoff import FETCH_FORMAT, KVHandoff, decode_handoff, \
    encode_handoff

__all__ = ["JOURNAL_MAGIC", "MANIFEST_FORMAT", "PrefixSpillStore",
           "WriteAheadJournal", "extract_chain", "journal_path",
           "list_epochs", "load_latest_manifest", "manifest_path",
           "slice_prefix_payload", "snapshot_path", "write_manifest"]

# durability metric families (registered at import so the catalog is
# complete at zero; no-ops until metrics.enable()/PT_METRICS)
_M_J_APPENDS = _om.counter("pt_journal_appends_total",
                           "control-plane records appended to the "
                           "write-ahead journal")
_M_J_BYTES = _om.counter("pt_journal_bytes_total",
                         "bytes fsync'd into write-ahead journal "
                         "segments")
_M_J_REPLAYS = _om.counter("pt_journal_replays_total",
                           "journal records replayed during recovery")
_M_J_TORN = _om.counter("pt_journal_torn_tails_total",
                        "torn/CRC-bad journal tails truncated at "
                        "replay")
_M_CKPT_COMMITS = _om.counter("pt_checkpoint_commits_total",
                              "coordinated fleet checkpoints committed "
                              "(manifest renamed into place)")
_M_CKPT_RECOVERIES = _om.counter("pt_checkpoint_recoveries_total",
                                 "cold-start fleet recoveries from a "
                                 "durability directory")
_M_SPILL_WRITES = _om.counter("pt_prefix_spill_writes_total",
                              "evicted prefix chains spilled to disk")
_M_SPILL_HITS = _om.counter("pt_prefix_spill_hits_total",
                            "prefix fetches served from the disk "
                            "spill tier")
_M_SPILL_MISSES = _om.counter("pt_prefix_spill_misses_total",
                              "spill-tier reads that fell back "
                              "(fault/CRC/collision/pool-full)")

# ---------------------------------------------------------------------------
# write-ahead journal
# ---------------------------------------------------------------------------

#: Disk frame discipline — the PR 15 wire framing, re-anchored on
#: disk: ``>4sQI`` header (magic | record seq | payload length), JSON
#: payload, then a ``>I`` CRC32 trailer over header+payload.
JOURNAL_MAGIC = b"PTJ1"
_HDR = struct.Struct(">4sQI")
_CRC = struct.Struct(">I")
#: Refuse absurd payload lengths up front so a corrupt header cannot
#: make replay attempt a multi-GB read before the CRC catches it.
_MAX_PAYLOAD = 64 * 1024 * 1024


def _frame(seq: int, payload: bytes) -> bytes:
    head = _HDR.pack(JOURNAL_MAGIC, seq, len(payload))
    return head + payload + _CRC.pack(zlib.crc32(head + payload))


class WriteAheadJournal:
    """One fsync'd append-only journal segment.

    ``append`` frames a JSON record, fires ``journal.write`` BEFORE
    any bytes touch the file (a transient injected failure leaves the
    segment clean for the retry), writes, flushes and fsyncs. The
    ``journal.torn_tail`` site instead writes a PARTIAL frame and then
    raises — the on-disk artifact of a crash mid-append, which
    :meth:`replay` must truncate loudly. A partial frame followed by a
    retried full copy means replay rolls back to the partial frame's
    boundary and LOSES the records after it: consistent but lossy,
    exactly a real torn-tail crash — lost terminals are safe because
    recovery redrives the stream bit-identically under the same rid."""

    def __init__(self, path: str, fsync: bool = True):
        self.path = path
        self.fsync = fsync
        self.appends = 0
        self.bytes_written = 0
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        self._f = open(path, "ab")
        self._seq = 0
        if self._f.tell():
            # reopening an existing segment (recovery continues it in
            # append mode): continue the record seq past the intact
            # prefix
            records, _ = self.replay(path, truncate=False)
            self._seq = len(records)

    def empty(self) -> bool:
        return self._f.tell() == 0

    @property
    def seq(self) -> int:
        return self._seq

    def append(self, record: dict) -> int:
        """Frame + fsync one record; returns its seq. Raises
        ``InjectedFault`` from an armed ``journal.write`` (before any
        bytes — transient, retryable) or ``journal.torn_tail`` (after
        a partial write — the crash artifact)."""
        faults.fault_point("journal.write")
        payload = json.dumps(record, separators=(",", ":"),
                             sort_keys=True).encode("utf-8")
        frame = _frame(self._seq, payload)
        if faults.should_fire("journal.torn_tail"):
            self._f.write(frame[:max(1, len(frame) // 2)])
            self._f.flush()
            os.fsync(self._f.fileno())
            raise faults.InjectedFault(
                "injected fault at journal.torn_tail")
        self._f.write(frame)
        self._f.flush()
        if self.fsync:
            os.fsync(self._f.fileno())
        self._seq += 1
        self.appends += 1
        self.bytes_written += len(frame)
        if _om.enabled():
            _M_J_APPENDS.inc()
            _M_J_BYTES.inc(len(frame))
        return self._seq - 1

    def close(self):
        if not self._f.closed:
            self._f.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass                # interpreter teardown — best effort

    @staticmethod
    def replay(path: str, truncate: bool = True
               ) -> Tuple[List[dict], bool]:
        """Read every intact record of a segment; returns
        ``(records, torn)``. The first short/CRC-bad/out-of-sequence
        frame ends the walk: everything after it is a torn tail,
        warned about LOUDLY and (by default) truncated off the file so
        the reopened segment appends from a clean boundary."""
        if not os.path.exists(path):
            return [], False
        with open(path, "rb") as f:
            blob = f.read()
        records: List[dict] = []
        off = 0
        torn = False
        while off < len(blob):
            if off + _HDR.size > len(blob):
                torn = True
                break
            magic, seq, plen = _HDR.unpack_from(blob, off)
            end = off + _HDR.size + plen + _CRC.size
            if magic != JOURNAL_MAGIC or plen > _MAX_PAYLOAD \
                    or seq != len(records) or end > len(blob):
                torn = True
                break
            body = blob[off:off + _HDR.size + plen]
            (crc,) = _CRC.unpack_from(blob, off + _HDR.size + plen)
            if crc != zlib.crc32(body):
                torn = True
                break
            try:
                records.append(json.loads(
                    body[_HDR.size:].decode("utf-8")))
            except ValueError:
                torn = True
                break
            off = end
        if torn:
            warnings.warn(
                f"journal {os.path.basename(path)}: torn tail at byte "
                f"{off} ({len(blob) - off} bytes discarded after "
                f"{len(records)} intact records)", RuntimeWarning,
                stacklevel=2)
            _M_J_TORN.inc()
            if truncate:
                with open(path, "r+b") as f:
                    f.truncate(off)
                    f.flush()
                    os.fsync(f.fileno())
        return records, torn


# ---------------------------------------------------------------------------
# checkpoint manifests
# ---------------------------------------------------------------------------

MANIFEST_FORMAT = "pt-fleet-manifest"
MANIFEST_VERSION = 1


def journal_path(dirname: str, epoch: int) -> str:
    return os.path.join(dirname, f"journal-{epoch:08d}.log")


def manifest_path(dirname: str, epoch: int) -> str:
    return os.path.join(dirname, f"manifest-{epoch:08d}.json")


def snapshot_path(dirname: str, epoch: int, worker: str) -> str:
    return os.path.join(dirname, f"ckpt-{epoch:08d}-{worker}.npz")


def list_epochs(dirname: str, prefix: str) -> List[int]:
    """Epochs present for ``prefix`` in (``'manifest'``/``'journal'``),
    ascending."""
    out = []
    for name in os.listdir(dirname):
        if not name.startswith(prefix + "-"):
            continue
        stem = name[len(prefix) + 1:].split(".", 1)[0]
        if stem.isdigit():
            out.append(int(stem))
    return sorted(set(out))


def write_manifest(dirname: str, epoch: int, manifest: dict) -> str:
    """Atomically commit a checkpoint manifest. The rename inside
    ``atomic_json_dump`` IS the checkpoint commit point;
    ``checkpoint.commit`` faults the instant before it so chaos tests
    can crash a fleet with every snapshot written but no commit."""
    path = manifest_path(dirname, epoch)
    doc = dict(manifest, format=MANIFEST_FORMAT,
               version=MANIFEST_VERSION, epoch=int(epoch))
    faults.fault_point("checkpoint.commit")
    atomic_json_dump(path, doc)
    if _om.enabled():
        _M_CKPT_COMMITS.inc()
    return path


def load_latest_manifest(dirname: str
                         ) -> Tuple[Optional[int], Optional[dict]]:
    """Newest VALID manifest wins. A torn/invalid manifest (killed
    mid-commit despite the atomic rename — e.g. a fault between write
    and rename left a stale ``.tmp``) is skipped with a loud warning,
    falling back to the previous epoch."""
    if not os.path.isdir(dirname):
        return None, None
    for epoch in reversed(list_epochs(dirname, "manifest")):
        path = manifest_path(dirname, epoch)
        try:
            with open(path, "r") as f:
                doc = json.load(f)
            if doc.get("format") != MANIFEST_FORMAT:
                raise ValueError(f"bad format {doc.get('format')!r}")
            if int(doc.get("version", -1)) > MANIFEST_VERSION:
                raise ValueError(
                    f"manifest version {doc.get('version')} is newer "
                    f"than this build supports ({MANIFEST_VERSION})")
            return epoch, doc
        except (OSError, ValueError) as e:
            warnings.warn(
                f"discarding invalid checkpoint manifest "
                f"{os.path.basename(path)}: {e}", RuntimeWarning,
                stacklevel=2)
    return None, None


# ---------------------------------------------------------------------------
# disk spill tier for evicted prefix chains
# ---------------------------------------------------------------------------

def _chain_block_ids(manager, tokens, n_blocks: int
                     ) -> Optional[List[int]]:
    """Walk ``tokens``'s digest chain through the manager's index
    WITHOUT the side effects of ``match_prefix`` (no ref acquire, no
    hit-count bump, no LRU reorder): a spill that perturbed the LRU
    would change which block the eviction it precedes picks. Safe
    because the fleet tick is single-threaded — nothing can evict
    between this walk and the row copy."""
    bs = manager.block_size
    parent = b""
    out: List[int] = []
    for j in range(n_blocks):
        chunk = tuple(int(t) for t in tokens[j * bs:(j + 1) * bs])
        if len(chunk) < bs:
            return None
        digest = manager.hash_fn(parent, chunk)
        entry = manager._index.get(digest)
        if entry is None or entry[1] != chunk:
            return None
        out.append(entry[0])
        parent = digest
    return out


def extract_chain(engine, tokens, n_blocks: int,
                  source: str = "") -> Optional[KVHandoff]:
    """Side-effect-free twin of ``prefix_cache.extract_prefix``: build
    a ``pt-kv-fetch`` payload (``skip=0``) for blocks ``[0,
    n_blocks)`` of ``tokens``'s chain, copying arena rows directly
    from the index walk. Identical meta shape, so ``adopt_prefix``
    accepts a spilled payload exactly like a live fetch."""
    from .prefix_cache import KV_HEAD_AXIS
    bs = engine.kv_block_size
    blocks = _chain_block_ids(engine.manager, tokens, n_blocks)
    if blocks is None:
        return None
    ids = np.asarray(blocks, np.int32)
    src_tp = engine.tp_degree()
    arrays: Dict[str, np.ndarray] = {
        "tokens": np.asarray(tokens[:n_blocks * bs], np.int32)}
    for i, c in enumerate(engine._cache):
        rows = np.asarray(c[ids])
        if src_tp > 1:
            for s, piece in enumerate(
                    np.split(rows, src_tp, axis=KV_HEAD_AXIS)):
                arrays[f"kv_{i}_p{s}"] = np.ascontiguousarray(piece)
        else:
            arrays[f"kv_{i}"] = rows
    meta = {
        "format": FETCH_FORMAT, "kind": "prefix",
        "n_blocks": int(n_blocks), "skip": 0,
        "block_size": int(bs), "kv_int8": bool(engine.kv_int8),
        "leaf_specs": [[list(s[1:]), str(np.dtype(d))]
                       for s, d in engine.backend.pool_specs],
        "src_tp_degree": int(src_tp),
        "source": {"worker": source, "spilled": True},
    }
    return KVHandoff(meta=meta, arrays=arrays)


def slice_prefix_payload(h: KVHandoff, n_local: int) -> KVHandoff:
    """Re-skip a stored ``skip=0`` spill payload for a requester that
    already holds ``n_local`` chain blocks locally: drop the covered
    rows (axis 0 — the block axis of every leaf and shard chunk) and
    stamp ``skip=n_local`` so ``adopt_prefix`` allocates only the
    uncovered remainder. CRC is not restamped — verification happened
    against the full stored payload before slicing."""
    if n_local <= 0:
        return h
    meta = dict(h.meta, skip=int(n_local))
    meta.pop("crc32", None)
    arrays = {}
    for name, a in h.arrays.items():
        arrays[name] = a if name == "tokens" else a[n_local:]
    return KVHandoff(meta=meta, arrays=arrays)


class PrefixSpillStore:
    """LRU-capped disk tier for watermark-evicted prefix chains.

    Files are raw ``encode_handoff`` bytes (npz + ``__meta__`` + CRC —
    the wire format, at storage dtype) named
    ``spill-<depth>-<digest>.kv`` so the index rebuilds from a
    directory listing alone: the store itself needs no journal. Writes
    evict oldest-written entries past ``max_bytes``; reads refresh
    recency. Every read re-verifies the payload CRC and the caller
    token-compares the stored chain — any failure is a MISS, never a
    wrong block."""

    FILE_PREFIX = "spill-"
    FILE_SUFFIX = ".kv"

    def __init__(self, dirname: str, max_bytes: int = 1 << 28):
        self.dir = dirname
        self.max_bytes = int(max_bytes)
        self.writes = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        os.makedirs(dirname, exist_ok=True)
        # digest-hex -> (depth, file size); insertion order is LRU
        self._index: "OrderedDict[str, Tuple[int, int]]" = OrderedDict()
        for name in sorted(os.listdir(dirname)):
            if not (name.startswith(self.FILE_PREFIX)
                    and name.endswith(self.FILE_SUFFIX)):
                continue
            stem = name[len(self.FILE_PREFIX):-len(self.FILE_SUFFIX)]
            depth_s, _, hexd = stem.partition("-")
            if not depth_s.isdigit() or not hexd:
                continue
            size = os.path.getsize(os.path.join(dirname, name))
            self._index[hexd] = (int(depth_s), size)

    def _path(self, hexdigest: str, depth: int) -> str:
        return os.path.join(
            self.dir,
            f"{self.FILE_PREFIX}{depth:04d}-{hexdigest}"
            f"{self.FILE_SUFFIX}")

    def total_bytes(self) -> int:
        return sum(size for _, size in self._index.values())

    def __len__(self) -> int:
        return len(self._index)

    def depth_of(self, digest: bytes) -> int:
        entry = self._index.get(digest.hex())
        return entry[0] if entry is not None else 0

    def put(self, digest: bytes, h: KVHandoff) -> bool:
        """Store one extracted chain payload; oldest entries are
        dropped past the byte cap. A digest already stored at >= depth
        is left alone (the deeper chain covers the shallower)."""
        depth = int(h.meta["n_blocks"])
        hexd = digest.hex()
        have = self._index.get(hexd)
        if have is not None and have[0] >= depth:
            return False
        h.meta["crc32"] = h.payload_crc32()
        blob = encode_handoff(h)
        if len(blob) > self.max_bytes:
            return False
        if have is not None:
            self._drop(hexd)
        path = self._path(hexd, depth)
        tmp = f"{path}.tmp.{os.getpid()}"
        try:
            with open(tmp, "wb") as f:
                f.write(blob)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        except BaseException:
            if os.path.exists(tmp):
                os.remove(tmp)
            raise
        self._index[hexd] = (depth, len(blob))
        self.writes += 1
        if _om.enabled():
            _M_SPILL_WRITES.inc()
        while self.total_bytes() > self.max_bytes \
                and len(self._index) > 1:
            oldest = next(iter(self._index))
            if oldest == hexd:
                break
            self._drop(oldest)
            self.evictions += 1
        return True

    def _drop(self, hexd: str):
        depth, _ = self._index.pop(hexd)
        try:
            os.remove(self._path(hexd, depth))
        except OSError:
            pass

    def lookup(self, prompt, block_size: int, hash_fn
               ) -> Tuple[int, Optional[bytes]]:
        """Deepest spilled digest on ``prompt``'s chain — the walk
        mirrors ``PrefixCacheDirectory.deepest_covered`` (full blocks
        only, consecutive from the root)."""
        best: Tuple[int, Optional[bytes]] = (0, None)
        parent = b""
        for j in range((len(prompt) - 1) // block_size):
            chunk = tuple(int(t)
                          for t in prompt[j * block_size:
                                          (j + 1) * block_size])
            digest = hash_fn(parent, chunk)
            entry = self._index.get(digest.hex())
            if entry is not None and entry[0] == j + 1:
                best = (j + 1, digest)
            parent = digest
        return best

    def read(self, digest: bytes) -> KVHandoff:
        """Load + CRC-verify one stored payload, refreshing recency.
        Raises (``InjectedFault``/``OSError``/``ValueError`` — armed
        ``spill.read``, unreadable file, CRC/format mismatch); the
        caller counts a miss and falls back."""
        hexd = digest.hex()
        entry = self._index.get(hexd)
        if entry is None:
            raise ValueError(f"digest {hexd[:12]} not in spill index")
        faults.fault_point("spill.read")
        depth, _ = entry
        with open(self._path(hexd, depth), "rb") as f:
            blob = f.read()
        h = decode_handoff(blob)
        h.verify_crc()
        self._index.move_to_end(hexd)
        return h

    def note_hit(self):
        self.hits += 1
        if _om.enabled():
            _M_SPILL_HITS.inc()

    def note_miss(self):
        self.misses += 1
        if _om.enabled():
            _M_SPILL_MISSES.inc()

    def stats(self) -> dict:
        return {"entries": len(self._index),
                "bytes": self.total_bytes(),
                "max_bytes": self.max_bytes,
                "writes": self.writes, "hits": self.hits,
                "misses": self.misses, "evictions": self.evictions,
                "deepest": max((d for d, _ in self._index.values()),
                               default=0)}
