"""Host-side request scheduler for the continuous-batching engine.

Reference parity: the reference serving frontend's dynamic batching
queue (SURVEY §2.1 Inference — verify). Admission is FIFO over an
arrival-ordered queue with a max-wait batching knob: the scheduler can
hold admissions until ``min_admit`` requests are queued (amortizing
prefill dispatches) but never longer than ``max_wait_steps`` engine
blocks past the oldest request's arrival — and it always releases when
the engine would otherwise sit idle."""
from __future__ import annotations

import bisect
from dataclasses import dataclass
from typing import List, Optional

import numpy as np

__all__ = ["Request", "Scheduler"]


@dataclass
class Request:
    """One generation request. ``temperature <= 0`` decodes greedily;
    per-request sampling params ride the engine's per-slot state arrays,
    so mixed greedy/sampled traffic shares one compiled program.
    ``arrival_step``: engine-block clock tick at which the request
    becomes visible (deterministic staggered-arrival testing).

    ``deadline_ticks`` / ``deadline_s``: per-request deadlines (engine
    ticks past ``arrival_step`` / wall seconds past submit). A request
    still queued or in flight past its deadline is CANCELLED — slot
    freed, paged blocks released — and a ``RequestFailure`` lands in
    ``Server.results`` instead of a silent hang (None disables; the
    server-level ``ResilienceConfig`` supplies defaults)."""
    request_id: int
    prompt: np.ndarray
    max_new_tokens: int = 20
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    eos_token_id: Optional[int] = None
    seed: int = 0
    arrival_step: int = 0
    t_submit: float = 0.0
    deadline_ticks: Optional[int] = None
    deadline_s: Optional[float] = None


class Scheduler:
    """FIFO admission queue + batching gate + prefill pacing.

    ``prefill_token_budget``: per-tick cap on admitted PROMPT tokens —
    the chunked-prefill pacing knob. A long prompt admitted into the
    paged engine prefills in chunks paced by this same budget
    (engine.prefill_tick), so one tick never steals more than ~budget
    tokens of prefill from the in-flight decode — that bounds the
    decode-latency spike a long prompt used to cause. At least one
    request always passes when the gate is open (no starvation)."""

    def __init__(self, max_wait_steps: int = 0, min_admit: int = 1,
                 prefill_token_budget: Optional[int] = None):
        if min_admit < 1:
            raise ValueError(f"min_admit={min_admit}; must be >= 1")
        if prefill_token_budget is not None and prefill_token_budget < 1:
            raise ValueError(
                f"prefill_token_budget={prefill_token_budget}; must be "
                ">= 1 (None disables pacing)")
        self.max_wait_steps = max_wait_steps
        self.min_admit = min_admit
        self.prefill_token_budget = prefill_token_budget
        self._queue: List[Request] = []

    def submit(self, request: Request):
        # keep the queue sorted by arrival tick; insort_right preserves
        # FIFO within a tick and costs O(log Q) per submit instead of a
        # full re-sort (the north star is heavy traffic)
        bisect.insort(self._queue, request,
                      key=lambda r: r.arrival_step)

    def requeue(self, request: Request):
        """Put a popped request back at the FRONT of its arrival tick
        (the engine deferred it — e.g. the paged block pool was
        exhausted); insort_left lands it before same-tick peers."""
        bisect.insort_left(self._queue, request,
                           key=lambda r: r.arrival_step)

    def pending(self) -> int:
        return len(self._queue)

    def drop_where(self, pred) -> List[Request]:
        """Remove and return every queued request matching ``pred`` —
        the deadline/queue-wait expiry and circuit-breaker drain hook
        (arrival order of the survivors is preserved)."""
        dropped = [r for r in self._queue if pred(r)]
        if dropped:
            self._queue = [r for r in self._queue if not pred(r)]
        return dropped

    def next_arrival(self) -> Optional[int]:
        return self._queue[0].arrival_step if self._queue else None

    def pop_ready(self, now: int, free_slots: int, engine_idle: bool,
                  token_budget: Optional[int] = None) -> List[Request]:
        """Requests to admit this tick. The batching gate holds until
        ``min_admit`` requests are visible OR the oldest visible request
        has waited ``max_wait_steps`` ticks — unless the engine is idle
        (no live slots), where holding would only add latency. The
        released prefix is additionally cut at the prefill token budget
        (argument, else the scheduler's own; first request exempt)."""
        if free_slots <= 0 or not self._queue:
            return []
        # the queue is arrival-sorted: visible requests are a prefix
        n_visible = bisect.bisect_right(self._queue, now,
                                        key=lambda r: r.arrival_step)
        if n_visible == 0:
            return []
        oldest_wait = now - self._queue[0].arrival_step
        gate_open = (n_visible >= self.min_admit
                     or oldest_wait >= self.max_wait_steps
                     or engine_idle)
        if not gate_open:
            return []
        if token_budget is None:
            token_budget = self.prefill_token_budget
        take: List[Request] = []
        tokens = 0
        for r in self._queue[:min(free_slots, n_visible)]:
            t = int(np.asarray(r.prompt).size)
            if take and token_budget is not None \
                    and tokens + t > token_budget:
                break
            take.append(r)
            tokens += t
        del self._queue[:len(take)]
        return take
