"""Host-side request scheduler for the continuous-batching engine.

Reference parity: the reference serving frontend's dynamic batching
queue (SURVEY §2.1 Inference — verify). Admission is FIFO over an
arrival-ordered queue with a max-wait batching knob: the scheduler can
hold admissions until ``min_admit`` requests are queued (amortizing
prefill dispatches) but never longer than ``max_wait_steps`` engine
blocks past the oldest request's arrival — and it always releases when
the engine would otherwise sit idle."""
from __future__ import annotations

import bisect
from dataclasses import dataclass, field
from typing import List, Optional

import numpy as np

__all__ = ["Request", "ResumeState", "Scheduler"]


@dataclass
class ResumeState:
    """Carried state of a preempted in-flight request (the host half of
    the PR 5 per-slot snapshot discipline, small enough to ride the
    queue): the token stream generated so far — ``tokens[-1]`` is the
    next token to decode, sampled but not yet written to KV — the
    slot's rng key at eviction (the key the NEXT step would have split,
    which is what makes a resumed seeded-sampled stream bit-identical
    to an uninterrupted run), and the original first-token timestamp so
    TTFT keeps measuring the FIRST admission. Serialized into server
    snapshots by ``resilience.request_to_meta``.

    ``redrive`` marks fleet failure recovery (serving/fleet.py): the
    carried state was reconstructed from the fleet's own records after
    the stream's decode worker died, not handed back by a live engine.
    Prefill-only engines accept redrive resumes (the lost stream must
    re-prefill SOMEWHERE) while still refusing user-initiated
    preemption resumes — the fleet never preempts."""
    tokens: List[int] = field(default_factory=list)
    key: Optional[np.ndarray] = None    # (2,) uint32 per-slot PRNG key
    t_admit: float = 0.0
    redrive: bool = False


@dataclass
class Request:
    """One generation request. ``temperature <= 0`` decodes greedily;
    per-request sampling params ride the engine's per-slot state arrays,
    so mixed greedy/sampled traffic shares one compiled program.
    ``arrival_step``: engine-block clock tick at which the request
    becomes visible (deterministic staggered-arrival testing).

    ``deadline_ticks`` / ``deadline_s``: per-request deadlines (engine
    ticks past ``arrival_step`` / wall seconds past submit). A request
    still queued or in flight past its deadline is CANCELLED — slot
    freed, paged blocks released — and a ``RequestFailure`` lands in
    ``Server.results`` instead of a silent hang (None disables; the
    server-level ``ResilienceConfig`` supplies defaults).

    ``tenant`` / ``priority``: the multi-tenant front-door dimensions
    (serving/frontend.py). Tenants share throughput by weighted-fair
    queueing; priorities are strict — a higher-priority request admits
    first within the fairness tier and, with preemption armed, can
    evict a strictly-lower-priority slot mid-decode. ``resume`` is set
    by that eviction: a queued request carrying one re-prefills its
    generated history instead of sampling afresh."""
    request_id: int
    prompt: np.ndarray
    max_new_tokens: int = 20
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    eos_token_id: Optional[int] = None
    seed: int = 0
    arrival_step: int = 0
    t_submit: float = 0.0
    deadline_ticks: Optional[int] = None
    deadline_s: Optional[float] = None
    tenant: str = "default"
    priority: int = 0
    resume: Optional[ResumeState] = None
    # set to the current tick when a preemption requeues the request:
    # the max-queue-wait gate measures from HERE, not arrival_step — a
    # victim's decode time is service, not queue wait (deadlines, which
    # are end-to-end by contract, still measure from arrival)
    wait_from: Optional[int] = None


class Scheduler:
    """FIFO admission queue + batching gate + prefill pacing.

    ``prefill_token_budget``: per-tick cap on admitted PROMPT tokens —
    the chunked-prefill pacing knob. A long prompt admitted into the
    paged engine prefills in chunks paced by this same budget
    (engine.prefill_tick), so one tick never steals more than ~budget
    tokens of prefill from the in-flight decode — that bounds the
    decode-latency spike a long prompt used to cause. At least one
    request always passes when the gate is open (no starvation)."""

    # strict FIFO pop: a slot freed by preemption would go back to the
    # front-inserted victim, so the Server's preemption policy refuses
    # to run on this class (frontend.FairScheduler sets True)
    priority_aware = False

    def __init__(self, max_wait_steps: int = 0, min_admit: int = 1,
                 prefill_token_budget: Optional[int] = None):
        if min_admit < 1:
            raise ValueError(f"min_admit={min_admit}; must be >= 1")
        if prefill_token_budget is not None and prefill_token_budget < 1:
            raise ValueError(
                f"prefill_token_budget={prefill_token_budget}; must be "
                ">= 1 (None disables pacing)")
        self.max_wait_steps = max_wait_steps
        self.min_admit = min_admit
        self.prefill_token_budget = prefill_token_budget
        self._queue: List[Request] = []

    def submit(self, request: Request):
        # keep the queue sorted by arrival tick; insort_right preserves
        # FIFO within a tick and costs O(log Q) per submit instead of a
        # full re-sort (the north star is heavy traffic)
        bisect.insort(self._queue, request,
                      key=lambda r: r.arrival_step)

    def requeue(self, request: Request):
        """Put a popped request back at the FRONT of its arrival tick
        (the engine deferred it — e.g. the paged block pool was
        exhausted); insort_left lands it before same-tick peers."""
        bisect.insort_left(self._queue, request,
                           key=lambda r: r.arrival_step)

    def pending(self) -> int:
        return len(self._queue)

    def drop_where(self, pred) -> List[Request]:
        """Remove and return every queued request matching ``pred`` —
        the deadline/queue-wait expiry and circuit-breaker drain hook
        (arrival order of the survivors is preserved)."""
        dropped = [r for r in self._queue if pred(r)]
        if dropped:
            self._queue = [r for r in self._queue if not pred(r)]
        return dropped

    def next_arrival(self) -> Optional[int]:
        return self._queue[0].arrival_step if self._queue else None

    def visible(self, now: int) -> List[Request]:
        """Queued requests already visible at tick ``now`` (a PEEK — the
        queue is untouched). The server's preemption policy reads this
        to decide whether higher-priority work is waiting on capacity."""
        n = bisect.bisect_right(self._queue, now,
                                key=lambda r: r.arrival_step)
        return self._queue[:n]

    def _gate_visible(self, now: int, free_slots: int,
                      engine_idle: bool,
                      token_budget: Optional[int]):
        """Shared admission preamble for this class and its fair
        subclass (one implementation, so a gate-semantics fix can never
        silently diverge the two): returns ``(n_visible,
        token_budget)`` when the batching gate is open, else None. The
        gate holds until ``min_admit`` requests are visible OR the
        oldest visible request has waited ``max_wait_steps`` ticks —
        unless the engine is idle (no live slots), where holding would
        only add latency."""
        if free_slots <= 0 or not self._queue:
            return None
        # the queue is arrival-sorted: visible requests are a prefix
        n_visible = bisect.bisect_right(self._queue, now,
                                        key=lambda r: r.arrival_step)
        if n_visible == 0:
            return None
        oldest_wait = now - self._queue[0].arrival_step
        gate_open = (n_visible >= self.min_admit
                     or oldest_wait >= self.max_wait_steps
                     or engine_idle)
        if not gate_open:
            return None
        if token_budget is None:
            token_budget = self.prefill_token_budget
        return n_visible, token_budget

    def pop_ready(self, now: int, free_slots: int, engine_idle: bool,
                  token_budget: Optional[int] = None) -> List[Request]:
        """Requests to admit this tick (see :meth:`_gate_visible` for
        the batching gate). The released prefix is additionally cut at
        the prefill token budget (argument, else the scheduler's own;
        first request exempt)."""
        gate = self._gate_visible(now, free_slots, engine_idle,
                                  token_budget)
        if gate is None:
            return []
        n_visible, token_budget = gate
        take: List[Request] = []
        tokens = 0
        for r in self._queue[:min(free_slots, n_visible)]:
            t = int(np.asarray(r.prompt).size)
            if take and token_budget is not None \
                    and tokens + t > token_budget:
                break
            take.append(r)
            tokens += t
        del self._queue[:len(take)]
        return take
