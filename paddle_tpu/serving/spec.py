"""Speculative multi-token decoding riding the slot pool: draft-verify
inside the engine's ONE compiled decode program.

Plain decode emits one token per compiled step per slot — the step is
memory-bandwidth-bound (every parameter and KV byte is re-read per
token) and the MXU sits mostly idle. Speculative decoding converts that
idle compute into tokens: a host-side **n-gram / prompt-lookup drafter**
(no second model) proposes up to ``k`` candidate tokens per live slot
from the tokens the slot has already seen (prompt + generated history),
and ONE compiled **verify step** scores all ``k+1`` positions per slot
in a single forward:

- the ``(S, k+1)`` verify block generalizes the existing ``(S, 1)``
  decode block — ``cached_attention`` already takes vector per-row
  ``pos``, so row ``i`` of the window attends its own prefix *plus the
  drafts before it*, exactly the causal semantics verification needs;
- the KV write is a masked ``k+1``-wide scatter: all ``k+1`` candidate
  K/V entries land at ``pos .. pos+k`` up front (paged: through the
  slot's block table, with positions past the table routed to the
  trash block);
- **greedy acceptance** keeps the longest draft prefix matching the
  target model's own argmax, plus one bonus token: the emitted tokens
  of a step are ``t_0 .. t_a`` where ``t_i = argmax(logits at position
  i)`` and ``a`` = number of leading drafts with ``d_{i+1} == t_i``.
  Every emitted token is the target model's own choice, so greedy
  streams are **bit-identical** to non-speculative decode — the
  serving parity harness is the verifier;
- per-slot **ragged advance** moves ``pos``/``remaining``/eos state
  in-graph by each slot's accepted length (0..k+1 tokens per step per
  slot, including an eos landing mid-span).

The dead-KV invariant (why rejected drafts are harmless): a step that
advances by ``n`` leaves junk K/V at positions ``pos+n .. pos+k``, but
the NEXT step writes its own ``k+1`` window starting at ``pos+n`` —
which covers every junk position — before attention can read them
(row ``i`` masks ``t_idx <= pos+n+i``, and positions up to ``pos+n+i``
are freshly written this step or emitted history). ``pos`` never
reaches a rejected position, so no junk entry is ever attended, dense
or paged. Paged slots already allocate blocks for the full request up
front (``blocks_needed``), so the max advance is always covered; draft
positions past the table width scatter into the reserved trash block.

Seeded sampling initially falls back to ``k = 0``: a sampled slot's
verify step emits exactly one token through the SAME per-slot
key-split + ``slot_sample_logits`` sequence as the plain block, so the
per-request key-schedule parity with ``generate(seed)`` is preserved
(speculative sampling with rejection resampling would change the
schedule — a follow-up, not a silent break).

Everything is default-off: pass ``spec=SpecConfig(k=...)`` (or
``spec=True``) to ``ContinuousBatchingEngine``, or set
``PT_SERVING_SPEC=<k>`` (``PT_SERVING_SPEC_NGRAM`` bounds the drafter's
n-gram length). Composes with ``paged=True``; tensor-parallel serving
(``tp=``) is not yet composed with spec and is refused loudly.
"""
from __future__ import annotations

import dataclasses
import time
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..observability import metrics as _om
from ..utils import faults
from ..utils.flags import env_int
from .engine import (ContinuousBatchingEngine, ModelStepBackend,
                     _M_COMPILES, _M_DECODE_TOKENS, _M_STEPS, _M_TOKENS,
                     slot_sample_logits)
from .paging import PagedEngine, PagedModelStepBackend

__all__ = ["SpecConfig", "resolve_spec_config", "ngram_propose",
           "build_spec_block_fn", "SpecModelStepBackend",
           "SpecPagedStepBackend", "SpecEngine", "SpecPagedEngine"]

# speculative-decode metric families (no-ops until metrics.enable() /
# PT_METRICS; registered at import so the catalog is complete at zero)
_M_SPEC_STEPS = _om.counter("pt_serving_spec_verify_steps_total",
                            "speculative verify steps dispatched")
_M_SPEC_DRAFTED = _om.counter("pt_serving_spec_draft_tokens_total",
                              "draft tokens proposed to the verify block")
_M_SPEC_ACCEPTED = _om.counter(
    "pt_serving_spec_accepted_tokens_total",
    "draft tokens the target model's argmax confirmed")
_M_SPEC_EMITTED = _om.counter(
    "pt_serving_spec_emitted_tokens_total",
    "tokens emitted by verify steps (accepted drafts + bonus tokens)")
_M_SPEC_RATE = _om.gauge(
    "pt_serving_spec_acceptance_rate",
    "lifetime accepted/proposed draft-token ratio of the engine")


# ---------------------------------------------------------------------------
# config
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class SpecConfig:
    """How to speculate. ``k``: max draft tokens per slot per verify
    step (the verify window is ``k+1`` wide; ``k=0`` degenerates to
    plain one-token decode through the same program). ``ngram_max`` /
    ``ngram_min``: the prompt-lookup drafter matches the longest
    trailing n-gram in this range against the slot's own history."""
    k: int = 4
    ngram_max: int = 3
    ngram_min: int = 1

    def __post_init__(self):
        if self.k < 0:
            raise ValueError(f"SpecConfig.k={self.k}; must be >= 0")
        if self.ngram_min < 1:
            raise ValueError(
                f"SpecConfig.ngram_min={self.ngram_min}; must be >= 1")
        if self.ngram_max < self.ngram_min:
            raise ValueError(
                f"SpecConfig.ngram_max={self.ngram_max} < ngram_min="
                f"{self.ngram_min}")


def resolve_spec_config(spec) -> Optional[SpecConfig]:
    """Normalize the engine's ``spec`` argument: SpecConfig
    pass-through, ``True`` -> defaults, ``False`` -> off, ``None`` ->
    the ``PT_SERVING_SPEC`` env knob (integer k; 0/unset disables)."""
    if isinstance(spec, SpecConfig):
        return spec
    if spec is True:
        return SpecConfig()
    if spec is False:
        return None
    if spec is not None:
        raise ValueError(f"spec={spec!r}: pass a SpecConfig, "
                         "True/False, or None (env-controlled)")
    k = env_int("PT_SERVING_SPEC", 0)
    if k <= 0:
        return None
    return SpecConfig(k=k, ngram_max=env_int("PT_SERVING_SPEC_NGRAM", 3))


def spec_requested(spec, backend) -> bool:
    """The ``__new__`` routing decision: an explicitly passed spec
    backend IS the decision; otherwise the spec argument / env knob
    (an explicit non-spec backend is never rerouted by the env flag —
    same contract as paged/tp)."""
    if backend is not None:
        return getattr(backend, "spec_k", None) is not None
    return resolve_spec_config(spec) is not None


# ---------------------------------------------------------------------------
# host-side drafter: n-gram / prompt lookup
# ---------------------------------------------------------------------------

def _lookup_once(h: np.ndarray, k: int, ngram_max: int,
                 ngram_min: int) -> np.ndarray:
    """One prompt-lookup round: the continuation after the most recent
    earlier occurrence of the longest trailing n-gram of ``h``."""
    L = int(h.size)
    empty = np.zeros((0,), np.int32)
    if L < 2:
        return empty
    for n in range(min(ngram_max, L - 1), ngram_min - 1, -1):
        pat = h[L - n:]
        # windows over h[:-1]: every match has at least one continuation
        # token (which may overlap the pattern itself — cycles)
        win = np.lib.stride_tricks.sliding_window_view(h[:L - 1], n)
        hits = np.flatnonzero((win == pat[None, :]).all(axis=1))
        if hits.size:
            s = int(hits[-1])
            out = h[s + n:s + n + k]
            if out.size:
                return out.astype(np.int32)
    return empty


def ngram_propose(history, k: int, ngram_max: int = 3,
                  ngram_min: int = 1) -> np.ndarray:
    """Prompt-lookup drafting: find the most recent earlier occurrence
    of the LONGEST trailing n-gram (length ``ngram_max`` down to
    ``ngram_min``) of ``history`` and propose the tokens that followed
    it. The lookup is SELF-EXTENDING: when the match sits near the end
    of history (a cycle of period p offers only p continuation tokens),
    the draft-so-far is appended to the history and the lookup repeats
    until ``k`` tokens are drafted or no match remains — so a period-2
    loop still fills a k=8 window. Returns a (<=k,) int32 array (empty
    = no draft). Pure host numpy — it never touches the compiled
    program; a greedy stream that has entered a cycle is predicted
    perfectly once the cycle has appeared twice."""
    h = np.asarray(history, np.int32).reshape(-1)
    empty = np.zeros((0,), np.int32)
    if k <= 0 or h.size < 2:
        return empty
    out = empty
    while out.size < k:
        prop = _lookup_once(np.concatenate([h, out]) if out.size else h,
                            k - int(out.size), ngram_max, ngram_min)
        if prop.size == 0:
            break
        out = np.concatenate([out, prop])
    return out


# ---------------------------------------------------------------------------
# the ONE compiled verify program
# ---------------------------------------------------------------------------

def build_spec_block_fn(pure, k: int, trace_counter=None,
                        paged: bool = False):
    """The spec engine's ONE decode program: a single draft-verify step
    over the slot pool. ``pure`` must be the all-positions verify head
    (``build_decode_step(..., all_positions=True)``) — it returns
    (S, k+1, V) log-probs for the window ``[tok, d_1 .. d_k]`` written
    at per-row positions ``pos .. pos+k``.

    In-graph per slot: targets ``t_i = argmax`` per position (row 0 of
    a sampled slot goes through the SAME key-split +
    ``slot_sample_logits`` sequence as the plain block — sampled slots
    never speculate, keeping generate(seed) key-schedule parity),
    greedy acceptance ``a`` = longest prefix with ``d_{i+1} == t_i``,
    ragged advance ``n_emit = min(a+1, remaining)`` further cut at the
    first emitted eos; ``pos/tok/remaining/live`` advance by each
    slot's own ``n_emit``. Emits the (S, k+1) target-token matrix,
    per-slot emission counts, and per-slot no-NaN ``ok`` flags (the
    resilience sentinel, same contract as the plain block)."""
    W = k + 1

    def block_fn(pv, bv, cache_flat, state, draft, n_draft):
        if trace_counter is not None:       # runs only while tracing
            trace_counter[0] += 1
        st = state
        sp = jax.vmap(jax.random.split)(st["key"])      # (S, 2, 2)
        new_key, sub = sp[:, 0], sp[:, 1]
        toks_in = jnp.concatenate(
            [st["tok"][:, None], draft.astype(jnp.int32)], axis=1)
        if paged:
            tbl = jnp.where(st["live"][:, None], st["table"], 0)
            logp, cf = pure(pv, bv, toks_in, cache_flat, st["pos"],
                            None, None, tbl)
        else:
            logp, cf = pure(pv, bv, toks_in, cache_flat, st["pos"],
                            None, st["pad"])
        # (S, W, V) log-probs; NaN anywhere in the slot's window marks
        # a poisoned row (finite weights/cache cannot produce NaN)
        ok = ~jnp.any(jnp.isnan(logp), axis=(1, 2))
        t = jnp.argmax(logp, axis=-1).astype(jnp.int32)       # (S, W)
        # position 0 through the sampling path: greedy rows get the
        # identical argmax, sampled rows the identical key schedule
        first = slot_sample_logits(logp[:, 0], sub, st["temp"],
                                   st["topk"], st["topp"])
        t = t.at[:, 0].set(first)
        # sampled rows never accept drafts (k=0 fallback in-graph even
        # if the host proposed some)
        n_eff = jnp.where(st["temp"] <= 0.0, n_draft, 0)
        if k > 0:
            idx = jnp.arange(k)
            acc = (idx[None, :] < n_eff[:, None]) & (draft == t[:, :k])
            a = jnp.cumprod(acc.astype(jnp.int32), axis=1).sum(axis=1)
        else:
            a = jnp.zeros_like(st["pos"])
        live = st["live"]
        n_emit = jnp.where(live, jnp.minimum(a + 1, st["remaining"]), 0)
        cols = jnp.arange(W)[None, :]
        is_eos = ((st["eos"][:, None] >= 0)
                  & (t == st["eos"][:, None])
                  & (cols < n_emit[:, None]))
        eos_pos = jnp.min(jnp.where(is_eos, cols, W), axis=1)
        hit = eos_pos < W               # eos inside the accepted span
        n_emit = jnp.where(hit, eos_pos + 1, n_emit)
        rem = jnp.where(live, st["remaining"] - n_emit, st["remaining"])
        rem = jnp.where(hit, 0, rem)
        last = jnp.take_along_axis(
            t, jnp.maximum(n_emit - 1, 0)[:, None], axis=1)[:, 0]
        st2 = dict(st,
                   tok=jnp.where(n_emit > 0, last, st["tok"]),
                   pos=st["pos"] + n_emit,
                   remaining=rem, key=new_key,
                   live=live & (rem > 0))
        return cf, st2, t, n_emit, ok

    return block_fn


# ---------------------------------------------------------------------------
# backends
# ---------------------------------------------------------------------------

class _SpecBackendMixin:
    """Adds the verify program to a model step backend. The plain
    decode-block jit stays constructed (jax.jit wrapping is free until
    traced) but the spec engine never calls it — ``decode_traces``
    counts the verify block, so the compile-count pin stays == 1."""

    def _setup_spec(self, model, spec: SpecConfig, paged: bool):
        from ..models.generation import build_decode_step
        self.spec = spec
        self.spec_k = spec.k
        # the verify head dequantizes the same weight codes as the
        # plain block under weight-only quant (no-op wrapper otherwise)
        verify = self._maybe_quant_pure(
            build_decode_step(model, None, self._tree_holder,
                              all_positions=True))
        self._spec_jit = jax.jit(
            build_spec_block_fn(verify, spec.k, self.decode_traces,
                                paged=paged),
            donate_argnums=(2, 3))
        # one verify step per host round-trip (drafts are host inputs)
        self.block_size = 1

    def spec_verify(self, cache_flat, state, draft, n_draft):
        return self._spec_jit(self._pv, self._bv, cache_flat, state,
                              draft, n_draft)


class SpecModelStepBackend(_SpecBackendMixin, ModelStepBackend):
    """Dense slot-pool backend with the (S, k+1) verify program."""

    def __init__(self, model, num_slots: int, max_len: int,
                 decode_block: int, spec: SpecConfig, quant=None,
                 fuse=None):
        super().__init__(model, num_slots, max_len, decode_block,
                         quant=quant, fuse=fuse)
        self._setup_spec(model, spec, paged=False)


class SpecPagedStepBackend(_SpecBackendMixin, PagedModelStepBackend):
    """Paged-arena backend with the (S, k+1) verify program (chunked
    prefill is inherited unchanged)."""

    def __init__(self, model, num_slots: int, max_len: int,
                 decode_block: int, block_size: int, num_blocks: int,
                 kv_int8: bool, prefill_chunk: int, spec: SpecConfig,
                 quant=None, fuse=None):
        super().__init__(model, num_slots, max_len, decode_block,
                         block_size, num_blocks, kv_int8, prefill_chunk,
                         quant=quant, fuse=fuse)
        self._setup_spec(model, spec, paged=True)


# ---------------------------------------------------------------------------
# engines
# ---------------------------------------------------------------------------

class _SpecEngineMixin:
    """Draft-verify step loop + acceptance accounting shared by the
    dense and paged speculative engines. Overrides ``step_block`` with
    the verify dispatch; admission, cancellation, deadlines, the NaN
    quarantine and snapshot/restore all ride the base machinery."""

    def _init_spec(self, spec: Optional[SpecConfig], backend, tp=None):
        from .tp import resolve_tp_config
        if backend is None and resolve_tp_config(tp) is not None:
            raise NotImplementedError(
                "speculative decoding is not yet composed with "
                "tensor-parallel serving — drop spec= or tp= (ROADMAP "
                "follow-up)")
        if backend is not None:
            cfg = getattr(backend, "spec", None)
            if cfg is None:
                raise ValueError(
                    "speculative engines need a spec backend "
                    "(SpecModelStepBackend / SpecPagedStepBackend); got "
                    f"{type(backend).__name__}")
            self.spec = cfg
        else:
            self.spec = resolve_spec_config(spec) or SpecConfig()
        self.spec_k = self.spec.k

    # -- lifecycle ---------------------------------------------------------
    def reset(self):
        super().reset()
        self.verify_steps = 0
        self.draft_proposed = 0        # draft tokens handed to verify
        self.draft_accepted = 0        # drafts the target confirmed

    # -- introspection -----------------------------------------------------
    def acceptance_rate(self) -> float:
        """Lifetime accepted/proposed draft-token ratio."""
        return self.draft_accepted / self.draft_proposed \
            if self.draft_proposed else 0.0

    def mean_accepted_per_step(self) -> float:
        """Mean accepted draft tokens per verify step (the emitted
        tokens/step is this + the always-emitted bonus token)."""
        return self.draft_accepted / self.verify_steps \
            if self.verify_steps else 0.0

    # -- drafting ----------------------------------------------------------
    @staticmethod
    def _history(run) -> np.ndarray:
        """The slot's prompt+generated history as int32, cached on the
        run and extended incrementally — re-converting the whole
        (growing) token list every tick measurably taxes the host side
        of the verify loop. The cache is plain derived state: restored
        runs just rebuild it on first use."""
        done = len(run.tokens)
        cached = getattr(run, "_spec_hist", None)
        if cached is not None and cached[0] == done:
            return cached[1]
        if cached is not None and cached[0] < done:
            hist = np.concatenate([
                cached[1],
                np.asarray(run.tokens[cached[0]:], np.int32)])
        else:
            hist = np.concatenate([
                np.asarray(run.request.prompt, np.int32).reshape(-1),
                np.asarray(run.tokens, np.int32)])
        run._spec_hist = (done, hist)
        return hist

    def _propose(self) -> Tuple[np.ndarray, np.ndarray]:
        """Per-slot draft proposals for this tick: (S, k) tokens +
        (S,) counts. Greedy decoding slots only (sampled slots keep the
        k=0 key-schedule fallback); capped at remaining-1 so a draft
        can never outrun the slot's token budget."""
        S, k = self.num_slots, self.spec_k
        draft = np.zeros((S, k), np.int32)
        n = np.zeros((S,), np.int32)
        if k == 0:
            return draft, n
        cfg = self.spec
        for slot, run in enumerate(self._slots):
            if run is None or slot in self._prefill_slots:
                continue
            if run.request.temperature > 0:
                continue               # sampled: k=0 fallback
            cap = min(k, int(self._remaining_host[slot]) - 1)
            if cap <= 0:
                continue
            prop = ngram_propose(self._history(run), cap,
                                 cfg.ngram_max, cfg.ngram_min)
            if prop.size:
                draft[slot, :prop.size] = prop
                n[slot] = prop.size
        return draft, n

    # -- decode ------------------------------------------------------------
    def step_block(self):
        """One draft-verify round over the pool, then sync ONCE: pull
        the (S, k+1) target-token matrix + per-slot emission counts,
        credit each slot its 0..k+1 accepted tokens, retire finished
        slots. Same failure semantics as the plain block: the
        ``serving.step_block`` fault site raises BEFORE drafting (a
        retry re-drafts the identical proposal — drafting is a pure
        function of host state), ``serving.harvest`` raises between
        dispatch and transfer with the outputs parked for a
        re-harvest, and a NaN slot is quarantined alone."""
        from ..profiler import RecordEvent
        if self._pending_block is None:
            if not self.has_decoding():
                return
            if faults.should_fire("serving.poison"):
                self._poison_live_slot()
            faults.fault_point("serving.step_block")
            draft, n_draft = self._propose()
            with RecordEvent("serving.spec_verify"):
                out = self.backend.spec_verify(
                    self._cache, self._state, jnp.asarray(draft),
                    jnp.asarray(n_draft))
            self._cache, self._state = out[0], out[1]
            self._pending_block = (out[2], out[3], out[4], n_draft)
            self.steps += 1
            self.verify_steps += 1
            proposed = int(n_draft.sum())
            self.draft_proposed += proposed
            # the verify lattice is S slots x (k+1) positions per step
            self.slot_steps += self.num_slots * (self.spec_k + 1)
            _M_STEPS.inc()
            _M_COMPILES.set(self.backend.decode_traces[0])
            self._note_decode_bytes(1)
            _M_SPEC_STEPS.inc()
            _M_SPEC_DRAFTED.inc(proposed)
        faults.fault_point("serving.harvest")
        toks, counts, oks, n_draft = self._pending_block
        # ONE batched host sync per verify step (4 separate np.asarray
        # round-trips measurably tax the tick at CPU dispatch scale)
        toks_np, counts_np, oks_np, rem_np = jax.device_get(
            (toks, counts, oks, self._state["remaining"]))
        self._pending_block = None
        emitted = int(counts_np.sum())
        accepted = int(np.maximum(counts_np - 1, 0).sum())
        self.decode_tokens += emitted
        self.tokens_emitted += emitted
        self.draft_accepted += accepted
        _M_DECODE_TOKENS.inc(emitted)
        _M_TOKENS.inc(emitted)
        _M_SPEC_EMITTED.inc(emitted)
        _M_SPEC_ACCEPTED.inc(accepted)
        _M_SPEC_RATE.set(self.acceptance_rate())
        now = time.perf_counter()
        for slot, run in enumerate(self._slots):
            if run is None or slot in self._prefill_slots:
                continue
            n = int(counts_np[slot])
            if n > 0:
                run.tokens.extend(int(t) for t in toks_np[slot, :n])
            if self.nan_sentinel and n > 0 and not bool(oks_np[slot]):
                self.cancel_slot(slot, "poisoned")
                continue
            self._remaining_host[slot] = rem_np[slot]
            if rem_np[slot] == 0:
                self._retire(slot, run, now)

    # -- snapshot / restore ------------------------------------------------
    def snapshot_state(self):
        meta, arrays = super().snapshot_state()
        meta["spec"] = {"k": self.spec.k,
                        "ngram_max": self.spec.ngram_max,
                        "ngram_min": self.spec.ngram_min,
                        "verify_steps": self.verify_steps,
                        "draft_proposed": self.draft_proposed,
                        "draft_accepted": self.draft_accepted}
        return meta, arrays

    def restore_state(self, meta, arrays):
        sm = meta.get("spec")
        if sm is not None and sm["k"] != self.spec.k:
            raise ValueError(
                f"snapshot was taken at spec k={sm['k']}, this engine "
                f"runs k={self.spec.k} — the verify program shape (and "
                "the paged write window) must match to resume")
        super().restore_state(meta, arrays)
        if sm is not None:
            self.verify_steps = sm["verify_steps"]
            self.draft_proposed = sm["draft_proposed"]
            self.draft_accepted = sm["draft_accepted"]


class SpecEngine(_SpecEngineMixin, ContinuousBatchingEngine):
    """Dense slot-pool engine with draft-verify decode. Constructed via
    ``ContinuousBatchingEngine(..., spec=SpecConfig(k=...))`` (or
    ``PT_SERVING_SPEC=<k>``)."""

    def __init__(self, model=None, num_slots: int = 4,
                 max_len: int = 256, decode_block: int = 8,
                 prompt_buckets: Optional[Sequence[int]] = None,
                 backend=None, *, paged: Optional[bool] = None,
                 spec=None, tp=None, quant=None, megakernel=None):
        if paged:
            # same loud-refusal rule as spec= on a direct subclass
            # ctor: silently serving DENSE from a paged= request would
            # be a misconfiguration, not a preference
            raise ValueError(
                "SpecEngine is the dense speculative engine — use the "
                "ContinuousBatchingEngine factory (paged=True, "
                "spec=...) or SpecPagedEngine for the paged one")
        self._init_spec(spec, backend, tp)
        super().__init__(model, num_slots, max_len, decode_block,
                         prompt_buckets, backend, paged=False,
                         quant=quant, megakernel=megakernel)

    def _build_backend(self, model, num_slots, max_len, decode_block,
                       quant=None, fuse=None):
        return SpecModelStepBackend(model, num_slots, max_len,
                                    decode_block, self.spec,
                                    quant=quant, fuse=fuse)


class SpecPagedEngine(_SpecEngineMixin, PagedEngine):
    """Paged-arena engine with draft-verify decode (chunked prefill,
    prefix reuse and the block manager are inherited unchanged — the
    verify window's junk writes past a slot's table land in the trash
    block, and accepted positions are covered by the blocks the
    request already allocated up front)."""

    def __init__(self, model=None, num_slots: int = 4,
                 max_len: int = 256, decode_block: int = 8,
                 prompt_buckets: Optional[Sequence[int]] = None,
                 backend=None, *, paged: bool = True, spec=None,
                 block_size: Optional[int] = None,
                 num_blocks: Optional[int] = None,
                 kv_int8: Optional[bool] = None,
                 prefill_chunk: Optional[int] = None,
                 hash_fn=None, tp=None, quant=None, megakernel=None):
        if paged is not None and not paged:
            raise ValueError(
                "SpecPagedEngine is the paged speculative engine — use "
                "the ContinuousBatchingEngine factory (spec=...) or "
                "SpecEngine for the dense one")
        self._init_spec(spec, backend, tp)
        super().__init__(model, num_slots, max_len, decode_block,
                         prompt_buckets, backend, paged=True,
                         block_size=block_size, num_blocks=num_blocks,
                         kv_int8=kv_int8, prefill_chunk=prefill_chunk,
                         hash_fn=hash_fn, quant=quant,
                         megakernel=megakernel)

    def _build_paged_backend(self, model, num_slots, max_len,
                             decode_block, block_size, num_blocks,
                             kv_int8, prefill_chunk, quant=None,
                             fuse=None):
        return SpecPagedStepBackend(model, num_slots, max_len,
                                    decode_block, block_size,
                                    num_blocks, kv_int8, prefill_chunk,
                                    self.spec, quant=quant, fuse=fuse)
