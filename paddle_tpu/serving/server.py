"""Serving loop: Scheduler + ContinuousBatchingEngine + metrics +
resilience policies.

One iteration of the loop = one tick of the engine-block clock: expire
deadlined requests, admit whatever the scheduler releases into free
slots, advance chunked prefills, run one compiled decode block, harvest
retired requests. Per-request latency and engine-level tokens/s /
slot-occupancy counters are emitted as profiler RecordEvent spans
(chrome-trace) and summarized by ``stats()`` — the serving analogue of
the training loop's MFU line.

Failure paths are first-class (serving/resilience.py): every submitted
request ends either in a completed output array or an explicit
``RequestFailure`` in ``results`` — deadlines cancel (slot freed, paged
blocks released), bounded queues shed, transient step failures retry
with seeded exponential backoff, a circuit breaker drains after N
consecutive failures, and a NaN-poisoned slot is quarantined alone.
``snapshot()``/``restore()`` make the whole server crash-safe: a
process killed between ticks resumes from the snapshot and finishes
every stream bit-identical to an uninterrupted run."""
from __future__ import annotations

import time
from typing import Dict, Optional

import numpy as np

from ..utils import faults
from .engine import ContinuousBatchingEngine
from .resilience import (RequestFailure, ResilienceConfig,
                         ResilienceState, load_snapshot,
                         request_from_meta, request_to_meta,
                         save_snapshot)
from .scheduler import Request, Scheduler

__all__ = ["Server"]


class Server:
    """Continuous-batching server over an engine. ``submit()`` requests
    (optionally with future ``arrival_step`` ticks and per-request
    deadlines), then ``run_until_idle()`` — results match per-request
    ``generate()``: prompt + generated ids, rows that hit eos padded
    with eos to ``max_new_tokens`` (greedy traffic is bit-identical).
    Failed requests surface as :class:`RequestFailure` values in
    ``results`` instead of hanging the loop."""

    def __init__(self, engine: ContinuousBatchingEngine,
                 scheduler: Optional[Scheduler] = None,
                 resilience: Optional[ResilienceConfig] = None):
        self.engine = engine
        self.scheduler = scheduler or Scheduler()
        self.resilience = resilience or ResilienceConfig()
        self._res = ResilienceState(self.resilience)
        engine.nan_sentinel = self.resilience.nan_sentinel
        self.results: Dict[int, object] = {}
        self.latencies: Dict[int, float] = {}
        self.ttft: Dict[int, float] = {}       # submit -> first token
        self.tick_seconds: list = []           # per-tick wall times
        self._next_id = 0
        self._clock = 0
        self._wall = 0.0

    def submit(self, prompt, max_new_tokens: int = 20,
               temperature: float = 0.0, top_k: int = 0,
               top_p: float = 1.0, eos_token_id: Optional[int] = None,
               seed: int = 0, arrival_step: int = 0,
               deadline_ticks: Optional[int] = None,
               deadline_s: Optional[float] = None) -> int:
        """Queue one request; returns its id (key into ``results``).
        Capacity is validated HERE — a request that can never fit a
        slot (or, paged, the block pool) is rejected at the door, not
        re-queued forever mid-stream. With ``max_queue_depth`` set, a
        submit beyond the cap is load-shed: the id comes back with a
        ``RequestFailure(reason="shed")`` already recorded."""
        prompt = np.asarray(prompt, np.int32)
        self.engine.validate_request(int(prompt.size), max_new_tokens)
        rid = self._next_id
        self._next_id += 1
        depth = self.resilience.max_queue_depth
        if depth is not None and self.scheduler.pending() >= depth:
            self._res.shed_requests += 1
            self._fail(rid, "shed",
                       f"queue depth at cap ({depth}); retry later")
            return rid
        self.scheduler.submit(Request(
            request_id=rid, prompt=np.asarray(prompt, np.int32),
            max_new_tokens=max_new_tokens, temperature=temperature,
            top_k=top_k, top_p=top_p, eos_token_id=eos_token_id,
            seed=seed, arrival_step=arrival_step,
            t_submit=time.perf_counter(),
            deadline_ticks=deadline_ticks, deadline_s=deadline_s))
        return rid

    # -- failure plumbing --------------------------------------------------
    def _fail(self, rid: int, reason: str, message: str = "",
              tokens: int = 0):
        self.results[rid] = RequestFailure(
            request_id=rid, reason=reason, message=message,
            tokens_emitted=tokens)
        self._res.count_failure(reason)

    def _deadline_hit(self, req: Request, now: float) -> bool:
        cfg = self.resilience
        dt = req.deadline_ticks if req.deadline_ticks is not None \
            else cfg.deadline_ticks
        if dt is not None and self._clock - req.arrival_step > dt:
            return True
        ds = req.deadline_s if req.deadline_s is not None \
            else cfg.deadline_s
        return ds is not None and now - req.t_submit > ds

    def _expire(self):
        """Cancel queued and in-flight requests past their deadline
        (and queued ones past the max queue wait). In-flight
        cancellation goes through ``engine.cancel_slot`` — the slot is
        killed in-graph and paged blocks release at correct refcounts;
        the failure surfaces through the normal harvest."""
        now = time.perf_counter()
        mw = self.resilience.max_queue_wait_ticks

        def queued_out(r):
            if mw is not None and self._clock - r.arrival_step > mw:
                return True
            return self._deadline_hit(r, now)

        for r in self.scheduler.drop_where(queued_out):
            self._fail(r.request_id, "timeout",
                       f"expired in queue at tick {self._clock}")
        for slot, run in self.engine.live_runs():
            if self._deadline_hit(run.request, now):
                self.engine.cancel_slot(slot, "timeout")

    def _with_retry(self, fn) -> bool:
        """Run ``fn`` with the transient-failure policy: seeded
        exponential backoff between attempts; every failed attempt
        counts toward the consecutive-failure budget that opens the
        circuit breaker. Returns False if ``fn`` never succeeded (the
        tick just moves on — or the breaker drains everything)."""
        res, cfg = self._res, self.resilience
        for attempt in range(cfg.retry_attempts + 1):
            if res.breaker_open:
                return False
            try:
                fn()
                res.consecutive_failures = 0
                return True
            except res.transient as e:
                res.step_failures += 1
                res.consecutive_failures += 1
                res.last_error = f"{type(e).__name__}: {e}"
                if res.consecutive_failures >= cfg.breaker_threshold:
                    res.breaker_open = True
                    return False
                if attempt < cfg.retry_attempts:
                    res.retries += 1
                    time.sleep(res.backoff_s(attempt))
        return False

    def _quarantine_all(self, reason: str):
        """Circuit-breaker drain: cancel every in-flight request and
        fail everything still queued — the server ends in a clean,
        fully-accounted state instead of wedging on a dead device."""
        for slot, _ in self.engine.live_runs():
            self.engine.cancel_slot(slot, reason)
        for r in self.scheduler.drop_where(lambda r: True):
            self._fail(r.request_id, reason,
                       "circuit breaker open: queue drained")

    # -- the tick ----------------------------------------------------------
    def _tick(self):
        self._expire()
        admitted = self.scheduler.pop_ready(
            self._clock, self.engine.free_slot_count(),
            engine_idle=not self.engine.has_live())
        for i, req in enumerate(admitted):
            if not self.engine.try_admit(req):
                # re-queue in reverse: requeue() front-inserts per
                # arrival tick, so forward order would flip
                # same-tick FIFO and let peers overtake the oldest
                for r in reversed(admitted[i:]):
                    self.scheduler.requeue(r)
                break
        prefill_tick = getattr(self.engine, "prefill_tick", None)
        if prefill_tick is not None:
            # chunks dispatched before a mid-loop fault keep their
            # cursors, so a retry must only get the UNSPENT part of the
            # tick's budget — otherwise each retry re-arms a full
            # budget and one tick can blow the decode-interference
            # bound chunked prefill exists to enforce
            budget = self.scheduler.prefill_token_budget
            spent = [0]

            def _prefill():
                b = None if budget is None else budget - spent[0]
                if b is not None and b <= 0 and spent[0] > 0:
                    return           # budget already consumed this tick
                # measure spend from the engine counter, not the return
                # value — a fault raises out of prefill_tick AFTER some
                # chunks already dispatched, and those must still count
                before = self.engine.prefilled_tokens
                try:
                    prefill_tick(b)
                finally:
                    spent[0] += self.engine.prefilled_tokens - before

            self._with_retry(_prefill)
        if self.engine.has_decoding() or \
                self.engine.has_pending_harvest():
            self._with_retry(self.engine.step_block)

    def _harvest(self):
        now = time.perf_counter()
        for run in self.engine.drain_finished():
            req = run.request
            if run.failure is not None:
                self._fail(req.request_id, run.failure,
                           f"cancelled after {len(run.tokens)} tokens",
                           tokens=len(run.tokens))
                continue
            toks = np.asarray(run.tokens, np.int32)
            if len(toks) < req.max_new_tokens:
                # retired early at eos: pad to max_new (generate parity)
                toks = np.concatenate([toks, np.full(
                    (req.max_new_tokens - len(toks),),
                    req.eos_token_id, np.int32)])
            self.results[req.request_id] = np.concatenate(
                [np.asarray(req.prompt, np.int32).reshape(-1), toks])
            self.latencies[req.request_id] = now - req.t_submit
            self.ttft[req.request_id] = run.t_admit - req.t_submit

    def run_until_idle(self, max_ticks: Optional[int] = None
                       ) -> Dict[int, object]:
        """Drive the loop until the queue is empty and every slot is
        free; returns ``results`` (arrays for completed requests,
        ``RequestFailure`` for shed/expired/quarantined ones). One tick
        = expire deadlines, admit what the scheduler releases (requests
        the engine defers — paged block pool exhausted — re-queue),
        advance chunked prefills within the scheduler's prefill token
        budget, run one decode block, harvest. Per-tick wall times land
        in ``tick_seconds`` — the max is the decode-interference figure
        chunked prefill exists to bound.

        ``max_ticks``: stop after that many ticks even with work in
        flight — the kill point for snapshot/restore tests and a hang
        bound for chaos schedules. A tick that trips the
        ``server.tick`` fault site is counted and skipped (requests
        stay queued; nothing is lost)."""
        t0 = time.perf_counter()
        ticks = 0
        while self.scheduler.pending() or self.engine.has_live():
            if max_ticks is not None and ticks >= max_ticks:
                break
            if self._res.breaker_open:   # incl. restored-open circuits
                self._quarantine_all("circuit_open")
                self._harvest()
                break
            t_tick = time.perf_counter()
            try:
                faults.fault_point("server.tick")
                self._tick()
            except faults.InjectedFault:
                self._res.tick_faults += 1
            self._clock += 1
            ticks += 1
            self._harvest()
            self.tick_seconds.append(time.perf_counter() - t_tick)
            if self._res.breaker_open:
                self._quarantine_all("circuit_open")
                self._harvest()
                break
        self._wall += time.perf_counter() - t0
        return self.results

    def stats(self) -> dict:
        lat = list(self.latencies.values())
        ttft = list(self.ttft.values())
        ticks = self.tick_seconds
        eng = self.engine
        completed = sum(1 for v in self.results.values()
                        if not isinstance(v, RequestFailure))
        out = {
            "requests_completed": completed,
            "tokens_emitted": eng.tokens_emitted,
            "decode_steps": eng.steps,
            "slot_occupancy": round(eng.occupancy(), 4),
            "wall_s": round(self._wall, 4),
            "tokens_per_sec": round(eng.tokens_emitted / self._wall, 1)
            if self._wall else 0.0,
            "decode_compile_count": eng.decode_compile_count(),
            "latency_avg_s": round(float(np.mean(lat)), 4) if lat else 0.0,
            "latency_p95_s": round(float(np.percentile(lat, 95)), 4)
            if lat else 0.0,
            "ttft_p50_s": round(float(np.percentile(ttft, 50)), 4)
            if ttft else 0.0,
            "ttft_p95_s": round(float(np.percentile(ttft, 95)), 4)
            if ttft else 0.0,
            "max_tick_s": round(max(ticks), 4) if ticks else 0.0,
            "p95_tick_s": round(float(np.percentile(ticks, 95)), 4)
            if ticks else 0.0,
        }
        out.update(self._res.counters())
        hit_rate = getattr(eng, "prefix_cache_hit_rate", None)
        if hit_rate is not None:               # paged engine extras
            out["prefix_cache_hit_rate"] = round(hit_rate(), 4)
            out["kv_bytes_per_slot"] = eng.backend.kv_bytes_per_slot()
        return out

    # -- crash-safe snapshot / restore -------------------------------------
    def snapshot(self, path: str):
        """Write server + engine state as ONE atomic npz: queue,
        results, clocks, resilience counters, and the engine's full
        device/host state. Taken between ticks (the engine enforces the
        no-pending-harvest boundary)."""
        meta, arrays = self.engine.snapshot_state()
        res_meta = {}
        for rid, v in self.results.items():
            if isinstance(v, RequestFailure):
                res_meta[str(rid)] = {
                    "kind": "failure", "reason": v.reason,
                    "message": v.message,
                    "tokens_emitted": v.tokens_emitted}
            else:
                res_meta[str(rid)] = {"kind": "ok"}
                arrays[f"res_{rid}"] = np.asarray(v, np.int32)
        # deliberate direct read: a custom scheduler without a _queue
        # list must FAIL the snapshot loudly, not silently serialize an
        # empty queue and lose every not-yet-admitted request
        queue = list(self.scheduler._queue)
        qmeta = []
        for i, r in enumerate(queue):
            arrays[f"q{i}_prompt"] = np.asarray(r.prompt,
                                                np.int32).reshape(-1)
            qmeta.append(request_to_meta(r))
        smeta = {
            "next_id": self._next_id, "clock": self._clock,
            "wall": self._wall,
            "latencies": {str(k): v for k, v in self.latencies.items()},
            "ttft": {str(k): v for k, v in self.ttft.items()},
            "results": res_meta, "queue": qmeta,
            "counters": self._res.counters(),
        }
        save_snapshot(path, {"engine": meta, "server": smeta}, arrays)

    @classmethod
    def restore(cls, path: str, engine: ContinuousBatchingEngine,
                scheduler: Optional[Scheduler] = None,
                resilience: Optional[ResilienceConfig] = None
                ) -> "Server":
        """Rebuild a server from a snapshot into a freshly constructed
        engine of the same configuration (fresh process simulation:
        programs recompile, state restores — then ``run_until_idle()``
        finishes every stream bit-identical to the uninterrupted run)."""
        meta, arrays = load_snapshot(path)
        engine.restore_state(meta["engine"], arrays)
        srv = cls(engine, scheduler, resilience)
        sm = meta["server"]
        srv._next_id = sm["next_id"]
        srv._clock = sm["clock"]
        srv._wall = sm["wall"]
        srv.latencies = {int(k): v for k, v in sm["latencies"].items()}
        srv.ttft = {int(k): v for k, v in sm["ttft"].items()}
        for rid_s, info in sm["results"].items():
            rid = int(rid_s)
            if info["kind"] == "ok":
                srv.results[rid] = np.asarray(arrays[f"res_{rid}"],
                                              np.int32)
            else:
                srv.results[rid] = RequestFailure(
                    request_id=rid, reason=info["reason"],
                    message=info["message"],
                    tokens_emitted=info["tokens_emitted"])
        # the full resilience runtime state (failure counts, retry
        # budget, breaker) survives the restore — an open circuit must
        # stay open in the resumed process
        srv._res.restore_counters(sm["counters"])
        # re-submit in saved order: insort is stable, so same-tick FIFO
        # order survives the round trip
        for i, rm in enumerate(sm["queue"]):
            srv.scheduler.submit(
                request_from_meta(rm, arrays[f"q{i}_prompt"]))
        return srv
