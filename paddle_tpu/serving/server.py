"""Serving loop: Scheduler + ContinuousBatchingEngine + metrics.

One iteration of the loop = one tick of the engine-block clock: admit
whatever the scheduler releases into free slots, run one compiled
decode block over the pool, harvest retired requests. Per-request
latency and engine-level tokens/s / slot-occupancy counters are emitted
as profiler RecordEvent spans (chrome-trace) and summarized by
``stats()`` — the serving analogue of the training loop's MFU line."""
from __future__ import annotations

import time
from typing import Dict, Optional

import numpy as np

from .engine import ContinuousBatchingEngine
from .scheduler import Request, Scheduler

__all__ = ["Server"]


class Server:
    """Continuous-batching server over an engine. ``submit()`` requests
    (optionally with future ``arrival_step`` ticks), then
    ``run_until_idle()`` — results match per-request ``generate()``:
    prompt + generated ids, rows that hit eos padded with eos to
    ``max_new_tokens`` (greedy traffic is bit-identical)."""

    def __init__(self, engine: ContinuousBatchingEngine,
                 scheduler: Optional[Scheduler] = None):
        self.engine = engine
        self.scheduler = scheduler or Scheduler()
        self.results: Dict[int, np.ndarray] = {}
        self.latencies: Dict[int, float] = {}
        self.ttft: Dict[int, float] = {}       # submit -> first token
        self.tick_seconds: list = []           # per-tick wall times
        self._next_id = 0
        self._clock = 0
        self._wall = 0.0

    def submit(self, prompt, max_new_tokens: int = 20,
               temperature: float = 0.0, top_k: int = 0,
               top_p: float = 1.0, eos_token_id: Optional[int] = None,
               seed: int = 0, arrival_step: int = 0) -> int:
        """Queue one request; returns its id (key into ``results``).
        Capacity is validated HERE — a request that can never fit a
        slot is rejected at the door, not mid-stream at admission."""
        prompt = np.asarray(prompt, np.int32)
        self.engine.validate_request(int(prompt.size), max_new_tokens)
        rid = self._next_id
        self._next_id += 1
        self.scheduler.submit(Request(
            request_id=rid, prompt=np.asarray(prompt, np.int32),
            max_new_tokens=max_new_tokens, temperature=temperature,
            top_k=top_k, top_p=top_p, eos_token_id=eos_token_id,
            seed=seed, arrival_step=arrival_step,
            t_submit=time.perf_counter()))
        return rid

    def _harvest(self):
        now = time.perf_counter()
        for run in self.engine.drain_finished():
            req = run.request
            toks = np.asarray(run.tokens, np.int32)
            if len(toks) < req.max_new_tokens:
                # retired early at eos: pad to max_new (generate parity)
                toks = np.concatenate([toks, np.full(
                    (req.max_new_tokens - len(toks),),
                    req.eos_token_id, np.int32)])
            self.results[req.request_id] = np.concatenate(
                [np.asarray(req.prompt, np.int32).reshape(-1), toks])
            self.latencies[req.request_id] = now - req.t_submit
            self.ttft[req.request_id] = run.t_admit - req.t_submit

    def run_until_idle(self) -> Dict[int, np.ndarray]:
        """Drive the loop until the queue is empty and every slot is
        free; returns ``results``. One tick = admit what the scheduler
        releases (requests the engine defers — paged block pool
        exhausted — re-queue), advance chunked prefills within the
        scheduler's prefill token budget, run one decode block, harvest.
        Per-tick wall times land in ``tick_seconds`` — the max is the
        decode-interference figure chunked prefill exists to bound."""
        t0 = time.perf_counter()
        while self.scheduler.pending() or self.engine.has_live():
            t_tick = time.perf_counter()
            admitted = self.scheduler.pop_ready(
                self._clock, self.engine.free_slot_count(),
                engine_idle=not self.engine.has_live())
            for i, req in enumerate(admitted):
                if not self.engine.try_admit(req):
                    # re-queue in reverse: requeue() front-inserts per
                    # arrival tick, so forward order would flip
                    # same-tick FIFO and let peers overtake the oldest
                    for r in reversed(admitted[i:]):
                        self.scheduler.requeue(r)
                    break
            prefill_tick = getattr(self.engine, "prefill_tick", None)
            if prefill_tick is not None:
                prefill_tick(self.scheduler.prefill_token_budget)
            if self.engine.has_decoding():
                self.engine.step_block()
            self._clock += 1
            self._harvest()
            self.tick_seconds.append(time.perf_counter() - t_tick)
        self._wall += time.perf_counter() - t0
        return self.results

    def stats(self) -> dict:
        lat = list(self.latencies.values())
        ttft = list(self.ttft.values())
        ticks = self.tick_seconds
        eng = self.engine
        out = {
            "requests_completed": len(self.results),
            "tokens_emitted": eng.tokens_emitted,
            "decode_steps": eng.steps,
            "slot_occupancy": round(eng.occupancy(), 4),
            "wall_s": round(self._wall, 4),
            "tokens_per_sec": round(eng.tokens_emitted / self._wall, 1)
            if self._wall else 0.0,
            "decode_compile_count": eng.decode_compile_count(),
            "latency_avg_s": round(float(np.mean(lat)), 4) if lat else 0.0,
            "latency_p95_s": round(float(np.percentile(lat, 95)), 4)
            if lat else 0.0,
            "ttft_p50_s": round(float(np.percentile(ttft, 50)), 4)
            if ttft else 0.0,
            "ttft_p95_s": round(float(np.percentile(ttft, 95)), 4)
            if ttft else 0.0,
            "max_tick_s": round(max(ticks), 4) if ticks else 0.0,
            "p95_tick_s": round(float(np.percentile(ticks, 95)), 4)
            if ticks else 0.0,
        }
        hit_rate = getattr(eng, "prefix_cache_hit_rate", None)
        if hit_rate is not None:               # paged engine extras
            out["prefix_cache_hit_rate"] = round(hit_rate(), 4)
            out["kv_bytes_per_slot"] = eng.backend.kv_bytes_per_slot()
        return out
