"""Serving loop: Scheduler + ContinuousBatchingEngine + metrics +
resilience policies.

One iteration of the loop = one tick of the engine-block clock: expire
deadlined requests, admit whatever the scheduler releases into free
slots, advance chunked prefills, run one compiled decode block, harvest
retired requests. Per-request latency and engine-level tokens/s /
slot-occupancy counters are emitted as profiler RecordEvent spans
(chrome-trace) and summarized by ``stats()`` — the serving analogue of
the training loop's MFU line.

Failure paths are first-class (serving/resilience.py): every submitted
request ends either in a completed output array or an explicit
``RequestFailure`` in ``results`` — deadlines cancel (slot freed, paged
blocks released), bounded queues shed, transient step failures retry
with seeded exponential backoff, a circuit breaker drains after N
consecutive failures, and a NaN-poisoned slot is quarantined alone.
``snapshot()``/``restore()`` make the whole server crash-safe: a
process killed between ticks resumes from the snapshot and finishes
every stream bit-identical to an uninterrupted run."""
from __future__ import annotations

import time
from typing import Dict, Optional

import numpy as np

from ..observability import (FlightRecorder, ObservabilityConfig,
                             RequestTracer)
from ..observability import metrics as _om
from ..observability.tracing import export_chrome_trace, now_us
from ..utils import faults
from .engine import ContinuousBatchingEngine
from .resilience import (RequestFailure, ResilienceConfig,
                         ResilienceState, load_snapshot,
                         request_from_meta, request_to_meta,
                         save_snapshot)
from .scheduler import Request, Scheduler

__all__ = ["Server"]

# metric families (registered at import; zero-cost until
# metrics.enable()/PT_METRICS arms the registry)
_M_TICKS = _om.counter("pt_server_ticks_total", "server ticks executed")
_M_TICK_S = _om.histogram("pt_server_tick_seconds",
                          "wall seconds per server tick")
_M_QUEUE = _om.gauge("pt_server_queue_depth",
                     "requests waiting in the scheduler queue")
_M_SUBMIT = _om.counter("pt_server_requests_submitted_total",
                        "requests submitted (accepted or shed)")
_M_DONE = _om.counter("pt_server_requests_completed_total",
                      "requests that completed with output tokens")
_M_FAILED = _om.counter("pt_server_requests_failed_total",
                        "requests ending in a RequestFailure, by reason",
                        labels=("reason",))
_M_SHED = _om.counter("pt_server_shed_total",
                      "submits rejected at the queue-depth cap")
_M_DEADLINE = _om.counter("pt_server_deadline_cancels_total",
                          "requests cancelled past a deadline/queue wait")
_M_DEFER = _om.counter("pt_server_admit_deferred_total",
                       "admissions re-queued (paged block pool exhausted)")
_M_RETRY = _om.counter("pt_server_retries_total",
                       "transient-failure retry attempts")
_M_STEPFAIL = _om.counter("pt_server_step_failures_total",
                          "transient step/prefill/harvest failures")
_M_BREAKER = _om.gauge("pt_server_breaker_open",
                       "1 while the circuit breaker is open")
_M_LAT = _om.histogram("pt_server_request_latency_seconds",
                       "submit -> harvest wall time per completed request")
_M_TTFT = _om.histogram("pt_server_ttft_seconds",
                        "submit -> first token per completed request")
_M_OCC = _om.gauge("pt_server_slot_occupancy",
                   "fraction of decode slot-steps that emitted a token")


class Server:
    """Continuous-batching server over an engine. ``submit()`` requests
    (optionally with future ``arrival_step`` ticks and per-request
    deadlines), then ``run_until_idle()`` — results match per-request
    ``generate()``: prompt + generated ids, rows that hit eos padded
    with eos to ``max_new_tokens`` (greedy traffic is bit-identical).
    Failed requests surface as :class:`RequestFailure` values in
    ``results`` instead of hanging the loop."""

    def __init__(self, engine: ContinuousBatchingEngine,
                 scheduler: Optional[Scheduler] = None,
                 resilience: Optional[ResilienceConfig] = None,
                 observability: Optional[ObservabilityConfig] = None):
        self.engine = engine
        self.scheduler = scheduler or Scheduler()
        self.resilience = resilience or ResilienceConfig()
        self._res = ResilienceState(self.resilience)
        engine.nan_sentinel = self.resilience.nan_sentinel
        # the breaker gauge tracks THIS server from birth — without the
        # reset, a fresh healthy server built after a drained one would
        # inherit the process-global 1 forever
        _M_BREAKER.set(1 if self._res.breaker_open else 0)
        obs = observability or ObservabilityConfig()
        self.observability = obs
        self.tracer = RequestTracer(enabled=obs.trace_requests)
        self.flight = FlightRecorder(capacity=obs.flight_size,
                                     dump_dir=obs.flight_dump_dir)
        # the engine only carries a tracer when tracing is armed, so
        # its hot paths pay one `is None` check when it isn't
        engine.tracer = self.tracer if self.tracer.enabled else None
        self.results: Dict[int, object] = {}
        self.latencies: Dict[int, float] = {}
        self.ttft: Dict[int, float] = {}       # submit -> first token
        self.tick_seconds: list = []           # per-tick wall times
        self._next_id = 0
        self._clock = 0
        self._wall = 0.0

    def submit(self, prompt, max_new_tokens: int = 20,
               temperature: float = 0.0, top_k: int = 0,
               top_p: float = 1.0, eos_token_id: Optional[int] = None,
               seed: int = 0, arrival_step: int = 0,
               deadline_ticks: Optional[int] = None,
               deadline_s: Optional[float] = None) -> int:
        """Queue one request; returns its id (key into ``results``).
        Capacity is validated HERE — a request that can never fit a
        slot (or, paged, the block pool) is rejected at the door, not
        re-queued forever mid-stream. With ``max_queue_depth`` set, a
        submit beyond the cap is load-shed: the id comes back with a
        ``RequestFailure(reason="shed")`` already recorded."""
        prompt = np.asarray(prompt, np.int32)
        self.engine.validate_request(int(prompt.size), max_new_tokens)
        rid = self._next_id
        self._next_id += 1
        _M_SUBMIT.inc()
        self.tracer.start(rid)
        depth = self.resilience.max_queue_depth
        if depth is not None and self.scheduler.pending() >= depth:
            self._res.shed_requests += 1
            _M_SHED.inc()
            self.flight.record("shed", rid=rid, depth=depth)
            self._fail(rid, "shed",
                       f"queue depth at cap ({depth}); retry later")
            return rid
        self.scheduler.submit(Request(
            request_id=rid, prompt=np.asarray(prompt, np.int32),
            max_new_tokens=max_new_tokens, temperature=temperature,
            top_k=top_k, top_p=top_p, eos_token_id=eos_token_id,
            seed=seed, arrival_step=arrival_step,
            t_submit=time.perf_counter(),
            deadline_ticks=deadline_ticks, deadline_s=deadline_s))
        _M_QUEUE.set(self.scheduler.pending())
        return rid

    # -- failure plumbing --------------------------------------------------
    def _fail(self, rid: int, reason: str, message: str = "",
              tokens: int = 0):
        self.results[rid] = RequestFailure(
            request_id=rid, reason=reason, message=message,
            tokens_emitted=tokens)
        self._res.count_failure(reason)
        _M_FAILED.inc(reason=reason)
        if reason == "timeout":
            _M_DEADLINE.inc()
        self.flight.record("request_failed", rid=rid, reason=reason,
                           tokens=tokens)
        self.tracer.terminal(rid, reason, tokens=tokens)

    def _deadline_hit(self, req: Request, now: float) -> bool:
        cfg = self.resilience
        dt = req.deadline_ticks if req.deadline_ticks is not None \
            else cfg.deadline_ticks
        if dt is not None and self._clock - req.arrival_step > dt:
            return True
        ds = req.deadline_s if req.deadline_s is not None \
            else cfg.deadline_s
        return ds is not None and now - req.t_submit > ds

    def _expire(self):
        """Cancel queued and in-flight requests past their deadline
        (and queued ones past the max queue wait). In-flight
        cancellation goes through ``engine.cancel_slot`` — the slot is
        killed in-graph and paged blocks release at correct refcounts;
        the failure surfaces through the normal harvest."""
        now = time.perf_counter()
        mw = self.resilience.max_queue_wait_ticks

        def queued_out(r):
            if mw is not None and self._clock - r.arrival_step > mw:
                return True
            return self._deadline_hit(r, now)

        for r in self.scheduler.drop_where(queued_out):
            self._fail(r.request_id, "timeout",
                       f"expired in queue at tick {self._clock}")
        for slot, run in self.engine.live_runs():
            if self._deadline_hit(run.request, now):
                self.engine.cancel_slot(slot, "timeout")

    def _with_retry(self, fn) -> bool:
        """Run ``fn`` with the transient-failure policy: seeded
        exponential backoff between attempts; every failed attempt
        counts toward the consecutive-failure budget that opens the
        circuit breaker. Returns False if ``fn`` never succeeded (the
        tick just moves on — or the breaker drains everything)."""
        res, cfg = self._res, self.resilience
        for attempt in range(cfg.retry_attempts + 1):
            if res.breaker_open:
                return False
            try:
                fn()
                res.consecutive_failures = 0
                return True
            except res.transient as e:
                res.step_failures += 1
                res.consecutive_failures += 1
                res.last_error = f"{type(e).__name__}: {e}"
                _M_STEPFAIL.inc()
                self.flight.record(
                    "step_failure", error=res.last_error[:200],
                    consecutive=res.consecutive_failures,
                    clock=self._clock)
                if res.consecutive_failures >= cfg.breaker_threshold:
                    res.breaker_open = True
                    _M_BREAKER.set(1)
                    self.flight.record("breaker_open", clock=self._clock,
                                       after=res.consecutive_failures)
                    self.tracer.server_instant(
                        "breaker_open", clock=self._clock)
                    return False
                if attempt < cfg.retry_attempts:
                    res.retries += 1
                    _M_RETRY.inc()
                    backoff = res.backoff_s(attempt)
                    self.flight.record("retry", attempt=attempt,
                                       backoff_s=round(backoff, 6),
                                       clock=self._clock)
                    self.tracer.server_instant("retry", attempt=attempt,
                                               clock=self._clock)
                    time.sleep(backoff)
        return False

    def _quarantine_all(self, reason: str):
        """Circuit-breaker drain: cancel every in-flight request and
        fail everything still queued — the server ends in a clean,
        fully-accounted state instead of wedging on a dead device."""
        for slot, _ in self.engine.live_runs():
            self.engine.cancel_slot(slot, reason)
        for r in self.scheduler.drop_where(lambda r: True):
            self._fail(r.request_id, reason,
                       "circuit breaker open: queue drained")

    # -- the tick ----------------------------------------------------------
    def _tick(self):
        self._expire()
        admitted = self.scheduler.pop_ready(
            self._clock, self.engine.free_slot_count(),
            engine_idle=not self.engine.has_live())
        for i, req in enumerate(admitted):
            if not self.engine.try_admit(req):
                # re-queue in reverse: requeue() front-inserts per
                # arrival tick, so forward order would flip
                # same-tick FIFO and let peers overtake the oldest
                _M_DEFER.inc(len(admitted) - i)
                self.flight.record(
                    "block_pool_defer", rid=req.request_id,
                    clock=self._clock,
                    deferred=len(admitted) - i)
                for r in reversed(admitted[i:]):
                    self.scheduler.requeue(r)
                break
        prefill_tick = getattr(self.engine, "prefill_tick", None)
        if prefill_tick is not None:
            # chunks dispatched before a mid-loop fault keep their
            # cursors, so a retry must only get the UNSPENT part of the
            # tick's budget — otherwise each retry re-arms a full
            # budget and one tick can blow the decode-interference
            # bound chunked prefill exists to enforce
            budget = self.scheduler.prefill_token_budget
            spent = [0]

            def _prefill():
                b = None if budget is None else budget - spent[0]
                if b is not None and b <= 0 and spent[0] > 0:
                    return           # budget already consumed this tick
                # measure spend from the engine counter, not the return
                # value — a fault raises out of prefill_tick AFTER some
                # chunks already dispatched, and those must still count
                before = self.engine.prefilled_tokens
                try:
                    prefill_tick(b)
                finally:
                    spent[0] += self.engine.prefilled_tokens - before

            self._with_retry(_prefill)
        if self.engine.has_decoding() or \
                self.engine.has_pending_harvest():
            self._with_retry(self.engine.step_block)

    def _harvest(self):
        now = time.perf_counter()
        for run in self.engine.drain_finished():
            req = run.request
            if run.failure is not None:
                self._fail(req.request_id, run.failure,
                           f"cancelled after {len(run.tokens)} tokens",
                           tokens=len(run.tokens))
                continue
            toks = np.asarray(run.tokens, np.int32)
            if len(toks) < req.max_new_tokens:
                # retired early at eos: pad to max_new (generate parity)
                toks = np.concatenate([toks, np.full(
                    (req.max_new_tokens - len(toks),),
                    req.eos_token_id, np.int32)])
            self.results[req.request_id] = np.concatenate(
                [np.asarray(req.prompt, np.int32).reshape(-1), toks])
            self.latencies[req.request_id] = now - req.t_submit
            self.ttft[req.request_id] = run.t_admit - req.t_submit
            _M_DONE.inc()
            _M_LAT.observe(self.latencies[req.request_id])
            _M_TTFT.observe(self.ttft[req.request_id])
            self.tracer.instant(req.request_id, "harvest",
                                tokens=len(run.tokens))
            self.tracer.terminal(req.request_id, "completed",
                                 tokens=len(run.tokens))

    def run_until_idle(self, max_ticks: Optional[int] = None
                       ) -> Dict[int, object]:
        """Drive the loop until the queue is empty and every slot is
        free; returns ``results`` (arrays for completed requests,
        ``RequestFailure`` for shed/expired/quarantined ones). One tick
        = expire deadlines, admit what the scheduler releases (requests
        the engine defers — paged block pool exhausted — re-queue),
        advance chunked prefills within the scheduler's prefill token
        budget, run one decode block, harvest. Per-tick wall times land
        in ``tick_seconds`` — the max is the decode-interference figure
        chunked prefill exists to bound.

        ``max_ticks``: stop after that many ticks even with work in
        flight — the kill point for snapshot/restore tests and a hang
        bound for chaos schedules. A tick that trips the
        ``server.tick`` fault site is counted and skipped (requests
        stay queued; nothing is lost)."""
        t0 = time.perf_counter()
        ticks = 0
        while self.scheduler.pending() or self.engine.has_live():
            if max_ticks is not None and ticks >= max_ticks:
                break
            if self._res.breaker_open:   # incl. restored-open circuits
                self._circuit_open_drain()
                break
            t_tick = time.perf_counter()
            t_tick_us = now_us() if self.tracer.enabled else 0.0
            try:
                faults.fault_point("server.tick")
                self._tick()
            except faults.InjectedFault:
                self._res.tick_faults += 1
                self.flight.record("tick_fault", clock=self._clock)
            self._clock += 1
            ticks += 1
            self._harvest()
            tick_s = time.perf_counter() - t_tick
            self.tick_seconds.append(tick_s)
            self.tracer.server_span_at("tick", t_tick_us,
                                       clock=self._clock - 1)
            _M_TICKS.inc()
            _M_TICK_S.observe(tick_s)
            _M_QUEUE.set(self.scheduler.pending())
            _M_OCC.set(self.engine.occupancy())
            self.flight.record(
                "tick", clock=self._clock - 1,
                queue=self.scheduler.pending(),
                live=len(self.engine.live_runs()),
                tokens=self.engine.tokens_emitted,
                tick_ms=round(tick_s * 1000, 3))
            if self._res.breaker_open:
                self._circuit_open_drain()
                break
        self._wall += time.perf_counter() - t0
        return self.results

    def _circuit_open_drain(self):
        """Breaker-open endgame: auto-dump the flight recorder (the
        black box exists for exactly this moment), then drain and
        account every in-flight/queued request as ``circuit_open``."""
        self.flight.record("circuit_open_drain", clock=self._clock,
                           queue=self.scheduler.pending(),
                           live=len(self.engine.live_runs()))
        _M_BREAKER.set(1)
        try:
            self.flight.dump(reason="circuit_open")
        except OSError as e:             # diagnostics must never block
            self.flight.record("flight_dump_failed",  # the drain
                               error=f"{type(e).__name__}: {e}"[:200])
        self._quarantine_all("circuit_open")
        self._harvest()

    def stats(self) -> dict:
        lat = list(self.latencies.values())
        ttft = list(self.ttft.values())
        ticks = self.tick_seconds
        eng = self.engine
        completed = sum(1 for v in self.results.values()
                        if not isinstance(v, RequestFailure))
        out = {
            "requests_completed": completed,
            "tokens_emitted": eng.tokens_emitted,
            "decode_steps": eng.steps,
            "slot_occupancy": round(eng.occupancy(), 4),
            "wall_s": round(self._wall, 4),
            "tokens_per_sec": round(eng.tokens_emitted / self._wall, 1)
            if self._wall else 0.0,
            "decode_compile_count": eng.decode_compile_count(),
            "latency_avg_s": round(float(np.mean(lat)), 4) if lat else 0.0,
            "latency_p95_s": round(float(np.percentile(lat, 95)), 4)
            if lat else 0.0,
            "ttft_p50_s": round(float(np.percentile(ttft, 50)), 4)
            if ttft else 0.0,
            "ttft_p95_s": round(float(np.percentile(ttft, 95)), 4)
            if ttft else 0.0,
            "max_tick_s": round(max(ticks), 4) if ticks else 0.0,
            "p95_tick_s": round(float(np.percentile(ticks, 95)), 4)
            if ticks else 0.0,
        }
        out.update(self._res.counters())
        if eng.tp_degree() > 1:                # tensor-parallel extras
            out["tp_degree"] = eng.tp_degree()
        acc = getattr(eng, "acceptance_rate", None)
        if acc is not None:                    # speculative extras: a
            # tick advances 0..k+1 tokens per slot, so per-tick token
            # accounting reads these, not decode_steps
            out["spec_k"] = eng.spec_k
            out["spec_verify_steps"] = eng.verify_steps
            out["spec_acceptance_rate"] = round(acc(), 4)
            out["spec_mean_accepted_per_step"] = round(
                eng.mean_accepted_per_step(), 4)
        hit_rate = getattr(eng, "prefix_cache_hit_rate", None)
        if hit_rate is not None:               # paged engine extras
            out["prefix_cache_hit_rate"] = round(hit_rate(), 4)
            out["kv_bytes_per_slot"] = eng.backend.kv_bytes_per_slot()
        return out

    def export_trace(self, path: str, profiler=None) -> str:
        """Write the served stream as ONE Perfetto-loadable chrome-trace
        JSON: this server's request rows + tick markers, merged (on the
        same perf_counter clock) with the profiler's ``RecordEvent``
        host-span ring when a :class:`~paddle_tpu.profiler.Profiler` is
        passed (drained destructively, like its own export)."""
        return export_chrome_trace(path, tracer=self.tracer,
                                   profiler=profiler)

    # -- crash-safe snapshot / restore -------------------------------------
    def snapshot(self, path: str):
        """Write server + engine state as ONE atomic npz: queue,
        results, clocks, resilience counters, and the engine's full
        device/host state. Taken between ticks (the engine enforces the
        no-pending-harvest boundary)."""
        meta, arrays = self.engine.snapshot_state()
        res_meta = {}
        for rid, v in self.results.items():
            if isinstance(v, RequestFailure):
                res_meta[str(rid)] = {
                    "kind": "failure", "reason": v.reason,
                    "message": v.message,
                    "tokens_emitted": v.tokens_emitted}
            else:
                res_meta[str(rid)] = {"kind": "ok"}
                arrays[f"res_{rid}"] = np.asarray(v, np.int32)
        # deliberate direct read: a custom scheduler without a _queue
        # list must FAIL the snapshot loudly, not silently serialize an
        # empty queue and lose every not-yet-admitted request
        queue = list(self.scheduler._queue)
        qmeta = []
        for i, r in enumerate(queue):
            arrays[f"q{i}_prompt"] = np.asarray(r.prompt,
                                                np.int32).reshape(-1)
            qmeta.append(request_to_meta(r))
        # the snapshot event goes into the ring BEFORE the ring is
        # captured, so the restored server's history and the sidecar
        # agree on it (and on every seq number)
        self.flight.record("snapshot", path=path, clock=self._clock)
        smeta = {
            "next_id": self._next_id, "clock": self._clock,
            "wall": self._wall,
            "latencies": {str(k): v for k, v in self.latencies.items()},
            "ttft": {str(k): v for k, v in self.ttft.items()},
            "results": res_meta, "queue": qmeta,
            "counters": self._res.counters(),
            # the flight ring rides the snapshot (restored server keeps
            # its pre-crash event history) AND dumps beside it for
            # humans reading the crash site without np.load
            "flight": self.flight.to_meta(),
        }
        self.flight.dump(path + ".flight.json", reason="snapshot")
        save_snapshot(path, {"engine": meta, "server": smeta}, arrays)

    @classmethod
    def restore(cls, path: str, engine: ContinuousBatchingEngine,
                scheduler: Optional[Scheduler] = None,
                resilience: Optional[ResilienceConfig] = None,
                observability: Optional[ObservabilityConfig] = None
                ) -> "Server":
        """Rebuild a server from a snapshot into a freshly constructed
        engine of the same configuration (fresh process simulation:
        programs recompile, state restores — then ``run_until_idle()``
        finishes every stream bit-identical to the uninterrupted run).
        Pass the original ``observability`` config to keep tracing
        armed and the flight ring at its configured capacity — the
        saved ring rehydrates into THIS server's ring, so restoring
        with a smaller capacity keeps only the newest events that fit."""
        meta, arrays = load_snapshot(path)
        engine.restore_state(meta["engine"], arrays)
        srv = cls(engine, scheduler, resilience, observability)
        sm = meta["server"]
        srv._next_id = sm["next_id"]
        srv._clock = sm["clock"]
        srv._wall = sm["wall"]
        srv.latencies = {int(k): v for k, v in sm["latencies"].items()}
        srv.ttft = {int(k): v for k, v in sm["ttft"].items()}
        for rid_s, info in sm["results"].items():
            rid = int(rid_s)
            if info["kind"] == "ok":
                srv.results[rid] = np.asarray(arrays[f"res_{rid}"],
                                              np.int32)
            else:
                srv.results[rid] = RequestFailure(
                    request_id=rid, reason=info["reason"],
                    message=info["message"],
                    tokens_emitted=info["tokens_emitted"])
        # the full resilience runtime state (failure counts, retry
        # budget, breaker) survives the restore — an open circuit must
        # stay open in the resumed process
        srv._res.restore_counters(sm["counters"])
        _M_BREAKER.set(1 if srv._res.breaker_open else 0)
        if "flight" in sm:       # pre-observability snapshots lack it
            srv.flight.restore_meta(sm["flight"])
        srv.flight.record("restored", path=path, clock=srv._clock)
        # re-submit in saved order: insort is stable, so same-tick FIFO
        # order survives the round trip. Carried-over requests also
        # (re)enter the tracer here — scheduler.submit bypasses
        # Server.submit, so without this every resumed request would
        # silently miss its trace (and its exactly-one terminal span)
        for i, rm in enumerate(sm["queue"]):
            req = request_from_meta(rm, arrays[f"q{i}_prompt"])
            srv.scheduler.submit(req)
            srv.tracer.start(req.request_id)
        for slot, run in engine.live_runs():
            rid = run.request.request_id
            srv.tracer.start(rid)
            srv.tracer.span_end(rid, "queue_wait", restored=True)
            # mid-prefill paged slots re-open this span at
            # _finish_prefill; for decoding slots it is simply resumed
            srv.tracer.span_begin(rid, "decode", slot=slot,
                                  restored=True)
        return srv
