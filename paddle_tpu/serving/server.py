"""Serving loop: Scheduler + ContinuousBatchingEngine + metrics +
resilience policies.

One iteration of the loop = one tick of the engine-block clock: expire
deadlined requests, admit whatever the scheduler releases into free
slots, advance chunked prefills, run one compiled decode block, harvest
retired requests. Per-request latency and engine-level tokens/s /
slot-occupancy counters are emitted as profiler RecordEvent spans
(chrome-trace) and summarized by ``stats()`` — the serving analogue of
the training loop's MFU line.

Failure paths are first-class (serving/resilience.py): every submitted
request ends either in a completed output array or an explicit
``RequestFailure`` in ``results`` — deadlines cancel (slot freed, paged
blocks released), bounded queues shed, transient step failures retry
with seeded exponential backoff, a circuit breaker drains after N
consecutive failures, and a NaN-poisoned slot is quarantined alone.
``snapshot()``/``restore()`` make the whole server crash-safe: a
process killed between ticks resumes from the snapshot and finishes
every stream bit-identical to an uninterrupted run."""
from __future__ import annotations

import time
from typing import Dict, Optional

import numpy as np

from ..observability import (FlightRecorder, ObservabilityConfig,
                             RequestTracer)
from ..observability import metrics as _om
from ..observability.tracing import export_chrome_trace, now_us
from ..utils import faults
from .engine import ContinuousBatchingEngine
from .resilience import (RequestFailure, ResilienceConfig,
                         ResilienceState, load_snapshot,
                         request_from_meta, request_to_meta,
                         save_snapshot)
from .scheduler import Request, Scheduler

__all__ = ["Server"]

# metric families (registered at import; zero-cost until
# metrics.enable()/PT_METRICS arms the registry)
_M_TICKS = _om.counter("pt_server_ticks_total", "server ticks executed")
_M_TICK_S = _om.histogram("pt_server_tick_seconds",
                          "wall seconds per server tick")
_M_QUEUE = _om.gauge("pt_server_queue_depth",
                     "requests waiting in the scheduler queue")
_M_SUBMIT = _om.counter("pt_server_requests_submitted_total",
                        "requests submitted (accepted or shed)")
_M_DONE = _om.counter("pt_server_requests_completed_total",
                      "requests that completed with output tokens")
_M_FAILED = _om.counter("pt_server_requests_failed_total",
                        "requests ending in a RequestFailure, by reason",
                        labels=("reason",))
_M_SHED = _om.counter("pt_server_shed_total",
                      "submits rejected at the queue-depth cap")
_M_DEADLINE = _om.counter("pt_server_deadline_cancels_total",
                          "requests cancelled past a deadline/queue wait")
_M_DEFER = _om.counter("pt_server_admit_deferred_total",
                       "admissions re-queued (paged block pool exhausted)")
_M_RETRY = _om.counter("pt_server_retries_total",
                       "transient-failure retry attempts")
_M_STEPFAIL = _om.counter("pt_server_step_failures_total",
                          "transient step/prefill/harvest failures")
_M_BREAKER = _om.gauge("pt_server_breaker_open",
                       "1 while the circuit breaker is open")
_M_LAT = _om.histogram("pt_server_request_latency_seconds",
                       "submit -> harvest wall time per completed request")
_M_TTFT = _om.histogram("pt_server_ttft_seconds",
                        "submit -> first token per completed request")
_M_OCC = _om.gauge("pt_server_slot_occupancy",
                   "fraction of decode slot-steps that emitted a token")
# multi-tenant front-door families (serving/frontend.py policy, but the
# Server owns the lifecycle accounting; registered here at import so
# the catalog stays complete at zero)
_M_T_DONE = _om.counter("pt_server_tenant_completed_total",
                        "completed requests by tenant",
                        labels=("tenant",))
_M_T_FAILED = _om.counter("pt_server_tenant_failed_total",
                          "failed requests by tenant",
                          labels=("tenant",))
_M_T_SHED = _om.counter("pt_server_tenant_shed_total",
                        "submits shed at the global depth cap or the "
                        "tenant queue quota, by tenant",
                        labels=("tenant",))
_M_T_PREEMPT = _om.counter("pt_server_tenant_preemptions_total",
                           "priority preemptions (slot evicted "
                           "mid-flight) by victim tenant",
                           labels=("tenant",))
_M_T_LAT = _om.histogram("pt_server_tenant_request_latency_seconds",
                         "submit -> harvest wall time per completed "
                         "request, by tenant", labels=("tenant",))
_M_T_TTFT = _om.histogram("pt_server_tenant_ttft_seconds",
                          "submit -> first token per completed "
                          "request, by tenant", labels=("tenant",))
_M_PREEMPT = _om.counter("pt_server_preemptions_total",
                         "slots evicted mid-flight for higher-priority "
                         "work (preempt/resume are span events, never "
                         "request terminals)")
_M_RESUMED = _om.counter("pt_server_resumes_total",
                         "preempted requests re-admitted via history "
                         "re-prefill")


class Server:
    """Continuous-batching server over an engine. ``submit()`` requests
    (optionally with future ``arrival_step`` ticks and per-request
    deadlines), then ``run_until_idle()`` — results match per-request
    ``generate()``: prompt + generated ids, rows that hit eos padded
    with eos to ``max_new_tokens`` (greedy traffic is bit-identical).
    Failed requests surface as :class:`RequestFailure` values in
    ``results`` instead of hanging the loop."""

    def __init__(self, engine: ContinuousBatchingEngine,
                 scheduler: Optional[Scheduler] = None,
                 resilience: Optional[ResilienceConfig] = None,
                 observability: Optional[ObservabilityConfig] = None,
                 preemption: Optional[bool] = None):
        self.engine = engine
        self.scheduler = scheduler or Scheduler()
        self.resilience = resilience or ResilienceConfig()
        env_armed = preemption is None
        if preemption is None:
            from ..utils.flags import env_bool
            preemption = env_bool("PT_SERVING_PREEMPTION")
        if preemption and not getattr(self.scheduler, "priority_aware",
                                      False):
            # a FIFO scheduler hands the freed slot straight back to
            # the front-inserted victim: eviction churn + priority
            # inversion instead of lower TTFT. Explicit misconfig is
            # refused loudly; the env knob (weaker than explicit
            # config, same contract as PT_SERVING_PAGED) never forces
            # an unsupported scheduler.
            if env_armed:
                preemption = False
            else:
                raise ValueError(
                    "preemption=True needs a priority-aware scheduler "
                    "(serving.frontend.FairScheduler): the FIFO "
                    "scheduler would hand every freed slot back to the "
                    "evicted victim")
        if preemption and engine.tp_degree() > 1:
            # the sharded state's eviction path is unpinned; refused
            # loudly, never run silently (ROADMAP follow-up). Spec
            # engines compose since PR 14: drafting is a pure host
            # function of history, so a resumed spec stream re-drafts
            # identically — pinned in tests/test_serving_spec.py.
            if env_armed:
                preemption = False
            else:
                raise NotImplementedError(
                    "priority preemption is not yet composed with "
                    "tensor-parallel engines — drop preemption= or "
                    "tp= (ROADMAP follow-up)")
        # priority preemption policy: strictly-higher-priority visible
        # work may evict a live lower-priority slot (engine.preempt_slot
        # mechanism; default off — the PR 1/4 bit-identity contract is
        # untouched without it)
        self.preemption = bool(preemption)
        self.preemptions = 0
        self.resumes = 0
        # per-tenant lifecycle accounting (frontend.py stats + metrics)
        self.tenant_counts: Dict[str, Dict[str, int]] = {}
        self._tenant_of: Dict[int, str] = {}
        # token-stream hook (serving/frontend.py): when set, called as
        # sink(rid, tokens_list_or_None, done, failure) from the
        # harvest/fail paths and once per tick for live runs — None
        # keeps every hot path at one `is None` check
        self.stream_sink = None
        self._res = ResilienceState(self.resilience)
        engine.nan_sentinel = self.resilience.nan_sentinel
        # the breaker gauge tracks THIS server from birth — without the
        # reset, a fresh healthy server built after a drained one would
        # inherit the process-global 1 forever
        _M_BREAKER.set(1 if self._res.breaker_open else 0)
        obs = observability or ObservabilityConfig()
        self.observability = obs
        self.tracer = RequestTracer(enabled=obs.trace_requests)
        self.flight = FlightRecorder(capacity=obs.flight_size,
                                     dump_dir=obs.flight_dump_dir)
        # the engine only carries a tracer when tracing is armed, so
        # its hot paths pay one `is None` check when it isn't
        engine.tracer = self.tracer if self.tracer.enabled else None
        # attachment points for layered state that must ride snapshots
        # (e.g. the frontend's per-stream delivered offsets): name ->
        # zero-arg callable returning a JSON-safe dict, captured at
        # snapshot time; a restored server surfaces the saved dicts in
        # ``restored_extras`` for the layer to rehydrate from
        self.snapshot_extras: Dict[str, object] = {}
        self.restored_extras: Dict[str, dict] = {}
        self.results: Dict[int, object] = {}
        self.latencies: Dict[int, float] = {}
        self.ttft: Dict[int, float] = {}       # submit -> first token
        self.tick_seconds: list = []           # per-tick wall times
        self._next_id = 0
        self._clock = 0
        self._wall = 0.0

    def submit(self, prompt, max_new_tokens: int = 20,
               temperature: float = 0.0, top_k: int = 0,
               top_p: float = 1.0, eos_token_id: Optional[int] = None,
               seed: int = 0, arrival_step: int = 0,
               deadline_ticks: Optional[int] = None,
               deadline_s: Optional[float] = None,
               tenant: str = "default", priority: int = 0) -> int:
        """Queue one request; returns its id (key into ``results``).
        Capacity is validated HERE — a request that can never fit a
        slot (or, paged, the block pool) is rejected at the door, not
        re-queued forever mid-stream. With ``max_queue_depth`` set, a
        submit beyond the cap is load-shed: the id comes back with a
        ``RequestFailure(reason="shed")`` already recorded. A scheduler
        with per-tenant quotas (frontend.FairScheduler) sheds the same
        way when ``tenant``'s queue quota is exhausted."""
        prompt = np.asarray(prompt, np.int32)
        self.engine.validate_request(int(prompt.size), max_new_tokens)
        rid = self._next_id
        self._next_id += 1
        _M_SUBMIT.inc()
        self._tenant_of[rid] = tenant
        self._tcount(tenant)["submitted"] += 1
        self.tracer.start(rid)
        depth = self.resilience.max_queue_depth
        if depth is not None and self.scheduler.pending() >= depth:
            self._res.shed_requests += 1
            _M_SHED.inc()
            self.flight.record("shed", rid=rid, depth=depth)
            self._fail(rid, "shed",
                       f"queue depth at cap ({depth}); retry later")
            return rid
        quota = getattr(self.scheduler, "quota_exceeded", None)
        if quota is not None and quota(tenant):
            self._res.shed_requests += 1
            _M_SHED.inc()
            self.flight.record("shed", rid=rid, tenant=tenant)
            self._fail(rid, "shed",
                       f"tenant {tenant!r} queue quota exhausted; "
                       "retry later")
            return rid
        self.scheduler.submit(Request(
            request_id=rid, prompt=np.asarray(prompt, np.int32),
            max_new_tokens=max_new_tokens, temperature=temperature,
            top_k=top_k, top_p=top_p, eos_token_id=eos_token_id,
            seed=seed, arrival_step=arrival_step,
            t_submit=time.perf_counter(),
            deadline_ticks=deadline_ticks, deadline_s=deadline_s,
            tenant=tenant, priority=priority))
        _M_QUEUE.set(self.scheduler.pending())
        return rid

    def inject(self, req: Request):
        """Queue an externally-constructed :class:`Request` under ITS
        OWN id — the fleet's redrive/resubmission path, where the id
        was assigned at the ORIGINAL submission and must survive the
        move to this server (one id, one terminal, one results entry
        fleet-wide). Door policies (shed, quota) deliberately do not
        run: the request was already admitted once; this is recovery,
        not new load."""
        self._tenant_of[req.request_id] = req.tenant
        self._tcount(req.tenant)["submitted"] += 1
        _M_SUBMIT.inc()
        self.tracer.start(req.request_id)
        self.scheduler.submit(req)
        _M_QUEUE.set(self.scheduler.pending())

    def _tcount(self, tenant: str) -> Dict[str, int]:
        c = self.tenant_counts.get(tenant)
        if c is None:
            c = {"submitted": 0, "completed": 0, "failed": 0,
                 "shed": 0, "preemptions": 0, "tokens": 0}
            self.tenant_counts[tenant] = c
        return c

    # -- failure plumbing --------------------------------------------------
    def _fail(self, rid: int, reason: str, message: str = "",
              tokens: int = 0):
        self.results[rid] = RequestFailure(
            request_id=rid, reason=reason, message=message,
            tokens_emitted=tokens)
        self._res.count_failure(reason)
        _M_FAILED.inc(reason=reason)
        tenant = self._tenant_of.get(rid, "default")
        tc = self._tcount(tenant)
        tc["failed"] += 1
        _M_T_FAILED.inc(tenant=tenant)
        if reason == "shed":
            tc["shed"] += 1
            _M_T_SHED.inc(tenant=tenant)
        if reason == "timeout":
            _M_DEADLINE.inc()
        self.flight.record("request_failed", rid=rid, reason=reason,
                           tokens=tokens)
        self.tracer.terminal(rid, reason, tokens=tokens)
        if self.stream_sink is not None:
            self.stream_sink(rid, None, True, reason)

    def _deadline_hit(self, req: Request, now: float) -> bool:
        cfg = self.resilience
        dt = req.deadline_ticks if req.deadline_ticks is not None \
            else cfg.deadline_ticks
        if dt is not None and self._clock - req.arrival_step > dt:
            return True
        ds = req.deadline_s if req.deadline_s is not None \
            else cfg.deadline_s
        return ds is not None and now - req.t_submit > ds

    def _expire(self):
        """Cancel queued and in-flight requests past their deadline
        (and queued ones past the max queue wait). In-flight
        cancellation goes through ``engine.cancel_slot`` — the slot is
        killed in-graph and paged blocks release at correct refcounts;
        the failure surfaces through the normal harvest."""
        now = time.perf_counter()
        mw = self.resilience.max_queue_wait_ticks

        def queued_out(r):
            # a preempted victim's wait is measured from its requeue
            # (wait_from), not arrival — its decode time was service;
            # deadlines stay end-to-end via _deadline_hit below
            base = r.arrival_step if r.wait_from is None else r.wait_from
            if mw is not None and self._clock - base > mw:
                return True
            return self._deadline_hit(r, now)

        for r in self.scheduler.drop_where(queued_out):
            self._fail(r.request_id, "timeout",
                       f"expired in queue at tick {self._clock}")
        for slot, run in self.engine.live_runs():
            if self._deadline_hit(run.request, now):
                self.engine.cancel_slot(slot, "timeout")

    def _with_retry(self, fn) -> bool:
        """Run ``fn`` with the transient-failure policy: seeded
        exponential backoff between attempts; every failed attempt
        counts toward the consecutive-failure budget that opens the
        circuit breaker. Returns False if ``fn`` never succeeded (the
        tick just moves on — or the breaker drains everything)."""
        res, cfg = self._res, self.resilience
        for attempt in range(cfg.retry_attempts + 1):
            if res.breaker_open:
                return False
            try:
                fn()
                res.consecutive_failures = 0
                return True
            except res.transient as e:
                res.step_failures += 1
                res.consecutive_failures += 1
                res.last_error = f"{type(e).__name__}: {e}"
                _M_STEPFAIL.inc()
                self.flight.record(
                    "step_failure", error=res.last_error[:200],
                    consecutive=res.consecutive_failures,
                    clock=self._clock)
                if res.consecutive_failures >= cfg.breaker_threshold:
                    res.breaker_open = True
                    _M_BREAKER.set(1)
                    self.flight.record("breaker_open", clock=self._clock,
                                       after=res.consecutive_failures)
                    self.tracer.server_instant(
                        "breaker_open", clock=self._clock)
                    return False
                if attempt < cfg.retry_attempts:
                    res.retries += 1
                    _M_RETRY.inc()
                    backoff = res.backoff_s(attempt)
                    self.flight.record("retry", attempt=attempt,
                                       backoff_s=round(backoff, 6),
                                       clock=self._clock)
                    self.tracer.server_instant("retry", attempt=attempt,
                                               clock=self._clock)
                    time.sleep(backoff)
        return False

    def _quarantine_all(self, reason: str):
        """Circuit-breaker drain: cancel every in-flight request and
        fail everything still queued — the server ends in a clean,
        fully-accounted state instead of wedging on a dead device."""
        for slot, _ in self.engine.live_runs():
            self.engine.cancel_slot(slot, reason)
        for r in self.scheduler.drop_where(lambda r: True):
            self._fail(r.request_id, reason,
                       "circuit breaker open: queue drained")

    # -- priority preemption ----------------------------------------------
    def _preempt_victim(self, below: int) -> bool:
        """Evict ONE live run with priority strictly under ``below``:
        lowest priority first, then fewest generated tokens (least
        re-prefill work lost), then highest slot — deterministic. Only
        resumable victims qualify (can_resume), so a preemption is
        always a pause, never a silent kill. Returns False when no run
        qualifies."""
        cands = [(run.request.priority, len(run.tokens), -slot, slot,
                  run)
                 for slot, run in self.engine.live_runs()
                 if run.request.priority < below
                 and self.engine.can_resume(run)]
        if not cands:
            return False
        *_, slot, run = min(cands, key=lambda c: c[:3])
        self._do_preempt(slot, run)
        return True

    def _do_preempt(self, slot: int, run):
        """Preempt mechanism glue: evict through the engine (in-graph
        slot kill, paged blocks released at exact refcounts with the
        prefix index retained), attach the carried stream state to the
        request, and requeue it at the front of its arrival tick. The
        request stays OPEN — preempt/resume are span events on its
        trace, never terminals."""
        from .scheduler import ResumeState
        req = run.request
        _, key = self.engine.preempt_slot(slot)
        if key is not None:          # was decoding: carry the stream
            req.resume = ResumeState(tokens=list(run.tokens),
                                     key=np.asarray(key, np.uint32),
                                     t_admit=run.t_admit)
        # else mid-prefill: a fresh victim requeues as-submitted; a
        # victim mid-RESUME-prefill keeps its existing resume state
        req.wait_from = self._clock      # queue wait restarts here
        self.scheduler.requeue(req)
        self.tracer.span_begin(req.request_id, "queue_wait",
                               requeued=True)
        self.preemptions += 1
        tenant = getattr(req, "tenant", "default")
        self._tcount(tenant)["preemptions"] += 1
        _M_PREEMPT.inc()
        _M_T_PREEMPT.inc(tenant=tenant)
        self.flight.record("preempt", rid=req.request_id, slot=slot,
                           tokens=len(run.tokens), clock=self._clock)

    def _preempt_for_priority(self):
        """Admission-side preemption: walk the visible queue from the
        highest priority down; each request that would otherwise wait
        on a full pool evicts one strictly-lower-priority victim. The
        freed slots are then handed out by the scheduler's normal
        pop_ready order — eviction opens capacity, it does not
        hard-assign slots. Runs only when the admission batching gate
        would actually release work (probed with one hypothetical free
        slot) — evicting into a held gate would idle the freed slot
        for up to max_wait_steps while the victim pays a re-prefill
        for nothing."""
        gate = getattr(self.scheduler, "_gate_visible", None)
        if gate is not None and gate(
                self._clock, 1, not self.engine.has_live(),
                None) is None:
            return
        vis = self.scheduler.visible(self._clock)
        if not vis:
            return
        free = self.engine.free_slot_count()
        if free >= len(vis):
            return          # every waiter gets a slot without eviction
        # O(V) bail before the O(V log V) sort: nothing waiting
        # outranks anything running -> no eviction is possible
        runs = self.engine.live_runs()
        if not runs or min(r.request.priority for _, r in runs) >= \
                max(r.priority for r in vis):
            return
        for req in sorted(vis, key=lambda r: -r.priority):
            if free > 0:
                free -= 1            # a free slot serves this request
                continue
            if not self._preempt_victim(below=req.priority):
                break    # nothing evictable at this (or any lower) tier
            # the freed slot is spoken for by req: net free stays 0

    # -- the tick ----------------------------------------------------------
    def _tick(self):
        self._expire()
        if self.preemption and not self.engine.has_pending_harvest():
            # only at a clean block boundary — a dispatched block
            # awaiting a harvest retry must land before any eviction
            self._preempt_for_priority()
        admitted = self.scheduler.pop_ready(
            self._clock, self.engine.free_slot_count(),
            engine_idle=not self.engine.has_live())
        for i, req in enumerate(admitted):
            resumed = getattr(req, "resume", None) is not None
            ok = self.engine.try_admit(req)
            while not ok and self.preemption and \
                    not self.engine.has_pending_harvest() and \
                    self._preempt_victim(below=req.priority):
                # paged: the block pool (not the slots) was the limit —
                # evict lower-priority work until the request fits or
                # no victims remain
                ok = self.engine.try_admit(req)
            if ok:
                if resumed:
                    self.resumes += 1
                    _M_RESUMED.inc()
                continue
            # re-queue in reverse: requeue() front-inserts per
            # arrival tick, so forward order would flip
            # same-tick FIFO and let peers overtake the oldest
            _M_DEFER.inc(len(admitted) - i)
            self.flight.record(
                "block_pool_defer", rid=req.request_id,
                clock=self._clock,
                deferred=len(admitted) - i)
            for r in reversed(admitted[i:]):
                self.scheduler.requeue(r)
            break
        prefill_tick = getattr(self.engine, "prefill_tick", None)
        if prefill_tick is not None:
            # chunks dispatched before a mid-loop fault keep their
            # cursors, so a retry must only get the UNSPENT part of the
            # tick's budget — otherwise each retry re-arms a full
            # budget and one tick can blow the decode-interference
            # bound chunked prefill exists to enforce
            budget = self.scheduler.prefill_token_budget
            spent = [0]

            def _prefill():
                b = None if budget is None else budget - spent[0]
                if b is not None and b <= 0 and spent[0] > 0:
                    return           # budget already consumed this tick
                # measure spend from the engine counter, not the return
                # value — a fault raises out of prefill_tick AFTER some
                # chunks already dispatched, and those must still count
                before = self.engine.prefilled_tokens
                try:
                    prefill_tick(b)
                finally:
                    spent[0] += self.engine.prefilled_tokens - before

            self._with_retry(_prefill)
        if self.engine.has_decoding() or \
                self.engine.has_pending_harvest():
            self._with_retry(self.engine.step_block)

    def _harvest(self):
        now = time.perf_counter()
        for run in self.engine.drain_finished():
            req = run.request
            if run.failure is not None:
                self._fail(req.request_id, run.failure,
                           f"cancelled after {len(run.tokens)} tokens",
                           tokens=len(run.tokens))
                continue
            toks = np.asarray(run.tokens, np.int32)
            if len(toks) < req.max_new_tokens:
                # retired early at eos: pad to max_new (generate parity)
                toks = np.concatenate([toks, np.full(
                    (req.max_new_tokens - len(toks),),
                    req.eos_token_id, np.int32)])
            self.results[req.request_id] = np.concatenate(
                [np.asarray(req.prompt, np.int32).reshape(-1), toks])
            self.latencies[req.request_id] = now - req.t_submit
            self.ttft[req.request_id] = run.t_admit - req.t_submit
            _M_DONE.inc()
            _M_LAT.observe(self.latencies[req.request_id])
            _M_TTFT.observe(self.ttft[req.request_id])
            tenant = getattr(req, "tenant", "default")
            tc = self._tcount(tenant)
            tc["completed"] += 1
            tc["tokens"] += len(run.tokens)
            _M_T_DONE.inc(tenant=tenant)
            _M_T_LAT.observe(self.latencies[req.request_id],
                             tenant=tenant)
            _M_T_TTFT.observe(self.ttft[req.request_id], tenant=tenant)
            self.tracer.instant(req.request_id, "harvest",
                                tokens=len(run.tokens))
            self.tracer.terminal(req.request_id, "completed",
                                 tokens=len(run.tokens))
            if self.stream_sink is not None:
                self.stream_sink(req.request_id, run.tokens, True, None)

    def run_until_idle(self, max_ticks: Optional[int] = None
                       ) -> Dict[int, object]:
        """Drive the loop until the queue is empty and every slot is
        free; returns ``results`` (arrays for completed requests,
        ``RequestFailure`` for shed/expired/quarantined ones). One tick
        = expire deadlines, admit what the scheduler releases (requests
        the engine defers — paged block pool exhausted — re-queue),
        advance chunked prefills within the scheduler's prefill token
        budget, run one decode block, harvest. Per-tick wall times land
        in ``tick_seconds`` — the max is the decode-interference figure
        chunked prefill exists to bound.

        ``max_ticks``: stop after that many ticks even with work in
        flight — the kill point for snapshot/restore tests and a hang
        bound for chaos schedules. A tick that trips the
        ``server.tick`` fault site is counted and skipped (requests
        stay queued; nothing is lost)."""
        t0 = time.perf_counter()
        ticks = 0
        while self.scheduler.pending() or self.engine.has_live():
            if max_ticks is not None and ticks >= max_ticks:
                break
            if self._res.breaker_open:   # incl. restored-open circuits
                self._circuit_open_drain()
                break
            t_tick = time.perf_counter()
            t_tick_us = now_us() if self.tracer.enabled else 0.0
            try:
                faults.fault_point("server.tick")
                self._tick()
            except faults.InjectedFault:
                self._res.tick_faults += 1
                self.flight.record("tick_fault", clock=self._clock)
            self._clock += 1
            ticks += 1
            self._harvest()
            self._drain_live_streams()
            tick_s = time.perf_counter() - t_tick
            self.tick_seconds.append(tick_s)
            self.tracer.server_span_at("tick", t_tick_us,
                                       clock=self._clock - 1)
            _M_TICKS.inc()
            _M_TICK_S.observe(tick_s)
            _M_QUEUE.set(self.scheduler.pending())
            _M_OCC.set(self.engine.occupancy())
            self.flight.record(
                "tick", clock=self._clock - 1,
                queue=self.scheduler.pending(),
                live=len(self.engine.live_runs()),
                tokens=self.engine.tokens_emitted,
                tick_ms=round(tick_s * 1000, 3))
            if self._res.breaker_open:
                self._circuit_open_drain()
                break
        self._wall += time.perf_counter() - t0
        return self.results

    def _drain_live_streams(self):
        """Token-by-token streaming out of the harvest path: after each
        tick's harvest, in-flight runs' freshly decoded tokens flow to
        the stream sink (the frontend fans them out to per-request
        bounded queues / callbacks). Token visibility granularity is
        the decode block — exactly when the host learns of them."""
        if self.stream_sink is None:
            return
        for _slot, run in self.engine.live_runs():
            if run.tokens:
                self.stream_sink(run.request.request_id, run.tokens,
                                 False, None)

    def _circuit_open_drain(self):
        """Breaker-open endgame: auto-dump the flight recorder (the
        black box exists for exactly this moment), then drain and
        account every in-flight/queued request as ``circuit_open``."""
        self.flight.record("circuit_open_drain", clock=self._clock,
                           queue=self.scheduler.pending(),
                           live=len(self.engine.live_runs()))
        _M_BREAKER.set(1)
        try:
            self.flight.dump(reason="circuit_open")
        except OSError as e:             # diagnostics must never block
            self.flight.record("flight_dump_failed",  # the drain
                               error=f"{type(e).__name__}: {e}"[:200])
        self._quarantine_all("circuit_open")
        self._harvest()

    def stats(self) -> dict:
        lat = list(self.latencies.values())
        ttft = list(self.ttft.values())
        ticks = self.tick_seconds
        eng = self.engine
        completed = sum(1 for v in self.results.values()
                        if not isinstance(v, RequestFailure))
        out = {
            "requests_completed": completed,
            "tokens_emitted": eng.tokens_emitted,
            "decode_steps": eng.steps,
            "slot_occupancy": round(eng.occupancy(), 4),
            "wall_s": round(self._wall, 4),
            "tokens_per_sec": round(eng.tokens_emitted / self._wall, 1)
            if self._wall else 0.0,
            "decode_compile_count": eng.decode_compile_count(),
            "latency_avg_s": round(float(np.mean(lat)), 4) if lat else 0.0,
            "latency_p95_s": round(float(np.percentile(lat, 95)), 4)
            if lat else 0.0,
            "ttft_p50_s": round(float(np.percentile(ttft, 50)), 4)
            if ttft else 0.0,
            "ttft_p95_s": round(float(np.percentile(ttft, 95)), 4)
            if ttft else 0.0,
            "max_tick_s": round(max(ticks), 4) if ticks else 0.0,
            "p95_tick_s": round(float(np.percentile(ticks, 95)), 4)
            if ticks else 0.0,
            "preemptions": self.preemptions,
            "resumes": self.resumes,
            # per-tenant breakdown (single-tenant traffic shows one
            # "default" row — the shape is stable either way)
            "tenants": {t: dict(c)
                        for t, c in sorted(self.tenant_counts.items())},
        }
        out.update(self._res.counters())
        if eng.tp_degree() > 1:                # tensor-parallel extras
            out["tp_degree"] = eng.tp_degree()
        acc = getattr(eng, "acceptance_rate", None)
        if acc is not None:                    # speculative extras: a
            # tick advances 0..k+1 tokens per slot, so per-tick token
            # accounting reads these, not decode_steps
            out["spec_k"] = eng.spec_k
            out["spec_verify_steps"] = eng.verify_steps
            out["spec_acceptance_rate"] = round(acc(), 4)
            out["spec_mean_accepted_per_step"] = round(
                eng.mean_accepted_per_step(), 4)
        hit_rate = getattr(eng, "prefix_cache_hit_rate", None)
        if hit_rate is not None:               # paged engine extras
            out["prefix_cache_hit_rate"] = round(hit_rate(), 4)
            out["kv_bytes_per_slot"] = eng.backend.kv_bytes_per_slot()
        return out

    def export_trace(self, path: str, profiler=None) -> str:
        """Write the served stream as ONE Perfetto-loadable chrome-trace
        JSON: this server's request rows + tick markers, merged (on the
        same perf_counter clock) with the profiler's ``RecordEvent``
        host-span ring when a :class:`~paddle_tpu.profiler.Profiler` is
        passed (drained destructively, like its own export)."""
        return export_chrome_trace(path, tracer=self.tracer,
                                   profiler=profiler)

    # -- crash-safe snapshot / restore -------------------------------------
    def snapshot(self, path: str):
        """Write server + engine state as ONE atomic npz: queue,
        results, clocks, resilience counters, and the engine's full
        device/host state. Taken between ticks (the engine enforces the
        no-pending-harvest boundary)."""
        meta, arrays = self.engine.snapshot_state()
        res_meta = {}
        for rid, v in self.results.items():
            if isinstance(v, RequestFailure):
                res_meta[str(rid)] = {
                    "kind": "failure", "reason": v.reason,
                    "message": v.message,
                    "tokens_emitted": v.tokens_emitted}
            else:
                res_meta[str(rid)] = {"kind": "ok"}
                arrays[f"res_{rid}"] = np.asarray(v, np.int32)
        # deliberate direct read: a custom scheduler without a _queue
        # list must FAIL the snapshot loudly, not silently serialize an
        # empty queue and lose every not-yet-admitted request
        queue = list(self.scheduler._queue)
        qmeta = []
        for i, r in enumerate(queue):
            arrays[f"q{i}_prompt"] = np.asarray(r.prompt,
                                                np.int32).reshape(-1)
            qmeta.append(request_to_meta(r))
        # the snapshot event goes into the ring BEFORE the ring is
        # captured, so the restored server's history and the sidecar
        # agree on it (and on every seq number)
        self.flight.record("snapshot", path=path, clock=self._clock)
        smeta = {
            "next_id": self._next_id, "clock": self._clock,
            "wall": self._wall,
            "latencies": {str(k): v for k, v in self.latencies.items()},
            "ttft": {str(k): v for k, v in self.ttft.items()},
            "results": res_meta, "queue": qmeta,
            "counters": self._res.counters(),
            "preemption_enabled": self.preemption,
            "preemptions": self.preemptions,
            "resumes": self.resumes,
            "tenant_counts": self.tenant_counts,
            "tenant_of": {str(k): v
                          for k, v in self._tenant_of.items()},
            # the flight ring rides the snapshot (restored server keeps
            # its pre-crash event history) AND dumps beside it for
            # humans reading the crash site without np.load
            "flight": self.flight.to_meta(),
            # layered-state providers (frontend stream offsets, ...)
            "extras": {name: fn()
                       for name, fn in self.snapshot_extras.items()},
        }
        self.flight.dump(path + ".flight.json", reason="snapshot")
        save_snapshot(path, {"engine": meta, "server": smeta}, arrays)

    @classmethod
    def restore(cls, path: str, engine: ContinuousBatchingEngine,
                scheduler: Optional[Scheduler] = None,
                resilience: Optional[ResilienceConfig] = None,
                observability: Optional[ObservabilityConfig] = None,
                preemption: Optional[bool] = None) -> "Server":
        """Rebuild a server from a snapshot into a freshly constructed
        engine of the same configuration (fresh process simulation:
        programs recompile, state restores — then ``run_until_idle()``
        finishes every stream bit-identical to the uninterrupted run).
        Pass the original ``observability`` config to keep tracing
        armed and the flight ring at its configured capacity — the
        saved ring rehydrates into THIS server's ring, so restoring
        with a smaller capacity keeps only the newest events that fit."""
        meta, arrays = load_snapshot(path)
        engine.restore_state(meta["engine"], arrays)
        sm = meta["server"]
        if preemption is None:   # the saved policy survives by default
            preemption = sm.get("preemption_enabled")
        srv = cls(engine, scheduler, resilience, observability,
                  preemption=preemption)
        srv._next_id = sm["next_id"]
        srv._clock = sm["clock"]
        srv._wall = sm["wall"]
        srv.latencies = {int(k): v for k, v in sm["latencies"].items()}
        srv.ttft = {int(k): v for k, v in sm["ttft"].items()}
        for rid_s, info in sm["results"].items():
            rid = int(rid_s)
            if info["kind"] == "ok":
                srv.results[rid] = np.asarray(arrays[f"res_{rid}"],
                                              np.int32)
            else:
                srv.results[rid] = RequestFailure(
                    request_id=rid, reason=info["reason"],
                    message=info["message"],
                    tokens_emitted=info["tokens_emitted"])
        # the full resilience runtime state (failure counts, retry
        # budget, breaker) survives the restore — an open circuit must
        # stay open in the resumed process
        srv._res.restore_counters(sm["counters"])
        # front-door accounting (tolerant: pre-frontend snapshots)
        srv.preemptions = sm.get("preemptions", 0)
        srv.resumes = sm.get("resumes", 0)
        srv.tenant_counts = {t: dict(c) for t, c in
                             sm.get("tenant_counts", {}).items()}
        srv._tenant_of = {int(k): v for k, v in
                          sm.get("tenant_of", {}).items()}
        # pre-extras snapshots restore with no layered state
        srv.restored_extras = dict(sm.get("extras", {}))
        _M_BREAKER.set(1 if srv._res.breaker_open else 0)
        if "flight" in sm:       # pre-observability snapshots lack it
            srv.flight.restore_meta(sm["flight"])
        srv.flight.record("restored", path=path, clock=srv._clock)
        # re-submit in saved order: insort is stable, so same-tick FIFO
        # order survives the round trip. Carried-over requests also
        # (re)enter the tracer here — scheduler.submit bypasses
        # Server.submit, so without this every resumed request would
        # silently miss its trace (and its exactly-one terminal span)
        for i, rm in enumerate(sm["queue"]):
            req = request_from_meta(rm, arrays[f"q{i}_prompt"])
            srv.scheduler.submit(req)
            srv.tracer.start(req.request_id)
        for slot, run in engine.live_runs():
            rid = run.request.request_id
            srv.tracer.start(rid)
            srv.tracer.span_end(rid, "queue_wait", restored=True)
            # mid-prefill paged slots re-open this span at
            # _finish_prefill; for decoding slots it is simply resumed
            srv.tracer.span_begin(rid, "decode", slot=slot,
                                  restored=True)
        return srv
