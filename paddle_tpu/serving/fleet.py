"""Disaggregated prefill/decode serving fleet: prefill workers, decode
workers, KV-block handoff, prefix-affinity routing, live migration.

Prefill is compute-bound (one big batched forward per prompt) and
decode is bandwidth-bound (every weight and KV byte re-read per token);
at production scale they want different hardware pools. This module
splits the Server into replicas of two specialties and a router:

- **PrefillWorker**: a Server over a prefill-only engine
  (:class:`PrefillDenseEngine` / :class:`PrefillPagedEngine`). Prompts
  admit, (chunked-)prefill and sample their first token exactly as on
  a unified server — same programs, same key schedule — but a finished
  prefill parks in a handoff **outbox** instead of arming the slot.
  The slot and its arena blocks stay held until the payload ships, so
  a serialize/transport fault retries against live state.
- **KV handoff** (serving/handoff.py): the outbox entry serializes to
  a versioned, bytes-true payload — prompt-position KV blocks at
  storage dtype (int8 codes + scales ship quantized, never dequantized
  in transit), the in-hand token, the post-split rng key, the request.
- **DecodeWorker**: a Server over an ordinary engine. ``adopt()``
  allocates the request's blocks from its OWN BlockManager at exact
  refcounts, scatters the shipped rows into its arena through ONE
  fixed-shape jitted program (padded to ``max_blocks``; pad rows land
  in the trash block), registers the prompt prefix in its own index,
  and arms the slot through the engine's EXISTING arm/admit program —
  zero new compiled programs on the decode steady path, decode compile
  count stays 1. A request prefilled on worker A and decoded on worker
  B streams BIT-IDENTICAL to a single-replica Server (greedy and
  seeded-sampled; dense, paged, paged+kv_int8) because the decode
  block is a pure function of exactly the adopted state.
- **FleetRouter**: chained-SHA1 prefix-hash affinity — the digest of a
  prompt's first full block (the same key the BlockManager indexes it
  under) picks the prefill worker, so a tenant's system prompt lands
  where its registered blocks already live and the PR 4 prefix cache
  becomes a fleet-wide asset. Queue-depth spillover diverts from a
  backlogged affinity target to the least-loaded worker.
- **Transport** (serving/transport.py): the in-process FIFO default,
  or the REAL localhost-TCP :class:`SocketTransport` (length-framed,
  CRC32-trailed, seq-numbered, acked, reconnecting, at-least-once —
  adopt() restores exactly-once by (rid, payload seq) dedup). Handoff
  failures ride the PR 5 retry/backoff/breaker machinery
  (``ResilienceState``): serialize, transport and adopt faults retry
  with seeded backoff, a permanent failure records an explicit
  ``RequestFailure(reason="handoff")``, and an open circuit fails
  fast as ``circuit_open``.
- **Failure domains** (PR 15): per-worker heartbeat leases (a worker
  missing N beats is DEAD — flight event + ``pt_fleet_worker_state``
  gauge, never read again), and REDRIVE of streams lost with a dead
  decode worker: rebuilt from the fleet's own records (submission +
  shipped key + heartbeat token progress, key host-replayed one
  split per observed token), re-prefilled on a surviving prefill
  worker via a ``redrive`` ResumeState, completing bit-identical to
  an unfailed run. A dead prefill worker's un-shipped requests
  resubmit under their original ids; unrecoverable streams fail
  explicitly as ``worker_lost``.
- **Live migration / scale**: a decode worker snapshots via the PR 5
  ``Server.snapshot`` path and restores into a fresh engine
  (``Fleet.migrate_decode_worker``) with every in-flight stream
  finishing bit-identical; ``add_decode_worker`` scales the decode
  pool mid-stream; ``drain_prefill_worker`` stops routing to a worker
  so it can retire cleanly.

- **Fleet-wide prefix cache** (PR 16, serving/prefix_cache.py): paged
  workers publish their registered digest chains with each heartbeat
  into a :class:`~paddle_tpu.serving.prefix_cache.
  PrefixCacheDirectory`; on a prefill-admission miss where the
  directory holds a longer chain, the admitting worker FETCHES the
  covered blocks from the owner over the same transport (a
  ``pt-kv-fetch`` payload on the worker's ``#fetch`` side channel,
  CRC-verified, resilience-retried, ``fleet.fetch`` fault site),
  adopts them through the shared idempotent-adopt scatter and
  chunk-prefills only the uncovered suffix. Any fetch failure falls
  back to local prefill — warm remote state is a perf tier, never a
  dependency. Fleet-global block-pressure watermarks evict LRU
  unreferenced registered blocks so the tier stays bounded.

Knobs (utils/flags helpers): ``PT_SERVING_FLEET_AFFINITY`` (default
on), ``PT_SERVING_FLEET_SPILL_DEPTH`` (default 8),
``PT_SERVING_FLEET_LEASE_MISSES`` (default 3 missed heartbeats),
``PT_SERVING_FLEET_PREFIX_CACHE`` (default on, paged fleets) and the
eviction watermarks ``PT_SERVING_FLEET_EVICT_HIGH`` / ``_LOW``
(default 0.85 / 0.70 of fleet-global block pressure).
"""
from __future__ import annotations

import os
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..observability import FlightRecorder
from ..observability import metrics as _om
from ..utils import faults
from ..utils.flags import env_bool, env_float, env_int
from . import durability as _dur
from .engine import (ContinuousBatchingEngine, _M_PREFILLS, _M_TOKENS,
                     _SlotRun)
from .handoff import KVHandoff, decode_handoff, encode_handoff
from .paging import PagedEngine, _sha1_chain
from . import prefix_cache as _pc
from .prefix_cache import (PrefixCacheDirectory, _adopt_scatter,
                           adopt_prefix, extract_prefix)
from .resilience import (RequestFailure, ResilienceConfig,
                         ResilienceState, request_from_meta,
                         request_to_meta)
from .scheduler import Request, ResumeState
from .server import Server
from .transport import (InProcessTransport, SocketTransport, Transport,
                        TransportError, fetch_endpoint)

__all__ = ["DecodeWorker", "Fleet", "FleetRouter", "InProcessTransport",
           "PrefillDenseEngine", "PrefillPagedEngine", "PrefillWorker",
           "SocketTransport", "Transport", "TransportError"]

# fleet metric families (registered at import so the catalog stays
# complete at zero; no-ops until metrics.enable()/PT_METRICS)
_M_HANDOFFS = _om.counter("pt_fleet_handoffs_total",
                          "KV handoff payloads adopted by decode "
                          "workers")
_M_HANDOFF_BYTES = _om.counter("pt_fleet_handoff_bytes_total",
                               "wire bytes of shipped handoff payloads")
_M_HANDOFF_FAILS = _om.counter(
    "pt_fleet_handoff_failures_total",
    "handoffs that permanently failed, by reason", labels=("reason",))
_M_FLEET_RETRIES = _om.counter("pt_fleet_retries_total",
                               "transient handoff-op retry attempts")
_M_ADOPT_DEFERS = _om.counter(
    "pt_fleet_adopt_defers_total",
    "adoptions deferred (decode slot/block pool momentarily full)")
_M_AFFINITY = _om.counter("pt_fleet_affinity_routes_total",
                          "submissions routed by prefix-hash affinity")
_M_SPILL = _om.counter("pt_fleet_spillovers_total",
                       "submissions diverted off their affinity worker "
                       "by queue-depth spillover")
_M_MIGRATIONS = _om.counter("pt_fleet_migrations_total",
                            "live worker migrations (snapshot/restore)")
_M_PF_DEPTH = _om.gauge("pt_fleet_prefill_queue_depth",
                        "queued requests per prefill worker",
                        labels=("worker",))
_M_DEC_FREE = _om.gauge("pt_fleet_decode_free_slots",
                        "free decode slots per decode worker",
                        labels=("worker",))
# failure-domain families (PR 15)
_M_WORKER_STATE = _om.gauge("pt_fleet_worker_state",
                            "per-worker lease state: 1 live, 0 dead",
                            labels=("worker",))
_M_WORKERS_LOST = _om.counter("pt_fleet_workers_lost_total",
                              "workers whose lease expired, by role",
                              labels=("role",))
_M_REDRIVES = _om.counter(
    "pt_fleet_redrives_total",
    "streams reconstructed from fleet records after a worker died")
_M_ADOPT_DUPS = _om.counter(
    "pt_fleet_adopt_duplicates_total",
    "adopt() calls deduplicated on (rid, payload seq) — the "
    "at-least-once wire's retransmits made idempotent")


def _replay_key(key0, n: int) -> np.ndarray:
    """Host replay of the decode block's per-slot key schedule: the
    in-graph step does ``key, sub = split(key)`` exactly once per
    emitted token, so a slot that produced ``n`` decode tokens after
    arming with ``key0`` holds ``split^n(key0)[0]``. This is what makes
    a stream reconstructible from OBSERVED tokens alone — the fleet
    never needs to read a dead worker's device state to resume its
    seeded-sampled streams bit-identically."""
    k = jnp.asarray(np.asarray(key0, np.uint32).reshape(2))
    for _ in range(n):
        k = jax.random.split(k)[0]
    return np.asarray(k, np.uint32)


def _leaf_specs(backend) -> list:
    """Canonical per-leaf KV layout (shape past the pool dim + dtype):
    the ONE compatibility signature shared by payload producers
    (extract_handoff), the adopt-time validator and the fleet-wide
    compat check — a format change cannot drift them apart."""
    return [[list(s[1:]), str(np.dtype(d))]
            for s, d in backend.pool_specs]


def _stamp_resume_meta(meta: dict, ph: "_PendingHandoff"):
    """Redrive payloads carry the generated history: the decode worker
    arms with ``tokens[-1]`` and its run starts from the FULL token
    list, so the completed result is original-prompt + every token.
    ``orig_prompt_len`` is recorded because ``arrays["prompt"]`` is
    then the re-prefilled ``prompt + tokens[:-1]`` sequence, not the
    user's prompt."""
    if ph.tokens is not None:
        meta["tokens"] = [int(t) for t in ph.tokens]
        meta["orig_prompt_len"] = int(ph.orig_len)


# ---------------------------------------------------------------------------
# prefill-only engines
# ---------------------------------------------------------------------------

@dataclass
class _PendingHandoff:
    """One finished prefill waiting to ship. The slot stays occupied
    (in ``_prefill_slots``, so it never decodes) and paged blocks stay
    referenced until the payload is on the wire — a serialize or
    transport fault retries against state that is still alive."""
    run: _SlotRun
    slot: int
    prompt: np.ndarray                  # the PREFILLED token sequence
    tok0: int
    rem0: int
    key: np.ndarray                     # (2,) uint32 post-split key
    row: Optional[tuple] = None         # dense: prefilled cache row
    pad0: int = 0                       # dense: bucket pad count
    bucket: int = 0                     # dense: bucket length Lb
    # redrive resume: the carried generated history (tokens[-1] ==
    # tok0) and the ORIGINAL prompt length — ``prompt`` above is then
    # prompt+tokens[:-1], the re-prefilled sequence
    tokens: Optional[List[int]] = None
    orig_len: Optional[int] = None


class _PrefillEngineMixin:
    """Outbox plumbing shared by the dense and paged prefill engines."""

    def reset(self):
        super().reset()
        self._outbox: List[_PendingHandoff] = []

    def take_handoffs(self) -> List[_PendingHandoff]:
        """Drain ship-ready outbox entries. Entries whose run was
        cancelled meanwhile (deadline expiry went through
        ``cancel_slot`` → ``_retire``, which already released the slot
        and blocks) are dropped here, not shipped."""
        live, self._outbox = self._outbox, []
        return [ph for ph in live
                if ph.run.failure is None
                and self._slots[ph.slot] is ph.run]

    def release_handoff(self, ph: _PendingHandoff):
        """Free everything a shipped (or permanently failed) handoff
        held on this worker: the slot, and — paged — its arena blocks
        at exact refcounts (registered prefix blocks park in the LRU
        cache, which is what keeps the worker's prefix index hot for
        the next same-prefix arrival)."""
        self._prefill_slots.discard(ph.slot)
        if self._slots[ph.slot] is ph.run:
            self._slots[ph.slot] = None
        self._release_slot_resources(ph.run)

    def snapshot_state(self):
        """Un-shipped handoffs RIDE the snapshot (PR 20) instead of
        refusing it: each live outbox entry serializes alongside the
        engine state — its run is a live slot, so the base snapshot
        already carries the slot/blocks; this adds the parked
        ship-side fields. A coordinated fleet checkpoint can therefore
        land at ANY tick boundary."""
        meta, arrays = super().snapshot_state()
        ob_meta = []
        for ph in self._outbox:
            if ph.run.failure is not None \
                    or self._slots[ph.slot] is not ph.run:
                continue                    # cancelled — never ships
            k = len(ob_meta)
            arrays[f"ob{k}_prompt"] = np.asarray(ph.prompt, np.int32)
            arrays[f"ob{k}_key"] = np.asarray(ph.key, np.uint32)
            if ph.row is not None:
                for i, r in enumerate(ph.row):
                    arrays[f"ob{k}_row{i}"] = np.asarray(r)
            ob_meta.append({
                "slot": int(ph.slot), "tok0": int(ph.tok0),
                "rem0": int(ph.rem0), "pad0": int(ph.pad0),
                "bucket": int(ph.bucket),
                "row": ph.row is not None,
                "tokens": None if ph.tokens is None
                else [int(t) for t in ph.tokens],
                "orig_len": None if ph.orig_len is None
                else int(ph.orig_len)})
        meta["outbox"] = ob_meta
        return meta, arrays

    def restore_state(self, meta, arrays):
        super().restore_state(meta, arrays)
        self._outbox = []
        n_leaves = len(self.backend.pool_specs)
        for k, e in enumerate(meta.get("outbox", ())):
            run = self._slots[e["slot"]]
            if run is None:
                continue
            row = None
            if e["row"]:
                row = tuple(np.asarray(arrays[f"ob{k}_row{i}"])
                            for i in range(n_leaves))
            self._outbox.append(_PendingHandoff(
                run=run, slot=int(e["slot"]),
                prompt=np.asarray(arrays[f"ob{k}_prompt"], np.int32),
                tok0=int(e["tok0"]), rem0=int(e["rem0"]),
                key=np.asarray(arrays[f"ob{k}_key"], np.uint32),
                row=row, pad0=int(e["pad0"]), bucket=int(e["bucket"]),
                tokens=e["tokens"],
                orig_len=e["orig_len"]))


class PrefillPagedEngine(_PrefillEngineMixin, PagedEngine):
    """Paged engine that prefills but never decodes: chunked prefill,
    prefix reuse and the block manager are inherited unchanged; a
    finished prefill parks in the handoff outbox with its blocks still
    referenced instead of arming the slot. Requests that finish AT
    prefill (eos on the first token, max_new==1) complete here — no
    decode worker ever sees them."""

    def try_admit(self, request) -> bool:
        resume = getattr(request, "resume", None)
        if resume is not None and resume.tokens \
                and not resume.redrive:
            raise NotImplementedError(
                "prefill workers do not take preemption resumes — the "
                "fleet never preempts (route resumes to a unified "
                "Server)")
        # a redrive resume rides the PR 13 paged resume branch
        # unchanged: chunked re-prefill of prompt+tokens[:-1] (mostly
        # prefix-index hits for shared prompts), carried key armed,
        # the chunk programs' in-graph samples discarded
        return super().try_admit(request)

    #: fleet-installed hook ``fn(full_tokens, local_blocks) ->
    #: fetched_block_ids | None``: consult the fleet prefix directory
    #: and fetch the covered blocks a remote worker holds beyond the
    #: local match (Fleet._fetch_prefix). None outside a fleet.
    prefix_fetcher = None

    def _match_prefix_for_admission(self, full):
        shared = self.manager.match_prefix(full)
        if self.prefix_fetcher is not None:
            fetched = self.prefix_fetcher(full, shared)
            if fetched:
                # fetched blocks arrive allocated at refcount 1 and
                # already registered — exactly the hold a local match
                # would have acquired, so the admission path (and its
                # release-on-exhaustion error path) treats them as
                # shared blocks with zero special cases
                shared = shared + fetched
                self.fetched_tokens += len(fetched) * self.kv_block_size
        return shared

    def _finish_prefill(self, job, tok0_dev):
        req = job.run.request
        now = time.perf_counter()
        eos = req.eos_token_id
        if job.resume_tok is not None:      # redrive re-prefill done
            tok0 = job.resume_tok           # the carried in-hand token
            rem0 = req.max_new_tokens - len(job.run.tokens)
            req.resume = None
            tokens = list(job.run.tokens)
            orig_len = int(np.asarray(req.prompt).reshape(-1).size)
            if self.tracer is not None:
                self.tracer.instant(req.request_id, "resume",
                                    slot=job.slot, redrive=True,
                                    reused_tokens=len(tokens))
        else:
            tok0 = int(tok0_dev)
            job.run.tokens = [tok0]
            job.run.t_admit = now           # the fleet TTFT timestamp
            self.tokens_emitted += 1
            _M_TOKENS.inc()
            rem0 = req.max_new_tokens - 1
            if eos is not None and tok0 == eos:
                rem0 = 0
            tokens, orig_len = None, None
        self.manager.register_prefix(job.prompt, job.run.block_ids)
        if rem0 <= 0:                       # finished at admission
            self._prefill_slots.discard(job.slot)
            self._retire(job.slot, job.run, now)
            return
        if self.tracer is not None:
            self.tracer.instant(req.request_id, "handoff_ready",
                                slot=job.slot)
        self._outbox.append(_PendingHandoff(
            run=job.run, slot=job.slot, prompt=job.prompt, tok0=tok0,
            rem0=rem0, key=np.asarray(job.key, np.uint32),
            tokens=tokens, orig_len=orig_len))

    def extract_handoff(self, ph: _PendingHandoff,
                        source: str = "") -> KVHandoff:
        """Build the wire payload from live state: only the blocks
        holding prompt positions ``[0, L)`` ship — decode-position
        blocks are junk the decode worker overwrites before reading.
        Arrays leave at storage dtype (int8 codes stay int8)."""
        L = int(ph.prompt.shape[0])
        bs = self.kv_block_size
        n_ship = -(-L // bs)
        ids = np.asarray(ph.run.block_ids[:n_ship], np.int32)
        arrays = {"prompt": np.asarray(ph.prompt, np.int32),
                  "key": np.asarray(ph.key, np.uint32)}
        for i, c in enumerate(self._cache):
            arrays[f"kv_{i}"] = np.asarray(c[ids])
        req = ph.run.request
        meta = {
            "kind": "paged", "request": request_to_meta(req),
            "tok0": ph.tok0, "pos0": L, "rem0": ph.rem0,
            "n_blocks": len(ph.run.block_ids), "n_ship": n_ship,
            "block_size": bs, "kv_int8": bool(self.kv_int8),
            "leaf_specs": _leaf_specs(self.backend),
            "t_admit": float(ph.run.t_admit),
            "source": {"worker": source,
                       "tp_degree": self.tp_degree()},
        }
        _stamp_resume_meta(meta, ph)
        return KVHandoff(meta=meta, arrays=arrays)


class PrefillDenseEngine(_PrefillEngineMixin, ContinuousBatchingEngine):
    """Dense engine that prefills but never decodes. Admission runs the
    SAME bucket prefill + key schedule as the unified dense engine
    (``key = PRNGKey(seed); key, sub = split(key)``; ``sub`` samples
    the first token, ``key`` arms the slot), but the prefilled row
    parks in the outbox instead of splicing into the pool."""

    def admit(self, request) -> bool:
        from ..profiler import RecordEvent
        resume = getattr(request, "resume", None)
        if resume is not None and resume.tokens:
            if not resume.redrive:
                raise NotImplementedError(
                    "prefill workers do not take preemption resumes — "
                    "the fleet never preempts (route resumes to a "
                    "unified Server)")
            return self._admit_redrive(request, resume)
        prompt = np.asarray(request.prompt, np.int32).reshape(-1)
        L = int(prompt.shape[0])
        self.validate_request(L, request.max_new_tokens)
        Lb = self.bucket_len(L)
        slot = next((i for i, s in enumerate(self._slots) if s is None),
                    None)
        if slot is None:
            raise RuntimeError("no free slot (scheduler bug)")
        tr = self.tracer
        if tr is not None:
            tr.span_end(request.request_id, "queue_wait")
        ids = np.zeros((1, Lb), np.int32)
        ids[0, Lb - L:] = prompt
        pad0 = Lb - L
        key = jax.random.PRNGKey(request.seed)
        key, sub = jax.random.split(key)     # generate()'s key schedule
        with RecordEvent("serving.prefill"):
            tok0_dev, row = self.backend.prefill(
                Lb, jnp.asarray(ids), jnp.asarray([pad0], jnp.int32),
                sub, jnp.float32(request.temperature),
                jnp.int32(request.top_k), jnp.float32(request.top_p))
        tok0 = int(tok0_dev)
        _M_PREFILLS.inc()
        _M_TOKENS.inc()
        run = _SlotRun(request, tokens=[tok0],
                       t_admit=time.perf_counter())
        self.tokens_emitted += 1
        eos = request.eos_token_id
        rem0 = request.max_new_tokens - 1
        if eos is not None and tok0 == eos:
            rem0 = 0
        if rem0 <= 0:                        # finished at admission
            run.t_done = time.perf_counter()
            self._finished.append(run)
            return True
        self._slots[slot] = run
        self._prefill_slots.add(slot)        # occupied, never decoding
        self._outbox.append(_PendingHandoff(
            run=run, slot=slot, prompt=prompt, tok0=tok0, rem0=rem0,
            key=np.asarray(key, np.uint32), row=row, pad0=pad0,
            bucket=Lb))
        return False

    def _admit_redrive(self, request, resume) -> bool:
        """Redrive re-prefill, dense flavour: prompt + tokens[:-1]
        left-padded to its bucket, the in-graph sample DISCARDED (the
        stream owns its next token and the carried key must not be
        advanced), the prefilled row parked in the outbox with the
        carried history — the mirror of the unified engine's
        ``_admit_resume`` with the arm replaced by a handoff."""
        from ..profiler import RecordEvent
        prompt = np.asarray(request.prompt, np.int32).reshape(-1)
        toks = list(resume.tokens)
        full = np.concatenate([prompt, np.asarray(toks[:-1], np.int32)])
        pl = int(full.shape[0])
        rem0 = request.max_new_tokens - len(toks)
        self.validate_request(pl, rem0 + 1)
        Lb = self.bucket_len(pl)
        slot = next((i for i, s in enumerate(self._slots) if s is None),
                    None)
        if slot is None:
            raise RuntimeError("no free slot (scheduler bug)")
        if self.tracer is not None:
            self.tracer.span_end(request.request_id, "queue_wait",
                                 resumed=True, redrive=True)
        ids = np.zeros((1, Lb), np.int32)
        ids[0, Lb - pl:] = full
        pad0 = Lb - pl
        with RecordEvent("serving.prefill"):
            _discard, row = self.backend.prefill(
                Lb, jnp.asarray(ids), jnp.asarray([pad0], jnp.int32),
                jax.random.PRNGKey(0), jnp.float32(0.0), jnp.int32(0),
                jnp.float32(1.0))
        _M_PREFILLS.inc()
        run = _SlotRun(request, tokens=toks, t_admit=resume.t_admit)
        request.resume = None
        if rem0 <= 0:                        # defensive: already done
            run.t_done = time.perf_counter()
            self._finished.append(run)
            return True
        self._slots[slot] = run
        self._prefill_slots.add(slot)
        if self.tracer is not None:
            self.tracer.instant(request.request_id, "resume",
                                slot=slot, redrive=True,
                                reused_tokens=len(toks))
        self._outbox.append(_PendingHandoff(
            run=run, slot=slot, prompt=full, tok0=int(toks[-1]),
            rem0=rem0, key=np.asarray(resume.key, np.uint32), row=row,
            pad0=pad0, bucket=Lb, tokens=toks,
            orig_len=int(prompt.shape[0])))
        return False

    def extract_handoff(self, ph: _PendingHandoff,
                        source: str = "") -> KVHandoff:
        """Dense payload: the populated row prefix ``[:, :Lb]``. The
        row beyond the bucket is zeros by construction (prefill starts
        from a zero row), so shipping the prefix and zero-filling on
        adopt reconstructs the row EXACTLY — bit-identity needs no
        junk bytes on the wire."""
        Lb = ph.bucket
        arrays = {"prompt": np.asarray(ph.prompt, np.int32),
                  "key": np.asarray(ph.key, np.uint32)}
        for i, r in enumerate(ph.row):
            arrays[f"kv_{i}"] = np.asarray(r[:, :Lb])
        req = ph.run.request
        meta = {
            "kind": "dense", "request": request_to_meta(req),
            "tok0": ph.tok0, "pos0": Lb, "pad0": ph.pad0,
            "rem0": ph.rem0,
            "leaf_specs": _leaf_specs(self.backend),
            "t_admit": float(ph.run.t_admit),
            "source": {"worker": source,
                       "tp_degree": self.tp_degree()},
        }
        _stamp_resume_meta(meta, ph)
        return KVHandoff(meta=meta, arrays=arrays)


# ---------------------------------------------------------------------------
# router
# ---------------------------------------------------------------------------

class FleetRouter:
    """Prefix-affinity request router with queue-depth spillover.

    The affinity key of a prompt is the chained-SHA1 digest of its
    FIRST full block — the exact key the BlockManager's prefix index
    stores that block under — so every request sharing a system prompt
    maps to the same prefill worker and its registered blocks.
    Prompts too short to share (no full block: ``L <= block_size``)
    key on their whole token tuple, which is still deterministic.
    Spillover: when the affinity target's queue is ``spill_depth``
    deeper than the shallowest worker's, the request diverts to the
    least-loaded worker (prefix locality traded for latency, counted).
    """

    def __init__(self, block_size: int, affinity: Optional[bool] = None,
                 spill_depth: Optional[int] = None):
        if affinity is None:
            affinity = env_bool("PT_SERVING_FLEET_AFFINITY", True)
        if spill_depth is None:
            spill_depth = env_int("PT_SERVING_FLEET_SPILL_DEPTH", 8)
        if spill_depth < 1:
            raise ValueError(
                f"spill_depth={spill_depth}; must be >= 1")
        self.block_size = block_size
        self.affinity = bool(affinity)
        self.spill_depth = spill_depth
        self.affinity_routes = 0
        self.spillovers = 0

    def affinity_key(self, prompt) -> bytes:
        toks = np.asarray(prompt).reshape(-1)
        if toks.size > self.block_size:      # has a shareable block
            toks = toks[:self.block_size]
        return _sha1_chain(b"", tuple(int(t) for t in toks))

    def route(self, prompt, depths: List[int],
              eligible: List[int], warm=None) -> int:
        """Pick a prefill worker index. ``depths`` aligns with
        ``eligible`` (the non-draining workers). ``warm`` (optional)
        is the set of positions within ``eligible`` whose worker the
        fleet prefix directory lists as holding this prompt's chain
        head: when the affinity target spills over, a warm worker
        within tolerance beats the plain least-loaded one (the fetch
        it saves costs more than a few queue places)."""
        if not eligible:
            raise RuntimeError("no routable prefill worker (all "
                               "draining)")
        least = min(range(len(eligible)), key=lambda i: (depths[i], i))
        if not self.affinity:
            return eligible[least]
        pick = int.from_bytes(self.affinity_key(prompt)[:8], "big") \
            % len(eligible)
        if depths[pick] - depths[least] > self.spill_depth:
            self.spillovers += 1
            _M_SPILL.inc()
            if warm:
                wl = min((i for i in warm if i != pick),
                         key=lambda i: (depths[i], i), default=None)
                if wl is not None \
                        and depths[wl] - depths[least] \
                        <= self.spill_depth:
                    return eligible[wl]
            return eligible[least]
        self.affinity_routes += 1
        _M_AFFINITY.inc()
        return eligible[pick]


# ---------------------------------------------------------------------------
# workers
# ---------------------------------------------------------------------------

class PrefillWorker:
    """A Server over a prefill-only engine. The full PR 5/13 door
    machinery applies — scheduler gating, queue-depth shedding,
    deadlines (an expired outbox entry is dropped un-shipped), retries
    around prefill faults — while decode never runs here."""

    def __init__(self, engine, *, name: str = "",
                 scheduler=None, resilience=None, observability=None,
                 server: Optional[Server] = None):
        if not isinstance(engine, (PrefillDenseEngine,
                                   PrefillPagedEngine)):
            raise ValueError(
                "PrefillWorker needs a prefill-only engine "
                "(PrefillDenseEngine / PrefillPagedEngine); got "
                f"{type(engine).__name__}")
        self.engine = engine
        self.name = name
        self.server = server or Server(engine, scheduler, resilience,
                                       observability)
        self.killed = False

    def kill(self):
        """Simulate whole-worker loss (see DecodeWorker.kill)."""
        self.killed = True

    def heartbeat(self) -> Optional[dict]:
        if self.killed:
            return None
        hb = {"queue_depth": self.server.scheduler.pending(),
              "occupancy": self.engine.occupancy(),
              "outbox": len(self.engine._outbox)}
        if isinstance(self.engine, PagedEngine):
            # the prefix-directory publish: heartbeat-shaped, so
            # directory state rides the lease machinery for free
            hb["prefixes"] = self.engine.manager.registered_chains()
        return hb

    def queue_depth(self) -> int:
        return self.server.scheduler.pending()

    def busy(self) -> bool:
        return self.server.scheduler.pending() > 0 \
            or self.engine.has_live()

    def tick(self):
        if self.killed:
            return
        self.server.run_until_idle(max_ticks=1)


class DecodeWorker:
    """A Server over an ordinary engine whose requests arrive by
    adoption instead of submission. ``adopt()`` is the only addition;
    decode, harvest, deadlines, NaN quarantine, streaming sinks and
    snapshot/restore are the stock Server/engine paths — which is why
    migration is just PR 5 snapshot/restore.

    Liveness: the worker emits a :meth:`heartbeat` each fleet tick
    (queue depth, occupancy, and per-stream token progress — the
    observations the fleet's redrive records are built from). A worker
    ``kill()``-ed to simulate whole-process loss stops ticking,
    adopting and heartbeating; the fleet notices via its lease and
    redrives every stream the corpse owned."""

    def __init__(self, engine, *, name: str = "", resilience=None,
                 observability=None, server: Optional[Server] = None):
        if isinstance(engine, (PrefillDenseEngine, PrefillPagedEngine)):
            raise ValueError("DecodeWorker needs a decoding engine, "
                             "not a prefill-only one")
        self.engine = engine
        self.name = name
        self.server = server or Server(engine, resilience=resilience,
                                       observability=observability)
        self._adopt_jit = None
        self.killed = False
        # exactly-once adoption over an at-least-once wire: payloads
        # already armed, keyed (rid, payload seq)
        self._adopted: set = set()
        self.duplicate_adopts = 0

    # -- liveness ----------------------------------------------------------
    def kill(self):
        """Simulate whole-worker loss: the worker stops participating
        (no ticks, no adopts, no heartbeats). Its ENGINE state — KV
        arena, slot state, rng keys — is deliberately never read again
        by the fleet: stream recovery must work from the fleet's own
        records, as it would have to across a real process boundary.
        Its ``server.results`` ledger IS still read: those outputs
        were delivered at harvest time (the stream sink fires before
        any kill can land), so the in-process dict stands in for the
        client's already-received copy, not for worker memory."""
        self.killed = True

    def heartbeat(self) -> Optional[dict]:
        """One liveness report, or None from a dead worker. Carries
        queue depth/occupancy (the health the router could act on) and
        per-live-stream token progress — the fleet's redrive substrate:
        everything needed to reconstruct a stream is on this side of
        the wire BEFORE the worker can die."""
        if self.killed:
            return None
        if len(self._adopted) > 256:
            # duplicates only arrive within one ship's retransmit
            # window; once a stream terminated (its rid is in the
            # results ledger, which adopt() also dedups against) its
            # dedup entries are dead weight
            self._adopted = {t for t in self._adopted
                             if t[0] not in self.server.results}
        hb = {
            "queue_depth": self.server.scheduler.pending(),
            "occupancy": self.engine.occupancy(),
            "free_slots": self.engine.free_slot_count(),
            "progress": {run.request.request_id: list(run.tokens)
                         for _slot, run in self.engine.live_runs()},
        }
        if isinstance(self.engine, PagedEngine):
            # decode workers publish too: adopted prompts and
            # decode-time-shared completed sequences are fetchable
            # warm state like any prefill worker's
            hb["prefixes"] = self.engine.manager.registered_chains()
        return hb

    # -- capacity ----------------------------------------------------------
    def free_slots(self) -> int:
        return self.engine.free_slot_count()

    def busy(self) -> bool:
        return self.engine.has_live()

    def tick(self):
        if self.killed:
            return
        self.server.run_until_idle(max_ticks=1)

    # -- adoption ----------------------------------------------------------
    def _validate(self, h: KVHandoff):
        eng = self.engine
        paged = isinstance(eng, PagedEngine)
        want_kind = "paged" if paged else "dense"
        if h.kind != want_kind:
            raise ValueError(
                f"{h.kind} handoff cannot adopt into a {want_kind} "
                "engine")
        specs = _leaf_specs(eng.backend)
        if h.meta["leaf_specs"] != specs:
            raise ValueError(
                "handoff KV layout does not match this engine "
                f"(payload {h.meta['leaf_specs'][:2]}..., engine "
                f"{specs[:2]}...) — same model config / paging layout "
                "required")
        if paged and (h.meta["block_size"] != eng.kv_block_size
                      or bool(h.meta["kv_int8"]) != bool(eng.kv_int8)):
            raise ValueError(
                "handoff arena geometry mismatch (block_size/kv_int8)")
        if h.meta["pos0"] + h.meta["rem0"] > eng.max_len:
            raise ValueError(
                f"handoff needs {h.meta['pos0'] + h.meta['rem0']} "
                f"positions but this engine's max_len is {eng.max_len}")

    #: adopt() outcomes
    ADOPTED = "adopted"         # slot armed in the ONE decode block
    DEFER = "defer"             # momentarily out of slots/blocks
    DUPLICATE = "duplicate"     # (rid, payload seq) already armed

    def adopt(self, h: KVHandoff) -> str:
        """Adopt one payload; returns :data:`ADOPTED`, :data:`DEFER`
        (retry after retirements) or :data:`DUPLICATE`. The
        ``fleet.adopt`` fault site fires before any state mutates, so
        a retry is clean.

        Idempotency contract (the at-least-once wire's other half): a
        payload whose ``(rid, meta["seq"])`` was already armed — an
        ack-lost retransmit — is a NO-OP at exact refcounts: no slot,
        no block allocation, no arena write, no double-registration.
        And a payload whose ``meta["crc32"]`` does not match its
        arrays is refused loudly BEFORE any allocator state is
        touched."""
        faults.fault_point("fleet.adopt")
        if self.killed:
            raise TransportError(
                f"decode worker {self.name!r} is dead")
        h.verify_crc()                  # loud, pre-allocation
        rid = h.request_id
        seq = h.meta.get("seq")
        if (seq is not None and (rid, seq) in self._adopted) \
                or rid in self.server.results:
            # dedup by (rid, seq) while the stream is open, and by the
            # results ledger after it terminated — a straggler
            # duplicate must never re-decode a finished stream
            self.duplicate_adopts += 1
            _M_ADOPT_DUPS.inc()
            return self.DUPLICATE
        self._validate(h)
        eng = self.engine
        slot = next((i for i, s in enumerate(eng._slots) if s is None),
                    None)
        if slot is None:
            return self.DEFER
        if isinstance(eng, PagedEngine):
            ok = self._adopt_paged(h, slot)
        else:
            ok = self._adopt_dense(h, slot)
        if not ok:
            return self.DEFER
        if seq is not None:
            self._adopted.add((rid, seq))
        srv = self.server
        srv._tenant_of[rid] = h.meta["request"].get("tenant", "default")
        if srv.tracer.enabled:
            srv.tracer.start(rid)
            srv.tracer.span_begin(rid, "decode", slot=slot,
                                  adopted=True)
        _M_HANDOFFS.inc()
        return self.ADOPTED

    def _commit(self):
        """TP targets re-shard freshly adopted arrays onto their mesh
        through the same backend hook snapshot restore uses — the
        portable-redistribution half of cross-degree handoff."""
        commit = getattr(self.engine.backend, "commit_arrays", None)
        if commit is not None:
            self.engine._cache, self.engine._state = commit(
                self.engine._cache, self.engine._state)

    @staticmethod
    def _carried(meta, prompt):
        """(request, tokens) for the adopted run: a redrive payload's
        ``arrays["prompt"]`` is the re-prefilled prompt+history, so
        the request is rebuilt over the ORIGINAL prompt prefix and the
        run starts from the full carried token list — harvest then
        assembles original-prompt + every token, exactly the unfailed
        stream."""
        orig = prompt[:int(meta.get("orig_prompt_len",
                                    prompt.shape[0]))]
        req = request_from_meta(meta["request"], orig)
        toks = [int(t) for t in meta.get("tokens", [meta["tok0"]])]
        return req, toks

    def _adopt_paged(self, h: KVHandoff, slot: int) -> bool:
        eng = self.engine
        meta = h.meta
        prompt = h.arrays["prompt"]
        n_total, n_ship = meta["n_blocks"], meta["n_ship"]
        blocks = eng.manager.allocate(n_total)
        if blocks is None:
            return False
        req, toks = self._carried(meta, prompt)
        table_row = np.zeros((eng.max_blocks,), np.int32)
        table_row[:n_total] = blocks
        if self._adopt_jit is None:
            # the shared adopt scatter (prefix_cache._adopt_scatter):
            # pad rows beyond the shipped prefix write zeros into the
            # reserved trash block, so handoff adopts and prefix-fetch
            # adopts are literally the same program
            self._adopt_jit = jax.jit(_adopt_scatter,
                                      donate_argnums=(0,))
        rows = []
        for i, (shape, dtype) in enumerate(eng.backend.pool_specs):
            r = np.zeros((eng.max_blocks,) + tuple(shape[1:]),
                         np.dtype(dtype))
            r[:n_ship] = h.arrays[f"kv_{i}"]
            rows.append(r)
        eng._cache = self._adopt_jit(eng._cache, tuple(rows), table_row)
        # index the prompt's prefix blocks in THIS worker's manager so
        # the adopted copy is reusable here too (no-op for any digest
        # already registered)
        eng.manager.register_prefix(prompt, blocks)
        run = _SlotRun(req, tokens=toks,
                       t_admit=meta["t_admit"], block_ids=blocks)
        eng._slots[slot] = run
        eos = req.eos_token_id
        eng._state = eng._arm_jit(
            eng._state, jnp.int32(slot), jnp.asarray(table_row),
            jnp.int32(meta["tok0"]), jnp.int32(meta["pos0"]),
            jnp.int32(meta["rem0"]),
            jnp.int32(-1 if eos is None else eos),
            jnp.float32(req.temperature), jnp.int32(req.top_k),
            jnp.float32(req.top_p),
            jnp.asarray(np.asarray(h.arrays["key"], np.uint32)))
        self._commit()
        eng._remaining_host[slot] = meta["rem0"]
        return True

    def _adopt_dense(self, h: KVHandoff, slot: int) -> bool:
        eng = self.engine
        meta = h.meta
        prompt = h.arrays["prompt"]
        req, toks = self._carried(meta, prompt)
        Lb = meta["pos0"]
        row = []
        for i, (shape, dtype) in enumerate(eng.backend.pool_specs):
            r = np.zeros((1,) + tuple(shape[1:]), np.dtype(dtype))
            r[:, :Lb] = h.arrays[f"kv_{i}"]
            row.append(r)
        eos = req.eos_token_id
        # the stock admission program: zero new compiled programs
        eng._cache, eng._state = eng._admit_jit(
            eng._cache, eng._state, tuple(row), jnp.int32(slot),
            jnp.int32(meta["tok0"]), jnp.int32(Lb),
            jnp.int32(meta["pad0"]), jnp.int32(meta["rem0"]),
            jnp.int32(-1 if eos is None else eos),
            jnp.float32(req.temperature), jnp.int32(req.top_k),
            jnp.float32(req.top_p),
            jnp.asarray(np.asarray(h.arrays["key"], np.uint32)))
        self._commit()
        run = _SlotRun(req, tokens=toks, t_admit=meta["t_admit"])
        eng._slots[slot] = run
        eng._remaining_host[slot] = meta["rem0"]
        return True


# ---------------------------------------------------------------------------
# the fleet
# ---------------------------------------------------------------------------

class Fleet:
    """N prefill workers + M decode workers + router + transport, one
    deterministic tick loop. ``submit()`` routes by prefix affinity;
    each tick advances every prefill worker, ships ready handoffs to
    the least-loaded decode worker, adopts delivered payloads,
    advances every decode worker, then collects heartbeats and renews
    leases. ``results`` aggregates every worker's results plus
    explicit handoff failures — each submitted request ends in exactly
    one of them.

    **Failure domains** (PR 15): every worker holds a lease renewed by
    its per-tick heartbeat; a worker missing ``lease_misses``
    consecutive heartbeats is marked DEAD (flight-recorder event +
    ``pt_fleet_worker_state`` gauge) and never read again. Streams a
    dead decode worker owned are REDRIVEN from the fleet's own
    records — the submitted request, the shipped rng key, and the
    token progress carried by heartbeats — via a ``redrive``
    :class:`ResumeState`: re-prefill of prompt+tokens[:-1] on a
    surviving prefill worker (mostly prefix-index hits), then a normal
    handoff arming the carried next token and the host-replayed key,
    so the recovered stream completes BIT-IDENTICAL to an unfailed
    run (greedy AND seeded-sampled). A dead prefill worker's
    un-handed-off requests are resubmitted from the fleet's
    submission records under their original ids. Streams that cannot
    be redriven (no surviving workers, unfittable history) fail
    explicitly as ``RequestFailure(reason="worker_lost")``."""

    def __init__(self, prefill_workers: List[PrefillWorker],
                 decode_workers: List[DecodeWorker], *,
                 transport: Optional[Transport] = None,
                 affinity: Optional[bool] = None,
                 spill_depth: Optional[int] = None,
                 resilience: Optional[ResilienceConfig] = None,
                 lease_misses: Optional[int] = None,
                 prefix_cache: Optional[bool] = None,
                 evict_high: Optional[float] = None,
                 evict_low: Optional[float] = None,
                 durability: Optional[str] = None,
                 spill_max_bytes: Optional[int] = None):
        if not prefill_workers or not decode_workers:
            raise ValueError("need at least one prefill and one decode "
                             "worker")
        if lease_misses is None:
            lease_misses = env_int("PT_SERVING_FLEET_LEASE_MISSES", 3)
        if lease_misses < 1:
            raise ValueError(
                f"lease_misses={lease_misses}; must be >= 1")
        self.lease_misses = lease_misses
        self.prefill = list(prefill_workers)
        self.decode = list(decode_workers)
        for i, w in enumerate(self.prefill):
            w.name = w.name or f"prefill{i}"
            # disjoint request-id ranges: the rid a prefill worker
            # assigns IS the fleet-wide id the decode worker completes
            if w.server._next_id == 0:
                w.server._next_id = (i + 1) * 1_000_000
        for i, d in enumerate(self.decode):
            d.name = d.name or f"decode{i}"
        names = [w.name for w in self.prefill] \
            + [d.name for d in self.decode]
        if len(set(names)) != len(names):
            raise ValueError(
                f"duplicate worker names {sorted(names)} — names "
                "address transport queues, leases and assignment "
                "counters, so they must be unique")
        self._check_compat()
        self.transport = transport or InProcessTransport()
        paged = isinstance(self.prefill[0].engine, PagedEngine)
        self.router = FleetRouter(
            self.prefill[0].engine.kv_block_size if paged else 16,
            affinity=affinity, spill_depth=spill_depth)
        self.resilience = resilience or ResilienceConfig()
        self._res = ResilienceState(self.resilience)
        self.flight = FlightRecorder()
        self._failures: Dict[int, RequestFailure] = {}
        # redrive-completed streams that never re-reach a worker (the
        # carried history already held every token)
        self._local_results: Dict[int, np.ndarray] = {}
        self._pending_adopt: Dict[str, deque] = {
            d.name: deque() for d in self.decode}
        self._assigned: Dict[str, int] = {d.name: 0
                                          for d in self.decode}
        self._draining: set = set()          # prefill indices
        self._draining_decode: set = set()   # decode NAMES (stable
        # across removals, unlike indices)
        # -- failure-domain records (everything redrive needs lives on
        # THIS side of the wire) --
        # rid -> {prompt, kw, worker, t_submit}: every submission
        self._requests: Dict[int, dict] = {}
        # rid -> {dst, key0, base_len, t_admit}: every shipped handoff
        # (key0 = the rng key at ship, base_len = carried tokens then)
        self._handoffs: Dict[int, dict] = {}
        # rid -> last observed token list (heartbeat-carried)
        self._progress: Dict[int, list] = {}
        # worker name -> health record; 1 heartbeat miss tolerated per
        # missing tick, lease_misses misses = dead
        self._health: Dict[str, dict] = {
            n: {"state": "live", "misses": 0} for n in names}
        for n in names:
            _M_WORKER_STATE.set(1, worker=n)
        # -- fleet-wide prefix cache (PR 16) --
        if prefix_cache is None:
            prefix_cache = env_bool("PT_SERVING_FLEET_PREFIX_CACHE",
                                    True)
        if evict_high is None:
            evict_high = env_float("PT_SERVING_FLEET_EVICT_HIGH", 0.85)
        if evict_low is None:
            evict_low = env_float("PT_SERVING_FLEET_EVICT_LOW", 0.70)
        if not 0.0 < evict_low <= evict_high <= 1.0:
            raise ValueError(
                f"eviction watermarks need 0 < low <= high <= 1; got "
                f"low={evict_low}, high={evict_high}")
        self.prefix_cache_enabled = bool(prefix_cache) and paged
        self.evict_high, self.evict_low = float(evict_high), \
            float(evict_low)
        self.directory = PrefixCacheDirectory()
        self._fetch_seq = 0
        self._fetch_endpoints: set = set()
        self.prefix_fetches = 0
        self.prefix_fetch_blocks = 0
        self.prefix_fetch_kv_bytes: List[int] = []
        self.prefix_fetch_failures: Dict[str, int] = {}
        self.prefix_fetch_duplicates = 0
        self.prefix_evictions = 0
        if self.prefix_cache_enabled:
            for w in self.prefill:
                w.engine.prefix_fetcher = self._make_fetcher(w)
        self._handoff_seq = 0
        self.handoffs = 0
        self.handoff_wire_bytes: List[int] = []
        self.handoff_kv_bytes: List[int] = []
        self.migrations = 0
        self.redrives = 0
        self.workers_lost = 0
        self.redrive_latencies: List[float] = []
        # rid -> (detection wall time) for redriven streams still open
        self._redrive_t0: Dict[int, float] = {}
        self._clock = 0
        # -- durable control plane (PR 20) --
        self.durability_dir: Optional[str] = None
        self._dur_epoch = 0
        self._journal: Optional[_dur.WriteAheadJournal] = None
        self._spill: Optional[_dur.PrefixSpillStore] = None
        # rid -> journaled token high-water mark / terminal written
        self._journaled_progress: Dict[int, int] = {}
        self._journaled_terminals: set = set()
        self.recoveries = 0
        self.last_recovery: Optional[dict] = None
        if spill_max_bytes is None:
            spill_max_bytes = env_int("PT_SERVING_SPILL_MAX_BYTES",
                                      1 << 28)
        self._spill_max_bytes = int(spill_max_bytes)
        if durability is not None:
            self._attach_durability(durability, epoch=0)
            if self._journal.empty():
                self._jrec({"k": "genesis",
                            "prefill": [w.name for w in self.prefill],
                            "decode": [d.name for d in self.decode]})

    def _attach_durability(self, dirname: str, epoch: int):
        """Open (or reopen, in recovery) the journal segment for
        ``epoch`` and the spill tier under ``dirname``."""
        os.makedirs(dirname, exist_ok=True)
        self.durability_dir = dirname
        self._dur_epoch = int(epoch)
        self._journal = _dur.WriteAheadJournal(
            _dur.journal_path(dirname, epoch))
        if self.prefix_cache_enabled:
            self._spill = _dur.PrefixSpillStore(
                os.path.join(dirname, "spill"),
                max_bytes=self._spill_max_bytes)

    def _jrec(self, rec: dict):
        """Append one control-plane record, retrying transient
        failures with the fleet's seeded backoff. Durability is a HARD
        contract: a permanently failing journal is a crashed fleet,
        not a silently forgetful one."""
        if self._journal is None:
            return
        last = None
        for attempt in range(self.resilience.retry_attempts + 1):
            try:
                self._journal.append(rec)
                return
            except (faults.InjectedFault, OSError) as e:
                last = e
                if attempt < self.resilience.retry_attempts:
                    time.sleep(self._res.backoff_s(attempt))
        raise RuntimeError(
            f"write-ahead journal append failed past the retry "
            f"budget: {type(last).__name__}: {last}")

    def _check_compat(self):
        """Every engine in the fleet must share the KV layout — a
        payload must adopt onto ANY decode worker. Refused loudly at
        construction (and at add_decode_worker), not discovered
        mid-stream."""
        engines = [w.engine for w in self.prefill] \
            + [d.engine for d in self.decode]
        for e in engines[1:]:
            self._check_engine_compat(e, engines[0])

    @staticmethod
    def _check_engine_compat(e, first):
        paged0 = isinstance(first, PagedEngine)
        if isinstance(e, PagedEngine) != paged0:
            raise ValueError("mixed dense/paged fleet — every "
                             "worker must share the engine kind")
        if e.max_len != first.max_len:
            raise ValueError(
                f"max_len mismatch across the fleet "
                f"({e.max_len} vs {first.max_len})")
        if _leaf_specs(e.backend) != _leaf_specs(first.backend):
            raise ValueError(
                "KV leaf layout mismatch across the fleet — same "
                "model config / paging layout required")
        if paged0 and (e.kv_block_size != first.kv_block_size
                       or bool(e.kv_int8) != bool(first.kv_int8)):
            raise ValueError(
                "paged arena geometry mismatch across the fleet "
                "(block_size/kv_int8)")

    # -- submission --------------------------------------------------------
    def submit(self, prompt, max_new_tokens: int = 20,
               prefill_worker: Optional[str] = None, **kw) -> int:
        """Route and submit one request; returns the fleet-wide id
        (key into ``results``). Capacity is validated against BOTH
        pools at the door: the routed prefill worker's (inside
        ``Server.submit``) and the largest decode pool's — a request no
        decode worker could ever adopt is refused here, not deferred
        forever mid-stream. ``prefill_worker`` pins the request to a
        named routable worker, bypassing the router — the test/bench
        hook that forces a warm-REMOTE prefill (affinity would
        otherwise co-locate every same-prefix request with the warm
        copy and the fetch path would never exercise)."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        err = None
        for d in self._live_decode():
            try:
                d.engine.validate_request(int(prompt.size),
                                          max_new_tokens)
                err = None
                break
            except ValueError as e:
                err = e
        if err is not None:
            raise ValueError(f"no decode worker can serve this "
                             f"request: {err}")
        eligible = self._routable_prefill()
        if prefill_worker is not None:
            by_name = {self.prefill[i].name: i for i in eligible}
            if prefill_worker not in by_name:
                raise ValueError(
                    f"prefill worker {prefill_worker!r} is not "
                    f"routable (have {sorted(by_name)})")
            wi = by_name[prefill_worker]
        else:
            depths = [self.prefill[i].queue_depth() for i in eligible]
            warm = None
            if self.prefix_cache_enabled:
                owners = set(self.directory.owners(
                    self.router.affinity_key(prompt)))
                if owners:
                    warm = {pos for pos, i in enumerate(eligible)
                            if self.prefill[i].name in owners}
            wi = self.router.route(prompt, depths, eligible, warm=warm)
        w = self.prefill[wi]
        rid = w.server.submit(prompt, max_new_tokens=max_new_tokens,
                              **kw)
        # the submission record: with this (plus the shipped key and
        # heartbeat-carried progress) the fleet can rebuild the request
        # after ANY worker holding it dies
        self._requests[rid] = {
            "prompt": prompt.copy(), "worker": w.name,
            "t_submit": time.perf_counter(),
            "kw": dict(kw, max_new_tokens=max_new_tokens)}
        if self._journal is not None:
            self._jrec({"k": "submit", "rid": int(rid),
                        "prompt": [int(t) for t in prompt],
                        "worker": w.name,
                        "kw": {k: v for k, v in
                               self._requests[rid]["kw"].items()}})
        return rid

    # -- liveness views ----------------------------------------------------
    def _alive(self, name: str) -> bool:
        return self._health[name]["state"] == "live"

    def _live_decode(self) -> List[DecodeWorker]:
        return [d for d in self.decode if self._alive(d.name)]

    def _routable_prefill(self) -> List[int]:
        return [i for i in range(len(self.prefill))
                if i not in self._draining
                and self._alive(self.prefill[i].name)]

    # -- the tick ----------------------------------------------------------
    def _with_retry(self, fn):
        """PR 5 retry/backoff/breaker around one handoff op. Returns
        ``(ok, value)``; counts toward the fleet's consecutive-failure
        budget and trips its breaker like Server's step retries. Same
        policy loop as ``Server._with_retry`` over the same
        ``ResilienceState``, minus the per-server flight-recorder/
        tracer hooks (the fleet has neither) and plus the return
        value adopt() needs."""
        res, cfg = self._res, self.resilience
        for attempt in range(cfg.retry_attempts + 1):
            if res.breaker_open:
                return False, None
            try:
                out = fn()
                res.consecutive_failures = 0
                return True, out
            except res.transient as e:
                res.step_failures += 1
                res.consecutive_failures += 1
                res.last_error = f"{type(e).__name__}: {e}"
                if res.consecutive_failures >= cfg.breaker_threshold:
                    res.breaker_open = True
                    return False, None
                if attempt < cfg.retry_attempts:
                    res.retries += 1
                    _M_FLEET_RETRIES.inc()
                    time.sleep(res.backoff_s(attempt))
        return False, None

    def _fail_handoff(self, rid: int, reason: str, message: str,
                      tokens: int = 0):
        self._failures[rid] = RequestFailure(
            request_id=rid, reason=reason, message=message,
            tokens_emitted=tokens)
        self._res.count_failure(reason)
        _M_HANDOFF_FAILS.inc(reason=reason)

    def _pick_decode(self) -> Optional[int]:
        """Least-loaded LIVE decode worker: free slots minus payloads
        already assigned but not yet adopted; ties break low-index for
        determinism. A killed-but-undetected worker is still a target
        (the fleet cannot know yet — its payloads are redriven when
        the lease expires); a detected-dead one never is; a DRAINING
        one only when no non-draining worker survives (correct but
        dispreferred — the drain must eventually converge). None when
        the decode pool is gone entirely."""
        names = [d.name for d in self.decode]
        live = [i for i in range(len(self.decode))
                if self._alive(names[i])]
        if not live:
            return None
        routable = [i for i in live
                    if names[i] not in self._draining_decode]
        return max(routable or live,
                   key=lambda i: (self.decode[i].free_slots()
                                  - self._assigned[names[i]],
                                  -i))

    def _ship(self, w: PrefillWorker, ph: _PendingHandoff):
        rid = ph.run.request.request_id
        if self._res.breaker_open:
            w.engine.release_handoff(ph)
            self._fail_handoff(rid, "circuit_open",
                               "fleet handoff circuit open")
            return
        di = self._pick_decode()
        if di is None:
            w.engine.release_handoff(ph)
            self._fail_handoff(rid, "worker_lost",
                               "no live decode worker to ship to",
                               tokens=len(ph.run.tokens))
            return
        dst = self.decode[di].name
        self._handoff_seq += 1
        seq = self._handoff_seq
        holder = {}

        def _do():
            if "data" not in holder:          # extract + serialize
                h = w.engine.extract_handoff(ph, source=w.name)
                # payload seq (adopt's dedup key half) + arrays CRC
                # (refused loudly pre-allocation) ride the meta
                h.meta["seq"] = seq
                h.meta["crc32"] = h.payload_crc32()
                holder["h"] = h
                holder["kv"] = h.kv_bytes()
                holder["data"] = encode_handoff(h)
            self.transport.send(dst, holder["data"])

        ok, _ = self._with_retry(_do)
        if ok:
            w.engine.release_handoff(ph)
            self._assigned[dst] += 1
            self.handoffs += 1
            self.handoff_wire_bytes.append(len(holder["data"]))
            self.handoff_kv_bytes.append(holder["kv"])
            _M_HANDOFF_BYTES.inc(len(holder["data"]))
            # the redrive record: the key the slot arms with and how
            # many tokens it carried — with heartbeat progress, the
            # slot key after m more emissions is split^m(key0)
            h = holder["h"]
            toks = [int(t) for t in h.meta.get("tokens",
                                               [h.meta["tok0"]])]
            self._handoffs[rid] = {
                "dst": dst,
                "key0": np.asarray(h.arrays["key"], np.uint32),
                "base_len": len(toks), "tokens0": list(toks),
                "t_admit": float(h.meta["t_admit"])}
            self._progress[rid] = toks
            if self._journal is not None:
                self._jrec({
                    "k": "ship", "rid": int(rid), "dst": dst,
                    "seq": int(seq),
                    "key0": [int(x) for x in
                             np.asarray(h.arrays["key"],
                                        np.uint32).reshape(-1)],
                    "base_len": len(toks),
                    "tokens0": [int(t) for t in toks],
                    "t_admit": float(h.meta["t_admit"])})
                self._journaled_progress[rid] = len(toks)
        else:
            reason = "circuit_open" if self._res.breaker_open \
                else "handoff"
            w.engine.release_handoff(ph)
            self._fail_handoff(
                rid, reason,
                f"handoff to {dst} failed: {self._res.last_error}",
                tokens=len(ph.run.tokens))

    def _deliver(self, d: DecodeWorker):
        if d.killed:        # a dead process runs no receive loop; its
            return          # queued payloads redrive at lease expiry
        q = self._pending_adopt[d.name]
        while True:
            if not q:
                data = self.transport.recv(d.name)
                if data is None:
                    return
                q.append(decode_handoff(data))
            h = q[0]
            if h.request_id in self._failures:
                # an at-least-once straggler: one send attempt reached
                # the receiver, but the ship as a whole was recorded a
                # permanent failure (breaker/budget) and released the
                # prefill state. The stream's terminal already exists —
                # drop the frame, never adopt it (and never decrement
                # _assigned: a failed ship never incremented it)
                q.popleft()
                continue
            carried = len(h.meta.get("tokens", [h.meta.get("tok0")]))
            try:
                ok, status = self._with_retry(lambda: d.adopt(h))
            except ValueError as e:
                # corrupt/incompatible payload: permanent, loud, no
                # retry — the prefill side's state is long released,
                # so the stream ends in an explicit failure
                self._fail_handoff(h.request_id, "handoff",
                                   f"adopt refused: {e}",
                                   tokens=carried)
                q.popleft()
                self._assigned[d.name] -= 1
                continue
            if ok and status == DecodeWorker.ADOPTED:
                q.popleft()
                self._assigned[d.name] -= 1
                if self._journal is not None:
                    self._jrec({"k": "adopt",
                                "rid": int(h.request_id),
                                "worker": d.name,
                                "seq": int(h.meta.get("seq", 0))})
                continue
            if ok and status == DecodeWorker.DUPLICATE:
                # an ack-lost retransmit: the first copy already
                # decremented the assignment — drop silently
                q.popleft()
                continue
            if ok:                            # DEFER: retry next tick
                _M_ADOPT_DEFERS.inc()
                return
            reason = "circuit_open" if self._res.breaker_open \
                else "handoff"
            self._fail_handoff(
                h.request_id, reason,
                f"adopt on {d.name} failed: {self._res.last_error}",
                tokens=carried)
            q.popleft()
            self._assigned[d.name] -= 1

    # -- fleet-wide prefix cache: fetch / directory / eviction -------------
    def _make_fetcher(self, w: PrefillWorker):
        def _fetch(full, local_blocks):
            return self._fetch_prefix(w, full, local_blocks)
        return _fetch

    def _worker_by_name(self, name: str):
        for w in self.prefill:
            if w.name == name:
                return w
        for d in self.decode:
            if d.name == name:
                return d
        return None

    def _note_fetch_fail(self, reason: str):
        self.prefix_fetch_failures[reason] = \
            self.prefix_fetch_failures.get(reason, 0) + 1
        _pc._M_FETCH_FAILS.inc(reason=reason)

    def _drain_fetch_endpoint(self, ep: str):
        """Discard stray frames on a fetch side channel — late
        at-least-once retransmits of fetches that already concluded
        (adopted, or given up on). Left queued they would hold
        ``transport.pending()`` above zero and spin the idle loop."""
        while self.transport.recv(ep) is not None:
            self.prefix_fetch_duplicates += 1
            _pc._M_FETCH_DUPS.inc()

    def _fetch_prefix(self, w: PrefillWorker, full,
                      local_blocks) -> Optional[List[int]]:
        """One synchronous remote prefix fetch on behalf of worker
        ``w``'s admission: directory lookup → owner-side extract →
        transport round trip on ``w``'s ``#fetch`` side channel → CRC
        verify → idempotent adopt → register. Returns the adopted
        block ids, or None — and EVERY failure (dead owner, exhausted
        retry budget, stale directory, CRC mismatch, full pool, open
        breaker) is a None: the request prefills locally, it never
        fails because warm remote state was advertised."""
        eng = w.engine
        n_local = len(local_blocks)
        exclude = {w.name} | {n for n, h in self._health.items()
                              if h["state"] != "live"}
        depth, owners = self.directory.deepest_covered(
            full, eng.kv_block_size, eng.manager.hash_fn,
            exclude=exclude)
        if self._spill is not None:
            # the disk tier competes with live owners: strictly deeper
            # spilled coverage wins (tie → live owner, it is fresher);
            # ANY spill failure falls through to the remote path below
            got = self._spill_fetch(w, full, local_blocks, depth)
            if got is not None:
                return got
        if depth <= n_local:
            return None                  # nothing beyond the local match
        if self._res.breaker_open:
            self._note_fetch_fail("circuit_open")
            return None
        owner = self._worker_by_name(owners[0])
        if owner is None:
            self._note_fetch_fail("stale")
            return None
        self._fetch_seq += 1
        seq = self._fetch_seq
        ep = fetch_endpoint(w.name)
        self._fetch_endpoints.add(ep)
        holder: dict = {}

        def _do():
            faults.fault_point("fleet.fetch")
            if owner.killed:
                raise TransportError(
                    f"prefix owner {owner.name!r} is dead")
            if "data" not in holder and "stale" not in holder:
                # extract + serialize ONCE; retries resend the same
                # bytes (same discipline as _ship)
                h = extract_prefix(owner.engine, full, depth,
                                   skip=n_local, source=owner.name)
                if h is None:    # owner evicted since its last beat
                    holder["stale"] = True
                    return
                h.meta["request"] = {"request_id": -seq}
                h.meta["seq"] = seq
                h.meta["crc32"] = h.payload_crc32()
                holder["kv"] = h.kv_bytes()
                holder["data"] = encode_handoff(h)
            self.transport.send(ep, holder["data"])

        ok, _ = self._with_retry(_do)
        if holder.get("stale"):
            self._note_fetch_fail("stale")
            return None
        if not ok:
            self._note_fetch_fail("circuit_open"
                                  if self._res.breaker_open
                                  else "transport")
            # one attempt may still have delivered a frame whose ack
            # was lost — clean the side channel before falling back
            self._drain_fetch_endpoint(ep)
            return None
        fetched = None
        while True:                      # drain the side channel FULLY
            data = self.transport.recv(ep)
            if data is None:
                break
            try:
                h = decode_handoff(data)
                if h.meta.get("seq") != seq or fetched is not None:
                    # at-least-once retransmit: this fetch's duplicate
                    # or a concluded earlier fetch's straggler
                    self.prefix_fetch_duplicates += 1
                    _pc._M_FETCH_DUPS.inc()
                    continue
                h.verify_crc()           # loud, pre-allocation
            except ValueError:
                self._note_fetch_fail("corrupt")
                continue
            got = adopt_prefix(eng, h, local_blocks, full)
            if got is None:
                self._note_fetch_fail("pool_full")
                continue
            fetched = got
            self.prefix_fetches += 1
            self.prefix_fetch_blocks += len(got)
            self.prefix_fetch_kv_bytes.append(holder["kv"])
            _pc._M_FETCHES.inc()
            _pc._M_FETCH_BLOCKS.inc(len(got))
            _pc._M_FETCH_BYTES.inc(len(holder["data"]))
            self.flight.record("prefix_fetch", worker=w.name,
                               owner=owner.name, blocks=len(got),
                               clock=self._clock)
        return fetched

    def _spill_fetch(self, w: PrefillWorker, full, local_blocks,
                     dir_depth: int) -> Optional[List[int]]:
        """Serve a prefix fetch from the disk spill tier: deepest
        spilled chain on the prompt's digest path, CRC-verified,
        token-compared, re-skipped past the local match and adopted
        through the SAME scatter as a live fetch — bit-identical state
        either way. Every failure (armed ``spill.read``, unreadable
        file, CRC/collision mismatch, full pool) counts a miss and
        returns None: the caller falls back to a live owner or local
        prefill."""
        eng = w.engine
        n_local = len(local_blocks)
        sdepth, digest = self._spill.lookup(
            full, eng.kv_block_size, eng.manager.hash_fn)
        if digest is None or sdepth <= max(dir_depth, n_local):
            return None
        try:
            h = self._spill.read(digest)
        except (faults.InjectedFault, OSError, ValueError):
            self._spill.note_miss()
            self._note_fetch_fail("spill")
            return None
        bs = eng.kv_block_size
        stored = [int(t) for t in h.arrays["tokens"][:sdepth * bs]]
        if stored != [int(t) for t in full[:sdepth * bs]] \
                or int(h.meta.get("n_blocks", 0)) != sdepth:
            self._spill.note_miss()      # hash collision / stale file
            self._note_fetch_fail("spill")
            return None
        try:
            got = adopt_prefix(eng, _dur.slice_prefix_payload(
                h, n_local), local_blocks, full)
        except ValueError:
            self._spill.note_miss()      # incompatible payload
            self._note_fetch_fail("spill")
            return None
        if got is None:
            self._spill.note_miss()
            self._note_fetch_fail("pool_full")
            return None
        self._spill.note_hit()
        self.prefix_fetches += 1
        self.prefix_fetch_blocks += len(got)
        self.flight.record("prefix_spill_hit", worker=w.name,
                           blocks=len(got), depth=sdepth,
                           clock=self._clock)
        return got

    def _evict_tick(self):
        """Watermark eviction: when fleet-global block pressure (the
        fraction of usable blocks not free, summed over every live
        arena) exceeds ``evict_high``, evict LRU unreferenced
        registered blocks — most-pressured arenas first — until it is
        back at ``evict_low``. Referenced blocks are untouchable, so
        live streams never lose state; the owners' next heartbeats
        retract the evicted digests from the directory."""
        pool = [(w.engine, w.name) for w in self.prefill
                if self._alive(w.name)] \
            + [(d.engine, d.name) for d in self.decode
               if self._alive(d.name)]
        usable = sum(e.manager.usable_blocks() for e, _ in pool)
        if not usable:
            return
        free = sum(len(e.manager._free) for e, _ in pool)
        if 1.0 - free / usable <= self.evict_high:
            return
        need = int(np.ceil((1.0 - self.evict_low) * usable)) - free
        done = 0
        for e, name in sorted(pool,
                              key=lambda p: p[0].manager
                              .block_pressure(), reverse=True):
            if need <= 0:
                break
            if self._spill is not None:
                self._spill_victims(e, name, need)
            n = e.manager.evict_cached(need)
            need -= n
            done += n
        if done:
            self.prefix_evictions += done
            self.flight.record("prefix_evict", blocks=done,
                               clock=self._clock)

    def _spill_victims(self, engine, name: str, n: int):
        """Copy the chains about to be watermark-evicted from
        ``engine``'s arena into the disk spill tier — BEFORE
        ``evict_cached`` frees them, via the side-effect-free preview
        + extraction (the spill must not perturb which blocks the
        eviction then picks). Deepest chains only, deduped by prefix
        containment; a failed spill write is a lost optimization,
        never a failed eviction."""
        m = engine.manager
        victims = set(m.eviction_victims(n))
        if not victims:
            return
        tok_map = m.chain_tokens_map()
        cands = []
        for b in victims:
            d = m._digest_of.get(b)
            t = tok_map.get(d) if d is not None else None
            if t is not None:
                cands.append((m._depth.get(d, 0), d, t))
        cands.sort(key=lambda c: (-c[0], c[1]))
        kept = []
        for depth, d, t in cands:
            if any(kt[:len(t)] == t for _, _, kt in kept):
                continue            # covered by a deeper kept chain
            kept.append((depth, d, t))
        for depth, d, t in kept:
            try:
                h = _dur.extract_chain(engine, t, depth, source=name)
                if h is not None:
                    self._spill.put(d, h)
            except (faults.InjectedFault, OSError, ValueError):
                continue            # spill is best-effort by contract

    def tick(self):
        """One fleet tick: prefill advance → ship → deliver/adopt →
        decode advance → heartbeats/lease scan. Deterministic given
        the same submissions, kill schedule and fault schedule. Dead
        workers (lease expired) are skipped everywhere; killed-but-
        undetected workers simply stop making progress until their
        lease expires and their streams redrive."""
        self._clock += 1
        for w in self.prefill:
            if self._alive(w.name):
                w.tick()
        for w in self.prefill:
            if w.killed or not self._alive(w.name):
                continue        # a dead process ships nothing
            for ph in w.engine.take_handoffs():
                self._ship(w, ph)
        for d in self.decode:
            if self._alive(d.name):
                self._deliver(d)
        for d in self.decode:
            if self._alive(d.name):
                d.tick()
        self._beat()
        if self.prefix_cache_enabled:
            for ep in list(self._fetch_endpoints):
                self._drain_fetch_endpoint(ep)
            self._evict_tick()
        if self._redrive_t0:
            self._settle_redrives()
        if self._journal is not None:
            # terminals journal BEFORE the gc can drop their records —
            # a crash after gc must still know the stream concluded
            self._journal_terminals()
        if self._clock % 64 == 0:
            self._gc_records()
        if _om.enabled():
            for w in self.prefill:
                if self._alive(w.name):
                    _M_PF_DEPTH.set(w.queue_depth(), worker=w.name)
            for d in self.decode:
                if self._alive(d.name):
                    _M_DEC_FREE.set(d.free_slots(), worker=d.name)

    def busy(self) -> bool:
        return (any(w.busy() for w in self.prefill
                    if self._alive(w.name))
                or self.transport.pending() > 0
                or any(q for n, q in self._pending_adopt.items()
                       if self._alive(n))
                or any(d.busy() for d in self.decode
                       if self._alive(d.name)))

    def run_until_idle(self, max_ticks: Optional[int] = None
                       ) -> Dict[int, object]:
        ticks = 0
        while self.busy():
            if max_ticks is not None and ticks >= max_ticks:
                break
            self.tick()
            ticks += 1
        return self.results

    # -- worker health: heartbeats, leases, death --------------------------
    def _beat(self):
        """Collect every worker's heartbeat, renew leases, absorb
        decode-side token progress into the redrive records, and
        declare workers whose lease ran out dead."""
        for w in self.prefill:
            self._beat_one(w, "prefill")
        for d in self.decode:
            self._beat_one(d, "decode")

    def _beat_one(self, worker, role: str):
        h = self._health[worker.name]
        if h["state"] == "dead":
            return
        hb = worker.heartbeat()
        if hb is None:
            h["misses"] += 1
            self.flight.record("heartbeat_miss", worker=worker.name,
                               role=role, misses=h["misses"],
                               clock=self._clock)
            if h["misses"] >= self.lease_misses:
                self._declare_dead(worker, role)
            return
        h["misses"] = 0
        h["last"] = hb
        if self.prefix_cache_enabled and "prefixes" in hb:
            # the fleet.directory fault drops ONE publish: the
            # directory serves a stale view until the next beat — the
            # fetch path must degrade to stale-fallback, never corrupt
            if not faults.should_fire("fleet.directory"):
                self.directory.publish(worker.name, hb["prefixes"])
        if role == "decode":
            # progress carried by the heartbeat IS the redrive record:
            # after the worker dies, tokens generated since its last
            # beat are simply regenerated (the decode block is a pure
            # function of the carried state)
            for rid, toks in hb["progress"].items():
                if rid in self._handoffs:
                    self._progress[rid] = list(toks)
                    if self._journal is not None:
                        n0 = self._journaled_progress.get(rid, 0)
                        if len(toks) > n0:
                            # high-water marks journal as DELTAS; the
                            # only-extend replay guard makes them
                            # idempotent over a newer manifest
                            self._jrec({
                                "k": "progress", "rid": int(rid),
                                "base": int(n0),
                                "ext": [int(t)
                                        for t in toks[n0:]]})
                            self._journaled_progress[rid] = len(toks)

    def _declare_dead(self, worker, role: str):
        h = self._health[worker.name]
        h["state"] = "dead"
        self.workers_lost += 1
        _M_WORKERS_LOST.inc(role=role)
        _M_WORKER_STATE.set(0, worker=worker.name)
        self.flight.record("worker_dead", worker=worker.name,
                           role=role, clock=self._clock,
                           lease_misses=self.lease_misses)
        # the dead worker's directory entries expire with its lease —
        # later fetches stop considering it immediately
        self.directory.drop_worker(worker.name)
        if self._journal is not None:
            # recovery must NOT restore a worker that died post-
            # checkpoint: its streams redrive below, producing fresh
            # ship records the restored corpse would conflict with
            self._jrec({"k": "scale", "action": "dead",
                        "worker": worker.name, "role": role})
        if role == "decode":
            self._recover_decode_streams(worker)
        else:
            self._recover_prefill_streams(worker)

    def kill_decode_worker(self, idx: int):
        """Test/chaos hook: kill decode worker ``idx`` (the worker
        stops participating; the fleet notices via the lease and
        redrives its streams ``lease_misses`` ticks later)."""
        self.decode[idx].kill()

    def kill_prefill_worker(self, idx: int):
        self.prefill[idx].kill()

    # -- redrive: streams lost with a dead worker --------------------------
    def _terminal(self, rid: int) -> bool:
        return (rid in self._failures or rid in self._local_results
                or any(rid in w.server.results for w in self.prefill)
                or any(rid in d.server.results for d in self.decode))

    def _terminal_value(self, rid: int):
        """The terminal row/failure for ``rid``, or None while the
        stream is still open."""
        if rid in self._failures:
            return self._failures[rid]
        if rid in self._local_results:
            return self._local_results[rid]
        for w in self.prefill:
            v = w.server.results.get(rid)
            if v is not None:
                return v
        for d in self.decode:
            v = d.server.results.get(rid)
            if v is not None:
                return v
        return None

    def _journal_terminals(self):
        """Journal every terminal not yet written: completed ROWS ride
        the journal (first-write-wins), so finished results survive a
        whole-process crash without re-decoding — the worker results
        ledgers live in hub memory otherwise."""
        for rid in list(self._requests):
            if rid in self._journaled_terminals:
                continue
            v = self._terminal_value(rid)
            if v is None:
                continue
            if isinstance(v, RequestFailure):
                self._jrec({"k": "terminal", "rid": int(rid),
                            "failure": {
                                "reason": v.reason,
                                "message": v.message,
                                "tokens_emitted":
                                    int(v.tokens_emitted)}})
            else:
                self._jrec({"k": "terminal", "rid": int(rid),
                            "tokens": [int(t)
                                       for t in np.asarray(v)
                                       .reshape(-1)]})
            self._journaled_terminals.add(rid)
            self._journaled_progress.pop(rid, None)

    def _recover_decode_streams(self, d: DecodeWorker):
        """Every stream the dead decode worker owned — adopted,
        in-flight on the wire, or queued for adoption — is redriven
        from the fleet's records. The corpse's ENGINE state is never
        read: completed results count as terminal because they were
        DELIVERED at harvest (see DecodeWorker.kill); everything else
        reconstructs from the submission record + shipped key +
        heartbeat progress."""
        self.transport.drop_endpoint(d.name)
        self._pending_adopt[d.name].clear()
        self._assigned[d.name] = 0
        lost = [rid for rid, rec in self._handoffs.items()
                if rec["dst"] == d.name and not self._terminal(rid)]
        for rid in sorted(lost):
            self._redrive(rid)

    def _recover_prefill_streams(self, w: PrefillWorker):
        """A dead prefill worker's un-handed-off requests (queued,
        mid-prefill, or parked in its outbox) resubmit from the
        fleet's submission records under their ORIGINAL ids — nothing
        was lost but compute, so a fresh prefill on a surviving worker
        regenerates the identical stream."""
        ep = fetch_endpoint(w.name)
        self.transport.drop_endpoint(ep)
        self._fetch_endpoints.discard(ep)
        lost = [rid for rid, rec in self._requests.items()
                if rec["worker"] == w.name and rid not in self._handoffs
                and not self._terminal(rid)]
        for rid in sorted(lost):
            self._reinject(rid, resume=None)

    def _request_from_record(self, rid: int, resume) -> Request:
        rec = self._requests[rid]
        kw = rec["kw"]
        return Request(
            request_id=rid, prompt=rec["prompt"],
            max_new_tokens=kw.get("max_new_tokens", 20),
            temperature=kw.get("temperature", 0.0),
            top_k=kw.get("top_k", 0), top_p=kw.get("top_p", 1.0),
            eos_token_id=kw.get("eos_token_id"),
            seed=kw.get("seed", 0), t_submit=rec["t_submit"],
            deadline_ticks=kw.get("deadline_ticks"),
            deadline_s=kw.get("deadline_s"),
            tenant=kw.get("tenant", "default"),
            priority=kw.get("priority", 0), resume=resume)

    def _reinject(self, rid: int, resume) -> bool:
        """Route a reconstructed request to a surviving prefill worker
        under its original id. False = nowhere to go / cannot fit —
        the stream fails explicitly as ``worker_lost``."""
        if rid not in self._requests:
            self._fail_handoff(rid, "worker_lost",
                               "no submission record to redrive from")
            return False
        eligible = self._routable_prefill()
        if not eligible:
            self._fail_handoff(rid, "worker_lost",
                               "no surviving prefill worker to "
                               "redrive on")
            return False
        rec = self._requests[rid]
        req = self._request_from_record(rid, resume)
        pl = int(rec["prompt"].size)
        mnt = req.max_new_tokens
        if resume is not None and resume.tokens:
            pl += len(resume.tokens) - 1
            mnt = req.max_new_tokens - len(resume.tokens) + 1
        depths = [self.prefill[i].queue_depth() for i in eligible]
        wi = self.router.route(rec["prompt"], depths, eligible)
        w = self.prefill[wi]
        try:
            # the re-prefill must fit the TARGET engine (dense: the
            # history may outgrow the original bucket)
            w.engine.validate_request(pl, mnt)
        except ValueError as e:
            self._fail_handoff(rid, "worker_lost",
                               f"redrive does not fit {w.name}: {e}",
                               tokens=len(resume.tokens)
                               if resume else 0)
            return False
        # visible on the target's own clock immediately; rec["worker"]
        # moves so a second failure redrives from the right place
        req.arrival_step = w.server._clock
        rec["worker"] = w.name
        w.server.inject(req)
        self.flight.record("redrive", rid=rid, to=w.name,
                           carried_tokens=len(resume.tokens)
                           if resume else 0, clock=self._clock)
        return True

    def _redrive(self, rid: int):
        """Rebuild one lost stream: carried tokens from the last
        heartbeat, the rng key host-replayed from the shipped key
        (one split per observed token — the decode block's schedule),
        and the PR 13 resume path doing the rest. The redriven stream
        completes BIT-IDENTICAL to an unfailed run."""
        hrec = self._handoffs.pop(rid)
        toks = [int(t)
                for t in self._progress.pop(rid, hrec["tokens0"])]
        self._redrive_t0[rid] = time.perf_counter()
        key = _replay_key(hrec["key0"], len(toks) - hrec["base_len"])
        resume = ResumeState(tokens=toks, key=key,
                             t_admit=hrec["t_admit"], redrive=True)
        rec = self._requests.get(rid)
        if rec is not None:
            kw = rec["kw"]
            eos = kw.get("eos_token_id")
            done = (len(toks) >= kw.get("max_new_tokens", 20)
                    or (eos is not None and toks[-1] == eos))
            if done:
                # the carried history already holds every token (the
                # worker died between producing the last token and
                # harvesting it): complete locally, eos-padded to
                # max_new exactly like Server._harvest
                out = list(toks)
                mn = kw.get("max_new_tokens", 20)
                if len(out) < mn:
                    out += [eos] * (mn - len(out))
                self._local_results[rid] = np.concatenate(
                    [rec["prompt"],
                     np.asarray(out, np.int32)]).astype(np.int32)
                self.redrives += 1
                _M_REDRIVES.inc()
                return
        if self._reinject(rid, resume):
            self.redrives += 1
            _M_REDRIVES.inc()

    def _gc_records(self):
        """Drop failure-domain records of streams that reached a
        terminal (amortized: every 64 ticks) — a long-lived fleet must
        not hold every prompt it ever served. Open streams' records
        are untouchable: they ARE the redrive substrate."""
        done = [rid for rid in self._requests if self._terminal(rid)]
        for rid in done:
            self._requests.pop(rid, None)
            self._handoffs.pop(rid, None)
            self._progress.pop(rid, None)
            self._journaled_progress.pop(rid, None)
        self._journaled_terminals &= set(self._requests)

    def _settle_redrives(self):
        """Close the redrive-latency clock for redriven streams that
        reached a terminal (the bench's recovery-latency numbers)."""
        for rid in list(self._redrive_t0):
            if self._terminal(rid):
                self.redrive_latencies.append(
                    time.perf_counter() - self._redrive_t0.pop(rid))

    # -- results / stats ---------------------------------------------------
    @property
    def results(self) -> Dict[int, object]:
        out: Dict[int, object] = {}
        for w in self.prefill:
            out.update(w.server.results)
        for d in self.decode:
            out.update(d.server.results)
        out.update(self._local_results)
        out.update(self._failures)
        return out

    def prefix_hit_rate(self) -> float:
        """Fleet-wide prefix-cache hit rate: shared / submitted prompt
        tokens summed over every prefill worker (0.0 on dense fleets,
        which have no prefix index)."""
        pt = sum(getattr(w.engine, "prompt_tokens", 0)
                 for w in self.prefill)
        st = sum(getattr(w.engine, "shared_tokens", 0)
                 for w in self.prefill)
        return st / pt if pt else 0.0

    def stats(self) -> dict:
        res = self.results
        completed = sum(1 for v in res.values()
                        if not isinstance(v, RequestFailure))
        wire = self.handoff_wire_bytes
        kv = self.handoff_kv_bytes
        return {
            "requests_completed": completed,
            "requests_failed": len(res) - completed,
            "handoffs": self.handoffs,
            "handoff_wire_bytes_mean": round(float(np.mean(wire)), 1)
            if wire else 0.0,
            "handoff_kv_bytes_mean": round(float(np.mean(kv)), 1)
            if kv else 0.0,
            "handoff_failures": dict(self._res.failures_by_reason),
            "handoff_retries": self._res.retries,
            "breaker_open": self._res.breaker_open,
            "affinity_routes": self.router.affinity_routes,
            "spillovers": self.router.spillovers,
            "prefix_hit_rate": round(self.prefix_hit_rate(), 4),
            "prefix_fetches": self.prefix_fetches,
            "prefix_fetch_blocks": self.prefix_fetch_blocks,
            "prefix_fetch_kv_bytes_mean": round(float(np.mean(
                self.prefix_fetch_kv_bytes)), 1)
            if self.prefix_fetch_kv_bytes else 0.0,
            "prefix_fetch_failures": dict(self.prefix_fetch_failures),
            "prefix_fetch_duplicates": self.prefix_fetch_duplicates,
            "prefix_evictions": self.prefix_evictions,
            "prefix_directory": self.directory.stats()
            if self.prefix_cache_enabled else None,
            "migrations": self.migrations,
            "ticks": self._clock,
            "lease_misses": self.lease_misses,
            "workers_lost": self.workers_lost,
            "redrives": self.redrives,
            "redrive_latency_p50_s": round(float(np.percentile(
                self.redrive_latencies, 50)), 4)
            if self.redrive_latencies else None,
            "redrive_latency_p95_s": round(float(np.percentile(
                self.redrive_latencies, 95)), 4)
            if self.redrive_latencies else None,
            "duplicate_adopts": sum(d.duplicate_adopts
                                    for d in self.decode),
            "worker_states": {n: h["state"]
                              for n, h in sorted(self._health.items())},
            "transport": self.transport.stats()
            if hasattr(self.transport, "stats") else None,
            "durability": None if self.durability_dir is None else {
                "dir": self.durability_dir,
                "epoch": self._dur_epoch,
                "journal_seq": self._journal.seq,
                "journal_appends": self._journal.appends,
                "journal_bytes": self._journal.bytes_written,
                "recoveries": self.recoveries,
                "last_recovery": self.last_recovery,
                "spill": self._spill.stats()
                if self._spill is not None else None},
            "prefill_workers": [
                {"name": w.name, "state": self._health[w.name]["state"],
                 "queue": w.queue_depth(),
                 "tokens_emitted": w.engine.tokens_emitted,
                 "block_pressure": round(
                     w.engine.manager.block_pressure(), 4)
                 if hasattr(w.engine, "manager") else 0.0,
                 "prefill_compiles": w.engine.prefill_compile_count()
                 if hasattr(w.engine, "prefill_compile_count") else 1}
                for w in self.prefill],
            "decode_workers": [
                {"name": d.name, "state": self._health[d.name]["state"],
                 "free_slots": d.free_slots(),
                 "draining": d.name in self._draining_decode,
                 "tokens_emitted": d.engine.tokens_emitted,
                 "block_pressure": round(
                     d.engine.manager.block_pressure(), 4)
                 if hasattr(d.engine, "manager") else 0.0,
                 "decode_compiles": d.engine.decode_compile_count()}
                for d in self.decode],
        }

    # -- durable control plane: checkpoint / recover (PR 20) ---------------
    def checkpoint(self) -> str:
        """Coordinated fleet checkpoint at a tick boundary: snapshot
        every live worker's Server (the PR 5 npz path — un-shipped
        outboxes now ride it), then commit fleet registries + topology
        + the flight ring ATOMICALLY by renaming the epoch manifest
        into place. The rename is THE commit: only after it does the
        journal rotate to a fresh segment (the old one is fully
        absorbed) and stale epochs get pruned. A crash anywhere in
        between recovers from the previous epoch's manifest+journal —
        every window is covered. Returns the manifest path."""
        if self.durability_dir is None:
            raise RuntimeError(
                "fleet has no durability directory — construct with "
                "durability=<dir> to enable checkpoints")
        d = self.durability_dir
        epoch = self._dur_epoch + 1
        # the checkpoint event goes into the ring BEFORE capture so
        # the recovered fleet's history includes it (PR 6 contract)
        self.flight.record("checkpoint", epoch=epoch,
                           clock=self._clock)
        workers = []
        for i, w in enumerate(self.prefill):
            if w.killed or not self._alive(w.name):
                continue        # a corpse's state is unreadable by
            snap = os.path.basename(    # contract; its streams redrive
                _dur.snapshot_path(d, epoch, w.name))
            w.server.snapshot(os.path.join(d, snap))
            workers.append({"name": w.name, "role": "prefill",
                            "snapshot": snap,
                            "draining": i in self._draining})
        for dw in self.decode:
            if dw.killed or not self._alive(dw.name):
                continue
            snap = os.path.basename(
                _dur.snapshot_path(d, epoch, dw.name))
            dw.server.snapshot(os.path.join(d, snap))
            workers.append({"name": dw.name, "role": "decode",
                            "snapshot": snap,
                            "draining":
                                dw.name in self._draining_decode})
        manifest = {
            "clock": self._clock,
            "workers": workers,
            "requests": {str(rid): {
                "prompt": [int(t) for t in rec["prompt"]],
                "worker": rec["worker"], "kw": dict(rec["kw"])}
                for rid, rec in self._requests.items()},
            "handoffs": {str(rid): {
                "dst": h["dst"],
                "key0": [int(x) for x in
                         np.asarray(h["key0"]).reshape(-1)],
                "base_len": int(h["base_len"]),
                "tokens0": [int(t) for t in h["tokens0"]],
                "t_admit": float(h["t_admit"])}
                for rid, h in self._handoffs.items()},
            "progress": {str(rid): [int(t) for t in toks]
                         for rid, toks in self._progress.items()},
            "failures": {str(rid): {
                "reason": f.reason, "message": f.message,
                "tokens_emitted": int(f.tokens_emitted)}
                for rid, f in self._failures.items()},
            "local_results": {str(rid): [int(t) for t in
                                         np.asarray(v).reshape(-1)]
                              for rid, v in
                              self._local_results.items()},
            "router": {"affinity_routes": self.router.affinity_routes,
                       "spillovers": self.router.spillovers},
            "handoff_seq": self._handoff_seq,
            "fetch_seq": self._fetch_seq,
            "counters": {
                "handoffs": self.handoffs,
                "migrations": self.migrations,
                "redrives": self.redrives,
                "workers_lost": self.workers_lost,
                "prefix_evictions": self.prefix_evictions,
                "prefix_fetches": self.prefix_fetches,
                "prefix_fetch_blocks": self.prefix_fetch_blocks,
                "recoveries": self.recoveries},
            "flight": self.flight.to_meta(),
        }
        path = _dur.write_manifest(d, epoch, manifest)
        # the commit landed: rotate to the fresh segment and remember
        # what the manifest already absorbed so nothing re-journals
        self._journal.close()
        self._attach_durability(d, epoch)
        self._journaled_terminals = {
            rid for rid in self._requests if self._terminal(rid)}
        self._journaled_progress = {
            rid: len(toks) for rid, toks in self._progress.items()}
        self._prune_durability(epoch)
        return path

    def _prune_durability(self, keep_epoch: int):
        """Delete manifests/journals/snapshots of epochs older than
        ``keep_epoch`` — including orphans of checkpoints that crashed
        before their commit."""
        d = self.durability_dir
        for name in os.listdir(d):
            for pfx in ("manifest-", "journal-", "ckpt-"):
                if not name.startswith(pfx):
                    continue
                stem = name[len(pfx):].split("-", 1)[0] \
                    .split(".", 1)[0]
                if stem.isdigit() and int(stem) < keep_epoch:
                    try:
                        os.remove(os.path.join(d, name))
                    except OSError:
                        pass

    @classmethod
    def recover(cls, dirname: str, *, engine_factory,
                transport: Optional[Transport] = None,
                **fleet_kw) -> "Fleet":
        """Cold-start recovery of a whole killed fleet: load the
        newest VALID manifest (torn ones discarded loudly), replay the
        journal tail (torn tail truncated loudly), rebuild every
        worker via ``engine_factory(role, name)`` + ``Server.restore``,
        purge streams the journal knows concluded, and REDRIVE every
        stream that was in flight — queued, mid-prefill, shipped-in-
        transit, adopted — with the PR 15 host-replayed key machinery,
        so completed rows are BIT-IDENTICAL to an uncrashed run. The
        recovered fleet continues journaling into the same epoch
        segment."""
        epoch, manifest = _dur.load_latest_manifest(dirname)
        if manifest is None:
            epochs = _dur.list_epochs(dirname, "journal")
            if not epochs:
                raise FileNotFoundError(
                    f"no checkpoint manifest or journal under "
                    f"{dirname!r} — nothing to recover")
            epoch = epochs[-1]
        records, torn = _dur.WriteAheadJournal.replay(
            _dur.journal_path(dirname, epoch))
        # -- topology: manifest workers (or journal genesis), then the
        # journal's scale/death records applied in order --
        if manifest is not None:
            spec = [dict(e) for e in manifest["workers"]]
        else:
            gen = next((r for r in records
                        if r.get("k") == "genesis"), None)
            if gen is None:
                raise RuntimeError(
                    f"journal epoch {epoch} has no genesis record and "
                    "no manifest — cannot derive the fleet topology")
            spec = [{"name": n, "role": "prefill", "snapshot": None,
                     "draining": False} for n in gen["prefill"]] \
                + [{"name": n, "role": "decode", "snapshot": None,
                    "draining": False} for n in gen["decode"]]
        for r in records:
            if r.get("k") != "scale":
                continue
            a, n = r["action"], r["worker"]
            if a == "add_decode":
                spec.append({"name": n, "role": "decode",
                             "snapshot": None, "draining": False})
            elif a in ("remove_decode", "remove_prefill", "dead"):
                spec = [e for e in spec if e["name"] != n]
            elif a in ("drain_decode", "drain_prefill"):
                for e in spec:
                    if e["name"] == n:
                        e["draining"] = True
            elif a == "undrain_decode":
                for e in spec:
                    if e["name"] == n:
                        e["draining"] = False
        pws: List[PrefillWorker] = []
        dws: List[DecodeWorker] = []
        for e in spec:
            eng = engine_factory(e["role"], e["name"])
            srv = None
            if e.get("snapshot"):
                srv = Server.restore(
                    os.path.join(dirname, e["snapshot"]), eng)
            if e["role"] == "prefill":
                pws.append(PrefillWorker(eng, name=e["name"],
                                         server=srv))
            else:
                dws.append(DecodeWorker(eng, name=e["name"],
                                        server=srv))
        fleet = cls(pws, dws, transport=transport, **fleet_kw)
        # -- registries: manifest base, then the journal overlay
        # applied sequentially (idempotent: progress only extends,
        # terminals first-write-wins) --
        if manifest is not None:
            fleet._clock = int(manifest.get("clock", 0))
            now = time.perf_counter()
            for rid_s, m in manifest["requests"].items():
                fleet._requests[int(rid_s)] = {
                    "prompt": np.asarray(m["prompt"], np.int32),
                    "worker": m["worker"], "t_submit": now,
                    "kw": dict(m["kw"])}
            for rid_s, m in manifest["handoffs"].items():
                fleet._handoffs[int(rid_s)] = {
                    "dst": m["dst"],
                    "key0": np.asarray(m["key0"], np.uint32),
                    "base_len": int(m["base_len"]),
                    "tokens0": list(m["tokens0"]),
                    "t_admit": float(m["t_admit"])}
            fleet._progress = {int(r): list(t) for r, t in
                               manifest["progress"].items()}
            for rid_s, m in manifest["failures"].items():
                rid = int(rid_s)
                fleet._failures[rid] = RequestFailure(
                    request_id=rid, reason=m["reason"],
                    message=m["message"],
                    tokens_emitted=int(m["tokens_emitted"]))
            fleet._local_results = {
                int(r): np.asarray(t, np.int32)
                for r, t in manifest["local_results"].items()}
            fleet.router.affinity_routes = \
                int(manifest["router"]["affinity_routes"])
            fleet.router.spillovers = \
                int(manifest["router"]["spillovers"])
            fleet._handoff_seq = int(manifest["handoff_seq"])
            fleet._fetch_seq = int(manifest["fetch_seq"])
            c = manifest.get("counters", {})
            fleet.handoffs = int(c.get("handoffs", 0))
            fleet.migrations = int(c.get("migrations", 0))
            fleet.redrives = int(c.get("redrives", 0))
            fleet.workers_lost = int(c.get("workers_lost", 0))
            fleet.prefix_evictions = int(c.get("prefix_evictions", 0))
            fleet.prefix_fetches = int(c.get("prefix_fetches", 0))
            fleet.prefix_fetch_blocks = \
                int(c.get("prefix_fetch_blocks", 0))
            fleet.recoveries = int(c.get("recoveries", 0))
            # the fleet flight ring survives the crash with continuing
            # seqs — the same contract PR 6 pinned for Server
            fleet.flight.restore_meta(manifest["flight"])
        name_to_pi = {w.name: i for i, w in enumerate(fleet.prefill)}
        for e in spec:
            if e.get("draining"):
                if e["role"] == "prefill":
                    fleet._draining.add(name_to_pi[e["name"]])
                else:
                    fleet._draining_decode.add(e["name"])
        now = time.perf_counter()
        for r in records:
            k = r.get("k")
            if k == "submit":
                fleet._requests[int(r["rid"])] = {
                    "prompt": np.asarray(r["prompt"], np.int32),
                    "worker": r["worker"], "t_submit": now,
                    "kw": dict(r["kw"])}
            elif k == "ship":
                rid = int(r["rid"])
                fleet._handoffs[rid] = {
                    "dst": r["dst"],
                    "key0": np.asarray(r["key0"], np.uint32),
                    "base_len": int(r["base_len"]),
                    "tokens0": list(r["tokens0"]),
                    "t_admit": float(r["t_admit"])}
                fleet._progress[rid] = list(r["tokens0"])
                fleet._handoff_seq = max(fleet._handoff_seq,
                                         int(r["seq"]))
            elif k == "progress":
                rid = int(r["rid"])
                cur = fleet._progress.get(rid)
                base = int(r["base"])
                if cur is None or base > len(cur):
                    continue        # its ship record fell in a torn
                cand = cur[:base] + list(r["ext"])      # tail — the
                if len(cand) > len(cur):    # redrive uses what stands
                    fleet._progress[rid] = cand
            elif k == "terminal":
                rid = int(r["rid"])
                if fleet._terminal(rid):
                    continue                # first write wins
                if "failure" in r:
                    f = r["failure"]
                    fleet._failures[rid] = RequestFailure(
                        request_id=rid, reason=f["reason"],
                        message=f["message"],
                        tokens_emitted=int(f["tokens_emitted"]))
                else:
                    fleet._local_results[rid] = np.asarray(
                        r["tokens"], np.int32)
        # fresh submissions must never reuse a pre-crash rid: bump
        # every prefill server's allocator past the ids its range is
        # known to have issued (snapshots cover their own, but rids
        # issued AFTER the checkpoint only exist in the journal)
        known = set(fleet._requests) | set(fleet._failures) \
            | set(fleet._local_results)
        for i, w in enumerate(fleet.prefill):
            base, hi = (i + 1) * 1_000_000, (i + 2) * 1_000_000
            mx = max((rid for rid in known if base <= rid < hi),
                     default=None)
            if mx is not None and w.server._next_id <= mx:
                w.server._next_id = mx + 1
        fleet._attach_durability(dirname, epoch)
        fleet._journaled_progress = {
            rid: len(toks) for rid, toks in fleet._progress.items()}
        fleet._journaled_terminals = {
            rid for rid in fleet._requests if fleet._terminal(rid)}
        # -- purge: streams the control plane knows concluded must not
        # decode again on a restored worker (exactly ONE terminal per
        # request across pre- and post-crash traces) --
        fleet._purge_terminal_streams()
        # -- redrive: everything in flight that no restored worker
        # owns reconstructs from the records, exactly as if the owner
        # alone had died (PR 15) --
        owned = fleet._owned_rids()
        redriven = 0
        for rid in sorted(fleet._requests):
            if rid in owned or fleet._terminal(rid):
                continue
            redriven += 1
            if rid in fleet._handoffs:
                fleet._redrive(rid)
            else:
                fleet._reinject(rid, None)
        fleet.recoveries += 1
        fleet.last_recovery = {
            "epoch": int(epoch), "replayed": len(records),
            "torn_tail": bool(torn), "redriven": redriven,
            "workers": len(spec)}
        _dur._M_J_REPLAYS.inc(len(records))
        _dur._M_CKPT_RECOVERIES.inc()
        fleet.flight.record("recovered", epoch=int(epoch),
                            clock=fleet._clock,
                            replayed=len(records), redriven=redriven)
        return fleet

    def _owned_rids(self) -> set:
        """Every rid a restored worker holds live — queued, mid-
        prefill, parked in an outbox (an outbox run occupies its
        slot), or decoding. Owned streams finish on their own,
        bit-identically: the decode block is a pure function of the
        restored state."""
        owned = set()
        for worker in list(self.prefill) + list(self.decode):
            for r in worker.server.scheduler._queue:
                owned.add(r.request_id)
            for _slot, run in worker.engine.live_runs():
                owned.add(run.request.request_id)
        return owned

    def _purge_terminal_streams(self):
        done = [rid for rid in self._requests if self._terminal(rid)]
        for rid in done:
            for w in self.prefill:
                self._purge_from_worker(w, rid, prefill=True)
            for d in self.decode:
                self._purge_from_worker(d, rid, prefill=False)

    def _purge_from_worker(self, worker, rid: int, prefill: bool):
        """Remove every live trace of a concluded stream from a
        restored worker: queue entry, outbox hold, occupied slot —
        and the cancel artifact itself, so the server never harvests
        a SECOND terminal for the rid."""
        eng = worker.engine
        worker.server.scheduler.drop_where(
            lambda r: r.request_id == rid)
        if prefill:
            for ph in list(eng._outbox):
                if ph.run.request.request_id == rid:
                    eng._outbox.remove(ph)
                    eng.release_handoff(ph)
        for slot, run in eng.live_runs():
            if run.request.request_id == rid:
                eng.cancel_slot(slot, "recovered_terminal")
        eng._finished = [r for r in eng._finished
                         if r.request.request_id != rid]

    # -- scale / migration -------------------------------------------------
    def add_decode_worker(self, worker: DecodeWorker):
        """Scale up the decode pool mid-stream; the least-loaded pick
        starts routing payloads to it on the next tick. Same
        compatibility contract as construction — an incompatible
        engine is refused here, not discovered when a payload fails to
        adopt mid-stream. The ``fleet.scale`` fault site fires BEFORE
        any state mutates, so a transiently-failed scale action
        retries cleanly under the PR 5 policy."""
        faults.fault_point("fleet.scale")
        self._check_engine_compat(worker.engine,
                                  self.prefill[0].engine)
        worker.name = worker.name or f"decode{len(self.decode)}"
        if worker.name in self._health:
            raise ValueError(f"decode worker name {worker.name!r} "
                             "already in the fleet")
        self.decode.append(worker)
        self._pending_adopt[worker.name] = deque()
        self._assigned[worker.name] = 0
        self._health[worker.name] = {"state": "live", "misses": 0}
        _M_WORKER_STATE.set(1, worker=worker.name)
        if self._journal is not None:
            self._jrec({"k": "scale", "action": "add_decode",
                        "worker": worker.name})

    def drain_decode_worker(self, idx: int):
        """Stop routing new handoffs to decode worker ``idx``; its
        in-flight streams finish in place (bit-identical — nothing
        about their state moves), and once idle it can be removed.
        Idempotent; refuses to drain the last routable decode
        worker. The ``fleet.scale`` fault site covers it like every
        scale action."""
        faults.fault_point("fleet.scale")
        if not 0 <= idx < len(self.decode):
            raise ValueError(f"no decode worker at index {idx}")
        name = self.decode[idx].name
        if name in self._draining_decode:
            return
        routable = [d.name for d in self._live_decode()
                    if d.name not in self._draining_decode
                    and d.name != name]
        if not routable:
            raise ValueError("cannot drain the last routable decode "
                             "worker")
        self._draining_decode.add(name)
        self.flight.record("decode_drain", worker=name,
                           clock=self._clock)
        if self._journal is not None:
            self._jrec({"k": "scale", "action": "drain_decode",
                        "worker": name})

    def undrain_decode_worker(self, idx: int):
        """Cancel a pending drain — the cheap scale-up when traffic
        returns before the drain converged (no fresh engine, no new
        programs; the worker simply becomes routable again)."""
        faults.fault_point("fleet.scale")
        if not 0 <= idx < len(self.decode):
            raise ValueError(f"no decode worker at index {idx}")
        name = self.decode[idx].name
        if name in self._draining_decode:
            self._draining_decode.discard(name)
            self.flight.record("decode_undrain", worker=name,
                               clock=self._clock)
            if self._journal is not None:
                self._jrec({"k": "scale", "action": "undrain_decode",
                            "worker": name})

    def remove_decode_worker(self, idx: int) -> DecodeWorker:
        """Scale down: remove a DRAINED decode worker. Refused while
        the worker still owns streams (busy slots, queued adoptions,
        or payloads assigned on the wire) — drain first and run the
        fleet until it empties. Dead workers are not removable: their
        tombstones keep the name reserved and the lease history
        readable."""
        faults.fault_point("fleet.scale")
        if not 0 <= idx < len(self.decode):
            raise ValueError(f"no decode worker at index {idx}")
        d = self.decode[idx]
        if not self._alive(d.name):
            raise RuntimeError(
                f"decode worker {d.name!r} is dead — its streams "
                "were redriven and its tombstone stays")
        if len(self._live_decode()) < 2:
            raise ValueError("cannot remove the last live decode "
                             "worker")
        if (d.busy() or self._pending_adopt[d.name]
                or self._assigned[d.name]):
            raise RuntimeError(
                f"decode worker {d.name!r} still owns streams — "
                "drain and run the fleet idle first")
        # results are fleet-durable: streams the worker completed must
        # survive its removal (scale-down would otherwise lose them)
        self._local_results.update(d.server.results)
        self.decode.pop(idx)
        self._draining_decode.discard(d.name)
        self._pending_adopt.pop(d.name, None)
        self._assigned.pop(d.name, None)
        self._health.pop(d.name, None)
        self.directory.drop_worker(d.name)
        self.transport.drop_endpoint(d.name)
        _M_WORKER_STATE.set(0, worker=d.name)
        self.flight.record("decode_remove", worker=d.name,
                           clock=self._clock)
        if self._journal is not None:
            # completed results move into _local_results above; the
            # terminal scan journals any not yet written, so removal
            # never loses a result across a crash
            self._journal_terminals()
            self._jrec({"k": "scale", "action": "remove_decode",
                        "worker": d.name})
        return d

    def migrate_decode_worker(self, idx: int, engine,
                              path: str) -> DecodeWorker:
        """Live migration = PR 5 snapshot/restore: snapshot worker
        ``idx``'s Server at a tick boundary, restore into a freshly
        constructed engine of the same configuration, and swap it into
        the fleet under the SAME name (in-transit payloads addressed to
        it deliver to the successor). Every in-flight stream finishes
        bit-identical — the decode block is a pure function of the
        restored state."""
        old = self.decode[idx]
        if old.killed or not self._alive(old.name):
            raise RuntimeError(
                "cannot migrate a dead worker — its state is "
                "unreadable by contract; its streams redrive instead")
        old.server.snapshot(path)
        srv = Server.restore(path, engine)
        new = DecodeWorker(engine, name=old.name, server=srv)
        new._adopted = set(old._adopted)     # the dedup history moves
        self.decode[idx] = new               # with the identity
        self._health[old.name] = {"state": "live", "misses": 0}
        _M_WORKER_STATE.set(1, worker=old.name)
        self.migrations += 1
        _M_MIGRATIONS.inc()
        return new

    def drain_prefill_worker(self, idx: int):
        """Stop routing new work to prefill worker ``idx``; once idle
        (queue drained, outbox shipped) it can be removed or
        snapshotted for migration. Idempotent — re-draining a draining
        worker is a no-op, not a spurious last-worker refusal."""
        if not 0 <= idx < len(self.prefill):
            raise ValueError(f"no prefill worker at index {idx}")
        if idx in self._draining:
            return
        if len([i for i in self._routable_prefill() if i != idx]) < 1:
            raise ValueError("cannot drain the last routable prefill "
                             "worker")
        self._draining.add(idx)
        if self._journal is not None:
            self._jrec({"k": "scale", "action": "drain_prefill",
                        "worker": self.prefill[idx].name})

    def remove_prefill_worker(self, idx: int):
        if self.prefill[idx].busy():
            raise RuntimeError("prefill worker still busy — drain and "
                               "run the fleet idle first")
        self._draining.discard(idx)
        w = self.prefill.pop(idx)
        self._health.pop(w.name, None)
        self.directory.drop_worker(w.name)
        ep = fetch_endpoint(w.name)
        self.transport.drop_endpoint(ep)
        self._fetch_endpoints.discard(ep)
        self._draining = {i - 1 if i > idx else i
                          for i in self._draining}
        if self._journal is not None:
            self._jrec({"k": "scale", "action": "remove_prefill",
                        "worker": w.name})
        return w
