"""Continuous-batching serving engine (slot-pool in-graph decode).

``ContinuousBatchingEngine`` keeps S sequence slots alive inside ONE
jitted decode program; ``Scheduler`` admits ragged requests into free
slots; ``Server`` is the loop + metrics. Greedy streams are
bit-identical to per-request ``generate()`` calls while sustaining
strictly higher aggregate tokens/s on mixed-length traffic. The AOT
path (``inference.export_decoder(engine_slots=...)`` +
``GenerationPredictor.serve``) serves the same engine from the
serialized artifact alone."""
from .autoscaler import (Autoscaler, AutoscalerConfig, DecisionKernel,
                         Observation)
from .durability import PrefixSpillStore, WriteAheadJournal
from .engine import (ArtifactStepBackend, ContinuousBatchingEngine,
                     ModelStepBackend, slot_sample_logits)
from .fleet import (DecodeWorker, Fleet, FleetRouter, InProcessTransport,
                    PrefillDenseEngine, PrefillPagedEngine,
                    PrefillWorker, SocketTransport, Transport,
                    TransportError)
from .frontend import FairScheduler, Frontend, TenantConfig, TokenStream
from .handoff import (KVHandoff, decode_handoff, encode_handoff,
                      reshard_kv_chunks)
from .loadgen import Trace, TraceConfig, generate_trace, replay
from .paging import (BlockManager, PagedArtifactStepBackend, PagedEngine,
                     PagedModelStepBackend)
from .prefix_cache import (PrefixCacheDirectory, adopt_prefix,
                           extract_prefix)
from .quant import QuantConfig
from .resilience import RequestFailure, ResilienceConfig
from .scheduler import Request, ResumeState, Scheduler
from .server import Server
from .spec import (SpecConfig, SpecEngine, SpecModelStepBackend,
                   SpecPagedEngine, SpecPagedStepBackend, ngram_propose)
from .tp import (ShardedModelStepBackend, ShardedPagedStepBackend,
                 TPConfig)

__all__ = ["Autoscaler", "AutoscalerConfig", "ContinuousBatchingEngine",
           "DecisionKernel", "ModelStepBackend", "Observation",
           "ArtifactStepBackend", "BlockManager", "DecodeWorker",
           "FairScheduler", "Fleet", "FleetRouter", "Frontend",
           "InProcessTransport", "KVHandoff",
           "PagedArtifactStepBackend", "PagedEngine",
           "PagedModelStepBackend", "PrefillDenseEngine",
           "PrefillPagedEngine", "PrefillWorker",
           "PrefixCacheDirectory", "PrefixSpillStore", "QuantConfig",
           "Request", "RequestFailure", "ResilienceConfig",
           "ResumeState", "Scheduler", "Server", "SocketTransport",
           "SpecConfig", "SpecEngine", "SpecModelStepBackend",
           "SpecPagedEngine", "SpecPagedStepBackend",
           "ShardedModelStepBackend", "ShardedPagedStepBackend",
           "TPConfig", "TenantConfig", "TokenStream", "Transport",
           "Trace", "TraceConfig", "TransportError",
           "WriteAheadJournal", "adopt_prefix",
           "decode_handoff", "encode_handoff", "extract_prefix",
           "generate_trace", "ngram_propose", "replay",
           "reshard_kv_chunks", "slot_sample_logits"]
