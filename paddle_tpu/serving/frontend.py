"""Multi-tenant serving front door: token streaming, weighted-fair
admission, priority preemption.

The Server loop (server.py) is a single synchronous tick over a FIFO
queue — correct, but blind to WHO is asking. This module is the traffic
layer in front of it for mixed production traffic:

- **Token-by-token streaming** out of the harvest path: every request
  can carry a bounded :class:`TokenStream` (iterator) and/or an
  ``on_token`` callback. Tokens become visible at decode-block
  granularity — exactly when the host harvests them — and an iterator
  that outruns the server PUMPS it one tick at a time, so a single
  thread drives submission, decoding and consumption deterministically.
  ``run_until_idle()`` keeps working unchanged, and greedy streams stay
  bit-identical to ``generate()``.
- **Weighted-fair queueing with quotas** (:class:`FairScheduler`):
  requests carry ``tenant``/``priority``. Admission picks strict
  priority tiers first; within a tier the tenant with the smallest
  weighted usage wins — a deficit ledger where admitting a request
  debits ``cost / weight`` (cost = its remaining token budget), so
  backlogged tenants' long-run token shares converge to their
  configured weights. Within a tenant, arrival FIFO. The base
  scheduler's max-wait batching gate, prefill token budget, snapshot
  format and requeue semantics are inherited unchanged; per-tenant
  ``max_queued`` quotas shed at submit, composing with the PR 5
  bounded-queue/deadline machinery.
- **Priority preemption** (policy in server.py, mechanism in
  engine.py/paging.py): a strictly-higher-priority request that would
  otherwise wait evicts a low-priority slot mid-decode — in-graph slot
  kill through the same ``_cancel_fn`` program deadlines use, paged
  blocks released at exact refcounts with their prefix-index entries
  RETAINED. The victim requeues carrying
  :class:`~.scheduler.ResumeState` (generated tokens + the slot's rng
  key + the original TTFT stamp) and later re-admits via chunked
  re-prefill of its history — mostly prefix-cache hits on the paged
  engine — arming with the carried key so the resumed greedy AND
  seeded-sampled streams are bit-identical to an uninterrupted run.
  Preempt/resume are span events on the request trace, never
  terminals; decode/prefill compile counts stay pinned at 1 (resume
  reuses the ONE decode block and the existing chunked-prefill/bucket
  prefill programs — no new compiled programs).
"""
from __future__ import annotations

from collections import deque
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional

import numpy as np

from ..observability import ObservabilityConfig
from ..observability import metrics as _om
from .engine import ContinuousBatchingEngine
from .resilience import RequestFailure, ResilienceConfig
from .scheduler import Request, Scheduler
from .server import Server

__all__ = ["FairScheduler", "Frontend", "TenantConfig", "TokenStream"]

# front-door stream families (registered at import; no-ops until
# metrics.enable()/PT_METRICS — catalog complete at zero)
_M_STREAM_TOKENS = _om.counter(
    "pt_frontend_stream_tokens_total",
    "tokens fanned out to per-request streams/callbacks")
_M_STREAM_DROPPED = _om.counter(
    "pt_frontend_stream_dropped_total",
    "stream tokens evicted from a bounded queue whose consumer lagged")


@dataclass
class TenantConfig:
    """Front-door policy for one tenant. ``weight`` sets its
    weighted-fair throughput share relative to the other backlogged
    tenants (default 1.0 — equal shares); ``max_queued`` caps its
    queued requests, shedding beyond (None = unbounded, only the
    server-level ``max_queue_depth`` applies)."""
    weight: float = 1.0
    max_queued: Optional[int] = None


class FairScheduler(Scheduler):
    """Per-tenant weighted-fair admission layered on the arrival-sorted
    FIFO queue. The ``_queue`` layout, ``submit``/``requeue``/
    ``drop_where`` and the snapshot format are the base class's —
    only the SELECTION in :meth:`pop_ready` changes:

    1. strict priority tiers (a visible higher-priority request always
       admits before any lower one);
    2. within a tier, the tenant with the smallest deficit ledger
       entry wins; admitting debits ``cost / weight`` where cost is
       the request's remaining token budget, so over a backlogged
       window per-tenant token throughput converges to the weights;
    3. within a tenant, arrival FIFO.

    Tenants enter (and re-enter after going idle) at the ledger floor
    of the currently backlogged set — no credit hoarding while idle.
    The max-wait/min-admit batching gate and the prefill token budget
    apply exactly as in the base scheduler."""

    # Server's preemption policy requires this: a freed slot must go
    # to the highest-priority waiter, which the base FIFO pop cannot
    # guarantee (it would hand the slot back to the requeued victim)
    priority_aware = True

    def __init__(self, tenants: Optional[Dict[str, TenantConfig]] = None,
                 default_weight: float = 1.0, **kw):
        super().__init__(**kw)
        self.tenants: Dict[str, TenantConfig] = dict(tenants or {})
        for name, cfg in self.tenants.items():
            if cfg.weight <= 0:
                raise ValueError(
                    f"tenant {name!r}: weight={cfg.weight}; must be > 0")
        if default_weight <= 0:
            raise ValueError(
                f"default_weight={default_weight}; must be > 0")
        self.default_weight = float(default_weight)
        self._usage: Dict[str, float] = {}    # the deficit ledger
        self._pending: Dict[str, int] = {}    # O(1) quota counts
        self._backlogged: set = set()  # tenants visible at the last pop

    def weight(self, tenant: str) -> float:
        cfg = self.tenants.get(tenant)
        return cfg.weight if cfg is not None else self.default_weight

    def tenant_pending(self, tenant: str) -> int:
        return self._pending.get(tenant, 0)

    def quota_exceeded(self, tenant: str) -> bool:
        """Server.submit's per-tenant shed hook (O(1) — every submit
        pays this, and the base queue was deliberately kept at O(log Q)
        per submit)."""
        cfg = self.tenants.get(tenant)
        return (cfg is not None and cfg.max_queued is not None
                and self.tenant_pending(tenant) >= cfg.max_queued)

    @staticmethod
    def _cost(r: Request) -> float:
        # remaining DECODE budget: what per-tenant throughput is
        # measured in — a resumed request only owes its tail
        done = len(r.resume.tokens) if r.resume is not None else 0
        return float(max(r.max_new_tokens - done, 1))

    # -- queue bookkeeping (pending counts + ledger credits) ---------------
    def submit(self, request: Request):
        self._pending[request.tenant] = \
            self._pending.get(request.tenant, 0) + 1
        super().submit(request)

    def requeue(self, request: Request):
        """Front-insert like the base class, but CREDIT the ledger: the
        pop that released this request debited its cost, and nothing of
        that charge was delivered — the engine deferred it, or a
        preemption carried the delivered part out in ``resume`` (whose
        remaining-tail cost is exactly what the next pop re-debits).
        Without the credit, deferrals and preemptions double-charge
        their tenant and its measured share drifts under its weight."""
        if request.tenant in self._usage:
            self._usage[request.tenant] -= \
                self._cost(request) / self.weight(request.tenant)
        self._pending[request.tenant] = \
            self._pending.get(request.tenant, 0) + 1
        super().requeue(request)

    def drop_where(self, pred) -> List[Request]:
        dropped = super().drop_where(pred)
        for r in dropped:
            self._pending[r.tenant] -= 1
        return dropped

    def pop_ready(self, now: int, free_slots: int, engine_idle: bool,
                  token_budget: Optional[int] = None) -> List[Request]:
        gate = self._gate_visible(now, free_slots, engine_idle,
                                  token_budget)
        if gate is None:
            return []
        n_visible, token_budget = gate
        pool = list(self._queue[:n_visible])
        order = {id(r): i for i, r in enumerate(pool)}  # arrival FIFO
        active = {r.tenant for r in pool}
        # re-entry floor comes from the CONTINUOUSLY backlogged tenants
        # only — including a returning tenant's own stale (frozen-low)
        # entry would make the clamp a no-op and let idling bank credit
        # (it then monopolizes admissions on return until the banked
        # credit drains, starving the tenants that kept submitting)
        cont = [self._usage[t] for t in (active & self._backlogged)
                if t in self._usage]
        floor = min(cont) if cont else 0.0
        for t in active - self._backlogged:
            self._usage[t] = max(self._usage.get(t, floor), floor)
        self._backlogged = active
        take: List[Request] = []
        tokens = 0
        while len(take) < free_slots and pool:
            pmax = max(r.priority for r in pool)
            heads: Dict[str, Request] = {}
            for r in pool:               # oldest per tenant in the tier
                if r.priority == pmax and r.tenant not in heads:
                    heads[r.tenant] = r
            pick = min(heads.values(),
                       key=lambda h: (self._usage[h.tenant],
                                      order[id(h)]))
            t = int(np.asarray(pick.prompt).size)
            if take and token_budget is not None \
                    and tokens + t > token_budget:
                break
            take.append(pick)
            tokens += t
            pool.remove(pick)
            self._usage[pick.tenant] += \
                self._cost(pick) / self.weight(pick.tenant)
        if take:
            taken = set(map(id, take))
            self._queue = [r for r in self._queue
                           if id(r) not in taken]
            for r in take:
                self._pending[r.tenant] -= 1
        return take


class TokenStream:
    """One request's bounded token stream. The frontend pushes freshly
    harvested tokens (and the terminal state) in; the consumer either
    iterates — ``next()`` PUMPS the owning frontend one server tick at
    a time while the buffer is empty — or registers an ``on_token``
    callback invoked inline at harvest. The buffer is BOUNDED: past
    ``capacity`` undrained tokens the oldest are evicted (``dropped``
    counts them), so a stalled consumer can never hold an unbounded
    backlog; ``tokens_seen`` always counts the full stream."""

    def __init__(self, request_id: int, frontend: "Frontend" = None,
                 capacity: int = 1024,
                 on_token: Optional[Callable[[int], None]] = None):
        if capacity < 1:
            raise ValueError(f"capacity={capacity}; must be >= 1")
        self.request_id = request_id
        self.capacity = capacity
        self.on_token = on_token
        self.tokens_seen = 0
        self.dropped = 0
        self.done = False
        self.failure: Optional[str] = None
        self._frontend = frontend
        self._buf: deque = deque()

    # -- producer side (frontend sink) -------------------------------------
    def _push(self, toks):
        for t in toks:
            t = int(t)
            self.tokens_seen += 1
            _M_STREAM_TOKENS.inc()
            if self.on_token is not None:
                self.on_token(t)
            if len(self._buf) >= self.capacity:
                self._buf.popleft()
                self.dropped += 1
                _M_STREAM_DROPPED.inc()
            self._buf.append(t)

    def _finish(self, failure: Optional[str]):
        self.done = True
        self.failure = failure

    # -- consumer side ------------------------------------------------------
    def __iter__(self):
        return self

    def __next__(self) -> int:
        while not self._buf:
            if self.done:
                raise StopIteration
            if self._frontend is None or not self._frontend.pump():
                raise RuntimeError(
                    f"stream for request {self.request_id} stalled: the "
                    "server is idle but the request never terminated — "
                    "a serving-loop bug, not a consumer error")
        return self._buf.popleft()

    def drain(self) -> List[int]:
        """Buffered tokens right now, without pumping the server."""
        out = list(self._buf)
        self._buf.clear()
        return out

    def read_all(self) -> List[int]:
        """Drive the stream to its terminal and return every token
        (minus any evicted past the bound — check ``dropped``)."""
        return list(self)


class Frontend:
    """The multi-tenant front door over an engine: builds the
    :class:`FairScheduler` + :class:`~.server.Server` pair, fans
    harvested tokens out to per-request streams, and (with
    ``preemption=True``) lets higher-priority traffic evict and later
    resume lower-priority slots. ``results``/``stats`` proxy the
    server's; everything the plain Server contract pins (bit-identity,
    one compiled decode program, exactly-one terminal per request)
    holds with the front door in place."""

    def __init__(self, engine: ContinuousBatchingEngine,
                 tenants: Optional[Dict[str, TenantConfig]] = None,
                 scheduler: Optional[Scheduler] = None,
                 resilience: Optional[ResilienceConfig] = None,
                 observability: Optional[ObservabilityConfig] = None,
                 preemption: Optional[bool] = None,
                 stream_capacity: int = 1024):
        scheduler = self._resolve_scheduler(tenants, scheduler)
        server = Server(engine, scheduler, resilience,
                        observability, preemption=preemption)
        self._wire(engine, scheduler, server, stream_capacity)

    @staticmethod
    def _resolve_scheduler(tenants, scheduler) -> Scheduler:
        """ONE tenants/scheduler contract for __init__ AND restore —
        the two entry points cannot drift."""
        if scheduler is None:
            return FairScheduler(tenants=tenants)
        if tenants:
            raise ValueError(
                "pass tenants= (builds a FairScheduler) or an explicit "
                "scheduler, not both — silently ignoring the tenant "
                "weights would be a misconfiguration")
        return scheduler

    def _wire(self, engine, scheduler, server: Server,
              stream_capacity: int):
        """Attach this frontend to a server (fresh or restored)."""
        self.engine = engine
        self.scheduler = scheduler
        self.server = server
        self.stream_capacity = stream_capacity
        self._streams: Dict[int, TokenStream] = {}
        self._emitted: Dict[int, int] = {}
        self.tenant_tokens: Dict[str, int] = {}   # streamed, per tenant
        ex = server.restored_extras.get("frontend")
        if ex is not None:
            # delivered offsets ride the snapshot: a re-attached
            # consumer (or a migrated decode worker's streams) sees
            # only the tokens the pre-kill consumer never took —
            # buffered-but-unconsumed tokens were subtracted at
            # snapshot time, so they re-deliver
            self._emitted = {int(k): v
                             for k, v in ex["emitted"].items()}
            self.tenant_tokens = dict(ex["tenant_tokens"])
        self.server.stream_sink = self._sink
        self.server.snapshot_extras["frontend"] = self._snapshot_extra

    @classmethod
    def restore(cls, path: str, engine: ContinuousBatchingEngine,
                tenants: Optional[Dict[str, TenantConfig]] = None,
                scheduler: Optional[Scheduler] = None,
                resilience: Optional[ResilienceConfig] = None,
                observability=None, preemption: Optional[bool] = None,
                stream_capacity: int = 1024) -> "Frontend":
        """Rebuild a front door from a ``Server`` snapshot (fresh
        process simulation). The per-request delivered offsets saved by
        the frontend's snapshot-extras provider rehydrate here, so
        streams re-attached via :meth:`attach_stream` resume at the
        first unseen token instead of re-streaming from offset 0."""
        scheduler = cls._resolve_scheduler(tenants, scheduler)
        server = Server.restore(path, engine, scheduler, resilience,
                                observability, preemption=preemption)
        fe = cls.__new__(cls)
        fe._wire(engine, scheduler, server, stream_capacity)
        return fe

    def _snapshot_extra(self) -> dict:
        """Snapshot-extras provider (server.snapshot_extras hook): the
        per-request DELIVERED offsets. Tokens still sitting in a LIVE
        stream's bounded buffer were never taken by the consumer, so
        they are subtracted — after a restore they deliver again,
        exactly once. Terminal streams keep their full offset (the
        sink never fires for them again, so subtracting would only
        undercount the tenant tallies forever — a re-attached consumer
        of a finished request reads ``results`` instead), and so do
        callback streams: ``on_token`` already fired for every pushed
        token, so their buffered copies WERE delivered."""
        emitted = dict(self._emitted)
        tenant_tokens = dict(self.tenant_tokens)
        for rid, ts in self._streams.items():
            buffered = len(ts._buf)
            if buffered and not ts.done and ts.on_token is None \
                    and rid in emitted:
                emitted[rid] -= buffered
                tenant = self.server._tenant_of.get(rid, "default")
                tenant_tokens[tenant] = \
                    tenant_tokens.get(tenant, 0) - buffered
        return {"emitted": {str(k): v for k, v in emitted.items()},
                "tenant_tokens": tenant_tokens}

    def attach_stream(self, rid: int,
                      on_token: Optional[Callable[[int], None]] = None
                      ) -> TokenStream:
        """(Re-)attach a consumer to a known request — the other half
        of the delivered-offset contract: after a restore, the new
        stream yields only tokens past the saved offset. Re-attaching
        over a LIVE existing stream hands its buffered-but-unconsumed
        tokens to the new one (exactly-once holds across re-attach
        too). A request already terminal closes the stream immediately
        (its full output lives in ``results``)."""
        ts = TokenStream(rid, frontend=self,
                         capacity=self.stream_capacity,
                         on_token=on_token)
        old = self._streams.get(rid)
        if old is not None:
            ts._buf.extend(old.drain())
            if old.done:
                ts._finish(old.failure)
        self._streams[rid] = ts
        v = self.server.results.get(rid)
        if v is not None and not ts.done:
            ts._finish(v.reason if isinstance(v, RequestFailure)
                       else None)
        return ts

    # -- server glue --------------------------------------------------------
    def _sink(self, rid: int, tokens, done: bool,
              failure: Optional[str]):
        """Server harvest hook: diff the run's token list against what
        this request already streamed, push the new suffix, and close
        the stream at its terminal. Per-tenant streamed-token tallies
        accumulate here for every request (the live share measure the
        fairness bench reads), streams or not."""
        emitted = self._emitted.get(rid, 0)
        if tokens is not None and len(tokens) > emitted:
            new = tokens[emitted:]
            self._emitted[rid] = len(tokens)
            tenant = self.server._tenant_of.get(rid, "default")
            self.tenant_tokens[tenant] = \
                self.tenant_tokens.get(tenant, 0) + len(new)
            stream = self._streams.get(rid)
            if stream is not None:
                stream._push(new)
        if done:
            stream = self._streams.get(rid)
            if stream is not None and not stream.done:
                stream._finish(failure)

    # -- API ----------------------------------------------------------------
    def submit(self, prompt, *, tenant: str = "default",
               priority: int = 0, stream: bool = False,
               on_token: Optional[Callable[[int], None]] = None,
               **kw):
        """Submit one request. Plain form returns the request id (same
        contract as ``Server.submit``); with ``stream=True`` and/or an
        ``on_token`` callback it returns a :class:`TokenStream` whose
        ``request_id`` indexes ``results``."""
        rid = self.server.submit(prompt, tenant=tenant,
                                 priority=priority, **kw)
        if not stream and on_token is None:
            return rid
        ts = TokenStream(rid, frontend=self,
                         capacity=self.stream_capacity,
                         on_token=on_token)
        self._streams[rid] = ts
        # a submit-time shed already recorded its failure before the
        # handle existed — close the stream now
        v = self.server.results.get(rid)
        if isinstance(v, RequestFailure):
            ts._finish(v.reason)
        return ts

    def stream(self, rid: int) -> Optional[TokenStream]:
        return self._streams.get(rid)

    def pump(self) -> bool:
        """Advance the server by ONE tick if it has work; returns
        whether it did. The pull edge of the streaming API — iterator
        consumers call this transparently via ``next()``."""
        busy = self.scheduler.pending() > 0 or self.engine.has_live()
        if busy:
            self.server.run_until_idle(max_ticks=1)
        return busy

    def run_until_idle(self, max_ticks: Optional[int] = None):
        return self.server.run_until_idle(max_ticks=max_ticks)

    @property
    def results(self):
        return self.server.results

    def stats(self) -> dict:
        out = self.server.stats()
        out["stream_tokens"] = sum(self._emitted.values())
        out["tenant_stream_tokens"] = dict(sorted(
            self.tenant_tokens.items()))
        return out
