"""paddle.version parity (reference: generated python/paddle/version.py).
The framework's own version; `full_version`/`commit` mirror the
reference's fields."""
full_version = "0.2.0"
major = "0"
minor = "2"
patch = "0"
commit = "tpu-native"
cuda_version = "False"      # no CUDA: TPU-native build
cudnn_version = "False"
tensorrt_version = "False"


def show():
    print(f"paddle_tpu {full_version} (commit {commit}; TPU-native, "
          "no CUDA)")


def cuda():
    return cuda_version


def cudnn():
    return cudnn_version
