"""Eager autograd engine: reverse tape walk.

Reference parity: ``egr::Backward`` reverse-topological ready-queue over the
GradNode graph (reference: paddle/fluid/eager/backward.cc — verify), plus
``paddle.autograd.PyLayer`` and ``paddle.no_grad``.

TPU-native design: the tape (paddle_tpu/tensor.py) is already in topological
creation order, so backward is a single reverse scan that calls each node's
stored ``jax.vjp`` pullback and accumulates cotangents per tensor. Cotangent
math is pure jax, so the whole backward is async-dispatched to the device.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from . import framework
from .tensor import Tensor, _tape

__all__ = ["backward", "grad", "no_grad", "enable_grad", "set_grad_enabled",
           "is_grad_enabled", "PyLayer", "PyLayerContext"]


import weakref

# id(tensor) -> (weakref to the tensor, [hooks]); the weakref guards
# against CPython id reuse after the tensor dies
_TENSOR_HOOKS: dict[int, tuple] = {}


def _register_tensor_hook(t: Tensor, hook):
    entry = _TENSOR_HOOKS.get(id(t))
    if entry is None or entry[0]() is not t:
        entry = (weakref.ref(t), [])
        _TENSOR_HOOKS[id(t)] = entry
    entry[1].append(hook)

    class _Handle:
        def remove(self):
            e = _TENSOR_HOOKS.get(id(t))
            if e and e[0]() is t and hook in e[1]:
                e[1].remove(hook)
    return _Handle()


def _prune_dead_hooks():
    dead = [k for k, (ref, _) in _TENSOR_HOOKS.items() if ref() is None]
    for k in dead:
        del _TENSOR_HOOKS[k]


def _is_float0(x) -> bool:
    return getattr(x, "dtype", None) == jax.dtypes.float0


def backward(loss: Tensor, grad_tensor: Optional[Tensor] = None,
             retain_graph: bool = False):
    """Accumulate gradients of `loss` into ``.grad`` of all leaf tensors
    with ``stop_gradient=False`` that participated in its history.

    Only the SUBGRAPH reachable from ``loss`` is consumed: other live
    graphs' nodes survive (reference eager semantics — e.g. GAN loops
    backward two losses in sequence). Dead nodes (all outputs
    garbage-collected) are pruned incrementally as the walk passes them."""
    tape = _tape()
    if loss._node is None:
        if not loss.stop_gradient:
            seed = (grad_tensor._value if grad_tensor is not None
                    else jnp.ones_like(loss._value))
            _deposit(loss, seed)
        return

    if grad_tensor is not None:
        seed = grad_tensor._value if isinstance(grad_tensor, Tensor) \
            else jnp.asarray(grad_tensor)
    else:
        if loss.size != 1:
            raise RuntimeError(
                "backward() on a non-scalar tensor requires grad_tensor")
        seed = jnp.ones_like(loss._value)

    # cotangent store keyed by tensor identity
    cotangents: dict[int, jax.Array] = {id(loss): seed}
    keep = {id(loss): loss}
    visited: set[int] = set()
    dead: set[int] = set()
    outs = out_cts = None

    for node in reversed(tape.nodes):
        outs = node.live_outputs()
        hit = any(o is not None and id(o) in cotangents for o in outs)
        if not hit:
            if all(o is None for o in outs):
                dead.add(id(node))     # fully dropped graph: prunable
            continue
        visited.add(id(node))
        out_cts = []
        for o, (shape, dtype) in zip(outs, node.out_meta):
            ct = None
            if o is not None:
                ct = cotangents.pop(id(o), None)
                keep.pop(id(o), None)
            if ct is None:
                ct = jnp.zeros(shape, dtype)
            out_cts.append(ct)
        # vjp_fn expects cotangent structure matching fn output
        arg = tuple(out_cts) if node.multi else out_cts[0]
        in_cts = node.vjp_fn(arg)
        for t, ct in zip(node.inputs, in_cts):
            if t.stop_gradient or _is_float0(ct):
                continue
            if ct.dtype != t._value.dtype:
                ct = ct.astype(t._value.dtype)
            tid = id(t)
            if tid in cotangents:
                cotangents[tid] = cotangents[tid] + ct
            else:
                cotangents[tid] = ct
                keep[tid] = t

    # validate EVERY terminus before touching any .grad, so a freed-trunk
    # error cannot leave gradient state half-updated
    node_ids = {id(n) for n in tape.nodes}
    for tid in cotangents:
        t = keep[tid]
        # _node None with is_leaf False = produced under no-grad and
        # later marked requires-grad (e.g. WGAN-GP interpolates): a
        # valid deposit target, not a freed trunk — freed-trunk tensors
        # keep a DANGLING _node, which the tape-membership test catches
        if not t.is_leaf and t._node is not None \
                and id(t._node) not in node_ids:
            # this tensor's producing node is GONE from the tape: an
            # earlier backward already freed the shared subgraph.
            # (In-place termini keep their node on the tape this pass,
            # so they deposit normally.)
            if t is loss:
                raise RuntimeError(
                    "trying to run backward through the same graph a "
                    "second time (its nodes were freed); use "
                    "retain_graph=True")
            raise RuntimeError(
                "backward() reached a non-leaf tensor whose producing "
                "nodes are gone — the shared trunk was freed by an "
                "earlier backward; pass retain_graph=True to the first "
                "backward when two losses share a trunk")
    for tid, ct in cotangents.items():
        _deposit(keep[tid], ct)

    drop = dead if retain_graph else (dead | visited)
    if drop:
        # an in-place op's surviving output becomes a LEAF again once its
        # history is consumed (it continues life as a plain value; later
        # fresh graphs through it must not see a freed-trunk tombstone)
        for n in tape.nodes:
            if id(n) in drop and n.inplace:
                for o in n.live_outputs():
                    if o is not None and o._node is n:
                        o._node = None
                        o.is_leaf = True
        tape.nodes = [n for n in tape.nodes if id(n) not in drop]
    # release this frame's references before the sweep — the loop locals
    # (outs/node/keep/cotangents) would otherwise pin dropped outputs
    # alive through gc()
    del outs, out_cts, keep, cotangents
    node = o = t = None   # noqa: F841
    tape.gc()
    _prune_dead_hooks()


# when non-empty, deposits are captured into the top dict instead of
# mutating .grad (paddle.grad contract: .grad fields stay untouched)
_CAPTURE: list = []


def _deposit(t: Tensor, ct):
    entry = _TENSOR_HOOKS.get(id(t))
    hooks = entry[1] if entry and entry[0]() is t else []
    for hook in hooks:
        res = hook(Tensor(ct))
        if res is not None:
            ct = res._value if isinstance(res, Tensor) else res
    if _CAPTURE:
        store = _CAPTURE[-1]
        if id(t) in store:
            store[id(t)] = (t, store[id(t)][1] + ct)
        else:
            store[id(t)] = (t, ct)
        return
    if t.grad is None:
        t.grad = Tensor(ct)
    else:
        t.grad = Tensor(t.grad._value + ct)


def _free_subgraph(roots):
    """Remove from the tape every node reachable (reverse) from roots."""
    tape = _tape()
    reach = {id(r) for r in roots if isinstance(r, Tensor)}
    drop = set()
    for node in reversed(tape.nodes):
        outs = node.live_outputs()
        if any(o is not None and id(o) in reach for o in outs):
            drop.add(id(node))
            for t in node.inputs:
                reach.add(id(t))
    if drop:
        tape.nodes = [n for n in tape.nodes if id(n) not in drop]
    tape.gc()


def _grad_create_graph(outputs, inputs, grad_outputs, allow_unused):
    """Differentiable grads for ``paddle.grad(create_graph=True)``.

    The eager tape stores pullbacks, but second-order terms flow through
    the RESIDUALS (d/dx of vjp(x -> x^2) is 2*ct), so replaying
    pullbacks alone cannot differentiate the grads. Instead, each tape
    node keeps its forward pure fn (``TapeNode.fwd``); this rebuilds
    the recorded subgraph as one pure function F(sources) — sources are
    the requested inputs (treated as CUT points / free variables) plus
    every other requires-grad leaf feeding the subgraph (params: the
    WGAN-GP penalty differentiates d2D/dtheta dx) — and runs the whole
    ``jax.vjp(F)`` as ONE recorded op via ``apply_op``. The returned
    grads then carry tape history themselves, so ``backward()`` or
    another ``grad(..., create_graph=True)`` through them works to any
    order. Reference parity: paddle.grad create_graph /
    double_grad (python/paddle/autograd, gradient_checker — verify)."""
    from .tensor import apply_op
    tape = _tape()
    # freed-graph detection (parity with the first-order paths): an
    # output whose producing node is GONE from the tape means an
    # earlier backward/grad consumed the subgraph — raise the same
    # actionable error instead of a misleading "no gradient"
    node_ids = {id(n) for n in tape.nodes}
    for o in outputs:
        if isinstance(o, Tensor) and not o.is_leaf \
                and o._node is not None and id(o._node) not in node_ids:
            raise RuntimeError(
                "trying to run grad() through the same graph a second "
                "time (its nodes were freed); pass retain_graph=True "
                "to the earlier backward()/grad()")
    input_ids = {id(t) for t in inputs}
    needed = {id(o) for o in outputs}
    nodes = []
    for node in reversed(tape.nodes):
        outs = node.live_outputs()
        live_hit = [o for o in outs if o is not None and id(o) in needed]
        if not live_hit:
            continue
        # a node needed ONLY to produce requested inputs is not
        # replayed: a requested input is a cut — its upstream history
        # does not contribute to d(outputs)/d(input)
        if all(id(o) in input_ids for o in live_hit):
            continue
        if node.fwd is None:
            raise RuntimeError(
                "paddle.grad(create_graph=True) cannot differentiate "
                "through a custom PyLayer node (no double-grad is "
                "defined for it); use the functional API "
                "(paddle.incubate.autograd.vjp/jacobian) instead")
        if node.inplace:
            raise RuntimeError(
                "paddle.grad(create_graph=True) through an in-place op "
                "is unsupported — the pre-mutation value needed to "
                "rebuild the graph no longer exists; use the "
                "out-of-place op")
        nodes.append(node)
        for t in node.inputs:
            if id(t) not in input_ids:
                needed.add(id(t))
    nodes.reverse()

    produced = set()
    for n in nodes:
        for o in n.live_outputs():
            if o is not None and id(o) not in input_ids:
                produced.add(id(o))

    # sources: requested inputs first (dedup by identity), then every
    # non-produced requires-grad feed of the replayed nodes
    sources: list = []
    pos_of: dict = {}
    for t in inputs:
        if id(t) not in pos_of:
            pos_of[id(t)] = len(sources)
            sources.append(t)
    for n in nodes:
        for t in n.inputs:
            tid = id(t)
            if tid in pos_of or tid in produced or t.stop_gradient:
                continue
            pos_of[tid] = len(sources)
            sources.append(t)
    n_src = len(sources)
    src_ids = [id(t) for t in sources]
    src_id_set = set(src_ids)
    req_idx = [pos_of[id(t)] for t in inputs]

    consumed = {id(t) for n in nodes for t in n.inputs}
    out_id_set = {id(o) for o in outputs}

    # non-source, non-produced feeds (stop-gradient leaves) close over
    # their current values; leaf outputs need a fallback value too
    closed = {}
    for n in nodes:
        for t in n.inputs:
            tid = id(t)
            if tid not in src_id_set and tid not in produced:
                closed[tid] = t._value
    out_closed = {id(o): o._value for o in outputs}

    replay = []
    for n in nodes:
        outs = n.live_outputs()
        replay.append((n, [None if o is None else id(o) for o in outs]))

    # seed handling: None -> ones (scalar outputs only, matching the
    # first-order path); Tensor seeds become differentiable args
    if grad_outputs is None:
        gos = [None] * len(outputs)
    else:
        gos = list(grad_outputs) if isinstance(grad_outputs,
                                               (list, tuple)) \
            else [grad_outputs]
    seed_tensors = []
    seed_spec = []
    for o, go in zip(outputs, gos):
        if go is None:
            if o.size != 1:
                raise RuntimeError(
                    "grad() on a non-scalar output requires "
                    "grad_outputs")
            seed_spec.append(("ones", None))
        elif isinstance(go, Tensor):
            seed_spec.append(("arg", len(seed_tensors)))
            seed_tensors.append(go)
        else:
            seed_spec.append(("const", jnp.asarray(go)))

    def vjp_all(*vals):
        src_vals = vals[:n_src]
        seed_vals = vals[n_src:]

        def F(*sv):
            env = dict(zip(src_ids, sv))
            for n, out_ids in replay:
                in_vals = [env[id(t)] if id(t) in env else closed[id(t)]
                           for t in n.inputs]
                r = n.fwd(*in_vals)
                if n.multi:
                    for oid, ov in zip(out_ids, r):
                        if oid is not None and oid not in src_id_set:
                            env[oid] = ov
                else:
                    oid = out_ids[0]
                    if oid is not None and oid not in src_id_set:
                        env[oid] = r
            return tuple(env.get(id(o), out_closed[id(o)])
                         for o in outputs)

        outs, pull = jax.vjp(F, *src_vals)
        seeds = []
        for (kind, payload), ov in zip(seed_spec, outs):
            if kind == "ones":
                s = jnp.ones_like(ov)
            elif kind == "arg":
                s = seed_vals[payload]
            else:
                s = payload
            if s.dtype != ov.dtype:
                s = s.astype(ov.dtype)
            seeds.append(s)
        cts = pull(tuple(seeds))
        return tuple(cts[i] for i in req_idx)

    grads = apply_op(vjp_all, *sources, *seed_tensors)
    if not isinstance(grads, (tuple, list)):
        grads = [grads]
    results = []
    for t, g in zip(inputs, grads):
        used = (id(t) in consumed or id(t) in out_id_set) \
            and not t.stop_gradient
        if not used:
            if not allow_unused:
                raise RuntimeError(
                    "one of the input tensors received no gradient "
                    "(pass allow_unused=True to permit)")
            results.append(None)
        else:
            results.append(g)
    return results


def grad(outputs, inputs, grad_outputs=None, retain_graph=None,
         create_graph=False, only_inputs=True, allow_unused=False):
    """paddle.grad parity: return grads of outputs wrt inputs without
    touching ``.grad`` fields. ``create_graph=True`` returns
    DIFFERENTIABLE grads (the subgraph is replayed as a pure function
    and its vjp recorded as one tape op — see ``_grad_create_graph``);
    ``retain_graph`` defaults to ``create_graph``."""
    outputs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    if grad_outputs is not None:
        n_go = len(grad_outputs) if isinstance(grad_outputs,
                                               (list, tuple)) else 1
        if n_go != len(outputs):
            raise ValueError(
                f"grad(): grad_outputs has {n_go} entries but there "
                f"are {len(outputs)} outputs — they must match "
                "one-to-one (use None entries for default seeds)")
    if create_graph:
        results = _grad_create_graph(outputs, inputs, grad_outputs,
                                     allow_unused)
        if retain_graph is False:
            _free_subgraph(outputs)
        return results
    capture: dict = {}
    _CAPTURE.append(capture)
    try:
        for i, out in enumerate(outputs):
            go = None
            if grad_outputs is not None and grad_outputs[i] is not None:
                go = grad_outputs[i]
            backward(out, go, retain_graph=True)
    finally:
        _CAPTURE.pop()
        if not retain_graph:
            # free the union subgraph of all outputs (each backward above
            # ran with retain_graph=True so shared nodes stayed for later
            # outputs); unrelated graphs survive — and NO .grad field was
            # touched anywhere (deposits went into the capture dict)
            _free_subgraph(outputs)
    results = []
    for t in inputs:
        got = capture.get(id(t))
        if got is None:
            if not allow_unused:
                raise RuntimeError(
                    "one of the input tensors received no gradient "
                    "(pass allow_unused=True to permit)")
            results.append(None)
        else:
            results.append(Tensor(got[1]))
    return results


# ---------------------------------------------------------------------------
# grad-mode context managers / decorators
# ---------------------------------------------------------------------------

class no_grad:
    """paddle.no_grad: context manager AND decorator."""

    def __enter__(self):
        self._prev = framework.state().grad_enabled
        framework.set_grad_enabled(False)
        return self

    def __exit__(self, *exc):
        framework.set_grad_enabled(self._prev)
        return False

    def __call__(self, fn):
        @functools.wraps(fn)
        def wrapper(*a, **k):
            with no_grad():
                return fn(*a, **k)
        return wrapper


class enable_grad:
    def __enter__(self):
        self._prev = framework.state().grad_enabled
        framework.set_grad_enabled(True)
        return self

    def __exit__(self, *exc):
        framework.set_grad_enabled(self._prev)
        return False


class set_grad_enabled:
    def __init__(self, mode: bool):
        self._mode = mode

    def __enter__(self):
        self._prev = framework.state().grad_enabled
        framework.set_grad_enabled(self._mode)
        return self

    def __exit__(self, *exc):
        framework.set_grad_enabled(self._prev)
        return False


def is_grad_enabled():
    return framework.state().grad_enabled


# ---------------------------------------------------------------------------
# PyLayer: custom autograd function
# ---------------------------------------------------------------------------

class PyLayerContext:
    def __init__(self):
        self._saved = ()
        self.materialize_grads = True

    def save_for_backward(self, *tensors):
        self._saved = tensors

    @property
    def saved_tensor(self):
        return self._saved

    def saved_tensors(self):
        return self._saved


class PyLayer:
    """Custom autograd op (reference: python/paddle/autograd/py_layer.py
    — verify). Subclass with static ``forward(ctx, *args)`` and
    ``backward(ctx, *grads)`` operating on Tensors."""

    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *args):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        ctx = PyLayerContext()
        with no_grad():
            out = cls.forward(ctx, *args, **kwargs)
        if not framework.is_grad_enabled():
            return out

        in_tensors = [a for a in args if isinstance(a, Tensor)
                      and not a.stop_gradient]
        if not in_tensors:
            return out

        multi = isinstance(out, (tuple, list))
        out_list = list(out) if multi else [out]

        def vjp_fn(cts):
            if not isinstance(cts, tuple):
                cts = (cts,)
            grads = cls.backward(ctx, *[Tensor(c) for c in cts])
            if not isinstance(grads, (tuple, list)):
                grads = (grads,)
            vals = []
            for g in grads:
                vals.append(g._value if isinstance(g, Tensor) else g)
            return tuple(vals)

        outputs_box: list = []
        node = _tape().record(vjp_fn, in_tensors, outputs_box, multi=multi)
        wrapped = []
        for i, o in enumerate(out_list):
            t = Tensor(o._value if isinstance(o, Tensor) else o,
                       stop_gradient=False)
            t.is_leaf = False
            t._node = node
            t._out_index = i
            outputs_box.append(t)
            wrapped.append(t)
        node.seal()
        return tuple(wrapped) if multi else wrapped[0]


class LegacyPyLayer(PyLayer):
    pass


# ---------------------------------------------------------------------------
# functional autodiff (paddle.autograd / paddle.incubate.autograd parity:
# python/paddle/autograd/functional.py — verify). These functionalize the
# wrapped callable and hand it to jax's transforms, so they compose with
# jit and give exact (not finite-difference) derivatives.
# ---------------------------------------------------------------------------

def _functionalize(func):
    """Wrap a Tensor->Tensor callable as a jax-array pure function."""
    def fn(*arrays):
        with framework.functional_mode():
            args = [Tensor(a) for a in arrays]
            for a in args:
                a.stop_gradient = False
            out = func(*args)
        if isinstance(out, (list, tuple)):
            return tuple(o._value if isinstance(o, Tensor) else o
                         for o in out)
        return out._value if isinstance(out, Tensor) else out
    return fn


def _unpack(xs):
    single = not isinstance(xs, (list, tuple))
    xs = [xs] if single else list(xs)
    return single, [x._value if isinstance(x, Tensor) else jnp.asarray(x)
                    for x in xs]


def _pack(vals, single):
    wrapped = jax.tree_util.tree_map(Tensor, vals)
    if single and isinstance(wrapped, (list, tuple)) and len(wrapped) == 1:
        return wrapped[0]
    return wrapped


def vjp(func, xs, v=None):
    """(outputs, vjp_result): pullback of ``func`` at ``xs`` along ``v``."""
    single, arrays = _unpack(xs)
    out, pull = jax.vjp(_functionalize(func), *arrays)
    if v is None:
        ct = jax.tree_util.tree_map(jnp.ones_like, out)
    else:
        _, cts = _unpack(v)
        ct = cts[0] if not isinstance(out, tuple) else tuple(cts)
    grads = pull(ct)
    return _pack(out, True), _pack(list(grads), single)


def jvp(func, xs, v=None):
    """(outputs, jvp_result): pushforward of ``func`` at ``xs`` along
    ``v`` (defaults to ones)."""
    single, arrays = _unpack(xs)
    if v is None:
        tangents = [jnp.ones_like(a) for a in arrays]
    else:
        _, tangents = _unpack(v)
    out, tan = jax.jvp(_functionalize(func), tuple(arrays), tuple(tangents))
    return _pack(out, True), _pack(tan, True)


class Jacobian:
    """Lazy Jacobian matrix (paddle.autograd.jacobian result object):
    index/slice it like a 2-D tensor over (flat_out, flat_in)."""

    def __init__(self, mat):
        self._mat = mat

    def __getitem__(self, idx):
        return Tensor(self._mat[idx])

    @property
    def shape(self):
        return list(self._mat.shape)

    def numpy(self):
        import numpy as _np
        return _np.asarray(self._mat)

    def as_tensor(self):
        return Tensor(self._mat)


def jacobian(func, xs, create_graph=False, allow_unused=False,
             batch_axis=None):
    """Exact Jacobian via jax.jacrev. Returns a Jacobian view per input
    (single input -> single Jacobian)."""
    single, arrays = _unpack(xs)
    jac = jax.jacrev(_functionalize(func), argnums=tuple(range(len(arrays))))
    mats = jac(*arrays)
    if isinstance(mats, tuple):
        out = [Jacobian(m) for m in mats]
        return out[0] if single else out
    return Jacobian(mats)


def hessian(func, xs, create_graph=False, allow_unused=False,
            batch_axis=None):
    """Exact Hessian of a scalar-valued ``func`` via forward-over-reverse."""
    single, arrays = _unpack(xs)
    hess = jax.hessian(_functionalize(func),
                       argnums=tuple(range(len(arrays))))
    mats = hess(*arrays)
    if isinstance(mats, tuple):
        if single:
            return Jacobian(mats[0][0] if isinstance(mats[0], tuple)
                            else mats[0])
        return [[Jacobian(m) for m in row] for row in mats]
    return Jacobian(mats)
