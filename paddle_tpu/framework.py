"""Global framework state: grad mode, default dtype, places, RNG.

Reference parity: paddle's dygraph tracer state + ``paddle.seed`` +
``paddle.set_default_dtype`` (reference: python/paddle/base/framework.py,
python/paddle/base/core.py — verify). TPU-native design: instead of a C++
Tracer we keep a tiny amount of host state; randomness is a JAX PRNG key that
is *threaded* through jitted step functions (see ``rng_context``) so that
compiled training steps stay pure while eager code keeps Paddle's stateful
``paddle.seed`` UX.
"""
from __future__ import annotations

import contextlib
import threading
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "set_default_dtype", "get_default_dtype", "set_printoptions",
    "seed", "get_rng_key",
    "split_key", "rng_context", "no_grad_guard", "is_grad_enabled",
    "set_grad_enabled", "in_functional_mode", "functional_mode",
    "Place", "CPUPlace", "TPUPlace", "set_device", "get_device",
    "convert_dtype", "DTYPE_MAP",
]

# ---------------------------------------------------------------------------
# dtype handling
# ---------------------------------------------------------------------------

DTYPE_MAP = {
    "float32": jnp.float32, "float16": jnp.float16, "bfloat16": jnp.bfloat16,
    "float64": jnp.float32,  # x64 is disabled JAX-side; degrade to f32
    "int64": jnp.int32,      # ditto: degrade to i32 (documented divergence)
    "int32": jnp.int32, "int16": jnp.int16, "int8": jnp.int8,
    "uint8": jnp.uint8, "bool": jnp.bool_,
    "complex64": jnp.complex64,
    "fp32": jnp.float32, "fp16": jnp.float16, "bf16": jnp.bfloat16,
}


def convert_dtype(dtype: Any):
    """Normalize a paddle-style dtype spec to a jnp dtype (or None)."""
    if dtype is None:
        return None
    if isinstance(dtype, str):
        if dtype not in DTYPE_MAP:
            raise ValueError(f"unsupported dtype string: {dtype!r}")
        return DTYPE_MAP[dtype]
    if dtype in (float,):
        return _state.default_dtype
    if dtype in (int,):
        return jnp.int32
    if dtype in (bool,):
        return jnp.bool_
    d = jnp.dtype(dtype)
    # degrade 64-bit requests (jax x64 disabled; TPU-first)
    if d == jnp.dtype("float64"):
        return jnp.float32
    if d == jnp.dtype("int64"):
        return jnp.int32
    return d


# ---------------------------------------------------------------------------
# thread-local framework state
# ---------------------------------------------------------------------------

class _State(threading.local):
    def __init__(self):
        self.grad_enabled: bool = True
        self.default_dtype = jnp.float32
        self._rng_key = None           # lazy: creating a key inits a backend
        self.rng_seed: int = 0
        self.rng_stack: list = []      # functional-mode threaded keys
        self.functional: bool = False  # True while compiling a pure step
        self._device: Optional[str] = None  # lazy: don't touch devices at
        self.amp_stack: list = []      # import (TPU tunnel is exclusive)
        self.lazy_init: int = 0        # LazyGuard nesting depth

    @property
    def rng_key(self):
        if self._rng_key is None:
            self._rng_key = jax.random.PRNGKey(self.rng_seed)
        return self._rng_key

    @rng_key.setter
    def rng_key(self, v):
        self._rng_key = v

    @property
    def device(self) -> str:
        if self._device is None:
            self._device = "tpu" if any(
                d.platform != "cpu" for d in jax.devices()) else "cpu"
        return self._device

    @device.setter
    def device(self, v: str):
        self._device = v


_state = _State()


def state() -> _State:
    return _state


def set_default_dtype(d) -> None:
    _state.default_dtype = convert_dtype(d)


def get_default_dtype() -> str:
    return jnp.dtype(_state.default_dtype).name


def set_printoptions(precision=None, threshold=None, edgeitems=None,
                     sci_mode=None, linewidth=None):
    """Tensor repr formatting (reference: paddle.set_printoptions,
    python/paddle/tensor/to_string.py — verify). Tensor.__repr__ renders
    through numpy, so this maps onto numpy's printoptions; ``sci_mode``
    toggles scientific notation (numpy's ``suppress`` inverted)."""
    kw = {}
    if precision is not None:
        kw["precision"] = int(precision)
    if threshold is not None:
        kw["threshold"] = int(threshold)
    if edgeitems is not None:
        kw["edgeitems"] = int(edgeitems)
    if linewidth is not None:
        kw["linewidth"] = int(linewidth)
    if sci_mode is not None:
        kw["suppress"] = not sci_mode
    np.set_printoptions(**kw)


# ---------------------------------------------------------------------------
# grad mode
# ---------------------------------------------------------------------------

def is_grad_enabled() -> bool:
    return _state.grad_enabled and not _state.functional


def set_grad_enabled(v: bool) -> None:
    _state.grad_enabled = bool(v)


@contextlib.contextmanager
def no_grad_guard():
    prev = _state.grad_enabled
    _state.grad_enabled = False
    try:
        yield
    finally:
        _state.grad_enabled = prev


def in_functional_mode() -> bool:
    return _state.functional


def in_static_mode() -> bool:
    return getattr(_state, "static_mode", False)


def set_static_mode(on: bool) -> None:
    _state.static_mode = on


@contextlib.contextmanager
def functional_mode():
    """While active, ops never record onto the eager tape (the surrounding
    ``jax.grad``/``jax.vjp`` of the step compiler owns differentiation)."""
    prev = _state.functional
    _state.functional = True
    try:
        yield
    finally:
        _state.functional = prev


def functional_wants_grad() -> bool:
    """True when the functional trace in progress will be differentiated
    by its surrounding vjp (set by the step compiler; consulted by
    dy2static to refuse non-transposable control flow upfront)."""
    return getattr(_state, "functional_wants_grad", False)


@contextlib.contextmanager
def functional_grad_hint(wants: bool):
    prev = getattr(_state, "functional_wants_grad", False)
    _state.functional_wants_grad = bool(wants)
    try:
        yield
    finally:
        _state.functional_wants_grad = prev


# ---------------------------------------------------------------------------
# RNG: stateful eager seed + pure threaded keys under jit
# ---------------------------------------------------------------------------

def seed(n: int) -> None:
    _state.rng_key = jax.random.PRNGKey(int(n))


def get_rng_key():
    return _state.rng_key


def split_key():
    """One fresh PRNG subkey.

    Eager: split the global key (stateful, matches ``paddle.seed`` UX).
    Functional mode (inside a compiled step): split the *threaded* key, so
    the trace derives all randomness from the per-step input key.
    """
    if _state.rng_stack:
        key = _state.rng_stack[-1]
        key, sub = jax.random.split(key)
        _state.rng_stack[-1] = key
        return sub
    key, sub = jax.random.split(_state.rng_key)
    _state.rng_key = key
    return sub


@contextlib.contextmanager
def rng_context(key):
    """Thread `key` as the RNG source (used by the step compiler)."""
    _state.rng_stack.append(key)
    try:
        yield
    finally:
        _state.rng_stack.pop()


# ---------------------------------------------------------------------------
# places / devices
# ---------------------------------------------------------------------------

class LazyGuard:
    """Defer parameter initialization inside the context (reference:
    paddle.LazyGuard — python/paddle/fluid/lazy_init.py, verify):
    ``with paddle.LazyGuard(): model = BigModel()`` builds the full
    module tree with :class:`~paddle_tpu.tensor.LazyParameter` leaves —
    shapes/dtypes known, zero initializer compute and weight memory —
    and every parameter materializes transparently on first value
    access (forward, state_dict, optimizer)."""

    def __enter__(self):
        _state.lazy_init += 1
        return self

    def __exit__(self, *exc):
        _state.lazy_init -= 1
        return False


def in_lazy_init() -> bool:
    return _state.lazy_init > 0


class Place:
    """Device place façade (reference: phi::Place — verify). On TPU the
    runtime places data via jax default device / shardings; Place is kept for
    API parity and host/device distinction."""

    def __init__(self, kind: str, index: int = 0):
        self.kind = kind
        self.index = index

    def __repr__(self):
        return f"Place({self.kind}:{self.index})"

    def __eq__(self, other):
        return (isinstance(other, Place) and self.kind == other.kind
                and self.index == other.index)


def CPUPlace() -> Place:
    return Place("cpu")


def TPUPlace(index: int = 0) -> Place:
    return Place("tpu", index)


def set_device(dev: str) -> Place:
    kind, _, idx = dev.partition(":")
    if kind in ("gpu", "cuda", "xpu"):  # parity alias: paddle scripts say gpu
        kind = "tpu"
    _state.device = kind
    return Place(kind, int(idx) if idx else 0)


def get_device() -> str:
    return _state.device


def default_backend_devices():
    return jax.devices()


class _DtypeInfo:
    def __init__(self, info, kind):
        self._i = info
        self.bits = info.bits
        self.max = float(info.max) if kind == "f" else int(info.max)
        self.min = float(info.min) if kind == "f" else int(info.min)
        self.dtype = str(np.dtype(info.dtype).name) if hasattr(
            info, "dtype") else ""
        if kind == "f":
            self.eps = float(info.eps)
            self.tiny = float(info.tiny)
            self.smallest_normal = float(info.tiny)
            self.resolution = float(info.resolution)

    def __repr__(self):
        return repr(self._i)


def iinfo(dtype):
    """paddle.iinfo parity: integer dtype limits."""
    return _DtypeInfo(np.iinfo(np.dtype(convert_dtype(dtype))), "i")


def finfo(dtype):
    """paddle.finfo parity: float dtype limits (bf16 via ml_dtypes)."""
    dt = convert_dtype(dtype)
    try:
        info = np.finfo(np.dtype(dt))
    except (TypeError, ValueError):
        import ml_dtypes
        info = ml_dtypes.finfo(dt)
    return _DtypeInfo(info, "f")


# paddle.framework.random parity (reference: python/paddle/framework/
# random.py — verify): rng state get/set over the jax key machinery
def get_rng_state():
    return [state().rng_key]


def set_rng_state(st):
    state().rng_key = st[0] if isinstance(st, (list, tuple)) else st
