"""Tensor façade + eager autograd tape.

Reference parity: paddle's eager ``Tensor`` (pybind class) with
``stop_gradient`` semantics, ``.grad`` accumulation, ``backward()``
(reference: paddle/fluid/pybind/eager_method.cc, paddle/fluid/eager/ — verify).

TPU-native design: a ``Tensor`` is a thin host wrapper over a ``jax.Array``
(or a tracer while inside a compiled step). Eager autograd is implemented as
a *vjp tape*: each differentiable op call runs ``jax.vjp`` immediately and
records the pullback; ``backward()`` replays the tape in reverse creation
order. Eager mode is the debug path — the perf path functionalizes whole
steps into one XLA program via ``paddle_tpu.jit`` where the tape is bypassed
and ``jax.grad`` differentiates the traced program (reference's dichotomy:
dygraph vs to_static/PIR).
"""
from __future__ import annotations

import numbers
from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from . import framework
from .framework import convert_dtype

__all__ = ["Tensor", "Parameter", "to_tensor", "apply_op",
           "reset_tape", "concrete_or_none"]


# ---------------------------------------------------------------------------
# The tape
# ---------------------------------------------------------------------------

class TapeNode:
    """One recorded differentiable op application.

    Outputs are held WEAKLY once sealed: backward walks only the
    subgraph reachable from its loss (unrelated live graphs survive a
    backward — reference eager semantics), and nodes whose every output
    has been garbage-collected are pruned incrementally, so dropped
    forward graphs don't pin memory."""
    __slots__ = ("vjp_fn", "inputs", "outputs", "idx", "multi",
                 "out_refs", "out_meta", "inplace", "fwd")

    def __init__(self, vjp_fn, inputs, outputs, idx, multi, fwd=None):
        self.vjp_fn = vjp_fn      # pullback: cotangents(out) -> cotangents(in)
        self.inputs = inputs      # list[Tensor] (diff inputs, tape order)
        self.outputs = outputs    # population box; dropped by seal()
        self.idx = idx
        self.multi = multi        # fn returned a tuple/list of arrays
        self.out_refs = None
        self.out_meta = None
        self.inplace = False      # output IS an input (zero_/fill_/…)
        # the forward pure fn over self.inputs' values, kept so
        # paddle.grad(create_graph=True) can REPLAY the subgraph as a
        # jax-differentiable function (residual-path second-order terms
        # need the forward, not just the pullback); None for custom
        # PyLayer nodes, whose double-grad is undefined
        self.fwd = fwd

    def seal(self):
        """Swap populated outputs for weakrefs + shape/dtype metadata
        (the metadata builds zero-cotangents for dead sibling outputs)."""
        import weakref
        self.out_refs = [weakref.ref(o) for o in self.outputs]
        self.out_meta = [(o._value.shape, o._value.dtype)
                         for o in self.outputs]
        self.outputs = None

    def live_outputs(self):
        if self.out_refs is None:      # unsealed (mid-apply_op)
            return list(self.outputs)
        return [r() for r in self.out_refs]


class _Tape:
    def __init__(self):
        self.nodes: list[TapeNode] = []

    def record(self, vjp_fn, inputs, outputs, multi=False, fwd=None):
        node = TapeNode(vjp_fn, inputs, outputs, len(self.nodes), multi,
                        fwd)
        self.nodes.append(node)
        return node

    def clear(self):
        self.nodes.clear()

    def gc(self):
        """Drop nodes whose every output died. A consumer is always newer
        than its producers, so one NEWEST-FIRST pass reaches the fixpoint
        — PROVIDED each dead consumer is actually released (del from the
        list + clear the loop variable) BEFORE its producers are tested,
        so the refcount drop frees the producer outputs in time."""
        i = len(self.nodes) - 1
        while i >= 0:
            n = self.nodes[i]
            alive = n.out_refs is None or \
                any(r() is not None for r in n.out_refs)
            if not alive:
                del self.nodes[i]
            n = None            # release before testing the next (older)
            i -= 1


_TAPE = _Tape()


def reset_tape():
    _TAPE.clear()


def _tape():
    return _TAPE


# ---------------------------------------------------------------------------
# Tensor
# ---------------------------------------------------------------------------

class Tensor:
    __slots__ = ("_value", "stop_gradient", "grad", "_node", "_out_index",
                 "name", "persistable", "is_leaf", "trainable",
                 # semi-auto parallel metadata (set by dist.shard_tensor)
                 "dist_attr", "process_mesh", "placements",
                 # Partial placement: names of mesh axes over which _value
                 # carries an UNREDUCED leading contribution dim each (the
                 # global value is the sum over those dims); resolved to a
                 # dense value on first consumption (dist.reshard p→r)
                 "_partial_axes",
                 # static-graph mode: producer record (paddle_tpu.static)
                 # + static.gradients() marker (targets, wrt)
                 "_static_src", "_static_grad",
                 # nn.quant int4 packing: original (pre-pad) row count a
                 # packed weight unpacks back to (odd in_features)
                 "_orig_in_features", "__weakref__")

    def __init__(self, value, stop_gradient: bool = True,
                 name: Optional[str] = None):
        self._value = value
        self.stop_gradient = stop_gradient
        self.grad: Optional[Tensor] = None
        self._node: Optional[TapeNode] = None
        self._out_index: int = 0
        self.name = name
        self.persistable = False
        self.is_leaf = True
        self.trainable = not stop_gradient

    # -- basic properties ---------------------------------------------------
    @property
    def value(self):
        return self._value

    @property
    def shape(self):
        np_ = len(getattr(self, "_partial_axes", None) or ())
        return list(self._value.shape[np_:])

    @property
    def ndim(self):
        np_ = len(getattr(self, "_partial_axes", None) or ())
        return self._value.ndim - np_

    @property
    def dtype(self):
        return self._value.dtype

    @property
    def size(self):
        return int(np.prod(self._value.shape)) if self._value.shape else 1

    @property
    def place(self):
        from .framework import Place
        try:
            dev = next(iter(self._value.devices()))
            return Place(dev.platform, dev.id)
        except Exception:
            return Place(framework.get_device())

    @property
    def T(self):
        from . import ops
        return ops.t(self)

    def dim(self):
        return self.ndim

    def numel(self):
        return self.size

    def element_size(self):
        return jnp.dtype(self.dtype).itemsize

    # -- host interop -------------------------------------------------------
    def _dense_value(self):
        """Value with any Partial contribution dims summed out."""
        np_ = len(getattr(self, "_partial_axes", None) or ())
        return self._value.sum(axis=tuple(range(np_))) if np_ \
            else self._value

    def numpy(self):
        return np.asarray(self._dense_value())

    def item(self):
        return self._dense_value().item()

    def tolist(self):
        return np.asarray(self._dense_value()).tolist()

    def __array__(self, dtype=None):
        a = np.asarray(self._dense_value())
        return a.astype(dtype) if dtype is not None else a

    def __float__(self):
        return float(self._dense_value())

    def __int__(self):
        return int(self._dense_value())

    def __index__(self):
        # lets range(n)/slicing accept a concrete 0-d integer Tensor;
        # under tracing jax raises TracerIntegerConversionError, which
        # to_static catches and routes into the conversion pipeline
        return self._dense_value().__index__()

    def __bool__(self):
        return bool(self._dense_value())

    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of a 0-D tensor")
        return self.shape[0]

    def __hash__(self):
        return id(self)

    def __repr__(self):
        grad_s = "" if self.stop_gradient else ", stop_gradient=False"
        return (f"Tensor(shape={self.shape}, dtype={jnp.dtype(self.dtype).name}"
                f"{grad_s},\n       {np.asarray(self._dense_value())!r})")

    # -- autograd -----------------------------------------------------------
    def backward(self, grad_tensor: Optional["Tensor"] = None,
                 retain_graph: bool = False):
        from .autograd import backward
        backward(self, grad_tensor, retain_graph=retain_graph)

    def clear_grad(self):
        self.grad = None

    def clear_gradient(self, set_to_zero: bool = False):
        if set_to_zero and self.grad is not None:
            self.grad = Tensor(jnp.zeros_like(self.grad._value))
        else:
            self.grad = None

    def detach(self) -> "Tensor":
        t = Tensor(self._value, stop_gradient=True, name=self.name)
        return t

    def detach_(self):
        self._node = None
        self.stop_gradient = True
        return self

    def clone(self) -> "Tensor":
        from . import ops
        return ops.assign(self)

    def stop_gradient_(self, v: bool):
        self.stop_gradient = v
        return self

    def register_hook(self, hook):
        # eager grad hook: applied when backward deposits into .grad
        if not hasattr(self, "_hooks"):
            pass
        # stored on the node at deposit time via autograd module
        from .autograd import _register_tensor_hook
        return _register_tensor_hook(self, hook)

    # -- in-place-ish mutators (replace payload; used by optimizers) --------
    def set_value(self, v):
        if isinstance(v, Tensor):
            v = v._value
        v = jnp.asarray(v, dtype=self.dtype)
        if tuple(v.shape) != tuple(self._value.shape):
            raise ValueError(
                f"set_value shape mismatch {v.shape} vs {self._value.shape}")
        self._value = v
        return self

    def copy_(self, other, blocking: bool = True):
        return self.set_value(other)

    def _update_value(self, v):
        """Unchecked payload swap (step compiler / optimizers)."""
        self._value = v

    def _notify_inplace_hook(self, name):
        """amp.debugging visibility for in-place ops (they bypass
        apply_op)."""
        if _OP_HOOK[0] is not None and not framework.in_functional_mode():
            class _Named:
                __qualname__ = name
            _run_op_hook(_Named, [self])

    def _record_inplace(self, pure, extra_inputs=()):
        """Tape-aware in-place update: record ``new = pure(old, *extras)``
        with self as both input and output (the eager engine's version-bump;
        reference tracks this via TensorWrapper inplace_version — verify).
        Correct under the id-keyed cotangent walk because tape nodes replay
        in reverse creation order: cotangents deposited by ops that read the
        NEW value are popped by this node, pass through the pullback, and
        re-deposit for ops that produced/read the OLD value."""
        in_tensors = [self] + [t for t in extra_inputs
                               if isinstance(t, Tensor)]
        out, vjp_fn = jax.vjp(pure, self._value,
                              *[t._value for t in in_tensors[1:]])
        node = _TAPE.record(vjp_fn, in_tensors, [self], multi=False,
                            fwd=pure)
        node.inplace = True
        self._value = out
        node.seal()
        self._node = node
        self._out_index = 0
        self.is_leaf = False
        self.stop_gradient = False
        self._notify_inplace_hook(pure.__qualname__
                                  if hasattr(pure, "__qualname__")
                                  else "inplace")
        return self

    def _reject_static_inplace(self, name):
        """Static graphs replay by tensor identity with no SSA
        versioning — a silent value overwrite would drop the op from
        the compiled program (see make_inplace)."""
        if (framework.in_static_mode()
                and not framework.in_functional_mode()):
            raise RuntimeError(
                f"{name}: in-place mutation is not recordable in "
                "static-graph mode; use the out-of-place op instead")

    def _inplace_wants_grad(self, *vals) -> bool:
        return (framework.is_grad_enabled()
                and not framework.in_static_mode()
                and (not self.stop_gradient
                     or any(isinstance(v, Tensor) and not v.stop_gradient
                            for v in vals)))

    def fill_(self, v):
        self._reject_static_inplace("fill_")
        if self._inplace_wants_grad():
            # constant overwrite: gradient to the old value is zero — the
            # recorded pullback encodes exactly that cut
            return self._record_inplace(lambda x: jnp.full_like(x, v))
        self._value = jnp.full_like(self._value, v)
        self._notify_inplace_hook("fill_")
        return self

    def zero_(self):
        self._reject_static_inplace("zero_")
        if self._inplace_wants_grad():
            return self._record_inplace(lambda x: jnp.zeros_like(x))
        self._value = jnp.zeros_like(self._value)
        self._notify_inplace_hook("zero_")
        return self

    def _random_overwrite_(self, sample):
        """Shared body of the in-place random fills (uniform_/normal_/…):
        like fill_, the overwrite cuts the gradient to the old value."""
        self._reject_static_inplace("random_overwrite_")
        new = sample(framework.split_key())
        if self._inplace_wants_grad():
            return self._record_inplace(
                lambda x: jnp.broadcast_to(new, x.shape).astype(x.dtype))
        self._value = new.astype(self._value.dtype)
        self._notify_inplace_hook("random_overwrite_")
        return self

    def uniform_(self, min=-1.0, max=1.0, seed=0, name=None):
        shape, dt = self._value.shape, self._value.dtype
        return self._random_overwrite_(lambda k: jax.random.uniform(
            k if not seed else jax.random.PRNGKey(seed), shape,
            jnp.float32, minval=min, maxval=max))

    def normal_(self, mean=0.0, std=1.0, name=None):
        shape = self._value.shape
        return self._random_overwrite_(
            lambda k: jax.random.normal(k, shape, jnp.float32) * std + mean)

    def exponential_(self, lam=1.0, name=None):
        shape = self._value.shape
        return self._random_overwrite_(
            lambda k: jax.random.exponential(k, shape, jnp.float32) / lam)

    def log_normal_(self, mean=1.0, std=2.0, name=None):
        shape = self._value.shape
        return self._random_overwrite_(lambda k: jnp.exp(
            jax.random.normal(k, shape, jnp.float32) * std + mean))

    def geometric_(self, probs, name=None):
        """Geometric(probs) fill: number of Bernoulli(p) trials to first
        success, support {1, 2, ...} (the reference's convention)."""
        shape = self._value.shape
        return self._random_overwrite_(lambda k: jnp.ceil(
            jnp.log1p(-jax.random.uniform(k, shape, jnp.float32))
            / jnp.log1p(-jnp.asarray(probs, jnp.float32))))

    # -- dunder arithmetic (defined in ops/__init__.py monkey-attach) -------
    # __add__ etc. attached by paddle_tpu.ops at import time.

    def astype(self, dtype):
        from . import ops
        return ops.cast(self, dtype)

    def cast(self, dtype):
        return self.astype(dtype)

    def cuda(self, *a, **k):
        return self  # parity no-op: data already on accelerator

    def cpu(self):
        t = Tensor(jax.device_get(self._value), self.stop_gradient)
        return t

    def pin_memory(self):
        return self

    def to(self, *args, **kwargs):
        dtype = kwargs.get("dtype")
        for a in args:
            if isinstance(a, str) and a in framework.DTYPE_MAP:
                dtype = a
            elif not isinstance(a, str):
                dtype = a
        if dtype is not None:
            return self.astype(dtype)
        return self

    # -- indexing -----------------------------------------------------------
    def __getitem__(self, idx):
        from . import ops
        return ops.getitem(self, idx)

    def __setitem__(self, idx, val):
        self._reject_static_inplace("Tensor.__setitem__")

        def unwrap_idx(i):
            if isinstance(i, Tensor):
                return i._value
            if isinstance(i, tuple):
                return tuple(unwrap_idx(e) for e in i)
            return i

        idx = unwrap_idx(idx)

        def fit(v, x):
            # numpy-style assignment shapes: (1,) into a scalar slot etc.
            # tgt computed only when an array value is actually assigned
            # (eval_shape is trace-only but not free on the eager hot path)
            v = v.astype(x.dtype) if v.dtype != x.dtype else v
            tgt = jax.eval_shape(lambda a: a[idx], x).shape
            if tuple(v.shape) != tuple(tgt):
                if int(np.prod(v.shape)) == int(np.prod(tgt)):
                    v = v.reshape(tgt)
                else:
                    v = jnp.broadcast_to(v, tgt)
            return v

        if self._inplace_wants_grad(val):
            if isinstance(val, Tensor):
                return self._record_inplace(
                    lambda x, v: x.at[idx].set(fit(v, x)),
                    extra_inputs=(val,))
            if hasattr(val, "shape") and hasattr(val, "dtype"):
                cv = fit(jnp.asarray(val), self._value)
                return self._record_inplace(lambda x: x.at[idx].set(cv))
            return self._record_inplace(lambda x: x.at[idx].set(val))
        if isinstance(val, Tensor):
            val = val._value
        if hasattr(val, "shape") and hasattr(val, "dtype"):
            val = fit(jnp.asarray(val), self._value)
        self._value = self._value.at[idx].set(val)

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]


class Parameter(Tensor):
    """Trainable tensor (reference: paddle.base.framework.Parameter — verify)."""
    __slots__ = ("optimize_attr", "regularizer", "do_model_average",
                 "need_clip", "is_distributed", "_sharding_spec",
                 "pp_stage", "sequence_parallel")

    def __init__(self, value, name=None, trainable=True):
        super().__init__(value, stop_gradient=not trainable, name=name)
        self.persistable = True
        self.optimize_attr = {"learning_rate": 1.0}
        self.regularizer = None
        self.do_model_average = None
        self.need_clip = True
        self.is_distributed = False
        self._sharding_spec = None  # jax PartitionSpec for auto-parallel

    def __repr__(self):
        return "Parameter " + super().__repr__()


_VALUE_SLOT = Tensor.__dict__["_value"]


class LazyParameter(Parameter):
    """Parameter whose initializer runs on FIRST value access
    (reference: paddle.LazyGuard lazy init for big models —
    python/paddle/fluid/lazy_init.py — verify).

    Shape/dtype come from the deferred spec, so constructing and
    inspecting a multi-billion-parameter architecture (param counts,
    layer wiring, sharding planning) costs no initializer compute or
    weight memory; any ``_value`` read — forward, state_dict, optimizer
    — materializes transparently. Under jit this also means a sharded
    init path can materialize directly into the target sharding."""
    __slots__ = ("_lazy_init",)

    def __init__(self, init_fn, shape, dtype, name=None, trainable=True):
        self._lazy_init = (init_fn, tuple(int(s) for s in shape), dtype)
        super().__init__(None, name=name, trainable=trainable)
        _VALUE_SLOT.__delete__(self)    # reads now trigger materialize

    # the subclass property shadows the Tensor slot; the slot member
    # descriptor remains the actual storage
    @property
    def _value(self):
        try:
            return _VALUE_SLOT.__get__(self)
        except AttributeError:
            init_fn, shape, dtype = self._lazy_init
            _VALUE_SLOT.__set__(self, init_fn(shape, dtype))
            return _VALUE_SLOT.__get__(self)

    @_value.setter
    def _value(self, v):
        _VALUE_SLOT.__set__(self, v)

    def materialized(self) -> bool:
        try:
            _VALUE_SLOT.__get__(self)
            return True
        except AttributeError:
            return False

    @property
    def shape(self):
        if not self.materialized():
            return list(self._lazy_init[1])
        return super().shape

    @property
    def ndim(self):
        if not self.materialized():
            return len(self._lazy_init[1])
        return super().ndim

    @property
    def size(self):
        if not self.materialized():
            return int(np.prod(self._lazy_init[1])) \
                if self._lazy_init[1] else 1
        return super().size

    @property
    def dtype(self):
        if not self.materialized():
            return jax.dtypes.canonicalize_dtype(self._lazy_init[2])
        return super().dtype

    def __repr__(self):
        if not self.materialized():
            return (f"LazyParameter(shape={self.shape}, "
                    f"dtype={self.dtype}, unmaterialized)")
        return "Lazy" + super().__repr__()


# ---------------------------------------------------------------------------
# op application: the single dispatch point of the framework
# ---------------------------------------------------------------------------

def _wrap_outputs(out, diff: bool, node_setter):
    if isinstance(out, (tuple, list)):
        outs = []
        for i, o in enumerate(out):
            t = Tensor(o, stop_gradient=not diff)
            t.is_leaf = False
            if diff:
                node_setter(t, i)
            outs.append(t)
        return type(out)(outs) if isinstance(out, tuple) else outs
    t = Tensor(out, stop_gradient=not diff)
    t.is_leaf = False
    if diff:
        node_setter(t, 0)
    return t


class _StaticSrc:
    """Producer record for a symbolic tensor in static-graph mode: the
    pure fn plus its input Tensors (paddle_tpu.static replays these)."""
    __slots__ = ("pure", "inputs", "multi")

    def __init__(self, pure, inputs, multi):
        self.pure = pure
        self.inputs = inputs
        self.multi = multi


def _apply_op_static(fn, args, kwargs, tensor_pos):
    """Static-graph branch: no compute — infer output avals with
    jax.eval_shape and record the producer so Executor.run can replay
    the graph into one jitted XLA program (the reference's
    ProgramDesc/PIR build step)."""
    in_tensors = [args[i] for i in tensor_pos]

    def pure(*tvals):
        full = list(args)
        for p, v in zip(tensor_pos, tvals):
            full[p] = v
        full = [a._value if isinstance(a, Tensor) else a for a in full]
        return fn(*full, **kwargs)

    out_aval = jax.eval_shape(pure, *[t._value for t in in_tensors])
    multi = isinstance(out_aval, (tuple, list))
    src = _StaticSrc(pure, in_tensors, multi)
    outs = []
    for i, av in enumerate(out_aval if multi else [out_aval]):
        t = Tensor(av, stop_gradient=all(x.stop_gradient
                                         for x in in_tensors))
        t._static_src = src
        t._out_index = i
        t.is_leaf = False
        outs.append(t)
    if multi:
        return type(out_aval)(outs)
    return outs[0]


def _departial(t: "Tensor") -> "Tensor":
    """Resolve a Partial-placed tensor (leading unreduced contribution
    dims, see dist.shard_tensor) into its dense global value — the
    reference's implicit p→r reshard on consumption. The sum over the
    stacked dim lowers to a psum over the partial mesh axis."""
    axes = getattr(t, "_partial_axes", None)
    if not axes:
        return t
    k = len(axes)
    stripped = Tensor(t._value, stop_gradient=t.stop_gradient)
    stripped._node = t._node
    stripped._out_index = t._out_index
    stripped.is_leaf = t.is_leaf
    return apply_op(lambda v: v.sum(axis=tuple(range(k))), stripped)


# amp.debugging hook: when set, called as hook(fn, output_tensors) after
# every eager dispatch (op stats / per-op nan checks)
_OP_HOOK: list = [None]


def _run_op_hook(fn, result):
    hook = _OP_HOOK[0]
    if hook is None:
        return
    outs = result if isinstance(result, (tuple, list)) else [result]
    hook(fn, [o for o in outs if isinstance(o, Tensor)])


def concrete_or_none(x):
    """np.ndarray of ``x``'s value when it is concrete, else None (the
    uniform tracer-skip contract for eager-only validation checks)."""
    try:
        return np.asarray(x._value if isinstance(x, Tensor) else x)
    except (TypeError, AttributeError):
        return None


def make_inplace(op, name=None):
    """In-place variant of single-output ``op`` (the reference's
    ``x_``-suffix ops). With grad wanted this records through
    ``_record_inplace`` — re-pointing x at the out-of-place result's
    node would register the output under the temp tensor's id and the
    id-keyed cotangent walk would skip the op entirely. Static mode
    raises (the replay graph has no SSA versioning). Differentiable
    (inexact-dtype) Tensor operands become vjp inputs; integer tensors
    (indices) are closed over by value."""
    opname = name or getattr(op, "__name__", "op")

    def f(x, *a, **k):
        x._reject_static_inplace(opname + "_")
        extras = tuple(
            t for t in list(a) + list(k.values())
            if isinstance(t, Tensor)
            and jnp.issubdtype(t._value.dtype, jnp.inexact))
        if x._inplace_wants_grad(*extras):
            ids = {id(t) for t in extras}

            def pure(xv, *ev):
                it = iter(ev)

                def wrap(arg):
                    if isinstance(arg, Tensor):
                        return Tensor(next(it)) if id(arg) in ids \
                            else Tensor(arg._value)
                    return arg
                with framework.no_grad_guard():
                    aa = [wrap(arg) for arg in a]
                    kk = {kn: wrap(kv) for kn, kv in k.items()}
                    return op(Tensor(xv), *aa, **kk)._value
            pure.__qualname__ = opname + "_"
            return x._record_inplace(pure, extras)
        out = op(x, *a, **k)
        x._value = out._value
        x._notify_inplace_hook(opname + "_")
        return x
    f.__name__ = f.__qualname__ = opname + "_"
    return f


def apply_op(fn, *args, **kwargs):
    """Run pure-jax `fn` on Tensor/array args; record vjp on the tape when
    eager grad is enabled and any Tensor input requires grad.

    Non-Tensor args (ints, axis tuples, python scalars) are closed over as
    statics. Returns Tensor or tuple/list of Tensors mirroring fn's output.
    """
    tensor_pos = [i for i, a in enumerate(args) if isinstance(a, Tensor)]
    if tensor_pos and any(
            getattr(args[i], "_partial_axes", None) for i in tensor_pos):
        args = list(args)
        for i in tensor_pos:
            args[i] = _departial(args[i])
        args = tuple(args)

    if framework.in_static_mode() and not framework.in_functional_mode():
        return _apply_op_static(fn, args, kwargs, tensor_pos)

    want_grad = (framework.is_grad_enabled()
                 and any(not args[i].stop_gradient for i in tensor_pos))

    if not want_grad:
        vals = [a._value if isinstance(a, Tensor) else a for a in args]
        out = fn(*vals, **kwargs)
        result = _wrap_outputs(out, False, None)
        if _OP_HOOK[0] is not None and not framework.in_functional_mode():
            _run_op_hook(fn, result)
        return result

    in_tensors = [args[i] for i in tensor_pos]
    in_vals = tuple(t._value for t in in_tensors)

    out_type_box = [None]

    def pure(*tvals):
        full = list(args)
        for p, v in zip(tensor_pos, tvals):
            full[p] = v
        full = [a._value if isinstance(a, Tensor) else a for a in full]
        r = fn(*full, **kwargs)
        if isinstance(r, (tuple, list)):
            out_type_box[0] = type(r)
            return tuple(r)  # normalize pytree so cotangents are tuples
        return r

    out, vjp_fn = jax.vjp(pure, *in_vals)
    if out_type_box[0] is list:
        out = list(out)

    outputs_box: list = []
    node = _TAPE.record(vjp_fn, in_tensors, outputs_box,
                        multi=isinstance(out, (tuple, list)), fwd=pure)

    def setter(t, i):
        t._node = node
        t._out_index = i
        outputs_box.append(t)

    wrapped = _wrap_outputs(out, True, setter)
    node.seal()
    if _OP_HOOK[0] is not None and not framework.in_functional_mode():
        _run_op_hook(fn, wrapped)
    return wrapped


# ---------------------------------------------------------------------------
# to_tensor
# ---------------------------------------------------------------------------

def to_tensor(data, dtype=None, place=None, stop_gradient: bool = True):
    """paddle.to_tensor parity (reference: python/paddle/tensor/creation.py
    — verify)."""
    if isinstance(data, Tensor):
        v = data._dense_value()  # Partial tensors copy as dense values
        if dtype is not None:
            v = v.astype(convert_dtype(dtype))
        return Tensor(v, stop_gradient=stop_gradient)
    if isinstance(data, (list, tuple)):
        # may contain Tensors
        def unwrap(x):
            if isinstance(x, Tensor):
                return np.asarray(x._value)
            if isinstance(x, (list, tuple)):
                return [unwrap(e) for e in x]
            return x
        data = unwrap(data)
    d = convert_dtype(dtype)
    if isinstance(data, jax.Array) or isinstance(data, jax.core.Tracer):
        # already on device (or a tracer inside jit) — never round-trip
        # through host numpy
        v = data if d is None else data.astype(d)
        return Tensor(v, stop_gradient=stop_gradient)
    arr = np.asarray(data)
    if d is None:
        if arr.dtype == np.float64:
            d = framework.state().default_dtype
        elif arr.dtype == np.int64:
            d = jnp.int32
    v = jnp.asarray(arr, dtype=d)
    return Tensor(v, stop_gradient=stop_gradient)
