"""Device management (reference: python/paddle/device/ — verify). The cuda
submodule is aliased to TPU equivalents so reference scripts keep working."""
from __future__ import annotations

import jax

from ..framework import set_device, get_device, Place

__all__ = ["set_device", "get_device", "get_available_device",
           "get_available_custom_device", "device_count", "cuda",
           "is_compiled_with_cuda", "synchronize", "Stream", "Event",
           "current_stream", "set_stream", "stream_guard",
           "register_custom_device", "get_all_custom_device_type"]


def get_available_device():
    return [f"{d.platform}:{d.id}" for d in jax.devices()]


def get_available_custom_device():
    out = []
    for name in _CUSTOM_DEVICES:
        try:
            out.extend(f"{name}:{d.id}" for d in jax.devices(name))
        except Exception:
            pass   # registered but backend not (yet) loaded
    return out


def device_count():
    return len(jax.devices())


def is_compiled_with_cuda():
    return False


def synchronize(device=None):
    # XLA dispatch is async; effective barrier:
    import jax.numpy as jnp
    jnp.zeros(()).block_until_ready()


class _CudaNamespace:
    """paddle.device.cuda.* parity mapped to the TPU runtime."""

    @staticmethod
    def device_count():
        return len(jax.devices())

    @staticmethod
    def synchronize(device=None):
        synchronize(device)

    @staticmethod
    def empty_cache():
        pass

    @staticmethod
    def max_memory_allocated(device=None):
        stats = jax.devices()[0].memory_stats() or {}
        return stats.get("peak_bytes_in_use", 0)

    @staticmethod
    def memory_allocated(device=None):
        stats = jax.devices()[0].memory_stats() or {}
        return stats.get("bytes_in_use", 0)

    @staticmethod
    def max_memory_reserved(device=None):
        stats = jax.devices()[0].memory_stats() or {}
        return stats.get("peak_bytes_in_use", 0)

    @staticmethod
    def memory_reserved(device=None):
        stats = jax.devices()[0].memory_stats() or {}
        return stats.get("bytes_limit", 0)

    @staticmethod
    def current_stream(device=None):
        return _default_stream

    @staticmethod
    def stream_guard(stream):
        return stream_guard(stream)

    Event = None  # assigned below (shared with paddle.device.Event)
    Stream = None


class Event:
    """paddle.device.Event parity. XLA has no user events; ``record``
    drains the async dispatch queue and timestamps — correct wall-clock
    semantics for the profiling uses the reference API serves
    (reference: paddle/phi/backends event APIs — verify)."""

    def __init__(self, device=None, enable_timing=True, blocking=False,
                 interprocess=False):
        self._t = None

    def record(self, stream=None):
        import time
        synchronize()
        self._t = time.perf_counter()

    def query(self):
        return self._t is not None

    def synchronize(self):
        synchronize()

    def elapsed_time(self, end):
        if self._t is None or end._t is None:
            raise RuntimeError("elapsed_time needs both events recorded")
        return (end._t - self._t) * 1000.0


class Stream:
    """paddle.device.Stream parity. XLA owns real streams (async
    dispatch + latency-hiding scheduler); this logical handle preserves
    the reference API: per-stream sync, event recording, and
    wait_event/wait_stream ordering (already guaranteed by XLA's
    program order, so they are correct no-ops)."""

    def __init__(self, device=None, priority=2, **kw):
        self.device = device

    def synchronize(self):
        synchronize()

    def record_event(self, event=None):
        event = event or Event()
        event.record(self)
        return event

    def wait_event(self, event):
        pass  # ordering is XLA program order

    def wait_stream(self, stream):
        pass

    def query(self):
        return True


class CudaEvent(Event):
    """paddle.device.cuda.Event signature parity: first positional is
    enable_timing, not device."""

    def __init__(self, enable_timing=False, blocking=False,
                 interprocess=False):
        super().__init__(enable_timing=enable_timing, blocking=blocking,
                         interprocess=interprocess)


_default_stream = Stream()
_CudaNamespace.Event = CudaEvent
_CudaNamespace.Stream = Stream


def current_stream(device=None):
    return _default_stream


def set_stream(stream):
    global _default_stream
    prev = _default_stream
    _default_stream = stream
    return prev


class stream_guard:
    def __init__(self, stream):
        self._stream = stream
        self._prev = None

    def __enter__(self):
        self._prev = set_stream(self._stream)
        return self._stream

    def __exit__(self, *exc):
        set_stream(self._prev)


cuda = _CudaNamespace()


# ---------------------------------------------------------------------------
# custom-device plugin surface (reference: paddle/phi/backends custom
# device C API + CustomDevice registration — verify)
# ---------------------------------------------------------------------------

_CUSTOM_DEVICES: dict = {}


def register_custom_device(name: str, library_path: str):
    """Register an out-of-tree accelerator plugin (reference: the custom
    -device C API loading device_ext.so — verify). TPU-native analogue:
    a PJRT plugin .so — jax discovers it through
    ``PJRT_NAMES_AND_LIBRARY_PATHS``. Must be called BEFORE the first
    backend use; raises if the backend already initialized or the
    library does not exist."""
    import os

    from ..utils.enforce import (AlreadyExistsError, NotFoundError,
                                 PreconditionNotMetError)
    if name in _CUSTOM_DEVICES:
        raise AlreadyExistsError(
            f"custom device {name!r} already registered "
            f"({_CUSTOM_DEVICES[name]})")
    if not os.path.exists(library_path):
        raise NotFoundError(
            f"custom device plugin library not found: {library_path}",
            "point at the PJRT plugin .so built for this accelerator")
    try:
        backends_initialized = bool(jax._src.xla_bridge._backends)
    except Exception:
        # fail CLOSED: if the (private) probe breaks on a jax upgrade,
        # refusing registration is recoverable; silently setting env
        # vars jax already consumed is not
        backends_initialized = True
    if backends_initialized:
        raise PreconditionNotMetError(
            "jax backends already initialized; register custom devices "
            "before the first jax.devices()/computation",
            "set PJRT_NAMES_AND_LIBRARY_PATHS in the environment before "
            "process start for late registration")
    entry = f"{name}:{library_path}"
    cur = os.environ.get("PJRT_NAMES_AND_LIBRARY_PATHS", "")
    os.environ["PJRT_NAMES_AND_LIBRARY_PATHS"] = \
        f"{cur},{entry}" if cur else entry
    _CUSTOM_DEVICES[name] = library_path


def get_all_custom_device_type():
    return list(_CUSTOM_DEVICES)
