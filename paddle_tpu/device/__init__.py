"""Device management (reference: python/paddle/device/ — verify). The cuda
submodule is aliased to TPU equivalents so reference scripts keep working."""
from __future__ import annotations

import jax

from ..framework import set_device, get_device, Place

__all__ = ["set_device", "get_device", "get_available_device",
           "get_available_custom_device", "device_count", "cuda",
           "is_compiled_with_cuda", "synchronize"]


def get_available_device():
    return [f"{d.platform}:{d.id}" for d in jax.devices()]


def get_available_custom_device():
    return []


def device_count():
    return len(jax.devices())


def is_compiled_with_cuda():
    return False


def synchronize(device=None):
    # XLA dispatch is async; effective barrier:
    import jax.numpy as jnp
    jnp.zeros(()).block_until_ready()


class _CudaNamespace:
    """paddle.device.cuda.* parity mapped to the TPU runtime."""

    @staticmethod
    def device_count():
        return len(jax.devices())

    @staticmethod
    def synchronize(device=None):
        synchronize(device)

    @staticmethod
    def empty_cache():
        pass

    @staticmethod
    def max_memory_allocated(device=None):
        stats = jax.devices()[0].memory_stats() or {}
        return stats.get("peak_bytes_in_use", 0)

    @staticmethod
    def memory_allocated(device=None):
        stats = jax.devices()[0].memory_stats() or {}
        return stats.get("bytes_in_use", 0)

    @staticmethod
    def max_memory_reserved(device=None):
        stats = jax.devices()[0].memory_stats() or {}
        return stats.get("peak_bytes_in_use", 0)

    @staticmethod
    def memory_reserved(device=None):
        stats = jax.devices()[0].memory_stats() or {}
        return stats.get("bytes_limit", 0)

    class Event:
        def __init__(self, enable_timing=False, **kw):
            self._t = None

        def record(self, stream=None):
            import time
            synchronize()
            self._t = time.perf_counter()

        def elapsed_time(self, end):
            return (end._t - self._t) * 1000.0

    class Stream:
        def __init__(self, *a, **k):
            pass

        def synchronize(self):
            synchronize()


cuda = _CudaNamespace()
