"""Probability distributions (``paddle.distribution`` parity).

Reference parity: python/paddle/distribution/ (Distribution base,
Normal/Uniform/Categorical/..., kl_divergence + register_kl,
TransformedDistribution + transforms — verify).

TPU-native design: parameters live as jnp arrays; ``sample`` draws from
the framework's threaded PRNG key (``framework.split_key``) so sampling is
reproducible under ``paddle.seed`` and traceable inside jitted code via
``rng_context``. log_prob/entropy are pure jnp — they fuse into
surrounding XLA programs (the reference dispatches per-op CUDA kernels).
"""
from __future__ import annotations

import math
from typing import Sequence

import jax
import jax.numpy as jnp

from .. import framework
from ..tensor import Tensor

__all__ = [
    "Distribution", "ExponentialFamily", "Normal", "Uniform", "Bernoulli",
    "Beta", "Binomial", "Categorical", "Cauchy", "Chi2", "Dirichlet",
    "Exponential", "Gamma", "Geometric", "Gumbel", "Laplace", "LogNormal",
    "Multinomial", "MultivariateNormal", "Poisson", "StudentT",
    "TransformedDistribution", "Transform", "AffineTransform", "ExpTransform",
    "SigmoidTransform", "kl_divergence", "register_kl",
]


def _arr(x):
    if isinstance(x, Tensor):
        return x._value
    return jnp.asarray(x, jnp.float32) if not isinstance(x, jnp.ndarray) \
        else x


def _shape(sample_shape):
    if sample_shape is None:
        return ()
    if isinstance(sample_shape, int):
        return (sample_shape,)
    return tuple(int(s) for s in sample_shape)


class Distribution:
    def __init__(self, batch_shape=(), event_shape=()):
        self._batch_shape = tuple(batch_shape)
        self._event_shape = tuple(event_shape)

    @property
    def batch_shape(self):
        return self._batch_shape

    @property
    def event_shape(self):
        return self._event_shape

    @property
    def mean(self):
        raise NotImplementedError

    @property
    def variance(self):
        raise NotImplementedError

    def sample(self, shape=()):
        """Non-reparameterized draw (gradients do not flow)."""
        return Tensor(jax.lax.stop_gradient(
            self._sample(_shape(shape), framework.split_key())))

    def rsample(self, shape=()):
        """Reparameterized draw where the distribution supports it."""
        return Tensor(self._sample(_shape(shape), framework.split_key()))

    def log_prob(self, value):
        return Tensor(self._log_prob(_arr(value)))

    def prob(self, value):
        return Tensor(jnp.exp(self._log_prob(_arr(value))))

    def entropy(self):
        return Tensor(self._entropy())

    def kl_divergence(self, other):
        return kl_divergence(self, other)

    def _extend(self, shape):
        return shape + self._batch_shape + self._event_shape


class ExponentialFamily(Distribution):
    pass


# ---------------------------------------------------------------------------

class Normal(ExponentialFamily):
    def __init__(self, loc, scale, name=None):
        self.loc, self.scale = _arr(loc), _arr(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape,
                                              self.scale.shape))

    @property
    def mean(self):
        return Tensor(jnp.broadcast_to(self.loc, self._batch_shape))

    @property
    def variance(self):
        return Tensor(jnp.broadcast_to(self.scale ** 2, self._batch_shape))

    @property
    def stddev(self):
        return Tensor(jnp.broadcast_to(self.scale, self._batch_shape))

    def _sample(self, shape, key):
        return self.loc + self.scale * jax.random.normal(
            key, self._extend(shape), jnp.asarray(self.loc).dtype
            if jnp.issubdtype(jnp.asarray(self.loc).dtype, jnp.floating)
            else jnp.float32)

    def _log_prob(self, v):
        var = self.scale ** 2
        return -((v - self.loc) ** 2) / (2 * var) - jnp.log(self.scale) \
            - 0.5 * math.log(2 * math.pi)

    def _entropy(self):
        return jnp.broadcast_to(
            0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(self.scale),
            self._batch_shape)

    def cdf(self, value):
        return Tensor(0.5 * (1 + jax.scipy.special.erf(
            (_arr(value) - self.loc) / (self.scale * math.sqrt(2)))))

    def icdf(self, value):
        return Tensor(self.loc + self.scale * math.sqrt(2)
                      * jax.scipy.special.erfinv(2 * _arr(value) - 1))


class LogNormal(Normal):
    def _sample(self, shape, key):
        return jnp.exp(super()._sample(shape, key))

    def _log_prob(self, v):
        return super()._log_prob(jnp.log(v)) - jnp.log(v)

    @property
    def mean(self):
        return Tensor(jnp.exp(self.loc + self.scale ** 2 / 2))

    @property
    def variance(self):
        s2 = self.scale ** 2
        return Tensor((jnp.exp(s2) - 1) * jnp.exp(2 * self.loc + s2))

    def _entropy(self):
        return super()._entropy() + self.loc


class Uniform(Distribution):
    def __init__(self, low, high, name=None):
        self.low, self.high = _arr(low), _arr(high)
        super().__init__(jnp.broadcast_shapes(self.low.shape,
                                              self.high.shape))

    @property
    def mean(self):
        return Tensor((self.low + self.high) / 2)

    @property
    def variance(self):
        return Tensor((self.high - self.low) ** 2 / 12)

    def _sample(self, shape, key):
        u = jax.random.uniform(key, self._extend(shape))
        return self.low + (self.high - self.low) * u

    def _log_prob(self, v):
        inside = (v >= self.low) & (v < self.high)
        return jnp.where(inside, -jnp.log(self.high - self.low), -jnp.inf)

    def _entropy(self):
        return jnp.broadcast_to(jnp.log(self.high - self.low),
                                self._batch_shape)


class Bernoulli(ExponentialFamily):
    def __init__(self, probs=None, logits=None, name=None):
        if (probs is None) == (logits is None):
            raise ValueError("pass exactly one of probs/logits")
        if probs is not None:
            self.probs = _arr(probs)
            self.logits = jnp.log(self.probs) - jnp.log1p(-self.probs)
        else:
            self.logits = _arr(logits)
            self.probs = jax.nn.sigmoid(self.logits)
        super().__init__(self.probs.shape)

    @property
    def mean(self):
        return Tensor(self.probs)

    @property
    def variance(self):
        return Tensor(self.probs * (1 - self.probs))

    def _sample(self, shape, key):
        return jax.random.bernoulli(
            key, self.probs, self._extend(shape)).astype(jnp.float32)

    def _log_prob(self, v):
        return v * jax.nn.log_sigmoid(self.logits) \
            + (1 - v) * jax.nn.log_sigmoid(-self.logits)

    def _entropy(self):
        p = self.probs
        return -(p * jnp.log(jnp.clip(p, 1e-12)) +
                 (1 - p) * jnp.log(jnp.clip(1 - p, 1e-12)))


class Geometric(Distribution):
    """P(X=k) = (1-p)^k p, k = 0, 1, 2, ... (failures before success)."""

    def __init__(self, probs=None, logits=None, name=None):
        if probs is not None:
            self.probs = _arr(probs)
        else:
            self.probs = jax.nn.sigmoid(_arr(logits))
        super().__init__(self.probs.shape)

    @property
    def mean(self):
        return Tensor((1 - self.probs) / self.probs)

    @property
    def variance(self):
        return Tensor((1 - self.probs) / self.probs ** 2)

    def _sample(self, shape, key):
        u = jax.random.uniform(key, self._extend(shape),
                               minval=1e-7, maxval=1.0)
        return jnp.floor(jnp.log(u) / jnp.log1p(-self.probs))

    def _log_prob(self, v):
        return v * jnp.log1p(-self.probs) + jnp.log(self.probs)

    def _entropy(self):
        p = self.probs
        q = 1 - p
        return -(q * jnp.log(jnp.clip(q, 1e-12)) +
                 p * jnp.log(jnp.clip(p, 1e-12))) / p


class Categorical(Distribution):
    def __init__(self, logits=None, probs=None, name=None):
        if logits is None and probs is None:
            raise ValueError("pass logits or probs")
        if logits is not None:
            self.logits = jax.nn.log_softmax(_arr(logits))
        else:
            self.logits = jnp.log(jnp.clip(
                _arr(probs) / jnp.sum(_arr(probs), -1, keepdims=True),
                1e-12))
        self.probs = jnp.exp(self.logits)
        super().__init__(self.logits.shape[:-1])

    def _sample(self, shape, key):
        return jax.random.categorical(
            key, self.logits, shape=shape + self._batch_shape)

    def sample(self, shape=()):
        return Tensor(self._sample(_shape(shape), framework.split_key())
                      .astype(jnp.int64))

    def _log_prob(self, v):
        v = v.astype(jnp.int32)
        return jnp.take_along_axis(self.logits, v[..., None], -1)[..., 0]

    def _entropy(self):
        return -jnp.sum(self.probs * self.logits, -1)


class Multinomial(Distribution):
    def __init__(self, total_count, probs, name=None):
        self.total_count = int(total_count)
        p = _arr(probs)
        self.probs = p / jnp.sum(p, -1, keepdims=True)
        self.logits = jnp.log(jnp.clip(self.probs, 1e-12))
        super().__init__(self.probs.shape[:-1], self.probs.shape[-1:])

    @property
    def mean(self):
        return Tensor(self.total_count * self.probs)

    @property
    def variance(self):
        return Tensor(self.total_count * self.probs * (1 - self.probs))

    def _sample(self, shape, key):
        draws = jax.random.categorical(
            key, self.logits, axis=-1,
            shape=(self.total_count,) + shape + self._batch_shape)
        onehot = jax.nn.one_hot(draws, self.probs.shape[-1])
        return jnp.sum(onehot, axis=0)

    def _log_prob(self, v):
        logc = jax.scipy.special.gammaln(self.total_count + 1.0) \
            - jnp.sum(jax.scipy.special.gammaln(v + 1.0), -1)
        return logc + jnp.sum(v * self.logits, -1)


class Beta(ExponentialFamily):
    def __init__(self, alpha, beta, name=None):
        self.alpha, self.beta = _arr(alpha), _arr(beta)
        super().__init__(jnp.broadcast_shapes(self.alpha.shape,
                                              self.beta.shape))

    @property
    def mean(self):
        return Tensor(self.alpha / (self.alpha + self.beta))

    @property
    def variance(self):
        s = self.alpha + self.beta
        return Tensor(self.alpha * self.beta / (s ** 2 * (s + 1)))

    def _sample(self, shape, key):
        return jax.random.beta(key, self.alpha, self.beta,
                               self._extend(shape))

    def _log_prob(self, v):
        return (self.alpha - 1) * jnp.log(v) \
            + (self.beta - 1) * jnp.log1p(-v) \
            - (jax.scipy.special.gammaln(self.alpha)
               + jax.scipy.special.gammaln(self.beta)
               - jax.scipy.special.gammaln(self.alpha + self.beta))

    def _entropy(self):
        a, b = self.alpha, self.beta
        dg = jax.scipy.special.digamma
        logB = (jax.scipy.special.gammaln(a) + jax.scipy.special.gammaln(b)
                - jax.scipy.special.gammaln(a + b))
        return logB - (a - 1) * dg(a) - (b - 1) * dg(b) \
            + (a + b - 2) * dg(a + b)


class Dirichlet(ExponentialFamily):
    def __init__(self, concentration, name=None):
        self.concentration = _arr(concentration)
        super().__init__(self.concentration.shape[:-1],
                         self.concentration.shape[-1:])

    @property
    def mean(self):
        return Tensor(self.concentration
                      / jnp.sum(self.concentration, -1, keepdims=True))

    def _sample(self, shape, key):
        return jax.random.dirichlet(key, self.concentration,
                                    shape + self._batch_shape)

    def _log_prob(self, v):
        a = self.concentration
        return jnp.sum((a - 1) * jnp.log(v), -1) \
            + jax.scipy.special.gammaln(jnp.sum(a, -1)) \
            - jnp.sum(jax.scipy.special.gammaln(a), -1)

    def _entropy(self):
        a = self.concentration
        a0 = jnp.sum(a, -1)
        k = a.shape[-1]
        dg = jax.scipy.special.digamma
        logB = jnp.sum(jax.scipy.special.gammaln(a), -1) \
            - jax.scipy.special.gammaln(a0)
        return logB + (a0 - k) * dg(a0) - jnp.sum((a - 1) * dg(a), -1)


class Gamma(ExponentialFamily):
    def __init__(self, concentration, rate, name=None):
        self.concentration, self.rate = _arr(concentration), _arr(rate)
        super().__init__(jnp.broadcast_shapes(self.concentration.shape,
                                              self.rate.shape))

    @property
    def mean(self):
        return Tensor(self.concentration / self.rate)

    @property
    def variance(self):
        return Tensor(self.concentration / self.rate ** 2)

    def _sample(self, shape, key):
        return jax.random.gamma(key, self.concentration,
                                self._extend(shape)) / self.rate

    def _log_prob(self, v):
        a, b = self.concentration, self.rate
        return a * jnp.log(b) + (a - 1) * jnp.log(v) - b * v \
            - jax.scipy.special.gammaln(a)

    def _entropy(self):
        a, b = self.concentration, self.rate
        dg = jax.scipy.special.digamma
        return a - jnp.log(b) + jax.scipy.special.gammaln(a) \
            + (1 - a) * dg(a)


class Chi2(Gamma):
    def __init__(self, df, name=None):
        df = _arr(df)
        super().__init__(df / 2, jnp.full_like(df, 0.5))
        self.df = df


class Exponential(ExponentialFamily):
    def __init__(self, rate, name=None):
        self.rate = _arr(rate)
        super().__init__(self.rate.shape)

    @property
    def mean(self):
        return Tensor(1.0 / self.rate)

    @property
    def variance(self):
        return Tensor(1.0 / self.rate ** 2)

    def _sample(self, shape, key):
        return jax.random.exponential(key, self._extend(shape)) / self.rate

    def _log_prob(self, v):
        return jnp.log(self.rate) - self.rate * v

    def _entropy(self):
        return 1.0 - jnp.log(self.rate)


class Laplace(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc, self.scale = _arr(loc), _arr(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape,
                                              self.scale.shape))

    @property
    def mean(self):
        return Tensor(jnp.broadcast_to(self.loc, self._batch_shape))

    @property
    def variance(self):
        return Tensor(2 * self.scale ** 2)

    def _sample(self, shape, key):
        return self.loc + self.scale * jax.random.laplace(
            key, self._extend(shape))

    def _log_prob(self, v):
        return -jnp.abs(v - self.loc) / self.scale \
            - jnp.log(2 * self.scale)

    def _entropy(self):
        return 1 + jnp.log(2 * self.scale)


class Gumbel(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc, self.scale = _arr(loc), _arr(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape,
                                              self.scale.shape))

    _euler = 0.5772156649015329

    @property
    def mean(self):
        return Tensor(self.loc + self.scale * self._euler)

    @property
    def variance(self):
        return Tensor((math.pi ** 2 / 6) * self.scale ** 2)

    def _sample(self, shape, key):
        return self.loc + self.scale * jax.random.gumbel(
            key, self._extend(shape))

    def _log_prob(self, v):
        z = (v - self.loc) / self.scale
        return -(z + jnp.exp(-z)) - jnp.log(self.scale)

    def _entropy(self):
        return jnp.log(self.scale) + 1 + self._euler


class Cauchy(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc, self.scale = _arr(loc), _arr(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape,
                                              self.scale.shape))

    def _sample(self, shape, key):
        return self.loc + self.scale * jax.random.cauchy(
            key, self._extend(shape))

    def _log_prob(self, v):
        z = (v - self.loc) / self.scale
        return -jnp.log(math.pi * self.scale * (1 + z ** 2))

    def _entropy(self):
        return jnp.log(4 * math.pi * self.scale)


class StudentT(Distribution):
    def __init__(self, df, loc=0.0, scale=1.0, name=None):
        self.df, self.loc, self.scale = _arr(df), _arr(loc), _arr(scale)
        super().__init__(jnp.broadcast_shapes(
            self.df.shape, self.loc.shape, self.scale.shape))

    @property
    def mean(self):
        return Tensor(jnp.where(self.df > 1, self.loc, jnp.nan))

    @property
    def variance(self):
        return Tensor(jnp.where(
            self.df > 2, self.scale ** 2 * self.df / (self.df - 2),
            jnp.nan))

    def _sample(self, shape, key):
        return self.loc + self.scale * jax.random.t(
            key, self.df, self._extend(shape))

    def _log_prob(self, v):
        d = self.df
        z = (v - self.loc) / self.scale
        return jax.scipy.special.gammaln((d + 1) / 2) \
            - jax.scipy.special.gammaln(d / 2) \
            - 0.5 * jnp.log(d * math.pi) - jnp.log(self.scale) \
            - (d + 1) / 2 * jnp.log1p(z ** 2 / d)


class Poisson(ExponentialFamily):
    def __init__(self, rate, name=None):
        self.rate = _arr(rate)
        super().__init__(self.rate.shape)

    @property
    def mean(self):
        return Tensor(self.rate)

    @property
    def variance(self):
        return Tensor(self.rate)

    def _sample(self, shape, key):
        return jax.random.poisson(key, self.rate,
                                  self._extend(shape)).astype(jnp.float32)

    def _log_prob(self, v):
        return v * jnp.log(self.rate) - self.rate \
            - jax.scipy.special.gammaln(v + 1)


class Binomial(Distribution):
    def __init__(self, total_count, probs, name=None):
        self.total_count = _arr(total_count)
        self.probs = _arr(probs)
        super().__init__(jnp.broadcast_shapes(self.total_count.shape,
                                              self.probs.shape))

    @property
    def mean(self):
        return Tensor(self.total_count * self.probs)

    @property
    def variance(self):
        return Tensor(self.total_count * self.probs * (1 - self.probs))

    def _sample(self, shape, key):
        n = int(jnp.max(self.total_count))
        u = jax.random.uniform(key, (n,) + self._extend(shape))
        idx = jnp.arange(n).reshape((n,) + (1,) * len(self._extend(shape)))
        draws = (u < self.probs) & (idx < self.total_count)
        return jnp.sum(draws, axis=0).astype(jnp.float32)

    def _log_prob(self, v):
        n, p = self.total_count, jnp.clip(self.probs, 1e-12, 1 - 1e-12)
        logc = jax.scipy.special.gammaln(n + 1) \
            - jax.scipy.special.gammaln(v + 1) \
            - jax.scipy.special.gammaln(n - v + 1)
        return logc + v * jnp.log(p) + (n - v) * jnp.log1p(-p)


class MultivariateNormal(Distribution):
    def __init__(self, loc, covariance_matrix=None, scale_tril=None,
                 name=None):
        self.loc = _arr(loc)
        if scale_tril is not None:
            self.scale_tril = _arr(scale_tril)
            self.covariance_matrix = self.scale_tril @ self.scale_tril.mT
        elif covariance_matrix is not None:
            self.covariance_matrix = _arr(covariance_matrix)
            self.scale_tril = jnp.linalg.cholesky(self.covariance_matrix)
        else:
            raise ValueError("pass covariance_matrix or scale_tril")
        super().__init__(self.loc.shape[:-1], self.loc.shape[-1:])

    @property
    def mean(self):
        return Tensor(self.loc)

    @property
    def variance(self):
        return Tensor(jnp.diagonal(self.covariance_matrix, axis1=-2,
                                   axis2=-1))

    def _sample(self, shape, key):
        eps = jax.random.normal(key, self._extend(shape))
        return self.loc + jnp.einsum("...ij,...j->...i", self.scale_tril,
                                     eps)

    def _log_prob(self, v):
        d = self.loc.shape[-1]
        diff = v - self.loc
        sol = jax.scipy.linalg.solve_triangular(self.scale_tril, diff[...,
                                                None], lower=True)[..., 0]
        logdet = jnp.sum(jnp.log(jnp.diagonal(self.scale_tril, axis1=-2,
                                              axis2=-1)), -1)
        return -0.5 * jnp.sum(sol ** 2, -1) - logdet \
            - 0.5 * d * math.log(2 * math.pi)

    def _entropy(self):
        d = self.loc.shape[-1]
        logdet = jnp.sum(jnp.log(jnp.diagonal(self.scale_tril, axis1=-2,
                                              axis2=-1)), -1)
        return 0.5 * d * (1 + math.log(2 * math.pi)) + logdet


# ---------------------------------------------------------------------------
# transforms + TransformedDistribution
# ---------------------------------------------------------------------------

class Transform:
    def forward(self, x):
        return Tensor(self._forward(_arr(x)))

    def inverse(self, y):
        return Tensor(self._inverse(_arr(y)))

    def forward_log_det_jacobian(self, x):
        return Tensor(self._fldj(_arr(x)))


class AffineTransform(Transform):
    def __init__(self, loc, scale):
        self.loc, self.scale = _arr(loc), _arr(scale)

    def _forward(self, x):
        return self.loc + self.scale * x

    def _inverse(self, y):
        return (y - self.loc) / self.scale

    def _fldj(self, x):
        return jnp.broadcast_to(jnp.log(jnp.abs(self.scale)), x.shape)


class ExpTransform(Transform):
    def _forward(self, x):
        return jnp.exp(x)

    def _inverse(self, y):
        return jnp.log(y)

    def _fldj(self, x):
        return x


class SigmoidTransform(Transform):
    def _forward(self, x):
        return jax.nn.sigmoid(x)

    def _inverse(self, y):
        return jnp.log(y) - jnp.log1p(-y)

    def _fldj(self, x):
        return jax.nn.log_sigmoid(x) + jax.nn.log_sigmoid(-x)


class TransformedDistribution(Distribution):
    def __init__(self, base, transforms):
        self.base = base
        self.transforms = list(transforms)
        super().__init__(base.batch_shape, base.event_shape)

    def _sample(self, shape, key):
        x = self.base._sample(shape, key)
        for t in self.transforms:
            x = t._forward(x)
        return x

    def _log_prob(self, v):
        lp = jnp.zeros(())
        y = v
        for t in reversed(self.transforms):
            x = t._inverse(y)
            lp = lp - t._fldj(x)
            y = x
        return lp + self.base._log_prob(y)


# ---------------------------------------------------------------------------
# KL divergence registry
# ---------------------------------------------------------------------------

_KL_REGISTRY = {}


def register_kl(p_cls, q_cls):
    def deco(fn):
        _KL_REGISTRY[(p_cls, q_cls)] = fn
        return fn
    return deco


def kl_divergence(p, q):
    # KL is invariant under invertible reparameterizations, so LogNormal
    # pairs reuse the Normal formula — but a LogNormal/Normal MIX has no
    # closed form, so both sides must agree on the transform.
    if isinstance(p, LogNormal) != isinstance(q, LogNormal):
        raise NotImplementedError("no closed-form KL(LogNormal, Normal)")
    for (pc, qc), fn in _KL_REGISTRY.items():
        if isinstance(p, pc) and isinstance(q, qc):
            return Tensor(fn(p, q))
    raise NotImplementedError(
        f"no KL registered for ({type(p).__name__}, {type(q).__name__})")


@register_kl(Normal, Normal)
def _kl_normal(p, q):
    vr = (p.scale / q.scale) ** 2
    return 0.5 * (vr + ((p.loc - q.loc) / q.scale) ** 2 - 1 - jnp.log(vr))


@register_kl(Uniform, Uniform)
def _kl_uniform(p, q):
    return jnp.log((q.high - q.low) / (p.high - p.low))


@register_kl(Categorical, Categorical)
def _kl_categorical(p, q):
    return jnp.sum(p.probs * (p.logits - q.logits), -1)


@register_kl(Bernoulli, Bernoulli)
def _kl_bernoulli(p, q):
    a = p.probs * (jnp.log(jnp.clip(p.probs, 1e-12))
                   - jnp.log(jnp.clip(q.probs, 1e-12)))
    b = (1 - p.probs) * (jnp.log(jnp.clip(1 - p.probs, 1e-12))
                         - jnp.log(jnp.clip(1 - q.probs, 1e-12)))
    return a + b


@register_kl(Beta, Beta)
def _kl_beta(p, q):
    dg = jax.scipy.special.digamma
    gl = jax.scipy.special.gammaln
    sp, sq = p.alpha + p.beta, q.alpha + q.beta
    return (gl(sp) - gl(p.alpha) - gl(p.beta)
            - gl(sq) + gl(q.alpha) + gl(q.beta)
            + (p.alpha - q.alpha) * (dg(p.alpha) - dg(sp))
            + (p.beta - q.beta) * (dg(p.beta) - dg(sp)))


@register_kl(Dirichlet, Dirichlet)
def _kl_dirichlet(p, q):
    dg = jax.scipy.special.digamma
    gl = jax.scipy.special.gammaln
    a0p = jnp.sum(p.concentration, -1)
    a0q = jnp.sum(q.concentration, -1)
    return (gl(a0p) - jnp.sum(gl(p.concentration), -1)
            - gl(a0q) + jnp.sum(gl(q.concentration), -1)
            + jnp.sum((p.concentration - q.concentration)
                      * (dg(p.concentration) - dg(a0p)[..., None]), -1))


@register_kl(Exponential, Exponential)
def _kl_exponential(p, q):
    r = q.rate / p.rate
    return jnp.log(p.rate) - jnp.log(q.rate) + r - 1


@register_kl(Gamma, Gamma)
def _kl_gamma(p, q):
    dg = jax.scipy.special.digamma
    gl = jax.scipy.special.gammaln
    return ((p.concentration - q.concentration) * dg(p.concentration)
            - gl(p.concentration) + gl(q.concentration)
            + q.concentration * (jnp.log(p.rate) - jnp.log(q.rate))
            + p.concentration * (q.rate / p.rate - 1))


class ContinuousBernoulli(ExponentialFamily):
    """Continuous Bernoulli on [0, 1] (reference:
    python/paddle/distribution/continuous_bernoulli.py — verify): density
    C(λ) λ^x (1-λ)^(1-x) with the standard normalizing constant and its
    λ→0.5 limit handled by a Taylor guard."""

    def __init__(self, probs, lims=(0.499, 0.501), name=None):
        self.probs = _arr(probs)
        self._lims = lims
        super().__init__(self.probs.shape)

    def _outside(self):
        lo, hi = self._lims
        return (self.probs < lo) | (self.probs > hi)

    def _safe_probs(self):
        # value used on the unstable branch only; keeps grads finite
        return jnp.where(self._outside(), self.probs, 0.4)

    def _log_norm(self):
        p = self._safe_probs()
        exact = jnp.log(jnp.abs(
            2 * jnp.arctanh(1 - 2 * p) / (1 - 2 * p)))
        x = self.probs - 0.5
        taylor = jnp.log(2.0) + (4. / 3. + 104. / 45. * x * x) * x * x
        return jnp.where(self._outside(), exact, taylor)

    @property
    def mean(self):
        p = self._safe_probs()
        exact = p / (2 * p - 1) + 1 / (2 * jnp.arctanh(1 - 2 * p))
        x = self.probs - 0.5
        taylor = 0.5 + (1. / 3. + 16. / 45. * x * x) * x
        return Tensor(jnp.where(self._outside(), exact, taylor))

    @property
    def variance(self):
        p = self._safe_probs()
        exact = p * (p - 1) / (1 - 2 * p) ** 2 \
            + 1 / (2 * jnp.arctanh(1 - 2 * p)) ** 2
        x = self.probs - 0.5
        taylor = 1. / 12. - (1. / 15. - 128. / 945. * x * x) * x * x
        return Tensor(jnp.where(self._outside(), exact, taylor))

    def _icdf(self, u):
        p = self._safe_probs()
        q = 1 - p
        # inverse CDF: x = log((u*(2p-1) + (1-p)) / (1-p)) / log(p/(1-p))
        exact = jnp.log((u * (2 * p - 1) + q) / q) / jnp.log(p / q)
        return jnp.where(self._outside(), exact, u)

    def _sample(self, shape, key):
        u = jax.random.uniform(key, shape + self._batch_shape)
        return self._icdf(u)

    def _log_prob(self, v):
        p = self.probs
        return v * jnp.log(jnp.clip(p, 1e-12, 1.0)) \
            + (1 - v) * jnp.log(jnp.clip(1 - p, 1e-12, 1.0)) \
            + self._log_norm()

    def _entropy(self):
        # -E[log p(x)] via mean (stays in jnp: traceable + differentiable)
        m = self.mean._value
        p = self.probs
        return -(m * jnp.log(jnp.clip(p, 1e-12, 1.0))
                 + (1 - m) * jnp.log(jnp.clip(1 - p, 1e-12, 1.0))
                 + self._log_norm())


class Independent(Distribution):
    """Reinterpret the rightmost ``reinterpreted_batch_rank`` batch dims of
    a base distribution as event dims (reference:
    python/paddle/distribution/independent.py — verify): log_prob sums
    over them."""

    def __init__(self, base, reinterpreted_batch_rank, name=None):
        if reinterpreted_batch_rank > len(base.batch_shape):
            raise ValueError(
                f"reinterpreted_batch_rank {reinterpreted_batch_rank} "
                f"exceeds base batch rank {len(base.batch_shape)}")
        self.base = base
        self._rank = int(reinterpreted_batch_rank)
        bs = base.batch_shape
        split = len(bs) - self._rank
        super().__init__(bs[:split], bs[split:] + tuple(base.event_shape))

    @property
    def mean(self):
        return self.base.mean

    @property
    def variance(self):
        return self.base.variance

    def _sample(self, shape, key):
        return self.base._sample(shape, key)

    def sample(self, shape=()):
        return self.base.sample(shape)

    def rsample(self, shape=()):
        return self.base.rsample(shape)

    def log_prob(self, value):
        lp = self.base.log_prob(value)
        axes = tuple(range(-self._rank, 0)) if self._rank else ()
        from ..tensor import apply_op
        return apply_op(lambda v: jnp.sum(v, axis=axes), lp) if axes \
            else lp

    def prob(self, value):
        from ..tensor import apply_op
        return apply_op(jnp.exp, self.log_prob(value))

    def entropy(self):
        ent = self.base.entropy()
        axes = tuple(range(-self._rank, 0)) if self._rank else ()
        from ..tensor import apply_op
        return apply_op(lambda v: jnp.sum(v, axis=axes), ent) if axes \
            else ent


__all__ += ["ContinuousBernoulli", "Independent"]


class AbsTransform(Transform):
    """y = |x|. DEVIATION from paddle's AbsTransform (whose inverse
    returns both branches (-y, y)): this inverse returns the positive
    branch only, torch-style — a single tensor keeps the Transform
    interface uniform."""

    def _forward(self, x):
        return jnp.abs(x)

    def _inverse(self, y):
        return y

    def _fldj(self, x):
        return jnp.zeros_like(x)


class PowerTransform(Transform):
    def __init__(self, power):
        self.power = _arr(power)

    def _forward(self, x):
        return jnp.power(x, self.power)

    def _inverse(self, y):
        return jnp.power(y, 1.0 / self.power)

    def _fldj(self, x):
        return jnp.log(jnp.abs(self.power * jnp.power(x, self.power - 1)))


class TanhTransform(Transform):
    def _forward(self, x):
        return jnp.tanh(x)

    def _inverse(self, y):
        return jnp.arctanh(jnp.clip(y, -1 + 1e-7, 1 - 1e-7))

    def _fldj(self, x):
        # log(1 - tanh^2) = 2(log2 - x - softplus(-2x)), the stable form
        return 2.0 * (jnp.log(2.0) - x - jax.nn.softplus(-2.0 * x))


class ChainTransform(Transform):
    """Compose transforms left-to-right: y = tN(...t1(x))."""

    def __init__(self, transforms):
        self.transforms = list(transforms)

    def _forward(self, x):
        for t in self.transforms:
            x = t._forward(x)
        return x

    def _inverse(self, y):
        for t in reversed(self.transforms):
            y = t._inverse(y)
        return y

    def _fldj(self, x):
        total = 0.0
        for t in self.transforms:
            total = total + t._fldj(x)
            x = t._forward(x)
        return total


class IndependentTransform(Transform):
    """Reinterpret the rightmost ``reinterpreted_batch_rank`` dims as
    event dims: the log-det sums over them."""

    def __init__(self, base, reinterpreted_batch_rank):
        self.base = base
        self._rank = int(reinterpreted_batch_rank)

    def _forward(self, x):
        return self.base._forward(x)

    def _inverse(self, y):
        return self.base._inverse(y)

    def _fldj(self, x):
        ldj = self.base._fldj(x)
        return jnp.sum(ldj, axis=tuple(range(-self._rank, 0)))


class ReshapeTransform(Transform):
    def __init__(self, in_event_shape, out_event_shape):
        self.in_event_shape = tuple(int(s) for s in in_event_shape)
        self.out_event_shape = tuple(int(s) for s in out_event_shape)
        import numpy as _np
        if int(_np.prod(self.in_event_shape)) != \
                int(_np.prod(self.out_event_shape)):
            raise ValueError(
                f"reshape {self.in_event_shape} -> {self.out_event_shape} "
                "changes the element count")

    def _forward(self, x):
        lead = x.shape[:x.ndim - len(self.in_event_shape)]
        return x.reshape(lead + self.out_event_shape)

    def _inverse(self, y):
        lead = y.shape[:y.ndim - len(self.out_event_shape)]
        return y.reshape(lead + self.in_event_shape)

    def _fldj(self, x):
        lead = x.shape[:x.ndim - len(self.in_event_shape)]
        return jnp.zeros(lead)


class SoftmaxTransform(Transform):
    """y = softmax(x) over the last dim (not bijective — the reference's
    inverse maps back via log, defined up to an additive constant)."""

    def _forward(self, x):
        return jax.nn.softmax(x, axis=-1)

    def _inverse(self, y):
        return jnp.log(y)

    def _fldj(self, x):
        raise NotImplementedError(
            "SoftmaxTransform is not bijective; log-det is undefined "
            "(reference raises here too)")


class StackTransform(Transform):
    """Apply transforms[i] to slice i along ``axis``."""

    def __init__(self, transforms, axis=0):
        self.transforms = list(transforms)
        self.axis = int(axis)

    def _map(self, x, method):
        n = len(self.transforms)
        if int(x.shape[self.axis]) != n:
            raise ValueError(
                f"StackTransform has {n} transforms but the input has "
                f"{x.shape[self.axis]} slices along axis {self.axis}")
        pieces = []
        for i, t in enumerate(self.transforms):
            sl = jnp.take(x, i, axis=self.axis)
            pieces.append(getattr(t, method)(sl))
        return jnp.stack(pieces, axis=self.axis)

    def _forward(self, x):
        return self._map(x, "_forward")

    def _inverse(self, y):
        return self._map(y, "_inverse")

    def _fldj(self, x):
        return self._map(x, "_fldj")


class StickBreakingTransform(Transform):
    """Unconstrained R^k -> (k+1)-simplex via stick breaking (the
    reference's simplex bijector)."""

    def _forward(self, x):
        k = x.shape[-1]
        offset = jnp.log(k - jnp.arange(k, dtype=x.dtype))
        z = jax.nn.sigmoid(x - offset)
        zpad = jnp.concatenate([z, jnp.ones(x.shape[:-1] + (1,), x.dtype)],
                               axis=-1)
        one_minus = jnp.concatenate(
            [jnp.ones(x.shape[:-1] + (1,), x.dtype), 1 - z], axis=-1)
        return zpad * jnp.cumprod(one_minus, axis=-1)

    def _inverse(self, y):
        k = y.shape[-1] - 1
        cum = jnp.cumsum(y[..., :-1], axis=-1)
        rest = 1 - jnp.concatenate(
            [jnp.zeros(y.shape[:-1] + (1,), y.dtype), cum[..., :-1]],
            axis=-1)
        z = y[..., :-1] / jnp.maximum(rest, 1e-12)
        offset = jnp.log(k - jnp.arange(k, dtype=y.dtype))
        return jax.scipy.special.logit(jnp.clip(z, 1e-12, 1 - 1e-12)) \
            + offset

    def _fldj(self, x):
        k = x.shape[-1]
        offset = jnp.log(k - jnp.arange(k, dtype=x.dtype))
        t = x - offset
        z = jax.nn.sigmoid(t)
        one_minus = jnp.concatenate(
            [jnp.ones(x.shape[:-1] + (1,), x.dtype), 1 - z[..., :-1]],
            axis=-1)
        rest = jnp.cumprod(one_minus, axis=-1)
        return jnp.sum(jnp.log(z) + jnp.log1p(-z) + jnp.log(rest),
                       axis=-1)


__all__ += ["AbsTransform", "PowerTransform", "TanhTransform",
            "ChainTransform", "IndependentTransform", "ReshapeTransform",
            "SoftmaxTransform", "StackTransform",
            "StickBreakingTransform"]
