"""paddle.regularizer — L1/L2 weight decay as grad regularization
(reference parity: python/paddle/regularizer.py L1Decay/L2Decay —
verify).

Semantics follow the reference: a regularizer attached to a parameter
(``ParamAttr(regularizer=...)``) WINS over the optimizer-level
``weight_decay`` regularizer for that parameter; regularization is
added to the gradient after gradient clipping (the reference's
append_regularization_ops ordering); and for decoupled-decay optimizers
(AdamW/Lamb) a parameter that carries its own regularizer is excluded
from the decoupled decay and gets the explicit regularizer gradient
instead.
"""
from __future__ import annotations

import jax.numpy as jnp

__all__ = ["L1Decay", "L2Decay"]


class WeightDecayRegularizer:
    def __init__(self, coeff=0.0):
        self._coeff = float(coeff)

    @property
    def coeff(self):
        return self._coeff

    def grad_term(self, param):
        """Contribution added to the parameter's gradient. Pure."""
        raise NotImplementedError

    def __repr__(self):
        return f"{type(self).__name__}(coeff={self._coeff})"


class L2Decay(WeightDecayRegularizer):
    """grad += coeff * param (classic coupled L2)."""

    def grad_term(self, param):
        return self._coeff * param


class L1Decay(WeightDecayRegularizer):
    """grad += coeff * sign(param)."""

    def grad_term(self, param):
        return self._coeff * jnp.sign(param)
