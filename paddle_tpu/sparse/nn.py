"""paddle.sparse.nn — sparse conv / norm / activation / attention.

Reference parity: python/paddle/sparse/nn/ (Conv3D, SubmConv3D,
BatchNorm, ReLU, functional.attention — verify). The reference backs
these with hand-written COO kernels (paddle/phi/kernels/sparse/); the
TPU-native design keeps COORDINATES on the host as numpy (the output
structure of a sparse conv is data-dependent — inherently eager, the
reference is too) and runs all VALUE math as jnp gathers + matmuls,
which XLA maps onto the MXU: one (nnz_out, Cin) x (Cin, Cout) matmul
per kernel offset. Coordinate lookup is a sorted-key binary search
(O(nnz) memory) — never a dense voxel grid.

Layout convention is paddle's: SparseCooTensor of shape
(N, D, H, W, C) with indices (4, nnz) over (n, d, h, w) and dense
values (nnz, C). Weight layout (kd, kh, kw, Cin, Cout).
"""
from __future__ import annotations

import math as _math

import jax
import jax.numpy as jnp
import numpy as np

from . import SparseCooTensor, SparseCsrTensor, sparse_coo_tensor
from ..nn.layer import Layer
from ..tensor import Parameter, Tensor

__all__ = ["Conv3D", "SubmConv3D", "BatchNorm", "ReLU", "functional"]


def _triple(v):
    if isinstance(v, (list, tuple)):
        if len(v) != 3:
            raise ValueError(f"expected 3 values, got {v!r}")
        return tuple(int(x) for x in v)
    return (int(v),) * 3


def _linearize(nidx, coords, dims):
    """(n, d, h, w) -> single sortable int64 key."""
    return ((nidx * dims[0] + coords[:, 0]) * dims[1]
            + coords[:, 1]) * dims[2] + coords[:, 2]


def _conv3d_coo(x: SparseCooTensor, weight, bias=None, stride=1,
                padding=0, dilation=1, subm=False):
    """Core sparse 3D convolution. Returns a SparseCooTensor."""
    stride, padding, dilation = (_triple(stride), _triple(padding),
                                 _triple(dilation))
    if not isinstance(x, SparseCooTensor):
        raise TypeError(f"expected SparseCooTensor, got {type(x)}")
    idx = np.asarray(x.indices())              # (4, nnz)
    vals = jnp.asarray(x.values()._value if isinstance(
        x.values(), Tensor) else x.values())   # (nnz, Cin)
    w = jnp.asarray(weight._value if isinstance(weight, Tensor)
                    else weight)
    N, D, H, W, cin = (int(s) for s in x.shape)
    kd, kh, kw, wcin, cout = (int(s) for s in w.shape)
    if wcin != cin:
        raise ValueError(f"weight Cin {wcin} != input channels {cin}")
    dims = np.array([D, H, W])
    if subm:
        if stride != (1, 1, 1):
            raise ValueError("SubmConv3D requires stride 1")
        out_spatial = (D, H, W)
        out_idx = idx
    else:
        out_spatial = tuple(
            (dims[i] + 2 * padding[i]
             - dilation[i] * ([kd, kh, kw][i] - 1) - 1) // stride[i] + 1
            for i in range(3))
        # candidate outputs: every (input voxel, kernel offset) pair that
        # lands on a stride-aligned, in-bounds output coordinate
        cands = []
        for od in range(kd):
            for oh in range(kh):
                for ow in range(kw):
                    off = np.array([od, oh, ow]) * np.array(dilation)
                    num = idx[1:].T + np.array(padding) - off
                    ok = (num % np.array(stride) == 0).all(1)
                    oc = num // np.array(stride)
                    ok &= ((oc >= 0) & (oc < np.array(out_spatial))) \
                        .all(1)
                    if ok.any():
                        cands.append(np.concatenate(
                            [idx[0][ok, None], oc[ok]], axis=1))
        if cands:
            allc = np.unique(np.concatenate(cands, axis=0), axis=0)
        else:
            allc = np.zeros((0, 4), np.int64)
        out_idx = allc.T                       # (4, nnz_out)

    Do, Ho, Wo = out_spatial
    # sorted-key lookup table over active INPUT voxels: O(nnz) memory
    # (a dense (N,D,H,W) grid would be ~720 MB for a detection-scale
    # 41x1600x1408 grid, rebuilt per conv call)
    in_keys = _linearize(idx[0].astype(np.int64), idx[1:].T.astype(
        np.int64), dims)
    order = np.argsort(in_keys)
    keys_sorted = in_keys[order]

    def lookup(nidx, coords, valid):
        q = _linearize(nidx.astype(np.int64), coords.astype(np.int64),
                       dims)
        pos = np.searchsorted(keys_sorted, q)
        pos_c = np.minimum(pos, len(keys_sorted) - 1)
        hit = valid & (len(keys_sorted) > 0)
        if len(keys_sorted):
            hit = hit & (keys_sorted[pos_c] == q)
        rows = np.where(hit, order[pos_c], -1)
        return rows

    vals_pad = jnp.concatenate(
        [vals, jnp.zeros((1, cin), vals.dtype)], axis=0)  # row -1 -> 0

    nnz_out = out_idx.shape[1]
    out = jnp.zeros((nnz_out, cout),
                    jnp.promote_types(vals.dtype, w.dtype))
    oc = out_idx[1:].T                         # (nnz_out, 3)
    on = out_idx[0]
    for od in range(kd):
        for oh in range(kh):
            for ow in range(kw):
                off = np.array([od, oh, ow]) * np.array(dilation)
                ic = oc * np.array(stride) - np.array(padding) + off
                inb = ((ic >= 0) & (ic < dims)).all(1)
                icc = np.clip(ic, 0, dims - 1)
                rows = lookup(on, icc, inb)
                g = vals_pad[jnp.asarray(rows)]          # (nnz_out, Cin)
                out = out + g @ w[od, oh, ow]
    if bias is not None:
        b = jnp.asarray(bias._value if isinstance(bias, Tensor) else bias)
        out = out + b
    return sparse_coo_tensor(
        out_idx, Tensor(out.astype(vals.dtype)),
        shape=(N, Do, Ho, Wo, cout))


class _ConvBase(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, subm=False,
                 bias_attr=None, data_format="NDHWC"):
        super().__init__()
        if groups != 1:
            raise NotImplementedError("sparse conv groups > 1")
        if data_format != "NDHWC":
            raise ValueError("sparse conv3d supports NDHWC only "
                             "(reference layout)")
        self._subm = subm
        self.stride = _triple(stride)
        self.padding = _triple(padding)
        self.dilation = _triple(dilation)
        k = _triple(kernel_size)
        from .. import framework
        key = framework.split_key()
        fan_in = in_channels * k[0] * k[1] * k[2]
        bound = 1.0 / _math.sqrt(fan_in)
        self.weight = Parameter(jax.random.uniform(
            key, (*k, in_channels, out_channels),
            minval=-bound, maxval=bound,
            dtype=framework.state().default_dtype))
        if bias_attr is not False:
            self.bias = Parameter(jnp.zeros((out_channels,),
                                            self.weight._value.dtype))
        else:
            self.bias = None

    def forward(self, x):
        return _conv3d_coo(x, self.weight, self.bias, self.stride,
                           self.padding, self.dilation, subm=self._subm)


class Conv3D(_ConvBase):
    """Sparse 3D convolution: output sites are every stride-aligned
    position reachable from an active input voxel (the sparse pattern
    DILATES — reference sparse/nn/layer/conv.py Conv3D)."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, bias_attr=None,
                 data_format="NDHWC"):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, False, bias_attr,
                         data_format)


class SubmConv3D(_ConvBase):
    """Submanifold sparse conv: output sites == input sites (no pattern
    dilation — the point-cloud workhorse; reference SubmConv3D)."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, bias_attr=None,
                 data_format="NDHWC"):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, True, bias_attr,
                         data_format)


class BatchNorm(Layer):
    """BatchNorm over the channel dim of ACTIVE voxels only (inactive
    sites don't dilute the statistics — reference sparse BatchNorm).
    Running stats are registered buffers (persisted by state_dict)."""

    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 data_format="NDHWC"):
        super().__init__()
        self.weight = Parameter(jnp.ones((num_features,)))
        self.bias = Parameter(jnp.zeros((num_features,)))
        self.register_buffer("_mean", Tensor(jnp.zeros((num_features,))))
        self.register_buffer("_variance",
                             Tensor(jnp.ones((num_features,))))
        self.momentum = momentum
        self.eps = epsilon

    def forward(self, x: SparseCooTensor):
        v = jnp.asarray(x.values()._value if isinstance(
            x.values(), Tensor) else x.values())
        if self.training:
            mean = v.mean(axis=0)
            var = v.var(axis=0)
            m = self.momentum
            self._mean._value = m * self._mean._value + (1 - m) * mean
            self._variance._value = (m * self._variance._value
                                     + (1 - m) * var)
        else:
            mean, var = self._mean._value, self._variance._value
        out = (v - mean) / jnp.sqrt(var + self.eps)
        out = out * self.weight._value + self.bias._value
        return sparse_coo_tensor(np.asarray(x.indices()),
                                 Tensor(out.astype(v.dtype)),
                                 shape=tuple(x.shape))


class ReLU(Layer):
    def forward(self, x: SparseCooTensor):
        v = x.values()
        v = v._value if isinstance(v, Tensor) else jnp.asarray(v)
        return sparse_coo_tensor(np.asarray(x.indices()),
                                 Tensor(jnp.maximum(v, 0)),
                                 shape=tuple(x.shape))


class functional:
    """paddle.sparse.nn.functional namespace."""

    @staticmethod
    def relu(x):
        return ReLU()(x)

    @staticmethod
    def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1,
               groups=1, data_format="NDHWC", name=None):
        if groups != 1:
            raise NotImplementedError("sparse conv groups > 1")
        return _conv3d_coo(x, weight, bias, stride, padding, dilation,
                           subm=False)

    @staticmethod
    def subm_conv3d(x, weight, bias=None, stride=1, padding=0,
                    dilation=1, groups=1, data_format="NDHWC", name=None):
        if groups != 1:
            raise NotImplementedError("sparse conv groups > 1")
        return _conv3d_coo(x, weight, bias, stride, padding, dilation,
                           subm=True)

    @staticmethod
    def attention(query, key, value, sparse_mask,
                  key_padding_mask=None, attn_mask=None, name=None):
        """Sparse attention: softmax runs over ONLY the positions named
        by ``sparse_mask`` (a SparseCsrTensor of shape (b*h, s, s) —
        reference sparse/nn/functional/transformer.py — verify).
        query/key/value: dense (b, h, s, d). Additive masks
        ``key_padding_mask`` (b, s) / ``attn_mask`` (s, s) follow the
        reference's semantics (−inf entries drop keys).

        TPU-native: the CSR pattern becomes a boolean score mask and
        XLA fuses the masked softmax; the pattern is static per call
        site, so the MXU still sees the full (s, s) matmul tiles (a
        gather-per-row formulation would defeat tiling for the
        moderate sparsities these masks carry)."""
        if not isinstance(sparse_mask, SparseCsrTensor):
            raise TypeError("sparse_mask must be a SparseCsrTensor")
        qv = query._value if isinstance(query, Tensor) \
            else jnp.asarray(query)
        kv = key._value if isinstance(key, Tensor) else jnp.asarray(key)
        vv = value._value if isinstance(value, Tensor) \
            else jnp.asarray(value)
        b, h, s, d = qv.shape
        # CSR pattern -> dense bool (b*h, s, s), vectorized: row ids
        # repeat by per-row counts from np.diff(crows)
        crows = np.asarray(sparse_mask.crows()).reshape(b * h, s + 1)
        cols = np.asarray(sparse_mask.cols()).reshape(b * h, -1)
        counts = np.diff(crows, axis=1)                  # (bh, s)
        allow = np.zeros((b * h, s, s), bool)
        bh_ids = np.repeat(np.arange(b * h), counts.sum(axis=1))
        row_ids = np.concatenate(
            [np.repeat(np.arange(s), c) for c in counts])
        col_ids = np.concatenate(
            [cols[i, :counts[i].sum()] for i in range(b * h)])
        allow[bh_ids, row_ids, col_ids] = True
        allow = jnp.asarray(allow.reshape(b, h, s, s))
        scores = jnp.einsum("bhqd,bhkd->bhqk", qv, kv,
                            preferred_element_type=jnp.float32) \
            / _math.sqrt(d)
        neg = jnp.float32(-1e30)
        scores = jnp.where(allow, scores, neg)
        if attn_mask is not None:
            am = attn_mask._value if isinstance(attn_mask, Tensor) \
                else jnp.asarray(attn_mask)
            scores = scores + am.astype(scores.dtype)
        if key_padding_mask is not None:
            kp = key_padding_mask._value if isinstance(
                key_padding_mask, Tensor) else jnp.asarray(key_padding_mask)
            scores = scores + kp.astype(scores.dtype)[:, None, None, :]
        probs = jax.nn.softmax(scores, axis=-1)
        # rows with no allowed entries must output exact zeros
        dead = ~allow.any(axis=-1)
        probs = jnp.where(dead[..., None], 0.0, probs)
        out = jnp.einsum("bhqk,bhkd->bhqd", probs.astype(vv.dtype), vv)
        return Tensor(out)
