"""Sparse tensors (``paddle.sparse`` parity: COO/CSR).

Reference parity: python/paddle/sparse/ (sparse_coo_tensor,
sparse_csr_tensor, unary/binary/matmul ops, SparseCooTensor /
SparseCsrTensor in paddle/phi/core — verify).

TPU-native design: backed by jax.experimental.sparse BCOO/BCSR, whose
matmuls lower to XLA gather/scatter + dense dot on the MXU (TPU has no
sparse systolic path, so "sparse matmul" is a compute-skipping gather —
same trade the reference's cuSPARSE path makes on consumer GPUs). The
wrapper keeps Paddle's API shape: .indices()/.values()/.to_dense().
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import sparse as jsparse

from ..tensor import Tensor, to_tensor

__all__ = [
    "SparseCooTensor", "SparseCsrTensor", "sparse_coo_tensor",
    "sparse_csr_tensor", "is_same_shape", "mask_as", "matmul", "masked_matmul", "mv",
    "add", "subtract", "multiply", "divide", "transpose", "relu", "tanh",
    "sin", "abs", "pow", "neg", "coalesce", "sqrt", "square", "cast",
]


def _as_array(x):
    if isinstance(x, Tensor):
        return x._value
    return jnp.asarray(x)


class SparseTensorBase:
    @property
    def shape(self):
        return list(self._mat.shape)

    @property
    def dtype(self):
        return self._mat.dtype

    @property
    def nnz(self):
        return int(self._mat.nse)

    def to_dense(self):
        return Tensor(self._mat.todense())

    def numpy(self):
        return np.asarray(self._mat.todense())

    def __repr__(self):
        return (f"{type(self).__name__}(shape={self.shape}, "
                f"nnz={self.nnz}, dtype={self.dtype})")


class SparseCooTensor(SparseTensorBase):
    def __init__(self, mat: jsparse.BCOO):
        self._mat = mat

    def indices(self):
        return Tensor(self._mat.indices.T)   # paddle: (sparse_dim, nnz)

    def values(self):
        return Tensor(self._mat.data)

    def coalesce(self):
        return SparseCooTensor(self._mat.sum_duplicates())

    def to_sparse_csr(self):
        return SparseCsrTensor(jsparse.BCSR.from_bcoo(
            self._mat.sum_duplicates()))

    def is_sparse_coo(self):
        return True

    def is_sparse_csr(self):
        return False


class SparseCsrTensor(SparseTensorBase):
    def __init__(self, mat: jsparse.BCSR):
        self._mat = mat

    def crows(self):
        return Tensor(self._mat.indptr)

    def cols(self):
        return Tensor(self._mat.indices)

    def values(self):
        return Tensor(self._mat.data)

    def to_sparse_coo(self, sparse_dim=2):
        return SparseCooTensor(self._mat.to_bcoo())

    def is_sparse_coo(self):
        return False

    def is_sparse_csr(self):
        return True


def sparse_coo_tensor(indices, values, shape=None, dtype=None,
                      place=None, stop_gradient=True):
    idx = _as_array(indices).T.astype(jnp.int32)     # (nnz, sparse_dim)
    vals = _as_array(values)
    if dtype is not None:
        from ..framework import convert_dtype
        vals = vals.astype(convert_dtype(dtype))
    if shape is None:
        shape = tuple(int(m) + 1 for m in jnp.max(idx, axis=0))
    mat = jsparse.BCOO((vals, idx), shape=tuple(shape))
    return SparseCooTensor(mat)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None,
                      place=None, stop_gradient=True):
    vals = _as_array(values)
    if dtype is not None:
        from ..framework import convert_dtype
        vals = vals.astype(convert_dtype(dtype))
    mat = jsparse.BCSR(
        (vals, _as_array(cols).astype(jnp.int32),
         _as_array(crows).astype(jnp.int32)), shape=tuple(shape))
    return SparseCsrTensor(mat)


def is_same_shape(x, y):
    return tuple(x.shape) == tuple(y.shape)


def coalesce(x):
    return x.coalesce()


def mask_as(x, mask, name=None):
    """Dense ``x`` filtered by the sparsity pattern of ``mask``
    (reference: paddle.sparse.mask_as — verify): returns a sparse
    tensor with mask's layout/indices and values taken from x."""
    xv = _as_array(x)
    was_csr = isinstance(mask, SparseCsrTensor)
    m = mask._mat.to_bcoo() if was_csr else mask._mat
    vals = xv[tuple(m.indices[:, d] for d in range(m.indices.shape[1]))]
    out = jsparse.BCOO((vals, m.indices), shape=m.shape)
    if was_csr:
        return SparseCsrTensor(jsparse.BCSR.from_bcoo(out))
    return SparseCooTensor(out)


# --- linear algebra ---------------------------------------------------------

def matmul(x, y, name=None):
    """sparse @ dense (and sparse @ sparse -> dense semantics of the
    reference's sparse.matmul when both sparse)."""
    if isinstance(x, SparseTensorBase) and isinstance(y, SparseTensorBase):
        return Tensor(x._mat.todense() @ y._mat.todense())
    if isinstance(x, SparseTensorBase):
        return Tensor(x._mat @ _as_array(y))
    return Tensor(_as_array(x) @ y._mat.todense())


def mv(x, vec, name=None):
    return Tensor(x._mat @ _as_array(vec))


def masked_matmul(x, y, mask, name=None):
    """dense @ dense, sampled at the sparsity pattern of ``mask``
    (SDDMM; the reference lowers to cusparseSDDMM — verify)."""
    dense = _as_array(x) @ _as_array(y)
    m = mask._mat if isinstance(mask, SparseTensorBase) else mask
    if isinstance(m, jsparse.BCSR):
        m = m.to_bcoo()
    idx = m.indices
    vals = dense[tuple(idx[:, i] for i in range(idx.shape[1]))]
    return SparseCooTensor(jsparse.BCOO((vals, idx), shape=dense.shape))


# --- elementwise ------------------------------------------------------------

def _unary(fn):
    def op(x, name=None):
        was_csr = isinstance(x, SparseCsrTensor)
        mat = x._mat.to_bcoo() if was_csr else x._mat
        out = jsparse.BCOO((fn(mat.data), mat.indices), shape=mat.shape)
        if was_csr:
            return SparseCsrTensor(jsparse.BCSR.from_bcoo(out))
        return SparseCooTensor(out)
    return op


relu = _unary(jax.nn.relu)
tanh = _unary(jnp.tanh)
sin = _unary(jnp.sin)
abs = _unary(jnp.abs)
neg = _unary(jnp.negative)
sqrt = _unary(jnp.sqrt)
square = _unary(jnp.square)


def pow(x, factor, name=None):
    return _unary(lambda v: jnp.power(v, factor))(x)


def cast(x, index_dtype=None, value_dtype=None, name=None):
    from ..framework import convert_dtype
    return _unary(lambda v: v.astype(convert_dtype(value_dtype))
                  if value_dtype else v)(x)


def _binary(fn):
    def op(x, y, name=None):
        # dense result semantics match the reference for mismatched
        # patterns; same-pattern inputs keep sparsity
        xm = x._mat.to_bcoo() if isinstance(x, SparseCsrTensor) else x._mat
        ym = y._mat.to_bcoo() if isinstance(y, SparseCsrTensor) else y._mat
        xs, ys = xm.sum_duplicates(), ym.sum_duplicates()
        if xs.indices.shape == ys.indices.shape and bool(
                jnp.all(xs.indices == ys.indices)):
            out = jsparse.BCOO((fn(xs.data, ys.data), xs.indices),
                               shape=xs.shape)
            if isinstance(x, SparseCsrTensor):
                return SparseCsrTensor(jsparse.BCSR.from_bcoo(out))
            return SparseCooTensor(out)
        dense = fn(xm.todense(), ym.todense())
        out = jsparse.BCOO.fromdense(dense)
        if isinstance(x, SparseCsrTensor):
            return SparseCsrTensor(jsparse.BCSR.from_bcoo(out))
        return SparseCooTensor(out)
    return op


add = _binary(jnp.add)
subtract = _binary(jnp.subtract)
multiply = _binary(jnp.multiply)
divide = _binary(jnp.divide)


def transpose(x, perm, name=None):
    was_csr = isinstance(x, SparseCsrTensor)
    mat = x._mat.to_bcoo() if was_csr else x._mat
    out = mat.transpose(tuple(perm))
    if was_csr:
        return SparseCsrTensor(jsparse.BCSR.from_bcoo(out.sum_duplicates()))
    return SparseCooTensor(out)


# sparse.nn must import after the containers above (it depends on them)
from . import nn                                            # noqa: E402
__all__ += ["nn"]


def _tensor_to_sparse_coo(self, sparse_dim=None):
    """Dense Tensor -> SparseCooTensor (reference:
    paddle.Tensor.to_sparse_coo — verify). ``sparse_dim`` defaults to
    the tensor's rank (every dim sparse, matching the reference)."""
    v = np.asarray(self._value)
    nd = sparse_dim if sparse_dim is not None else v.ndim
    if nd != v.ndim:
        raise NotImplementedError(
            "to_sparse_coo with sparse_dim < ndim (hybrid tensors) is "
            "unsupported")
    idx = np.stack(np.nonzero(v))
    return sparse_coo_tensor(idx, v[tuple(idx)], shape=v.shape)


def _tensor_to_sparse_csr(self):
    """Dense 2-D Tensor -> SparseCsrTensor (reference:
    paddle.Tensor.to_sparse_csr — verify)."""
    return _tensor_to_sparse_coo(self).to_sparse_csr()


def _attach_tensor_methods():
    from ..tensor import Tensor
    if not hasattr(Tensor, "to_sparse_coo"):
        Tensor.to_sparse_coo = _tensor_to_sparse_coo
        Tensor.to_sparse_csr = _tensor_to_sparse_csr


_attach_tensor_methods()
