"""paddle.sparse.nn — sparse layers (reference: python/paddle/sparse/nn/
layer/ — verify). Layers are nn.Layer subclasses (params register in an
enclosing model's parameters()/state_dict) built on the coordinate-
sparse kernels in :mod:`.functional`."""
from __future__ import annotations

import math as _math

import jax
import jax.numpy as jnp
import numpy as np

from . import functional
from .functional import _conv3d_coo, _triple
from .. import SparseCooTensor, sparse_coo_tensor
from ...nn.layer import Layer
from ...tensor import Tensor

__all__ = ["Conv3D", "SubmConv3D", "BatchNorm", "ReLU", "functional"]


class _ConvBase(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, subm=False,
                 weight_attr=None, bias_attr=None, data_format="NDHWC"):
        super().__init__()
        if groups != 1:
            raise NotImplementedError("sparse conv groups > 1")
        if data_format != "NDHWC":
            raise ValueError("sparse conv3d supports NDHWC only "
                             "(reference layout)")
        self._subm = subm
        self.stride = _triple(stride)
        self.padding = _triple(padding)
        self.dilation = _triple(dilation)
        k = _triple(kernel_size)
        fan_in = in_channels * k[0] * k[1] * k[2]
        bound = 1.0 / _math.sqrt(fan_in)
        from ...nn import initializer as I
        from ...param_attr import ParamAttr
        weight_attr = ParamAttr._to_attr(weight_attr)
        bias_attr = ParamAttr._to_attr(bias_attr)
        self.weight = self.create_parameter(
            (*k, in_channels, out_channels), attr=weight_attr,
            default_initializer=None if (
                weight_attr and weight_attr.initializer) else
            I.Uniform(-bound, bound))
        if bias_attr is not False:
            self.bias = self.create_parameter(
                (out_channels,), attr=bias_attr, is_bias=True)
        else:
            self.bias = None

    def forward(self, x):
        return _conv3d_coo(x, self.weight, self.bias, self.stride,
                           self.padding, self.dilation, subm=self._subm)


class Conv3D(_ConvBase):
    """Sparse 3D convolution: output sites are every stride-aligned
    position reachable from an active input voxel (the sparse pattern
    DILATES — reference sparse/nn/layer/conv.py Conv3D)."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, weight_attr=None,
                 bias_attr=None, data_format="NDHWC"):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, False, weight_attr,
                         bias_attr, data_format)


class SubmConv3D(_ConvBase):
    """Submanifold sparse conv: output sites == input sites (no pattern
    dilation — the point-cloud workhorse; reference SubmConv3D)."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, weight_attr=None,
                 bias_attr=None, data_format="NDHWC"):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, True, weight_attr,
                         bias_attr, data_format)


class BatchNorm(Layer):
    """BatchNorm over the channel dim of ACTIVE voxels only (inactive
    sites don't dilute the statistics — reference routes sparse BN
    through the same batch_norm kernel). Delegates to F.batch_norm on
    the (nnz, C) values, so momentum/unbiased-variance semantics and
    running-stat buffers match the dense layer exactly."""

    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NDHWC"):
        super().__init__()
        from ...nn import initializer as I
        from ...param_attr import ParamAttr
        weight_attr = ParamAttr._to_attr(weight_attr)
        bias_attr = ParamAttr._to_attr(bias_attr)
        self.weight = self.create_parameter(
            (num_features,), attr=weight_attr,
            default_initializer=None if (
                weight_attr and weight_attr.initializer) else
            I.Constant(1.0))
        self.bias = self.create_parameter(
            (num_features,), attr=bias_attr, is_bias=True)
        dt = self.weight._value.dtype
        self.register_buffer("_mean",
                             Tensor(jnp.zeros((num_features,), dt)))
        self.register_buffer("_variance",
                             Tensor(jnp.ones((num_features,), dt)))
        self.momentum = momentum
        self.eps = epsilon

    def forward(self, x: SparseCooTensor):
        from ...nn import functional as F
        v = x.values()
        vt = v if isinstance(v, Tensor) else Tensor(jnp.asarray(v))
        out = F.batch_norm(vt, self._mean, self._variance, self.weight,
                           self.bias, training=self.training,
                           momentum=self.momentum, epsilon=self.eps,
                           data_format="NLC")
        return sparse_coo_tensor(np.asarray(x.indices()), out,
                                 shape=tuple(x.shape))


class ReLU(Layer):
    def forward(self, x: SparseCooTensor):
        return functional.relu(x)
