"""paddle.sparse.nn.functional — sparse conv / activation / attention.

Reference parity: python/paddle/sparse/nn/functional/ (conv3d,
subm_conv3d, relu, attention — verify). The reference backs these with
hand-written COO kernels (paddle/phi/kernels/sparse/); the TPU-native
design keeps COORDINATES on the host as numpy (the output structure of
a sparse conv is data-dependent — inherently eager, the reference is
too) and runs all VALUE math as jnp gathers + matmuls, which XLA maps
onto the MXU: one (nnz_out, Cin) x (Cin, Cout) matmul per kernel
offset. Coordinate lookup is a sorted-key binary search (O(nnz)
memory) — never a dense voxel grid.

Layout convention is paddle's: SparseCooTensor of shape
(N, D, H, W, C) with indices (4, nnz) over (n, d, h, w) and dense
values (nnz, C). Weight layout (kd, kh, kw, Cin, Cout).
"""
from __future__ import annotations

import math as _math

import jax
import jax.numpy as jnp
import numpy as np

from .. import SparseCooTensor, SparseCsrTensor, sparse_coo_tensor
from ...tensor import Tensor

__all__ = ["relu", "conv3d", "subm_conv3d", "attention"]


def _triple(v):
    if isinstance(v, (list, tuple)):
        if len(v) != 3:
            raise ValueError(f"expected 3 values, got {v!r}")
        return tuple(int(x) for x in v)
    return (int(v),) * 3


def _linearize(nidx, coords, dims):
    """(n, d, h, w) -> single sortable int64 key."""
    return ((nidx * dims[0] + coords[:, 0]) * dims[1]
            + coords[:, 1]) * dims[2] + coords[:, 2]


def _conv3d_coo(x: SparseCooTensor, weight, bias=None, stride=1,
                padding=0, dilation=1, subm=False):
    """Core sparse 3D convolution. Returns a SparseCooTensor."""
    stride, padding, dilation = (_triple(stride), _triple(padding),
                                 _triple(dilation))
    if not isinstance(x, SparseCooTensor):
        raise TypeError(f"expected SparseCooTensor, got {type(x)}")
    idx = np.asarray(x.indices())              # (4, nnz)
    vals = jnp.asarray(x.values()._value if isinstance(
        x.values(), Tensor) else x.values())   # (nnz, Cin)
    w = jnp.asarray(weight._value if isinstance(weight, Tensor)
                    else weight)
    N, D, H, W, cin = (int(s) for s in x.shape)
    kd, kh, kw, wcin, cout = (int(s) for s in w.shape)
    if wcin != cin:
        raise ValueError(f"weight Cin {wcin} != input channels {cin}")
    dims = np.array([D, H, W])
    if subm:
        if stride != (1, 1, 1):
            raise ValueError("subm_conv3d requires stride 1")
        out_spatial = (D, H, W)
        out_idx = idx
    else:
        out_spatial = tuple(
            (dims[i] + 2 * padding[i]
             - dilation[i] * ([kd, kh, kw][i] - 1) - 1) // stride[i] + 1
            for i in range(3))
        # candidate outputs: every (input voxel, kernel offset) pair that
        # lands on a stride-aligned, in-bounds output coordinate
        cands = []
        for od in range(kd):
            for oh in range(kh):
                for ow in range(kw):
                    off = np.array([od, oh, ow]) * np.array(dilation)
                    num = idx[1:].T + np.array(padding) - off
                    ok = (num % np.array(stride) == 0).all(1)
                    oc = num // np.array(stride)
                    ok &= ((oc >= 0) & (oc < np.array(out_spatial))) \
                        .all(1)
                    if ok.any():
                        cands.append(np.concatenate(
                            [idx[0][ok, None], oc[ok]], axis=1))
        if cands:
            allc = np.unique(np.concatenate(cands, axis=0), axis=0)
        else:
            allc = np.zeros((0, 4), np.int64)
        out_idx = allc.T                       # (4, nnz_out)

    Do, Ho, Wo = out_spatial
    # sorted-key lookup table over active INPUT voxels: O(nnz) memory
    # (a dense (N,D,H,W) grid would be ~720 MB for a detection-scale
    # 41x1600x1408 grid, rebuilt per conv call)
    in_keys = _linearize(idx[0].astype(np.int64), idx[1:].T.astype(
        np.int64), dims)
    order = np.argsort(in_keys)
    keys_sorted = in_keys[order]

    def lookup(nidx, coords, valid):
        q = _linearize(nidx.astype(np.int64), coords.astype(np.int64),
                       dims)
        pos = np.searchsorted(keys_sorted, q)
        pos_c = np.minimum(pos, len(keys_sorted) - 1)
        hit = valid & (len(keys_sorted) > 0)
        if len(keys_sorted):
            hit = hit & (keys_sorted[pos_c] == q)
        rows = np.where(hit, order[pos_c], -1)
        return rows

    vals_pad = jnp.concatenate(
        [vals, jnp.zeros((1, cin), vals.dtype)], axis=0)  # row -1 -> 0

    nnz_out = out_idx.shape[1]
    out = jnp.zeros((nnz_out, cout),
                    jnp.promote_types(vals.dtype, w.dtype))
    oc = out_idx[1:].T                         # (nnz_out, 3)
    on = out_idx[0]
    for od in range(kd):
        for oh in range(kh):
            for ow in range(kw):
                off = np.array([od, oh, ow]) * np.array(dilation)
                ic = oc * np.array(stride) - np.array(padding) + off
                inb = ((ic >= 0) & (ic < dims)).all(1)
                icc = np.clip(ic, 0, dims - 1)
                rows = lookup(on, icc, inb)
                g = vals_pad[jnp.asarray(rows)]          # (nnz_out, Cin)
                out = out + g @ w[od, oh, ow]
    if bias is not None:
        b = jnp.asarray(bias._value if isinstance(bias, Tensor) else bias)
        out = out + b
    return sparse_coo_tensor(
        out_idx, Tensor(out.astype(vals.dtype)),
        shape=(N, Do, Ho, Wo, cout))


def relu(x, name=None):
    v = x.values()
    v = v._value if isinstance(v, Tensor) else jnp.asarray(v)
    return sparse_coo_tensor(np.asarray(x.indices()),
                             Tensor(jnp.maximum(v, 0)),
                             shape=tuple(x.shape))


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1,
           groups=1, data_format="NDHWC", name=None):
    if groups != 1:
        raise NotImplementedError("sparse conv groups > 1")
    return _conv3d_coo(x, weight, bias, stride, padding, dilation,
                       subm=False)


def subm_conv3d(x, weight, bias=None, stride=1, padding=0,
                dilation=1, groups=1, data_format="NDHWC", name=None):
    if groups != 1:
        raise NotImplementedError("sparse conv groups > 1")
    return _conv3d_coo(x, weight, bias, stride, padding, dilation,
                       subm=True)


def attention(query, key, value, sparse_mask, key_padding_mask=None,
              attn_mask=None, name=None):
    """Sparse attention: softmax runs over ONLY the positions named by
    ``sparse_mask`` (a SparseCsrTensor of shape (b*h, s, s) — reference
    sparse/nn/functional/transformer.py — verify). query/key/value:
    dense (b, h, s, d). Additive masks ``key_padding_mask`` (b, s) /
    ``attn_mask`` (s, s) follow the reference's semantics (−inf entries
    drop keys). A row whose every participating key is masked out
    yields exact zeros (never probability mass outside the pattern).

    TPU-native: the CSR pattern becomes a boolean score mask and XLA
    fuses the masked softmax; the pattern is static per call site, so
    the MXU still sees the full (s, s) matmul tiles (a gather-per-row
    formulation would defeat tiling for the moderate sparsities these
    masks carry)."""
    if not isinstance(sparse_mask, SparseCsrTensor):
        raise TypeError("sparse_mask must be a SparseCsrTensor")
    qv = query._value if isinstance(query, Tensor) \
        else jnp.asarray(query)
    kv = key._value if isinstance(key, Tensor) else jnp.asarray(key)
    vv = value._value if isinstance(value, Tensor) \
        else jnp.asarray(value)
    b, h, s, d = qv.shape
    # CSR pattern -> dense bool (b*h, s, s), vectorized: row ids repeat
    # by per-row counts from np.diff(crows)
    crows = np.asarray(sparse_mask.crows()).reshape(b * h, s + 1)
    cols = np.asarray(sparse_mask.cols()).reshape(b * h, -1)
    counts = np.diff(crows, axis=1)                  # (bh, s)
    allow = np.zeros((b * h, s, s), bool)
    bh_ids = np.repeat(np.arange(b * h), counts.sum(axis=1))
    row_ids = np.concatenate(
        [np.repeat(np.arange(s), c) for c in counts])
    col_ids = np.concatenate(
        [cols[i, :counts[i].sum()] for i in range(b * h)])
    allow[bh_ids, row_ids, col_ids] = True
    allow = jnp.asarray(allow.reshape(b, h, s, s))
    scores = jnp.einsum("bhqd,bhkd->bhqk", qv, kv,
                        preferred_element_type=jnp.float32) \
        / _math.sqrt(d)
    # additive masks apply FIRST (on allowed positions), then the
    # pattern mask sets disallowed to -inf — so a -inf padding mask can
    # never rank an allowed key BELOW a disallowed one
    if attn_mask is not None:
        am = attn_mask._value if isinstance(attn_mask, Tensor) \
            else jnp.asarray(attn_mask)
        scores = scores + am.astype(scores.dtype)
    if key_padding_mask is not None:
        kp = key_padding_mask._value if isinstance(
            key_padding_mask, Tensor) else jnp.asarray(key_padding_mask)
        scores = scores + kp.astype(scores.dtype)[:, None, None, :]
    scores = jnp.where(allow, scores, -jnp.inf)
    # -inf-safe softmax: fully-masked rows output exact zeros
    m = jnp.max(scores, axis=-1, keepdims=True)
    m_safe = jnp.where(jnp.isneginf(m), 0.0, m)
    e = jnp.where(jnp.isneginf(scores), 0.0, jnp.exp(scores - m_safe))
    denom = jnp.sum(e, axis=-1, keepdims=True)
    probs = e / jnp.maximum(denom, 1e-30)
    out = jnp.einsum("bhqk,bhkd->bhqd", probs.astype(vv.dtype), vv)
    return Tensor(out)
