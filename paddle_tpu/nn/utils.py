"""paddle.nn.utils (reference: python/paddle/nn/utils/ — weight_norm,
spectral_norm wrappers, clip_grad_norm_, clip_grad_value_,
parameters_to_vector / vector_to_parameters — verify).

TPU-native design: the norm wrappers are forward-pre-hooks that
recompute the layer's weight from the reparameterized pieces — the
recomputation is jnp math that fuses into the surrounding program; the
grad-clip helpers mutate ``.grad`` in place exactly like the reference
(global-norm scaling or value clamping before the optimizer step).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..tensor import Parameter, Tensor, apply_op

__all__ = ["weight_norm", "remove_weight_norm", "spectral_norm",
           "clip_grad_norm_", "clip_grad_value_", "parameters_to_vector",
           "vector_to_parameters"]


def _norm_except(v, dim):
    """||v|| over every axis except ``dim`` (dim=None → full norm)."""
    if dim is None:
        return jnp.sqrt(jnp.sum(v * v))
    dim = dim % v.ndim                     # negative dims welcome
    axes = tuple(i for i in range(v.ndim) if i != dim)
    shape = [1] * v.ndim
    shape[dim] = v.shape[dim]
    return jnp.sqrt(jnp.sum(v * v, axis=axes)).reshape(shape)


def weight_norm(layer, name="weight", dim=0):
    """Reparameterize ``layer.<name>`` as g * v / ||v|| (reference:
    paddle.nn.utils.weight_norm). Trains g and v; the effective weight is
    rebuilt by a forward-pre-hook every call."""
    w = getattr(layer, name)
    if dim is not None:
        dim = dim % w._value.ndim
    v0 = w._value
    g0 = np.asarray(_norm_except(v0, dim))
    wv = Parameter(np.asarray(v0))
    wg = Parameter(g0)
    del layer._parameters[name]
    setattr(layer, f"{name}_v", wv)
    setattr(layer, f"{name}_g", wg)

    def recompute(lyr, inputs):
        eff = apply_op(
            lambda vv, gg: gg * vv / jnp.maximum(
                _norm_except(vv, dim), 1e-12), wv, wg)
        object.__setattr__(lyr, name, eff)
        return None

    handle = layer.register_forward_pre_hook(recompute)
    layer.__dict__[f"_{name}_norm_handle"] = (handle, dim)
    recompute(layer, None)     # effective weight available immediately
    return layer


def remove_weight_norm(layer, name="weight"):
    """Fold g*v/||v|| back into a single parameter and drop the hook."""
    entry = layer.__dict__.pop(f"_{name}_norm_handle", None)
    if entry is None:
        raise ValueError(f"{name!r} has no weight_norm on this layer")
    handle, dim = entry
    handle.remove()
    wv = getattr(layer, f"{name}_v")
    wg = getattr(layer, f"{name}_g")
    eff = np.asarray(wg._value) * np.asarray(wv._value) / np.maximum(
        np.asarray(_norm_except(wv._value, dim)), 1e-12)
    del layer._parameters[f"{name}_v"]
    del layer._parameters[f"{name}_g"]
    # drop the stale instance attributes too — feature-testing via
    # hasattr(layer, "weight_v") must see a clean layer afterwards
    layer.__dict__.pop(f"{name}_v", None)
    layer.__dict__.pop(f"{name}_g", None)
    layer.__dict__.pop(name, None)
    setattr(layer, name, Parameter(eff))
    return layer


def spectral_norm(layer, name="weight", n_power_iterations=1, eps=1e-12,
                  dim=0):
    """Divide ``layer.<name>`` by its spectral norm each forward
    (reference: paddle.nn.utils.spectral_norm), reusing the
    nn.SpectralNorm power-iteration module."""
    from .norm import SpectralNorm
    w = getattr(layer, name)
    sn = SpectralNorm(tuple(int(s) for s in w._value.shape), dim=dim,
                      power_iters=n_power_iterations, eps=eps)
    orig = Parameter(np.asarray(w._value))
    del layer._parameters[name]
    setattr(layer, f"{name}_orig", orig)
    layer.add_sublayer(f"_{name}_spectral_norm", sn)

    def recompute(lyr, inputs):
        object.__setattr__(lyr, name, sn(orig))
        return None

    handle = layer.register_forward_pre_hook(recompute)
    layer.__dict__[f"_{name}_sn_handle"] = handle
    recompute(layer, None)
    return layer


def clip_grad_norm_(parameters, max_norm, norm_type=2.0,
                    error_if_nonfinite=False):
    """Scale all grads so their GLOBAL norm is at most max_norm; returns
    the pre-clip total norm (reference semantics)."""
    if isinstance(parameters, Tensor):
        parameters = [parameters]
    params = [p for p in list(parameters) if p.grad is not None]
    if not params:
        return Tensor(jnp.zeros(()))
    if float(norm_type) == float("inf"):
        total = jnp.max(jnp.stack(
            [jnp.max(jnp.abs(p.grad._value)) for p in params]))
    else:
        total = jnp.sum(jnp.stack(
            [jnp.sum(jnp.abs(p.grad._value) ** norm_type)
             for p in params])) ** (1.0 / norm_type)
    if error_if_nonfinite and not bool(jnp.isfinite(total)):
        raise RuntimeError(
            f"clip_grad_norm_: total norm is {float(total)} "
            "(error_if_nonfinite=True)")
    scale = jnp.minimum(max_norm / (total + 1e-6), 1.0)
    for p in params:
        p.grad = Tensor(p.grad._value * scale)
    return Tensor(total)


def clip_grad_value_(parameters, clip_value):
    """Clamp every grad element into [-clip_value, clip_value]."""
    if isinstance(parameters, Tensor):
        parameters = [parameters]
    for p in list(parameters):
        if p.grad is not None:
            p.grad = Tensor(jnp.clip(p.grad._value, -clip_value,
                                     clip_value))


def parameters_to_vector(parameters, name=None):
    """Flatten-concatenate parameters into one 1-D tensor."""
    params = list(parameters)
    return apply_op(
        lambda *vs: jnp.concatenate([v.reshape(-1) for v in vs]), *params)


def vector_to_parameters(vec, parameters, name=None):
    """Write slices of ``vec`` back into the parameters (in place)."""
    params = list(parameters)
    v = vec._value if isinstance(vec, Tensor) else jnp.asarray(vec)
    need = sum(int(np.prod(p._value.shape)) if p._value.shape else 1
               for p in params)
    if int(v.shape[0]) != need:
        raise ValueError(
            f"vector has {v.shape[0]} elements but parameters need "
            f"{need}")
    offset = 0
    for p in params:
        n = int(np.prod(p._value.shape)) if p._value.shape else 1
        piece = v[offset:offset + n].reshape(p._value.shape)
        p._update_value(piece.astype(p._value.dtype))
        offset += n
