"""Loss layers (reference: python/paddle/nn/layer/loss.py — verify)."""
from __future__ import annotations

from . import functional as F
from .layer import Layer

__all__ = ["CrossEntropyLoss", "MSELoss", "L1Loss", "NLLLoss", "BCELoss",
           "BCEWithLogitsLoss", "KLDivLoss", "SmoothL1Loss", "HuberLoss",
           "MarginRankingLoss", "CosineEmbeddingLoss"]


class CrossEntropyLoss(Layer):
    def __init__(self, weight=None, ignore_index=-100, reduction="mean",
                 soft_label=False, axis=-1, use_softmax=True,
                 label_smoothing=0.0, name=None):
        super().__init__()
        self.weight = weight
        self.ignore_index = ignore_index
        self.reduction = reduction
        self.soft_label = soft_label
        self.axis = axis
        self.use_softmax = use_softmax
        self.label_smoothing = label_smoothing

    def forward(self, input, label):
        return F.cross_entropy(input, label, self.weight, self.ignore_index,
                               self.reduction, self.soft_label, self.axis,
                               self.use_softmax, self.label_smoothing)


class MSELoss(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.mse_loss(input, label, self.reduction)


class L1Loss(Layer):
    def __init__(self, reduction="mean", name=None):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.l1_loss(input, label, self.reduction)


class NLLLoss(Layer):
    def __init__(self, weight=None, ignore_index=-100, reduction="mean",
                 name=None):
        super().__init__()
        self.weight = weight
        self.ignore_index = ignore_index
        self.reduction = reduction

    def forward(self, input, label):
        return F.nll_loss(input, label, self.weight, self.ignore_index,
                          self.reduction)


class BCELoss(Layer):
    def __init__(self, weight=None, reduction="mean", name=None):
        super().__init__()
        self.weight = weight
        self.reduction = reduction

    def forward(self, input, label):
        return F.binary_cross_entropy(input, label, self.weight,
                                      self.reduction)


class BCEWithLogitsLoss(Layer):
    def __init__(self, weight=None, reduction="mean", pos_weight=None,
                 name=None):
        super().__init__()
        self.weight = weight
        self.reduction = reduction
        self.pos_weight = pos_weight

    def forward(self, logit, label):
        return F.binary_cross_entropy_with_logits(
            logit, label, self.weight, self.reduction, self.pos_weight)


class KLDivLoss(Layer):
    def __init__(self, reduction="mean", log_target=False):
        super().__init__()
        self.reduction = reduction
        self.log_target = log_target

    def forward(self, input, label):
        return F.kl_div(input, label, self.reduction, self.log_target)


class SmoothL1Loss(Layer):
    def __init__(self, reduction="mean", delta=1.0, name=None):
        super().__init__()
        self.reduction = reduction
        self.delta = delta

    def forward(self, input, label):
        return F.smooth_l1_loss(input, label, self.reduction, self.delta)


class HuberLoss(Layer):
    """0.5*d^2 for |d|<=delta else delta*(|d|-0.5*delta) (reference:
    paddle.nn.HuberLoss — verify; differs from SmoothL1Loss by the
    1/delta scaling of the quadratic zone)."""

    def __init__(self, reduction="mean", delta=1.0, name=None):
        super().__init__()
        self.reduction = reduction
        self.delta = float(delta)

    def forward(self, input, label):
        from .. import ops
        d = input - label
        ad = d.abs()
        loss = ops.where(ad <= self.delta, 0.5 * d * d,
                         self.delta * (ad - 0.5 * self.delta))
        if self.reduction == "mean":
            return loss.mean()
        if self.reduction == "sum":
            return loss.sum()
        return loss


class MarginRankingLoss(Layer):
    def __init__(self, margin=0.0, reduction="mean", name=None):
        super().__init__()
        self.margin = margin
        self.reduction = reduction

    def forward(self, input, other, label):
        return F.margin_ranking_loss(input, other, label, self.margin,
                                     self.reduction)


class CosineEmbeddingLoss(Layer):
    def __init__(self, margin=0.0, reduction="mean", name=None):
        super().__init__()
        self.margin = margin
        self.reduction = reduction

    def forward(self, input1, input2, label):
        import jax.numpy as jnp
        from ..tensor import apply_op

        def f(a, b, y):
            cos = jnp.sum(a * b, -1) / (
                jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1)
                + 1e-12)
            loss = jnp.where(y > 0, 1 - cos,
                             jnp.maximum(cos - self.margin, 0.0))
            if self.reduction == "mean":
                return jnp.mean(loss)
            if self.reduction == "sum":
                return jnp.sum(loss)
            return loss
        return apply_op(f, input1, input2, label)


# ---- round-2 batch-2 losses (reference: python/paddle/nn/layer/loss.py) ----

class CTCLoss(Layer):
    def __init__(self, blank=0, reduction="mean"):
        super().__init__()
        self.blank = blank
        self.reduction = reduction

    def forward(self, log_probs, labels, input_lengths, label_lengths,
                norm_by_times=False):
        return F.ctc_loss(log_probs, labels, input_lengths, label_lengths,
                          self.blank, self.reduction, norm_by_times)


class HingeEmbeddingLoss(Layer):
    def __init__(self, margin=1.0, reduction="mean", name=None):
        super().__init__()
        self.margin = margin
        self.reduction = reduction

    def forward(self, input, label):
        return F.hinge_embedding_loss(input, label, self.margin,
                                      self.reduction)


class HSigmoidLoss(Layer):
    """Hierarchical sigmoid classifier head: owns the internal-node weight
    (num_classes-1, feature_size) and optional bias."""

    def __init__(self, feature_size, num_classes, weight_attr=None,
                 bias_attr=None, is_custom=False, is_sparse=False,
                 name=None):
        super().__init__()
        from ..tensor import Parameter
        from . import initializer as I
        import numpy as np
        self.num_classes = num_classes
        n_nodes = num_classes - 1
        init = I.XavierUniform()
        self.weight = Parameter(
            init((n_nodes, feature_size), "float32"))
        if bias_attr is not False:
            self.bias = Parameter(np.zeros((n_nodes,), "float32"))
        else:
            self.bias = None

    def forward(self, input, label, path_table=None, path_code=None):
        return F.hsigmoid_loss(input, label, self.num_classes, self.weight,
                               self.bias, path_table, path_code)


class MultiLabelSoftMarginLoss(Layer):
    def __init__(self, weight=None, reduction="mean", name=None):
        super().__init__()
        self.weight = weight
        self.reduction = reduction

    def forward(self, input, label):
        return F.multi_label_soft_margin_loss(input, label, self.weight,
                                              self.reduction)


class MultiMarginLoss(Layer):
    def __init__(self, p=1, margin=1.0, weight=None, reduction="mean",
                 name=None):
        super().__init__()
        self.p = p
        self.margin = margin
        self.weight = weight
        self.reduction = reduction

    def forward(self, input, label):
        return F.multi_margin_loss(input, label, self.p, self.margin,
                                   self.weight, self.reduction)


class PoissonNLLLoss(Layer):
    def __init__(self, log_input=True, full=False, epsilon=1e-8,
                 reduction="mean", name=None):
        super().__init__()
        self.log_input = log_input
        self.full = full
        self.epsilon = epsilon
        self.reduction = reduction

    def forward(self, input, label):
        return F.poisson_nll_loss(input, label, self.log_input, self.full,
                                  self.epsilon, self.reduction)


class SoftMarginLoss(Layer):
    def __init__(self, reduction="mean", name=None):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.soft_margin_loss(input, label, self.reduction)


class TripletMarginLoss(Layer):
    def __init__(self, margin=1.0, p=2.0, epsilon=1e-6, swap=False,
                 reduction="mean", name=None):
        super().__init__()
        self.margin = margin
        self.p = p
        self.epsilon = epsilon
        self.swap = swap
        self.reduction = reduction

    def forward(self, input, positive, negative):
        return F.triplet_margin_loss(input, positive, negative, self.margin,
                                     self.p, self.epsilon, self.swap,
                                     self.reduction)


class TripletMarginWithDistanceLoss(Layer):
    def __init__(self, distance_function=None, margin=1.0, swap=False,
                 reduction="mean", name=None):
        super().__init__()
        self.distance_function = distance_function
        self.margin = margin
        self.swap = swap
        self.reduction = reduction

    def forward(self, input, positive, negative):
        return F.triplet_margin_with_distance_loss(
            input, positive, negative, self.distance_function, self.margin,
            self.swap, self.reduction)


__all__ += ["CTCLoss", "HingeEmbeddingLoss", "HSigmoidLoss",
            "MultiLabelSoftMarginLoss", "MultiMarginLoss", "PoissonNLLLoss",
            "SoftMarginLoss", "TripletMarginLoss",
            "TripletMarginWithDistanceLoss"]


class GaussianNLLLoss(Layer):
    """reference: python/paddle/nn/layer/loss.py GaussianNLLLoss — verify."""

    def __init__(self, full=False, epsilon=1e-6, reduction="mean",
                 name=None):
        super().__init__()
        self.full, self.epsilon, self.reduction = full, epsilon, reduction

    def forward(self, input, label, variance):
        return F.gaussian_nll_loss(input, label, variance, self.full,
                                   self.epsilon, self.reduction)


class AdaptiveLogSoftmaxWithLoss(Layer):
    """Efficient softmax over a frequency-sorted vocabulary: a small
    head over [frequent classes + one logit per tail cluster], tail
    clusters projected down by div_value^i (reference:
    python/paddle/nn/layer/loss.py AdaptiveLogSoftmaxWithLoss — verify).

    forward(input, label) -> (target_log_probs, loss); also provides
    log_prob(input) (full (N, n_classes) log-probabilities) and
    predict(input)."""

    def __init__(self, in_features, n_classes, cutoffs, div_value=4.0,
                 head_bias=False, name=None):
        super().__init__()
        from .common import Linear, Sequential
        cutoffs = list(cutoffs)
        if not cutoffs or cutoffs != sorted(set(cutoffs)) \
                or cutoffs[-1] > n_classes - 1 or min(cutoffs) <= 0:
            raise ValueError(
                f"cutoffs must be unique, increasing, positive ints "
                f"< n_classes-1; got {cutoffs} for {n_classes} classes")
        self.in_features = in_features
        self.n_classes = n_classes
        self.cutoffs = cutoffs + [n_classes]
        self.div_value = div_value
        self.shortlist_size = cutoffs[0]
        self.n_clusters = len(self.cutoffs) - 1
        self.head_size = self.shortlist_size + self.n_clusters
        self.head = Linear(in_features, self.head_size,
                           bias_attr=head_bias)
        self.tail = []
        for i in range(self.n_clusters):
            hsz = max(1, int(in_features / (div_value ** (i + 1))))
            osz = self.cutoffs[i + 1] - self.cutoffs[i]
            proj = Sequential(Linear(in_features, hsz, bias_attr=False),
                              Linear(hsz, osz, bias_attr=False))
            self.tail.append(proj)
            setattr(self, f"tail_{i}", proj)   # registers parameters

    def _head_logprob(self, input):
        return F.log_softmax(self.head(input), axis=-1)

    def forward(self, input, label):
        from ..ops import math as M
        from ..ops import manipulation as MP
        import jax
        import jax.numpy as jnp
        try:  # concrete labels: out-of-range targets are an error, not
            # a silently-clamped shortlist gather (reference raises)
            lv = label._value if hasattr(label, "_value") else label
            lo_, hi_ = int(jnp.min(lv)), int(jnp.max(lv))
            if lo_ < 0 or hi_ >= self.n_classes:
                raise ValueError(
                    f"AdaptiveLogSoftmaxWithLoss: labels must be in "
                    f"[0, {self.n_classes - 1}], got [{lo_}, {hi_}]")
        except (jax.errors.TracerIntegerConversionError,
                jax.errors.ConcretizationTypeError):
            pass
        head_lp = self._head_logprob(input)          # (N, head_size)
        # shortlist target logprob (clamped gather; masked out later)
        short_idx = M.clip(label, 0, self.shortlist_size - 1)
        out = MP.squeeze(MP.take_along_axis(
            head_lp, MP.unsqueeze(short_idx.astype("int64"), -1), 1), -1)
        for i in range(self.n_clusters):
            lo, hi = self.cutoffs[i], self.cutoffs[i + 1]
            in_cl = M.logical_and(label >= lo, label < hi)
            tail_lp = F.log_softmax(self.tail[i](input), axis=-1)
            rel = M.clip(label - lo, 0, hi - lo - 1)
            cl_lp = MP.squeeze(MP.take_along_axis(
                tail_lp, MP.unsqueeze(rel.astype("int64"), -1), 1), -1)
            cluster_logit = head_lp[:, self.shortlist_size + i]
            out = MP.where(in_cl, cluster_logit + cl_lp, out)
        loss = -out.mean()
        return out, loss

    def log_prob(self, input):
        from ..ops import manipulation as MP
        head_lp = self._head_logprob(input)
        parts = [head_lp[:, :self.shortlist_size]]
        for i in range(self.n_clusters):
            tail_lp = F.log_softmax(self.tail[i](input), axis=-1)
            parts.append(
                tail_lp + MP.unsqueeze(
                    head_lp[:, self.shortlist_size + i], -1))
        return MP.concat(parts, axis=-1)

    def predict(self, input):
        from ..ops import math as M
        return M.argmax(self.log_prob(input), axis=-1)


__all__ += ["GaussianNLLLoss", "AdaptiveLogSoftmaxWithLoss"]


class RNNTLoss(Layer):
    """RNN-Transducer loss layer (reference: paddle.nn.RNNTLoss over
    warprnnt — verify; lax-native lattice recursion here)."""

    def __init__(self, blank=0, fastemit_lambda=0.001, reduction="mean",
                 name=None):
        super().__init__()
        self.blank = blank
        self.fastemit_lambda = fastemit_lambda
        self.reduction = reduction

    def forward(self, input, label, input_lengths, label_lengths):
        return F.rnnt_loss(input, label, input_lengths, label_lengths,
                           self.blank, self.fastemit_lambda,
                           self.reduction)


class EmbeddingBag(Layer):
    """Bagged embedding (reference: paddle.nn.EmbeddingBag — verify)."""

    def __init__(self, num_embeddings, embedding_dim, mode="mean",
                 weight_attr=None, name=None):
        super().__init__()
        self.mode = mode
        self.weight = self.create_parameter(
            (num_embeddings, embedding_dim), attr=weight_attr)

    def forward(self, input, offsets=None):
        return F.embedding_bag(input, self.weight, offsets, self.mode)


__all__ += ["RNNTLoss", "EmbeddingBag"]
